#!/usr/bin/env bash
# Pipeline-parallelism CI brick (docs/pipeline.md): the interleaved-1F1B
# A/B on the emulated 2x2x2 mesh — 2 pipeline stages over a (2, 2)
# data mesh — asserting the three contracts the pp perf-gate leg hard
# checks: pipelined-vs-dense parity, measured bubble fraction strictly
# under the no-overlap GPipe analytic bound (S-1)/(M+S-1), and the
# send-leg predicted-vs-measured wire-ms drift. A second zb leg runs
# the zero-bubble schedule with ZeRO-3 fill on the same geometry and
# asserts the zb1 contracts: measured zb1 bubble strictly below
# interleaved-1F1B's (the bench A/Bs both schedules in one run), a
# nonzero accounted bubble fill, and accounted == predicted fill
# bytes.
#
# Usage: scripts/pp_smoke.sh
# Env:   PP_SMOKE_KNOBS="--zero-stage 2 --quantized" adds composition
#        to the first leg (the zb leg always runs --zero-stage 3).
set -euo pipefail
cd "$(dirname "$0")/.."

KNOBS=${PP_SMOKE_KNOBS:-}

out=$(JAX_PLATFORMS=cpu python bench.py --pp 2 --mesh-shape 2x2 \
    --pp-microbatches 8 --pp-interleave 2 \
    --platform cpu --cpu-devices 8 \
    --num-iters 2 --num-batches-per-iter 2 $KNOBS | tail -n 1)
echo "$out"

python - "$out" <<'EOF'
import json, sys
rec = json.loads(sys.argv[1])
assert rec["parity_rel_err"] <= rec["parity_tol"], (
    f"pp smoke: parity {rec['parity_rel_err']} > {rec['parity_tol']}")
assert rec["bubble_fraction"] < rec["bubble_bound_gpipe"], (
    f"pp smoke: bubble {rec['bubble_fraction']} not strictly below the "
    f"GPipe bound {rec['bubble_bound_gpipe']}")
wm = rec["wire_ms"]
drift = abs(wm["predicted"] - wm["modeled"]) / max(1e-9, wm["modeled"])
assert drift <= 0.25, f"pp smoke: send wire drift {drift} > 0.25"
assert rec["pp_send_bytes"] > 0, "pp smoke: no send-leg wire bytes"
assert rec["value"] > 0, "pp smoke: zero throughput"
print(f"pp smoke OK: {rec['value']} tok/s, bubble "
      f"{rec['bubble_fraction']} < {rec['bubble_bound_gpipe']}, "
      f"send drift {drift:.4f}")
EOF

# zb leg: zero-bubble schedule + ZeRO-3 bubble fill, same geometry.
zb=$(JAX_PLATFORMS=cpu python bench.py --pp 2 --mesh-shape 2x2 \
    --pp-microbatches 8 --pp-interleave 2 --pp-schedule zb1 \
    --zero-stage 3 --platform cpu --cpu-devices 8 \
    --num-iters 2 --num-batches-per-iter 2 | tail -n 1)
echo "$zb"

python - "$zb" <<'EOF'
import json, sys
rec = json.loads(sys.argv[1])
assert rec["parity_rel_err"] <= rec["parity_tol"], (
    f"zb smoke: parity {rec['parity_rel_err']} > {rec['parity_tol']}")
zb, fb = rec["bubble_fraction_zb1"], rec["bubble_fraction_1f1b"]
assert zb < fb, (
    f"zb smoke: zb1 bubble {zb} not strictly below 1F1B {fb} on the "
    f"same geometry")
assert rec["bubble_hidden_bytes"] > 0, (
    "zb smoke: zero accounted bubble-fill bytes — the ZeRO-3 flights "
    "never streamed into the idle ticks")
assert rec["filled_ticks"] >= 1, "zb smoke: no idle ticks filled"
pred = rec["fill_predicted_bytes"]
fdrift = abs(pred - rec["bubble_hidden_bytes"]) / max(1.0, pred)
assert fdrift <= 1e-6, (
    f"zb smoke: fill accounted {rec['bubble_hidden_bytes']} != "
    f"predicted {pred}")
print(f"zb smoke OK: bubble {zb} < {fb} (1F1B), fill "
      f"{rec['filled_ticks']}/{rec['fill_capacity_ticks']} ticks, "
      f"{rec['bubble_hidden_bytes']:.0f} B hidden == predicted")
EOF
