#!/bin/bash
# Round-5 second chip session: re-capture ONLY the legs the first session
# lost (relay died mid-sweep, BENCH_r05_sweep/*.log) plus the two fixes
# landed since:
#   - fused-LN backward Mosaic block legality (ee75828) -> --fused-ln A/Bs
#   - trace-time autotune sweep runs in a worker thread (27b814b) ->
#     fresh-cache autotune pair (first-run sweep, second-run cache hit)
#   - elastic smoke import path (examples/_path_setup.py)
# Already-good legs from session 1 (resnet50, gpt124m, gpt350m, remat16)
# are NOT re-run unless you pass --all.
#
# Usage: tpu_round5b_measurements.sh [OUTDIR] [--all]
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/measure_lib.sh
OUT=$PWD/BENCH_r05_sweep
ALL=0
for arg in "$@"; do
  case "$arg" in
    --all) ALL=1 ;;
    --*) echo "unknown flag: $arg" >&2; exit 2 ;;
    *) OUT=$arg ;;
  esac
done
mkdir -p "$OUT"

# MFU levers first (the >=0.50 goal), then the autotune pair, then the
# risky teardown legs last so a wedge can't cost the perf numbers.
# The fused-LN A/B legs pin HOROVOD_KERNEL_AUTOTUNE=0: session 1's
# baselines effectively ran default blocks (the trace-time sweep was
# inert until 27b814b), so the A/B stays apples-to-apples — and an
# implicit first-use sweep (compile per candidate through the relay)
# would blow a 900 s budget anyway.
run 900  gpt350m_fusedln   env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --gpt-scale 350m --batch-size 8 --fused-ln
run 900  gpt124m_fusedln   env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --batch-size 16 --fused-ln
# Fresh-cache autotune: sweep on run 1 (compile per candidate -> the big
# budget), cache hit on run 2. rm guarantees "fresh" even on a re-run —
# except on a MEASURE_RESUME continuation where run 1 already landed:
# wiping then would force the remaining legs to re-sweep inside budgets
# sized for a cache hit.
AT_CACHE=$OUT/autotune_cache.json
if ! { [ "${MEASURE_RESUME:-0}" = 1 ] && [ -e "$OUT/gpt124m_autotune1.done" ]; }; then
  rm -f "$AT_CACHE"
fi
run 2400 gpt124m_autotune1 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" HOROVOD_KERNEL_AUTOTUNE=1 python bench.py --model gpt --batch-size 16
run_if_done gpt124m_autotune1 900  gpt124m_autotune2 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" HOROVOD_KERNEL_AUTOTUNE=1 python bench.py --model gpt --batch-size 16
# Best-config attempt at the MFU >= 0.50 goal: fused LN + whatever the
# warmed cache picked (the flash-block choice alone measured +9% at 124M).
run 2400 gpt350m_autotune1 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" HOROVOD_KERNEL_AUTOTUNE=1 python bench.py --model gpt --gpt-scale 350m --batch-size 8 --fused-ln
run_if_done gpt350m_autotune1 900  gpt350m_best      env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" HOROVOD_KERNEL_AUTOTUNE=1 python bench.py --model gpt --gpt-scale 350m --batch-size 8 --fused-ln
# Batch-growth lever: b12 without remat is a maybe-fit on 16 GB HBM
# (b16 OOMs, hence the r5s1 remat leg); a compile OOM just fails the
# leg. Uses the warmed cache + fused LN = best-known config.
run_if_done gpt350m_autotune1 900  gpt350m_b12       env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" HOROVOD_KERNEL_AUTOTUNE=1 python bench.py --model gpt --gpt-scale 350m --batch-size 12 --fused-ln
# Profile matches the 42.3k baseline config (autotune off) so the MFU
# attribution table describes the number we actually reported.
run 1200 gpt350m_profile   env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --gpt-scale 350m --batch-size 8 --profile "$OUT/profile"
run 900  elastic_smoke     env HOROVOD_KERNEL_AUTOTUNE=0 python examples/elastic_tpu_smoke.py --cycles 3 --steps 20 --reset-backend
if [ "$ALL" = 1 ]; then
  run 560  resnet50          env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py
  run 900  gpt124m           env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --batch-size 16
  run 900  gpt350m           env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --gpt-scale 350m --batch-size 8
fi
echo "all artifacts in $OUT ($MEASURE_MISSED legs missed)"
grep -h '"metric"' "$OUT"/*.log 2>/dev/null | tail -20
exit $((MEASURE_MISSED > 0))
