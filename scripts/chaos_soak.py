#!/usr/bin/env python
"""Chaos soak: loop the elastic recovery scenario under injected faults.

Runs the same scenario the integration tests pin
(tests/test_elastic_integration.py::TestChaosElastic) N times with a
different chaos seed per iteration, and checks the recovery invariants
each time: the faulted host is blacklisted, the world re-forms at a new
world_id, every survivor finishes, and all finishers agree on the
trained weights. Exit code is the number of failed iterations.

Usage::

    JAX_PLATFORMS=cpu python scripts/chaos_soak.py --iterations 10
    python scripts/chaos_soak.py --fault drop --iterations 50 --seed 100
    python scripts/chaos_soak.py --fault stall -n 5 --keep-going

Faults: ``crash`` (hostB worker dies at an eager collective), ``drop``
(driver slot-grant RPCs go unanswered; retry absorbs), ``stall``
(hostB worker hangs before rendezvous; the stall watchdog abandons the
incarnation), ``ckpt`` (EVERY worker hard-crashes mid-run — only the
async rank-sharded checkpoint survives; a fresh driver must resume from
the last committed step with a loss trajectory bit-identical to an
uninterrupted run, docs/checkpoint.md), ``mixed`` (cycle through all).
"""

import argparse
import json
import os
import shlex
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = os.path.join(REPO, "tests", "elastic_worker.py")

FAULTS = ("crash", "drop", "stall", "ckpt")


def _read_log(path):
    if not os.path.exists(path):
        return []
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def run_once(fault, seed, workdir, verbose=False):
    """One soak iteration; returns (ok, detail)."""
    from horovod_tpu import chaos
    from horovod_tpu.common import counters
    from horovod_tpu.elastic import constants
    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner import safe_shell_exec

    constants.DISCOVER_HOSTS_FREQUENCY_SECS = 0.25
    chaos.reset()
    counters.reset_all()

    script = os.path.join(workdir, "discover.sh")
    with open(script, "w") as f:
        f.write("#!/bin/sh\necho hostA:2\necho hostB:1\n")
    os.chmod(script, 0o755)
    log_file = os.path.join(workdir, "log.jsonl")

    worker_env = {}
    driver_kwargs = {}
    worker_args = ["--batches", "8", "--batch-sleep", "0.1"]
    if fault == "crash":
        plan = chaos.FaultPlan(seed=seed).add(
            "collective.eager", "crash", where="hostB:0", after=3,
            max_count=1)
        worker_env = plan.to_env()
    elif fault == "drop":
        chaos.configure(chaos.FaultPlan(seed=seed).add(
            "driver.slot_grant", "drop", prob=0.3, max_count=4))
    elif fault == "stall":
        # Timing contract (three-way): the abandon deadline must exceed
        # a healthy worker's startup (process spawn + jax import — the
        # hostA slots must have rendezvoused by then or they get
        # blacklisted too), stay far below the injected stall (so ONLY
        # hostB is still missing at abandon time), and stay below the
        # workers' formation timeout (a failed-formation report resumes
        # the driver, which resets the very progress clock the watchdog
        # reads — churn must not outrun the deadline).
        plan = chaos.FaultPlan(seed=seed).add(
            "bootstrap.rendezvous", "stall", where="hostB:0", secs=45,
            max_count=1)
        worker_env = {**plan.to_env(), "HOROVOD_START_TIMEOUT": "15"}
        worker_args = ["--batches", "4", "--batch-sleep", "0.05"]
        driver_kwargs = dict(stall_warn_secs=2.0,
                             stall_shutdown_secs=8.0)
    else:
        raise ValueError(f"unknown fault {fault!r}")

    # Forensics armed for the stall leg: the driver's abandon-
    # incarnation path must leave a postmortem-joinable flight dump
    # naming the slots that never formed (docs/observability.md).
    flight_dir = None
    if fault == "stall":
        flight_dir = os.path.join(workdir, "flight")
        os.environ["HOROVOD_FLIGHT_RECORDER_DIR"] = flight_dir
        worker_env["HOROVOD_FLIGHT_RECORDER_DIR"] = flight_dir

    driver = ElasticDriver(HostDiscoveryScript(script, 1), min_np=2,
                           max_np=3, controller_addr_override="127.0.0.1",
                           **driver_kwargs)

    def _exec(slot, world_id):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "PYTHONPATH": REPO,
            "HOROVOD_HOSTNAME": slot.hostname,
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_ELASTIC_DRIVER_ADDR": "127.0.0.1",
            "HOROVOD_ELASTIC_DRIVER_PORT": str(driver.service_port),
            "HOROVOD_ELASTIC_DRIVER_KEY": driver.key.hex(),
            "HOROVOD_START_TIMEOUT": "30",
        })
        env.update(worker_env)
        cmd = " ".join(shlex.quote(c) for c in [
            sys.executable, WORKER, "--log-file", log_file, *worker_args])
        return safe_shell_exec.execute(cmd, env=env)

    try:
        driver.start(_exec)
        ok = driver.join(timeout=180)
    finally:
        driver.stop()
        driver.shutdown_service()
        chaos.reset()
        os.environ.pop("HOROVOD_FLIGHT_RECORDER_DIR", None)

    records = _read_log(log_file)
    done = [r for r in records if r.get("done")]
    problems = []
    if not ok:
        problems.append("job did not finish successfully")
    if fault in ("crash", "stall"):
        if not driver.host_manager.is_blacklisted("hostB"):
            problems.append("hostB was not blacklisted")
        if driver.world_id < 1:
            problems.append(f"no new incarnation (world_id="
                            f"{driver.world_id})")
        if len(done) != 2:
            problems.append(f"{len(done)} finishers, expected 2")
    else:  # drop: absorbed invisibly, full world finishes
        if len(done) != 3:
            problems.append(f"{len(done)} finishers, expected 3")
    if fault == "stall":
        # Postmortem assertion: the abandoned incarnation left a flight
        # dump whose join names the missing hostB slot.
        import importlib.util

        pm_path = os.path.join(REPO, "scripts", "postmortem.py")
        spec = importlib.util.spec_from_file_location("_postmortem",
                                                      pm_path)
        pm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pm)
        report = pm.build_report(flight_dir)
        if report["corrupt"]:
            problems.append(f"corrupt flight dumps: {report['corrupt']}")
        abandons = [r for r in report["ranks"].values()
                    if r["reason"] == "elastic.abandon"]
        if not abandons:
            problems.append(
                f"no elastic.abandon flight dump in {flight_dir} "
                f"({report['dumps']} dump(s))")
        elif not any("hostB" in s for a in abandons
                     for s in (a.get("extra") or {}).get(
                         "missing_slots", [])):
            problems.append(
                f"abandon dump does not name the missing hostB slot: "
                f"{[a.get('extra') for a in abandons]}")
    if len({r["weights"] for r in done}) > 1:
        problems.append(f"finishers disagree on weights: {done}")
    detail = (f"world_id={driver.world_id} done={len(done)} "
              f"counters={counters.counters(total=True)}")
    if verbose and problems:
        detail += f" records={records}"
    return not problems, detail + ("" if not problems
                                   else f" PROBLEMS={problems}")


def _run_ckpt_leg(script, log_file, worker_args, *, min_np, max_np,
                  join_timeout=180, quiesce=None):
    """One driver incarnation of the checkpoint scenario; returns the
    driver's join verdict (None when ``quiesce`` cut it short).

    ``quiesce`` is a predicate over the parsed log records: when it turns
    true the job is considered dead-by-design (the all-rank crash leg —
    the driver cannot re-form a world once every host is blacklisted, so
    joining would just burn the timeout) and the driver is stopped."""
    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner import safe_shell_exec

    driver = ElasticDriver(HostDiscoveryScript(script, 1), min_np=min_np,
                           max_np=max_np,
                           controller_addr_override="127.0.0.1")

    def _exec(slot, world_id):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "PYTHONPATH": REPO,
            "HOROVOD_HOSTNAME": slot.hostname,
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_ELASTIC_DRIVER_ADDR": "127.0.0.1",
            "HOROVOD_ELASTIC_DRIVER_PORT": str(driver.service_port),
            "HOROVOD_ELASTIC_DRIVER_KEY": driver.key.hex(),
            "HOROVOD_START_TIMEOUT": "30",
        })
        cmd = " ".join(shlex.quote(c) for c in [
            sys.executable, WORKER, "--log-file", log_file, *worker_args])
        return safe_shell_exec.execute(cmd, env=env)

    ok = None
    try:
        driver.start(_exec)
        if quiesce is None:
            ok = driver.join(timeout=join_timeout)
        else:
            deadline = time.monotonic() + join_timeout
            while time.monotonic() < deadline:
                if quiesce(_read_log(log_file)):
                    time.sleep(1.0)  # let os._exit land driver-side
                    break
                time.sleep(0.25)
    finally:
        driver.stop()
        driver.shutdown_service()
    return ok


def run_ckpt_once(seed, workdir, verbose=False):
    """Checkpoint soak iteration: an uninterrupted REFERENCE run, then a
    run whose every worker hard-crashes mid-training (in-memory elastic
    state is gone — min_np equals the world, so no surviving subset can
    re-form) and a fresh driver that must resume from the last committed
    checkpoint, finishing with a bit-identical loss trajectory."""
    from horovod_tpu.checkpoint import layout

    batches, crash_at, world = 8, 4, 3
    script = os.path.join(workdir, "discover.sh")
    with open(script, "w") as f:
        f.write("#!/bin/sh\necho hostA:2\necho hostB:1\n")
    os.chmod(script, 0o755)

    def leg(log_name, ckpt_dir, extra, **kw):
        log_file = os.path.join(workdir, log_name)
        wargs = ["--batches", str(batches), "--batch-sleep", "0.1",
                 "--ckpt-dir", ckpt_dir, *extra]
        ok = _run_ckpt_leg(script, log_file, wargs, min_np=world,
                           max_np=world, **kw)
        return ok, _read_log(log_file)

    problems = []

    # Leg 1: uninterrupted reference (its own checkpoint dir).
    ok_ref, ref = leg("ref.jsonl", os.path.join(workdir, "ckpt_ref"), [])
    ref_by_batch = {}
    for r in ref:
        if "batch" in r:
            ref_by_batch.setdefault(r["batch"], set()).add(r["weights"])
    if not ok_ref or len([r for r in ref if r.get("done")]) != world:
        problems.append("reference run did not finish cleanly")
    if any(len(v) > 1 for v in ref_by_batch.values()):
        problems.append(f"reference ranks disagree: {ref_by_batch}")

    # Leg 2: whole-job crash after committing batch `crash_at`.
    ckpt_dir = os.path.join(workdir, "ckpt")
    _, crashed = leg(
        "crash.jsonl", ckpt_dir, ["--exit-at-batch", str(crash_at)],
        join_timeout=60,
        quiesce=lambda recs: len([r for r in recs
                                  if r.get("batch") == crash_at]) >= world)
    committed = layout.list_steps(ckpt_dir)
    if not committed:
        problems.append("no committed checkpoint survived the crash")
    elif not 1 <= committed[-1] <= crash_at:
        problems.append(f"unexpected committed steps {committed}")

    # Leg 3: fresh driver over the same dir — resume, run to completion.
    ok_res, resumed = leg("resume.jsonl", ckpt_dir, [], join_timeout=180)
    done = [r for r in resumed if r.get("done")]
    starts = {r["resumed_from"] for r in resumed if "resumed_from" in r}
    if not ok_res or len(done) != world:
        problems.append(f"resume run: ok={ok_res} done={len(done)}")
    if starts != {committed[-1] if committed else -1}:
        problems.append(f"workers resumed from {starts}, last committed "
                        f"step is {committed}")
    if 0 in starts:
        problems.append("resume started from scratch, not the checkpoint")

    # The trajectory invariant: every logged (batch, weights) point of
    # the crashed + resumed runs must equal the uninterrupted run's.
    for r in [*crashed, *resumed]:
        if "batch" not in r:
            continue
        want = ref_by_batch.get(r["batch"])
        if want != {r["weights"]}:
            problems.append(
                f"batch {r['batch']}: resumed weights {r['weights']} != "
                f"uninterrupted {want}")
            break
    final = {r["weights"] for r in done}
    if ref_by_batch.get(batches) and final != ref_by_batch[batches]:
        problems.append(f"final weights {final} != reference "
                        f"{ref_by_batch[batches]}")

    detail = (f"committed={committed} resumed_from={sorted(starts)} "
              f"done={len(done)}")
    if verbose and problems:
        detail += f" ref={ref} crashed={crashed} resumed={resumed}"
    return not problems, detail + ("" if not problems
                                   else f" PROBLEMS={problems}")


def main():
    parser = argparse.ArgumentParser(
        description="loop the chaos-driven elastic recovery scenario")
    parser.add_argument("-n", "--iterations", type=int, default=10)
    parser.add_argument("--fault", choices=FAULTS + ("mixed",),
                        default="crash")
    parser.add_argument("--seed", type=int, default=0,
                        help="base chaos seed (iteration i uses seed+i)")
    parser.add_argument("--keep-going", action="store_true",
                        help="run all iterations even after a failure")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    failures = 0
    for i in range(args.iterations):
        fault = FAULTS[i % len(FAULTS)] if args.fault == "mixed" \
            else args.fault
        t0 = time.monotonic()
        with tempfile.TemporaryDirectory(prefix="chaos_soak_") as workdir:
            try:
                if fault == "ckpt":
                    ok, detail = run_ckpt_once(args.seed + i, workdir,
                                               verbose=args.verbose)
                else:
                    ok, detail = run_once(fault, args.seed + i, workdir,
                                          verbose=args.verbose)
            except Exception as e:  # a crash of the harness is a failure
                ok, detail = False, f"harness exception: {e!r}"
        status = "ok" if ok else "FAIL"
        print(f"[{i + 1}/{args.iterations}] fault={fault} "
              f"seed={args.seed + i} {status} "
              f"({time.monotonic() - t0:.1f}s) {detail}", flush=True)
        if not ok:
            failures += 1
            if not args.keep_going:
                break
    print(f"chaos soak: {failures} failure(s)")
    sys.exit(min(failures, 125))


if __name__ == "__main__":
    main()
