#!/usr/bin/env bash
# Continuous perf regression gate (ROADMAP open item 5, first brick).
#
# Turns the BENCH_r*.json artifact trail from a record into a CONTRACT:
# each gated leg runs bench.py now, extracts the one-line JSON metric,
# and fails (rc 1) when the measured value regresses below
# PERF_GATE_TOL (default 0.60, i.e. the run must keep >= 60% of the
# recorded trajectory's best same-platform value — CPU-mesh numbers are
# noisy; tighten on real hardware) of:
#   * the recorded trajectory: best same-platform value for that metric
#     across BENCH_r*.json (training legs), and
#   * the seeded serve baseline BENCH_serve_baseline.json (the new
#     --serve leg) — created by the first run, refreshed with
#     PERF_GATE_UPDATE=1.
#
# Usage:
#   scripts/perf_gate.sh             # gate the serve leg (default)
#   PERF_GATE_LEGS="serve train" scripts/perf_gate.sh
#   PERF_GATE_LEGS="zero1 zero2 zero3" scripts/perf_gate.sh
#   PERF_GATE_LEGS="plan" scripts/perf_gate.sh  # wire-plan equivalence
#                     matrix + quantized+zero3+overlap combined leg
#   PERF_GATE_LEGS="fused" scripts/perf_gate.sh # fused-kernel A/B:
#                     parity + nonzero saved-HBM hard gates, step time
#                     vs trajectory (docs/fused-kernels.md)
#   PERF_GATE_LEGS="cost" scripts/perf_gate.sh  # cost-model drift:
#                     |predicted - measured| wire-ms within
#                     PERF_GATE_COST_DRIFT (docs/cost-model.md)
#   PERF_GATE_LEGS="pp" scripts/perf_gate.sh    # pipeline parallelism:
#                     parity + bubble <= PERF_GATE_PP_BUBBLE x the
#                     GPipe analytic bound + send-leg wire-ms drift
#                     (docs/pipeline.md)
#   PERF_GATE_LEGS="pp4d" scripts/perf_gate.sh  # 4-D composition:
#                     PP x EP x ZeRO-3 x quantized x overlap in one
#                     compiled step — parity + bubble-fill predicted
#                     == accounted + a2a wire-ms drift
#                     (docs/pipeline.md, docs/moe.md)
#   PERF_GATE_LEGS="moe" scripts/perf_gate.sh   # expert-parallel MoE:
#                     forced-routing parity + dropped-token fraction
#                     <= PERF_GATE_MOE_DROPPED + a2a wire-ms drift
#                     (docs/moe.md)
#   PERF_GATE_LEGS="serve_disagg" scripts/perf_gate.sh # disaggregated
#                     serving A/B: goodput >= the same-run symmetric
#                     baseline, bit-identical outputs, nonzero prefix
#                     hits, migrations with zero byte drift, stall
#                     budget (docs/serving.md)
#   PERF_GATE_LEGS="soak" scripts/perf_gate.sh  # self-healing soak:
#                     the smoke gauntlet (preempt + flap + resize) must
#                     pass every soak-report gate (docs/robustness.md)
#   PERF_GATE_LEGS="compile" scripts/perf_gate.sh # compile-once
#                     runtime: warm rerun against the populated
#                     executable cache must pay ZERO compiles with TTFS
#                     >= PERF_GATE_COMPILE_TTFS (default 0.30) below
#                     cold, and the background-precompiled resize must
#                     stall under the cold rebuild (docs/compile.md)
#   PERF_GATE_UPDATE=1 scripts/perf_gate.sh   # re-seed baselines
#
# The zero<stage> legs gate the --zero-stage A/B STRUCTURALLY against
# the replicated baseline measured in the same run (docs/zero.md): the
# sharded state components must stay within PERF_GATE_ZERO_SLACK
# (default 1.30, bucket padding) of 1/world — opt state at every stage,
# grad accumulation at stage >= 2, params at stage 3 — the async
# checkpoint stall must stay under PERF_GATE_CKPT_STALL_FRAC (default
# 0.10) of a step, and the stage-parity probe must have passed.
# Throughput additionally gates against the recorded trajectory like
# the train leg.
#
# Every verdict is also appended as a metrics-JSONL snapshot to
# PERF_GATE_METRICS_JSONL (default .perf_gate/metrics.jsonl — a
# gitignored directory; set to 0 to disable): per-leg measured/baseline/
# tolerance gauges + pass/fail, so the regression history is queryable
# data (docs/observability.md).
set -euo pipefail
cd "$(dirname "$0")/.."

LEGS="${PERF_GATE_LEGS:-serve}"
TOL="${PERF_GATE_TOL:-0.60}"
UPDATE="${PERF_GATE_UPDATE:-0}"
FAIL=0

run_leg() {  # run_leg <name> <bench args...>
    local name="$1"; shift
    echo "== perf gate: $name leg ==" >&2
    local out
    out=$(JAX_PLATFORMS=cpu python bench.py "$@" | tail -n 1)
    echo "$out"
    PERF_GATE_LEG="$name" PERF_GATE_TOL="$TOL" PERF_GATE_UPDATE="$UPDATE" \
        python scripts/_perf_gate_check.py "$out" || FAIL=1
}

for leg in $LEGS; do
    case "$leg" in
        serve)
            run_leg serve --serve --platform cpu --cpu-devices 8 \
                --serve-requests "${PERF_GATE_SERVE_REQUESTS:-12}" \
                --serve-rate 50
            ;;
        serve_disagg)
            # Disaggregated serving gate (docs/serving.md): the --disagg
            # A/B measures a symmetric baseline in the SAME run, so the
            # gate is structural — goodput >= the baseline's (x
            # PERF_GATE_DISAGG_GOODPUT), zero drops on both legs,
            # bit-identical greedy outputs (migration + prefix COW +
            # spec decode), nonzero prefix hit rate, >= 1 migration with
            # zero predicted-vs-accounted byte drift, the migration
            # stall budget (PERF_GATE_DISAGG_STALLS decode steps), and
            # p99 within PERF_GATE_DISAGG_P99 x the baseline's tail.
            run_leg serve_disagg --serve \
                --disagg "${PERF_GATE_DISAGG_SPLIT:-3:1}" \
                --platform cpu --cpu-devices 8 \
                --serve-requests "${PERF_GATE_SERVE_REQUESTS:-12}" \
                --serve-rate 50
            ;;
        train)
            run_leg train --platform cpu --cpu-devices 8 \
                --model resnet18 --batch-size 2 --image-size 64 \
                --num-warmup 1 --num-iters 3 --num-batches-per-iter 2
            ;;
        zero1|zero2|zero3)
            run_leg "$leg" --zero-stage "${leg#zero}" --platform cpu \
                --cpu-devices 8 --model resnet18 --batch-size 2 \
                --image-size 64 --num-warmup 1 --num-iters 3 \
                --num-batches-per-iter 2
            ;;
        plan)
            # Wire-plan gate (docs/wire-plan.md): (1) the plan-equivalence
            # matrix — the compiler must stay bit-identical to the
            # pre-refactor paths for every knob combination — then (2) a
            # combined quantized + ZeRO-3 + overlap plan-compiled bench
            # step, throughput gated against the recorded trajectory.
            echo "== perf gate: plan leg (equivalence matrix) ==" >&2
            if ! JAX_PLATFORMS=cpu python -m pytest -q tests/test_plan.py \
                -k "TestWireEquivalence or TestOptimizerMatrix or TestThreeLevel"
            then
                echo "perf gate [plan]: equivalence matrix FAILED" >&2
                FAIL=1
            fi
            run_leg plan --zero-stage 3 --quantized --overlap \
                --mesh-shape 2x4 --platform cpu --cpu-devices 8 \
                --model resnet18 --batch-size 2 --image-size 64 \
                --num-warmup 1 --num-iters 3 --num-batches-per-iter 2
            ;;
        fused)
            # Fused compute-collective kernels (docs/fused-kernels.md):
            # the --fused A/B hard-fails itself on parity loss or
            # never-engaged kernels; the checker re-asserts both and
            # gates step time against the trajectory (lower is better).
            run_leg fused --fused --zero-stage 3 --overlap \
                --platform cpu --cpu-devices 8 --batch-size 2 \
                --num-iters 3 --num-batches-per-iter 2
            ;;
        pp)
            # Pipeline-parallel gate (docs/pipeline.md): interleaved-1F1B
            # A/B — parity vs the dense model, measured bubble fraction
            # at or under PERF_GATE_PP_BUBBLE x the analytic GPipe bound
            # (S-1)/(M+S-1), send-leg predicted-vs-measured wire-ms
            # within PERF_GATE_COST_DRIFT, throughput vs trajectory.
            run_leg pp --pp 4 --zero-stage 3 --quantized --overlap \
                --platform cpu --cpu-devices 8 \
                --num-iters 2 --num-batches-per-iter 2
            ;;
        pp4d)
            # 4-D composition gate (docs/pipeline.md, docs/moe.md):
            # the combined --pp x --moe leg — zero-bubble-capable
            # pipeline over per-(stage, expert-group) ZeRO-3 cells
            # with int8+EF a2a and bucket flights streamed into the
            # idle ticks. The bench hard-fails itself on parity / fill
            # drift; the checker re-asserts parity, the fill contract
            # (nonzero hidden bytes, accounted == predicted), engaged
            # a2a + send wire, and the a2a wire-ms drift, then
            # throughput vs trajectory.
            run_leg pp4d --pp 2 --moe 2 --zero-stage 3 --quantized \
                --overlap --platform cpu --cpu-devices 8 \
                --num-iters 2 --num-batches-per-iter 2
            ;;
        cost)
            # Cost-model drift gate (docs/cost-model.md): the quantized
            # A/B's JSON carries wire_ms.predicted (the analytic
            # planner) vs wire_ms.modeled (the traced program's actual
            # wire bytes at the modeled bandwidths); the checker gates
            # |predicted - measured| within PERF_GATE_COST_DRIFT
            # (default 0.25 relative) and throughput against the
            # trajectory like a train leg.
            run_leg cost --quantized --platform cpu --cpu-devices 8 \
                --model resnet18 --batch-size 2 --image-size 64 \
                --num-warmup 1 --num-iters 3 --num-batches-per-iter 2
            ;;
        moe)
            # Expert-parallel MoE gate (docs/moe.md): the --moe A/B
            # hard-checks its own forced-routing parity; the checker
            # re-asserts it plus dropped-token fraction and the a2a
            # predicted-vs-measured wire-ms drift, then throughput vs
            # the trajectory.
            run_leg moe --moe 4 --quantized \
                --platform cpu --cpu-devices 8 \
                --num-iters 2 --num-batches-per-iter 2
            ;;
        soak)
            # Self-healing soak gate (docs/robustness.md): the CI-shaped
            # gauntlet (one preemption + one flap + one resize against
            # the durable elastic run) must pass every gate in its
            # soak-report JSON — recovery, loss trajectory vs the
            # uninterrupted reference, commit cadence, a deadline-met
            # priority snapshot, monotone counters.
            echo "== perf gate: soak leg ==" >&2
            SOAK_REPORT="${TMPDIR:-/tmp}/perf_gate_soak_report.json"
            rm -f "$SOAK_REPORT"
            scripts/soak_smoke.sh --report "$SOAK_REPORT" >&2 || FAIL=1
            if [ -f "$SOAK_REPORT" ]; then
                PERF_GATE_LEG=soak PERF_GATE_TOL="$TOL" \
                    PERF_GATE_UPDATE="$UPDATE" \
                    python scripts/_perf_gate_check.py \
                    "$(cat "$SOAK_REPORT")" || FAIL=1
            else
                echo "perf gate [soak]: no soak report written" >&2
                FAIL=1
            fi
            ;;
        compile)
            # Compile-once gate (docs/compile.md): the smoke runs the
            # cold/warm A/B + the serve resize leg and writes a report;
            # the checker hard-gates zero warm compiles, the TTFS cut,
            # and background-precompiled stall < cold rebuild.
            echo "== perf gate: compile leg ==" >&2
            COMPILE_REPORT="${TMPDIR:-/tmp}/perf_gate_compile_report.json"
            rm -f "$COMPILE_REPORT"
            scripts/compile_smoke.sh --report "$COMPILE_REPORT" >&2 || FAIL=1
            if [ -f "$COMPILE_REPORT" ]; then
                PERF_GATE_LEG=compile PERF_GATE_TOL="$TOL" \
                    PERF_GATE_UPDATE="$UPDATE" \
                    python scripts/_perf_gate_check.py \
                    "$(cat "$COMPILE_REPORT")" || FAIL=1
            else
                echo "perf gate [compile]: no compile report written" >&2
                FAIL=1
            fi
            ;;
        *)
            echo "unknown gate leg: $leg (serve|serve_disagg|train|zero{1,2,3}|plan|fused|cost|pp|pp4d|moe|soak|compile)" >&2
            exit 2
            ;;
    esac
done

if [ "$FAIL" -ne 0 ]; then
    echo "PERF GATE: REGRESSION DETECTED (see above)" >&2
    exit 1
fi
echo "PERF GATE: all legs within tolerance $TOL" >&2
