#!/usr/bin/env python
"""Cost-model smoke (CI brick for docs/cost-model.md), run by
scripts/cost_smoke.sh on the 8-device virtual CPU mesh:

1. calibrate the link classes with the microbenchmark sweep and prove
   the store round-trips (geometry-keyed JSON beside the autotune
   cache);
2. enumerate + price the legal plan space: the ranked shortlist must be
   nonempty and sorted by predicted step-wire milliseconds;
3. lower the top-priced candidate and assert it is BIT-identical to the
   same knobs threaded without the pricing machinery — the cost model
   ranks plans, it must never change what they compute.
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.ops import fusion  # noqa: E402
from horovod_tpu.plan import calibrate as hvd_cal  # noqa: E402
from horovod_tpu.plan import planner as hvd_planner  # noqa: E402


def main():
    assert len(jax.devices()) >= 8, "need 8 virtual CPU devices"
    hvd.init(devices=jax.devices()[:8], mesh_shape=(2, 4))
    mesh = hvd.mesh()

    # -- 1. calibrate + persistence round-trip -------------------------
    calib = hvd_cal.calibrate_links(sizes=(4096, 32768, 262144), reps=2)
    assert calib.links, "sweep fitted no link classes"
    for hop, lk in calib.links.items():
        assert lk.bandwidth_gbps > 0 and np.isfinite(lk.bandwidth_gbps), \
            f"{hop}: bad bandwidth {lk.bandwidth_gbps}"
        assert lk.latency_us >= 0, f"{hop}: negative latency"
        assert lk.quant_rate_gbps > 0, f"{hop}: bad quant rate"
    loaded = hvd_cal.load_calibration()
    assert loaded is not None, \
        f"stored calibration did not load back from " \
        f"{hvd_cal.calibration_path()}"
    assert loaded.geometry == calib.geometry
    assert set(loaded.links) == set(calib.links)
    model = hvd_cal.get_cost_model()
    assert model.source == "calibrated", model.source
    print(f"cost smoke: calibrated {sorted(calib.links)} on "
          f"{calib.geometry} -> "
          f"{ {h: round(lk.bandwidth_gbps, 2) for h, lk in calib.links.items()} } GB/s")

    # -- 2. shortlist: nonempty, ranked ascending ----------------------
    shortlist = hvd_planner.shortlist(
        8 * 1024 * 1024, quantized=True, tune_overlap=True,
        tune_fused=True, model=model)
    assert shortlist, "shortlist is empty"
    preds = [pp.predicted_ms for pp in shortlist]
    assert preds == sorted(preds), "shortlist is not ranked"
    assert all(p >= 0 for p in preds)
    top = shortlist[0]
    print(f"cost smoke: {len(shortlist)} priced plans, top "
          f"{top.plan.encode()} @ {top.predicted_ms:.4f} ms "
          f"(worst {preds[-1]:.4f} ms)")

    # -- 3. top candidate lowers bit-identically to the unpriced path --
    rs = np.random.RandomState(7)
    tree = {"w": jnp.asarray(rs.randn(8, 96, 41), jnp.float32),
            "b": jnp.asarray(rs.randn(8, 23), jnp.float32)}
    p = top.params

    def run(tuned_params=None, **knobs):
        def f(t):
            local = jax.tree.map(lambda v: v[0], t)
            return fusion.allreduce_pytree(
                local, op=hvd.Sum, tuned_params=tuned_params,
                quantized=True, **knobs)

        return hvd.shard_map(f, mesh=mesh, in_specs=P(hvd.HVD_AXES),
                             out_specs=P())(tree)

    out_priced = run(tuned_params=p)
    out_plain = run(
        threshold_bytes=p.fusion_threshold_bytes, block=p.quant_block,
        hierarchical=p.hierarchical_allreduce, overlap=p.overlap,
        num_comm_streams=p.num_comm_streams, fused=p.fused)
    for k in tree:
        np.testing.assert_array_equal(
            np.asarray(out_priced[k]), np.asarray(out_plain[k]),
            err_msg=f"top shortlist candidate diverges from the "
                    f"unpriced lowering on leaf {k!r}")
    print(f"cost smoke OK: top candidate "
          f"(thr={p.fusion_threshold_bytes >> 20}MiB block="
          f"{p.quant_block} streams={p.num_comm_streams} "
          f"fused={p.fused}) lowers bit-identically to the unpriced "
          f"plan")


if __name__ == "__main__":
    main()
