#!/bin/bash
# One-shot hardware measurement sweep for the round-5 features.
# Run the moment the TPU tunnel is reachable:
#   bash scripts/tpu_round5_measurements.sh [OUTDIR]
# Captures, in order of VERDICT r4 priority:
#   1. ResNet-50 + GPT-124M/350M regressions vs round 3 (2271 img/s,
#      117.2k / 42.9k tok/s)
#   2. the MFU A/B levers: --fused-ln, --remat (+batch sweep), and a
#      fresh-cache kernel-autotune run (first-run sweep -> second-run
#      cache hit in the log tail)
#   3. GPT-350M profile for the MFU gap attribution table
#   4. the elastic-on-TPU smoke (PJRT teardown/re-acquisition)
# The targeted re-run after session 1's relay death is
# scripts/tpu_round5b_measurements.sh (same legs minus the ones that
# landed, plus the warmed-cache best-config attempt).
#
# Session learnings baked in (first r5 chip session, BENCH_r05_sweep/):
#   - GPT train-step compiles take 150-200 s through the relay, so the
#     old 560 s cap was too tight for the autotune legs (compile + sweep)
#     and a `timeout`-kill mid-remote-compile can take the RELAY down
#     with it (PALLAS_AXON_REMOTE_COMPILE posts compiles to the relay) -
#     every later leg then burns its full budget on probe timeouts.
#     Budgets are per-leg now, generous for compile-heavy legs.
#   - Probe the relay before each leg and skip (not fall back) when it is
#     down: a CPU-fallback "measurement" is worthless and costs minutes.
#   - Since 27b814b the first-use autotune sweep really runs, so every
#     leg that is NOT deliberately measuring the autotuner pins
#     HOROVOD_KERNEL_AUTOTUNE=0: keeps baselines comparable to the
#     hand-tuned defaults the README cites, and keeps a multi-candidate
#     compile sweep from blowing a budget sized for one compile.
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/measure_lib.sh
OUT=${1:-$PWD/BENCH_r05_sweep}
mkdir -p "$OUT"

run 560  resnet50          env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py
run 700  gpt124m           env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --batch-size 16
run 700  gpt350m           env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --gpt-scale 350m --batch-size 8
run 700  gpt350m_fusedln   env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --gpt-scale 350m --batch-size 8 --fused-ln
run 700  gpt350m_remat16   env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --gpt-scale 350m --batch-size 16 --remat
run 700  gpt124m_fusedln   env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --batch-size 16 --fused-ln
# Fresh-cache autotune: sweep on run 1 (compile per candidate -> the big
# budget), cache hit on run 2. rm guarantees "fresh" even on a re-run.
AT_CACHE=$OUT/autotune_cache.json
rm -f "$AT_CACHE"
run 2400 gpt124m_autotune1 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" HOROVOD_KERNEL_AUTOTUNE=1 python bench.py --model gpt --batch-size 16
run_if_done gpt124m_autotune1 700  gpt124m_autotune2 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" HOROVOD_KERNEL_AUTOTUNE=1 python bench.py --model gpt --batch-size 16
run 900  gpt350m_profile   env HOROVOD_KERNEL_AUTOTUNE=0 python bench.py --model gpt --gpt-scale 350m --batch-size 8 --profile "$OUT/profile"
run 700  elastic_smoke     env HOROVOD_KERNEL_AUTOTUNE=0 python examples/elastic_tpu_smoke.py --cycles 3 --steps 20 --reset-backend
echo "all artifacts in $OUT ($MEASURE_MISSED legs missed)"
grep -h '"metric"' "$OUT"/*.log 2>/dev/null | tail -20
exit $((MEASURE_MISSED > 0))
