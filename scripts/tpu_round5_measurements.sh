#!/bin/bash
# One-shot hardware measurement sweep for the round-5 features.
# Run the moment the TPU tunnel is reachable:
#   bash scripts/tpu_round5_measurements.sh [OUTDIR]
# Captures, in order of VERDICT r4 priority:
#   1. ResNet-50 + GPT-124M/350M regressions vs round 3 (2271 img/s,
#      117.2k / 42.9k tok/s)
#   2. the MFU A/B levers: --fused-ln, --remat (+batch sweep), and a
#      fresh-cache kernel-autotune run (first-run sweep -> second-run
#      cache hit in the log tail)
#   3. GPT-350M profile for the MFU gap attribution table
#   4. the elastic-on-TPU smoke (PJRT teardown/re-acquisition)
#
# Session learnings baked in (first r5 chip session, BENCH_r05_sweep/):
#   - GPT train-step compiles take 150-200 s through the relay, so the
#     old 560 s cap was too tight for the autotune legs (compile + sweep)
#     and a `timeout`-kill mid-remote-compile can take the RELAY down
#     with it (PALLAS_AXON_REMOTE_COMPILE posts compiles to the relay) -
#     every later leg then burns its full budget on probe timeouts.
#     Budgets are per-leg now, generous for compile-heavy legs.
#   - Probe the relay before each leg and skip (not fall back) when it is
#     down: a CPU-fallback "measurement" is worthless and costs minutes.
set -u
cd "$(dirname "$0")/.." || exit 1
OUT=${1:-$PWD/BENCH_r05_sweep}
mkdir -p "$OUT"

relay_up() {
  # No relay configured (real TPU VM): treat as up.
  [ -z "${PALLAS_AXON_POOL_IPS:-}" ] && return 0
  python - <<'EOF'
import os, socket, sys
port = int(os.environ.get("HOROVOD_AXON_RELAY_PORT", "8083"))
for ip in os.environ["PALLAS_AXON_POOL_IPS"].split(","):
    try:
        with socket.create_connection((ip.strip(), port), timeout=3):
            sys.exit(0)
    except OSError:
        pass
sys.exit(1)
EOF
}

run() {
  budget=$1; name=$2; shift 2
  if ! relay_up; then
    echo "--- $name SKIPPED (relay down; a CPU fallback would measure nothing)"
    return
  fi
  echo "=== $name: $* ==="
  timeout "$budget" "$@" >"$OUT/$name.log" 2>&1
  rc=$?
  tail -3 "$OUT/$name.log"
  echo "--- $name rc=$rc"
  if [ "$rc" = 124 ]; then
    # The kill may have wedged the client/relay; give it a recovery
    # window before the next leg's probe burns its budget.
    echo "--- $name timed out; 60 s relay recovery pause"
    sleep 60
  fi
}

run 560  resnet50          python bench.py
run 700  gpt124m           python bench.py --model gpt --batch-size 16
run 700  gpt350m           python bench.py --model gpt --gpt-scale 350m --batch-size 8
run 700  gpt350m_fusedln   python bench.py --model gpt --gpt-scale 350m --batch-size 8 --fused-ln
run 700  gpt350m_remat16   python bench.py --model gpt --gpt-scale 350m --batch-size 16 --remat
run 700  gpt124m_fusedln   python bench.py --model gpt --batch-size 16 --fused-ln
# Fresh-cache autotune: sweep on run 1 (compile per candidate -> the big
# budget), cache hit on run 2.
AT_CACHE=$OUT/autotune_cache.json
run 2400 gpt124m_autotune1 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" python bench.py --model gpt --batch-size 16
run 700  gpt124m_autotune2 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" python bench.py --model gpt --batch-size 16
run 900  gpt350m_profile   python bench.py --model gpt --gpt-scale 350m --batch-size 8 --profile "$OUT/profile"
run 700  elastic_smoke     python examples/elastic_tpu_smoke.py --cycles 3 --steps 20 --reset-backend
echo "all artifacts in $OUT"
grep -h '"metric"' "$OUT"/*.log 2>/dev/null | tail -20
