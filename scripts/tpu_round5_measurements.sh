#!/bin/bash
# One-shot hardware measurement sweep for the round-5 features.
# Run the moment the TPU tunnel is reachable:
#   bash scripts/tpu_round5_measurements.sh [OUTDIR]
# Captures, in order of VERDICT r4 priority:
#   1. ResNet-50 + GPT-124M/350M regressions vs round 3 (2271 img/s,
#      117.2k / 42.9k tok/s)
#   2. the MFU A/B levers: --fused-ln, --remat (+batch sweep), and a
#      fresh-cache kernel-autotune run (first-run sweep -> second-run
#      cache hit in the log tail)
#   3. GPT-350M profile for the MFU gap attribution table
#   4. the elastic-on-TPU smoke (PJRT teardown/re-acquisition)
set -u
cd "$(dirname "$0")/.." || exit 1
OUT=${1:-$PWD/BENCH_r05_sweep}
mkdir -p "$OUT"
run() {
  name=$1; shift
  echo "=== $name: $* ==="
  timeout 560 "$@" >"$OUT/$name.log" 2>&1
  rc=$?
  tail -3 "$OUT/$name.log"
  echo "--- $name rc=$rc"
}

run resnet50          python bench.py
run gpt124m           python bench.py --model gpt --batch-size 16
run gpt350m           python bench.py --model gpt --gpt-scale 350m --batch-size 8
run gpt350m_fusedln   python bench.py --model gpt --gpt-scale 350m --batch-size 8 --fused-ln
run gpt350m_remat16   python bench.py --model gpt --gpt-scale 350m --batch-size 16 --remat
run gpt124m_fusedln   python bench.py --model gpt --batch-size 16 --fused-ln
# Fresh-cache autotune: sweep on run 1, cache hit on run 2.
AT_CACHE=$OUT/autotune_cache.json
run gpt124m_autotune1 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" python bench.py --model gpt --batch-size 16
run gpt124m_autotune2 env "HOROVOD_AUTOTUNE_CACHE=$AT_CACHE" python bench.py --model gpt --batch-size 16
run gpt350m_profile   python bench.py --model gpt --gpt-scale 350m --batch-size 8 --profile "$OUT/profile"
run elastic_smoke     python examples/elastic_tpu_smoke.py --cycles 3 --steps 20 --reset-backend
echo "all artifacts in $OUT"
grep -h '"metric"' "$OUT"/*.log 2>/dev/null | tail -20
