#!/usr/bin/env bash
# Fused compute-collective kernel smoke (docs/fused-kernels.md): the
# `bench.py --fused` A/B on the 8-device virtual CPU mesh, interpret-mode
# Pallas kernels.
#
# Asserts: rc 0 (the bench itself hard-fails on fused-vs-unfused parity
# loss or never-engaged kernels), a passed parity probe, nonzero saved
# HBM round-trip bytes, nonzero `comm.fused.*` metrics in the embedded
# snapshot, and a positive modeled step-time saving. Runtime ~1 min.
#
# Usage: scripts/fused_smoke.sh [extra bench.py args...]
#   FUSED_SMOKE_KNOBS="--quantized" scripts/fused_smoke.sh   # int8 legs
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(JAX_PLATFORMS=cpu python bench.py --fused --zero-stage 3 --overlap \
    ${FUSED_SMOKE_KNOBS:-} \
    --platform cpu --cpu-devices 8 --batch-size 2 \
    --num-iters 2 --num-batches-per-iter 2 \
    "$@" | tail -n 1)
echo "$OUT"

python - "$OUT" <<'EOF'
import json
import sys

rec = json.loads(sys.argv[1])
assert rec["metric"] == "fused_matmul_collective_step_ms", rec["metric"]
assert rec["parity"]["ok"], f"parity failed: {rec['parity']}"
assert rec["hbm_saved_bytes_per_step"] > 0, "kernels never engaged"
assert rec["fused_kernel_calls"] > 0, "zero fused kernel calls"
assert rec["modeled"]["saving_ms"] > 0, "zero modeled saving"
counters = rec["metrics_snapshot"]["counters"]
fused_counters = {k: v for k, v in counters.items()
                  if k.startswith("comm.fused.")}
assert fused_counters and all(v > 0 for v in fused_counters.values()), \
    f"comm.fused.* metrics missing or zero: {fused_counters}"
print(f"fused smoke OK: parity max_rel_err "
      f"{rec['parity']['max_rel_err']:.2e}, "
      f"{rec['fused_kernel_calls']} kernel calls, "
      f"{rec['hbm_saved_bytes_per_step'] / 1e3:.1f} kB HBM round-trip "
      f"saved/step/dev (modeled {rec['modeled']['saving_ms']:.4f} ms at "
      f"{rec['modeled']['hbm_gbps']:.0f} GB/s), plan {rec['plan']}")
EOF
