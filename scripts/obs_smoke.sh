#!/usr/bin/env bash
# Observability smoke (CI brick for docs/observability.md): run one short
# bench leg with the timeline AND the metrics JSONL sink enabled, then
# assert scripts/obs_report.py joins them into a coherent report —
# nonzero wire bytes, balanced spans, zero stalls, and a
# comm_hidden_fraction that reproduces the bench-reported value within 1%.
# A second, forensics leg then closes the crash loop on the CPU mesh:
# an injected straggler must be detected and attributed with zero
# clean-run false positives, an injected crash must leave an atomic
# flight dump scripts/postmortem.py names the crashing rank from, and
# armed forensics (flight ring + straggler accounting) must cost <1% of
# the bench leg's measured step.
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="${OBS_SMOKE_TMP:-$(mktemp -d)}"
mkdir -p "$TMP"
trap '[ -z "${OBS_SMOKE_TMP:-}" ] && rm -rf "$TMP"' EXIT
echo "== obs smoke: artifacts in $TMP ==" >&2

JAX_PLATFORMS=cpu \
HOROVOD_TIMELINE="$TMP/tl.json" \
HOROVOD_METRICS_JSONL="$TMP/metrics.jsonl" \
python bench.py --overlap --platform cpu --cpu-devices 8 \
    --model resnet18 --batch-size 2 --image-size 64 \
    --num-warmup 1 --num-iters 2 --num-batches-per-iter 1 \
    | tail -n 1 > "$TMP/bench.json"

python scripts/obs_report.py --timeline "$TMP/tl.json" \
    --metrics "$TMP/metrics.jsonl" --json "$TMP/report.json"

python - "$TMP" <<'PY'
import json, sys
tmp = sys.argv[1]
report = json.load(open(f"{tmp}/report.json"))
bench = json.load(open(f"{tmp}/bench.json"))

assert report["spans_balanced"], report["span_imbalance"]
assert report["total_spans"] > 0, "no spans recorded"
wb = report["wire_budget"]
assert wb["ici_bytes_per_step_device"] > 0, "zero ICI wire bytes"
assert not report["stalls"] and report["stall_warnings"] == 0, \
    f"unexpected stalls: {report['stalls']}"
got, want = report["comm_hidden_fraction"], bench["comm_hidden_fraction"]
assert abs(got - want) <= 0.01, \
    f"hidden fraction mismatch: report {got} vs bench {want}"
assert bench["metrics_snapshot"]["histograms"].get("step.time_ms", {}) \
    .get("count", 0) > 0, "bench JSON missing the step-latency histogram"
# the bench leg ran its own straggler detection: a clean run must have
# flagged nothing, and the per-rank phase gauges must be present
snap = bench["metrics_snapshot"]
assert not any(k.startswith("straggler.detected")
               for k in snap["counters"]), \
    f"clean-run false positive: {snap['counters']}"
assert any(k.startswith("straggler.phase_ms")
           for k in snap["gauges"]), "no straggler phase gauges"
print(f"obs smoke OK: {report['total_spans']} spans, "
      f"ICI {wb['ici_bytes_per_step_device']/1e6:.2f} MB/step, "
      f"hidden fraction {got:.4f} (bench {want:.4f}), 0 stalls")
PY

echo "== obs forensics leg ==" >&2
JAX_PLATFORMS=cpu python - "$TMP" <<'PY'
import json, os, subprocess, sys, time
tmp = sys.argv[1]
bench = json.load(open(f"{tmp}/bench.json"))
step_ms = bench["step_ms_median"]

# -- 1. injected straggler: detected, attributed, zero clean-run FPs --
from horovod_tpu.monitor.registry import MetricsRegistry
from horovod_tpu.monitor.straggler import StragglerDetector

def drive(delay_rank, steps=3):
    reg = MetricsRegistry(enabled=True)
    dets = [StragglerDetector(reg, world=4, rank=r) for r in range(4)]
    found = []
    for step in range(steps):
        for r, det in enumerate(dets):
            det.record_phase("compute", 100.0)
            det.record_phase("wire.dcn",
                             10.0 + (90.0 if r == delay_rank else 0.0))
            det.end_step(step)
        found += dets[0].detect(snapshot=reg.snapshot())
    return found

found = drive(delay_rank=2)
assert found and {(d["rank"], d["phase"]) for d in found} == \
    {(2, "wire.dcn")}, f"bad attribution: {found}"
assert drive(delay_rank=None) == [], "clean-run false positive"

# -- 2. injected crash -> atomic dump -> postmortem names the rank --
flight = os.path.join(tmp, "flight")
code = (
    "import horovod_tpu as hvd\n"
    "from horovod_tpu import chaos\n"
    "import jax.numpy as jnp\n"
    "hvd.init()\n"
    "chaos.configure(chaos.FaultPlan(seed=5).add(\n"
    "    'collective.eager', 'crash', after=2))\n"
    "for i in range(9):\n"
    "    hvd.allreduce(jnp.ones(2), name=f'smoke.{i}')\n"
    "    hvd.monitor.flight_recorder().mark_step(i)\n")
env = dict(os.environ, JAX_PLATFORMS="cpu",
           HOROVOD_FLIGHT_RECORDER_DIR=flight)
p = subprocess.run([sys.executable, "-c", code], env=env,
                   capture_output=True, text=True, timeout=300)
assert p.returncode != 0, "chaos crash did not kill the process"
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__))))
import importlib.util
spec = importlib.util.spec_from_file_location(
    "_postmortem", os.path.join("scripts", "postmortem.py"))
pm = importlib.util.module_from_spec(spec)
spec.loader.exec_module(pm)
report = pm.build_report(flight)
assert report["dumps"] >= 1 and not report["corrupt"], report
assert report["crashed_ranks"] == ["rank0"], report["crashed_ranks"]
row = report["ranks"]["rank0"]
assert row["reason"] == "chaos.crash"
assert row["last_step"] is not None and row["last_step"] <= 2

# -- 3. hard overhead gate: armed forensics <1% of the measured step --
from horovod_tpu.monitor.flight import FlightRecorder
fr = FlightRecorder(capacity=4096, snapshot_every=1024)
reg = MetricsRegistry(enabled=True)
det = StragglerDetector(reg, world=8, rank=0)
n = 300
t0 = time.perf_counter()
for i in range(n):
    for j in range(4):
        fr.record("FLIGHT:COLLECTIVE", tid="flight",
                  args={"name": f"op.{i}.{j}", "ms": 1.0})
    for ph in ("compute", "wire.ici", "wire.dcn", "wire.pod",
               "pp_bubble", "ckpt"):
        det.record_phase(ph, 1.0)
    det.end_step(i)
overhead_ms = (time.perf_counter() - t0) / n * 1e3
frac = overhead_ms / step_ms
assert frac < 0.01, (
    f"armed forensics {overhead_ms:.4f} ms vs step {step_ms:.2f} ms "
    f"({100*frac:.2f}% >= 1%)")
print(f"obs forensics OK: straggler attributed (rank 2, wire.dcn), "
      f"crash postmortem named {report['crashed_ranks'][0]} at step "
      f"{row['last_step']}, armed overhead {100*frac:.3f}% of a "
      f"{step_ms:.1f} ms step")
PY
