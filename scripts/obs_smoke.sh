#!/usr/bin/env bash
# Observability smoke (CI brick for docs/observability.md): run one short
# bench leg with the timeline AND the metrics JSONL sink enabled, then
# assert scripts/obs_report.py joins them into a coherent report —
# nonzero wire bytes, balanced spans, zero stalls, and a
# comm_hidden_fraction that reproduces the bench-reported value within 1%.
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="${OBS_SMOKE_TMP:-$(mktemp -d)}"
mkdir -p "$TMP"
trap '[ -z "${OBS_SMOKE_TMP:-}" ] && rm -rf "$TMP"' EXIT
echo "== obs smoke: artifacts in $TMP ==" >&2

JAX_PLATFORMS=cpu \
HOROVOD_TIMELINE="$TMP/tl.json" \
HOROVOD_METRICS_JSONL="$TMP/metrics.jsonl" \
python bench.py --overlap --platform cpu --cpu-devices 8 \
    --model resnet18 --batch-size 2 --image-size 64 \
    --num-warmup 1 --num-iters 2 --num-batches-per-iter 1 \
    | tail -n 1 > "$TMP/bench.json"

python scripts/obs_report.py --timeline "$TMP/tl.json" \
    --metrics "$TMP/metrics.jsonl" --json "$TMP/report.json"

python - "$TMP" <<'PY'
import json, sys
tmp = sys.argv[1]
report = json.load(open(f"{tmp}/report.json"))
bench = json.load(open(f"{tmp}/bench.json"))

assert report["spans_balanced"], report["span_imbalance"]
assert report["total_spans"] > 0, "no spans recorded"
wb = report["wire_budget"]
assert wb["ici_bytes_per_step_device"] > 0, "zero ICI wire bytes"
assert not report["stalls"] and report["stall_warnings"] == 0, \
    f"unexpected stalls: {report['stalls']}"
got, want = report["comm_hidden_fraction"], bench["comm_hidden_fraction"]
assert abs(got - want) <= 0.01, \
    f"hidden fraction mismatch: report {got} vs bench {want}"
assert bench["metrics_snapshot"]["histograms"].get("step.time_ms", {}) \
    .get("count", 0) > 0, "bench JSON missing the step-latency histogram"
print(f"obs smoke OK: {report['total_spans']} spans, "
      f"ICI {wb['ici_bytes_per_step_device']/1e6:.2f} MB/step, "
      f"hidden fraction {got:.4f} (bench {want:.4f}), 0 stalls")
PY
