#!/bin/bash
# Poll the axon relay; the moment it answers, run the round-5b
# measurement sweep (scripts/tpu_round5b_measurements.sh). The relay is
# external to this container (a tunnel on 127.0.0.1:8083) — nothing
# in-process can revive it, so when it wedges (a SIGTERM mid-remote-
# compile is enough) all we can do is watch for its return and pounce.
set -u
cd "$(dirname "$0")/.." || exit 1
. scripts/measure_lib.sh
LOG=${1:-/tmp/relay_watch.log}
POLL=${RELAY_POLL_SECS:-30}
MAX_ATTEMPTS=${RELAY_MAX_SWEEP_ATTEMPTS:-4}
# Hard stop (epoch seconds). The driver runs the official bench.py at
# round end — a watcher-launched sweep colliding with it would corrupt
# the headline number, so the watcher must be long gone by then.
# Default: 4 h from launch.
DEADLINE=${RELAY_WATCH_DEADLINE:-$(($(date +%s) + 14400))}
attempt=0
echo "$(date -u +%T) watching for relay (deadline $(date -u -d "@$DEADLINE" +%T))..." >>"$LOG"
while :; do
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "$(date -u +%T) deadline reached; exiting so a late relay return can't collide with the driver's round-end bench" >>"$LOG"
    exit 3
  fi
  if relay_up; then
    attempt=$((attempt + 1))
    echo "$(date -u +%T) relay is UP; settling 30s then sweep attempt $attempt/$MAX_ATTEMPTS" >>"$LOG"
    sleep 30
    # MEASURE_RESUME: stamped (.done) legs are skipped, so a mid-sweep
    # relay flap only costs the unmeasured legs — keep watching until a
    # sweep finishes with nothing missed (exit 0), not merely finishes.
    # The attempt cap keeps a leg that fails deterministically (not a
    # relay flap) from re-burning its chip budget forever.
    if MEASURE_RESUME=1 bash scripts/tpu_round5b_measurements.sh >>"$LOG" 2>&1; then
      echo "$(date -u +%T) sweep complete: every leg measured" >>"$LOG"
      exit 0
    fi
    if [ "$attempt" -ge "$MAX_ATTEMPTS" ]; then
      echo "$(date -u +%T) $MAX_ATTEMPTS sweep attempts, legs still missing — a deterministic failure, not a relay flap; see the SKIPPED/rc lines above" >>"$LOG"
      exit 1
    fi
    echo "$(date -u +%T) sweep incomplete (relay flap?); resuming watch" >>"$LOG"
  fi
  sleep "$POLL"
done
