#!/usr/bin/env python
"""Worker for scripts/ckpt_smoke.sh — one phase per invocation.

Phases (argv[1], with artifact dir argv[2] and mesh CxL argv[3]):

``shadow``   uninterrupted run on the 8-device mesh; records the sha256
             of the gathered parameters after every step (truth.json).
``train``    same run with async rank-sharded checkpointing every step;
             hard-kills the process (os._exit) right after SUBMITTING
             the save at KILL_AT — the background writer dies mid-flight,
             so the last commit is whatever landed atomically.
``resume``   runs at a DIFFERENT world (the shell passes a smaller
             mesh): restores the latest committed step, reshards the
             stage-3 param shards and the ZeRO optimizer state to the
             new world, verifies the restored parameters are
             bit-identical to the truth digest, then trains to the end
             asserting every step (including the first resumed one)
             stays bit-identical to the uninterrupted run.

Bitwise comparability across world sizes is by construction: the data is
integer-valued, the SGD hyperparameters are dyadic rationals, and the
run is float64 (JAX_ENABLE_X64, set by the shell) — every
mean/reduce-scatter along the way stays EXACT (the fractional bits grow
a few per step, far under the 53-bit mantissa), so any summation order
gives the same bits and the trajectory is world-independent (the same
trick as the fixed-world determinism of chaos_soak's ckpt fault, pushed
one step further).
"""

import hashlib
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import checkpoint as hvd_ckpt  # noqa: E402

STEPS = 8
KILL_AT = 5
KILL_RC = 17
D = 5
GLOBAL_BATCH = 16


def digest(params):
    h = hashlib.sha256()
    for k in sorted(params):
        h.update(np.ascontiguousarray(np.asarray(params[k])).tobytes())
    return h.hexdigest()


def main():
    phase, tmp, mesh_arg = sys.argv[1], sys.argv[2], sys.argv[3]
    mesh_shape = tuple(int(v) for v in mesh_arg.split("x"))
    hvd.init(mesh_shape=mesh_shape)
    world = hvd.size()
    mesh = hvd.mesh()
    truth_path = os.path.join(tmp, "truth.json")
    ckpt_dir = os.path.join(tmp, "ckpt")

    assert jax.config.jax_enable_x64, "run via scripts/ckpt_smoke.sh"
    rng = np.random.RandomState(0)
    x = rng.randint(-1, 2, size=(GLOBAL_BATCH * STEPS, D)).astype(np.float64)
    y = rng.randint(-1, 2, size=(GLOBAL_BATCH * STEPS, 1)).astype(np.float64)

    params0 = {"w": jnp.zeros((D, 1), jnp.float64),
               "b": jnp.zeros((1,), jnp.float64)}
    tpl = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                       params0)
    tx = hvd.DistributedOptimizer(optax.sgd(0.125, momentum=0.5),
                                  zero_stage=3)

    psh = hvd.zero3_shard_params(params0)
    pspec = hvd.zero3_param_pspecs(psh)
    state = tx.init(params0)
    sspec = hvd.zero_state_pspecs(state)

    def put(tree, spec):
        return jax.device_put(tree, jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec))

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    @jax.jit
    def step(psh, s, xb, yb):
        def spmd(psh, s, xb, yb):
            p = hvd.zero3_gather_params(psh, tpl)
            _, g = hvd.value_and_grad(loss_fn, zero=True)(p, (xb, yb))
            u, ns = tx.update(g, s, psh)
            return optax.apply_updates(psh, u), ns

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(pspec, sspec, hvd.data_pspec(), hvd.data_pspec()),
            out_specs=(pspec, sspec))(psh, s, xb, yb)

    def batch(i):  # 1-based step number
        sl = slice((i - 1) * GLOBAL_BATCH, i * GLOBAL_BATCH)
        return jnp.asarray(x[sl]), jnp.asarray(y[sl])

    def gathered(psh):
        return hvd.zero3_gather_params(jax.device_get(psh), params0)

    start = 1
    if phase == "resume":
        reg = hvd.monitor.metrics()
        mgr = hvd_ckpt.CheckpointManager(ckpt_dir, keep=3)
        latest = mgr.latest_step()
        assert latest is not None and 1 <= latest <= KILL_AT, \
            f"no usable committed step after the kill (latest={latest})"
        manifest, tree = mgr.restore()
        assert manifest.world != world, \
            "resume phase must run at a different world size"
        assert reg.counter("ckpt.restores").value >= 1
        psh = hvd.zero3_reshard_params(tuple(tree["pshards"]), params0,
                                       from_world=manifest.world,
                                       to_world=world)
        state = hvd.zero_reshard_state(tree["opt_state"], params0,
                                       from_world=manifest.world,
                                       to_world=world)
        truth = json.load(open(truth_path))
        got = digest(gathered(psh))
        assert got == truth[str(latest)], \
            (f"restored params at step {latest} are not bit-identical to "
             f"the uninterrupted run: {got} != {truth[str(latest)]}")
        print(f"ckpt smoke: restored step {latest} at world "
              f"{manifest.world} -> {world}, params bit-identical")
        start = latest + 1
    elif phase == "train":
        mgr = hvd_ckpt.CheckpointManager(ckpt_dir, keep=3)
        truth = json.load(open(truth_path))
    else:
        assert phase == "shadow", phase
        mgr, truth = None, {}

    psh, state = put(psh, pspec), put(state, sspec)
    for i in range(start, STEPS + 1):
        xb, yb = batch(i)
        psh, state = step(psh, state, xb, yb)
        d = digest(gathered(psh))
        if phase == "shadow":
            truth[str(i)] = d
        else:
            assert d == truth[str(i)], \
                f"step {i} diverged from the uninterrupted run"
        if mgr is not None:
            mgr.save(i, {"pshards": psh, "opt_state": state})
        if phase == "train" and i == KILL_AT:
            os._exit(KILL_RC)  # writer mid-flight; no drain, no goodbye

    if phase == "shadow":
        with open(truth_path, "w") as f:
            json.dump(truth, f, indent=1)
        print(f"ckpt smoke: recorded {len(truth)}-step truth trajectory "
              f"at world {world}")
    else:  # resume
        assert mgr.wait(60)
        commits = hvd.monitor.metrics().counter("ckpt.commits").value
        assert commits >= 1, "resume phase produced no checkpoint commits"
        mgr.close()
        print(f"ckpt smoke: resumed steps {start}..{STEPS} bit-identical "
              f"at world {world}; {int(commits)} commits this process")


if __name__ == "__main__":
    main()
