# Shared helpers for the TPU measurement scripts (sourced, not run).
# Single home for the relay probe + budgeted-leg runner so a fix (port,
# probe timeout, recovery window) lands once — the copies diverged the
# first time they existed separately.
#
# Contract: caller sets $OUT before using run().
#   relay_up          — 0 iff the axon relay answers (or none configured)
#   run BUDGET NAME CMD... — skip if relay down; else run under `timeout
#                       BUDGET` with output in $OUT/NAME.log; on a 124
#                       timeout, pause 60 s (a kill mid-remote-compile
#                       can wedge the relay; give it a recovery window).
#                       A clean exit stamps $OUT/NAME.done; with
#                       MEASURE_RESUME=1, stamped legs are skipped so a
#                       sweep re-run after a mid-sweep relay flap only
#                       measures what it missed (the watcher sets this).

relay_up() {
  # No relay configured (real TPU VM): treat as up.
  [ -z "${PALLAS_AXON_POOL_IPS:-}" ] && return 0
  python - <<'EOF'
import os, socket, sys
port = int(os.environ.get("HOROVOD_AXON_RELAY_PORT", "8083"))
for ip in os.environ["PALLAS_AXON_POOL_IPS"].split(","):
    try:
        with socket.create_connection((ip.strip(), port), timeout=3):
            sys.exit(0)
    except OSError:
        pass
sys.exit(1)
EOF
}

# Legs that could not produce a measurement this invocation (relay down,
# nonzero exit, timeout, CPU fallback). Callers may `exit $((
# MEASURE_MISSED > 0 ))` so wrappers know a re-run is needed.
MEASURE_MISSED=0

run() {
  # Declared local so legs can't leak state into each other (or into the
  # sourcing script) through these helper variables.
  local budget name rc
  budget=$1; name=$2; shift 2
  if [ "${MEASURE_RESUME:-0}" = 1 ] && [ -e "$OUT/$name.done" ]; then
    echo "--- $name already measured ($OUT/$name.done); resume skips it"
    return
  fi
  if ! relay_up; then
    echo "--- $name SKIPPED (relay down; a CPU fallback would measure nothing)"
    MEASURE_MISSED=$((MEASURE_MISSED + 1))
    return
  fi
  echo "=== $name: $* ==="
  timeout "$budget" "$@" >"$OUT/$name.log" 2>&1
  rc=$?
  tail -3 "$OUT/$name.log"
  echo "--- $name rc=$rc"
  # A CPU fallback (relay died between our probe and the leg's own)
  # exits 0 but measured nothing — don't stamp it done. bench.py prints
  # the "falling back" banner; every leg's JSON line carries platform.
  if [ "$rc" = 0 ] && \
     ! grep -qE 'falling back to CPU|"platform": "cpu"' "$OUT/$name.log"; then
    : >"$OUT/$name.done"
  else
    MEASURE_MISSED=$((MEASURE_MISSED + 1))
  fi
  if [ "$rc" = 124 ]; then
    # The kill may have wedged the client/relay; give it a recovery
    # window before the next leg's probe burns its budget.
    echo "--- $name timed out; 60 s relay recovery pause"
    sleep 60
  fi
}

# run_if_done PRIOR BUDGET NAME CMD... — like run(), but only when leg
# PRIOR is stamped done. For cache-hit legs: re-running a "hit" leg
# against the empty cache its failed predecessor left would do the full
# first-use sweep under a budget sized for a hit (timeout -> possible
# relay wedge).
run_if_done() {
  local prior
  prior=$1; shift
  if [ ! -e "$OUT/$prior.done" ]; then
    echo "--- $2 SKIPPED (prerequisite $prior not measured)"
    MEASURE_MISSED=$((MEASURE_MISSED + 1))
    return
  fi
  run "$@"
}
