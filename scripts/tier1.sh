#!/usr/bin/env bash
# Tier-1 verify — the EXACT command from ROADMAP.md ("Tier-1 verify:"),
# wrapped so builders and the re-anchor reviewer run the identical check
# (same pipefail discipline, same DOTS_PASSED echo, same exit code).
#
# Usage: scripts/tier1.sh            (from the repo root)
# Log:   /tmp/_t1.log
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
