#!/bin/sh
# The production soak gauntlet (docs/robustness.md) on an 8-device CPU
# mesh: reference run, chaos gauntlet (preempt + crash + stall + flap +
# resize) with a live serve trace and the degraded-link replan leg, all
# gated from the soak-report JSON. Exit code = number of failed gates.
#
#   scripts/soak.sh [--report out.json] [extra soak.py args]
set -e
cd "$(dirname "$0")/.."
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
JAX_PLATFORMS=cpu \
exec python scripts/soak.py "$@"
