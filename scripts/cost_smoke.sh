#!/usr/bin/env bash
# Cost-model smoke (CI brick for docs/cost-model.md): calibrate the link
# classes on the 8-device virtual CPU mesh, persist + reload the
# geometry-keyed calibration, enumerate + price the legal plan space
# (shortlist nonempty, ranked ascending), and lower the top candidate —
# it must match the unpriced lowering BIT-identically (pricing ranks
# plans; it never changes what they compute). Runtime ~1 min.
#
# Usage: scripts/cost_smoke.sh
#   COST_SMOKE_TMP=/path scripts/cost_smoke.sh   # keep artifacts
set -euo pipefail
cd "$(dirname "$0")/.."

TMP="${COST_SMOKE_TMP:-$(mktemp -d)}"
mkdir -p "$TMP"
trap '[ -z "${COST_SMOKE_TMP:-}" ] && rm -rf "$TMP"' EXIT
echo "== cost smoke: calibration store in $TMP ==" >&2

JAX_PLATFORMS=cpu \
HOROVOD_CALIBRATION_CACHE="$TMP/link_calibration.json" \
HOROVOD_AUTOTUNE_CACHE="$TMP/autotune_cache.json" \
python scripts/_cost_smoke.py

echo "COST SMOKE: OK" >&2
