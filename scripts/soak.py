#!/usr/bin/env python
"""The production soak gauntlet (docs/robustness.md, soak contract).

One run drives the whole self-healing vertical at once:

* **Reference leg** — an uninterrupted durable elastic training run
  (tests/soak_worker.py) on a fixed world; its per-batch trajectory and
  step cadence are the yardstick every later gate measures against.
* **Gauntlet leg** — the same run under a scripted chaos plan: a spot
  **preemption** (the chaos ``preempt`` action SIGTERMs a worker
  mid-collective; its supervisor must land a deadline-met priority
  snapshot before the flight dump re-delivers the signal), a worker
  **crash**, a discovery **flap**, a short **stall**, and a world
  **resize** (the discovery script grows mid-run; the preempted host
  re-enters through the health-gated readmission path after its
  blacklist cooldown). Runs on a background thread.
* **Serve leg** — a live continuous-batching generation trace
  (ReplicaSet + Poisson arrivals, mid-trace resize down/up) running in
  the soak process WHILE the gauntlet is under fire. Zero dropped
  requests is the bar.
* **Replan leg** — an in-process training loop whose eager collective
  is chaos-``delay``ed on the DCN hop: the straggler link-health latch
  must flip, the supervisor must re-price the shortlist under the
  EWMA-derated cost model and hot-swap the step to the quantized wire,
  and when the injected delay expires the latch must clear and the
  swap revert.

Everything lands in one soak-report JSON (--report), and the gates —
loss trajectory vs reference, step time, serve p99 + zero drops,
checkpoint commit cadence, monotone counters, >=1 deadline-met priority
snapshot, >=1 reverted replan — are asserted from that report; exit
code is the number of failed gates. ``--smoke`` is the CI shape: one
preemption + one flap + one resize, training legs only
(scripts/soak_smoke.sh; the full gauntlet is scripts/soak.sh).
"""

import argparse
import glob
import json
import math
import os
import shlex
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = os.path.join(REPO, "tests", "soak_worker.py")

TRAJECTORY_TOL = 1e-4  # |gauntlet - reference| per logged batch point


def log(msg):
    print(f"[soak] {msg}", flush=True)


def _read_log(path):
    if not os.path.exists(path):
        return []
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    records.append(json.loads(line))
                except ValueError:
                    pass  # a torn line from a preempted writer
    return records


def _write_discovery(script, hosts):
    """(Re)write the discovery script atomically — a rewrite mid-run IS
    the world-resize event."""
    tmp = script + ".tmp"
    with open(tmp, "w") as f:
        f.write("#!/bin/sh\n")
        for host, slots in hosts:
            f.write(f"echo {host}:{slots}\n")
    os.chmod(tmp, 0o755)
    os.replace(tmp, script)


def _step_intervals(records):
    """Per-identity deltas between consecutive batch log times (the
    observable step cadence; recovery gaps ride the tail percentiles)."""
    by_ident = {}
    for r in records:
        if "batch" in r and "t" in r:
            by_ident.setdefault(r["identity"], []).append(
                (r["batch"], r["t"]))
    deltas = []
    for pts in by_ident.values():
        pts.sort()
        deltas.extend(t1 - t0 for (b0, t0), (b1, t1)
                      in zip(pts, pts[1:]) if b1 == b0 + 1)
    return sorted(deltas)


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
    return sorted_vals[i]


# ---------------------------------------------------------------------------
# Training legs (reference + gauntlet) — subprocess elastic workers.
# ---------------------------------------------------------------------------

def run_training_leg(workdir, label, *, batches, batch_sleep, hosts,
                     min_np, max_np, worker_plans=None, flight_dir=None,
                     resize_to=None, resize_at_batch=None,
                     blacklist_cooldown=0.0, join_timeout=300):
    """One elastic incarnation chain; returns the leg's evidence dict."""
    from horovod_tpu import resilience
    from horovod_tpu.checkpoint import layout
    from horovod_tpu.elastic import constants
    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner import safe_shell_exec

    constants.DISCOVER_HOSTS_FREQUENCY_SECS = 0.25
    script = os.path.join(workdir, f"discover_{label}.sh")
    _write_discovery(script, hosts)
    log_file = os.path.join(workdir, f"{label}.jsonl")
    ckpt_dir = os.path.join(workdir, f"ckpt_{label}")

    driver = ElasticDriver(
        HostDiscoveryScript(script, 1), min_np=min_np, max_np=max_np,
        controller_addr_override="127.0.0.1",
        blacklist_cooldown_secs=(blacklist_cooldown or None))
    # The supervisor on the DRIVER side owns the readmission gate: a
    # cooled-down host re-enters only through the probe.
    sup = resilience.Supervisor(driver=driver).attach()

    def _exec(slot, world_id):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "PYTHONPATH": REPO,
            "HOROVOD_HOSTNAME": slot.hostname,
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_ELASTIC_DRIVER_ADDR": "127.0.0.1",
            "HOROVOD_ELASTIC_DRIVER_PORT": str(driver.service_port),
            "HOROVOD_ELASTIC_DRIVER_KEY": driver.key.hex(),
            "HOROVOD_START_TIMEOUT": "30",
        })
        if flight_dir:
            env["HOROVOD_FLIGHT_RECORDER_DIR"] = flight_dir
        # Chaos plans are keyed by world incarnation: a restarted worker
        # process re-reads the env with fresh rule counters, so an
        # unconditioned plan would re-fire the same fault in every
        # world — the gauntlet wants each fault to land exactly once.
        if worker_plans is not None:
            plan = worker_plans.get(world_id)
            if plan:
                env.update(plan.to_env())
        cmd = " ".join(shlex.quote(c) for c in [
            sys.executable, WORKER, "--log-file", log_file,
            "--batches", str(batches), "--batch-sleep", str(batch_sleep),
            "--ckpt-dir", ckpt_dir])
        return safe_shell_exec.execute(cmd, env=env)

    commit_samples = []  # monotone commit-cadence evidence
    resized = threading.Event()

    def _monitor():
        while not done_evt.wait(0.5):
            steps = layout.list_steps(ckpt_dir)
            commit_samples.append(
                {"t": time.time(), "latest": steps[-1] if steps else 0})
            if (resize_to is not None and not resized.is_set()):
                recs = _read_log(log_file)
                top = max((r.get("batch", 0) for r in recs), default=0)
                if top >= (resize_at_batch or batches // 3):
                    _write_discovery(script, resize_to)
                    resized.set()
                    log(f"{label}: discovery resized to {resize_to} "
                        f"at batch {top}")

    done_evt = threading.Event()
    mon = threading.Thread(target=_monitor, daemon=True)
    ok = False
    try:
        driver.start(_exec)
        mon.start()
        ok = driver.join(timeout=join_timeout)
    finally:
        done_evt.set()
        driver.stop()
        driver.shutdown_service()
        sup.detach()
        mon.join(timeout=5)

    records = _read_log(log_file)
    intervals = _step_intervals(records)
    return {
        "ok": bool(ok),
        "label": label,
        "records": records,
        "done": [r for r in records if r.get("done")],
        "world_id": driver.world_id,
        "committed_steps": layout.list_steps(ckpt_dir),
        "commit_samples": commit_samples,
        "resized": resized.is_set() if resize_to is not None else None,
        "step_p50_s": _pct(intervals, 0.5),
        "step_p90_s": _pct(intervals, 0.9),
        "supervisor": sup.report(),
        "flight_dir": flight_dir,
        "ckpt_dir": ckpt_dir,
    }


def trajectory_by_batch(records):
    traj = {}
    for r in records:
        if "batch" in r:
            traj.setdefault(int(r["batch"]), set()).add(
                float(r["weights"]))
    return traj


def flight_preempt_events(flight_dir):
    """RESILIENCE:PREEMPT events across every dump in the dir — the
    preempted worker's black box is the snapshot's proof."""
    events = []
    for path in sorted(glob.glob(os.path.join(flight_dir or "",
                                              "flight_*.json"))):
        try:
            with open(path) as f:
                dump = json.load(f)
        except Exception:
            continue
        for ev in dump.get("events", []):
            if ev.get("name") == "RESILIENCE:PREEMPT":
                events.append({"dump": os.path.basename(path),
                               "reason": dump.get("reason"),
                               **(ev.get("args") or {})})
    return events


# ---------------------------------------------------------------------------
# Serve leg — a live generation trace in the soak process.
# ---------------------------------------------------------------------------

def run_serve_leg(requests=36, rate=30.0, replicas=2):
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu.models import GPT, gpt_tiny
    from horovod_tpu.serve import PageConfig, PoissonTrace, ReplicaSet

    devices = jax.devices()
    hvd.shutdown()
    hvd.init(devices=devices)
    cfg = gpt_tiny(num_heads=8, dtype=jnp.float32)
    params = GPT(cfg).init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))["params"]
    page_size, max_slots = 16, 8
    p_lo, p_hi, n_lo, n_hi = 8, 16, 8, 16
    pages_per_slot = -(-(p_hi + n_hi + 1) // page_size)
    num_pages = 1 + max(pages_per_slot,
                        int(0.75 * max_slots * pages_per_slot))
    pc = PageConfig(num_pages=num_pages, page_size=page_size,
                    max_slots=max_slots, pages_per_slot=pages_per_slot,
                    num_layers=cfg.num_layers, num_heads=cfg.num_heads,
                    head_dim=cfg.d_model // cfg.num_heads)
    trace = PoissonTrace(rate=rate, num_requests=requests, seed=12345,
                         prompt_len=(p_lo, p_hi),
                         max_new_tokens=(n_lo, n_hi),
                         vocab_size=cfg.vocab_size, eos_id=1)
    rset = ReplicaSet(cfg, params, pc, devices=devices,
                      n_replicas=replicas, eos_id=1)
    for req in trace:
        rset.submit(req)
    total = len(trace)
    resize_down_at = max(1, total // 3)
    resize_up_at = max(2, (2 * total) // 3)
    did_down = did_up = False
    t0 = time.monotonic()
    steps = 0
    while rset.has_work:
        now = time.monotonic() - t0
        done = (len(rset.stats.completed)
                + sum(len(e.stats.completed) for e in rset.engines))
        if not did_down and done >= resize_down_at and replicas > 1:
            rset.resize(max(1, replicas // 2), now)
            did_down = True
        if did_down and not did_up and done >= resize_up_at \
                and replicas > 1:
            rset.resize(replicas, now)
            did_up = True
        if rset.step_all(now) == 0:
            time.sleep(1e-3)
        steps += 1
        if steps > 200_000:
            break
    wall = time.monotonic() - t0
    stats = rset.stats
    for eng in rset.engines:
        stats.merge(eng.stats)
    completed = len(stats.completed)
    lat = stats.latency_percentiles()
    return {
        "requests": total,
        "completed": completed,
        "dropped": total - completed,
        "wall_s": round(wall, 3),
        "latency_p50_ms": round((lat["p50"] or 0) * 1e3, 2),
        "latency_p99_ms": round((lat["p99"] or 0) * 1e3, 2),
        "resizes": len(rset.resize_events),
        "preemptions": stats.preemptions,
    }


# ---------------------------------------------------------------------------
# Replan leg — chaos delay on the DCN hop → degraded → quantized swap →
# recovery → swap-back, all in-process at real step boundaries.
# ---------------------------------------------------------------------------

def run_replan_leg(steps=28, delay_after=6, delay_count=12,
                   delay_secs=0.02):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import chaos, resilience
    from horovod_tpu.monitor.straggler import StragglerDetector
    from horovod_tpu.plan import cost as _cost

    devices = jax.devices()
    hvd.shutdown()
    hvd.init(devices=devices, mesh_shape=(2, len(devices) // 2))
    mesh_shape = (2, len(devices) // 2)

    # The injected fault: the step's eager collective slows down for a
    # window of `delay_count` invocations — a transient congested link.
    chaos.configure(chaos.FaultPlan(seed=7).add(
        "collective.eager", "delay", secs=delay_secs,
        after=delay_after, max_count=delay_count))

    det = StragglerDetector(link_drift_gate=1.5, patience=3)
    sup = resilience.Supervisor(straggler=det)
    payload = np.zeros((64, 1024), np.float32)  # 256 KiB
    nbytes = payload.nbytes
    predicted = _cost.predict_hop_ms("dcn", nbytes)

    quantized = False
    baseline_ms = None
    probe = jnp.zeros((64,), jnp.float32)
    events = []
    for step in range(steps):
        x = jnp.asarray(payload)
        hvd.allreduce(x, name=f"replan.step.{step}",
                      quantized=quantized).block_until_ready()
        # The link-health signal comes from a small fixed-wire probe
        # collective, not the step itself: after the hot swap the step
        # runs a *different* (quantized) wire whose cost is not
        # comparable to the pre-swap baseline, but the probe always
        # measures the same hop the same way — so its ratio falls back
        # to ~1 when the congestion clears and the latch can release.
        t0 = time.perf_counter()
        hvd.allreduce(probe, name="replan.probe").block_until_ready()
        measured_ms = (time.perf_counter() - t0) * 1e3
        if baseline_ms is None:
            baseline_ms = measured_ms  # first healthy probe calibrates
        elif measured_ms < 1.5 * baseline_ms:
            # Keep the healthy baseline honest while un-delayed.
            baseline_ms = 0.5 * baseline_ms + 0.5 * measured_ms
        # Score the DCN hop as observed-over-baseline, scaled onto the
        # model's prediction: the CPU mesh's absolute wire time is not
        # the model's (no recalibration happens here); the RATIO of a
        # congested probe to the healthy cadence is what the latch gates.
        det.observe_wire("dcn", nbytes,
                         predicted * measured_ms / baseline_ms)
        directive = sup.maybe_replan(nbytes, mesh_shape=mesh_shape,
                                     step=step)
        if directive and "swap" in directive:
            quantized = True  # the hot swap, at this step boundary
            events.append({"step": step, "event": "swap",
                           "plan": directive["decision"].plan_after})
            log(f"replan: step {step} swapped to quantized wire")
        elif directive and directive.get("revert"):
            quantized = False  # the recovery swap-back
            events.append({"step": step, "event": "revert"})
            log(f"replan: step {step} reverted to the original wire")
    report = sup.report()
    chaos.reset()
    return {"steps": steps, "events": events,
            "replans": report["replans"],
            "swapped": any(e["event"] == "swap" for e in events),
            "reverted": any(e["event"] == "revert" for e in events)}


# ---------------------------------------------------------------------------
# Gates + report.
# ---------------------------------------------------------------------------

def check_trajectory(ref_records, gauntlet_records):
    ref = trajectory_by_batch(ref_records)
    gnt = trajectory_by_batch(gauntlet_records)
    worst = 0.0
    bad = None
    for batch, values in gnt.items():
        want = ref.get(batch)
        if not want:
            continue
        w0 = next(iter(want))
        for v in values:
            err = abs(v - w0)
            if err > worst:
                worst, bad = err, batch
    return {"max_abs_err": worst, "worst_batch": bad,
            "batches_compared": len(set(gnt) & set(ref)),
            "within_tol": worst <= TRAJECTORY_TOL}


def run(args):
    from horovod_tpu import chaos
    from horovod_tpu.common import counters

    chaos.reset()
    counters.reset_all()
    workdir = args.workdir or tempfile.mkdtemp(prefix="soak_")
    os.makedirs(workdir, exist_ok=True)
    flight_dir = os.path.join(workdir, "flight")
    report = {"smoke": args.smoke, "workdir": workdir, "gates": {}}
    t_start = time.monotonic()

    # ---- reference leg (no chaos anywhere) ----------------------------
    log("reference leg: uninterrupted durable run")
    ref = run_training_leg(
        workdir, "ref", batches=args.batches,
        batch_sleep=args.batch_sleep,
        hosts=[("hostA", 2), ("hostB", 1)], min_np=3, max_np=3,
        join_timeout=args.leg_timeout)
    report["reference"] = {k: ref[k] for k in
                           ("ok", "world_id", "committed_steps",
                            "step_p50_s", "step_p90_s")}
    report["reference"]["done"] = len(ref["done"])

    # ---- gauntlet leg -------------------------------------------------
    # Worker-side chaos (ships via env, keyed by world incarnation so
    # each fault lands exactly once): world 0 takes the spot preemption;
    # the rebuilt world 1 takes a short stall and then a hard crash;
    # world 2+ runs clean through the resize and readmissions.
    worker_plans = {0: chaos.FaultPlan(seed=args.seed).add(
        "collective.eager", "preempt", where="hostB:0",
        after=3, max_count=1)}
    if not args.smoke:
        worker_plans[1] = (
            chaos.FaultPlan(seed=args.seed)
            .add("collective.eager", "stall", where="hostA:0",
                 after=2, secs=1.0, max_count=1)
            .add("collective.eager", "crash", where="hostA:1",
                 after=4, max_count=1, exit_code=1))
    # Driver-side chaos (this process): one discovery flap.
    chaos.configure(chaos.FaultPlan(seed=args.seed).add(
        "discovery.update", "flap", after=8, max_count=1))

    log("gauntlet leg: preempt + flap"
        + ("" if args.smoke else " + crash + stall") + " + resize")
    counters_before = dict(counters.counters(total=True))
    gauntlet_kwargs = dict(
        batches=args.batches, batch_sleep=args.batch_sleep,
        hosts=[("hostA", 2), ("hostB", 1)], min_np=2, max_np=4,
        worker_plans=worker_plans, flight_dir=flight_dir,
        resize_to=[("hostA", 2), ("hostB", 1), ("hostC", 1)],
        resize_at_batch=max(2, args.batches // 3),
        blacklist_cooldown=4.0, join_timeout=args.leg_timeout)

    if args.smoke:
        gauntlet = run_training_leg(workdir, "gauntlet",
                                    **gauntlet_kwargs)
        serve = replan = None
    else:
        # The serve trace and the replan loop run LIVE in this process
        # while the gauntlet burns in the background thread.
        result = {}

        def _gauntlet_thread():
            try:
                result["gauntlet"] = run_training_leg(
                    workdir, "gauntlet", **gauntlet_kwargs)
            except Exception as e:
                result["error"] = repr(e)

        th = threading.Thread(target=_gauntlet_thread, daemon=True)
        th.start()
        log("serve leg: live generation trace under the gauntlet")
        serve = run_serve_leg(requests=args.serve_requests)
        log(f"serve leg: {serve['completed']}/{serve['requests']} "
            f"completed, p99 {serve['latency_p99_ms']} ms, "
            f"{serve['dropped']} dropped")
        log("replan leg: degraded DCN hop -> quantized swap -> revert")
        replan = run_replan_leg()
        th.join(timeout=args.leg_timeout + 60)
        if "gauntlet" not in result:
            raise SystemExit(
                f"gauntlet leg never finished: "
                f"{result.get('error', 'timeout')}")
        gauntlet = result["gauntlet"]

    counters_after = dict(counters.counters(total=True))
    preempts = flight_preempt_events(flight_dir)
    trajectory = check_trajectory(ref["records"], gauntlet["records"])

    report["gauntlet"] = {k: gauntlet[k] for k in
                          ("ok", "world_id", "committed_steps",
                           "resized", "step_p50_s", "step_p90_s",
                           "supervisor")}
    report["gauntlet"]["done"] = len(gauntlet["done"])
    report["gauntlet"]["commit_samples"] = gauntlet["commit_samples"]
    report["trajectory"] = trajectory
    report["preempt_events"] = preempts
    report["serve"] = serve
    report["replan"] = (None if replan is None else
                        {k: replan[k] for k in
                         ("events", "replans", "swapped", "reverted")})
    report["counters"] = {"before_gauntlet": counters_before,
                          "after": counters_after}

    # ---- gates --------------------------------------------------------
    gates = report["gates"]
    gates["reference_ok"] = {
        "pass": ref["ok"] and len(ref["done"]) == 3,
        "detail": f"ok={ref['ok']} done={len(ref['done'])}"}
    gates["gauntlet_recovered"] = {
        "pass": (gauntlet["ok"] and gauntlet["world_id"] >= 1
                 and len(gauntlet["done"]) >= 2
                 and bool(gauntlet["committed_steps"])
                 and gauntlet["committed_steps"][-1] == args.batches),
        "detail": (f"ok={gauntlet['ok']} world_id="
                   f"{gauntlet['world_id']} done="
                   f"{len(gauntlet['done'])} committed="
                   f"{gauntlet['committed_steps']}")}
    gates["resize_happened"] = {
        "pass": bool(gauntlet["resized"]),
        "detail": f"resized={gauntlet['resized']}"}
    gates["loss_trajectory"] = {
        "pass": (trajectory["within_tol"]
                 and trajectory["batches_compared"] > 0),
        "detail": (f"max|err|={trajectory['max_abs_err']:.2e} over "
                   f"{trajectory['batches_compared']} batches "
                   f"(tol {TRAJECTORY_TOL:g})")}
    ref_p50 = ref["step_p50_s"] or args.batch_sleep
    gates["step_time"] = {
        "pass": (gauntlet["step_p50_s"] is not None
                 and gauntlet["step_p50_s"] <= 10 * ref_p50),
        "detail": (f"gauntlet p50 {gauntlet['step_p50_s']} s vs "
                   f"reference p50 {ref_p50} s (gate 10x)")}
    samples = [s["latest"] for s in gauntlet["commit_samples"]]
    gates["commit_cadence"] = {
        "pass": (len(gauntlet["committed_steps"]) >= 2
                 and all(a <= b for a, b in zip(samples, samples[1:]))),
        "detail": (f"{len(gauntlet['committed_steps'])} live commits, "
                   f"latest-step samples monotone="
                   f"{all(a <= b for a, b in zip(samples, samples[1:]))}")}
    met = [e for e in preempts if e.get("deadline_met")]
    gates["priority_snapshot"] = {
        "pass": len(met) >= 1,
        "detail": (f"{len(met)} deadline-met of {len(preempts)} "
                   f"RESILIENCE:PREEMPT events in flight dumps")}
    flap_seen = (counters_after.get("chaos.flap", 0)
                 - counters_before.get("chaos.flap", 0))
    gates["flap_injected"] = {
        "pass": flap_seen >= 1,
        "detail": f"chaos.flap delta={flap_seen}"}
    monotone = all(counters_after.get(k, 0) >= v
                   for k, v in counters_before.items())
    gates["counters_monotone"] = {
        "pass": monotone,
        "detail": "all driver-process counters non-decreasing"}
    if not args.smoke:
        gates["serve_no_drops"] = {
            "pass": (serve["dropped"] == 0
                     and serve["latency_p99_ms"] > 0),
            "detail": (f"{serve['dropped']} dropped, p99 "
                       f"{serve['latency_p99_ms']} ms, "
                       f"{serve['resizes']} resizes")}
        gates["replan_swap_back"] = {
            "pass": (replan["swapped"] and replan["reverted"]
                     and any(r["reverted"]
                             for r in replan["replans"])),
            "detail": (f"swapped={replan['swapped']} "
                       f"reverted={replan['reverted']} "
                       f"replans={len(replan['replans'])}")}

    report["wall_s"] = round(time.monotonic() - t_start, 1)
    failed = [name for name, g in gates.items() if not g["pass"]]
    report["ok"] = not failed
    report_path = args.report or os.path.join(workdir,
                                              "soak_report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, default=str)
    log(f"report: {report_path}")
    for name, g in gates.items():
        log(f"gate {name}: {'PASS' if g['pass'] else 'FAIL'} "
            f"({g['detail']})")
    if failed:
        log(f"SOAK FAILED: {failed}")
    else:
        log(f"SOAK PASSED ({report['wall_s']}s)")
    return len(failed)


def main():
    parser = argparse.ArgumentParser(
        description="the production soak gauntlet (docs/robustness.md)")
    parser.add_argument("--batches", type=int, default=14)
    parser.add_argument("--batch-sleep", type=float, default=0.15)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--serve-requests", type=int, default=36)
    parser.add_argument("--leg-timeout", type=float, default=300.0)
    parser.add_argument("--workdir", default=None)
    parser.add_argument("--report", default=None,
                        help="soak-report JSON path (default: in the "
                             "workdir)")
    parser.add_argument("--smoke", action="store_true",
                        help="CI shape: one preemption + one flap + one "
                             "resize, training legs only")
    args = parser.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(min(run(args), 125))


if __name__ == "__main__":
    main()
