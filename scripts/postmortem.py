#!/usr/bin/env python
"""Join all ranks' flight-record dumps into one crash postmortem.

Usage:
    scripts/postmortem.py --dir FLIGHT_DIR [--json OUT.json]

Every process of a run armed with ``HOROVOD_FLIGHT_RECORDER_DIR`` leaves
``flight_*.json`` dumps there on its crash paths (unhandled exception,
SIGTERM, chaos ``crash``, StallInspector escalation, elastic
reset/abandon — monitor/flight.py). This tool verifies each dump's crc32
(torn files are reported, never trusted), groups them by rank, and
answers the three questions an on-call asks first
(docs/observability.md):

* **Who died, and of what?** Per-rank last dump reason + last recorded
  event; crash-class reasons (``chaos.crash``, ``exception``,
  ``sigterm``, ``stall.escalation``) name the crashing rank(s).
* **Where did the job diverge?** The last step/commit every rank
  reached; the *last common step* is the highest step all ranks
  completed, the *divergence step* the first step some rank is missing.
* **What was in flight?** Each rank's in-flight collectives and stalled
  tensors at dump time, plus the straggler-detection history leading up
  to the crash (was the dead rank dragging before it died?).

Exit 0 on success, 2 when the directory holds no parseable dumps.
``--json`` writes the machine-readable report (what the chaos tests and
``scripts/obs_smoke.sh`` assert on).
"""

import argparse
import glob
import json
import os
import sys
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Dump reasons that mean "this rank died here" (vs a survivor's
#: reset/abandon bookkeeping dump).
CRASH_REASONS = ("chaos.crash", "exception", "sigterm",
                 "stall.escalation")


def load_dumps(directory):
    """(dumps, corrupt) — parsed dumps with verified event crc32s, and
    the [(path, why)] list of files that failed."""
    dumps, corrupt = [], []
    for path in sorted(glob.glob(os.path.join(directory, "flight_*.json"))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            corrupt.append((path, f"unreadable: {e}"))
            continue
        want = d.get("events_crc32")
        payload = json.dumps(d.get("events", []), sort_keys=True).encode()
        got = f"crc32:{zlib.crc32(payload) & 0xFFFFFFFF:08x}"
        if want != got:
            corrupt.append((path, f"checksum mismatch: {want} != {got}"))
            continue
        d["_path"] = path
        dumps.append(d)
    return dumps, corrupt


def _rank_key(dump):
    """Stable per-process key: the rank when known, else the
    host:local_rank identity, else driver/pid."""
    ident = dump.get("identity", {})
    rank = ident.get("rank", -1)
    if isinstance(rank, int) and rank >= 0:
        return f"rank{rank}"
    host = ident.get("hostname") or ""
    lr = ident.get("local_rank") or ""
    if host:
        return f"{host}:{lr}"
    return ident.get("role") or f"pid{ident.get('pid', '?')}"


def _last_step(events):
    """Highest completed step/commit mark in an event list (None when
    the rank never marked one)."""
    last = None
    for ev in events:
        args = ev.get("args") or {}
        n = None
        if ev.get("name") == "FLIGHT:STEP":
            n = args.get("step")
        elif ev.get("name") == "FLIGHT:COMMIT":
            n = args.get("batch")
        if n is not None:
            last = n if last is None else max(last, n)
    return last


def _summarize_rank(dumps):
    """One report row per process key, from its LATEST dump (earlier
    dumps of the same process still contribute step marks)."""
    latest = max(dumps, key=lambda d: d.get("ts", 0.0))
    events = latest.get("events", [])
    last_ev = events[-1] if events else None
    steps = [s for s in (_last_step(d.get("events", [])) for d in dumps)
             if s is not None]
    faults = {}
    for ev in events:
        name = str(ev.get("name", ""))
        if name.startswith("FAULT:"):
            faults[name[len("FAULT:"):]] = \
                faults.get(name[len("FAULT:"):], 0) + 1
    return {
        "identity": latest.get("identity", {}),
        "dumps": len(dumps),
        "path": latest.get("_path"),
        "reason": latest.get("reason"),
        "ts": latest.get("ts"),
        "crashed": latest.get("reason") in CRASH_REASONS,
        "last_step": max(steps) if steps else None,
        "events": len(events),
        "last_event": ({"name": last_ev.get("name"),
                        "wall": last_ev.get("wall"),
                        "args": last_ev.get("args")}
                       if last_ev else None),
        "in_flight": latest.get("in_flight", []),
        "stalled": latest.get("stalled", []),
        "faults": faults,
        "straggler": latest.get("straggler", []),
        "expert_load": latest.get("expert_load") or {},
        "serve_cache": latest.get("serve_cache") or {},
        "extra": latest.get("extra"),
    }


def build_report(directory):
    dumps, corrupt = load_dumps(directory)
    by_key = {}
    for d in dumps:
        by_key.setdefault(_rank_key(d), []).append(d)
    ranks = {k: _summarize_rank(v) for k, v in sorted(by_key.items())}

    worker_rows = {k: r for k, r in ranks.items()
                   if r["identity"].get("role") != "driver"}
    steps = {k: r["last_step"] for k, r in worker_rows.items()
             if r["last_step"] is not None}
    last_common = min(steps.values()) if steps else None
    max_step = max(steps.values()) if steps else None
    crashed = sorted(k for k, r in ranks.items() if r["crashed"])
    # Divergence: the first step NOT completed by every rank — set when
    # some rank got further than another, or when a crash-class dump
    # exists (the crashed rank died inside step last_common + 1 even if
    # its peers rolled back to the same commit).
    divergence = (last_common + 1
                  if last_common is not None
                  and (crashed or (max_step is not None
                                   and max_step > last_common))
                  else None)
    laggards = []
    if divergence is not None:
        laggards = sorted(k for k, s in steps.items() if s < max_step)
        if not laggards:
            laggards = [k for k in crashed if k in worker_rows]
    straggler_history = []
    for r in ranks.values():
        straggler_history.extend(r["straggler"])
    straggler_history.sort(key=lambda d: d.get("ts", 0.0))
    # Per-expert load (docs/moe.md): merge every rank's expert_load so
    # the postmortem can NAME the hot expert a skewed run died under.
    expert_load = {}
    for r in ranks.values():
        for e, tokens in (r.get("expert_load") or {}).items():
            expert_load[e] = expert_load.get(e, 0.0) + float(tokens)
    hot_expert = None
    if expert_load:
        total = sum(expert_load.values())
        if total > 0:
            e, tokens = max(expert_load.items(), key=lambda kv: kv[1])
            hot_expert = {"expert": e, "tokens": tokens,
                          "share": round(tokens / total, 4)}
    # Disaggregated-serving view (docs/serving.md): merge every rank's
    # serve_cache snapshot (scalars take the max — each rank reports its
    # own fleet totals — and the per-replica stall map folds by sum) so
    # the postmortem can NAME the replica that idled on a migration.
    serve_cache = {}
    for r in ranks.values():
        for key, val in (r.get("serve_cache") or {}).items():
            if isinstance(val, dict):
                bucket = serve_cache.setdefault(key, {})
                for sub, x in val.items():
                    bucket[sub] = bucket.get(sub, 0.0) + float(x)
            else:
                serve_cache[key] = max(
                    float(val), float(serve_cache.get(key, 0.0)))
    stalled_replica = None
    stall_by = serve_cache.get("stall_steps_by_replica") or {}
    if stall_by:
        name, steps_stalled = max(stall_by.items(), key=lambda kv: kv[1])
        if steps_stalled > 0:
            stalled_replica = {"replica": name, "stall_steps": steps_stalled}
    return {
        "directory": os.path.abspath(directory),
        "dumps": len(dumps),
        "corrupt": [{"path": p, "error": e} for p, e in corrupt],
        "ranks": ranks,
        "last_common_step": last_common,
        "max_step": max_step,
        "divergence_step": divergence,
        "crashed_ranks": crashed,
        "diverged_ranks": laggards,
        "straggler_history": straggler_history,
        "expert_load": expert_load,
        "hot_expert": hot_expert,
        "serve_cache": serve_cache,
        "migration_stalled_replica": stalled_replica,
    }


def print_report(r):
    w = print
    w("== flight-record postmortem ==")
    w(f"directory: {r['directory']} ({r['dumps']} dump(s), "
      f"{len(r['corrupt'])} corrupt)")
    for c in r["corrupt"]:
        w(f"  CORRUPT {c['path']}: {c['error']}")
    w("")
    w("-- per-rank summary --")
    for key, row in r["ranks"].items():
        mark = " <-- CRASHED" if row["crashed"] else ""
        step = row["last_step"] if row["last_step"] is not None else "?"
        last = row["last_event"]["name"] if row["last_event"] else "(none)"
        w(f"  {key:<14} reason={row['reason']:<16} last_step={step:<6} "
          f"events={row['events']:<5} last_event={last}{mark}")
        if row["in_flight"]:
            w(f"  {'':<14} in flight: {', '.join(row['in_flight'])}")
        for s in row["stalled"]:
            w(f"  {'':<14} stalled: {s.get('name')} "
              f"({s.get('elapsed_secs', 0):.1f}s)")
    w("")
    w("-- verdict --")
    if r["crashed_ranks"]:
        w(f"  crashing rank(s): {', '.join(r['crashed_ranks'])}")
    else:
        w("  no crash-class dump found (resets/abandons only)")
    lc = r["last_common_step"]
    w(f"  last common step: {lc if lc is not None else 'unknown'}")
    if r["divergence_step"] is not None:
        w(f"  divergence at step {r['divergence_step']}: "
          f"{', '.join(r['diverged_ranks'])} never completed it "
          f"(furthest rank reached {r['max_step']})")
    if r.get("hot_expert"):
        he = r["hot_expert"]
        w(f"  hot expert: expert {he['expert']} carried "
          f"{he['share']:.0%} of the MoE load "
          f"({he['tokens']:.0f} tokens) — docs/moe.md")
    if r.get("migration_stalled_replica"):
        ms = r["migration_stalled_replica"]
        w(f"  migration-stalled replica: {ms['replica']} idled "
          f"{ms['stall_steps']:.0f} decode step(s) waiting on KV "
          f"migrations — docs/serving.md")
    sc = r.get("serve_cache") or {}
    if sc:
        hits = sc.get("serve.prefix_hits")
        rate = sc.get("serve.prefix_hit_rate")
        acc = sc.get("serve.spec.acceptance_rate")
        migs = sc.get("serve.kv.migrations")
        parts = []
        if hits is not None and rate is not None:
            parts.append(f"prefix hits {hits:.0f} (rate {rate:.2f})")
        if acc is not None:
            parts.append(f"spec acceptance {acc:.2f}")
        if migs is not None:
            parts.append(f"kv migrations {migs:.0f}")
        if parts:
            w(f"  serving cache: {', '.join(parts)}")
    if r["straggler_history"]:
        w("")
        w("-- straggler history (pre-crash) --")
        for d in r["straggler_history"][-10:]:
            if d.get("kind") == "link":
                w(f"  rank {d.get('rank')} link {d.get('hop')} "
                  f"health {d.get('ratio')} > gate {d.get('gate')}")
            else:
                w(f"  rank {d.get('rank')} phase {d.get('phase')} "
                  f"{d.get('ms')} ms vs median {d.get('median_ms')} ms")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True,
                    help="HOROVOD_FLIGHT_RECORDER_DIR of the dead run")
    ap.add_argument("--json", help="also write the report dict here")
    args = ap.parse_args()
    if not os.path.isdir(args.dir):
        ap.error(f"no such directory: {args.dir}")
    report = build_report(args.dir)
    if report["dumps"] == 0:
        print(f"no parseable flight dumps in {args.dir}", file=sys.stderr)
        for c in report["corrupt"]:
            print(f"  CORRUPT {c['path']}: {c['error']}", file=sys.stderr)
        return 2
    print_report(report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
