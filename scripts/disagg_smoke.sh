#!/usr/bin/env bash
# Disaggregated-serving smoke: the prefill/decode split A/B on the
# 8-device virtual CPU mesh through `bench.py --serve --disagg`
# (docs/serving.md): prefill replicas hand finished prompts to decode
# replicas over the kv_migrate wire plan, shared prompt prefixes hit
# the copy-on-write prefix cache, and the drafter's speculative windows
# are verified in one batched step.
# Asserts: rc 0 (the bench itself aborts on dropped requests, a
# decode/full-context parity failure, or disagg-vs-baseline output
# divergence), a clean drain with ZERO drops on both legs, at least one
# KV migration with zero predicted-vs-accounted byte drift, a nonzero
# prefix hit rate, and the greedy spec-decode parity probe. Runtime
# ~1 min.
#
# Usage: scripts/disagg_smoke.sh [extra bench.py args...]
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(JAX_PLATFORMS=cpu python bench.py --serve \
    --disagg "${DISAGG_SMOKE_SPLIT:-3:1}" --platform cpu \
    --cpu-devices 8 \
    --serve-requests "${DISAGG_SMOKE_REQUESTS:-12}" \
    --serve-rate "${DISAGG_SMOKE_RATE:-50}" \
    "$@" | tail -n 1)
echo "$OUT"

python - "$OUT" <<'EOF'
import json
import sys

rec = json.loads(sys.argv[1])
assert rec["metric"] == "gpt_serve_goodput_tokens_per_sec", rec["metric"]
assert rec["goodput_tokens_per_sec"] > 0, "zero goodput"
assert rec["requests_dropped"] == 0, f"dropped {rec['requests_dropped']}"
assert rec["requests_completed"] == rec["requests"], "trace did not drain"
assert rec["disagg"], "record is not a --disagg run"
assert rec["kv_migrations"] >= 1, "no KV migrations happened"
assert rec["kv_migration_bytes"] > 0, "zero migration wire bytes"
assert rec["kv_bytes_drift"] == 0, \
    f"predicted-vs-accounted drift {rec['kv_bytes_drift']}"
assert rec["prefix_hits"] > 0 and rec["prefix_hit_rate"] > 0, \
    "prefix cache never hit"
assert rec["spec_parity_ok"], "greedy spec-decode parity probe failed"
assert rec["spec_accepted"] > 0, "drafter never had a token accepted"
assert rec["baseline_goodput_tokens_per_sec"] > 0, "no baseline leg"
print(f"disagg smoke OK: {rec['disagg']} split, goodput "
      f"{rec['goodput_tokens_per_sec']} tok/s "
      f"({rec['goodput_vs_baseline']}x symmetric baseline), "
      f"{rec['kv_migrations']} migrations "
      f"({rec['kv_migration_bytes']:.0f} wire bytes, drift 0), "
      f"prefix hit rate {rec['prefix_hit_rate']}, spec acceptance "
      f"{rec['spec_acceptance_rate']}, parity bit-identical")
EOF
