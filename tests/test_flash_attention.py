"""Pallas flash attention vs the dense reference path.

Flash attention is an exact algorithm — forward AND backward (custom VJP
kernels) must match ``dense_attention`` to float tolerance. On the CPU
test mesh the kernels run in Pallas interpreter mode; the identical code
compiles through Mosaic on TPU (verified by bench.py --model gpt).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel import sequence as seqpar
from jax0437_repros import _old_jax


def _qkv(B=1, T=128, H=2, D=32, seed=0, dtype=jnp.float32):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(B, T, H, D), dtype) * 0.3
    return mk(), mk(), mk()


class TestFlashForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=causal)
        expect = seqpar.dense_attention(q, k, v, causal=causal)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=1e-5, atol=1e-5)

    def test_multi_block(self):
        """T spans several blocks (explicit block 128 < T) so the streaming
        softmax carry and the causal block-skip both execute."""
        q, k, v = _qkv(T=384, H=1, D=16, seed=3)
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
        expect = seqpar.dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=1e-5, atol=1e-5)

    def test_block_shrinks_to_divisor(self):
        """T > preferred block and indivisible by it: block shrinks to the
        largest 128-multiple divisor instead of falling back to dense."""
        from horovod_tpu.ops.flash_attention import _pick_block

        assert _pick_block(768, 512) == 384
        assert _pick_block(100, 512) == 100    # single whole-seq block
        assert _pick_block(520, 512) is None   # no aligned divisor
        q, k, v = _qkv(T=768, H=1, D=16, seed=8)
        out = flash_attention(q, k, v, causal=True)
        expect = seqpar.dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16(self):
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=1)
        out = flash_attention(q, k, v, causal=True)
        expect = seqpar.dense_attention(q, k, v, causal=True)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(expect, np.float32),
            rtol=2e-2, atol=2e-2)

    def test_no_aligned_divisor_falls_back_to_dense(self):
        # 520 > 512 and has no 128-multiple divisor → dense path.
        q, k, v = _qkv(T=520, seed=2)
        out = flash_attention(q, k, v, causal=True)
        expect = seqpar.dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=1e-5, atol=1e-5)

    def test_causal_cross_attention_rejected(self):
        q, _, _ = _qkv(T=128)
        _, k, v = _qkv(T=256)
        with pytest.raises(ValueError, match="Tq == Tk"):
            flash_attention(q, k, v, causal=True)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_dense(self, causal):
        q, k, v = _qkv(seed=4)
        w = jnp.asarray(np.random.RandomState(5).randn(32), jnp.float32)

        def loss(attn):
            return lambda q, k, v: jnp.sum(attn(q, k, v, causal=causal) * w)

        gf = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss(seqpar.dense_attention),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gd, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
                err_msg=f"d{name} mismatch")

    def test_grads_multi_block(self):
        q, k, v = _qkv(T=256, H=1, D=16, seed=6)

        def loss(attn):
            return lambda q, k, v: jnp.mean(
                attn(q, k, v, causal=True) ** 2)

        gf = jax.grad(loss(lambda q, k, v, causal: flash_attention(
            q, k, v, causal=causal, block_q=128, block_k=128)),
            argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss(seqpar.dense_attention),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gd, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                err_msg=f"d{name} mismatch")

    def test_grads_single_block(self):
        q, k, v = _qkv(T=256, H=1, D=16, seed=6)

        def loss(attn):
            return lambda q, k, v: jnp.mean(
                attn(q, k, v, causal=True) ** 2)

        gf = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss(seqpar.dense_attention),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gd, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                err_msg=f"d{name} mismatch")


class TestFlashRingAttention:
    """Sequence-parallel flash attention: ppermute ring of flash kernels
    with logsumexp partial merging; backward replays the ring with dk/dv
    accumulators traveling alongside their blocks."""

    @pytest.mark.parametrize("causal", [
        True,
        pytest.param(False, marks=pytest.mark.xfail(
            _old_jax(), strict=False,
            reason="upstream jax 0.4.37: axis_index over a mesh-axis "
                   "tuple in a scan body lowers to stablehlo.partition_id"
                   ", which the SPMD partitioner rejects (UNIMPLEMENTED) "
                   "in the non-causal ring layout — pure-jax repro: "
                   "tests/jax0437_repros.py::repro_partition_id (fixed "
                   "by the jax.shard_map graduation, jax >= 0.6)")),
    ])
    def test_matches_dense(self, causal):
        from horovod_tpu.ops.flash_attention import flash_ring_attention

        q, k, v = _qkv(T=256, H=2, D=16, seed=9)
        expect = seqpar.dense_attention(q, k, v, causal=causal)
        mesh = hvd.mesh()
        spec = P(None, hvd.HVD_AXES)
        out = jax.jit(hvd.shard_map(
            lambda a, b, c: flash_ring_attention(
                a, b, c, axis=hvd.HVD_AXES, causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        ))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_grads_match_dense(self):
        from horovod_tpu.ops.flash_attention import flash_ring_attention

        q, k, v = _qkv(T=256, H=2, D=16, seed=10)
        w = jnp.asarray(np.random.RandomState(11).randn(16), jnp.float32)
        mesh = hvd.mesh()
        spec = P(None, hvd.HVD_AXES)

        def ring_loss(q, k, v):
            o = hvd.shard_map(
                lambda a, b, c: flash_ring_attention(
                    a, b, c, axis=hvd.HVD_AXES, causal=True),
                mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec)(q, k, v)
            return jnp.sum(o * w)

        def dense_loss(q, k, v):
            return jnp.sum(seqpar.dense_attention(q, k, v, causal=True) * w)

        gf = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gd, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
                err_msg=f"d{name} mismatch")

    def test_gpt_flash_ring_matches_dense_gpt(self):
        cfg_d = gpt_tiny(dtype=jnp.float32)
        cfg_r = gpt_tiny(dtype=jnp.float32, attention="flash_ring",
                         seq_axis=hvd.HVD_AXES)
        B, T = 2, 64
        rs = np.random.RandomState(12)
        tokens = jnp.asarray(rs.randint(0, cfg_d.vocab_size, (B, T)))

        variables = GPT(cfg_d).init(jax.random.PRNGKey(0), tokens)
        expect = GPT(cfg_d).apply(variables, tokens)
        mesh = hvd.mesh()
        out = jax.jit(hvd.shard_map(
            lambda v, t: GPT(cfg_r).apply(v, t),
            mesh=mesh, in_specs=(P(), P(None, hvd.HVD_AXES)),
            out_specs=P(None, hvd.HVD_AXES),
        ))(variables, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=5e-4, atol=5e-4)


class TestFlashIntegration:
    def test_gpt_flash_matches_gpt_dense(self):
        cfg_d = gpt_tiny(dtype=jnp.float32)
        cfg_f = gpt_tiny(dtype=jnp.float32, attention="flash")
        B, T = 1, 128  # T = one full flash block → kernel path, not fallback
        rs = np.random.RandomState(0)
        tokens = jnp.asarray(rs.randint(0, cfg_d.vocab_size, (B, T)))

        variables = GPT(cfg_d).init(jax.random.PRNGKey(0), tokens)
        expect = GPT(cfg_d).apply(variables, tokens)
        out = GPT(cfg_f).apply(variables, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=5e-4, atol=5e-4)

    def test_ulysses_with_flash_local_attention(self):
        """Ulysses re-shards seq→heads; the local attention on the full
        gathered sequence runs the flash kernel inside shard_map."""
        q, k, v = _qkv(B=1, T=256, H=8, D=16, seed=7)
        expect = seqpar.dense_attention(q, k, v, causal=True)
        mesh = hvd.mesh()
        spec = P(None, hvd.HVD_AXES)
        out = jax.jit(hvd.shard_map(
            lambda a, b, c: seqpar.ulysses_attention(
                a, b, c, axis=hvd.HVD_AXES, causal=True,
                attn_fn=lambda qf, kf, vf: flash_attention(
                    qf, kf, vf, causal=True)),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        ))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)
