"""Compile-once runtime tests (docs/compile.md): the persistent
executable cache, AOT warm pools, and background precompile for elastic
resizes.

Covers the contract surface the CI bricks lean on:
  * key anatomy — tag / wire-plan encoding / mesh geometry / shape+dtype
    signature each produce a DIFFERENT executable key (transfer safety:
    an executable compiled for one topology or plan never hits another);
  * hit ladder — miss compiles once; the second identical request is a
    memory hit; a fresh registry (new process) loads the entry from
    disk; a fresh PROCESS pays zero compiles (subprocess warm rerun —
    the scripts/compile_smoke.sh gate in miniature);
  * failure discipline — a corrupt index, a truncated payload, or a
    missing cache dir logs a warning and falls back to a cold compile
    (the cache is an optimization, never a failure);
  * resize ordering — ``ReplicaSet.request_resize`` keeps serving on the
    OLD geometry until the background warm-pool thread reports ready;
    only then does ``step_all`` drain and rebuild (drain-after-warm is
    the resize_stall_ms win);
  * observability — COMPILE:LOWER / COMPILE:COMPILE spans balance under
    the strict span audit; hits emit COMPILE:CACHE_HIT instants.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.compile import (
    CompileResult,
    arm_persistent_cache,
    cache as xcache,
    executable_key,
    get_or_compile,
    precompile,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = 8


@pytest.fixture()
def fresh_cache(tmp_path, monkeypatch):
    """Point the executable cache at an empty per-test directory and
    zero the process counters, restoring both afterwards."""
    monkeypatch.setenv("HOROVOD_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("HOROVOD_COMPILE_CACHE", raising=False)
    # Isolate the XLA persistent cache too: an executable whose
    # compile() was itself served from a (session-shared) XLA disk cache
    # can serialize into a payload that will not deserialize in the same
    # process — the registry tolerates that (cold-compile fallback), but
    # these tests pin the clean-layer hit ladder.
    prev_xla = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir",
                      str(tmp_path / "xla"))
    xcache.clear_memory()
    xcache.reset_stats()
    yield tmp_path
    jax.config.update("jax_compilation_cache_dir", prev_xla)
    xcache.clear_memory()
    xcache.reset_stats()


def _lower_double(shape=(8,)):
    f = jax.jit(lambda x: x * 2.0 + 1.0)
    spec = jax.ShapeDtypeStruct(shape, jnp.float32)
    return lambda: f.lower(spec)


# ---------------------------------------------------------------------------
# key anatomy
# ---------------------------------------------------------------------------


class TestExecutableKey:
    def test_key_is_stable(self):
        spec = (jax.ShapeDtypeStruct((4, 8), jnp.float32),)
        assert executable_key("t", plan="p", shapes=spec) == \
            executable_key("t", plan="p", shapes=spec)

    def test_key_carries_tag_plan_and_jax_version(self):
        k = executable_key("stepfn", plan="z3|ov1")
        assert "stepfn" in k and "z3|ov1" in k
        assert f"jax{jax.__version__}" in k

    def test_tag_sensitivity(self):
        assert executable_key("a") != executable_key("b")

    def test_plan_sensitivity(self):
        assert executable_key("t", plan="z1") != \
            executable_key("t", plan="z3")

    def test_shape_dtype_sensitivity(self):
        s32 = (jax.ShapeDtypeStruct((4, 8), jnp.float32),)
        s16 = (jax.ShapeDtypeStruct((4, 8), jnp.bfloat16),)
        s_wide = (jax.ShapeDtypeStruct((4, 16), jnp.float32),)
        keys = {executable_key("t", shapes=s)
                for s in (s32, s16, s_wide)}
        assert len(keys) == 3

    def test_mesh_geometry_sensitivity(self):
        devs = jax.devices()
        m4 = jax.sharding.Mesh(np.array(devs[:4]), ("serve_tp",))
        m8 = jax.sharding.Mesh(np.array(devs[:8]), ("serve_tp",))
        m4b = jax.sharding.Mesh(np.array(devs[4:8]), ("serve_tp",))
        keys = {executable_key("t", mesh=m) for m in (m4, m8, m4b)}
        # Different world sizes AND different device slices of the same
        # size are different executables (a replica's engine is pinned
        # to its device group).
        assert len(keys) == 3

    def test_framework_mesh_uses_geometry_fingerprint(self):
        from horovod_tpu.common import basics

        k = executable_key("t", mesh=hvd.mesh())
        assert basics.mesh_geometry() in k


# ---------------------------------------------------------------------------
# hit ladder: miss -> memory -> disk -> warm process
# ---------------------------------------------------------------------------


class TestHitLadder:
    def test_miss_then_memory_hit(self, fresh_cache):
        r1 = get_or_compile("t_ladder", _lower_double())
        assert isinstance(r1, CompileResult)
        assert r1.source == "compiled" and not r1.cache_hit
        assert r1.compile_ms > 0
        r2 = get_or_compile("t_ladder", _lower_double())
        assert r2.source == "memory" and r2.cache_hit
        assert r2.key == r1.key
        s = xcache.stats()
        assert s["misses"] == 1 and s["hits"] == 1
        assert xcache.compile_count() == 1
        x = jnp.arange(8, dtype=jnp.float32)
        np.testing.assert_allclose(r2.compiled(x), x * 2 + 1)

    def test_disk_hit_after_registry_clear(self, fresh_cache):
        r1 = get_or_compile("t_disk", _lower_double(),
                            aux_fn=lambda lowered: {"bytes": 123})
        assert r1.source == "compiled" and r1.aux == {"bytes": 123}
        xcache.clear_memory()
        r2 = get_or_compile("t_disk", _lower_double())
        assert r2.source == "disk" and r2.cache_hit
        # aux rides the disk entry: warm hits replay the metadata the
        # miss captured at trace time (bench's wire-stats pattern).
        assert r2.aux == {"bytes": 123}
        assert xcache.stats()["disk_hits"] == 1
        x = jnp.ones((8,), jnp.float32)
        np.testing.assert_allclose(r2.compiled(x), x * 2 + 1)

    def test_lower_not_called_on_hit(self, fresh_cache):
        calls = []

        def lower():
            calls.append(1)
            return _lower_double()()

        get_or_compile("t_lazy", lower)
        get_or_compile("t_lazy", lower)
        xcache.clear_memory()
        get_or_compile("t_lazy", lower)
        assert len(calls) == 1  # memory AND disk hits skip lowering

    def test_distinct_shapes_do_not_alias(self, fresh_cache):
        f = jax.jit(lambda x: x + 1.0)
        r8 = get_or_compile(
            "t_shape", lambda: f.lower(
                jax.ShapeDtypeStruct((8,), jnp.float32)),
            shapes=(jax.ShapeDtypeStruct((8,), jnp.float32),))
        r4 = get_or_compile(
            "t_shape", lambda: f.lower(
                jax.ShapeDtypeStruct((4,), jnp.float32)),
            shapes=(jax.ShapeDtypeStruct((4,), jnp.float32),))
        assert r8.key != r4.key
        assert r4.source == "compiled"
        np.testing.assert_allclose(
            r4.compiled(jnp.zeros((4,), jnp.float32)), np.ones((4,)))

    def test_persistence_disabled_keeps_memory_layer(
            self, fresh_cache, monkeypatch):
        monkeypatch.setenv("HOROVOD_COMPILE_CACHE", "0")
        r1 = get_or_compile("t_off", _lower_double())
        assert r1.source == "compiled"
        assert get_or_compile("t_off", _lower_double()).source == "memory"
        # nothing persisted: a fresh registry compiles again
        xcache.clear_memory()
        assert get_or_compile("t_off", _lower_double()).source == \
            "compiled"
        assert not os.path.exists(
            os.path.join(str(fresh_cache), "exec", "index.json"))

    def test_warm_process_pays_zero_compiles(self, fresh_cache):
        """The compile_smoke.sh contract in miniature: a second PROCESS
        with the same cache dir serves its executable from disk —
        compile_count == 0."""
        script = (
            "import json, os\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "import jax, jax.numpy as jnp\n"
            "from horovod_tpu.compile import cache\n"
            "f = jax.jit(lambda x: x * 2.0 + 1.0)\n"
            "spec = jax.ShapeDtypeStruct((8,), jnp.float32)\n"
            "res = cache.get_or_compile('t_warm_proc',"
            " lambda: f.lower(spec))\n"
            "out = res.compiled(jnp.arange(8, dtype=jnp.float32))\n"
            "print(json.dumps({'source': res.source,"
            " 'compile_count': cache.compile_count(),"
            " 'stats': cache.stats(), 'y3': float(out[3])}))\n")
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["HOROVOD_COMPILE_CACHE_DIR"] = str(fresh_cache)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

        def run():
            proc = subprocess.run([sys.executable, "-c", script],
                                  env=env, capture_output=True,
                                  text=True, timeout=300)
            assert proc.returncode == 0, proc.stderr[-4000:]
            return json.loads(proc.stdout.strip().splitlines()[-1])

        cold = run()
        assert cold["source"] == "compiled"
        assert cold["compile_count"] == 1
        warm = run()
        assert warm["source"] == "disk", warm
        assert warm["compile_count"] == 0
        assert warm["stats"]["disk_hits"] == 1
        assert warm["y3"] == cold["y3"] == 7.0


# ---------------------------------------------------------------------------
# failure discipline: the cache is an optimization, never a failure
# ---------------------------------------------------------------------------


class TestCorruptCacheTolerance:
    def test_corrupt_index_falls_back_to_cold_compile(self, fresh_cache):
        idx = os.path.join(str(fresh_cache), "exec", "index.json")
        os.makedirs(os.path.dirname(idx), exist_ok=True)
        with open(idx, "w") as f:
            f.write("{not json at all")
        r = get_or_compile("t_corrupt_idx", _lower_double())
        assert r.source == "compiled"
        x = jnp.zeros((8,), jnp.float32)
        np.testing.assert_allclose(r.compiled(x), np.ones((8,)))
        # and the store path healed the index for the next reader
        xcache.clear_memory()
        assert get_or_compile("t_corrupt_idx",
                              _lower_double()).source == "disk"

    def test_truncated_payload_logs_and_recompiles(self, fresh_cache,
                                                   caplog):
        get_or_compile("t_trunc", _lower_double())
        exec_dir = os.path.join(str(fresh_cache), "exec")
        with open(os.path.join(exec_dir, "index.json")) as f:
            meta = next(iter(json.load(f).values()))
        with open(os.path.join(exec_dir, meta["file"]), "wb") as f:
            f.write(b"\x80garbage")
        xcache.clear_memory()
        xcache._warned["disk"] = False
        import logging

        with caplog.at_level(logging.WARNING, "horovod_tpu.compile"):
            r = get_or_compile("t_trunc", _lower_double())
        assert r.source == "compiled"  # cold compile, not an exception
        assert any("falling back to cold compile" in m
                   for m in caplog.messages)

    def test_unwritable_cache_dir_still_compiles(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_COMPILE_CACHE_DIR",
                           "/proc/definitely/not/writable")
        xcache.clear_memory()
        r = get_or_compile("t_nodir", _lower_double())
        assert r.source == "compiled"
        np.testing.assert_allclose(
            r.compiled(jnp.zeros((8,), jnp.float32)), np.ones((8,)))
        xcache.clear_memory()


# ---------------------------------------------------------------------------
# arm_persistent_cache + hvd.precompile
# ---------------------------------------------------------------------------


class TestArmAndPrecompile:
    def test_arm_points_jax_at_the_cache_dir(self, fresh_cache):
        prev = jax.config.jax_compilation_cache_dir
        try:
            armed = arm_persistent_cache()
            assert armed == os.path.join(str(fresh_cache), "xla")
            assert os.path.isdir(armed)
            assert jax.config.jax_compilation_cache_dir == armed
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_arm_respects_disable_knob(self, fresh_cache, monkeypatch):
        monkeypatch.setenv("HOROVOD_COMPILE_CACHE", "0")
        assert arm_persistent_cache() is None

    def test_precompile_warms_every_spec_once(self, fresh_cache):
        specs = [(jax.ShapeDtypeStruct((4,), jnp.float32),),
                 (jax.ShapeDtypeStruct((16,), jnp.float32),)]
        out = hvd.precompile(lambda x: x - 1.0, specs, tag="t_pool")
        assert [r.source for r in out] == ["compiled", "compiled"]
        np.testing.assert_allclose(
            out[1].compiled(jnp.ones((16,), jnp.float32)),
            np.zeros((16,)))
        # the warm pool dedupes: same specs again -> all hits
        again = precompile(lambda x: x - 1.0, specs, tag="t_pool")
        assert all(r.cache_hit for r in again)
        assert xcache.compile_count() == 2


# ---------------------------------------------------------------------------
# background precompile before the resize drain (serve)
# ---------------------------------------------------------------------------


@pytest.mark.serve
class TestResizePrecompileOrdering:
    @pytest.fixture(scope="class")
    def serve_bits(self):
        from horovod_tpu.models import GPT, gpt_tiny
        from horovod_tpu.serve import PageConfig

        cfg = gpt_tiny(dtype=jnp.float32, num_heads=8)
        params = GPT(cfg).init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 8), jnp.int32))["params"]
        pc = PageConfig(num_pages=64, page_size=4, max_slots=4,
                        pages_per_slot=16, num_layers=cfg.num_layers,
                        num_heads=cfg.num_heads,
                        head_dim=cfg.d_model // cfg.num_heads)
        return cfg, params, pc

    def test_drain_waits_for_background_warm_pool(self, serve_bits,
                                                  fresh_cache):
        from horovod_tpu.serve import ReplicaSet, Request

        cfg, params, pc = serve_bits
        rset = ReplicaSet(cfg, params, pc, n_replicas=2, eos_id=1)
        for i in range(3):
            rset.submit(Request(req_id=i, prompt=[2, 3, 4, 5],
                                max_new_tokens=4, arrival_time=0.0))
        assert rset.request_resize(1)
        assert rset.resize_pending
        # a second request while one is pending is refused
        assert not rset.request_resize(2)
        # the old geometry keeps serving while the target warms: the
        # engine set must NOT shrink until the warm pool reports ready
        saw_old_geometry_step = False
        deadline = time.monotonic() + 120.0
        step = 0
        while rset.resize_pending:
            if len(rset.engines) == 2:
                saw_old_geometry_step = True
            rset.step_all(float(step))
            step += 1
            assert time.monotonic() < deadline, \
                "background precompile never completed"
        assert saw_old_geometry_step
        assert len(rset.engines) == 1
        ev = rset.resize_events[-1]
        assert ev["background"] is True
        assert ev["to"] == 1 and ev["from"] == 2
        # ordering contract: the warm pool ran BEFORE the drain, so the
        # stall window excludes it — precompile_ms is accounted
        # separately and the event says the rebuild was not warm-blocking
        assert ev["precompile_ms"] > 0
        assert ev["resize_stall_ms"] >= 0
        # in-flight work survived the flip
        while rset.has_work and time.monotonic() < deadline:
            rset.step_all(float(step))
            step += 1
        done = len(rset.stats.completed) + sum(
            len(e.stats.completed) for e in rset.engines)
        assert done == 3

    def test_foreground_resize_warms_before_drain(self, serve_bits,
                                                  fresh_cache):
        from horovod_tpu.serve import ReplicaSet

        cfg, params, pc = serve_bits
        rset = ReplicaSet(cfg, params, pc, n_replicas=2, eos_id=1)
        xcache.reset_stats()
        rset.resize(1)
        ev = rset.resize_events[-1]
        assert ev["warm"] is True and ev["background"] is False
        assert ev["precompile_ms"] > 0
        from horovod_tpu import monitor

        g = monitor.metrics().gauge("serve.resize_stall_ms").value
        # the event value is rounded to 3 decimals; the gauge is raw
        assert g == pytest.approx(ev["resize_stall_ms"], abs=1e-3)


# ---------------------------------------------------------------------------
# observability: strict span balance
# ---------------------------------------------------------------------------


class TestCompileSpans:
    def test_compile_spans_balance_strict(self, tmp_path, monkeypatch):
        from horovod_tpu.monitor import span_audit

        tl = str(tmp_path / "compile_tl.json")
        monkeypatch.setenv("HOROVOD_COMPILE_CACHE_DIR",
                           str(tmp_path / "cc"))
        hvd.shutdown()
        os.environ["HOROVOD_TIMELINE"] = tl
        try:
            hvd.init(devices=jax.devices())
            xcache.clear_memory()
            xcache.reset_stats()
            get_or_compile("t_span", _lower_double())
            get_or_compile("t_span", _lower_double())  # CACHE_HIT instant
        finally:
            del os.environ["HOROVOD_TIMELINE"]
            hvd.shutdown()
            hvd.init(devices=jax.devices())
            xcache.clear_memory()
        audit = span_audit.audit_spans(tl, prefix="COMPILE:",
                                       require_balanced=True,
                                       require_spans=True, strict=True)
        assert audit.count.get("COMPILE:LOWER", 0) == 1
        assert audit.count.get("COMPILE:COMPILE", 0) == 1
        events = span_audit.load_events(tl)
        hits = [e for e in events
                if e.get("name") == "COMPILE:CACHE_HIT"]
        assert len(hits) == 1 and hits[0].get("ph") == "i"

    def test_compile_is_a_known_span_prefix(self):
        from horovod_tpu.monitor.span_audit import KNOWN_PREFIXES

        assert "COMPILE" in KNOWN_PREFIXES

    def test_miss_records_compile_straggler_phase_and_metrics(
            self, fresh_cache):
        from horovod_tpu import monitor

        m0 = monitor.metrics().counter("compile.misses",
                                       key="t_metrics").value
        get_or_compile("t_metrics", _lower_double())
        get_or_compile("t_metrics", _lower_double())
        assert monitor.metrics().counter(
            "compile.misses", key="t_metrics").value == m0 + 1
        assert monitor.metrics().counter(
            "compile.hits", key="t_metrics").value >= 1
