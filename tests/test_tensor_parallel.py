"""Tensor parallelism: Megatron-style GPT sharding on the virtual mesh.

The tp model applied to sliced dense parameters must reproduce the dense
model exactly (column/row-parallel slicing + psum is a reorganization of
the same arithmetic), and DP x TP training must step with gradients
averaged over the data axis only.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.parallel.tensor import (
    tp_merge_params,
    tp_shard_params,
    tp_split_params,
    tp_unshard_params,
)


def _dense_and_tokens(B=2, T=32, seed=0, **over):
    cfg = gpt_tiny(dtype=jnp.float32, num_heads=8, d_model=64, d_ff=128,
                   **over)
    rs = np.random.RandomState(seed)
    tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
    variables = GPT(cfg).init(jax.random.PRNGKey(0), tokens)
    return cfg, variables["params"], tokens


class TestTPShardParams:
    def test_roundtrip(self):
        _, params, _ = _dense_and_tokens()
        stacked = tp_shard_params(params, 4)
        back = tp_unshard_params(stacked)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6),
            params, back)

    def test_shard_shapes(self):
        _, params, _ = _dense_and_tokens()
        stacked = tp_shard_params(params, 8)
        qkv = stacked["h0"]["attn"]["qkv"]["kernel"]
        assert qkv.shape == (8, 64, 3 * 64 // 8)
        fc1 = stacked["h0"]["mlp"]["Dense_0"]["kernel"]
        assert fc1.shape == (8, 64, 128 // 8)
        fc2 = stacked["h0"]["mlp"]["Dense_1"]["kernel"]
        assert fc2.shape == (8, 128 // 8, 64)
        assert stacked["wte"].shape[0] == 8  # replicated copies


class TestTPGPT:
    def test_tp_overlapping_seq_axis_rejected(self):
        """Sequence-parallel attention on the same axis as tp would rotate
        k/v between different head shards — must fail loudly."""
        import dataclasses

        import pytest

        cfg, params, tokens = _dense_and_tokens()
        bad = dataclasses.replace(cfg, attention="ring",
                                  tp_axis=hvd.LOCAL_AXIS,
                                  seq_axis=hvd.LOCAL_AXIS)
        sharded, repl = tp_split_params(
            params, hvd.mesh().devices.shape[1])
        mesh = hvd.mesh()

        def spmd(stk, rp, tok):
            local = tp_merge_params(
                jax.tree.map(lambda a: a[0], stk), rp)
            return GPT(bad).apply({"params": local}, tok)

        with pytest.raises(ValueError, match="overlaps"):
            jax.jit(hvd.shard_map(
                spmd, mesh=mesh,
                in_specs=(P(hvd.LOCAL_AXIS), P(), P()),
                out_specs=P()))(sharded, repl, tokens)

    def test_tp8_matches_dense(self):
        """8-way TP over the full mesh == the dense model."""
        import dataclasses

        cfg, params, tokens = _dense_and_tokens()
        expect = GPT(cfg).apply({"params": params}, tokens)

        tp_cfg = dataclasses.replace(cfg, tp_axis=hvd.HVD_AXES)
        sharded, repl = tp_split_params(params, hvd.size())
        mesh = hvd.mesh()

        def spmd(stk, rp, tok):
            local = tp_merge_params(
                jax.tree.map(lambda a: a[0], stk), rp)
            return GPT(tp_cfg).apply({"params": local}, tok)

        out = jax.jit(hvd.shard_map(
            spmd, mesh=mesh, in_specs=(P(hvd.HVD_AXES), P(), P()),
            out_specs=P()))(sharded, repl, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)

    def test_dp_tp_2d(self):
        """DP over hvd_cross x TP over hvd_local: batch-sharded forward
        equals the dense model."""
        import dataclasses

        cfg, params, tokens = _dense_and_tokens(B=4)
        expect = GPT(cfg).apply({"params": params}, tokens)

        mesh = hvd.mesh()
        n_tp = mesh.devices.shape[1]
        tp_cfg = dataclasses.replace(cfg, tp_axis=hvd.LOCAL_AXIS)
        sharded, repl = tp_split_params(params, n_tp)

        def spmd(stk, rp, tok):
            local = tp_merge_params(
                jax.tree.map(lambda a: a[0], stk), rp)
            return GPT(tp_cfg).apply({"params": local}, tok)

        out = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.CROSS_AXIS)),
            out_specs=P(hvd.CROSS_AXIS)))(sharded, repl, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)

    def test_dp_tp_train_step(self):
        """One DP x TP training step: tp-sharded params update with
        gradients averaged over the DATA axis only."""
        import dataclasses

        cfg, params, tokens = _dense_and_tokens(B=4, seed=2)
        targets = jnp.asarray(
            np.random.RandomState(3).randint(0, cfg.vocab_size,
                                             tokens.shape))
        mesh = hvd.mesh()
        n_tp = mesh.devices.shape[1]
        tp_cfg = dataclasses.replace(cfg, tp_axis=hvd.LOCAL_AXIS)
        sharded, repl = tp_split_params(params, n_tp)
        # Gradient averaging over the dp (cross) axis ONLY — tp shards are
        # different parameters.
        tx = hvd.DistributedOptimizer(optax.adam(1e-3),
                                      axes=hvd.CROSS_AXIS)
        model = GPT(tp_cfg)

        def loss_fn(p, tok, tgt):
            logits = model.apply({"params": p}, tok)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

        def spmd(stk, rp, tok, tgt):
            local = tp_merge_params(
                jax.tree.map(lambda a: a[0], stk), rp)
            opt_state = tx.init(local)
            loss, grads = hvd.value_and_grad(loss_fn, axes=hvd.CROSS_AXIS)(
                local, tok, tgt)
            updates, _ = tx.update(grads, opt_state, local)
            new_local = optax.apply_updates(local, updates)
            new_qkv = new_local["h0"]["attn"]["qkv"]["kernel"]
            return new_qkv[None], hvd.allreduce(loss)

        new_qkv, loss = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.CROSS_AXIS),
                      P(hvd.CROSS_AXIS)),
            out_specs=(P(hvd.LOCAL_AXIS), P())))(sharded, repl, tokens,
                                                 targets)
        assert np.isfinite(float(loss))
        # Parameters moved, and the qkv shards differ across tp ranks
        # (they are genuinely different parameters).
        q0 = np.asarray(new_qkv)
        assert not np.allclose(q0[0], np.asarray(
            sharded["h0"]["attn"]["qkv"]["kernel"][0]))
        assert not np.allclose(q0[0], q0[1])
