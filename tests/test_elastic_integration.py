"""Elastic end-to-end integration (reference:
test/integration/elastic_common.py + test_elastic.py — real worker
processes on localhost, scripted host churn, hard-crash fault injection)."""

import json
import os
import shlex
import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.elastic import constants
from horovod_tpu.elastic.discovery import HostDiscoveryScript
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.runner import safe_shell_exec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


def _read_log(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _make_local_exec(extra_args, log_file, extra_env=None):
    """create_worker_fn that always executes locally regardless of the
    (possibly fake) hostname — the reference mocks ssh the same way.
    ``extra_env`` rides into every worker (e.g. a FaultPlan.to_env())."""

    def _exec(slot, world_id):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "PYTHONPATH": REPO,
            "HOROVOD_HOSTNAME": slot.hostname,
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_ELASTIC_DRIVER_ADDR": "127.0.0.1",
            "HOROVOD_ELASTIC_DRIVER_PORT": str(_exec.driver.service_port),
            "HOROVOD_ELASTIC_DRIVER_KEY": _exec.driver.key.hex(),
            # fail world formation fast so the retry path, not the 120 s
            # default, bounds test time
            "HOROVOD_START_TIMEOUT": "30",
        })
        env.update(extra_env or {})
        cmd = " ".join(shlex.quote(c) for c in [
            sys.executable, WORKER, "--log-file", log_file, *extra_args])
        return safe_shell_exec.execute(cmd, env=env)

    return _exec


@pytest.fixture(autouse=True)
def _fast_discovery(monkeypatch):
    monkeypatch.setattr(constants, "DISCOVER_HOSTS_FREQUENCY_SECS", 0.25)


def _run_driver(discovery, exec_fn, min_np, max_np, timeout=240,
                reset_limit=None, **driver_kwargs):
    driver = ElasticDriver(discovery, min_np=min_np, max_np=max_np,
                           reset_limit=reset_limit,
                           controller_addr_override="127.0.0.1",
                           **driver_kwargs)
    exec_fn.driver = driver
    try:
        driver.start(exec_fn)
        ok = driver.join(timeout=timeout)
        return driver, ok
    finally:
        driver.stop()
        driver.shutdown_service()


class TestElasticGrowth:
    def test_world_grows_when_host_added(self, tmp_path):
        """Start with 1 slot; add a second host mid-run; workers must
        re-rendezvous into a world of 2 and finish."""
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("hostA:1\n")
        script = tmp_path / "discover.sh"
        script.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
        script.chmod(0o755)
        log_file = str(tmp_path / "log.jsonl")

        def _grow():
            # wait until training is underway, then add capacity
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if len(_read_log(log_file)) >= 2:
                    hosts_file.write_text("hostA:1\nhostB:1\n")
                    return
                time.sleep(0.1)

        grower = threading.Thread(target=_grow, daemon=True)
        grower.start()
        exec_fn = _make_local_exec(
            ["--batches", "14", "--batch-sleep", "0.3"], log_file)
        driver, ok = _run_driver(HostDiscoveryScript(str(script), 1),
                                 exec_fn, min_np=1, max_np=2)
        assert ok, _read_log(log_file)
        records = _read_log(log_file)
        sizes = {r["size"] for r in records}
        assert 1 in sizes and 2 in sizes, sizes
        done = [r for r in records if r.get("done")]
        assert len(done) == 2, done
        # allreduce contract held in both worlds: weights grew by `size`
        # per batch and every finisher agrees (synced via rank-0 broadcast).
        assert len({r["weights"] for r in done}) == 1, done

    def test_worker_crash_rolls_back_and_continues(self, tmp_path):
        """3 slots on 2 (fake) hosts; the hostB worker hard-crashes at batch
        3. hostB is blacklisted, survivors restore from the last commit and
        finish in a world of 2."""
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho hostA:2\necho hostB:1\n")
        script.chmod(0o755)
        log_file = str(tmp_path / "log.jsonl")
        exec_fn = _make_local_exec(
            ["--batches", "10", "--batch-sleep", "0.2",
             "--exit-at", "hostB:0:3"], log_file)
        driver, ok = _run_driver(HostDiscoveryScript(str(script), 1),
                                 exec_fn, min_np=2, max_np=3)
        assert ok, _read_log(log_file)
        assert driver.host_manager.is_blacklisted("hostB")
        records = _read_log(log_file)
        done = [r for r in records if r.get("done")]
        assert len(done) == 2, done
        assert all(r["size"] == 2 for r in done), done
        # crashed worker must not have logged past its injection point
        b_records = [r for r in records
                     if r["identity"] == "hostB:0" and "batch" in r]
        assert all(r["batch"] < 3 for r in b_records), b_records
        assert len({r["weights"] for r in done}) == 1, done


class TestChaosElastic:
    """Recovery demonstrated under deterministic injected faults (the
    three fault families from docs/robustness.md: worker crash, RPC
    message loss, rendezvous stall)."""

    @pytest.fixture(autouse=True)
    def _clean_chaos(self):
        from horovod_tpu import chaos
        from horovod_tpu.common import counters

        chaos.reset()
        counters.reset_all()
        yield
        chaos.reset()
        counters.reset_all()

    @pytest.mark.chaos
    def test_injected_worker_crash_blacklists_and_recovers(self, tmp_path):
        """FaultPlan-injected hard crash in hostB's worker at its 4th
        eager collective: the driver must blacklist hostB,
        re-rendezvous survivors at a new world_id, and committed
        training state must survive the rollback."""
        from horovod_tpu import chaos

        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho hostA:2\necho hostB:1\n")
        script.chmod(0o755)
        log_file = str(tmp_path / "log.jsonl")
        plan = chaos.FaultPlan(seed=11).add(
            "collective.eager", "crash", where="hostB:0", after=3,
            max_count=1)
        exec_fn = _make_local_exec(
            ["--batches", "10", "--batch-sleep", "0.2"], log_file,
            extra_env=plan.to_env())
        driver, ok = _run_driver(HostDiscoveryScript(str(script), 1),
                                 exec_fn, min_np=2, max_np=3)
        assert ok, _read_log(log_file)
        assert driver.host_manager.is_blacklisted("hostB")
        assert driver.world_id >= 1  # re-rendezvoused past the crash
        records = _read_log(log_file)
        done = [r for r in records if r.get("done")]
        assert len(done) == 2, done
        assert all(r["size"] == 2 for r in done), done
        # training state survived: every finisher agrees on the weights
        assert len({r["weights"] for r in done}) == 1, done
        # the crashed worker stopped exactly where the plan said
        b_records = [r for r in records
                     if r["identity"] == "hostB:0" and "batch" in r]
        assert b_records, records  # it did train before dying
        assert all(r["batch"] <= 4 for r in b_records), b_records

    @pytest.mark.chaos
    def test_injected_rpc_drops_are_absorbed_by_retry(self, tmp_path):
        """Driver-side chaos: the first two slot-grant RPCs go
        unanswered. Worker clients must absorb the loss via backoff
        retry / re-rendezvous and the job completes as if nothing
        happened."""
        from horovod_tpu import chaos
        from horovod_tpu.common import counters

        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho localhost:2\n")
        script.chmod(0o755)
        log_file = str(tmp_path / "log.jsonl")
        inj = chaos.configure(chaos.FaultPlan(seed=2).add(
            "driver.slot_grant", "drop", max_count=2))
        exec_fn = _make_local_exec(
            ["--batches", "4", "--batch-sleep", "0.05"], log_file)
        driver, ok = _run_driver(HostDiscoveryScript(str(script), 1),
                                 exec_fn, min_np=2, max_np=2)
        assert ok, _read_log(log_file)
        assert len(inj.schedule) == 2, inj.schedule  # both faults fired
        assert counters.get("chaos.drop") == 2
        done = [r for r in _read_log(log_file) if r.get("done")]
        assert len(done) == 2, _read_log(log_file)

    # slow: the injected 8 s stall bounds the runtime past the 10 s
    # tier-1 budget for chaos tests; the fast TestStallWatchdog unit
    # tests keep the watchdog in tier-1.
    @pytest.mark.chaos
    @pytest.mark.slow
    def test_injected_rendezvous_stall_trips_watchdog(self, tmp_path):
        """hostB's worker stalls before its first rendezvous; the
        driver's stall watchdog must warn, then abandon the incarnation
        — blacklisting hostB and re-forming with the survivors — and
        the stalled worker must be released cleanly when it wakes."""
        from horovod_tpu import chaos
        from horovod_tpu.common import counters

        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho hostA:2\necho hostB:1\n")
        script.chmod(0o755)
        log_file = str(tmp_path / "log.jsonl")
        plan = chaos.FaultPlan(seed=4).add(
            "bootstrap.rendezvous", "stall", where="hostB:0", secs=8,
            max_count=1)
        exec_fn = _make_local_exec(
            ["--batches", "4", "--batch-sleep", "0.05"], log_file,
            # world 0 (size 3) never forms; fail native init fast so
            # hostA's workers re-rendezvous into the post-watchdog world
            extra_env={**plan.to_env(), "HOROVOD_START_TIMEOUT": "3"})
        driver, ok = _run_driver(HostDiscoveryScript(str(script), 1),
                                 exec_fn, min_np=2, max_np=3,
                                 stall_warn_secs=1.0,
                                 stall_shutdown_secs=2.0)
        assert ok, _read_log(log_file)
        assert counters.get("elastic.stall.warning", total=True) >= 1
        assert counters.get("elastic.stall.shutdown", total=True) >= 1
        assert driver.host_manager.is_blacklisted("hostB")
        assert driver.world_id >= 1
        records = _read_log(log_file)
        done = [r for r in records if r.get("done")]
        assert len(done) == 2, records
        assert all(r["size"] == 2 for r in done), done
        # the stalled worker never trained a batch
        assert not any(r["identity"] == "hostB:0" and "batch" in r
                       for r in records), records


class TestElasticCLI:
    def test_hvdrun_elastic_localhost(self, tmp_path):
        """Full CLI path: hvdrun --min-np 2 --host-discovery-script
        (reference: test_elastic.py driving _run_elastic)."""
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho localhost:2\n")
        script.chmod(0o755)
        log_file = str(tmp_path / "log.jsonl")
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = REPO
        env["HOROVOD_ELASTIC_DISCOVER_HOSTS_FREQUENCY_SECS"] = "0.25"
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner",
             "--min-np", "2", "--max-np", "2",
             "--host-discovery-script", str(script),
             sys.executable, WORKER, "--log-file", log_file,
             "--batches", "4", "--batch-sleep", "0.05"],
            env=env, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        done = [r for r in _read_log(log_file) if r.get("done")]
        assert len(done) == 2, _read_log(log_file)
        assert all(r["size"] == 2 for r in done)
