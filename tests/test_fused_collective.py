"""Fused compute-collective Pallas kernels (docs/fused-kernels.md).

Four tiers, mirroring tests/test_plan.py:

* **kernel parity** — the interpret-mode Pallas kernels against the XLA
  compositions they replace: the int8 quantize kernel's payload is
  BIT-identical to ``ops/compression.py``'s math under jit (scales/err
  to the last ulp of the scale division — the documented contract), the
  ring matmul ops match their gather-then-matmul / matmul-then-scatter
  references to float-association tolerance;
* **wire parity matrix** — fused-vs-unfused through the PUBLIC entry
  points across {rs-epilogue, ag-prologue, quantized} × {zero_stage
  0/2/3, TP row-parallel}: identical wire bytes, ulp-bounded values,
  matching EF residual activity;
* **golden text** — ``describe_plan`` tables with the ``backend``
  column and the predicted-HBM ``fused:`` line, pinned literally;
* **satellites** — the quantized pod hop on the 2x2x2 mesh, the
  per-level HOROVOD_BENCH_POD_GBPS bandwidth model, the autotuner's
  ``fused`` dimension (schema v6) and its dead-knob canonicalization.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.ops import compression as Z
from horovod_tpu.ops import fused_collective as F
from horovod_tpu.plan import (DCN, ICI, INT8, PALLAS, POD, XLA, Leg,
                              PlanError, WirePlan, decode_tuned,
                              describe_plan, encode_tuned, planner)
from horovod_tpu.plan.accounting import bench_gbps, _modeled_wire_ms

N = 8


@pytest.fixture(scope="module", autouse=True)
def _mesh_2x4():
    hvd.shutdown()
    hvd.init(mesh_shape=(2, 4))
    yield
    hvd.shutdown()
    hvd.init()


def mesh_2x4() -> Mesh:
    return hvd.mesh()


def _run(fn, in_specs, out_specs, *args):
    return hvd.shard_map(fn, mesh=mesh_2x4(), in_specs=in_specs,
                         out_specs=out_specs)(*args)


# ---------------------------------------------------------------------------
# Kernel parity: the Pallas bodies vs the XLA compositions they replace.
# ---------------------------------------------------------------------------


class TestKernelParity:
    def test_quantize_kernel_bit_parity_under_jit(self):
        rng = np.random.RandomState(0)
        blocks = rng.randn(2, 4, 256).astype(np.float32)
        blocks[0, 1] = 0.0  # all-zero block: scale must snap to 1.0

        @jax.jit
        def both(b):
            scales = Z._block_scales(b)
            q = jnp.clip(jnp.round(b / scales[..., None]),
                         -127, 127).astype(jnp.int8)
            err = b - q.astype(jnp.float32) * scales[..., None]
            qp, sp, ep = F.quantize_blockwise(b)
            return q, scales, err, qp, sp, ep

        q, s, e, qp, sp, ep = both(jnp.asarray(blocks))
        # One compiled program, one division lowering: bit-identical.
        np.testing.assert_array_equal(np.asarray(qp), np.asarray(q))
        np.testing.assert_array_equal(np.asarray(sp), np.asarray(s))
        np.testing.assert_array_equal(np.asarray(ep), np.asarray(e))

    def test_dequant_accumulate_bit_parity_under_jit(self):
        rng = np.random.RandomState(1)
        q = rng.randint(-127, 128, (4, 3, 256)).astype(np.int8)
        s = np.abs(rng.randn(4, 3)).astype(np.float32)

        @jax.jit
        def both(q, s):
            ref = jnp.sum(q.astype(jnp.float32) * s[..., None], axis=0)
            return ref, F.dequantize_accumulate(q, s)

        ref, got = both(jnp.asarray(q), jnp.asarray(s))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_matmul_accumulate_matches_jnp(self):
        rng = np.random.RandomState(2)
        x = rng.randn(16, 512).astype(np.float32)
        w = rng.randn(512, 8).astype(np.float32)
        acc = rng.randn(16, 8).astype(np.float32)
        got = jax.jit(F._matmul_accumulate)(x, w, jnp.asarray(acc))
        np.testing.assert_allclose(np.asarray(got), acc + x @ w,
                                   rtol=1e-5, atol=1e-4)

    def test_matmul_accumulate_k_blocking(self):
        # HOROVOD_FUSED_BLOCK_K-style explicit K blocks: same result.
        rng = np.random.RandomState(3)
        x = rng.randn(8, 512).astype(np.float32)
        w = rng.randn(512, 8).astype(np.float32)
        z = jnp.zeros((8, 8), jnp.float32)
        a = jax.jit(lambda x, w: F._matmul_accumulate(
            x, w, z, block_k=128))(x, w)
        b = jax.jit(lambda x, w: F._matmul_accumulate(
            x, w, z, block_k=512))(x, w)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-4)

    def test_hbm_model_formulas(self):
        # ONE definition, shared by kernels / planner / bench.
        assert F.matmul_rs_hbm_saved(64, 10, 8, 4) == \
            2.0 * (64 - 8) * 10 * 4
        assert F.ag_matmul_hbm_saved(64, 10, 8, 4) == \
            2.0 * (64 - 8) * 10 * 4
        assert F.quant_hbm_saved(2, 3, 256) == \
            2.0 * (2 * 3 * 256 + 2 * 3 * 4)
        assert F.dequant_hbm_saved(2, 3, 256) == 2.0 * 2 * 3 * 256 * 4


# ---------------------------------------------------------------------------
# Ring ops: fused matmul⇄collective vs the unfused two-op reference.
# ---------------------------------------------------------------------------


class TestRingOps:
    def test_matmul_rs_epilogue_matches_reference(self):
        rng = np.random.RandomState(0)
        M, K, Nc = 32, 24, 16
        X = rng.randn(N, M, K).astype(np.float32)
        W = rng.randn(N, K, Nc).astype(np.float32)
        spec = P(hvd.HVD_AXES)
        got = _run(lambda xr, wr: hvd.fused_matmul_reduce_scatter(
            xr[0], wr[0]), (spec, spec), spec, X, W)
        # Reference: the unfused pair — full local product, then the
        # plan-compiled reduce-scatter of its flattened rows.
        ref = _run(lambda xr, wr: hvd.reduce_scatter(
            (xr[0] @ wr[0]).reshape(-1),
            op=hvd.Sum).reshape(M // N, Nc), (spec, spec), spec, X, W)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-4)
        # and against numpy truth
        truth = sum(X[r] @ W[r] for r in range(N))
        np.testing.assert_allclose(np.asarray(got).reshape(M, Nc),
                                   truth, rtol=1e-4, atol=1e-3)

    def test_ag_matmul_prologue_matches_reference(self):
        rng = np.random.RandomState(1)
        M, K, Nc = 8, 32, 16
        Wfull = rng.randn(K, Nc).astype(np.float32)
        x = rng.randn(M, K).astype(np.float32)
        wsh = Wfull.reshape(N, K // N, Nc)
        spec = P(hvd.HVD_AXES)

        def fused(w):
            return hvd.fused_all_gather_matmul(jnp.asarray(x), w[0])[None]

        def unfused(w):
            wf = hvd.all_gather(w[0].reshape(-1)).reshape(K, Nc)
            return (jnp.asarray(x) @ wf)[None]

        got = _run(fused, (spec,), spec, wsh)
        ref = _run(unfused, (spec,), spec, wsh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-4)
        for r in range(N):
            np.testing.assert_allclose(np.asarray(got)[r], x @ Wfull,
                                       rtol=1e-4, atol=1e-3)

    def test_eager_world_of_one_is_local_matmul(self):
        x = np.ones((4, 6), np.float32)
        w = np.ones((6, 2), np.float32)
        out = hvd.fused_matmul_reduce_scatter(jnp.asarray(x),
                                              jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(out), x @ w)
        out2 = hvd.fused_all_gather_matmul(jnp.asarray(x),
                                           jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(out2), x @ w)

    def test_shape_contract_errors(self):
        spec = P(hvd.HVD_AXES)
        X = np.zeros((N, 9, 4), np.float32)   # 9 rows !% 8
        W = np.zeros((N, 4, 4), np.float32)
        with pytest.raises(ValueError, match="does not divide"):
            _run(lambda xr, wr: hvd.fused_matmul_reduce_scatter(
                xr[0], wr[0]), (spec, spec), spec, X, W)
        Wsh = np.zeros((N, 3, 4), np.float32)  # 3*8 != 4 K columns
        with pytest.raises(ValueError, match="rank-major"):
            _run(lambda wr: hvd.fused_all_gather_matmul(
                jnp.zeros((4, 4)), wr[0])[None], (spec,), spec, Wsh)

    def test_ring_wire_accounting_matches_unfused_rs(self):
        # The fused ring moves the unfused reduce-scatter's bytes:
        # (n-1)/n of the payload, split ici/dcn by the host-boundary
        # link fraction.
        rng = np.random.RandomState(2)
        X = rng.randn(N, 32, 8).astype(np.float32)
        W = rng.randn(N, 8, 16).astype(np.float32)
        spec = P(hvd.HVD_AXES)

        def trace(fn):
            with hvd.record_wire_stats() as ws:
                jax.jit(hvd.shard_map(
                    fn, mesh=mesh_2x4(), in_specs=(spec, spec),
                    out_specs=spec)).lower(X, W)
            return ws

        wf = trace(lambda xr, wr: hvd.fused_matmul_reduce_scatter(
            xr[0], wr[0]))
        wu = trace(lambda xr, wr: hvd.reduce_scatter(
            (xr[0] @ wr[0]).reshape(-1), op=hvd.Sum).reshape(4, 16))
        assert wf.ici_bytes + wf.dcn_bytes == pytest.approx(
            wu.ici_bytes + wu.dcn_bytes)
        assert wf.fused_hbm_saved_bytes == F.matmul_rs_hbm_saved(
            32, 16, N, 4)
        assert wu.fused_hbm_saved_bytes == 0


# ---------------------------------------------------------------------------
# Wire parity matrix through the public entry points: fused == unfused
# (ulp-bounded on int8 legs) across the knob matrix, with identical wire
# bytes.
# ---------------------------------------------------------------------------


def _quant_tol(x):
    """A couple of int8 quanta of the payload's absmax — the bound a
    1-ulp scale difference (docs/fused-kernels.md parity contract) can
    reach after the dequant-accumulate."""
    return 4.0 * float(np.abs(x).max()) / 127.0


class TestEntryPointParity:
    @pytest.mark.parametrize("with_ef", [False, True])
    def test_quantized_allreduce(self, with_ef):
        rng = np.random.RandomState(1)
        x = rng.randn(8, 1024).astype(np.float32)
        res = (rng.randn(8, 1024).astype(np.float32) * 1e-3
               if with_ef else None)
        spec = P(hvd.HVD_AXES)

        def leg(fused):
            def fn(xs, rs=None):
                if with_ef:
                    return hvd.quantized_allreduce(xs, rs, op=hvd.Sum,
                                                   fused=fused)
                return hvd.allreduce(xs, op=hvd.Sum, quantized=True,
                                     fused=fused)

            if with_ef:
                return _run(fn, (spec, spec), (P(), spec), x, res)
            return _run(fn, (spec,), P(), x)

        got, ref = leg(True), leg(False)
        tol = _quant_tol(x.sum(axis=0))
        if with_ef:
            assert np.abs(np.asarray(got[0])
                          - np.asarray(ref[0])).max() <= tol
            # residuals bounded by one scale quantum of what was sent
            assert np.abs(np.asarray(got[1])
                          - np.asarray(ref[1])).max() <= tol
        else:
            assert np.abs(np.asarray(got)
                          - np.asarray(ref)).max() <= tol

    def test_zero_wire_rs_then_ag(self):
        # The ZeRO gradient wire halves (stage 2/3's rs + stage 1/2's
        # ag), fused vs unfused, through the flat bucket entry points.
        rng = np.random.RandomState(2)
        flat = rng.randn(N * 512).astype(np.float32)
        xs = np.broadcast_to(flat, (N,) + flat.shape).copy()
        spec = P(hvd.HVD_AXES)

        def split(fused):
            def fn(xrow):
                shard = hvd.reduce_scatter(xrow[0], op=hvd.Sum,
                                           quantized=True, fused=fused)
                return hvd.all_gather(shard, quantized=True,
                                      fused=fused)

            return _run(fn, (spec,), P(), xs)

        got, ref = split(True), split(False)
        assert np.abs(np.asarray(got) - np.asarray(ref)).max() <= \
            _quant_tol(flat * N)

    def test_wire_bytes_identical_fused_vs_unfused(self):
        rng = np.random.RandomState(3)
        x = rng.randn(8, 2048).astype(np.float32)
        spec = P(hvd.HVD_AXES)

        def trace(fused):
            with hvd.record_wire_stats() as ws:
                jax.jit(hvd.shard_map(
                    lambda xs: hvd.allreduce(xs, op=hvd.Sum,
                                             quantized=True, fused=fused),
                    mesh=mesh_2x4(), in_specs=(spec,),
                    out_specs=P())).lower(x)
            return ws

        wf, wu = trace(True), trace(False)
        assert wf.ici_bytes == wu.ici_bytes
        assert wf.dcn_bytes == wu.dcn_bytes
        assert wf.dcn_bytes_fp == wu.dcn_bytes_fp
        assert wf.fused_hbm_saved_bytes > 0
        assert wf.fused_calls >= 3     # quant rs, quant ag, dequant
        assert wu.fused_hbm_saved_bytes == 0 and wu.fused_calls == 0

    @pytest.mark.parametrize("stage", [0, 2, 3])
    def test_optimizer_matrix_fused_tracks_unfused(self, stage):
        """DistributedOptimizer(quantized=True, fused=True) trains in
        lock-step with fused=False across the ZeRO stages: same wire,
        kernel-lowered quant math, params within int8 quanta."""
        def train(fused, steps=3):
            rng = np.random.RandomState(0)
            d = 8
            x = rng.randn(96, d).astype(np.float32)
            y = (x @ rng.randn(d, 1).astype(np.float32))
            params = {"w": jnp.zeros((d, 1)), "b": jnp.zeros((1,))}
            tpl = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
            vg = hvd.value_and_grad(
                lambda p, b: jnp.mean((b[0] @ p["w"] + p["b"]
                                       - b[1]) ** 2), reduce=False)
            tx = hvd.DistributedOptimizer(
                optax.sgd(0.1, momentum=0.9), quantized=True,
                fused=fused,
                zero_stage=stage if stage else None)
            mesh = mesh_2x4()
            if stage == 3:
                pshards = hvd.zero3_shard_params(params)
                pspec = hvd.zero3_param_pspecs(pshards)
                state = tx.init(params)
                sspec = hvd.zero_state_pspecs(state)
                state = jax.device_put(state, jax.tree.map(
                    lambda s: NamedSharding(mesh, s), sspec))
                pshards = jax.device_put(pshards, jax.tree.map(
                    lambda s: NamedSharding(mesh, s), pspec))

                @jax.jit
                def step(psh, s, xb, yb):
                    def spmd(psh, s, xb, yb):
                        pfull = hvd.zero3_gather_params(psh, tpl)
                        loss, g = vg(pfull, (xb, yb))
                        u, ns = tx.update(g, s, psh)
                        return optax.apply_updates(psh, u), ns, \
                            hvd.allreduce(loss)

                    return hvd.shard_map(
                        spmd, mesh=mesh,
                        in_specs=(pspec, sspec, hvd.data_pspec(),
                                  hvd.data_pspec()),
                        out_specs=(pspec, sspec, P()))(psh, s, xb, yb)

                carry = pshards
            else:
                state = tx.init(params)
                if stage:
                    sspec = hvd.zero_state_pspecs(state)
                else:
                    sspec = hvd.QuantizedEFState(
                        inner=jax.tree.map(lambda _: P(), state.inner),
                        residual=jax.tree.map(
                            lambda _: P(hvd.HVD_AXES), state.residual))
                state = jax.device_put(state, jax.tree.map(
                    lambda s: NamedSharding(mesh, s), sspec))

                @jax.jit
                def step(p, s, xb, yb):
                    def spmd(p, s, xb, yb):
                        loss, g = vg(p, (xb, yb))
                        u, ns = tx.update(g, s, p)
                        return optax.apply_updates(p, u), ns, \
                            hvd.allreduce(loss)

                    return hvd.shard_map(
                        spmd, mesh=mesh,
                        in_specs=(P(), sspec, hvd.data_pspec(),
                                  hvd.data_pspec()),
                        out_specs=(P(), sspec, P()))(p, s, xb, yb)

                carry = params
            losses = []
            bs = 16
            for i in range(steps):
                carry, state, loss = step(
                    carry, state, jnp.asarray(x[i * bs:(i + 1) * bs]),
                    jnp.asarray(y[i * bs:(i + 1) * bs]))
                losses.append(float(loss))
            leaves = np.concatenate([np.asarray(l).ravel()
                                     for l in jax.tree.leaves(carry)])
            return leaves, losses

        pf, lf = train(True)
        pu, lu = train(False)
        # Same wire format; the fused kernels' scale division may differ
        # in the last ulp, so the trajectories track within int8 quanta
        # of the (small) updates, and both actually train.
        denom = max(1e-9, float(np.abs(pu).max()))
        assert np.abs(pf - pu).max() / denom <= 5e-2
        assert lu[-1] < lu[0] and lf[-1] < lf[0]

    def test_tp_row_parallel_psum_vs_fused_rs(self):
        # TP row-parallel: y = sum_r x[:, K_r] @ W[K_r, :]. The fused
        # epilogue returns rank-major row shards of the same sum.
        rng = np.random.RandomState(4)
        M, K, Nc = 16, 64, 8
        x = rng.randn(M, K).astype(np.float32)
        Wfull = rng.randn(K, Nc).astype(np.float32)
        xs = np.stack(np.split(x, N, axis=1))          # [n, M, K/n]
        ws = np.stack(np.split(Wfull, N, axis=0))      # [n, K/n, Nc]
        spec = P(hvd.HVD_AXES)
        got = _run(lambda xr, wr: hvd.fused_matmul_reduce_scatter(
            xr[0], wr[0]), (spec, spec), spec, xs, ws)
        ref = _run(lambda xr, wr: lax.psum(xr[0] @ wr[0],
                                           hvd.HVD_AXES)[None],
                   (spec, spec), spec, xs, ws)
        np.testing.assert_allclose(
            np.asarray(got).reshape(M, Nc),
            np.asarray(ref)[0], rtol=1e-4, atol=1e-3)


# ---------------------------------------------------------------------------
# FUSED:* spans + comm.fused.* metrics.
# ---------------------------------------------------------------------------


class TestFusedObservability:
    def test_fused_timeline_spans_balanced(self, tmp_path):
        path = str(tmp_path / "tl.json")
        rng = np.random.RandomState(0)
        x = rng.randn(8, 1024).astype(np.float32)
        spec = P(hvd.HVD_AXES)
        hvd.start_timeline(path)
        try:
            jax.jit(hvd.shard_map(
                lambda xs: hvd.allreduce(xs, op=hvd.Sum, quantized=True,
                                         fused=True),
                mesh=mesh_2x4(), in_specs=(spec,),
                out_specs=P())).lower(x)
        finally:
            hvd.stop_timeline()
        events = json.load(open(path))
        names = {e["name"] for e in events}
        assert any(n.startswith("FUSED:QUANT") for n in names), names
        assert any(n.startswith("FUSED:DEQUANT") for n in names), names
        from horovod_tpu.monitor.span_audit import audit_spans

        # strict=: the whole trace is checked against the event-
        # vocabulary table, not just the FUSED:* family under audit.
        audit = audit_spans(events, prefix="FUSED", require_spans=True,
                            strict=True)
        assert audit.balanced

    def test_comm_fused_metrics_counted(self):
        from horovod_tpu import monitor

        before = dict(monitor.snapshot()["counters"])
        rng = np.random.RandomState(0)
        x = rng.randn(8, 512).astype(np.float32)
        spec = P(hvd.HVD_AXES)
        jax.jit(hvd.shard_map(
            lambda xs: hvd.allreduce(xs, op=hvd.Sum, quantized=True,
                                     fused=True),
            mesh=mesh_2x4(), in_specs=(spec,), out_specs=P())).lower(x)
        after = monitor.snapshot()["counters"]

        def delta(name):
            return after.get(name, 0.0) - before.get(name, 0.0)

        assert delta("comm.fused.calls{kind=QUANT}") >= 1
        assert delta("comm.fused.calls{kind=DEQUANT}") >= 1
        assert delta("comm.fused.hbm_saved_bytes{kind=QUANT}") > 0


# ---------------------------------------------------------------------------
# Golden text: the backend column and the predicted-HBM fused line.
# ---------------------------------------------------------------------------

GOLDEN_FUSED_QUANTIZED_2x4 = """\
wire plan  mesh=2x4  payload=1048576B (itemsize 4)
knobs: quantized=on block=256 zero_stage=0 overlap=off hierarchical=off streams=1 fusion_threshold=67108864 fused=on quantized_pod=off
collective       leg level primitive      wire       ef  backend stream    bytes/dev  model ms  pred ms
allreduce          1 ici   reduce_scatter payload    -   xla          0       786432    0.0079   0.0109
allreduce          2 dcn   reduce_scatter int8/256   yes pallas       0        33280    0.0013   0.0276
allreduce          3 dcn   all_gather     int8/256   yes pallas       0        66560    0.0027   0.0303
allreduce          4 ici   all_gather     payload    -   xla          0      1572864    0.0157   0.0187
totals: ici=2359296 dcn=99840 pod=0 dcn_fp_equiv=393216 dcn_reduction=3.94x
fused: predicted hbm round-trip saved 723968 bytes/dev vs unfused (docs/fused-kernels.md)
predicted: 0.0875 ms step wire = bytes 0.0276 + latency 0.0560 + quant 0.0039 - hidden 0.0000 (modeled 0.0276 ms, 1 bucket) [cost model: static]
encoding: allreduce:ici.reduce_scatter[payload]>dcn.reduce_scatter[int8/256+ef]@pl>dcn.all_gather[int8/256+ef]@pl>ici.all_gather[payload]|s1|sync"""

GOLDEN_QUANTIZED_POD_2x2x2 = """\
wire plan  mesh=2x2x2  payload=1048576B (itemsize 4)
knobs: quantized=off block=256 zero_stage=0 overlap=off hierarchical=on streams=1 fusion_threshold=67108864 fused=on quantized_pod=on
collective       leg level primitive      wire       ef  backend stream    bytes/dev  model ms  pred ms
allreduce          1 ici   reduce_scatter payload    -   xla          0       524288    0.0052   0.0062
allreduce          2 dcn   psum           payload    -   xla          0       524288    0.0210   0.0460
allreduce          3 pod   reduce_scatter int8/256   -   pallas       0        66560    0.0027   0.0303
allreduce          4 pod   all_gather     int8/256   -   pallas       0       133120    0.0053   0.0356
allreduce          5 ici   all_gather     payload    -   xla          0      1048576    0.0105   0.0115
totals: ici=1572864 dcn=524288 pod=199680 dcn_fp_equiv=524288 dcn_reduction=1.00x pod_fp_equiv=786432 pod_reduction=3.94x
fused: predicted hbm round-trip saved 1447936 bytes/dev vs unfused (docs/fused-kernels.md)
predicted: 0.1296 ms step wire = bytes 0.0447 + latency 0.0770 + quant 0.0079 - hidden 0.0000 (modeled 0.0447 ms, 1 bucket) [cost model: static]
encoding: allreduce:ici.reduce_scatter[payload]>dcn.psum[payload]>pod.reduce_scatter[int8/256]@pl>pod.all_gather[int8/256]@pl>ici.all_gather[payload]|s1|sync"""


class TestGoldenTables:
    def test_fused_quantized_table(self):
        sp = describe_plan(quantized=True, mesh_shape=(2, 4), fused=True,
                           fusion_threshold_bytes=64 * 1024 * 1024,
                           quant_block=256)
        assert sp.table(payload_bytes=1 << 20) == \
            GOLDEN_FUSED_QUANTIZED_2x4

    def test_quantized_pod_table(self):
        sp = describe_plan(hierarchical=True, quantized_pod=True,
                           fused=True, mesh_shape=(2, 2, 2),
                           fusion_threshold_bytes=64 * 1024 * 1024,
                           quant_block=256)
        assert sp.table(payload_bytes=1 << 20) == \
            GOLDEN_QUANTIZED_POD_2x2x2

    def test_fused_ring_plans_validate_and_encode(self):
        rs = planner.fused_matmul_rs_plan()
        ag = planner.fused_ag_matmul_plan()
        assert all(l.backend == PALLAS for l in rs.legs + ag.legs)
        assert "@pl" in rs.encode() and "@pl" in ag.encode()


# ---------------------------------------------------------------------------
# Satellite: quantized pod hop (3-level tree plans).
# ---------------------------------------------------------------------------


class TestQuantizedPod:
    @pytest.fixture()
    def mesh_2x2x2(self):
        grid = np.array(jax.devices()[:N]).reshape(2, 2, 2)
        return Mesh(grid, basics.ALL_AXES)

    def test_validation_rejects_int8_psum(self):
        p = WirePlan("allreduce", (
            Leg(ICI, "reduce_scatter"), Leg(POD, "psum", INT8),
            Leg(ICI, "all_gather")))
        with pytest.raises(PlanError, match="not closed under addition"):
            p.validate()

    def test_validation_rejects_pallas_on_flat_and_psum(self):
        with pytest.raises(PlanError, match="flat leg"):
            WirePlan("allreduce",
                     (Leg("flat", "psum", backend=PALLAS),)).validate()
        with pytest.raises(PlanError, match="no kernel body"):
            WirePlan("allreduce", (
                Leg(ICI, "reduce_scatter"),
                Leg(DCN, "psum", backend=PALLAS),
                Leg(ICI, "all_gather"))).validate()
        with pytest.raises(PlanError, match="unknown backend"):
            WirePlan("allreduce",
                     (Leg(ICI, "reduce_scatter", backend="cuda"),
                      Leg(ICI, "all_gather"))).validate()

    def test_planner_knob_builds_pod_rs_ag_pair(self):
        sp = describe_plan(hierarchical=True, quantized_pod=True,
                           mesh_shape=(2, 2, 2))
        assert sp.quantized_pod
        legs = sp.gradient.legs
        assert [(l.level, l.primitive) for l in legs] == [
            (ICI, "reduce_scatter"), (DCN, "psum"),
            (POD, "reduce_scatter"), (POD, "all_gather"),
            (ICI, "all_gather")]
        assert legs[2].wire_dtype == INT8 and legs[3].wire_dtype == INT8
        assert not sp.gradient.is_dcn_quantized  # routes via the tree

    def test_smoke_2x2x2_numerics_and_accounting(self, mesh_2x2x2):
        # Per-rank payload dim 0 divisible by local_size=2 AND the
        # post-ICI shard by pod_size=2 → the quantized pod pair engages.
        rng = np.random.RandomState(0)
        x = rng.randn(8, 64).astype(np.float32)
        spec = P(basics.ALL_AXES)
        sp = describe_plan(hierarchical=True, quantized_pod=True,
                           mesh_shape=(2, 2, 2))

        def fn(xs):
            return hvd.allreduce(xs[0], op=hvd.Sum, plan=sp.gradient)

        out = hvd.shard_map(fn, mesh=mesh_2x2x2, in_specs=(spec,),
                            out_specs=P())(x)
        ref = x.sum(axis=0)
        err = np.abs(np.asarray(out) - ref).max()
        # Quantization error: bounded by quanta of the partial sums the
        # pod hop carries — and NONZERO, proving int8 actually rode the
        # pod links (the exact psum would be ~1e-6).
        bound = 8.0 * np.abs(x).max() / 127.0
        assert 1e-5 < err <= bound, err
        with hvd.record_wire_stats() as ws:
            jax.jit(hvd.shard_map(fn, mesh=mesh_2x2x2, in_specs=(spec,),
                                  out_specs=P())).lower(x)
        assert ws.pod_bytes > 0 and ws.pod_bytes_fp > 0
        assert ws.dcn_bytes > 0 and ws.ici_bytes > 0

    def test_non_divisible_pod_shard_falls_back_exact(self, mesh_2x2x2):
        x = np.random.RandomState(1).randn(8, 7).astype(np.float32)
        spec = P(basics.ALL_AXES)
        sp = describe_plan(hierarchical=True, quantized_pod=True,
                           mesh_shape=(2, 2, 2))
        got = hvd.shard_map(
            lambda xs: hvd.allreduce(xs, op=hvd.Sum, plan=sp.gradient),
            mesh=mesh_2x2x2, in_specs=(spec,), out_specs=P())(x)
        ref = hvd.shard_map(
            lambda xs: lax.psum(xs, basics.ALL_AXES),
            mesh=mesh_2x2x2, in_specs=(spec,), out_specs=P())(x)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_env_knob_route(self, mesh_2x2x2, monkeypatch):
        monkeypatch.setenv("HOROVOD_QUANTIZED_POD", "1")
        monkeypatch.setenv("HOROVOD_FUSED_KERNELS", "1")
        hvd.shutdown()
        hvd.init()
        try:
            sp = describe_plan(hierarchical=True, mesh_shape=(2, 2, 2))
            assert sp.quantized_pod and sp.fused
            assert "pod.reduce_scatter[int8/256]@pl" in \
                sp.gradient.encode()
        finally:
            hvd.shutdown()
            hvd.init(mesh_shape=(2, 4))


# ---------------------------------------------------------------------------
# Satellite: per-level modeled bandwidths (HOROVOD_BENCH_POD_GBPS).
# ---------------------------------------------------------------------------


class TestPodBandwidthModel:
    def test_pod_defaults_to_dcn(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_BENCH_POD_GBPS", raising=False)
        monkeypatch.setenv("HOROVOD_BENCH_DCN_GBPS", "40")
        ici, dcn, pod = bench_gbps()
        assert dcn == 40.0 and pod == 40.0

    def test_pod_knob_overrides(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BENCH_DCN_GBPS", "25")
        monkeypatch.setenv("HOROVOD_BENCH_POD_GBPS", "5")
        ici, dcn, pod = bench_gbps()
        assert pod == 5.0 and dcn == 25.0
        # modeled time: the pod term rides its own bandwidth
        ms = _modeled_wire_ms(0.0, 0.0, 5e9)
        assert ms == pytest.approx(1000.0)
        assert _modeled_wire_ms(0.0, 25e9, 0.0) == pytest.approx(1000.0)

    def test_wire_stats_pod_class_separate(self, monkeypatch):
        # flat psum over a 2x2x2 mesh charges the cross-pod hop to the
        # pod class, not dcn (the uniform-DCN assumption is gone).
        grid = np.array(jax.devices()[:N]).reshape(2, 2, 2)
        mesh = Mesh(grid, basics.ALL_AXES)
        x = np.random.RandomState(0).randn(8, 64).astype(np.float32)
        spec = P(basics.ALL_AXES)
        with hvd.record_wire_stats() as ws:
            jax.jit(hvd.shard_map(
                lambda xs: hvd.allreduce(xs, op=hvd.Sum),
                mesh=mesh, in_specs=(spec,), out_specs=P())).lower(x)
        assert ws.pod_bytes > 0
        assert ws.pod_bytes < ws.dcn_bytes < ws.ici_bytes


# ---------------------------------------------------------------------------
# Satellite: autotune `fused` dimension (schema v6).
# ---------------------------------------------------------------------------


class TestAutotuneFused:
    def test_encode_decode_with_fused(self):
        from horovod_tpu.autotune import TunedParams

        p = TunedParams(quant_block=128, fused=True)
        enc = encode_tuned(p, quantized=True)
        assert enc.endswith("|pl")
        d = decode_tuned(enc)
        assert d["fused"] is True and d["quant_block"] == 128
        # v5 strings (no |pl) stay decodable: fused defaults False.
        d5 = decode_tuned("ar.tree|int8/256|s2|ovl")
        assert d5["fused"] is False

    def test_fused_dead_without_quantized_wire(self):
        from horovod_tpu.autotune import TunedParams

        a = encode_tuned(TunedParams(fused=True), quantized=False)
        b = encode_tuned(TunedParams(fused=False), quantized=False)
        assert a == b  # no int8 leg to kernel-back: same wire, one trial

    def test_manager_searches_and_dedups_fused(self):
        from horovod_tpu.autotune import ParameterManager, TunedParams

        pm = ParameterManager(TunedParams(), tune_quant_block=True,
                              tune_fused=True, warmup_samples=0,
                              max_samples=12, seed=7)
        while not pm.done:
            pm.record_sample(1.0 + 0.1 * pm.samples_done)
        tried = [p for p, _ in pm.history]
        assert any(p.fused for p in tried), "fused never proposed"
        assert any(not p.fused for p in tried)
        # dedup key: same plan encoding → one trial
        keys = [pm._unit_key(p) for p in tried]
        assert len(keys) == len(set(keys))

    def test_gate_off_never_proposes_fused(self):
        from horovod_tpu.autotune import ParameterManager, TunedParams

        pm = ParameterManager(TunedParams(), tune_quant_block=True,
                              warmup_samples=0, max_samples=6, seed=9)
        while not pm.done:
            pm.record_sample(1.0)
        assert all(not p.fused for p, _ in pm.history)

    def test_csv_fused_column_round_trips(self, tmp_path):
        from horovod_tpu.autotune import (ParameterManager, TunedParams,
                                          read_log)
        from horovod_tpu.autotune import parameter_manager as pm_mod

        path = str(tmp_path / "v6.csv")
        pm = ParameterManager(TunedParams(), tune_quant_block=True,
                              tune_fused=True, warmup_samples=0,
                              max_samples=5, log_path=path, seed=3)
        while not pm.done:
            pm.record_sample(2.0)
        with open(path) as f:
            header = f.readline().strip().split(",")
        assert header == list(pm_mod.CSV_FIELDS)
        assert "fused" in header
        rows = read_log(path)
        for row, (p, _) in zip(rows, pm.history):
            assert row["fused"] == p.fused
            assert row["plan"] == encode_tuned(p, quantized=True)

    def test_read_log_tolerant_of_v5_csv_without_fused(self, tmp_path):
        from horovod_tpu.autotune import read_log

        path = tmp_path / "v5.csv"
        path.write_text(
            "sample,fusion_threshold_bytes,quant_block,"
            "hierarchical_allreduce,zero_sharding,zero_stage,overlap,"
            "num_comm_streams,score_steps_per_sec,plan\n"
            "1,67108864,256,0,0,0,1,2,10.5,ar.flat|fp|s2|ovl\n")
        rows = read_log(str(path))
        assert rows[0]["fused"] is False
        assert rows[0]["plan"] == "ar.flat|fp|s2|ovl"

    def test_tuned_params_fused_threads_to_describe_plan(self):
        from horovod_tpu.autotune import TunedParams

        sp = describe_plan(quantized=True, mesh_shape=(2, 4),
                           tuned_params=TunedParams(fused=True))
        assert sp.fused
        assert any(l.backend == PALLAS for l in sp.gradient.legs)
