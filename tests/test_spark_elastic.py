"""Spark elastic + estimator data-path tests.

pyspark is not installable here, so (mirroring the reference's strategy of
mocked ssh + localhost processes, SURVEY §4):

- ``run_elastic_core`` is driven with real *subprocess* tasks running the
  actual ``task_loop`` (what a Spark task executes), including a worker
  hard-crash → host blacklist → survivors finish (reference:
  test_elastic_spark_*.py).
- ``_materialize_shards`` is driven with a fake DataFrame implementing the
  exact select/repartition/rdd.mapPartitionsWithIndex surface, proving the
  dataset is partition-materialized through the Store and never collected
  on the driver (reference: spark/common/util.py prepare_data).
"""

import os
import pickle
import subprocess
import sys
import time

import cloudpickle
import pytest

import elastic_fn
from horovod_tpu.elastic import constants
from horovod_tpu.spark.elastic import run_elastic_core, task_loop  # noqa: F401
from horovod_tpu.spark.estimator import _load_shard, _materialize_shards
from horovod_tpu.spark.store import LocalStore

cloudpickle.register_pickle_by_value(elastic_fn)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TASK_CHILD = """
import sys, pickle
d = pickle.load(sys.stdin.buffer)
from horovod_tpu.spark.elastic import task_loop
n = task_loop(d["addr"], d["port"], d["key"], d["fn"], hostname=d["host"])
print(f"task on {d['host']} executed {n} workers", flush=True)
"""


def _subprocess_task_launcher(hostnames):
    """launch_tasks factory: one subprocess per (fake) host slot, running
    the real task_loop — standing in for the Spark stage."""

    procs = []

    def launch(fn_blob, addr, port, key):
        for host in hostnames:
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env["PYTHONPATH"] = REPO
            env["HOROVOD_START_TIMEOUT"] = "30"
            p = subprocess.Popen(
                [sys.executable, "-c", _TASK_CHILD],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, env=env)
            p.stdin.write(pickle.dumps({
                "addr": addr, "port": port, "key": key, "fn": fn_blob,
                "host": host}))
            p.stdin.close()
            procs.append(p)

        class _Handle:
            def join(self):
                deadline = time.monotonic() + 60
                for p in procs:
                    try:
                        p.wait(max(1.0, deadline - time.monotonic()))
                    except subprocess.TimeoutExpired:
                        p.kill()

        return _Handle()

    launch.procs = procs
    return launch


@pytest.fixture(autouse=True)
def _fast_discovery(monkeypatch):
    monkeypatch.setattr(constants, "DISCOVER_HOSTS_FREQUENCY_SECS", 0.25)


class TestRunElasticCore:
    def test_completes_and_returns_results(self, tmp_path):
        log_file = str(tmp_path / "log.jsonl")
        launch = _subprocess_task_launcher(["hostA", "hostA"])
        results = run_elastic_core(
            launch, elastic_fn.make_worker_fn(log_file, batches=4,
                                              batch_sleep=0.05),
            num_proc=2, controller_addr_override="127.0.0.1",
            driver_addr="127.0.0.1")
        assert results == [4, 4]
        done = [r for r in elastic_fn.read_log(log_file) if r.get("done")]
        assert len(done) == 2
        assert all(r["size"] == 2 for r in done)

    def test_survives_worker_crash(self, tmp_path):
        """3 slots on 2 fake hosts; hostB's worker hard-crashes at batch 3:
        hostB is blacklisted and the survivors finish in a world of 2
        (reference: test_elastic_spark fault cases)."""
        log_file = str(tmp_path / "log.jsonl")
        launch = _subprocess_task_launcher(["hostA", "hostA", "hostB"])
        results = run_elastic_core(
            launch, elastic_fn.make_worker_fn(log_file, batches=6,
                                              exit_at="hostB:0:3"),
            num_proc=3, min_np=2, max_np=3,
            controller_addr_override="127.0.0.1",
            driver_addr="127.0.0.1")
        records = elastic_fn.read_log(log_file)
        assert results == [6, 6], records
        done = [r for r in records if r.get("done")]
        assert len(done) == 2, records
        assert all(r["size"] == 2 for r in done), done
        b_records = [r for r in records
                     if r["identity"] == "hostB:0" and "batch" in r]
        assert all(r["batch"] < 3 for r in b_records), b_records


# ------------------------------------------------------- estimator data path


class _FakeRDD:
    def __init__(self, rows, n_parts):
        self.rows = rows
        self.n_parts = n_parts

    def mapPartitionsWithIndex(self, f):
        per = (len(self.rows) + self.n_parts - 1) // self.n_parts
        out = []
        for i in range(self.n_parts):
            part = self.rows[i * per:(i + 1) * per]
            out.extend(f(i, iter(part)))
        return _FakeCollected(out)


class _FakeCollected:
    def __init__(self, items):
        self.items = items

    def collect(self):
        return list(self.items)


class _FakeDF:
    """The exact DataFrame surface _materialize_shards touches."""

    def __init__(self, rows, n_parts=1):
        self.rows = rows
        self.n_parts = n_parts
        self.collected = False

    def select(self, *cols):
        return self

    def repartition(self, n):
        return _FakeDF(self.rows, n)

    @property
    def rdd(self):
        return _FakeRDD(self.rows, self.n_parts)

    def collect(self):  # the path that must NOT be taken
        self.collected = True
        return self.rows


class TestMaterializeShards:
    def test_partition_materialization_roundtrip(self, tmp_path):
        rows = [{"x1": float(i), "x2": float(2 * i), "y": float(i % 3)}
                for i in range(103)]
        df = _FakeDF(rows)
        store = LocalStore(str(tmp_path / "store"))
        data_dir, counts = _materialize_shards(
            df, ["x1", "x2"], ["y"], 4, store, "run_7")
        assert not df.collected, "driver-side collect is forbidden"
        assert sum(counts) == 103
        assert all(c > 0 for c in counts)
        total = 0
        for rank in range(4):
            x, y = _load_shard(store, data_dir, rank)
            assert x.shape[1] == 2 and y.shape[1] == 1
            assert x.shape[0] == counts[rank]
            total += x.shape[0]
            # content check: x2 == 2*x1, via the original rows
            import numpy as np

            np.testing.assert_allclose(x[:, 1], 2 * x[:, 0])
        assert total == 103

    def test_empty_partition_allowed(self, tmp_path):
        rows = [{"x": 1.0, "y": 0.0}, {"x": 2.0, "y": 1.0}]
        store = LocalStore(str(tmp_path / "store"))
        data_dir, counts = _materialize_shards(
            _FakeDF(rows), ["x"], ["y"], 4, store, "run_1")
        assert sum(counts) == 2
        for rank, c in enumerate(counts):
            x, y = _load_shard(store, data_dir, rank)
            assert x.shape == (c, 1)
