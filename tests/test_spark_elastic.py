"""Spark elastic + estimator data-path tests.

pyspark is not installable here, so (mirroring the reference's strategy of
mocked ssh + localhost processes, SURVEY §4):

- ``run_elastic_core`` is driven with real *subprocess* tasks running the
  actual ``task_loop`` (what a Spark task executes), including a worker
  hard-crash → host blacklist → survivors finish (reference:
  test_elastic_spark_*.py).
- ``_materialize_shards`` is driven with a fake DataFrame implementing the
  exact select/repartition/rdd.mapPartitionsWithIndex surface, proving the
  dataset is partition-materialized through the Store and never collected
  on the driver (reference: spark/common/util.py prepare_data).
"""

import os
import pickle
import subprocess
import sys
import time

import cloudpickle
import pytest

import elastic_fn
from horovod_tpu.elastic import constants
from horovod_tpu.spark.elastic import run_elastic_core, task_loop  # noqa: F401
from horovod_tpu.spark.estimator import (
    ShardReader,
    _load_shard,
    _materialize_shards,
)
from horovod_tpu.spark.store import DBFSLocalStore, LocalStore, Store

cloudpickle.register_pickle_by_value(elastic_fn)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TASK_CHILD = """
import sys, pickle
d = pickle.load(sys.stdin.buffer)
from horovod_tpu.spark.elastic import task_loop
n = task_loop(d["addr"], d["port"], d["key"], d["fn"], hostname=d["host"])
print(f"task on {d['host']} executed {n} workers", flush=True)
"""


def _subprocess_task_launcher(hostnames):
    """launch_tasks factory: one subprocess per (fake) host slot, running
    the real task_loop — standing in for the Spark stage."""

    procs = []

    def launch(fn_blob, addr, port, key):
        for host in hostnames:
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env["PYTHONPATH"] = REPO
            env["HOROVOD_START_TIMEOUT"] = "30"
            p = subprocess.Popen(
                [sys.executable, "-c", _TASK_CHILD],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, env=env)
            p.stdin.write(pickle.dumps({
                "addr": addr, "port": port, "key": key, "fn": fn_blob,
                "host": host}))
            p.stdin.close()
            procs.append(p)

        class _Handle:
            def join(self):
                deadline = time.monotonic() + 60
                for p in procs:
                    try:
                        p.wait(max(1.0, deadline - time.monotonic()))
                    except subprocess.TimeoutExpired:
                        p.kill()

        return _Handle()

    launch.procs = procs
    return launch


@pytest.fixture(autouse=True)
def _fast_discovery(monkeypatch):
    monkeypatch.setattr(constants, "DISCOVER_HOSTS_FREQUENCY_SECS", 0.25)


class TestRunElasticCore:
    def test_completes_and_returns_results(self, tmp_path):
        log_file = str(tmp_path / "log.jsonl")
        launch = _subprocess_task_launcher(["hostA", "hostA"])
        results = run_elastic_core(
            launch, elastic_fn.make_worker_fn(log_file, batches=4,
                                              batch_sleep=0.05),
            num_proc=2, controller_addr_override="127.0.0.1",
            driver_addr="127.0.0.1")
        assert results == [4, 4]
        done = [r for r in elastic_fn.read_log(log_file) if r.get("done")]
        assert len(done) == 2
        assert all(r["size"] == 2 for r in done)

    def test_survives_worker_crash(self, tmp_path):
        """3 slots on 2 fake hosts; hostB's worker hard-crashes at batch 3:
        hostB is blacklisted and the survivors finish in a world of 2
        (reference: test_elastic_spark fault cases)."""
        log_file = str(tmp_path / "log.jsonl")
        launch = _subprocess_task_launcher(["hostA", "hostA", "hostB"])
        results = run_elastic_core(
            launch, elastic_fn.make_worker_fn(log_file, batches=6,
                                              exit_at="hostB:0:3"),
            num_proc=3, min_np=2, max_np=3,
            controller_addr_override="127.0.0.1",
            driver_addr="127.0.0.1")
        records = elastic_fn.read_log(log_file)
        assert results == [6, 6], records
        done = [r for r in records if r.get("done")]
        assert len(done) == 2, records
        assert all(r["size"] == 2 for r in done), done
        b_records = [r for r in records
                     if r["identity"] == "hostB:0" and "batch" in r]
        assert all(r["batch"] < 3 for r in b_records), b_records


# ------------------------------------------------------- estimator data path


class _FakeRDD:
    def __init__(self, rows, n_parts):
        self.rows = rows
        self.n_parts = n_parts

    def mapPartitionsWithIndex(self, f):
        per = (len(self.rows) + self.n_parts - 1) // self.n_parts
        out = []
        for i in range(self.n_parts):
            part = self.rows[i * per:(i + 1) * per]
            out.extend(f(i, iter(part)))
        return _FakeCollected(out)


class _FakeCollected:
    def __init__(self, items):
        self.items = items

    def collect(self):
        return list(self.items)


class _FakeDF:
    """The exact DataFrame surface _materialize_shards touches."""

    def __init__(self, rows, n_parts=1):
        self.rows = rows
        self.n_parts = n_parts
        self.collected = False

    def select(self, *cols):
        return self

    def repartition(self, n):
        return _FakeDF(self.rows, n)

    @property
    def rdd(self):
        return _FakeRDD(self.rows, self.n_parts)

    def collect(self):  # the path that must NOT be taken
        self.collected = True
        return self.rows


class TestMaterializeShards:
    def test_partition_materialization_roundtrip(self, tmp_path):
        rows = [{"x1": float(i), "x2": float(2 * i), "y": float(i % 3)}
                for i in range(103)]
        df = _FakeDF(rows)
        store = LocalStore(str(tmp_path / "store"))
        data_dir, counts = _materialize_shards(
            df, ["x1", "x2"], ["y"], 4, store, "run_7")
        assert not df.collected, "driver-side collect is forbidden"
        assert sum(counts) == 103
        assert all(c > 0 for c in counts)
        total = 0
        for rank in range(4):
            x, y = _load_shard(store, data_dir, rank)
            assert x.shape[1] == 2 and y.shape[1] == 1
            assert x.shape[0] == counts[rank]
            total += x.shape[0]
            # content check: x2 == 2*x1, via the original rows
            import numpy as np

            np.testing.assert_allclose(x[:, 1], 2 * x[:, 0])
        assert total == 103

    def test_empty_partition_allowed(self, tmp_path):
        rows = [{"x": 1.0, "y": 0.0}, {"x": 2.0, "y": 1.0}]
        store = LocalStore(str(tmp_path / "store"))
        data_dir, counts = _materialize_shards(
            _FakeDF(rows), ["x"], ["y"], 4, store, "run_1")
        assert sum(counts) == 2
        for rank, c in enumerate(counts):
            x, y = _load_shard(store, data_dir, rank)
            assert x.shape == (c, 1)

    def test_streaming_reader_bounds_memory(self, tmp_path):
        """A shard far bigger than the chunk cap trains while at most one
        chunk is ever resident (round-2 missing #5: whole-shard loads
        capped dataset size at worker RAM)."""
        import numpy as np

        rows = [{"x": float(i), "y": float(i % 2)} for i in range(103)]
        store = LocalStore(str(tmp_path / "store"))
        data_dir, counts = _materialize_shards(
            _FakeDF(rows), ["x"], ["y"], 1, store, "run_c", chunk_rows=8)
        assert counts == [103]
        reader = ShardReader(store, data_dir, 0)
        assert reader.rows == 103
        assert len(reader.chunk_sizes) == 13  # ceil(103/8)
        # one "epoch" of batches: order preserved, all rows seen once
        seen = np.concatenate([xb[:, 0] for xb, _ in
                               reader.iter_batches(batch_size=5)])
        np.testing.assert_allclose(seen, np.arange(103, dtype="float32"))
        assert reader.max_resident_rows <= 8, reader.max_resident_rows
        assert reader.steps_per_epoch(5) == sum(
            (s + 4) // 5 for s in reader.chunk_sizes)

    def test_torch_estimator_streams_under_memory_cap(self, tmp_path,
                                                      monkeypatch):
        """End-to-end: TorchEstimator.fit trains from a shard larger than
        the configured chunk cap; the reader high-water mark stays at the
        cap (the 'train from a shard larger than a configured memory cap'
        done-criterion)."""
        import numpy as np
        import torch

        from horovod_tpu.spark import estimator as est_mod

        monkeypatch.setenv("HOROVOD_SPARK_CHUNK_ROWS", "16")
        residents = []
        orig_iter = est_mod.ShardReader.iter_batches

        def tracking_iter(self, batch_size):
            yield from orig_iter(self, batch_size)
            residents.append(self.max_resident_rows)

        monkeypatch.setattr(est_mod.ShardReader, "iter_batches",
                            tracking_iter)
        # pyspark is not installable here: stand in for the barrier-stage
        # job with an in-process world-1 run (the reader path under test
        # is identical; the barrier machinery has its own tests above)
        import horovod_tpu.spark as hvd_spark

        monkeypatch.setattr(hvd_spark, "run",
                            lambda fn, num_proc=None, **kw: [fn()])
        rng = np.random.RandomState(0)
        rows = [{"x1": float(v), "y": float(2 * v + 1)}
                for v in rng.randn(120)]
        store = LocalStore(str(tmp_path / "store"))
        est = est_mod.TorchEstimator(
            model=torch.nn.Linear(1, 1), store=store,
            feature_cols=["x1"], label_cols=["y"],
            batch_size=8, epochs=2, num_proc=1)
        est.fit(_FakeDF(rows))
        assert residents and max(residents) <= 16, residents


class TestValidationSplit:
    """validation=<fraction|column> (reference keras/estimator.py:128-142):
    executor-side split into sibling val chunks, streamed per epoch, with
    per-epoch validation metrics averaged across ranks."""

    def test_materialize_fraction_split(self, tmp_path):
        import numpy as np

        rows = [{"x": float(i), "y": 0.0} for i in range(40)]
        store = LocalStore(str(tmp_path / "store"))
        data_dir, counts = _materialize_shards(
            _FakeDF(rows), ["x"], ["y"], 2, store, "run_v",
            chunk_rows=8, validation=0.25)
        train_x, val_x = [], []
        for rank in range(2):
            tr = ShardReader(store, data_dir, rank)
            va = ShardReader(store, data_dir, rank, split="val")
            for x, _ in tr.iter_chunks():
                train_x.extend(x[:, 0].tolist())
            for x, _ in va.iter_chunks():
                val_x.extend(x[:, 0].tolist())
            # every 4th row of each partition is validation
            assert va.rows == 5 and tr.rows == 15
        assert not set(train_x) & set(val_x)  # disjoint
        assert sorted(train_x + val_x) == [float(i) for i in range(40)]
        assert counts == [15, 15]  # counts report TRAIN rows

    def test_materialize_column_split(self, tmp_path):
        rows = [{"x": float(i), "y": 0.0, "is_val": float(i >= 30)}
                for i in range(40)]
        store = LocalStore(str(tmp_path / "store"))
        data_dir, _ = _materialize_shards(
            _FakeDF(rows), ["x"], ["y"], 2, store, "run_vc",
            validation="is_val")
        total_val = sum(
            ShardReader(store, data_dir, r, split="val").rows
            for r in range(2))
        total_train = sum(
            ShardReader(store, data_dir, r).rows for r in range(2))
        assert total_val == 10 and total_train == 30

    def test_no_validation_writes_no_val_files(self, tmp_path):
        rows = [{"x": 1.0, "y": 0.0}] * 4
        store = LocalStore(str(tmp_path / "store"))
        data_dir, _ = _materialize_shards(
            _FakeDF(rows), ["x"], ["y"], 1, store, "run_nv")
        va = ShardReader(store, data_dir, 0, split="val")
        assert va.rows == 0 and va.chunk_sizes == []

    def test_fraction_bounds_validated(self):
        import torch

        from horovod_tpu.spark import estimator as est_mod

        with pytest.raises(ValueError, match="validation fraction"):
            est_mod.TorchEstimator(
                model=torch.nn.Linear(1, 1), feature_cols=["x"],
                label_cols=["y"], validation=1.5)

    def test_torch_estimator_validation_history(self, tmp_path,
                                                monkeypatch):
        import numpy as np
        import torch

        import horovod_tpu.spark as hvd_spark
        from horovod_tpu.spark import estimator as est_mod

        monkeypatch.setattr(hvd_spark, "run",
                            lambda fn, num_proc=None, **kw: [fn()])
        rng = np.random.RandomState(1)
        rows = [{"x1": float(v), "y": float(3 * v)} for v in rng.randn(64)]
        store = LocalStore(str(tmp_path / "store"))
        est = est_mod.TorchEstimator(
            model=torch.nn.Linear(1, 1), store=store,
            feature_cols=["x1"], label_cols=["y"],
            batch_size=8, epochs=3, num_proc=1, validation=0.25)
        est.fit(_FakeDF(rows))
        assert sorted(est.history_) == ["loss", "val_loss"]
        assert len(est.history_["val_loss"]) == 3
        assert all(np.isfinite(v) for v in est.history_["val_loss"])
        # training reduces the train loss on this linear fit
        assert est.history_["loss"][-1] < est.history_["loss"][0]

    def test_keras_estimator_validation_history(self, tmp_path,
                                                monkeypatch):
        keras = pytest.importorskip("keras")
        import numpy as np

        import horovod_tpu.spark as hvd_spark
        from horovod_tpu.spark import estimator as est_mod

        monkeypatch.setattr(hvd_spark, "run",
                            lambda fn, num_proc=None, **kw: [fn()])
        rng = np.random.RandomState(2)
        rows = [{"x1": float(v), "y": float(2 * v)} for v in rng.randn(48)]
        store = LocalStore(str(tmp_path / "store"))
        model = keras.Sequential([keras.layers.Input(shape=(1,)),
                                  keras.layers.Dense(1)])
        est = est_mod.KerasEstimator(
            model=model, store=store, feature_cols=["x1"],
            label_cols=["y"], batch_size=8, epochs=2, num_proc=1,
            validation=0.25)
        est.fit(_FakeDF(rows))
        assert "val_loss" in est.history_
        assert len(est.history_["val_loss"]) == 2

    def test_validation_steps_per_epoch_caps_batches(self, tmp_path,
                                                     monkeypatch):
        """validation_steps_per_epoch (reference keras/estimator.py:142)
        bounds the per-epoch validation work."""
        import numpy as np
        import torch

        import horovod_tpu.spark as hvd_spark
        from horovod_tpu.spark import estimator as est_mod

        monkeypatch.setattr(hvd_spark, "run",
                            lambda fn, num_proc=None, **kw: [fn()])
        seen = []
        orig = est_mod.ShardReader.iter_batches

        def counting(self, batch_size):
            for b in orig(self, batch_size):
                if self._prefix == "val_":
                    seen.append(len(b[0]))
                yield b

        monkeypatch.setattr(est_mod.ShardReader, "iter_batches", counting)
        rng = np.random.RandomState(3)
        rows = [{"x1": float(v), "y": float(v)} for v in rng.randn(64)]
        store = LocalStore(str(tmp_path / "store"))
        est = est_mod.TorchEstimator(
            model=torch.nn.Linear(1, 1), store=store,
            feature_cols=["x1"], label_cols=["y"], batch_size=4,
            epochs=2, num_proc=1, validation=0.5,
            validation_steps_per_epoch=3)
        est.fit(_FakeDF(rows))
        # islice stops the generator after 3 val batches per epoch.
        assert len(seen) == 2 * 3, seen

    def test_empty_validation_shard_fails_loudly(self, tmp_path,
                                                 monkeypatch):
        import numpy as np
        import torch

        import horovod_tpu.spark as hvd_spark
        from horovod_tpu.spark import estimator as est_mod

        monkeypatch.setattr(hvd_spark, "run",
                            lambda fn, num_proc=None, **kw: [fn()])
        # Column split where NO row is marked validation -> empty val
        # shard must raise, not hang the metric collective.
        rows = [{"x1": 1.0, "y": 1.0, "v": 0.0}] * 8
        store = LocalStore(str(tmp_path / "store"))
        est = est_mod.TorchEstimator(
            model=torch.nn.Linear(1, 1), store=store,
            feature_cols=["x1"], label_cols=["y"],
            batch_size=4, epochs=1, num_proc=1, validation="v")
        with pytest.raises(ValueError, match="VALIDATION"):
            est.fit(_FakeDF(rows))


class TestDistributedTransform:
    class _MapInPandasDF:
        """Spark-DataFrame double pinning the mapInPandas surface the
        transformer uses; toPandas is the path that must NOT be taken."""

        def __init__(self, rows, n_parts=3):
            import pandas as pd

            self._parts = []
            per = (len(rows) + n_parts - 1) // n_parts
            for i in range(0, len(rows), per):
                self._parts.append(pd.DataFrame(rows[i:i + per]))
            self.schema = ("x1", "y")
            self.topandas_called = False

        def mapInPandas(self, fn, schema):
            import pandas as pd

            assert schema is self.schema  # pyspark-free fallback path
            return pd.concat(list(fn(iter(self._parts))),
                             ignore_index=True)

        def toPandas(self):
            self.topandas_called = True
            raise AssertionError("transform must not collect to the driver")

    def test_transform_uses_map_in_pandas(self):
        import numpy as np
        import torch

        from horovod_tpu.spark.estimator import _ModelTransformer

        model = torch.nn.Linear(1, 1)
        with torch.no_grad():
            model.weight.fill_(2.0)
            model.bias.fill_(1.0)
        t = _ModelTransformer(
            model, ["x1"], ["y"],
            lambda m, f: m(torch.from_numpy(f)).detach().numpy())
        rows = [{"x1": float(i), "y": 0.0} for i in range(10)]
        df = self._MapInPandasDF(rows)
        out = t.transform(df)
        assert not df.topandas_called
        assert len(out) == 10
        preds = np.concatenate(out["prediction"].tolist())
        np.testing.assert_allclose(preds, 2.0 * np.arange(10) + 1.0,
                                   rtol=1e-6)

    def test_transform_plain_rows_fallback(self):
        from horovod_tpu.spark.estimator import _ModelTransformer

        t = _ModelTransformer(None, ["x1"], ["y"],
                              lambda m, f: f * 3.0)
        out = t.transform([{"x1": 2.0, "y": 0.0}])
        assert float(out["prediction"][0][0]) == 6.0


class TestStores:
    def test_dbfs_normalization_and_dispatch(self, tmp_path,
                                             monkeypatch):
        assert DBFSLocalStore.normalize_path("dbfs:/a/b") == "/dbfs/a/b"
        assert DBFSLocalStore.normalize_path("dbfs:///a") == "/dbfs/a"
        assert DBFSLocalStore.normalize_path(
            "file:///dbfs/a") == "/dbfs/a"
        # create() dispatch (redirect /dbfs to tmp so no real mount needed)
        monkeypatch.setattr(DBFSLocalStore, "normalize_path",
                            staticmethod(lambda p: str(tmp_path / "dbfs")))
        store = Store.create("dbfs:/ml/horovod")
        assert isinstance(store, DBFSLocalStore)
        assert store.get_run_path("r1").endswith("runs/r1")

    def test_local_store_sync_fn(self, tmp_path):
        store = LocalStore(str(tmp_path / "store"))
        local = tmp_path / "local_run"
        (local / "logs").mkdir(parents=True)
        (local / "logs" / "events.txt").write_text("hello")
        (local / "model.bin").write_bytes(b"\x00\x01")
        # estimators ship worker fns with cloudpickle; sync_fn rides along
        fn = cloudpickle.loads(cloudpickle.dumps(store.sync_fn("run_9")))
        fn(str(local))
        run = store.get_run_path("run_9")
        assert open(os.path.join(run, "logs", "events.txt")).read() == \
            "hello"
        assert open(os.path.join(run, "model.bin"), "rb").read() == \
            b"\x00\x01"
