"""Adasum numerics against a NumPy reference implementation.

Models the reference's test_adasum_pytorch.py / test_adasum_tensorflow.py,
which validate the VHDD tree combine against a straight NumPy port of the
math (adasum.h:101-141)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import adasum

N = 8


def np_adasum_pair(a, b):
    dot = np.vdot(a, b)
    na = np.vdot(a, a)
    nb = np.vdot(b, b)
    ac = 1.0 - dot / (2 * na) if na > 0 else 1.0
    bc = 1.0 - dot / (2 * nb) if nb > 0 else 1.0
    return ac * a + bc * b


def np_adasum_tree(stack):
    vecs = [stack[i].astype(np.float64) for i in range(stack.shape[0])]
    while len(vecs) > 1:
        tail = [vecs[-1]] if len(vecs) % 2 == 1 else []
        body = vecs[: len(vecs) - len(tail)]
        vecs = [np_adasum_pair(body[i], body[i + 1])
                for i in range(0, len(body), 2)] + tail
    return vecs[0]


def test_pair_combine_matches_numpy():
    rng = np.random.RandomState(0)
    a = rng.randn(16).astype(np.float32)
    b = rng.randn(16).astype(np.float32)
    out = adasum.adasum_combine(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), np_adasum_pair(a, b),
                               rtol=1e-5)


def test_pair_combine_orthogonal_sums():
    # Orthogonal vectors: dot = 0 → plain sum (docs/adasum_user_guide.rst).
    a = jnp.asarray([1.0, 0.0])
    b = jnp.asarray([0.0, 1.0])
    np.testing.assert_allclose(np.asarray(adasum.adasum_combine(a, b)),
                               [1.0, 1.0])


def test_pair_combine_parallel_averages():
    # Identical vectors: dot = |a|² → coefficients ½ → average.
    a = jnp.asarray([2.0, 4.0])
    np.testing.assert_allclose(np.asarray(adasum.adasum_combine(a, a)),
                               [2.0, 4.0])


def test_pair_combine_zero_operand():
    a = jnp.zeros(4)
    b = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    np.testing.assert_allclose(np.asarray(adasum.adasum_combine(a, b)),
                               np.asarray(b))


def test_adasum_allreduce_matches_numpy_tree():
    rng = np.random.RandomState(7)
    x = rng.randn(N, 32).astype(np.float32)

    out = hvd.shard_map(
        lambda v: hvd.allreduce(v[0], op=hvd.Adasum),
        mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
        out_specs=P())(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np_adasum_tree(x), rtol=1e-4)


def test_vhdd_matches_numpy_tree():
    """The distributed VHDD path (ppermute halving + psum reassembly) must
    agree with the gathered tree combine and the NumPy reference
    (reference: FusedAllreduce, adasum.h:196+)."""
    rng = np.random.RandomState(3)
    for n_elem in (32, 37):  # even and odd (pad + uneven halving) lengths
        x = rng.randn(N, n_elem).astype(np.float32)
        out = hvd.shard_map(
            lambda v: adasum._vhdd_allreduce(v[0], hvd.HVD_AXES),
            mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
            out_specs=P())(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), np_adasum_tree(x),
                                   rtol=1e-4, atol=1e-5)


def test_vhdd_threshold_dispatch(monkeypatch):
    """Above GATHER_THRESHOLD_ELEMS the public adasum_allreduce must route
    to VHDD and still produce tree numerics."""
    monkeypatch.setattr(adasum, "GATHER_THRESHOLD_ELEMS", 1)
    rng = np.random.RandomState(5)
    x = rng.randn(N, 48).astype(np.float32)
    out = hvd.shard_map(
        lambda v: hvd.allreduce(v[0], op=hvd.Adasum),
        mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
        out_specs=P())(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np_adasum_tree(x),
                               rtol=1e-4, atol=1e-5)


def test_vhdd_2d_shape_roundtrip(monkeypatch):
    monkeypatch.setattr(adasum, "GATHER_THRESHOLD_ELEMS", 1)
    rng = np.random.RandomState(9)
    x = rng.randn(N, 5, 7).astype(np.float32)
    out = hvd.shard_map(
        lambda v: hvd.allreduce(v[0], op=hvd.Adasum),
        mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
        out_specs=P())(jnp.asarray(x))
    assert out.shape == (5, 7)
    np.testing.assert_allclose(
        np.asarray(out).ravel(),
        np_adasum_tree(x.reshape(N, 35)), rtol=1e-4, atol=1e-5)


def test_adasum_eager_single_process_identity():
    x = jnp.arange(4.0)
    np.testing.assert_array_equal(
        np.asarray(hvd.allreduce(x, op=hvd.Adasum)), np.asarray(x))
