"""4-D composed mesh: PP x EP x DP/ZeRO (docs/parallelism.md).

The ``(hvd_pp, hvd_ep, hvd_cross, hvd_local)`` mesh replaces the PR-14
EP x PP loud-fail. The contracts under test:

* geometry: pp leads, ep nests inside a stage, the data mesh is the
  trailing (cross, local) pair; the fingerprint carries the combined
  ``ppS.epE`` marker;
* expert a2a dispatch stays STAGE-LOCAL (the ep axis never crosses
  stage boundaries);
* gradient reductions: router/dense leaves pmean over hvd_ep and
  average over the data axes, expert leaves scale by 1/ep and average
  over the data axes, and NEITHER ever reduces over hvd_pp;
* one pipelined MoE ZeRO-2 step — under both the interleaved-1F1B and
  the zero-bubble zb1 schedule — equals the dense single-device step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.moe import (
    EXPERT_LEAVES,
    ep_mean_dense_grads,
    ep_stack_params,
    moe_ffn,
)
from horovod_tpu.parallel.pipeline import interleaved_1f1b

E, C, F, K = 4, 8, 16, 2       # experts, model, ffn, topk
DOUT = 4                       # head output width

PP, EP = 2, 2                  # stage count, expert-group count
DATA = (1, 2)                  # per-cell data mesh
NCELL = EP * DATA[0] * DATA[1]
M, NL = 4, 8                   # microbatches, tokens per mb per cell

EPALL = (hvd.EP_AXIS,) + hvd.HVD_AXES
SALL = (hvd.PP_AXIS, hvd.EP_AXIS) + hvd.HVD_AXES


def mesh4d():
    hvd.shutdown()
    hvd.init(devices=jax.devices(), mesh_shape=DATA, ep_size=EP,
             pp_stages=PP)
    return hvd.mesh()


def restore_mesh():
    hvd.shutdown()
    hvd.init(devices=jax.devices())


def stage_dense_params(seed):
    """One stage's dense (world-1) MoE block params."""
    rs = np.random.RandomState(seed)
    return {
        "router": jnp.asarray(rs.randn(C, E) * 0.1, jnp.float32),
        "w1": jnp.asarray(rs.randn(E, C, F) * 0.1, jnp.float32),
        "b1": jnp.asarray(rs.randn(E, F) * 0.01, jnp.float32),
        "w2": jnp.asarray(rs.randn(E, F, C) * 0.1, jnp.float32),
        "b2": jnp.asarray(rs.randn(E, C) * 0.01, jnp.float32),
    }


def stack_stages(stages):
    """Per-stage dense params -> the 4-D mesh's sharded layout: expert
    leaves ``[pp, ep, E_local, ...]``, replicated leaves ``[pp, ...]``."""
    ep_stacked = [ep_stack_params(p, EP) for p in stages]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *ep_stacked)


def chunk_pspecs(chunks):
    """Expert leaves shard over (pp, ep); the rest over pp only."""
    def spec(path, _leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in EXPERT_LEAVES:
            return P(hvd.PP_AXIS, hvd.EP_AXIS)
        return P(hvd.PP_AXIS)

    return jax.tree_util.tree_map_with_path(spec, chunks)


def local_chunks(cp):
    """shard_map-local chunk tree -> the ``[v=1, ...]`` stacked form
    ``interleaved_1f1b`` consumes: expert leaves drop the pp-local unit
    dim (the ep-local unit dim doubles as the v dim); replicated leaves
    already lead with it."""
    def pick(path, a):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return a[0] if name in EXPERT_LEAVES else a

    return jax.tree_util.tree_map_with_path(pick, cp)


def relift_chunks(cp_local):
    """Inverse of :func:`local_chunks` for the update's return trip."""
    def lift(path, a):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return a[None] if name in EXPERT_LEAVES else a

    return jax.tree_util.tree_map_with_path(lift, cp_local)


def stage_fn(p, x):
    """One pipeline stage: a residual MoE FFN. capacity_factor=E keeps
    every top-k choice (no drops) so the dense reference is exact."""
    y, _, _ = moe_ffn(x, p, topk=K, capacity_factor=float(E),
                      ep_axis=hvd.EP_AXIS)
    return x + y


def loss_fn(hp, y, tgt):
    """Per-microbatch LOCAL-MEAN loss — the convention
    :func:`ep_mean_dense_grads` normalizes (docs/moe.md)."""
    return jnp.mean((y @ hp["wh"] - tgt) ** 2)


def make_data(seed):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(M, NCELL * NL, C), jnp.float32)
    tgt = jnp.asarray(rs.randn(M, NCELL * NL, DOUT), jnp.float32)
    return x, tgt


def dense_step(stages, hp, x, tgt, lr=0.1, mom=0.9):
    """Single-device reference: full-batch forward through both stages,
    global-mean loss, one SGD-momentum step."""
    def ref_loss(tree):
        y = x.reshape(-1, C)
        for p in tree["stages"]:
            y = stage_fn_dense(p, y)
        return jnp.mean((y @ tree["head"]["wh"]
                         - tgt.reshape(-1, DOUT)) ** 2)

    def stage_fn_dense(p, xx):
        y, _, _ = moe_ffn(xx, p, topk=K, capacity_factor=float(E))
        return xx + y

    tree = {"stages": list(stages), "head": hp}
    loss, g = jax.value_and_grad(ref_loss)(tree)
    tx = optax.sgd(lr, momentum=mom)
    upd, _ = tx.update(g, tx.init(tree), tree)
    return loss, g, optax.apply_updates(tree, upd)


class TestMesh4D:
    def test_4d_geometry(self):
        try:
            m = mesh4d()
            assert m.axis_names == SALL
            assert m.devices.shape == (PP, EP) + DATA
            assert hvd.pp_size() == PP
            assert hvd.ep_size() == EP
            assert hvd.pod_size() == 1
            assert hvd.data_mesh_shape() == DATA
            assert basics.world_axes() == hvd.HVD_AXES
            assert f"pp{PP}.ep{EP}" in basics.mesh_geometry()
        finally:
            restore_mesh()

    def test_a2a_plan_is_stage_local(self):
        """The expert a2a prices against the per-cell DATA mesh, not
        the whole world: dispatch never crosses a stage boundary."""
        from horovod_tpu.moe import default_a2a_plan
        from horovod_tpu.plan import ep_a2a_level

        try:
            mesh4d()
            plan = default_a2a_plan()
            assert plan.legs[0].level == ep_a2a_level(DATA)
        finally:
            restore_mesh()


class TestCheckpointEPGuard:
    def test_ep_group_count_change_fails_loudly(self, tmp_path):
        """The manifest records ep_size alongside pp_stages; restoring
        on a different expert-group count fails with the recovery
        recipe instead of silently re-assigning experts."""
        from horovod_tpu import checkpoint as hvd_ckpt

        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(1, 4),
                     ep_size=2)
            mgr = hvd_ckpt.CheckpointManager(str(tmp_path), keep=2)
            state = hvd_ckpt.CheckpointedJaxState(
                mgr, params=jnp.arange(8.0), step=0)
            state.step = 3
            state.commit()
            assert state.wait(30)
            mgr.close()
        finally:
            hvd.shutdown()
        try:
            hvd.init(devices=jax.devices())  # no-ep mesh
            mgr = hvd_ckpt.CheckpointManager(str(tmp_path), keep=2)
            with pytest.raises(ValueError,
                               match="2-group expert-parallel mesh"):
                hvd_ckpt.CheckpointedJaxState(
                    mgr, params=jnp.arange(8.0), step=0)
            mgr.close()
        finally:
            restore_mesh()

    def test_same_geometry_roundtrip_on_4d_mesh(self, tmp_path):
        """A matching (pp, ep) geometry restores bit-identically — the
        guards only reject actual geometry changes."""
        from horovod_tpu import checkpoint as hvd_ckpt

        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=DATA,
                     ep_size=EP, pp_stages=PP)
            vals = jnp.asarray(
                np.random.RandomState(0).randn(16).astype(np.float32))
            mgr = hvd_ckpt.CheckpointManager(str(tmp_path), keep=2)
            state = hvd_ckpt.CheckpointedJaxState(mgr, params=vals,
                                                  step=0)
            state.step = 5
            state.commit()
            assert state.wait(30)
            mgr.close()
            hvd.shutdown()
            hvd.init(devices=jax.devices(), mesh_shape=DATA,
                     ep_size=EP, pp_stages=PP)
            mgr = hvd_ckpt.CheckpointManager(str(tmp_path), keep=2)
            restored = hvd_ckpt.CheckpointedJaxState(
                mgr, params=jnp.zeros(16), step=0)
            assert restored.restored_from == 5
            np.testing.assert_array_equal(np.asarray(restored.params),
                                          np.asarray(vals))
            mgr.close()
        finally:
            restore_mesh()


class TestEPxPPxZero2Parity:
    @pytest.mark.parametrize("family", ["1f1b", "zb1"])
    def test_one_step_parity_vs_dense(self, family):
        """One pipelined MoE ZeRO-2 step on the 4-D mesh == the dense
        single-device SGD-momentum step: loss, and updated router /
        expert / head leaves (per-stage shard worlds = the per-cell
        data world)."""
        try:
            mesh = mesh4d()
            stages = [stage_dense_params(3), stage_dense_params(4)]
            rs = np.random.RandomState(6)
            hp = {"wh": jnp.asarray(rs.randn(C, DOUT) * 0.1,
                                    jnp.float32)}
            x, tgt = make_data(7)
            want_loss, _, want_tree = dense_step(stages, hp, x, tgt)

            chunks = stack_stages(stages)
            pspec = chunk_pspecs(chunks)
            tx = hvd.DistributedOptimizer(
                optax.sgd(0.1, momentum=0.9), zero_stage=2,
                pp_stages=PP, pp_microbatches=M,
                pp_schedule=("zb1" if family == "zb1"
                             else "interleaved_1f1b"),
                moe_experts=E, moe_capacity_factor=float(E))
            state_tpl = tx.init(
                {"chunks": local_chunks(
                    jax.tree.map(lambda a: a[:1], chunks)),
                 "head": hp})
            sspec_of = lambda st: jax.tree.map(  # noqa: E731
                lambda l: P(SALL) if getattr(l, "ndim", 0) >= 1
                else P(), st)

            def init_spmd(cp, h):
                return tx.init({"chunks": local_chunks(cp), "head": h})

            state = jax.jit(hvd.shard_map(
                init_spmd, mesh=mesh, in_specs=(pspec, P()),
                out_specs=sspec_of(state_tpl)))(chunks, hp)
            sspec = sspec_of(state)

            def step_spmd(cp, h, xb, tg, st):
                local_c = local_chunks(cp)
                loss, g_cp, g_hp, _ = interleaved_1f1b(
                    stage_fn, loss_fn, local_c, h, xb, tg,
                    axis=hvd.PP_AXIS, interleave=1, family=family)
                # Normalize to the global-mean gradient's share
                # (docs/moe.md): router/head pmean over hvd_ep, expert
                # leaves 1/ep — NEVER a reduction over hvd_pp.
                g = ep_mean_dense_grads({"chunks": g_cp, "head": g_hp})
                local = {"chunks": local_c, "head": h}
                upd, st2 = tx.update(g, st, local)
                new = optax.apply_updates(local, upd)
                loss = hvd.allreduce(loss, op=hvd.Average, axes=EPALL)
                # Re-establish the head's pp x ep replication by
                # construction (the ZeRO buckets mixed pp/ep-varying
                # chunk leaves into the gather; every cell holds the
                # same head values).
                rpp = lax.axis_index(hvd.PP_AXIS)
                rep = lax.axis_index(hvd.EP_AXIS)
                on0 = jnp.logical_and(rpp == 0, rep == 0)
                new_head = jax.tree.map(
                    lambda a: lax.psum(
                        jnp.where(on0, a, jnp.zeros_like(a)),
                        (hvd.PP_AXIS, hvd.EP_AXIS)), new["head"])
                # Same for the ep replication of the non-expert chunk
                # leaves (router): pp-varying, ep-replicated.
                def fix_ep(path, a):
                    name = (path[-1].key if hasattr(path[-1], "key")
                            else str(path[-1]))
                    if name in EXPERT_LEAVES:
                        return a
                    return lax.psum(
                        jnp.where(rep == 0, a, jnp.zeros_like(a)),
                        hvd.EP_AXIS)

                new_c = jax.tree_util.tree_map_with_path(
                    fix_ep, new["chunks"])
                return loss, relift_chunks(new_c), new_head, st2

            data_spec = P(None, EPALL)
            step = jax.jit(hvd.shard_map(
                step_spmd, mesh=mesh,
                in_specs=(pspec, P(), data_spec, data_spec, sspec),
                out_specs=(P(), pspec, P(), sspec)))
            loss, new_chunks, new_head, state = step(
                chunks, hp, x, tgt, state)

            np.testing.assert_allclose(float(loss), float(want_loss),
                                       rtol=3e-5)
            got = jax.device_get(new_chunks)
            for s in range(PP):
                want_s = want_tree["stages"][s]
                np.testing.assert_allclose(
                    np.asarray(got["router"][s]),
                    np.asarray(want_s["router"]),
                    rtol=2e-4, atol=2e-6)
                # expert leaf: ep group g holds experts
                # [g*E/EP, (g+1)*E/EP)
                for g in range(EP):
                    np.testing.assert_allclose(
                        np.asarray(got["w1"][s, g]),
                        np.asarray(want_s["w1"].reshape(
                            (EP, E // EP) + want_s["w1"].shape[1:])[g]),
                        rtol=2e-4, atol=2e-6)
            np.testing.assert_allclose(
                np.asarray(jax.device_get(new_head)["wh"]),
                np.asarray(want_tree["head"]["wh"]),
                rtol=2e-4, atol=2e-6)
        finally:
            restore_mesh()
