"""Kernel block-size autotuner (ops/kernel_autotune.py) — cache and
dispatch logic. The sweep's timing machinery only means anything on a
real TPU (see the module docstring), so these tests drive get_or_tune
with canned bench functions; the real-hardware proof is the flagship
bench converging to >= the hand-tuned number with a fresh cache."""

import json

import pytest

import horovod_tpu.ops.kernel_autotune as at


@pytest.fixture()
def fresh_cache(tmp_path, monkeypatch):
    path = tmp_path / "autotune.json"
    monkeypatch.setenv("HOROVOD_AUTOTUNE_CACHE", str(path))
    monkeypatch.setattr(at, "_mem", {})
    monkeypatch.setattr(at, "_loaded", False)
    yield path


class TestGetOrTune:
    def test_disabled_off_tpu_returns_default(self, fresh_cache):
        # CPU test env: enabled() is False -> default, no bench calls.
        calls = []
        out = at.get_or_tune("k", "s", [(1,), (2,)],
                             lambda c: calls.append(c) or 0.1, (9,))
        assert out == (9,) and calls == []

    def test_sweep_picks_fastest_and_caches(self, fresh_cache, monkeypatch):
        monkeypatch.setattr(at, "enabled", lambda: True)
        times = {(256,): 0.003, (512,): 0.001, (1024,): 0.002}
        calls = []

        def bench(c):
            calls.append(c)
            return times[c]

        out = at.get_or_tune("k", "sig1", list(times), bench, (9,))
        assert out == (512,)
        assert sorted(calls) == sorted(times)
        # cache hit: no bench calls the second time
        calls.clear()
        assert at.get_or_tune("k", "sig1", list(times), bench,
                              (9,)) == (512,)
        assert calls == []
        # and the on-disk cache is a fresh process's warm start
        disk = json.loads(fresh_cache.read_text())
        key = [k for k in disk if "|sig1|" in k][0]
        # Key carries a kernel version + candidate-grid token so kernel
        # or grid changes self-invalidate stale entries (ADVICE r4).
        assert "|v1.g" in key
        assert disk[key]["blocks"] == [512]
        monkeypatch.setattr(at, "_mem", {})
        monkeypatch.setattr(at, "_loaded", False)
        assert at.get_or_tune("k", "sig1", list(times), bench,
                              (9,)) == (512,)
        assert calls == []

    def test_failing_candidates_skipped(self, fresh_cache, monkeypatch):
        monkeypatch.setattr(at, "enabled", lambda: True)

        def bench(c):
            if c == (512,):
                raise RuntimeError("VMEM")
            return 0.002 if c == (256,) else 0.004

        out = at.get_or_tune("k", "sig2", [(256,), (512,), (1024,)],
                             bench, (9,))
        assert out == (256,)

    def test_all_failing_returns_default(self, fresh_cache, monkeypatch):
        monkeypatch.setattr(at, "enabled", lambda: True)

        def bench(c):
            raise RuntimeError("timing not linear")

        assert at.get_or_tune("k", "sig3", [(1,)], bench, (9,)) == (9,)
        # nothing cached: a later process may succeed where this one failed
        assert not fresh_cache.exists() or "sig3" not in \
            fresh_cache.read_text()

    def test_multiprocess_never_sweeps(self, fresh_cache, monkeypatch):
        import jax

        monkeypatch.setattr(at, "enabled", lambda: True)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(at, "_multihost_cache_ok", [False])
        calls = []
        cands = [(1,), (2,)]
        out = at.get_or_tune("k", "sig4", cands,
                             lambda c: calls.append(c) or 0.1, (9,))
        assert out == (9,) and calls == []  # no sweep in multi-host
        # A local cache hit is NOT trusted until the init-time
        # fingerprint agreement proved every host loaded the same cache
        # (ADVICE r4: per-host caches can legitimately differ ->
        # divergent XLA programs); until then, the default.
        chip = getattr(jax.devices()[0], "device_kind", "tpu")
        key = f"k|{chip}|sig4|v1.g{at._grid_token(cands)}"
        at._mem[key] = {"blocks": [2]}
        assert at.get_or_tune("k", "sig4", cands, lambda c: 0.1, (9,)) == (9,)
        # After verification, the (identical-everywhere) cache is used.
        monkeypatch.setattr(at, "_multihost_cache_ok", [True])
        assert at.get_or_tune("k", "sig4", cands, lambda c: 0.1, (9,)) == (2,)

    def test_verify_multihost_cache(self, fresh_cache, monkeypatch):
        import jax

        from horovod_tpu.ops import collective_ops as C
        from horovod_tpu.parallel import functions

        # Single process: trivially consistent.
        monkeypatch.setattr(at, "_multihost_cache_ok", [False])
        assert at.verify_multihost_cache() is True
        assert at._multihost_cache_ok[0]

        # Multi-host, agreement channel spans the world, fingerprints
        # agree -> trusted.
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(C, "_eager_world", lambda: 2)
        fp = at.cache_fingerprint()
        monkeypatch.setattr(functions, "allgather_object",
                            lambda obj: [fp, obj])
        assert at.verify_multihost_cache() is True

        # Fingerprints differ -> defaults (loud warning, no deadlock).
        monkeypatch.setattr(functions, "allgather_object",
                            lambda obj: ["other", obj])
        assert at.verify_multihost_cache() is False
        assert not at._multihost_cache_ok[0]

        # Agreement channel does not span the world -> not trusted.
        monkeypatch.setattr(C, "_eager_world", lambda: 1)
        assert at.verify_multihost_cache() is False


class TestTraceTimeSweep:
    def test_sweep_executes_under_an_active_jit_trace(self, fresh_cache,
                                                      monkeypatch):
        """The sweep fires while the caller's train step is being traced
        (block resolution happens inside flash_attention's forward). An
        ambient trace must not stage the bench's inner jits — r5 hardware
        sessions lost every candidate to TracerArrayConversionError this
        way. The worker-thread escape gives the bench a clean (thread-
        local) trace context, so real execution + host fetch works."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        monkeypatch.setattr(at, "enabled", lambda: True)
        swept = {}

        def traced(x):
            def bench(cand):
                # Real execution + concrete fetch, as _timed_chain does.
                y = jax.jit(lambda a: (a * cand[0]).sum())(
                    jnp.ones((8, 8), jnp.float32))
                return 1.0 / float(np.asarray(y))

            swept["blocks"] = at.get_or_tune(
                "k", "trace_sig", [(1,), (2,)], bench, (9,))
            return x * 1.0

        jax.jit(traced).lower(jnp.zeros((2, 2)))
        # (2,) is faster by construction (bench returns 1/(64*c)).
        assert swept["blocks"] == (2,)
        assert "trace_sig" in fresh_cache.read_text()

    def test_worker_inherits_callers_default_device(self, fresh_cache,
                                                    monkeypatch):
        """jax.default_device is thread-local; the sweep worker must
        carry the caller's pin so candidates are timed on the device the
        user chose, not device 0."""
        import jax

        monkeypatch.setattr(at, "enabled", lambda: True)
        pinned = jax.devices()[-1]
        seen = []

        def bench(cand):
            seen.append(jax.config.jax_default_device)
            return 0.001 * cand[0]

        with jax.default_device(pinned):
            out = at.get_or_tune("k", "devsig", [(1,), (2,)], bench, (9,))
        assert out == (1,)
        assert seen and all(d is pinned for d in seen)


class TestShapeGates:
    def test_small_shapes_keep_defaults(self, fresh_cache, monkeypatch):
        """The B=1 model.init trace must not trigger a sweep."""
        monkeypatch.setattr(at, "enabled", lambda: True)
        import jax.numpy as jnp

        from horovod_tpu.ops.flash_attention import _pick_block

        out = at.flash_blocks(1, 1024, 1024, 12, 64, jnp.bfloat16, True,
                              (1024, 1024), _pick_block)
        assert out == (1024, 1024)
        out = at.xent_blocks(64, 1024, 128, jnp.float32, (1024, 1024),
                             _pick_block)
        assert out == (1024, 1024)
