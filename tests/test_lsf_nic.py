"""LSF/jsrun launcher synthesis + NIC discovery tests (reference:
test/single/test_jsrun.py rankfile/command checks and the driver-service
interface-intersection behavior, driver_service.py:260)."""

import os

import pytest

from horovod_tpu.runner import js_run, lsf, nic


class TestLSF:
    def test_using_lsf(self, monkeypatch):
        monkeypatch.delenv("LSB_JOBID", raising=False)
        assert not lsf.using_lsf()
        monkeypatch.setenv("LSB_JOBID", "1234")
        assert lsf.using_lsf()

    def test_hosts_from_mcpu(self, monkeypatch):
        monkeypatch.setenv("LSB_MCPU_HOSTS", "launchA 0 nodeB 4 nodeC 2")
        assert lsf.get_compute_hosts_and_slots() == {"nodeB": 4, "nodeC": 2}
        assert lsf.get_num_processes() == 6
        assert lsf.get_compute_hosts() == ["nodeB", "nodeC"]
        assert lsf.get_hosts_arg() == "nodeB:4,nodeC:2"

    def test_hosts_from_lsb_hosts_fallback(self, monkeypatch):
        monkeypatch.delenv("LSB_MCPU_HOSTS", raising=False)
        monkeypatch.setenv("LSB_HOSTS", "n1 n1 n2")
        assert lsf.get_compute_hosts_and_slots() == {"n1": 2, "n2": 1}

    def test_malformed_mcpu(self, monkeypatch):
        monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeB 4 nodeC")
        with pytest.raises(ValueError, match="malformed"):
            lsf.get_compute_hosts_and_slots()

    def test_no_allocation(self, monkeypatch):
        monkeypatch.delenv("LSB_MCPU_HOSTS", raising=False)
        monkeypatch.delenv("LSB_HOSTS", raising=False)
        with pytest.raises(RuntimeError, match="LSF allocation"):
            lsf.get_compute_hosts_and_slots()


class TestJsrun:
    HOSTS = {"nodeB": 2, "nodeC": 2}

    def test_validate_truncates(self):
        v = js_run.validate_host_slots(self.HOSTS, 3)
        assert v == [("nodeB", 2), ("nodeC", 1)]

    def test_validate_rejects_overflow(self):
        with pytest.raises(ValueError, match="not enough slots"):
            js_run.validate_host_slots(self.HOSTS, 5)

    def test_validate_rejects_oversubscription(self):
        with pytest.raises(ValueError, match="per-host limit"):
            js_run.validate_host_slots({"n": 8}, 8, max_slots_per_host=4)

    def test_rankfile_content(self, tmp_path):
        path = str(tmp_path / "erf")
        js_run.generate_jsrun_rankfile(self.HOSTS, 4, cpus_per_slot=2,
                                       path=path)
        text = open(path).read()
        assert "overlapping_rs: allow" in text
        assert "cpu_index_using: logical" in text
        # 4 ranks, disjoint cpu ranges restarting per host
        assert "rank: 0: { hostname: nodeB; cpu: {0-1} ; mem: * }" in text
        assert "rank: 1: { hostname: nodeB; cpu: {2-3} ; mem: * }" in text
        assert "rank: 2: { hostname: nodeC; cpu: {0-1} ; mem: * }" in text
        assert "rank: 3: { hostname: nodeC; cpu: {2-3} ; mem: * }" in text

    def test_command_synthesis(self, tmp_path):
        rf = str(tmp_path / "erf")
        cmd = js_run.build_jsrun_command(
            ["python", "train.py", "--lr", "0.1"],
            env={"HOROVOD_AUTOTUNE": "1"}, num_proc=4, hosts=self.HOSTS,
            output_filename="/tmp/out.log", rankfile_path=rf)
        assert cmd.startswith(f"jsrun --erf_input {rf} ")
        assert "--stdio_stdout /tmp/out.log" in cmd
        assert "--stdio_stderr /tmp/out.log" in cmd
        # env contract: knobs + rendezvous on the first compute host
        assert "HOROVOD_AUTOTUNE=1" in cmd
        assert "HOROVOD_CONTROLLER_ADDR=nodeB" in cmd
        assert f"HOROVOD_CONTROLLER_PORT="\
               f"{js_run.DEFAULT_CONTROLLER_PORT}" in cmd
        assert "HOROVOD_SIZE=4" in cmd
        assert cmd.endswith("python train.py --lr 0.1")

    def test_port_override_honored(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HOROVOD_CONTROLLER_PORT", "50000")
        cmd = js_run.build_jsrun_command(
            ["python", "t.py"], num_proc=2, hosts={"n1": 2},
            rankfile_path=str(tmp_path / "erf"))
        assert "HOROVOD_CONTROLLER_PORT=50000" in cmd

    def test_jsrun_rejects_elastic_flags(self):
        from horovod_tpu.runner.launch import parse_args, _validate

        args = parse_args(["--jsrun", "--min-np", "2", "-H", "a:2",
                           "python", "t.py"])
        with pytest.raises(ValueError, match="elastic flags"):
            _validate(args)

    def test_cli_np_hosts_from_lsf(self, monkeypatch):
        """-np becomes optional under LSF (reference launch.py:221)."""
        from horovod_tpu.runner.launch import parse_args, _validate

        monkeypatch.setenv("LSB_JOBID", "7")
        monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeB 2 nodeC 2")
        args = parse_args(["python", "train.py"])
        _validate(args)
        assert args.np == 4
        assert args.hosts == "nodeB:2,nodeC:2"


IFACES_A = [("eth0", "10.0.0.1"), ("ib0", "192.168.1.1"),
            ("lo", "127.0.0.1")]
IFACES_B = [("eth0", "10.0.0.2"), ("ib0", "192.168.1.2"),
            ("lo", "127.0.0.1")]
IFACES_C = [("ens3", "10.1.0.3"), ("ib0", "192.168.1.3"),
            ("lo", "127.0.0.1")]


class TestNic:
    def test_common_interfaces(self):
        common = nic.common_interfaces(
            {"a": IFACES_A, "b": IFACES_B, "c": IFACES_C})
        assert common == ["ib0", "lo"]

    def test_common_interfaces_with_allowlist(self):
        common = nic.common_interfaces({"a": IFACES_A, "b": IFACES_B},
                                       allow=["ib0"])
        assert common == ["ib0"]

    def test_select_controller_addr(self):
        addr = nic.select_controller_addr(
            IFACES_A, {"a": IFACES_A, "b": IFACES_B, "c": IFACES_C})
        assert addr == "192.168.1.1"  # rank0's address on the common NIC

    def test_select_prefers_non_loopback(self):
        addr = nic.select_controller_addr(
            IFACES_A, {"a": IFACES_A, "b": IFACES_B})
        assert addr == "10.0.0.1"  # eth0 ranks before ib0 in a's order

    def test_select_loopback_only_for_same_host(self):
        only_lo = [("lo", "127.0.0.1")]
        per_host = {"a": only_lo, "b": [("lo", "127.0.0.1"),
                                        ("eth9", "10.9.9.9")]}
        # a remote dialer must NEVER be handed loopback (it would dial its
        # own machine) — fall back to the hostname heuristic instead
        assert nic.select_controller_addr(only_lo, per_host) is None
        assert nic.select_controller_addr(
            only_lo, per_host, allow_loopback=True) == "127.0.0.1"

    def test_select_no_loopback_across_disjoint_real_nics(self):
        # eth0-vs-ens3 hosts share only 'lo': remote dialer gets None
        a = [("eth0", "10.0.0.1"), ("lo", "127.0.0.1")]
        c = [("ens3", "10.1.0.3"), ("lo", "127.0.0.1")]
        assert nic.select_controller_addr(a, {"a": a, "c": c}) is None

    def test_select_none_without_intersection(self):
        assert nic.select_controller_addr(
            [("eth0", "10.0.0.1")],
            {"a": [("eth0", "10.0.0.1")], "b": [("ens3", "10.1.0.3")]}) \
            is None

    def test_iface_filter_env(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_IFACE", raising=False)
        monkeypatch.delenv("HOROVOD_GLOO_IFACE", raising=False)
        assert nic.iface_filter_from_env() is None
        monkeypatch.setenv("HOROVOD_GLOO_IFACE", "ib0, ib1")
        assert nic.iface_filter_from_env() == ["ib0", "ib1"]
        monkeypatch.setenv("HOROVOD_IFACE", "eth0")
        assert nic.iface_filter_from_env() == ["eth0"]

    def test_list_interfaces_real(self):
        ifaces = nic.list_interfaces()
        assert ifaces, "expected at least one interface"
        assert all(len(t) == 2 for t in ifaces)
        # loopback sorts last so real NICs win intersections
        if len(ifaces) > 1:
            assert not ifaces[0][1].startswith("127.")


class TestDriverNicSelection:
    def test_driver_uses_common_iface_addr(self):
        """Workers register NICs at rendezvous; peers are handed rank-0's
        address on the intersected interface instead of the 'rank-0
        hostname resolves everywhere' guess."""
        import threading

        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.elastic.driver import ElasticDriver

        driver = ElasticDriver(FixedHosts({"hostA": 1, "hostB": 1}),
                               min_np=2)
        hold = threading.Event()  # workers stay 'running' for the test
        try:
            driver.start(lambda slot, world_id: (hold.wait(30), 0)[1])
            rank0_host = next(s.hostname
                              for s in driver.current_assignments()
                              if s.rank == 0)
            other = "hostB" if rank0_host == "hostA" else "hostA"
            r0_ifaces = IFACES_A if rank0_host == "hostA" else IFACES_B
            o_ifaces = IFACES_B if rank0_host == "hostA" else IFACES_A
            # rank-0 rendezvouses (registers NICs), reports its port
            resp0 = driver.get_slot_info(rank0_host, 0, ifaces=r0_ifaces)
            assert resp0.status == "ok"
            driver.set_controller_port(driver.world_id, 33333)
            # peer rendezvouses with its own NICs: gets the common-NIC addr
            resp = driver.get_slot_info(other, 0, ifaces=o_ifaces)
            assert resp.status == "ok"
            assert resp.controller_addr == r0_ifaces[0][1]
            # a host that never registered NICs falls back to hostname
            driver._host_ifaces.clear()
            resp = driver.get_slot_info(other, 0)
            assert resp.controller_addr == rank0_host
        finally:
            hold.set()
            driver.stop()
            driver.shutdown_service()
