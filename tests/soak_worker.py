"""Soak-gauntlet worker: the durable elastic worker with the resilience
supervisor attached.

Extends the ``--ckpt-dir`` mode of tests/elastic_worker.py with the
self-healing pieces scripts/soak.py exercises: the flight recorder is
armed (SIGTERM handler installed), and a
:class:`horovod_tpu.resilience.Supervisor` registers a priority-snapshot
provider so a preemption notice — the chaos ``preempt`` action delivers
a real SIGTERM mid-collective — commits the newest uncommitted state
through the AsyncWriter *before* the flight dump re-delivers the signal.
The deterministic batch-dependent trajectory (world-size-normalized
``cos(0.3 * batch)`` contributions) depends only on the batch number, so
the gauntlet's resized/interrupted trajectory is comparable point-for-
point against an uninterrupted reference run.

Logs one JSON line per batch to --log-file:
``{identity, rank, size, batch, weights, t}``.
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import elastic, resilience  # noqa: E402
from horovod_tpu.monitor import flight  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--log-file", required=True)
    p.add_argument("--batches", type=int, default=12)
    p.add_argument("--batch-sleep", type=float, default=0.1)
    p.add_argument("--ckpt-dir", required=True)
    args = p.parse_args()

    identity = (f"{os.environ['HOROVOD_HOSTNAME']}:"
                f"{os.environ['HOROVOD_LOCAL_RANK']}")

    def log(record):
        record["identity"] = identity
        record["t"] = time.time()
        with open(args.log_file, "a") as f:
            f.write(json.dumps(record) + "\n")

    # Crash forensics + the SIGTERM ordering contract (snapshot → writer
    # drain → dump → re-delivery) both hang off arm().
    flight.arm()

    from horovod_tpu import checkpoint as hvd_ckpt

    mgr = hvd_ckpt.CheckpointManager(args.ckpt_dir, keep=4)
    start_batch, start_weights = 0, 0.0
    latest = mgr.latest_step()
    if latest is not None:
        manifest, tree = mgr.restore()
        start_batch = manifest.step
        start_weights = float(np.asarray(tree["train"]["weights"])[0])
    log({"resumed_from": latest or 0, "start_weights": start_weights})

    # The priority-snapshot provider reads the live (possibly not yet
    # rank-0-committed) state; weights are replicated, so ANY preempted
    # rank's snapshot is a valid commit for the whole world.
    live = {"batch": start_batch, "weights": start_weights}

    def provider():
        b = int(live["batch"])
        if b <= 0:
            return None
        return b, {"train": {"weights": np.full(
            (4,), live["weights"], dtype=np.float64)}}, \
            {"src": "priority", "identity": identity}

    sup = resilience.Supervisor(ckpt_manager=mgr,
                                snapshot_provider=provider).attach()

    @elastic.run
    def train(state):
        while state.batch < args.batches:
            contrib = jnp.full((4,), math.cos(0.3 * state.batch),
                               dtype=jnp.float32)
            total = hvd.allreduce(contrib, op=hvd.Sum,
                                  name=f"train.step.{state.batch}")
            state.weights = (state.weights
                             + float(total[0]) / hvd.size())
            state.batch += 1
            live["batch"], live["weights"] = state.batch, state.weights
            log({"rank": hvd.rank(), "size": hvd.size(),
                 "batch": state.batch, "weights": state.weights})
            state.commit()
            if hvd.rank() == 0:
                mgr.save(state.batch, {"train": {
                    "weights": np.full((4,), state.weights,
                                       dtype=np.float64)}})
            time.sleep(args.batch_sleep)

    state = elastic.ObjectState(batch=start_batch, weights=start_weights)
    train(state)
    mgr.wait(30)
    sup.detach()
    mgr.close()
    log({"rank": hvd.rank(), "size": hvd.size(), "done": True,
         "weights": state.weights})


if __name__ == "__main__":
    main()
