"""Test harness: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; collective semantics are
tested on 8 virtual CPU devices (the same XLA collectives, different
interconnect), mirroring the reference's localhost `mpirun -np 2` strategy
(SURVEY §4). The axon sitecustomize preimports jax, so the platform switch
must go through jax.config (backends initialize lazily)."""

import os

# Must be set before the first backend initialization.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from the tier-1 "
        "'not slow' set")
    config.addinivalue_line(
        "markers", "chaos: fault-injection (chaos) robustness test — "
        "see docs/robustness.md and scripts/chaos_soak.py")
    config.addinivalue_line(
        "markers", "serve: continuous-batching generation engine test "
        "(horovod_tpu/serve/) — see docs/serving.md and "
        "scripts/serve_smoke.sh")


@pytest.fixture(scope="session", autouse=True)
def _hvd_init():
    hvd.init()
    yield


@pytest.fixture()
def mesh():
    return hvd.mesh()
