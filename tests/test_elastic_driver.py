"""ElasticDriver unit tests with fake discovery and mocked workers
(reference: test/single/test_elastic_driver.py — simulates multi-node
without any cluster)."""

import os
import threading
import time

import pytest

from horovod_tpu.elastic.discovery import (
    FixedHosts,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
    HostUpdateResult,
)
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.elastic.registration import FAILURE, SUCCESS
from horovod_tpu.elastic.sampler import ElasticSampler


class MutableDiscovery(HostDiscovery):
    def __init__(self, hosts):
        self.hosts = dict(hosts)

    def find_available_hosts_and_slots(self):
        return dict(self.hosts)


@pytest.fixture(autouse=True)
def _fast_discovery(monkeypatch):
    from horovod_tpu.elastic import constants

    monkeypatch.setattr(constants, "DISCOVER_HOSTS_FREQUENCY_SECS", 0.05)


class TestHostManager:
    def test_update_detects_added_and_removed(self):
        disc = MutableDiscovery({"a": 2})
        mgr = HostManager(disc)
        assert mgr.update_available_hosts() == HostUpdateResult.added
        assert mgr.update_available_hosts() == HostUpdateResult.no_update
        disc.hosts = {"a": 2, "b": 1}
        assert mgr.update_available_hosts() == HostUpdateResult.added
        disc.hosts = {"a": 1, "c": 1}
        res = mgr.update_available_hosts()
        assert res == HostUpdateResult.mixed
        disc.hosts = {"a": 1}
        assert mgr.update_available_hosts() == HostUpdateResult.removed

    def test_blacklist_hides_host(self):
        disc = MutableDiscovery({"a": 2, "b": 2})
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        mgr.blacklist("b")
        assert mgr.current_hosts == {"a": 2}
        # blacklisted host coming back is still hidden
        assert mgr.update_available_hosts() == HostUpdateResult.no_update

    def test_discovery_script(self, tmp_path):
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho h1:2\necho h2\n")
        script.chmod(0o755)
        disc = HostDiscoveryScript(str(script), default_slots=4)
        assert disc.find_available_hosts_and_slots() == {"h1": 2, "h2": 4}


class TestBlacklistCooldown:
    def test_default_blacklist_is_forever(self):
        mgr = HostManager(MutableDiscovery({"a": 1, "b": 1}))
        mgr.update_available_hosts()
        mgr.blacklist("b")
        time.sleep(0.1)
        assert mgr.is_blacklisted("b")
        assert mgr.update_available_hosts() == HostUpdateResult.no_update
        assert mgr.current_hosts == {"a": 1}

    def test_readmission_after_cooldown_expiry(self):
        mgr = HostManager(MutableDiscovery({"a": 1, "b": 1}),
                          cooldown_secs=0.2)
        mgr.update_available_hosts()
        mgr.blacklist("b")
        assert mgr.is_blacklisted("b")
        assert mgr.current_hosts == {"a": 1}
        assert mgr.update_available_hosts() == HostUpdateResult.no_update
        time.sleep(0.25)
        assert not mgr.is_blacklisted("b")
        # the diff must report the re-admitted host as ADDED even though
        # the raw discovery result never changed — that's what makes the
        # driver build a world that includes it again
        assert mgr.update_available_hosts() == HostUpdateResult.added
        assert mgr.current_hosts == {"a": 1, "b": 1}

    def test_reblacklist_rearms_the_timer(self):
        mgr = HostManager(MutableDiscovery({"a": 1}), cooldown_secs=0.3)
        mgr.blacklist("a")
        time.sleep(0.2)
        mgr.blacklist("a")  # failed again: fresh cooldown
        time.sleep(0.15)    # 0.35s after first, 0.15s after second
        assert mgr.is_blacklisted("a")
        time.sleep(0.2)
        assert not mgr.is_blacklisted("a")

    def test_cooldown_from_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN_SECS", "0.2")
        mgr = HostManager(MutableDiscovery({"a": 1}))
        mgr.blacklist("a")
        assert mgr.is_blacklisted("a")
        time.sleep(0.25)
        assert not mgr.is_blacklisted("a")


def _wait(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class RecordingWorkers:
    """create_worker_fn that keeps workers 'running' until told to exit
    (reference mocks workers the same way)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.spawned = []           # (host, local_rank, world_id)
        self.exit_codes = {}        # (host, local_rank) → code to return
        self.events = {}            # (host, local_rank) → Event

    def __call__(self, slot, world_id):
        key = (slot.hostname, slot.local_rank)
        with self.lock:
            self.spawned.append((slot.hostname, slot.local_rank, world_id))
            ev = self.events.setdefault(key, threading.Event())
        ev.wait(timeout=30)
        with self.lock:
            return self.exit_codes.get(key, 0)

    def finish(self, host, local_rank, code=0):
        key = (host, local_rank)
        with self.lock:
            self.exit_codes[key] = code
            ev = self.events.setdefault(key, threading.Event())
        ev.set()
        with self.lock:
            self.events[key] = threading.Event()  # re-arm for respawn


class TestElasticDriver:
    def test_initial_world_spawns_all_slots(self):
        workers = RecordingWorkers()
        driver = ElasticDriver(FixedHosts({"a": 2, "b": 2}), min_np=4)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 4, msg="4 workers")
            assert driver.world_id == 0
            slots = driver.current_assignments()
            assert [s.rank for s in slots] == [0, 1, 2, 3]
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_min_np_not_met_raises(self):
        driver = ElasticDriver(FixedHosts({"a": 1}), min_np=2)
        with pytest.raises((RuntimeError, TimeoutError)):
            driver.wait_for_available_slots(2, timeout=0.2)
        driver.stop()
        driver.shutdown_service()

    def test_worker_failure_blacklists_and_resumes(self):
        workers = RecordingWorkers()
        driver = ElasticDriver(FixedHosts({"a": 2, "b": 1}), min_np=2,
                               max_np=3)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 3, msg="initial spawn")
            workers.finish("b", 0, code=1)  # worker on b dies
            _wait(lambda: driver.host_manager.is_blacklisted("b"),
                  msg="blacklist")
            _wait(lambda: driver.world_id == 1, msg="resume")
            # New world excludes b; a's two live workers keep their slots and
            # re-rendezvous (no respawn needed).
            slots = driver.current_assignments()
            assert {s.hostname for s in slots} == {"a"}
            assert driver.registry.total_count(FAILURE) == 1
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_host_added_triggers_new_world_and_spawn(self):
        workers = RecordingWorkers()
        disc = MutableDiscovery({"a": 1})
        driver = ElasticDriver(disc, min_np=1, max_np=4)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 1, msg="first worker")
            disc.hosts = {"a": 1, "b": 1}
            _wait(lambda: driver.world_id == 1, msg="world grows")
            _wait(lambda: ("b", 0, 1) in workers.spawned,
                  msg="worker spawned on b")
            assert len(driver.current_assignments()) == 2
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_rank0_stays_on_surviving_host(self):
        """A newly-added host must not become rank 0 (state broadcast
        source)."""
        workers = RecordingWorkers()
        disc = MutableDiscovery({"m": 1})
        driver = ElasticDriver(disc, min_np=1, max_np=4)
        try:
            driver.start(workers)
            _wait(lambda: driver.world_id == 0, msg="start")
            disc.hosts = {"a": 1, "m": 1}  # 'a' sorts before 'm'
            _wait(lambda: driver.world_id == 1, msg="resume")
            slots = driver.current_assignments()
            rank0 = next(s for s in slots if s.rank == 0)
            assert rank0.hostname == "m"
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_success_finishes_job(self):
        workers = RecordingWorkers()
        driver = ElasticDriver(FixedHosts({"a": 2}), min_np=2)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 2, msg="spawn")
            workers.finish("a", 0, code=0)
            workers.finish("a", 1, code=0)
            assert driver.join(timeout=10)
            assert driver.registry.count(SUCCESS) == 2
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_reset_limit_stops_job(self):
        workers = RecordingWorkers()
        driver = ElasticDriver(FixedHosts({"a": 1, "b": 1, "c": 1}),
                               min_np=1, max_np=3, reset_limit=1)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 3, msg="spawn")
            workers.finish("a", 0, code=1)
            _wait(lambda: driver.world_id == 1, msg="first reset")
            workers.finish("b", 0, code=1)
            workers.finish("c", 0, code=1)
            driver.join(timeout=10)
            assert driver.registry.reset_count >= 1
        finally:
            driver.stop()
            driver.shutdown_service()


class TestShrinkRelease:
    def test_released_worker_is_not_a_success(self):
        """A worker released by a shrink exits 0 but must not mark the job
        successful (its func never completed)."""
        workers = RecordingWorkers()
        disc = MutableDiscovery({"a": 1, "b": 1})
        driver = ElasticDriver(disc, min_np=1, max_np=2)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 2, msg="spawn")
            disc.hosts = {"a": 1}  # graceful shrink: b removed, not failed
            _wait(lambda: driver.world_id == 1, msg="shrink world")
            # b's worker re-rendezvouses and is told to shut down
            resp = driver.get_slot_info("b", 0, min_world_id=1)
            assert resp.status == "shutdown"
            workers.finish("b", 0, code=0)
            _wait(lambda: ("b", 0) not in driver._live_workers,
                  msg="b exits")
            assert driver.registry.total_count(SUCCESS) == 0
            assert not driver.join(timeout=0.5)  # job is NOT finished
        finally:
            driver.stop()
            driver.shutdown_service()


class TestWindDown:
    def test_late_failure_does_not_restart_finished_job(self):
        """Once any worker has succeeded, a failure elsewhere must wind the
        job down — not erase the success record and respawn the finished
        slot (which would re-run training from scratch)."""
        workers = RecordingWorkers()
        driver = ElasticDriver(FixedHosts({"a": 1, "b": 1}), min_np=1,
                               max_np=2)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 2, msg="spawn")
            workers.finish("a", 0, code=0)   # a finishes training
            _wait(lambda: driver.registry.total_count(SUCCESS) == 1,
                  msg="success recorded")
            workers.finish("b", 0, code=1)   # b then crashes
            assert driver.join(timeout=10)   # job ends successfully
            # a:0 must NOT have been respawned into a new world
            assert len([s for s in workers.spawned if s[0] == "a"]) == 1
        finally:
            driver.stop()
            driver.shutdown_service()


class TestWindDownRendezvous:
    def test_rerendezvous_after_success_gets_shutdown(self):
        """A worker re-rendezvousing after another worker succeeded must be
        told to shut down (not wait forever for a world that will never
        form), and its clean exit is neither success nor failure."""
        workers = RecordingWorkers()
        disc = MutableDiscovery({"a": 1, "b": 1})
        driver = ElasticDriver(disc, min_np=1, max_np=2)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 2, msg="spawn")
            workers.finish("a", 0, code=0)
            _wait(lambda: driver.registry.total_count(SUCCESS) == 1,
                  msg="success")
            resp = driver.get_slot_info("b", 0, min_world_id=1)
            assert resp.status == "shutdown"
            workers.finish("b", 0, code=0)
            assert driver.join(timeout=10)
            assert driver.registry.total_count(FAILURE) == 0
            # b's post-success clean exit must not double-count as success
            assert driver.registry.total_count(SUCCESS) == 1
        finally:
            driver.stop()
            driver.shutdown_service()


class TestHostFlap:
    def test_readded_host_respawns_after_released_worker_exits(self):
        """Host removed then re-added while its released worker is still
        exiting: the slot must be spawned when the old process goes away,
        or the new world never forms."""
        workers = RecordingWorkers()
        disc = MutableDiscovery({"a": 1, "b": 1})
        driver = ElasticDriver(disc, min_np=1, max_np=2)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 2, msg="spawn")
            disc.hosts = {"a": 1}            # b removed
            _wait(lambda: driver.world_id == 1, msg="shrink world")
            resp = driver.get_slot_info("b", 0, min_world_id=1)
            assert resp.status == "shutdown"  # b's worker is released
            disc.hosts = {"a": 1, "b": 1}    # b flaps back
            _wait(lambda: driver.world_id == 2, msg="regrow world")
            # old b worker still alive → not respawned yet
            assert ("b", 0, 2) not in workers.spawned
            workers.finish("b", 0, code=0)   # released worker finally exits
            _wait(lambda: ("b", 0, 2) in workers.spawned,
                  msg="slot respawned after flap")
        finally:
            driver.stop()
            driver.shutdown_service()


class FlakyDiscovery(HostDiscovery):
    def __init__(self, hosts, failures=1):
        self.hosts = dict(hosts)
        self.failures = failures

    def find_available_hosts_and_slots(self):
        if self.failures > 0:
            self.failures -= 1
            raise RuntimeError("transient discovery blip")
        return dict(self.hosts)


class TestStartupDiscovery:
    def test_transient_blip_during_startup_is_retried(self):
        driver = ElasticDriver(FlakyDiscovery({"a": 2}, failures=2),
                               min_np=2)
        try:
            hosts = driver.wait_for_available_slots(2, timeout=10)
            assert hosts == {"a": 2}
        finally:
            driver.stop()
            driver.shutdown_service()


class TestGetSlotProtocol:
    def test_waiting_then_ok_then_shutdown(self):
        workers = RecordingWorkers()
        disc = MutableDiscovery({"a": 1, "b": 1})
        driver = ElasticDriver(disc, min_np=1, max_np=2)
        try:
            driver.start(workers)
            # current world is 0: a NON-assignee request for world >= 1
            # waits (an assignee's would be a formation-failure report and
            # bump the world — covered below)
            resp = driver.get_slot_info("zzz", 5, min_world_id=1)
            assert resp.status == "waiting"
            # rank 0's slot: ok immediately, controller_port=0 = "you bind"
            rank0_host = next(
                s.hostname for s in driver.current_assignments()
                if s.rank == 0)
            other_host = "b" if rank0_host == "a" else "a"
            resp = driver.get_slot_info(rank0_host, 0, min_world_id=0)
            assert resp.status == "ok"
            assert resp.slot["rank"] == 0
            assert resp.controller_port == 0
            # unknown slot → shutdown signal
            resp = driver.get_slot_info("zzz", 5, min_world_id=0)
            assert resp.status == "shutdown"
            # non-rank-0 waits until rank 0 reports its bound port
            resp = driver.get_slot_info(other_host, 0, min_world_id=0)
            assert resp.status == "waiting"
            driver.set_controller_port(driver.world_id, 45678)
            resp = driver.get_slot_info(other_host, 0, min_world_id=0)
            assert resp.status == "ok"
            assert resp.controller_port == 45678
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_failed_formation_report_builds_next_world(self):
        """A current-world assignee asking for world+1 signals that
        formation failed under it; the driver must build the next
        incarnation instead of letting every worker wait out
        ELASTIC_TIMEOUT (the round-2 'timeout-into-next-incarnation'
        deadlock)."""
        workers = RecordingWorkers()
        disc = MutableDiscovery({"a": 2})
        driver = ElasticDriver(disc, min_np=2)
        try:
            driver.start(workers)
            wid = driver.world_id
            resp = driver.get_slot_info("a", 0, min_world_id=wid + 1)
            assert driver.world_id == wid + 1
            assert resp.status in ("ok", "waiting")
            # non-assignees and released slots must NOT bump the world
            driver.get_slot_info("zzz", 9, min_world_id=driver.world_id + 1)
            assert driver.world_id == wid + 1
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_controller_port_allocated_on_worker_not_driver(self,
                                                            monkeypatch):
        """The round-2 flaw: the driver probed ITS OWN port space for a
        socket that binds on the rank-0 worker host. Now the driver never
        probes — even with find_free_port broken, worlds form, and a stale
        incarnation's report cannot poison a newer world."""
        from horovod_tpu.runner import network

        def _boom():
            raise AssertionError("driver must not probe local ports")

        monkeypatch.setattr(network, "find_free_port", _boom)
        workers = RecordingWorkers()
        disc = MutableDiscovery({"a": 2})
        driver = ElasticDriver(disc, min_np=2)
        try:
            driver.start(workers)  # would raise if the driver probed
            wid = driver.world_id
            driver.set_controller_port(wid - 1, 11111)  # stale: ignored
            resp = driver.get_slot_info("a", 1, min_world_id=0)
            if resp.slot is not None and resp.slot["rank"] != 0:
                assert resp.status == "waiting"
            driver.set_controller_port(wid, 22222)
            resp = driver.get_slot_info("a", 1, min_world_id=0)
            assert resp.status == "ok"
            assert resp.controller_port == 22222
        finally:
            driver.stop()
            driver.shutdown_service()


class TestStallWatchdog:
    def test_formation_stall_warns_then_abandons_incarnation(self):
        """Enforces the --stall-check-* contract: a slot that never
        reaches rendezvous first draws a warning, then (past the shutdown
        threshold) its host is blacklisted and the driver resumes into a
        new world without it."""
        from horovod_tpu.common import counters

        counters.reset_all()
        workers = RecordingWorkers()
        driver = ElasticDriver(FixedHosts({"a": 1, "b": 1}), min_np=1,
                               max_np=2, stall_warn_secs=0.3,
                               stall_shutdown_secs=0.8)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 2, msg="spawn")
            # only a's worker rendezvouses; b's is 'hung' before init
            resp = driver.get_slot_info("a", 0, min_world_id=0)
            assert resp.status == "ok"
            _wait(lambda: counters.get("elastic.stall.warning") >= 1,
                  timeout=5, msg="stall warning")
            assert not driver.host_manager.is_blacklisted("b")  # warn only
            _wait(lambda: counters.get("elastic.stall.shutdown") >= 1,
                  timeout=5, msg="stall shutdown")
            _wait(lambda: driver.host_manager.is_blacklisted("b"),
                  msg="stalled host blacklisted")
            _wait(lambda: driver.world_id == 1, msg="new incarnation")
            assert {s.hostname for s in driver.current_assignments()} \
                == {"a"}
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_no_watchdog_when_disabled(self):
        workers = RecordingWorkers()
        driver = ElasticDriver(FixedHosts({"a": 1}), min_np=1,
                               stall_check_disable=True,
                               stall_warn_secs=0.1,
                               stall_shutdown_secs=0.2)
        try:
            driver.start(workers)
            assert driver._stall_thread is None
        finally:
            driver.stop()
            driver.shutdown_service()

    def test_formed_world_does_not_trip_the_watchdog(self):
        """Once every slot is ready the formation watchdog goes quiet —
        in-step stalls are the native stall inspector's job."""
        from horovod_tpu.common import counters

        counters.reset_all()
        workers = RecordingWorkers()
        driver = ElasticDriver(FixedHosts({"a": 1}), min_np=1,
                               stall_warn_secs=0.2,
                               stall_shutdown_secs=0.4)
        try:
            driver.start(workers)
            _wait(lambda: len(workers.spawned) == 1, msg="spawn")
            assert driver.get_slot_info("a", 0).status == "ok"
            time.sleep(0.6)  # well past both thresholds
            assert counters.get("elastic.stall.warning") == 0
            assert counters.get("elastic.stall.shutdown") == 0
        finally:
            driver.stop()
            driver.shutdown_service()


class TestElasticSampler:
    def test_shards_and_records(self):
        s = ElasticSampler(dataset_size=20, shuffle=False, rank=0, size=1)
        assert len(s) == 20
        s.record_batch(0, 5)
        assert len(s.processed_indices) == 5
        s.reset()
        assert len(s) == 15
        assert set(s.indices).isdisjoint(s.processed_indices)

    def test_state_dict_roundtrip(self):
        s = ElasticSampler(dataset_size=10, shuffle=False, rank=0, size=1)
        s.record_batch(0, 4)
        st = s.state_dict()
        s2 = ElasticSampler(dataset_size=10, shuffle=False, rank=0, size=1)
        s2.load_state_dict(st)
        assert s2.processed_indices == s.processed_indices
        s2.set_epoch(1)
        assert s2.processed_indices == set()
        assert len(s2) == 10
