"""Worker for multi-process quantized-allreduce correctness.

Run under the launcher env contract (HOROVOD_RANK/SIZE + controller
address) with HOROVOD_QUANTIZED_ALLREDUCE=1. On the eager (host) path the
native core reduces full-width dtypes, so quantization is applied as a
local fake-quant of each rank's contribution — every rank can therefore
compute the exact expected result from the deterministic per-rank payloads
and assert bit-level agreement with the quantized-semantics model.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.ops.compression import fake_quantize_int8  # noqa: E402


def rank_payload(r, n=700):
    # Deterministic per-rank data every rank can reconstruct.
    return np.random.RandomState(100 + r).randn(n).astype(np.float32)


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.is_initialized()
    # The env knob must land in the typed config.
    from horovod_tpu.common import basics

    assert basics.config().quantized_allreduce, "env knob not picked up"

    mine = jnp.asarray(rank_payload(rank))
    expect = np.mean(
        [np.asarray(fake_quantize_int8(jnp.asarray(rank_payload(r))))
         for r in range(size)], axis=0)

    # Knob-driven quantization (no per-call arg): hvd.allreduce resolves
    # quantized=None from HOROVOD_QUANTIZED_ALLREDUCE.
    out = hvd.allreduce(mine, op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6, atol=1e-6)

    # Explicit API with error feedback: residual == corrected - transmitted.
    res = jnp.zeros_like(mine)
    out2, res2 = hvd.quantized_allreduce(mine, res, op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out2), expect, rtol=1e-6,
                               atol=1e-6)
    want_res = np.asarray(mine) - np.asarray(fake_quantize_int8(mine))
    np.testing.assert_allclose(np.asarray(res2), want_res, rtol=1e-6,
                               atol=1e-6)
    # Second step carries the residual: the transmitted value is
    # fake_quant(grad + residual).
    out3, res3 = hvd.quantized_allreduce(mine, res2, op=hvd.Average)
    corrected = np.asarray(mine) + np.asarray(res2)
    sent = np.asarray(fake_quantize_int8(jnp.asarray(corrected)))
    np.testing.assert_allclose(np.asarray(res3), corrected - sent,
                               rtol=1e-6, atol=1e-6)

    # Default-off contract: quantized=False must bypass quantization even
    # with the env knob set.
    exact = hvd.allreduce(mine, op=hvd.Average, quantized=False)
    want_exact = np.mean([rank_payload(r) for r in range(size)], axis=0)
    np.testing.assert_allclose(np.asarray(exact), want_exact, rtol=1e-6,
                               atol=1e-6)

    print(f"quantized_worker rank {rank}/{size} OK", flush=True)


if __name__ == "__main__":
    main()
