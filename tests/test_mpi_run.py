"""mpirun launch path (runner/mpi_run.py): implementation detection,
command construction, the MPI->HOROVOD env bridge, and an end-to-end
2-process launch through a shim mpirun that emulates OpenMPI's contract
(parses -np, spawns local ranks with OMPI_COMM_WORLD_* set) — the
reference's mpi_run.py:57-226 behavior without needing a cluster MPI."""

import os
import socket
import stat
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.common import basics
from horovod_tpu.runner import mpi_run

SHIM = textwrap.dedent("""\
    #!{python}
    import os, subprocess, sys
    args = sys.argv[1:]
    if args == ["--version"]:
        print("{version}")
        sys.exit({rc})
    np_ = int(args[args.index("-np") + 1])
    cmd = args[args.index("env"):]
    procs = []
    for r in range(np_):
        env = dict(os.environ,
                   OMPI_COMM_WORLD_RANK=str(r),
                   OMPI_COMM_WORLD_SIZE=str(np_),
                   OMPI_COMM_WORLD_LOCAL_RANK=str(r),
                   OMPI_COMM_WORLD_LOCAL_SIZE=str(np_))
        procs.append(subprocess.Popen(cmd, env=env))
    sys.exit(max(p.wait() for p in procs))
""")


def write_shim(tmp_path, version="mpirun (Open MPI) 4.1.5", rc=0):
    shim = tmp_path / "mpirun"
    shim.write_text(SHIM.format(python=sys.executable, version=version,
                                rc=rc))
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    return tmp_path


class TestDetection:
    def test_missing(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PATH", str(tmp_path))
        assert mpi_run.detect_mpi_implementation() == mpi_run.MISSING
        assert not mpi_run.mpi_available()

    @pytest.mark.parametrize("version,impl", [
        ("mpirun (Open MPI) 4.1.5", mpi_run.OPENMPI),
        ("mpirun (OpenRTE) 3.1", mpi_run.OPENMPI),
        ("IBM Spectrum MPI 10.4", mpi_run.SPECTRUM),
        ("HYDRA build details: MPICH Version 4.1", mpi_run.MPICH),
        ("SomeVendor MPI 1.0", mpi_run.UNKNOWN),
    ])
    def test_impls(self, tmp_path, monkeypatch, version, impl):
        write_shim(tmp_path, version=version)
        monkeypatch.setenv("PATH",
                           f"{tmp_path}{os.pathsep}{os.environ['PATH']}")
        assert mpi_run.detect_mpi_implementation() == impl

    def test_failing_version_is_missing(self, tmp_path, monkeypatch):
        write_shim(tmp_path, rc=1)
        monkeypatch.setenv("PATH",
                           f"{tmp_path}{os.pathsep}{os.environ['PATH']}")
        assert mpi_run.detect_mpi_implementation() == mpi_run.MISSING


class TestCommand:
    def test_openmpi_command_shape(self):
        cmd = mpi_run.build_mpirun_command(
            ["python", "t.py"], env={"HOROVOD_LOG_LEVEL": "info"},
            num_proc=4, hosts={"h1": 2, "h2": 2}, impl=mpi_run.OPENMPI,
            ssh_port=2222)
        s = " ".join(cmd)
        assert s.startswith("mpirun -np 4 -H h1:2,h2:2")
        assert "-mca pml ob1" in s and "-bind-to none" in s
        assert "plm_rsh_args -p 2222" in s
        # env contract rides an explicit prefix; size + controller
        # rendezvous defaults present.
        assert "HOROVOD_SIZE=4" in s
        assert "HOROVOD_CONTROLLER_ADDR=h1" in s
        assert "HOROVOD_LOG_LEVEL=info" in s
        assert cmd[-2:] == ["python", "t.py"]

    def test_mpich_has_no_openmpi_flags(self):
        cmd = mpi_run.build_mpirun_command(
            ["x"], num_proc=2, impl=mpi_run.MPICH)
        s = " ".join(cmd)
        assert "-mca" not in s and "--allow-run-as-root" not in s

    def test_missing_raises(self):
        with pytest.raises(RuntimeError, match="no usable MPI"):
            mpi_run.build_mpirun_command(["x"], num_proc=2,
                                         impl=mpi_run.MISSING)


@pytest.fixture()
def env_snapshot():
    """Full os.environ snapshot/restore: the bridge under test WRITES
    os.environ directly, which monkeypatch.delenv(raising=False) on an
    absent var does not register for cleanup."""
    snap = dict(os.environ)
    yield
    os.environ.clear()
    os.environ.update(snap)


class TestEnvBridge:
    def test_openmpi_bridge(self, env_snapshot, monkeypatch):
        for k in ("HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
                  "HOROVOD_LOCAL_SIZE"):
            os.environ.pop(k, None)
        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "8")
        monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_RANK", "1")
        monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_SIZE", "4")
        basics._bridge_mpi_env()
        assert os.environ["HOROVOD_RANK"] == "3"
        assert os.environ["HOROVOD_SIZE"] == "8"
        assert os.environ["HOROVOD_LOCAL_RANK"] == "1"

    def test_pmi_bridge(self, env_snapshot, monkeypatch):
        for k in list(os.environ):
            if k.startswith(("OMPI_", "HOROVOD_RANK", "HOROVOD_SIZE",
                             "HOROVOD_LOCAL_")):
                os.environ.pop(k)
        monkeypatch.setenv("PMI_RANK", "2")
        monkeypatch.setenv("PMI_SIZE", "4")
        monkeypatch.setenv("MPI_LOCALRANKID", "1")
        monkeypatch.setenv("MPI_LOCALNRANKS", "2")
        basics._bridge_mpi_env()
        assert os.environ["HOROVOD_RANK"] == "2"
        assert os.environ["HOROVOD_SIZE"] == "4"
        # Hydra's local identity rides MPI_LOCALRANKID (optional keys).
        assert os.environ["HOROVOD_LOCAL_RANK"] == "1"
        assert os.environ["HOROVOD_LOCAL_SIZE"] == "2"

    def test_explicit_contract_wins(self, env_snapshot, monkeypatch):
        monkeypatch.setenv("HOROVOD_RANK", "0")
        monkeypatch.setenv("HOROVOD_SIZE", "2")
        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "7")
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "9")
        monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_RANK", "7")
        monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_SIZE", "9")
        basics._bridge_mpi_env()
        assert os.environ["HOROVOD_RANK"] == "0"
        assert os.environ["HOROVOD_SIZE"] == "2"


class TestEndToEnd:
    def test_two_process_world_through_shim(self, tmp_path, monkeypatch):
        """hvdrun --mpi -> shim mpirun -> 2 local ranks form a real
        controller world via the OMPI_* bridge and allreduce."""
        write_shim(tmp_path)
        monkeypatch.setenv("PATH",
                           f"{tmp_path}{os.pathsep}{os.environ['PATH']}")
        with socket.socket() as s:  # unique controller port per test run
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        monkeypatch.setenv("HOROVOD_CONTROLLER_PORT", str(port))
        worker = tmp_path / "worker.py"
        worker.write_text(textwrap.dedent("""\
            import jax; jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            assert hvd.size() == 2, hvd.size()
            out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum)
            assert np.allclose(np.asarray(out), 2.0)
            print("mpi-shim rank", hvd.rank(), "OK")
        """))
        rc = mpi_run.mpi_run([sys.executable, str(worker)],
                             env={"PYTHONPATH": mpi_run_repo()},
                             num_proc=2, verbose=2)
        assert rc == 0


def mpi_run_repo():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
