"""bench.py weak-scaling sweep (--scaling): the north-star harness.

The reference's headline metric is scaling efficiency 1->N workers
(docs/benchmarks.rst:13-43, produced by running the synthetic benchmark
under ``horovodrun -np N``); here one process sweeps growing device-subset
meshes. On shared-host virtual CPU devices the efficiency *number* is
meaningless (the "chips" contend for the same cores) — these tests verify
the harness: the sweep runs, the world re-inits per size, the efficiency
table is emitted, and the JSON contract holds. The identical command with
``--platform auto`` is the pod run.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(*extra, timeout=560):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # bench sets its own virtual-device count
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, BENCH, "--platform", "cpu", "--model", "resnet18",
         "--image-size", "32", "--batch-size", "2", "--num-warmup", "1",
         "--num-iters", "1", "--num-batches-per-iter", "1", *extra],
        env=env, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, proc.stderr[-4000:]
    return json.loads(proc.stdout.strip().splitlines()[-1]), proc.stderr


class TestScalingSweep:
    def test_sweep_emits_efficiency_table(self):
        res, err = _run_bench("--cpu-devices", "2", "--scaling", "1,2")
        assert res["metric"] == "resnet18_scaling_efficiency_2chip"
        assert res["unit"] == "fraction"
        assert [r["chips"] for r in res["table"]] == [1, 2]
        assert res["table"][0]["efficiency"] == 1.0
        assert res["value"] == res["table"][-1]["efficiency"] > 0
        # vs_baseline anchors on the reference's published 90% figure
        assert abs(res["vs_baseline"] - res["value"] / 0.90) < 2e-3
        # MFU must be omitted on CPU, not fabricated
        assert all(r["mfu"] is None for r in res["table"])
        assert "weak scaling" in err

    def test_chips_subset_single_run(self):
        res, _ = _run_bench("--cpu-devices", "2", "--chips", "1")
        assert res["metric"] == "resnet18_images_per_sec_per_chip"
        assert res["chips"] == 1
        assert res["platform"] == "cpu"
        assert res["mfu"] is None

    def test_scaling_rejects_bad_spec(self):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        for bad in ("1,two", "0,2"):
            proc = subprocess.run(
                [sys.executable, BENCH, "--platform", "cpu",
                 "--scaling", bad],
                env=env, capture_output=True, text=True, timeout=120)
            assert proc.returncode != 0, bad
            assert "--scaling" in proc.stderr, proc.stderr[-500:]
