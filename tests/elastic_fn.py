"""Shared elastic worker-fn factory for platform-integration tests
(Ray/Spark). The closure is cloudpickled into task subprocesses; callers
register this module for by-value pickling so the child needs no import
path back to tests/."""


def make_worker_fn(log_file, batches, exit_at=None, batch_sleep=0.15):
    """Elastic worker body: trains a toy loop under hvd.elastic.run with a
    real collective per step, logging JSON lines (the reference's
    integration worker pattern, elastic_common.py). Returns the final
    committed batch count."""

    def _worker():
        import json as _json
        import os as _os
        import time as _time

        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu import elastic

        identity = (f"{_os.environ['HOROVOD_HOSTNAME']}:"
                    f"{_os.environ['HOROVOD_LOCAL_RANK']}")
        crash_at = None
        if exit_at:
            h, lr, b = exit_at.rsplit(":", 2)
            if identity == f"{h}:{lr}":
                crash_at = int(b)

        def log(rec):
            rec["identity"] = identity
            with open(log_file, "a") as f:
                f.write(_json.dumps(rec) + "\n")

        @elastic.run
        def train(state):
            while state.batch < batches:
                total = hvd.allreduce(jnp.full((4,), 1.0), op=hvd.Sum,
                                      name=f"el.{state.batch}")
                assert np.allclose(total, hvd.size())
                state.batch += 1
                if crash_at is not None and state.batch == crash_at:
                    _os._exit(1)
                log({"rank": int(hvd.rank()), "size": int(hvd.size()),
                     "batch": int(state.batch)})
                state.commit()
                _time.sleep(batch_sleep)

        state = elastic.ObjectState(batch=0)
        train(state)
        log({"rank": int(hvd.rank()), "size": int(hvd.size()), "done": True})
        return int(state.batch)

    return _worker


def read_log(path):
    import json
    import os

    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line.strip()))
    return out
