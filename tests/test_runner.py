"""Runner tests (reference analogue: test/single/test_run.py — arg parsing,
host assignment, command construction; plus end-to-end static launch the
reference covers in test/integration/test_static_run.py)."""

import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from horovod_tpu.runner import hosts as hosts_mod
from horovod_tpu.runner import network, secret
from horovod_tpu.runner import run as hvd_run
from horovod_tpu.runner.http_server import (
    KVStoreServer,
    RendezvousServer,
    put_data_into_kvstore,
    read_data_from_kvstore,
)
from horovod_tpu.runner.launch import parse_args, _validate
from horovod_tpu.runner import config_parser, safe_shell_exec
from horovod_tpu.runner.static_run import get_run_command, slot_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestHosts:
    def test_parse_hosts(self):
        hs = hosts_mod.parse_hosts("a:2,b:4")
        assert [(h.hostname, h.slots) for h in hs] == [("a", 2), ("b", 4)]

    def test_parse_hosts_default_slot(self):
        hs = hosts_mod.parse_hosts("a,b:3")
        assert [(h.hostname, h.slots) for h in hs] == [("a", 1), ("b", 3)]

    def test_parse_hostfile(self, tmp_path):
        f = tmp_path / "hostfile"
        f.write_text("h1 slots=2\n# comment\nh2:3\nh3\n")
        hs = hosts_mod.parse_host_files(str(f))
        assert [(h.hostname, h.slots) for h in hs] == \
            [("h1", 2), ("h2", 3), ("h3", 1)]

    def test_assignment_packs_host_by_host(self):
        # Reference semantics (hosts.py:100-150): ranks packed host-major.
        hs = hosts_mod.parse_hosts("a:2,b:2")
        slots = hosts_mod.get_host_assignments(hs, 4)
        assert [(s.hostname, s.rank, s.local_rank, s.cross_rank)
                for s in slots] == [
            ("a", 0, 0, 0), ("a", 1, 1, 0), ("b", 2, 0, 1), ("b", 3, 1, 1)]
        assert all(s.size == 4 and s.local_size == 2 and s.cross_size == 2
                   for s in slots)

    def test_assignment_uneven(self):
        hs = hosts_mod.parse_hosts("a:3,b:1")
        slots = hosts_mod.get_host_assignments(hs, 4)
        by_rank = {s.rank: s for s in slots}
        # local_rank 0 exists on both hosts → cross_size 2
        assert by_rank[0].cross_size == 2
        assert by_rank[3].hostname == "b" and by_rank[3].local_size == 1
        # local ranks 1,2 exist only on host a → cross_size 1
        assert by_rank[1].cross_size == 1 and by_rank[2].cross_size == 1

    def test_assignment_partial_fill(self):
        hs = hosts_mod.parse_hosts("a:4,b:4")
        slots = hosts_mod.get_host_assignments(hs, 6)
        assert sum(1 for s in slots if s.hostname == "a") == 4
        assert sum(1 for s in slots if s.hostname == "b") == 2

    def test_assignment_insufficient_slots(self):
        with pytest.raises(ValueError):
            hosts_mod.get_host_assignments(hosts_mod.parse_hosts("a:1"), 2)


class TestLaunchArgs:
    def test_parse_basic(self):
        args = parse_args(["-np", "4", "python", "train.py", "--lr", "0.1"])
        assert args.np == 4
        assert args.command == ["python", "train.py", "--lr", "0.1"]
        assert not args.elastic
        _validate(args)

    def test_parse_elastic(self):
        args = parse_args(["-np", "2", "--min-np", "2", "--max-np", "4",
                           "--host-discovery-script", "./d.sh", "cmd"])
        assert args.elastic
        _validate(args)

    def test_missing_np_rejected(self):
        with pytest.raises(ValueError):
            _validate(parse_args(["python", "train.py"]))

    def test_missing_command_rejected(self):
        with pytest.raises(ValueError):
            _validate(parse_args(["-np", "2"]))

    def test_tuning_flags_to_env(self):
        args = parse_args(["-np", "2", "--fusion-threshold-mb", "32",
                           "--cycle-time-ms", "3.5", "--autotune",
                           "--timeline-filename", "/tmp/t.json",
                           "--log-level", "debug", "cmd"])
        env = {}
        config_parser.set_env_from_args(env, args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
        assert env["HOROVOD_CYCLE_TIME"] == "3.5"
        assert env["HOROVOD_AUTOTUNE"] == "1"
        assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
        assert env["HOROVOD_LOG_LEVEL"] == "debug"

    def test_config_file(self, tmp_path):
        cfg = tmp_path / "cfg.yaml"
        cfg.write_text(textwrap.dedent("""
            fusion:
              threshold-mb: 16
              cycle-time-ms: 2.5
            autotune:
              enabled: true
            timeline:
              filename: /tmp/tl.json
        """))
        args = parse_args(["-np", "2", "--config-file", str(cfg), "cmd"])
        config_parser.parse_config_file(str(cfg), args)
        assert args.fusion_threshold_mb == 16
        assert args.cycle_time_ms == 2.5
        assert args.autotune is True
        assert args.timeline_filename == "/tmp/tl.json"


class TestSlotEnv:
    def test_env_contract(self):
        slot = hosts_mod.SlotInfo("localhost", 1, 1, 0, 2, 2, 1)
        env = slot_env(slot, "127.0.0.1", 4567, rendezvous_port=8899,
                       base_env={})
        assert env["HOROVOD_RANK"] == "1"
        assert env["HOROVOD_SIZE"] == "2"
        assert env["HOROVOD_LOCAL_RANK"] == "1"
        assert env["HOROVOD_CROSS_SIZE"] == "1"
        assert env["HOROVOD_CONTROLLER_ADDR"] == "127.0.0.1"
        assert env["HOROVOD_CONTROLLER_PORT"] == "4567"
        assert env["HOROVOD_GLOO_RENDEZVOUS_PORT"] == "8899"

    def test_remote_command_uses_ssh(self):
        slot = hosts_mod.SlotInfo("farhost", 0, 0, 0, 2, 1, 2)
        env = slot_env(slot, "farhost", 4567, base_env={"PATH": "/bin"})
        cmd = get_run_command(["python", "t.py"], slot.hostname, env)
        assert cmd.startswith("ssh ")
        assert "HOROVOD_RANK=0" in cmd

    def test_local_command_plain(self):
        slot = hosts_mod.SlotInfo("localhost", 0, 0, 0, 1, 1, 1)
        env = slot_env(slot, "127.0.0.1", 4567, base_env={})
        cmd = get_run_command(["python", "t.py"], slot.hostname, env)
        assert cmd == "python t.py"


class TestSafeShellExec:
    def test_exit_code_and_output(self, capsys):
        code = safe_shell_exec.execute("echo hello; exit 3", index=7)
        assert code == 3
        assert "[7]hello" in capsys.readouterr().out

    def test_event_kills_process_group(self):
        ev = threading.Event()
        t = threading.Timer(0.3, ev.set)
        t.start()
        start = time.monotonic()
        code = safe_shell_exec.execute("sleep 30", events=[ev])
        assert time.monotonic() - start < 10
        assert code != 0


class TestNetwork:
    def test_ping_roundtrip(self):
        key = secret.make_secret_key()
        svc = network.BasicService("test", key)
        try:
            client = network.BasicClient("test", "127.0.0.1", svc.port, key)
            resp = client.ping()
            assert resp.service_name == "test"
        finally:
            svc.shutdown()

    def test_wrong_key_rejected(self):
        svc = network.BasicService("test", secret.make_secret_key())
        try:
            client = network.BasicClient("test", "127.0.0.1", svc.port,
                                         b"x" * 32, attempts=1)
            with pytest.raises((ConnectionError, PermissionError)):
                client.ping()
        finally:
            svc.shutdown()


class TestKVStore:
    def test_put_get_roundtrip(self):
        kv = KVStoreServer()
        port = kv.start_server()
        try:
            put_data_into_kvstore("127.0.0.1", port, "s", "k", {"a": 1})
            assert read_data_from_kvstore("127.0.0.1", port, "s", "k") == \
                {"a": 1}
        finally:
            kv.shutdown_server()

    def test_auth_token_required(self):
        kv = KVStoreServer(auth_token="s3cret")
        port = kv.start_server()
        try:
            import urllib.error
            import urllib.request

            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/s/k").read()
            assert exc.value.code == 403
            # with the token (as workers get it via HOROVOD_KV_TOKEN):
            os.environ["HOROVOD_KV_TOKEN"] = "s3cret"
            try:
                put_data_into_kvstore("127.0.0.1", port, "s", "k", 42)
                assert read_data_from_kvstore("127.0.0.1", port, "s",
                                              "k") == 42
            finally:
                del os.environ["HOROVOD_KV_TOKEN"]
        finally:
            kv.shutdown_server()

    def test_rendezvous_publishes_slots(self):
        rs = RendezvousServer()
        rs.start_server()
        try:
            slots = hosts_mod.get_host_assignments(
                hosts_mod.parse_hosts("localhost:2"), 2)
            rs.init(slots)
            raw = rs.store.get("rendezvous", "localhost:1")
            assert raw == b"1:2:1:2:0:1"
        finally:
            rs.stop()


WORKER_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd
hvd.init()
out = hvd.allreduce(jnp.full((3,), float(hvd.rank())), op=hvd.Sum)
expected = sum(range(hvd.size()))
assert np.allclose(out, expected), (out, expected)
print(f"OK rank={{hvd.rank()}} size={{hvd.size()}}")
"""


class TestKVBootstrap:
    """The static controller bootstrap (runner/bootstrap.py): rank 0 binds
    its own port and publishes (hostname, ifaces, port); workers resolve a
    routable address by NIC intersection. Reference analogue:
    driver_service.py's interface exchange for static runs."""

    @pytest.fixture()
    def kv(self, monkeypatch):
        server = KVStoreServer(auth_token=None)
        port = server.start_server()
        monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
        monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
        monkeypatch.delenv("HOROVOD_KV_TOKEN", raising=False)
        monkeypatch.delenv("HOROVOD_CONTROLLER_ADDR", raising=False)
        monkeypatch.delenv("HOROVOD_CONTROLLER_PORT", raising=False)
        yield server
        server.shutdown_server()
        # resolve_controller WRITES these into os.environ; delenv on an
        # absent var registers no cleanup, so scrub explicitly or they
        # leak into later tests (observed: jsrun command synthesis).
        os.environ.pop("HOROVOD_CONTROLLER_ADDR", None)
        os.environ.pop("HOROVOD_CONTROLLER_PORT", None)

    def test_worker_uses_reported_port_and_nic_intersection(
            self, kv, monkeypatch):
        """The worker's controller coordinates are exactly what rank 0
        reported — any port the launcher might have believed free is
        irrelevant (the round-2/3 flaw: find_free_port() on the launcher
        host can disagree with the rank-0 host's port space)."""
        import json as _json

        from horovod_tpu.runner import bootstrap, nic

        # Emulate a REMOTE rank 0: hostname that doesn't resolve here, a
        # port nobody on this host could have predicted, two NICs.
        put_data_into_kvstore(
            "127.0.0.1", kv.port, "controller", bootstrap._gen_key(),
            _json.dumps({"hostname": "node-a.cluster.invalid",
                         "port": 45671,
                         "ifaces": [["eth1", "10.0.0.7"],
                                    ["lo", "127.0.0.1"]]}).encode())
        # This worker shares only eth1 with rank 0.
        monkeypatch.setattr(
            nic, "list_interfaces",
            lambda: [("eth1", "10.0.0.9"), ("docker0", "172.17.0.1"),
                     ("lo", "127.0.0.1")])
        bootstrap.resolve_controller(timeout=10)
        assert os.environ["HOROVOD_CONTROLLER_ADDR"] == "10.0.0.7"
        assert os.environ["HOROVOD_CONTROLLER_PORT"] == "45671"

    def test_worker_falls_back_to_hostname_without_intersection(
            self, kv, monkeypatch):
        import json as _json

        from horovod_tpu.runner import bootstrap, nic

        put_data_into_kvstore(
            "127.0.0.1", kv.port, "controller", bootstrap._gen_key(),
            _json.dumps({"hostname": "node-a.cluster.invalid",
                         "port": 45672,
                         "ifaces": [["ib0", "192.168.5.1"]]}).encode())
        monkeypatch.setattr(nic, "list_interfaces",
                            lambda: [("eth0", "10.0.0.9")])
        bootstrap.resolve_controller(timeout=10)
        assert os.environ["HOROVOD_CONTROLLER_ADDR"] == \
            "node-a.cluster.invalid"
        assert os.environ["HOROVOD_CONTROLLER_PORT"] == "45672"

    def test_worker_times_out_without_rank0_report(self, kv, monkeypatch):
        from horovod_tpu.runner import bootstrap

        monkeypatch.setenv("HOROVOD_BOOTSTRAP_TIMEOUT", "0.5")
        with pytest.raises(TimeoutError, match="rank 0"):
            bootstrap.resolve_controller()

    def test_rank0_publishes_bound_port(self, kv, monkeypatch):
        from horovod_tpu.runner import bootstrap

        monkeypatch.delenv("HOROVOD_HOSTNAME", raising=False)
        cb = bootstrap.apply(rank=0)
        assert os.environ["HOROVOD_CONTROLLER_PORT"] == "0"  # Listen(0)
        cb(43219)  # the native watcher reports the real bound port
        import json as _json
        import pickle

        raw = kv.store.get("controller", bootstrap._gen_key())
        info = _json.loads(pickle.loads(raw))
        assert info["port"] == 43219
        assert info["hostname"] == socket.gethostname()

    def test_reinit_ignores_previous_incarnations_report(
            self, kv, monkeypatch):
        """shutdown()+init() re-forms the world; workers must not dial the
        dead listener the previous incarnation published (the static
        analogue of elastic's world_id-versioned port report)."""
        import json as _json

        from horovod_tpu.runner import bootstrap

        put_data_into_kvstore(
            "127.0.0.1", kv.port, "controller", bootstrap._gen_key(),
            _json.dumps({"hostname": "stale.invalid", "port": 1,
                         "ifaces": []}).encode())
        bootstrap.apply(rank=0)  # new generation (rank 1 bumps in lockstep)
        monkeypatch.setenv("HOROVOD_BOOTSTRAP_TIMEOUT", "0.5")
        with pytest.raises(TimeoutError):
            bootstrap.resolve_controller()

    def test_static_launch_never_guesses_controller_ports(
            self, monkeypatch, tmp_path):
        """Launcher-side regression guard: the static path must not call
        find_free_port() for the controller (the guess raced with the
        rank-0 host's port space). launch.py and runner.run() now pass
        controller_port=None; any reintroduced guess trips this."""
        calls = []
        monkeypatch.setattr(network, "find_free_port",
                            lambda: calls.append(1) or 1)
        script = tmp_path / "w.py"
        script.write_text("import os\n"
                          "assert os.environ['HOROVOD_CONTROLLER_BOOTSTRAP'"
                          "] == 'kv'\n"
                          "assert 'HOROVOD_CONTROLLER_PORT' not in "
                          "os.environ\n")
        from horovod_tpu.runner.hosts import (get_host_assignments,
                                              parse_hosts)
        from horovod_tpu.runner.static_run import launch_static

        kv = KVStoreServer(auth_token=None)
        port = kv.start_server()
        try:
            slots = get_host_assignments(parse_hosts("localhost:2"), 2)
            launch_static([sys.executable, str(script)], slots,
                          rendezvous_port=port)
        finally:
            kv.shutdown_server()
        assert calls == []


class TestEndToEnd:
    def test_cli_static_run(self, tmp_path):
        """hvdrun -np 2 python worker.py — full CLI path (reference:
        test_static_run.py)."""
        script = tmp_path / "worker.py"
        script.write_text(WORKER_SCRIPT.format(repo=REPO))
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = REPO
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             sys.executable, str(script)],
            env=env, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK rank=0 size=2" in proc.stdout
        assert "OK rank=1 size=2" in proc.stdout

    def test_cli_failfast_kills_peers(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(
            "import os, sys, time\n"
            "if int(os.environ['HOROVOD_RANK']) == 1: sys.exit(5)\n"
            "time.sleep(60)\n")
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = REPO
        start = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
             sys.executable, str(script)],
            env=env, capture_output=True, text=True, timeout=120)
        assert proc.returncode != 0
        assert time.monotonic() - start < 60  # rank 0 was killed, not waited

    def test_programmatic_run(self):
        """horovod.run-equivalent (reference: test_interactiverun.py).
        Launched in a subprocess so worker env stays clean."""
        driver = textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {REPO!r})
            from horovod_tpu.runner import run

            def fn(base):
                import jax
                jax.config.update("jax_platforms", "cpu")
                import horovod_tpu as hvd
                import jax.numpy as jnp
                hvd.init()
                s = float(hvd.allreduce(jnp.ones(1), op=hvd.Sum)[0])
                return base + hvd.rank(), s

            results = run(fn, args=(100,), np=2)
            assert results == [(100, 2.0), (101, 2.0)], results
            print("RUN_API_OK")
        """)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = REPO
        proc = subprocess.run([sys.executable, "-c", driver], env=env,
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "RUN_API_OK" in proc.stdout
