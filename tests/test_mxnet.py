"""MXNet binding tests (reference analogue: test/parallel/test_mxnet.py).

World-1 semantics run in-process against the fake-mxnet shim
(tests/fake_mxnet.py — MXNet is EOL and uninstallable here, same strategy
as the Ray tests vs fake_ray.py); multi-process numerics run 2 real worker
processes over the native TCP data plane (tests/mxnet_worker.py).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import fake_mxnet  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mxnet_worker.py")


def _mxnet_modules():
    return [n for n in sys.modules
            if n == "mxnet" or n.startswith("mxnet.")
            or n.startswith("horovod_tpu.mxnet")]


@pytest.fixture()
def mx():
    """Install the shim for one test and restore sys.modules exactly
    afterwards — a leaked fake 'mxnet' would break the import-gate tests
    elsewhere in the suite (e.g. test_tensorflow's TestMXNetGate)."""
    saved = {n: sys.modules[n] for n in _mxnet_modules()}
    for n in saved:
        del sys.modules[n]
    mod = fake_mxnet.install()
    yield mod
    for n in _mxnet_modules():
        del sys.modules[n]
    sys.modules.update(saved)


class TestWorldOne:
    def test_allreduce_identity(self, mx):
        import horovod_tpu.mxnet as hvd

        hvd.init()
        t = mx.nd.array(np.arange(6, dtype=np.float32))
        out = hvd.allreduce(t)
        assert np.allclose(out.asnumpy(), np.arange(6))
        out = hvd.allreduce(t, average=False, prescale_factor=2.0)
        assert np.allclose(out.asnumpy(), 2 * np.arange(6))

    def test_allgather_broadcast_alltoall_identity(self, mx):
        import horovod_tpu.mxnet as hvd

        hvd.init()
        t = mx.nd.array(np.ones((2, 3), np.float32))
        assert hvd.allgather(t).shape == (2, 3)
        assert np.allclose(hvd.broadcast(t, 0).asnumpy(), 1.0)
        assert np.allclose(hvd.alltoall(t).asnumpy(), 1.0)
        assert hvd.broadcast_object({"a": 1}) == {"a": 1}
        assert hvd.allgather_object(5) == [5]

    def test_distributed_optimizer_world1(self, mx):
        import horovod_tpu.mxnet as hvd

        hvd.init()
        w = mx.nd.array(np.ones(3, np.float32))
        g = mx.nd.array(np.full(3, 2.0, np.float32))
        opt = hvd.DistributedOptimizer(mx.optimizer.SGD(learning_rate=0.5))
        opt.update(0, w, g, None)
        assert np.allclose(w.asnumpy(), 1.0 - 0.5 * 2.0)
        # delegation surface: setter routes to the wrapped optimizer, and
        # __getattr__ reads back through it
        opt.set_learning_rate(0.1)
        assert opt.lr == 0.1

    def test_predivide_cancels_at_world1(self, mx):
        """gradient_predivide_factor folds f into rescale_grad and 1/f into
        the wire prescale; at world 1 both must still apply so updates match
        the unwrapped optimizer exactly (regression: the early-return skip
        of the prescale left updates scaled by f)."""
        import horovod_tpu.mxnet as hvd

        hvd.init()
        w = mx.nd.array(np.ones(3, np.float32))
        g = mx.nd.array(np.full(3, 2.0, np.float32))
        opt = hvd.DistributedOptimizer(mx.optimizer.SGD(learning_rate=0.5),
                                       gradient_predivide_factor=4.0)
        opt.update(0, w, g, None)
        assert np.allclose(w.asnumpy(), 1.0 - 0.5 * 2.0)

        p = mx.gluon.parameter.Parameter("w")
        p.initialize(np.ones(2, np.float32))
        tr = hvd.DistributedTrainer([p], "sgd", {"learning_rate": 0.5},
                                    gradient_predivide_factor=4.0)
        p.list_grad()[0][:] = np.full(2, 2.0, np.float32)
        tr.step(batch_size=1)
        assert np.allclose(p.data().asnumpy(), 1.0 - 0.5 * 2.0)

    def test_trainer_unwraps_distributed_optimizer(self, mx):
        import horovod_tpu.mxnet as hvd

        hvd.init()
        inner = mx.optimizer.SGD(learning_rate=0.5)
        wrapped = hvd.DistributedOptimizer(inner)
        with pytest.warns(UserWarning, match="unwrapped"):
            trainer = hvd.DistributedTrainer([], wrapped)
        assert trainer._optimizer is inner

    def test_broadcast_parameters_world1_noop(self, mx):
        import horovod_tpu.mxnet as hvd

        hvd.init()
        p = mx.gluon.parameter.Parameter("w")  # never initialized:
        hvd.broadcast_parameters({"w": p})     # world-1 returns before touch

    def test_import_error_without_mxnet(self, monkeypatch):
        for name in [n for n in sys.modules
                     if n.startswith("horovod_tpu.mxnet") or n == "mxnet"
                     or n.startswith("mxnet.")]:
            monkeypatch.delitem(sys.modules, name, raising=False)
        monkeypatch.setitem(sys.modules, "mxnet", None)
        with pytest.raises(ImportError, match="fake_mxnet"):
            import horovod_tpu.mxnet  # noqa: F401


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_world(n, timeout=300):
    port = _free_port()
    procs = []
    for r in range(n):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "PYTHONPATH": REPO,
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": str(n),
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
        })
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs, ok = [], True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            ok = False
        outs.append(out)
        ok = ok and p.returncode == 0
    assert ok, "mxnet worker failures:\n" + "\n----\n".join(outs)


class TestMultiProcess:
    def test_world_2(self):
        _run_world(2)
