"""DistributedOptimizer / tape tests.

Key invariant (the reference's core correctness property): N-way data
parallel training with gradient averaging must match single-device training
on the concatenated global batch (test/parallel/test_torch.py optimizer
tests assert the same)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from jax0437_repros import _old_jax

N = 8


def make_data(rng, n=64, d=5):
    w = rng.randn(d, 1).astype(np.float32)
    x = rng.randn(n, d).astype(np.float32)
    y = x @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
    return x, y


def loss_fn(params, batch):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def init_params(d=5):
    return {"w": jnp.zeros((d, 1)), "b": jnp.zeros((1,))}


def dp_train(tx, steps, x, y):
    """shard_map data-parallel training over the 8-device mesh."""
    params = init_params()
    opt_state = tx.init(params)
    mesh = hvd.mesh()

    @jax.jit
    def step(params, opt_state, xb, yb):
        def spmd_full(params, opt_state, xb, yb):
            loss, grads = jax.value_and_grad(loss_fn)(params, (xb, yb))
            updates, new_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return new_params, new_state, hvd.allreduce(loss)

        rep = jax.tree.map(lambda _: P(), (params, opt_state))
        return hvd.shard_map(
            spmd_full, mesh=mesh,
            in_specs=(rep[0], rep[1], P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(rep[0], rep[1], P()))(params, opt_state, xb, yb)

    bs = x.shape[0] // steps
    for i in range(steps):
        xb = jnp.asarray(x[i * bs:(i + 1) * bs])
        yb = jnp.asarray(y[i * bs:(i + 1) * bs])
        params, opt_state, loss = step(params, opt_state, xb, yb)
    return params


def single_train(tx, steps, x, y):
    params = init_params()
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        grads = jax.grad(loss_fn)(params, (xb, yb))
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    bs = x.shape[0] // steps
    for i in range(steps):
        params, opt_state = step(params, opt_state,
                                 jnp.asarray(x[i * bs:(i + 1) * bs]),
                                 jnp.asarray(y[i * bs:(i + 1) * bs]))
    return params


def test_dp_matches_single_device_global_batch():
    rng = np.random.RandomState(0)
    x, y = make_data(rng, n=8 * 4 * N)
    dist_tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    ref_tx = optax.sgd(0.1)
    p_dist = dp_train(dist_tx, 4, x, y)
    p_ref = single_train(ref_tx, 4, x, y)
    for k in p_ref:
        np.testing.assert_allclose(np.asarray(p_dist[k]),
                                   np.asarray(p_ref[k]), rtol=1e-4, atol=1e-6)


def test_distributed_optimizer_sum_op():
    rng = np.random.RandomState(1)
    x, y = make_data(rng, n=8 * N)
    # op=Sum multiplies the effective lr by N vs Average.
    p_sum = dp_train(hvd.DistributedOptimizer(optax.sgd(0.01), op=hvd.Sum),
                     1, x, y)
    p_avg = dp_train(hvd.DistributedOptimizer(optax.sgd(0.01 * N)), 1, x, y)
    for k in p_sum:
        np.testing.assert_allclose(np.asarray(p_sum[k]),
                                   np.asarray(p_avg[k]), rtol=1e-4, atol=1e-6)


def test_gradient_predivide_factor():
    # predivide splits the averaging divisor (tensorflow/__init__.py:462-476);
    # final result must equal plain averaging.
    rng = np.random.RandomState(2)
    x, y = make_data(rng, n=8 * N)
    p_pre = dp_train(
        hvd.DistributedOptimizer(optax.sgd(0.1),
                                 gradient_predivide_factor=4.0), 1, x, y)
    p_avg = dp_train(hvd.DistributedOptimizer(optax.sgd(0.1)), 1, x, y)
    for k in p_avg:
        np.testing.assert_allclose(np.asarray(p_pre[k]),
                                   np.asarray(p_avg[k]), rtol=1e-4, atol=1e-6)


def test_predivide_requires_average():
    with pytest.raises(ValueError):
        hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd.Sum,
                                 gradient_predivide_factor=2.0)


@pytest.mark.xfail(
    _old_jax(), strict=False,
    reason="upstream jax 0.4.37: optax.MultiSteps selects its accumulate/"
           "apply arms with lax.cond, whose mixed-replication branches "
           "fail old shard_map's rep checker — pure-jax repro: "
           "tests/jax0437_repros.py::repro_cond_rep_mismatch (fixed by "
           "the jax.shard_map graduation, jax >= 0.6; overlap=True uses "
           "the branchless _overlap_multi_steps accumulator, which "
           "traces fine — see test_overlap.py)")
def test_backward_passes_per_step_accumulates():
    # k accumulation steps at lr then one apply ≈ one step on the averaged
    # grads (reference: torch/optimizer.py:133-149). With SGD the result
    # equals a single step with the mean of the k microbatch gradients.
    rng = np.random.RandomState(3)
    x, y = make_data(rng, n=2 * 8 * N)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=2)
    p2 = dp_train(tx, 2, x, y)  # two microbatches → exactly one apply

    # Single big batch with plain averaging must match.
    tx1 = hvd.DistributedOptimizer(optax.sgd(0.1))
    p1 = dp_train(tx1, 1, x, y)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p2[k]), np.asarray(p1[k]),
                                   rtol=1e-4, atol=1e-6)


def test_value_and_grad_allreduces():
    rng = np.random.RandomState(4)
    xs = rng.randn(N, 3).astype(np.float32)

    def f(p, x):
        return jnp.sum(p * x)

    def spmd(p, x):
        val, g = hvd.value_and_grad(f)(p, x[0])
        return g

    out = hvd.shard_map(spmd, mesh=hvd.mesh(),
                        in_specs=(P(), P(hvd.HVD_AXES)),
                        out_specs=P())(jnp.ones(3), jnp.asarray(xs))
    np.testing.assert_allclose(np.asarray(out), xs.mean(0), rtol=1e-5)


def test_distributed_gradient_tape_shim():
    rng = np.random.RandomState(5)
    xs = rng.randn(N, 3).astype(np.float32)

    def f(p, x):
        return jnp.sum(p * x)

    tape = hvd.DistributedGradientTape(f)

    def spmd(p, x):
        loss, g = tape.gradient(p, x[0])
        return g

    out = hvd.shard_map(spmd, mesh=hvd.mesh(),
                        in_specs=(P(), P(hvd.HVD_AXES)),
                        out_specs=P())(jnp.ones(3), jnp.asarray(xs))
    np.testing.assert_allclose(np.asarray(out), xs.mean(0), rtol=1e-5)


def test_grad_has_aux_contract():
    # Regression: hvd.grad(has_aux=True) must return (grads, aux) like
    # jax.grad.
    def f(p):
        return jnp.sum(p ** 2), {"aux": 7}

    g, aux = hvd.grad(f, has_aux=True)(jnp.ones(3))
    np.testing.assert_allclose(np.asarray(g), 2 * np.ones(3))
    assert aux == {"aux": 7}


def test_allreduce_pytree_collective_semantics_on_replicated():
    # Regression: public allreduce_pytree defaults to plain collective
    # semantics — Min on a replicated leaf is the identity, not an error.
    def f(_):
        tree = {"m": jnp.asarray([4.0, 5.0])}
        return hvd.allreduce_pytree(tree, op=hvd.Min)

    out = hvd.shard_map(f, mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                        out_specs=P())(jnp.zeros(N))
    np.testing.assert_array_equal(np.asarray(out["m"]), [4.0, 5.0])


def test_adasum_with_compression():
    # Regression: op=Adasum must honor compression (wire dtype) and still
    # produce float32 output close to the uncompressed result.
    rng = np.random.RandomState(11)
    x = rng.randn(N, 16).astype(np.float32)

    def f(v):
        return hvd.allreduce(v[0], op=hvd.Adasum,
                             compression=hvd.Compression.bf16)

    out = hvd.shard_map(f, mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                        out_specs=P())(jnp.asarray(x))
    ref = hvd.shard_map(lambda v: hvd.allreduce(v[0], op=hvd.Adasum),
                        mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                        out_specs=P())(jnp.asarray(x))
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=5e-2,
                               atol=0.1)
