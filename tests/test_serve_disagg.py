"""Disaggregated serving tests (ISSUE 17, docs/serving.md): the
kv_migrate wire-plan family, copy-on-write prefix caching, the batched
speculative-verify window, and the prefill/decode replica split.

Core invariants:
  * kv_migrate plans validate (one SEND leg; int8 only on DCN/pod
    hops) and their predicted wire bytes equal what the lowering
    charges — including the error-feedback residual doubling;
  * PageAllocator refcounts aliased (COW) pages exactly — a shared
    page returns to the pool only when its LAST reader lets go, even
    under worst-case LIFO preemption churn;
  * the prefix cache shares only FULL prompt pages, first writer wins,
    and eviction never frees a page a live tenant reads;
  * a windowed (W-token) decode step is bit-identical to W chained
    single-token steps — the property that makes greedy speculative
    decoding exact;
  * a disaggregated ReplicaSet (prefill -> kv_migrate -> decode, both
    fp and int8+EF wires, prefix cache and spec decode on) produces
    bit-identical outputs to the symmetric baseline, with zero
    predicted-vs-accounted migration byte drift;
  * the flight recorder's ``serve_cache`` view and the postmortem's
    migration-stall attribution name the replica that idled.

Compiled tests run single-device engines to keep compiles cheap.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.plan import ir
from horovod_tpu.plan.compiler import lower_kv_migrate, quant_wire_bytes
from horovod_tpu.plan.cost import predict_hop_ms, price_kv_migrate
from horovod_tpu.plan.planner import (
    derive_kv_migrate,
    predict_kv_migrate_bytes,
)
from horovod_tpu.serve import kv_cache as kvlib
from horovod_tpu.serve import (
    PageAllocator,
    PageConfig,
    ReplicaAutoscaler,
    ReplicaSet,
    Request,
    Scheduler,
)
from horovod_tpu.serve.engine import GenerationEngine, VirtualClock
from horovod_tpu.serve.kv_cache import PrefixCache

pytestmark = pytest.mark.serve


def tiny_cfg(**over):
    return gpt_tiny(dtype=jnp.float32, num_heads=8, **over)


def tiny_page_cfg(cfg, **over):
    kw = dict(num_pages=96, page_size=4, max_slots=4, pages_per_slot=24,
              num_layers=cfg.num_layers, num_heads=cfg.num_heads,
              head_dim=cfg.d_model // cfg.num_heads)
    kw.update(over)
    return PageConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    params = GPT(cfg).init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, params


# ---------------------------------------------------------------------------
# kv_migrate plan family: validation, byte accounting, pricing


class TestKvMigratePlan:
    def test_level_derivation_and_int8_legality(self):
        # Single host (cross dim 1): ICI hop, int8 forced off.
        ici = derive_kv_migrate(mesh_shape=(1, 4), quantized=True)
        (leg,) = ici.legs
        assert leg.level == ir.ICI and leg.wire_dtype != ir.INT8
        # Cross-host column: DCN hop, int8 + the EF residual by default.
        dcn = derive_kv_migrate(mesh_shape=(2, 4), quantized=True)
        (leg,) = dcn.legs
        assert leg.level == ir.DCN and leg.wire_dtype == ir.INT8
        assert leg.error_feedback
        assert "int8+ef" in dcn.encode()
        # Pod dimension present: pod hop.
        pod = derive_kv_migrate(mesh_shape=(2, 2, 2), quantized=True)
        assert pod.legs[0].level == ir.POD

    def test_exactly_one_send_leg(self):
        plan = derive_kv_migrate(mesh_shape=(2, 4))
        assert plan.collective == "kv_migrate" and len(plan.legs) == 1
        plan.validate()

    def test_predicted_bytes_match_lowering_fp(self):
        plan = derive_kv_migrate(mesh_shape=(2, 4), quantized=False)
        x = np.random.RandomState(0).randn(2, 37, 8, 8).astype(np.float32)
        recv, wire = lower_kv_migrate(plan, x)
        np.testing.assert_array_equal(recv, x)  # fp wire is lossless
        (row,) = predict_kv_migrate_bytes(plan, x.size, 4)
        assert row["bytes"] == wire == x.size * 4.0
        assert row["hop"] == "dcn"

    def test_predicted_bytes_match_lowering_int8_ef(self):
        plan = derive_kv_migrate(mesh_shape=(2, 4), quantized=True,
                                 block=64)
        rs = np.random.RandomState(1)
        for n_tok in (5, 16, 33):  # odd sizes exercise block padding
            x = rs.randn(2, n_tok, 8, 8).astype(np.float32)
            recv, wire = lower_kv_migrate(plan, x)
            (row,) = predict_kv_migrate_bytes(plan, x.size, 4)
            assert row["bytes"] == wire
            # EF residual rides the same wire: 2x the one-pass bytes.
            assert wire == 2.0 * quant_wire_bytes(x.size, 64)
            # Two blockwise-int8 passes reconstruct closely (the EF
            # residual quantizes the first pass's error).
            assert np.max(np.abs(recv - x)) < np.max(np.abs(x)) * 0.05

    def test_price_and_hop_prediction(self):
        plan = derive_kv_migrate(mesh_shape=(2, 4), quantized=True)
        priced = price_kv_migrate(plan, 4096.0, transfers=3,
                                  mesh_shape=(2, 4))
        assert priced["predicted_ms"] > 0
        assert priced["wire_bytes"] > 0
        assert predict_hop_ms("dcn", 1 << 20) > predict_hop_ms("dcn", 1)


# ---------------------------------------------------------------------------
# PageAllocator: COW refcounts and LIFO-preemption worst case


class TestAllocatorCOW:
    def test_aliased_page_freed_at_last_reader(self):
        alloc = PageAllocator(16)
        a = alloc.alloc("a", 3)
        b = alloc.alloc("b", 1, shared=a[:2])
        assert alloc.refcount(a[0]) == 2 and alloc.refcount(a[2]) == 1
        alloc.check_invariants()
        freed = alloc.free("a")
        # Only the exclusive page returns; the aliased two stay granted.
        assert freed == [a[2]]
        assert alloc.refcount(a[0]) == 1
        alloc.check_invariants()
        freed = alloc.free("b")
        # Last reader: both aliased pages AND b's fresh page return.
        assert set(freed) == set(a[:2]) | {b[-1]}
        alloc.check_invariants()
        assert alloc.free_pages == 15  # everything but the null page

    def test_external_hold_keeps_page_granted(self):
        alloc = PageAllocator(8)
        a = alloc.alloc("a", 2)
        alloc.retain([a[0]])          # the prefix cache's pin
        assert alloc.free("a") == [a[1]]
        alloc.check_invariants()
        assert alloc.refcount(a[0]) == 1
        assert alloc.release([a[0]]) == [a[0]]
        alloc.check_invariants()

    def test_check_invariants_catches_double_listing(self):
        alloc = PageAllocator(8)
        a = alloc.alloc("a", 1)
        alloc._owner["a"].append(a[0])  # corrupt: same page twice
        with pytest.raises(AssertionError):
            alloc.check_invariants()

    def test_check_invariants_catches_refcount_drift(self):
        alloc = PageAllocator(8)
        a = alloc.alloc("a", 1)
        b = alloc.alloc("b", 1, shared=a)
        del b
        alloc._refs[a[0]] += 1          # corrupt: phantom reader
        with pytest.raises(AssertionError):
            alloc.check_invariants()

    def test_lifo_preemption_worst_case(self):
        """Admission churn under page pressure: tenants alias one shared
        prefix page, the pool runs dry, and the YOUNGEST tenant is
        repeatedly preempted (freed) and re-admitted. The shared page
        must survive every round with an exact refcount, and no page
        may leak across any number of rounds."""
        alloc = PageAllocator(10)       # null + 9 usable
        prefix = alloc.alloc("prefix_owner", 1)
        alloc.retain(prefix)            # cache pin outlives tenants
        alloc.free("prefix_owner")
        live = []
        for round_ in range(25):
            # Fill until the pool refuses (each tenant: shared + 2).
            i = 0
            while True:
                seq = (round_, i)
                got = alloc.alloc(seq, 2, shared=prefix)
                if got is None:
                    break
                live.append(seq)
                alloc.check_invariants()
                i += 1
            assert alloc.alloc((round_, "x"), alloc.free_pages + 1,
                               shared=prefix) is None
            alloc.check_invariants()
            # LIFO: preempt the youngest admissions first.
            for _ in range(min(2, len(live))):
                victim = live.pop()
                freed = alloc.free(victim)
                assert prefix[0] not in freed
                alloc.check_invariants()
        assert alloc.refcount(prefix[0]) == 1 + len(live)
        for seq in live:
            alloc.free(seq)
        alloc.check_invariants()
        assert alloc.release(prefix) == prefix
        assert alloc.free_pages == 9


# ---------------------------------------------------------------------------
# PrefixCache: full-page sharing, first-writer-wins, safe eviction


class TestPrefixCache:
    def _mk(self, pages=32, ps=4):
        alloc = PageAllocator(pages)
        return alloc, PrefixCache(alloc, ps)

    def test_share_cap_keeps_last_token_private(self):
        _, cache = self._mk()
        # 9 tokens at ps=4: only 2 FULL pages are shareable (the tenant
        # must consume >= 1 prompt token itself).
        assert cache._shareable_pages(list(range(9))) == 2
        assert cache._shareable_pages(list(range(8))) == 1
        assert cache._shareable_pages(list(range(4))) == 0

    def test_insert_lookup_and_stats(self):
        alloc, cache = self._mk()
        prompt = list(range(10, 19))
        pages = alloc.alloc("t0", 3)
        assert cache.insert(prompt, pages) == 2
        hit, matched = cache.lookup(prompt)
        assert hit == pages[:2] and matched == 8
        assert cache.hits == 1 and cache.hit_tokens == 8
        miss, matched = cache.lookup([99] * 9)
        assert miss == [] and matched == 0
        assert cache.lookups == 2 and cache.hit_rate == 0.5

    def test_first_writer_wins(self):
        alloc, cache = self._mk()
        prompt = list(range(20, 29))
        p0 = alloc.alloc("t0", 3)
        p1 = alloc.alloc("t1", 3)
        cache.insert(prompt, p0)
        assert cache.insert(prompt, p1) == 0   # existing nodes kept
        hit, _ = cache.lookup(prompt)
        assert hit == p0[:2]

    def test_eviction_never_frees_live_reader_pages(self):
        alloc, cache = self._mk(pages=16)
        prompt = list(range(30, 39))
        p0 = alloc.alloc("writer", 3)
        cache.insert(prompt, p0)
        alloc.free("writer")               # cache pin keeps the 2 cached
        alloc.check_invariants()
        shared, matched = cache.lookup(prompt)
        reader = alloc.alloc("reader", 1, shared=shared)
        assert cache.evict_unreferenced() == 0  # live reader: untouchable
        assert alloc.refcount(shared[0]) == 2
        alloc.free("reader")
        assert cache.evict_unreferenced() == 2  # now reclaimable
        alloc.check_invariants()
        assert cache.cached_pages == 0

    def test_scheduler_defers_prefix_mate_then_shares(self, model):
        """Two queued requests share a full first page: the scheduler
        admits the first, DEFERS the second while the prefix is
        uncached, then admits it as a COW hit once the first registers
        its prompt pages."""
        cfg, _ = model
        pc = tiny_page_cfg(cfg)
        alloc = PageAllocator(pc.num_pages)
        cache = PrefixCache(alloc, pc.page_size)
        sched = Scheduler(pc, alloc, prefix_cache=cache)
        shared = [7, 8, 9, 10]
        sched.submit(Request(req_id=0, prompt=shared + [11, 12],
                             max_new_tokens=2))
        sched.submit(Request(req_id=1, prompt=shared + [13, 14],
                             max_new_tokens=2))
        slots = sched.admit(0.0)
        assert len(slots) == 1            # mate deferred, not admitted
        assert sched.queue_depth() == 1
        sched.register_prefix(slots[0])   # prefill "completed"
        slots2 = sched.admit(1.0)
        assert len(slots2) == 1
        assert sched.take_prefix_len(slots2[0]) == pc.page_size
        # The mate reads the SAME physical first page (COW alias).
        assert sched.page_table[slots[0]][0] == \
            sched.page_table[slots2[0]][0]
        alloc.check_invariants()


# ---------------------------------------------------------------------------
# Windowed decode: one batched apply == W chained single-token steps


def _cache_with_slots(pc, n_slots, n_tokens):
    alloc = PageAllocator(pc.num_pages)
    cache = kvlib.init_cache(pc)
    table = np.array(cache.page_table)
    for s in range(n_slots):
        pages = alloc.alloc(s, pc.pages_for(n_tokens))
        table[s, :len(pages)] = pages
    return cache._replace(page_table=jnp.asarray(table))


class TestWindowedDecode:
    def test_window_meta_and_advance(self, model):
        cfg, _ = model
        pc = tiny_page_cfg(cfg, max_slots=2)
        cache = _cache_with_slots(pc, 2, 12)
        cache = cache._replace(seq_lens=jnp.asarray([3, 5], jnp.int32))
        valid = jnp.asarray([[True, True, True, False],
                             [True, False, False, False]])
        meta = kvlib.step_meta(cache, valid, page_size=pc.page_size)
        assert meta.write_page.shape == (2, 4)
        np.testing.assert_array_equal(
            np.asarray(meta.attend_len),
            [[4, 5, 6, 1], [6, 1, 1, 1]])
        # Invalid positions write the null page.
        assert int(meta.write_page[0, 3]) == kvlib.NULL_PAGE
        assert int(meta.write_page[1, 1]) == kvlib.NULL_PAGE
        out = kvlib.advance(cache, meta)
        np.testing.assert_array_equal(np.asarray(out.seq_lens), [6, 6])

    def test_windowed_apply_matches_chained(self, model):
        """The batched W-token verify step must match W sequential
        single-token steps: identical greedy argmax at EVERY window
        position (the invariant that makes greedy speculative decoding
        lossless), logits/cache equal to float tolerance (XLA
        vectorizes [S,1,C] and [S,W,C] shapes differently, so raw
        bit-equality across shapes is not a property to demand), and
        exactly-equal sequence lengths."""
        cfg, params = model
        pc = tiny_page_cfg(cfg, max_slots=2)
        rs = np.random.RandomState(3)
        prompt = rs.randint(2, cfg.vocab_size, size=(2, 6))
        W = 3    # same window shape as the partial-validity test below
        window = rs.randint(2, cfg.vocab_size, size=(2, W))

        def single(tokens, cache, active):
            return GPT(cfg).apply({"params": params},
                                  jnp.asarray(tokens, jnp.int32),
                                  cache=cache, active=jnp.asarray(active))

        # Shared warm state: both slots prefilled token by token.
        cache = _cache_with_slots(pc, 2, prompt.shape[1] + W)
        for t in range(prompt.shape[1]):
            _, cache = single(prompt[:, t], cache, [True, True])

        # Path A: W chained single-token steps.
        seq_cache = cache
        seq_logits = []
        for w in range(W):
            lg, seq_cache = single(window[:, w], seq_cache, [True, True])
            seq_logits.append(np.asarray(lg))
        seq_logits = np.stack(seq_logits, axis=1)       # [S, W, V]

        # Path B: ONE batched windowed apply.
        win_logits, win_cache = GPT(cfg).apply(
            {"params": params}, jnp.asarray(window, jnp.int32),
            cache=cache, active=jnp.ones((2, W), bool))

        win_logits = np.asarray(win_logits)
        np.testing.assert_array_equal(win_logits.argmax(-1),
                                      seq_logits.argmax(-1))
        np.testing.assert_allclose(win_logits, seq_logits,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(win_cache.seq_lens),
                                      np.asarray(seq_cache.seq_lens))
        np.testing.assert_allclose(np.asarray(win_cache.k),
                                   np.asarray(seq_cache.k),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(win_cache.v),
                                   np.asarray(seq_cache.v),
                                   rtol=1e-5, atol=1e-6)

    def test_windowed_apply_partial_validity(self, model):
        """Contiguous-prefix validity: a slot with fewer valid window
        positions advances by its own count and its valid logits match
        the chained path exactly."""
        cfg, params = model
        pc = tiny_page_cfg(cfg, max_slots=2)
        rs = np.random.RandomState(4)
        prompt = rs.randint(2, cfg.vocab_size, size=(2, 5))
        window = rs.randint(2, cfg.vocab_size, size=(2, 3))
        valid = np.array([[True, True, True], [True, False, False]])

        def single(tokens, cache, active):
            return GPT(cfg).apply({"params": params},
                                  jnp.asarray(tokens, jnp.int32),
                                  cache=cache, active=jnp.asarray(active))

        cache = _cache_with_slots(pc, 2, prompt.shape[1] + 3)
        for t in range(prompt.shape[1]):
            _, cache = single(prompt[:, t], cache, [True, True])

        seq_cache = cache
        seq_logits = []
        for w in range(3):
            lg, seq_cache = single(window[:, w], seq_cache, valid[:, w])
            seq_logits.append(np.asarray(lg))

        win_logits, win_cache = GPT(cfg).apply(
            {"params": params}, jnp.asarray(window, jnp.int32),
            cache=cache, active=jnp.asarray(valid))
        np.testing.assert_array_equal(np.asarray(win_cache.seq_lens),
                                      np.asarray(seq_cache.seq_lens))
        for w in range(3):
            for s in range(2):
                if valid[s, w]:
                    got = np.asarray(win_logits[s, w])
                    want = seq_logits[w][s]
                    assert got.argmax() == want.argmax()
                    np.testing.assert_allclose(got, want,
                                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# Engine / ReplicaSet: spec-decode parity, migration bit-exactness,
# prefix hits, demand-split autoscaling


def _mkreqs(n=8, shared_len=9, tail=3, new=8, seed=0):
    rs = np.random.RandomState(seed)
    shared = [int(t) for t in rs.randint(2, 100, shared_len)]
    return [Request(req_id=i,
                    prompt=shared + [int(t) for t in
                                     rs.randint(2, 100, tail)],
                    max_new_tokens=new, arrival_time=float(3 * i))
            for i in range(n)]


def _outs(stats):
    return {r.req_id: list(r.generated) for r in stats.completed}


class TestEngineSpecDecode:
    def test_greedy_spec_parity_bit_identical(self, model):
        cfg, params = model
        pc = tiny_page_cfg(cfg)
        dev = [jax.devices()[0]]
        outs = []
        for spec_k in (0, 3):
            eng = GenerationEngine(cfg, params, pc, devices=dev,
                                   spec_k=spec_k)
            for r in _mkreqs(4, seed=5):
                eng.submit(Request(req_id=r.req_id,
                                   prompt=list(r.prompt),
                                   max_new_tokens=r.max_new_tokens))
            clock = VirtualClock()
            steps = 0
            while (eng.queue_depth() or eng.in_flight()) and steps < 500:
                eng.step(clock.now)
                clock.tick()
                steps += 1
            outs.append(_outs(eng.stats))
        assert outs[0] and outs[0] == outs[1]
        # The drafter must actually have verified something in a window.
        assert eng._spec_proposed > 0 and eng._spec_accepted > 0

    def test_spec_metrics_counted(self, model):
        cfg, params = model
        pc = tiny_page_cfg(cfg)
        eng = GenerationEngine(cfg, params, pc,
                               devices=[jax.devices()[0]], spec_k=2)
        eng.submit(Request(req_id=0, prompt=[3, 3, 3, 3, 3, 3],
                           max_new_tokens=12))
        clock = VirtualClock()
        for _ in range(60):
            if not (eng.queue_depth() or eng.in_flight()):
                break
            eng.step(clock.now)
            clock.tick()
        # A constant prompt makes the n-gram drafter near-perfect.
        assert eng._spec_accepted > 0
        assert eng._spec_accepted <= eng._spec_proposed


class TestDisaggReplicaSet:
    def _run(self, cfg, params, pc, reqs, **kw):
        rset = ReplicaSet(cfg, params, pc, **kw)
        stats = rset.run(reqs, clock=VirtualClock())
        return rset, stats

    def test_migration_bit_exact_fp_and_int8_ef(self, model):
        """Both wire flavors against ONE shared symmetric baseline:
        fp on an ICI-class mesh (lossless), then int8+EF with the
        prefix cache and spec decoding on a DCN-class mesh — every
        greedy output dict-equal to the undisturbed run."""
        cfg, params = model
        pc = tiny_page_cfg(cfg)
        devs = jax.devices()[:2]
        _, sym = self._run(cfg, params, pc, _mkreqs(6), n_replicas=2,
                           devices=devs)

        dis, d_stats = self._run(
            cfg, params, pc, _mkreqs(6), n_replicas=2, devices=devs,
            disagg=(1, 1), kv_mesh_shape=(1, 2))
        assert _outs(d_stats) == _outs(sym)
        assert dis.kv_migrations > 0
        # ICI mesh: fp wire (int8 would be illegal on this hop).
        assert dis.kv_plan.legs[0].wire_dtype != ir.INT8
        assert dis.kv_migration_bytes == dis.kv_migration_fp_bytes

        dis, d_stats = self._run(
            cfg, params, pc, _mkreqs(6), n_replicas=2, devices=devs,
            disagg=(1, 1), prefix_cache=True, spec_k=3,
            kv_migrate_quantized=True, kv_mesh_shape=(2, 2))
        assert _outs(d_stats) == _outs(sym)
        assert "int8+ef" in dis.kv_plan.encode()
        assert dis.kv_migrations > 0
        # The quantized wire must actually compress vs fp.
        assert dis.kv_migration_bytes < dis.kv_migration_fp_bytes
        # Prefix cache engaged across tenants.
        cache = dis.prefill_engines[0].prefix_cache
        assert cache.hits > 0 and cache.hit_tokens > 0
        # Spec decoding engaged on the decode replica.
        dec = dis.decode_engines[0]
        assert dec._spec_accepted > 0
        # Zero predicted-vs-accounted drift, event by event.
        predicted = sum(e["predicted_bytes"] for e in
                        dis.migration_events)
        assert abs(predicted - dis.kv_migration_bytes) < 1e-6
        for ev in dis.migration_events:
            assert ev["hop"] in ("ici", "dcn", "pod")
            assert ev["predicted_ms"] > 0

    @pytest.mark.slow
    def test_no_cross_tenant_leak_through_shared_pages(self, model):
        """Tenants aliasing a quantized-migrated prefix must still match
        the symmetric baseline EXACTLY — the scatter skips shared pages,
        so one tenant's (lossy) migrated KV can never perturb another's
        reads."""
        cfg, params = model
        pc = tiny_page_cfg(cfg)
        devs = jax.devices()[:2]
        reqs = _mkreqs(6, shared_len=13, tail=2, seed=9)
        _, sym = self._run(cfg, params, pc,
                           [Request(req_id=r.req_id,
                                    prompt=list(r.prompt),
                                    max_new_tokens=r.max_new_tokens,
                                    arrival_time=r.arrival_time)
                            for r in reqs],
                           n_replicas=2, devices=devs)
        _, d_stats = self._run(
            cfg, params, pc, reqs, n_replicas=2, devices=devs,
            disagg=(1, 1), prefix_cache=True,
            kv_migrate_quantized=True, kv_mesh_shape=(2, 2))
        assert _outs(d_stats) == _outs(sym)

    @pytest.mark.slow
    def test_demand_split_autoscaler(self, model):
        cfg, params = model
        pc = tiny_page_cfg(cfg)
        rset = ReplicaSet(cfg, params, pc, n_replicas=4,
                          devices=jax.devices()[:4], disagg=(2, 2),
                          prefix_cache=True, kv_mesh_shape=(2, 2))
        auto = ReplicaAutoscaler(rset, min_replicas=4, max_replicas=4,
                                 split_min_tokens=50)
        stats = rset.run(_mkreqs(8, new=10, seed=11),
                         clock=VirtualClock(), autoscaler=auto)
        assert len(stats.completed) == 8
        # The measured prefill:decode demand drove at least one re-split
        # decision, and the final split still covers both roles.
        assert auto.decisions
        p, d = rset._disagg
        assert p >= 1 and d >= 1 and p + d == 4


# ---------------------------------------------------------------------------
# Flight serve_cache view + postmortem migration-stall attribution


def _load_postmortem():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "postmortem.py")
    spec = importlib.util.spec_from_file_location("_postmortem_disagg",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestServeCacheForensics:
    def test_flight_dump_carries_serve_cache_view(self, tmp_path):
        from horovod_tpu import monitor
        from horovod_tpu.monitor.flight import FlightRecorder

        reg = monitor.metrics()
        reg.gauge("serve.prefix_hit_rate").set(0.75)
        reg.counter("serve.kv.migrations").inc(4)
        reg.counter("serve.kv.stall_steps_by", replica="decode1").inc(6)
        reg.counter("comm.kv.bytes", hop="dcn").inc(1234.0)
        fr = FlightRecorder(capacity=32, snapshot_every=0)
        fr.record("FLIGHT:SERVE_STEP", tid="flight",
                  args={"engine": "decode1", "step": 1})
        dump = fr.build_dump("test")
        view = dump.get("serve_cache") or {}
        assert view.get("serve.prefix_hit_rate") == 0.75
        assert view.get("serve.kv.migrations", 0) >= 4
        assert view.get("kv_bytes", {}).get("dcn", 0) >= 1234.0
        assert view.get("stall_steps_by_replica", {}).get(
            "decode1", 0) >= 6

    def test_postmortem_names_migration_stalled_replica(self, tmp_path):
        from horovod_tpu.monitor.flight import FlightRecorder

        pm = _load_postmortem()
        fr = FlightRecorder(capacity=16, snapshot_every=0)
        fr.record("FLIGHT:SERVE_STEP", tid="flight",
                  args={"engine": "decode0", "step": 3})
        dump = fr.build_dump("watchdog_abort")
        dump["serve_cache"] = {
            "serve.prefix_hit_rate": 0.5,
            "stall_steps_by_replica": {"decode0": 9.0, "decode1": 1.0},
        }
        path = tmp_path / "flight_rank0.json"
        path.write_text(json.dumps(dump))
        report = pm.build_report(str(tmp_path))
        named = report["migration_stalled_replica"]
        assert named and named["replica"] == "decode0"
        assert named["stall_steps"] == 9.0
        assert report["serve_cache"]["serve.prefix_hit_rate"] == 0.5
