"""Interleaved-1F1B pipeline parallelism (docs/pipeline.md).

The schedule family must be exact (or documented-ulp) against the dense
model through gradients, the send legs must validate/lower/account like
every other wire-plan leg, and the pp knobs must ride the autotune and
checkpoint machinery (schema v8; stage-count restore guard).
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.parallel.pipeline import (
    PPSchedule,
    _send_plan_for_axis,
    build_interleaved_schedule,
    pipelined_gpt_train,
    pp_split_chunks,
)
from horovod_tpu.plan import (
    PlanError,
    SEND,
    Leg,
    WirePlan,
    pp_bubble_bound,
    send_plan,
)


# ---------------------------------------------------------------------------
# IR: the send primitive.
# ---------------------------------------------------------------------------


class TestSendIR:
    def test_send_plan_encodes(self):
        p = send_plan("dcn", quantized=True, block=256,
                      error_feedback=True)
        assert p.encode() == "send:dcn.send[int8/256+ef]|s1|sync"
        assert send_plan("ici").encode() == "send:ici.send[payload]|s1|sync"

    def test_int8_on_ici_send_rejected(self):
        with pytest.raises(PlanError, match="non-DCN"):
            WirePlan("send", (Leg("ici", SEND, "int8", block=256),)
                     ).validate()

    def test_send_leg_outside_send_plan_rejected(self):
        with pytest.raises(PlanError, match="only belongs to a 'send'"):
            WirePlan("allreduce", (Leg("dcn", SEND),)).validate()

    def test_non_send_leg_inside_send_plan_rejected(self):
        with pytest.raises(PlanError, match="only send legs"):
            WirePlan("send", (Leg("dcn", "psum"),)).validate()

    def test_multi_leg_send_plan_rejected(self):
        with pytest.raises(PlanError, match="exactly ONE hop"):
            WirePlan("send", (Leg("dcn", SEND), Leg("ici", SEND))
                     ).validate()

    def test_flat_and_pallas_send_rejected(self):
        with pytest.raises(PlanError, match="LINK CLASS"):
            WirePlan("send", (Leg("flat", SEND),)).validate()
        with pytest.raises(PlanError, match="pallas"):
            WirePlan("send", (Leg("dcn", SEND, backend="pallas"),)
                     ).validate()

    def test_send_level_from_axis(self):
        assert _send_plan_for_axis(hvd.LOCAL_AXIS).legs[0].level == "ici"
        assert _send_plan_for_axis(hvd.HVD_AXES).legs[0].level == "dcn"
        # quantization is forced off on an ICI-class hop
        p = _send_plan_for_axis(hvd.LOCAL_AXIS, quantized=True)
        assert not p.is_quantized


# ---------------------------------------------------------------------------
# The schedule builder.
# ---------------------------------------------------------------------------


class TestSchedule:
    def test_units_complete_and_unique(self):
        M, n, v = 8, 4, 2
        s = build_interleaved_schedule(M, n, v)
        K = n * v
        # every (m, chunk) F and B lands exactly once, on its owner rank
        seen_f, seen_b = set(), set()
        for r in range(n):
            for t in range(s.ticks):
                if s.f_valid[r, t]:
                    c = s.f_j[r, t] * n + r
                    assert (s.f_m[r, t], c) not in seen_f
                    seen_f.add((s.f_m[r, t], c))
                if s.b_valid[r, t]:
                    c = s.b_j[r, t] * n + r
                    assert (s.b_m[r, t], c) not in seen_b
                    seen_b.add((s.b_m[r, t], c))
        assert seen_f == {(m, c) for m in range(M) for c in range(K)}
        assert seen_b == seen_f
        assert s.unit_count() == 2 * M * K

    def test_dependencies_respect_hop_latency(self):
        M, n, v = 8, 4, 2
        s = build_interleaved_schedule(M, n, v)
        K = n * v
        done_f, done_b = {}, {}
        for r in range(n):
            for t in range(s.ticks):
                if s.f_valid[r, t]:
                    done_f[(s.f_m[r, t], s.f_j[r, t] * n + r)] = t
                if s.b_valid[r, t]:
                    done_b[(s.b_m[r, t], s.b_j[r, t] * n + r)] = t
        for (m, c), t in done_f.items():
            if c > 0:
                assert done_f[(m, c - 1)] <= t - 1, (m, c)
        for (m, c), t in done_b.items():
            if c == K - 1:
                assert done_f[(m, c)] <= t - 1, (m, c)
            else:
                assert done_b[(m, c + 1)] <= t - 1, (m, c)

    def test_interleave_beats_gpipe_bound(self):
        # v = 1 (plain 1F1B) sits exactly AT the bound; v >= 2 beats it.
        for (M, n) in ((8, 4), (16, 4), (8, 2)):
            s1 = build_interleaved_schedule(M, n, 1)
            assert s1.bubble_fraction == pytest.approx(
                pp_bubble_bound(n, M), abs=1e-9)
            s2 = build_interleaved_schedule(M, n, 2)
            assert s2.bubble_fraction < pp_bubble_bound(n, M)
            # the Megatron interleaved bubble (S-1)/(Mv+S-1)
            assert s2.bubble_fraction == pytest.approx(
                (n - 1) / (M * 2 + n - 1), abs=1e-9)

    def test_microbatch_divisibility_enforced(self):
        with pytest.raises(ValueError, match="divisible"):
            build_interleaved_schedule(6, 4, 2)
        build_interleaved_schedule(6, 4, 1)  # v=1: any M is legal


# ---------------------------------------------------------------------------
# The zero-bubble family (zb1): B/W split + fill-tick capacity.
# ---------------------------------------------------------------------------


class TestZeroBubbleSchedule:
    def test_unknown_family_rejected(self):
        with pytest.raises(ValueError, match="unknown schedule family"):
            build_interleaved_schedule(8, 4, 1, family="zb2")

    def test_zb_units_complete_and_w_after_b(self):
        """Every (m, chunk) gets exactly one W unit, strictly after its
        B (dw consumes the grads B stashed), on the owner rank."""
        M, n, v = 8, 4, 2
        s = build_interleaved_schedule(M, n, v, family="zb1")
        K = n * v
        done_b, done_w = {}, {}
        for r in range(n):
            for t in range(s.ticks):
                assert not (s.f_valid[r, t] and s.w_valid[r, t])
                assert not (s.b_valid[r, t] and s.w_valid[r, t])
                if s.b_valid[r, t]:
                    done_b[(s.b_m[r, t], s.b_j[r, t] * n + r)] = t
                if s.w_valid[r, t]:
                    key = (s.w_m[r, t], s.w_j[r, t] * n + r)
                    assert key not in done_w, key
                    done_w[key] = t
        assert set(done_w) == {(m, c) for m in range(M)
                               for c in range(K)}
        for key, t in done_w.items():
            assert done_b[key] < t, key
        assert s.unit_count() == 3 * M * K
        assert s.units_per_rank == 3 * M * v

    def test_zb_bubble_strictly_below_1f1b(self):
        """The tentpole claim: on the same (S, M, v) the measured zb1
        bubble is strictly below the interleaved-1F1B bubble."""
        for (M, n, v) in ((8, 2, 1), (8, 4, 1), (16, 4, 4)):
            s1 = build_interleaved_schedule(M, n, v)
            sz = build_interleaved_schedule(M, n, v, family="zb1")
            assert sz.bubble_fraction < s1.bubble_fraction, (M, n, v)
            # and still below the GPipe bound, trivially
            assert sz.bubble_fraction < pp_bubble_bound(n, M)

    def test_zb_fill_ticks_enumerate_the_idle_grid(self):
        """fill_ticks[r, t] numbers rank r's idle ticks 0..cap-1 and is
        -1 on every busy tick — the T3 fill-capacity contract the
        ZeRO-3 flights are credited against (rank-uniform)."""
        for family in ("1f1b", "zb1"):
            s = build_interleaved_schedule(8, 4, 1, family=family)
            for r in range(s.stages):
                ks = []
                for t in range(s.ticks):
                    busy = bool(s.f_valid[r, t]) or bool(s.b_valid[r, t])
                    if s.w_valid is not None:
                        busy = busy or bool(s.w_valid[r, t])
                    if busy:
                        assert s.fill_ticks[r, t] == -1
                    else:
                        ks.append(int(s.fill_ticks[r, t]))
                assert ks == list(range(len(ks)))
                assert len(ks) == s.idle_ticks_per_rank


# ---------------------------------------------------------------------------
# Exactness: the schedule family vs the dense model, through gradients.
# ---------------------------------------------------------------------------


def _setup_gpt(L, B, T, seed):
    cfg = gpt_tiny(dtype=jnp.float32, num_layers=L)
    rs = np.random.RandomState(seed)
    tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
    targets = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
    params = GPT(cfg).init(jax.random.PRNGKey(0), tokens)["params"]
    return cfg, params, tokens, targets


def _dense_ref(cfg, params, tokens, targets):
    def loss_fn(p):
        logits = GPT(cfg).apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    return jax.value_and_grad(loss_fn)(params)


class TestInterleavedParity:
    def _train(self, cfg, chunks, rest, tokens, targets, *, axis, n, v,
               M, schedule, send_plan_=None, dp_axes=None):
        mesh = hvd.mesh()

        def spmd(cp, rst, tok, tgt):
            local = jax.tree.map(lambda a: a[0], cp)
            loss, g_cp, g_rest = pipelined_gpt_train(
                cfg, local, rst, tok, tgt, axis=axis,
                num_microbatches=M, schedule=schedule, interleave=v,
                send_plan=send_plan_)
            if dp_axes:
                loss = hvd.allreduce(loss, op=hvd.Average, axes=dp_axes)
                g_cp = hvd.allreduce_pytree(g_cp, op=hvd.Average,
                                            axes=dp_axes)
                g_rest = hvd.allreduce_pytree(g_rest, op=hvd.Average,
                                              axes=dp_axes)
            return loss, jax.tree.map(lambda a: a[None], g_cp), g_rest

        in_data = P(dp_axes) if dp_axes else P()
        return jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(axis), P(), in_data, in_data),
            out_specs=(P(), P(axis), P())))(chunks, rest, tokens,
                                            targets)

    def test_interleaved_matches_dense_and_gpipe(self):
        """Interleaved-1F1B == 1F1B == GPipe == the dense model: loss
        and gradients (chunk blocks, tied embedding/head) within
        documented fp tolerance. DP over hvd_cross x PP over hvd_local
        — the 2-D composition users run at scale."""
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 4))
            n, v, M = 4, 2, 4
            cfg, params, tokens, targets = _setup_gpt(
                L=n * v, B=2 * M, T=16, seed=0)
            want_loss, g_dense = _dense_ref(cfg, params, tokens, targets)
            chunks, rest = pp_split_chunks(params, n, v)
            chunks1, _ = pp_split_chunks(params, n, 1)

            results = {}
            for sched, cp, vv in (("gpipe", chunks1, 1),
                                  ("1f1b", chunks1, 1),
                                  ("interleaved_1f1b", chunks, v)):
                loss, g_cp, g_rest = self._train(
                    cfg, cp, rest, tokens, targets, axis=hvd.LOCAL_AXIS,
                    n=n, v=vv, M=M, schedule=sched,
                    dp_axes=hvd.CROSS_AXIS)
                results[sched] = (loss, g_cp, g_rest)
                np.testing.assert_allclose(float(loss), float(want_loss),
                                           rtol=3e-5)
                np.testing.assert_allclose(
                    np.asarray(g_rest["wte"]), np.asarray(g_dense["wte"]),
                    rtol=1e-3, atol=1e-6)

            # interleaved chunk grads == the dense per-block grads:
            # rank r's local chunk j is global chunk c = j*n + r.
            _, g_cp, _ = results["interleaved_1f1b"]
            for (r, j) in ((0, 0), (n - 1, v - 1)):
                got = jax.tree.map(lambda a: np.asarray(a[r, j, 0]), g_cp)
                want = jax.tree.map(np.asarray, g_dense[f"h{j * n + r}"])
                jax.tree.map(
                    lambda a, b: np.testing.assert_allclose(
                        a, b, rtol=1e-3, atol=1e-6), got, want)
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_zb1_matches_dense(self):
        """zb1 == the dense model: the B/W split changes WHEN dw runs,
        never WHAT it computes — loss and per-block gradients at the
        same documented tolerance as interleaved-1F1B."""
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 4))
            n, v, M = 4, 2, 4
            cfg, params, tokens, targets = _setup_gpt(
                L=n * v, B=2 * M, T=16, seed=4)
            want_loss, g_dense = _dense_ref(cfg, params, tokens, targets)
            chunks, rest = pp_split_chunks(params, n, v)
            loss, g_cp, g_rest = self._train(
                cfg, chunks, rest, tokens, targets, axis=hvd.LOCAL_AXIS,
                n=n, v=v, M=M, schedule="zb1", dp_axes=hvd.CROSS_AXIS)
            np.testing.assert_allclose(float(loss), float(want_loss),
                                       rtol=3e-5)
            np.testing.assert_allclose(
                np.asarray(g_rest["wte"]), np.asarray(g_dense["wte"]),
                rtol=1e-3, atol=1e-6)
            for (r, j) in ((0, 0), (n - 1, v - 1)):
                got = jax.tree.map(lambda a: np.asarray(a[r, j, 0]), g_cp)
                want = jax.tree.map(np.asarray, g_dense[f"h{j * n + r}"])
                jax.tree.map(
                    lambda a, b: np.testing.assert_allclose(
                        a, b, rtol=1e-3, atol=1e-6), got, want)
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_quantized_ef_send_wire(self):
        """The int8+EF activation wire: loss within the blockwise
        quantization error bound of the exact wire (documented
        tolerance; the residual carries each hop's error forward)."""
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 4))
            n, v, M = 4, 2, 4
            cfg, params, tokens, targets = _setup_gpt(
                L=n * v, B=2 * M, T=16, seed=1)
            want_loss, _ = _dense_ref(cfg, params, tokens, targets)
            chunks, rest = pp_split_chunks(params, n, v)
            # hvd_local is ICI-class; force a DCN-level plan to exercise
            # the quantized lowering (the wire, not the topology, is
            # under test).
            sp = send_plan("dcn", quantized=True, block=256,
                           error_feedback=True)
            loss, _, _ = self._train(
                cfg, chunks, rest, tokens, targets, axis=hvd.LOCAL_AXIS,
                n=n, v=v, M=M, schedule="interleaved_1f1b",
                send_plan_=sp, dp_axes=hvd.CROSS_AXIS)
            rel = abs(float(loss) - float(want_loss)) / abs(
                float(want_loss))
            assert rel < 1e-3, rel
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())


class TestPPMesh:
    """The dedicated hvd_pp mesh axis."""

    def test_pp_mesh_geometry(self):
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 2),
                     pp_stages=2)
            assert hvd.pp_size() == 2
            assert hvd.pod_size() == 1
            assert hvd.data_mesh_shape() == (2, 2)
            assert hvd.mesh().axis_names == (hvd.PP_AXIS, hvd.CROSS_AXIS,
                                             hvd.LOCAL_AXIS)
            # data axes exclude the pp axis
            from horovod_tpu.common import basics

            assert basics.world_axes() == hvd.HVD_AXES
            assert "pp2" in basics.mesh_geometry()
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_compose_zero2_on_pp_mesh(self):
        """pp x ZeRO-2: one pipelined SGD-momentum step on the hvd_pp
        mesh equals the dense single-device step (per-stage shard
        worlds = the data world)."""
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(1, 4),
                     pp_stages=2)
            mesh = hvd.mesh()
            n, v, M = 2, 2, 4
            cfg, params, tokens, targets = _setup_gpt(
                L=n * v, B=4 * M, T=8, seed=2)
            chunks, rest = pp_split_chunks(params, n, v)
            tx = hvd.DistributedOptimizer(
                optax.sgd(0.1, momentum=0.9), zero_stage=2,
                pp_stages=n, pp_microbatches=M,
                pp_schedule="interleaved_1f1b", pp_interleave=v)
            pspec = {"chunks": jax.tree.map(lambda _: P(hvd.PP_AXIS),
                                            chunks),
                     "rest": jax.tree.map(lambda _: P(), rest)}
            PPALL = (hvd.PP_AXIS,) + hvd.HVD_AXES
            sspec_of = lambda st: jax.tree.map(  # noqa: E731
                lambda l: P(PPALL) if getattr(l, "ndim", 0) >= 1
                else P(), st)
            state_tpl = tx.init(
                {"chunks": jax.tree.map(lambda a: a[0], chunks),
                 "rest": rest})

            def init_spmd(pt):
                return tx.init(
                    {"chunks": jax.tree.map(lambda a: a[0],
                                            pt["chunks"]),
                     "rest": pt["rest"]})

            ptree = {"chunks": chunks, "rest": rest}
            state = jax.jit(hvd.shard_map(
                init_spmd, mesh=mesh, in_specs=(pspec,),
                out_specs=sspec_of(state_tpl)))(ptree)
            sspec = sspec_of(state)

            def step_spmd(pt, st, tok, tgt):
                local_c = jax.tree.map(lambda a: a[0], pt["chunks"])
                loss, g_cp, g_rest = pipelined_gpt_train(
                    cfg, local_c, pt["rest"], tok, tgt,
                    axis=hvd.PP_AXIS, num_microbatches=M,
                    schedule="interleaved_1f1b", interleave=v)
                local = {"chunks": local_c, "rest": pt["rest"]}
                upd, st2 = tx.update({"chunks": g_cp, "rest": g_rest},
                                     st, local)
                new = optax.apply_updates(local, upd)
                loss = hvd.allreduce(loss, op=hvd.Average)
                # Re-establish the rest tree's pp replication by
                # construction (the buckets mixed pp-varying chunk
                # leaves into the gather; every stage holds the same
                # rest values).
                from jax import lax

                rpp = lax.axis_index(hvd.PP_AXIS)
                new_rest = jax.tree.map(
                    lambda a: lax.psum(
                        jnp.where(rpp == 0, a, jnp.zeros_like(a)),
                        hvd.PP_AXIS), new["rest"])
                return loss, {"chunks": jax.tree.map(
                    lambda a: a[None], new["chunks"]),
                    "rest": new_rest}, st2

            data = P(hvd.HVD_AXES)
            step = jax.jit(hvd.shard_map(
                step_spmd, mesh=mesh,
                in_specs=(pspec, sspec, data, data),
                out_specs=(P(), pspec, sspec)))
            loss, ptree, state = step(ptree, state, tokens, targets)

            # dense reference: one SGD-momentum step on the mean grads
            want_loss, g_dense = _dense_ref(cfg, params, tokens, targets)
            np.testing.assert_allclose(float(loss), float(want_loss),
                                       rtol=3e-5)
            ref_tx = optax.sgd(0.1, momentum=0.9)
            upd, _ = ref_tx.update(g_dense, ref_tx.init(params), params)
            want_p = optax.apply_updates(params, upd)
            got_rest = jax.device_get(ptree["rest"])
            np.testing.assert_allclose(
                np.asarray(got_rest["wte"]), np.asarray(want_p["wte"]),
                rtol=2e-4, atol=2e-6)
            # a chunk leaf: rank 0 chunk 0 == dense block h0
            got_c = jax.tree.map(lambda a: np.asarray(a[0, 0, 0]),
                                 jax.device_get(ptree["chunks"]))
            want_c = jax.tree.map(np.asarray, want_p["h0"])
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=2e-4, atol=2e-6), got_c, want_c)
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_pp_knob_validation(self):
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(1, 4),
                     pp_stages=2)
            with pytest.raises(ValueError, match="disagrees with"):
                hvd.DistributedOptimizer(optax.sgd(0.1), pp_stages=4)
            with pytest.raises(ValueError, match="unknown pp_schedule"):
                hvd.DistributedOptimizer(optax.sgd(0.1), pp_stages=2,
                                         pp_schedule="zigzag")
            with pytest.raises(ValueError, match="divide"):
                hvd.DistributedOptimizer(
                    optax.sgd(0.1), pp_stages=2, pp_microbatches=5,
                    pp_interleave=2)
            # a legal composition builds
            hvd.DistributedOptimizer(optax.sgd(0.1), pp_stages=2,
                                     pp_microbatches=8, pp_interleave=2)
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())


# ---------------------------------------------------------------------------
# Accounting + spans.
# ---------------------------------------------------------------------------


class TestAccounting:
    def _trace_interleaved(self, send_plan_=None,
                           schedule="interleaved_1f1b"):
        n, v, M = 4, 2, 4
        cfg, params, tokens, targets = _setup_gpt(L=n * v, B=2 * M, T=8,
                                                  seed=3)
        chunks, rest = pp_split_chunks(params, n, v)
        mesh = hvd.mesh()

        def spmd(cp, rst, tok, tgt):
            local = jax.tree.map(lambda a: a[0], cp)
            loss, g_cp, g_rest = pipelined_gpt_train(
                cfg, local, rst, tok, tgt, axis=hvd.LOCAL_AXIS,
                num_microbatches=M, schedule=schedule,
                interleave=v, send_plan=send_plan_)
            loss = hvd.allreduce(loss, op=hvd.Average,
                                 axes=hvd.CROSS_AXIS)
            return loss

        f = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.CROSS_AXIS),
                      P(hvd.CROSS_AXIS)),
            out_specs=P()))
        with hvd.record_wire_stats() as ws:
            f.lower(chunks, rest, tokens, targets)
        return ws, n, v, M, cfg, tokens

    def test_send_bytes_accounted(self):
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 4))
            ws, n, v, M, cfg, tokens = self._trace_interleaved()
            sched = build_interleaved_schedule(M, n, v)
            # per-tick cyclic hops: one activation (payload dtype) + one
            # grad (f32) per rank, repeats = ticks; the per-shard
            # microbatch is [B/(M*dp_cross), T, C].
            mb = (tokens.shape[0] // (M * 2)) * tokens.shape[1] \
                * cfg.d_model
            want = 2 * sched.ticks * mb * 4.0
            assert ws.pp_bytes == pytest.approx(want)
            assert ws.pp_sends == 2 * sched.ticks
            # send bytes also land on their link-class totals
            assert ws.ici_bytes >= ws.pp_bytes

        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_pp_spans_balanced(self, tmp_path):
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 4))
            path = str(tmp_path / "pp_tl.json")
            hvd.start_timeline(path)
            try:
                self._trace_interleaved()
            finally:
                hvd.stop_timeline()
            events = json.load(open(path))
            from horovod_tpu.monitor.span_audit import audit_spans

            # strict=: every event in the trace must come from the
            # CHECKED vocabulary table (span_audit.KNOWN_PREFIXES) — a
            # typo'd span family fails here, not in a skewed report.
            audit = audit_spans(events, prefix="PP:", require_spans=True,
                                strict=True)
            assert audit.balanced
            sched = build_interleaved_schedule(4, 4, 2)
            busy = audit.count.get("PP:F", 0) + audit.count.get("PP:B", 0)
            assert busy == sched.unit_count()
            assert audit.count.get("PP:SEND", 0) == 2  # one per direction
            assert audit.instants.get("PP:SCHEDULE", 0) == 1
            bubble = 1.0 - busy / float(sched.stages * sched.ticks)
            assert bubble == pytest.approx(sched.bubble_fraction)
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_zb_spans_count_w_units(self, tmp_path):
        """Under zb1 the W units show up as PP:W spans and the measured
        busy fraction reproduces the (smaller) zb bubble — the same
        span-derived bubble bench.py reports."""
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 4))
            path = str(tmp_path / "zb_tl.json")
            hvd.start_timeline(path)
            try:
                self._trace_interleaved(schedule="zb1")
            finally:
                hvd.stop_timeline()
            events = json.load(open(path))
            from horovod_tpu.monitor.span_audit import audit_spans

            audit = audit_spans(events, prefix="PP:", require_spans=True,
                                strict=True)
            assert audit.balanced
            sched = build_interleaved_schedule(4, 4, 2, family="zb1")
            assert audit.count.get("PP:W", 0) == \
                sched.microbatches * sched.interleave * sched.stages
            busy = (audit.count.get("PP:F", 0)
                    + audit.count.get("PP:B", 0)
                    + audit.count.get("PP:W", 0))
            assert busy == sched.unit_count()
            bubble = 1.0 - busy / float(sched.stages * sched.ticks)
            assert bubble == pytest.approx(sched.bubble_fraction)
            ref = build_interleaved_schedule(4, 4, 2)
            assert bubble < ref.bubble_fraction
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_bubble_fill_credits_streamed_gathers(self):
        """A zero3_gather_params trace under fill_sched= credits one
        idle tick per streamed bucket flight, capped at the schedule's
        per-rank fill capacity; without the window nothing is
        credited."""
        params = {f"w{i}": jnp.ones((1024,), jnp.float32)
                  for i in range(6)}
        tpl = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
        psh = hvd.zero3_shard_params(params,
                                     fusion_threshold_bytes=4096)
        pspec = hvd.zero3_param_pspecs(psh)
        n_buckets = len(jax.tree.leaves(psh))
        sched = build_interleaved_schedule(8, 4, 1, family="zb1")
        cap = sched.idle_ticks_per_rank
        assert 0 < cap < n_buckets  # the capacity cap is exercised

        def run(fill):
            def spmd(psh):
                p = hvd.zero3_gather_params(
                    psh, tpl, fusion_threshold_bytes=4096,
                    overlap=True, num_comm_streams=2, fill_sched=fill)
                return jax.tree.map(lambda a: a.sum(), p)

            f = jax.jit(hvd.shard_map(
                spmd, mesh=hvd.mesh(), in_specs=(pspec,),
                out_specs=jax.tree.map(lambda _: P(), tpl)))
            with hvd.record_wire_stats() as ws:
                f.lower(psh)
            return ws

        ws = run(sched)
        assert ws.filled_ticks == cap
        assert ws.bubble_hidden_bytes > 0
        # a filled flight is still overlap-scheduled — never double
        # freedom: hidden-in-bubble bytes are a subset of overlap bytes
        assert ws.bubble_hidden_bytes <= ws.overlap_bytes
        ws0 = run(None)
        assert ws0.filled_ticks == 0
        assert ws0.bubble_hidden_bytes == 0.0
        assert ws0.overlap_bytes == ws.overlap_bytes


# ---------------------------------------------------------------------------
# Golden --dump-plan table: the send legs are pinned text.
# ---------------------------------------------------------------------------


class TestGoldenPlan:
    def test_dump_plan_pins_send_leg(self):
        sp = hvd.describe_plan(mesh_shape=(2, 2), pp_stages=4,
                               pp_microbatches=8, pp_interleave=2,
                               pp_quantized=True, quantized=False,
                               zero_stage=0, overlap=False,
                               hierarchical=False, num_comm_streams=1,
                               quant_block=256,
                               fusion_threshold_bytes=64 * 1024 * 1024,
                               fused=False, quantized_pod=False)
        table = sp.table(payload_bytes=4 * 1024 * 1024)
        assert ("send               1 dcn   send           int8/256   "
                "yes xla          0") in table
        assert ("pp: stages=4 interleave=2 microbatches=8 "
                "schedule=interleaved_1f1b gpipe_bubble_bound=0.2727 "
                "(send rows priced per issue, docs/pipeline.md)") in table
        assert sp.encode() == (
            "allreduce:flat.psum[payload]|s1|sync + "
            "pp4v2m8.interleaved_1f1b@send:dcn.send[int8/256+ef]|s1|sync")

    def test_ici_hop_never_quantizes(self):
        sp = hvd.describe_plan(mesh_shape=(1, 4), pp_stages=2,
                               pp_quantized=True, quantized=False,
                               zero_stage=0, overlap=False,
                               hierarchical=False)
        assert sp.send.legs[0].level == "ici"
        assert not sp.send.is_quantized


# ---------------------------------------------------------------------------
# Autotune schema v8.
# ---------------------------------------------------------------------------


class TestAutotuneV8:
    def test_encode_decode_pp_segment(self):
        from horovod_tpu.autotune.parameter_manager import TunedParams
        from horovod_tpu.plan.planner import decode_tuned, encode_tuned

        p = TunedParams(pp_microbatches=16, pp_interleave=2)
        enc = encode_tuned(p, pp=True)
        assert enc == "ar.flat|fp|s1|sync|pp16/2"
        d = decode_tuned(enc)
        assert d["pp_microbatches"] == 16 and d["pp_interleave"] == 2
        # pp off: the segment (and both knobs) drop out — dead knobs
        # never split trials
        assert encode_tuned(p) == "ar.flat|fp|s1|sync"
        d0 = decode_tuned(encode_tuned(p))
        assert d0["pp_microbatches"] == 0 and d0["pp_interleave"] == 1

    def test_manager_canonicalizes_dead_pp_knobs(self):
        from horovod_tpu.autotune.parameter_manager import (
            ParameterManager, TunedParams)

        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=3, tune_pp=False)
        c = pm._canonicalize(TunedParams(pp_microbatches=16,
                                         pp_interleave=4))
        assert c.pp_microbatches == 0 and c.pp_interleave == 1

    def test_manager_snaps_pp_proposals(self):
        from horovod_tpu.autotune.parameter_manager import (
            ParameterManager, TunedParams)

        pm = ParameterManager(TunedParams(pp_microbatches=8,
                                          pp_interleave=2),
                              warmup_samples=0, max_samples=8,
                              tune_pp=True, pp_stages=3,
                              pp_max_interleave=2)
        for u7 in (0.0, 0.33, 0.7, 1.0):
            p = pm._from_unit((0.5, 0.5, 0.25, 0.25, 0.25, 0.0, 0.25,
                               u7, 1.0))
            assert p.pp_microbatches % 3 == 0
            assert p.pp_microbatches >= 3
            assert p.pp_interleave <= 2

    def test_csv_roundtrip_with_pp_columns(self, tmp_path):
        from horovod_tpu.autotune.parameter_manager import (
            CSV_FIELDS, ParameterManager, TunedParams, read_log)

        assert "pp_microbatches" in CSV_FIELDS
        assert "pp_interleave" in CSV_FIELDS
        path = str(tmp_path / "log.csv")
        pm = ParameterManager(TunedParams(pp_microbatches=8,
                                          pp_interleave=2),
                              warmup_samples=0, max_samples=3,
                              tune_pp=True, pp_stages=4,
                              pp_max_interleave=2, log_path=path)
        while not pm.done:
            pm.record_sample(1.0)
        rows = read_log(path)
        assert rows and all("pp_microbatches" in r for r in rows)
        assert rows[0]["pp_microbatches"] == 8
        assert rows[0]["pp_interleave"] == 2
        assert rows[0]["plan"].endswith("|pp8/2")

    def test_read_log_tolerant_of_v7_csv(self, tmp_path):
        from horovod_tpu.autotune.parameter_manager import read_log

        path = tmp_path / "v7.csv"
        path.write_text(
            "sample,fusion_threshold_bytes,quant_block,"
            "hierarchical_allreduce,zero_sharding,zero_stage,overlap,"
            "num_comm_streams,fused,score_steps_per_sec,plan\n"
            "1,4194304,256,0,0,0,0,1,0,12.5,ar.flat|fp|s1|sync\n")
        rows = read_log(str(path))
        assert rows[0]["pp_microbatches"] == 0
        assert rows[0]["pp_interleave"] == 1

    def test_tuned_params_from_v7_dict(self):
        from horovod_tpu.autotune.parameter_manager import TunedParams

        p = TunedParams.from_dict({
            "fusion_threshold_bytes": 4 << 20, "quant_block": 256,
            "hierarchical_allreduce": False, "zero_stage": 2,
            "overlap": True, "num_comm_streams": 2, "fused": False})
        assert p.pp_microbatches == 0 and p.pp_interleave == 1

    def test_shortlist_prices_pp_candidates(self):
        from horovod_tpu.plan.planner import shortlist

        rows = shortlist(8 * 1024 * 1024, mesh_shape=(2, 2),
                         tune_pp=True, pp_stages=4, pp_max_interleave=2,
                         tune_hierarchical=False, k=6)
        assert rows
        ppms = {r.params.pp_microbatches for r in rows}
        assert len(ppms) > 1  # distinct pp candidates priced + ranked
        for r in rows:
            assert r.plan.send is not None
            assert r.cost.pp_ms > 0


# ---------------------------------------------------------------------------
# Autotune schema v11: the pp_schedule knob (zero-bubble family).
# ---------------------------------------------------------------------------


class TestAutotuneV11:
    def test_encode_decode_zb_segment(self):
        from horovod_tpu.autotune.parameter_manager import TunedParams
        from horovod_tpu.plan.planner import decode_tuned, encode_tuned

        p = TunedParams(pp_microbatches=8, pp_interleave=2,
                        pp_schedule="zb1")
        enc = encode_tuned(p, pp=True)
        assert enc == "ar.flat|fp|s1|sync|pp8/2|zb1"
        d = decode_tuned(enc)
        assert d["pp_schedule"] == "zb1"
        assert d["pp_microbatches"] == 8 and d["pp_interleave"] == 2
        # the segment is optional: every v10 encoding is a valid v11
        # encoding and decodes to the exact pre-v11 default
        d10 = decode_tuned("ar.flat|fp|s1|sync|pp8/2")
        assert d10["pp_schedule"] == "interleaved_1f1b"
        # pp off: schedule rides the pp group, so it drops with it
        assert encode_tuned(p) == "ar.flat|fp|s1|sync"
        assert decode_tuned("ar.flat|fp|s1|sync")["pp_schedule"] == \
            "interleaved_1f1b"

    def test_manager_canonicalizes_dead_zb_knob(self):
        from horovod_tpu.autotune.parameter_manager import (
            ParameterManager, TunedParams)

        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=3, tune_pp=False)
        c = pm._canonicalize(TunedParams(pp_microbatches=16,
                                         pp_interleave=2,
                                         pp_schedule="zb1"))
        # zb1 is meaningless without a pipeline: collapses with the
        # other pp knobs so equal plans dedup as ONE trial
        assert c.pp_schedule == "interleaved_1f1b"
        assert c.pp_microbatches == 0 and c.pp_interleave == 1

    def test_unit_cube_roundtrip_and_v10_tuple_tolerance(self):
        from horovod_tpu.autotune.parameter_manager import (
            ParameterManager, TunedParams)

        pm = ParameterManager(TunedParams(pp_microbatches=8),
                              warmup_samples=0, max_samples=8,
                              tune_pp=True, pp_stages=4,
                              pp_max_interleave=1)
        for u13, want in ((0.0, "interleaved_1f1b"),
                          (0.25, "interleaved_1f1b"),
                          (0.75, "zb1"), (1.0, "zb1")):
            p = pm._from_unit((0.5, 0.5, 0.25, 0.25, 0.25, 0.0, 0.25,
                               0.5, 0.0, 0.25, 0.25, 0.25, 0.25, u13))
            assert p.pp_schedule == want
            # round trip: _to_unit lands the same side of 0.5
            back = pm._from_unit(pm._to_unit(p))
            assert back.pp_schedule == want
        # pre-v11 unit tuples (len < 14) still resolve — the zb dim
        # was appended at the tail precisely so old coordinates stay
        # valid, defaulting to the pre-v11 schedule
        p9 = pm._from_unit((0.5, 0.5, 0.25, 0.25, 0.25, 0.0, 0.25,
                            0.5, 0.0))
        assert p9.pp_schedule == "interleaved_1f1b"

    def test_csv_roundtrip_with_pp_schedule_column(self, tmp_path):
        from horovod_tpu.autotune.parameter_manager import (
            CSV_FIELDS, ParameterManager, TunedParams, read_log)

        assert "pp_schedule" in CSV_FIELDS
        path = str(tmp_path / "log.csv")
        pm = ParameterManager(TunedParams(pp_microbatches=8,
                                          pp_schedule="zb1"),
                              warmup_samples=0, max_samples=3,
                              tune_pp=True, pp_stages=4,
                              pp_max_interleave=1, log_path=path)
        while not pm.done:
            pm.record_sample(1.0)
        rows = read_log(path)
        assert rows and all("pp_schedule" in r for r in rows)
        assert rows[0]["pp_schedule"] == "zb1"
        assert rows[0]["plan"].endswith("|zb1")

    def test_read_log_tolerant_of_v10_csv(self, tmp_path):
        from horovod_tpu.autotune.parameter_manager import read_log

        # A v10-era log: no pp_schedule column — reads cleanly and
        # defaults to the exact pre-v11 schedule.
        path = tmp_path / "v10.csv"
        path.write_text(
            "sample,fusion_threshold_bytes,quant_block,"
            "hierarchical_allreduce,zero_sharding,zero_stage,overlap,"
            "num_comm_streams,fused,pp_microbatches,pp_interleave,"
            "moe_capacity_factor,moe_quantized,spec_draft_k,"
            "kv_migrate_quantized,score_steps_per_sec,plan\n"
            "1,4194304,256,0,0,0,0,1,0,8,2,0.0,0,0,0,12.5,"
            "ar.flat|fp|s1|sync|pp8/2\n")
        rows = read_log(str(path))
        assert rows[0]["pp_schedule"] == "interleaved_1f1b"
        assert rows[0]["pp_microbatches"] == 8

    def test_tuned_params_from_v10_dict(self):
        from horovod_tpu.autotune.parameter_manager import TunedParams

        p = TunedParams.from_dict({
            "fusion_threshold_bytes": 4 << 20, "quant_block": 256,
            "hierarchical_allreduce": False, "zero_stage": 2,
            "overlap": True, "num_comm_streams": 2,
            "pp_microbatches": 8, "pp_interleave": 2})
        assert p.pp_schedule == "interleaved_1f1b"
        rt = TunedParams.from_dict(p.as_dict())
        assert rt == p

    def test_enumerate_offers_both_schedules_under_tune_pp(self):
        from horovod_tpu.plan.planner import enumerate_tuned

        cands = enumerate_tuned(tune_pp=True, pp_stages=4,
                                pp_max_interleave=1)
        scheds = {p.pp_schedule for p in cands}
        assert scheds == {"interleaved_1f1b", "zb1"}
        # tune_pp off: the schedule stays pinned — no phantom trials
        pinned = {p.pp_schedule for p in enumerate_tuned()}
        assert pinned == {"interleaved_1f1b"}


# ---------------------------------------------------------------------------
# Checkpoint ride-along: stage-count guard + same-stage round-trip.
# ---------------------------------------------------------------------------


class TestCheckpointGuard:
    def test_stage_count_change_fails_loudly(self, tmp_path):
        from horovod_tpu import checkpoint as hvd_ckpt

        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(1, 4),
                     pp_stages=2)
            mgr = hvd_ckpt.CheckpointManager(str(tmp_path), keep=2)
            state = hvd_ckpt.CheckpointedJaxState(
                mgr, params=jnp.arange(8.0), step=0)
            state.step = 3
            state.commit()
            assert state.wait(30)
            mgr.close()
        finally:
            hvd.shutdown()
        try:
            hvd.init(devices=jax.devices())  # 1-stage (no pp) mesh
            mgr = hvd_ckpt.CheckpointManager(str(tmp_path), keep=2)
            with pytest.raises(ValueError,
                               match="2-stage pipeline mesh"):
                hvd_ckpt.CheckpointedJaxState(
                    mgr, params=jnp.arange(8.0), step=0)
            mgr.close()
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_same_stage_roundtrip_bit_identical(self, tmp_path):
        from horovod_tpu import checkpoint as hvd_ckpt

        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(1, 4),
                     pp_stages=2)
            vals = jnp.asarray(
                np.random.RandomState(0).randn(16).astype(np.float32))
            mgr = hvd_ckpt.CheckpointManager(str(tmp_path), keep=2)
            state = hvd_ckpt.CheckpointedJaxState(mgr, params=vals,
                                                  step=0)
            state.step = 5
            state.commit()
            assert state.wait(30)
            mgr.close()
            hvd.shutdown()
            hvd.init(devices=jax.devices(), mesh_shape=(1, 4),
                     pp_stages=2)
            mgr = hvd_ckpt.CheckpointManager(str(tmp_path), keep=2)
            restored = hvd_ckpt.CheckpointedJaxState(
                mgr, params=jnp.zeros(16), step=0)
            assert restored.restored_from == 5
            assert restored.step == 5
            np.testing.assert_array_equal(np.asarray(restored.params),
                                          np.asarray(vals))
            mgr.close()
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())
