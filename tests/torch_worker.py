"""Worker for multi-process PyTorch binding tests (reference analogue:
`mpirun -np 2 pytest test_torch.py`, SURVEY §4)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank, (hvd.rank(), rank)
    assert hvd.size() == size

    # -- allreduce: average (default), sum, in-place, prescale --
    t = torch.full((4,), float(rank))
    out = hvd.allreduce(t)
    expect = sum(range(size)) / size
    assert torch.allclose(out, torch.full((4,), expect)), out
    assert torch.allclose(t, torch.full((4,), float(rank))), "input mutated"

    out = hvd.allreduce(t, op=hvd.Sum)
    assert torch.allclose(out, torch.full((4,), float(sum(range(size)))))

    t2 = torch.full((3,), float(rank + 1))
    hvd.allreduce_(t2, op=hvd.Sum, prescale_factor=2.0, postscale_factor=0.5)
    assert torch.allclose(t2, torch.full((3,), float(sum(r + 1 for r in
                                                         range(size)))))

    # min/max/product
    assert hvd.allreduce(torch.tensor([float(rank)]),
                         op=hvd.Min).item() == 0.0
    assert hvd.allreduce(torch.tensor([float(rank)]),
                         op=hvd.Max).item() == size - 1
    out = hvd.allreduce(torch.tensor([2.0]), op=hvd.Product)
    assert abs(out.item() - 2.0 ** size) < 1e-5

    # -- dtype coverage: fp64, int64, fp16, bf16 --
    out = hvd.allreduce(torch.ones(4, dtype=torch.float64), op=hvd.Sum)
    assert out.dtype == torch.float64 and out[0].item() == size
    out = hvd.allreduce(torch.ones(4, dtype=torch.int64), op=hvd.Sum)
    assert out.dtype == torch.int64 and out[0].item() == size
    out = hvd.allreduce(torch.ones(4, dtype=torch.float16), op=hvd.Sum)
    assert out.dtype == torch.float16 and out[0].item() == size
    out = hvd.allreduce(torch.ones(4, dtype=torch.bfloat16), op=hvd.Sum)
    assert out.dtype == torch.bfloat16 and out.float()[0].item() == size

    # -- autograd through allreduce --
    x = torch.full((2,), float(rank), requires_grad=True)
    y = hvd.allreduce(x, op=hvd.Sum).sum()
    y.backward()
    # d(sum over ranks)/dx allreduced with Sum again -> grad = size
    assert torch.allclose(x.grad, torch.full((2,), float(size))), x.grad

    # -- allgather (ragged first dim) --
    g = hvd.allgather(torch.full((rank + 1, 2), float(rank)))
    assert g.shape == (sum(r + 1 for r in range(size)), 2)
    row = 0
    for r in range(size):
        assert torch.allclose(g[row:row + r + 1],
                              torch.full((r + 1, 2), float(r)))
        row += r + 1

    # -- broadcast --
    out = hvd.broadcast(torch.full((4,), float(rank)), root_rank=0)
    assert torch.allclose(out, torch.zeros(4))
    t3 = torch.full((4,), float(rank))
    hvd.broadcast_(t3, root_rank=size - 1)
    assert torch.allclose(t3, torch.full((4,), float(size - 1)))

    # -- alltoall --
    out, splits = hvd.alltoall(
        torch.arange(size * 2, dtype=torch.float32))
    assert out.shape[0] == size * 2
    assert splits.tolist() == [2] * size

    # -- handle API + duplicate name rejection --
    h = hvd.allreduce_async(torch.ones(8), name="tw.async")
    out = hvd.synchronize(h)
    assert torch.allclose(out, torch.ones(8))
    h1 = hvd.allreduce_async(torch.ones(2), name="tw.dup")
    try:
        hvd.allreduce_async(torch.ones(2), name="tw.dup")
        raise SystemExit("duplicate name not rejected")
    except Exception as e:
        # Rejected either by the torch handle manager or (first) by the
        # native core's name table (DUPLICATE_NAME_ERROR, common.h:163).
        assert "dup" in str(e).lower() or "same name" in str(e), e
    hvd.synchronize(h1)

    # -- broadcast_parameters / broadcast_object / allgather_object --
    model = torch.nn.Linear(4, 2)
    with torch.no_grad():
        for p in model.parameters():
            p.fill_(float(rank + 1))
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    for p in model.parameters():
        assert torch.allclose(p, torch.ones_like(p)), p

    obj = hvd.broadcast_object({"rank": rank, "x": [1, 2, 3]}, root_rank=0)
    assert obj["rank"] == 0

    objs = hvd.allgather_object({"rank": rank})
    assert [o["rank"] for o in objs] == list(range(size))

    # -- DistributedOptimizer: grads averaged across ranks --
    torch.manual_seed(0)  # same init on all ranks
    model = torch.nn.Linear(3, 1, bias=False)
    opt = torch.optim.SGD(model.parameters(), lr=1.0)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    assert isinstance(opt, torch.optim.SGD)

    w0 = model.weight.detach().clone()
    x = torch.full((1, 3), float(rank + 1))
    opt.zero_grad()
    loss = model(x).sum()
    loss.backward()  # dL/dw = x, differs per rank
    opt.step()
    mean_x = np.mean([r + 1 for r in range(size)])
    expect_w = w0 - torch.full((1, 3), mean_x)
    assert torch.allclose(model.weight, expect_w, atol=1e-5), \
        (model.weight, expect_w)

    # -- backward_passes_per_step: step() mid-accumulation must flush the
    # partial gradient through an allreduce (not apply it un-reduced) --
    torch.manual_seed(0)
    model_a = torch.nn.Linear(3, 1, bias=False)
    opt_a = hvd.DistributedOptimizer(
        torch.optim.SGD(model_a.parameters(), lr=1.0),
        named_parameters=model_a.named_parameters(),
        backward_passes_per_step=2)
    w0 = model_a.weight.detach().clone()
    xa = torch.full((1, 3), float(rank + 1))
    opt_a.zero_grad()
    model_a(xa).sum().backward()   # only ONE of the two expected passes
    opt_a.step()                   # must flush + reduce the partial grad
    expect_w = w0 - torch.full((1, 3), mean_x)
    assert torch.allclose(model_a.weight, expect_w, atol=1e-5), \
        (model_a.weight, expect_w)
    # delay counter must be fully re-armed: two more backwards then step
    opt_a.zero_grad()
    model_a(xa).sum().backward()
    model_a(xa).sum().backward()
    opt_a.step()

    # -- broadcast_optimizer_state --
    inner = torch.optim.SGD(model.parameters(), lr=0.5, momentum=0.9)
    loss = model(x).sum()
    loss.backward()
    inner.step()
    if rank != 0:
        for st in inner.state.values():
            if "momentum_buffer" in st:
                st["momentum_buffer"].fill_(99.0)
    hvd.broadcast_optimizer_state(inner, root_rank=0)
    bufs = [st["momentum_buffer"] for st in inner.state.values()]
    assert bufs and not any(torch.allclose(b, torch.full_like(b, 99.0))
                            for b in bufs)

    # -- SyncBatchNorm: global batch stats (verified vs. a local BN over
    # the concatenated global batch, reconstructible because per-rank
    # inputs are deterministic) --
    torch.manual_seed(1)
    bn = hvd.SyncBatchNorm(3, momentum=0.5)
    gen = torch.Generator().manual_seed(42 + rank)
    xb = torch.randn(4, 3, 5, generator=gen)
    out = bn(xb)
    # rebuild the global batch locally
    full = torch.cat([torch.randn(4, 3, 5,
                                  generator=torch.Generator().manual_seed(
                                      42 + r)) for r in range(size)])
    ref_bn = torch.nn.BatchNorm1d(3, momentum=0.5)
    ref_out = ref_bn(full)
    assert torch.allclose(out, ref_out[rank * 4:(rank + 1) * 4], atol=1e-4)
    assert torch.allclose(bn.running_mean, ref_bn.running_mean, atol=1e-4)
    assert torch.allclose(bn.running_var, ref_bn.running_var, atol=1e-4)

    # SyncBatchNorm backward: grads wrt input must match the local-BN
    # backward over the global batch
    xb_g = xb.clone().requires_grad_(True)
    bn2 = hvd.SyncBatchNorm(3)
    bn2(xb_g).sum().backward()
    full_g = full.clone().requires_grad_(True)
    ref_bn2 = torch.nn.BatchNorm1d(3)
    ref_bn2(full_g).sum().backward()
    assert torch.allclose(xb_g.grad,
                          full_g.grad[rank * 4:(rank + 1) * 4], atol=1e-4)

    # -- TorchState sync: rank!=0 state must converge to rank 0's --
    model_s = torch.nn.Linear(2, 2)
    with torch.no_grad():
        for p in model_s.parameters():
            p.fill_(float(rank))
    opt_s = torch.optim.SGD(model_s.parameters(), lr=0.1)
    state = hvd.elastic.TorchState(model=model_s, optimizer=opt_s,
                                   epoch=rank, batch=rank * 10)
    state.sync()
    for p in model_s.parameters():
        assert torch.allclose(p, torch.zeros_like(p))
    assert state.epoch == 0 and state.batch == 0

    # -- join: all ranks join; returns last rank to join --
    last = hvd.join()
    assert 0 <= last < size

    hvd.shutdown()
    print(f"rank {rank}: torch worker OK")


if __name__ == "__main__":
    main()
