"""Minimal PURE-JAX reproductions of the jax 0.4.37 bugs behind the
tier-1 ``xfail`` markers — no horovod_tpu involved, so each failure is
provably upstream (old ``jax.experimental.shard_map``), not ours. All
three are gone on jax >= 0.6 (the graduated ``jax.shard_map`` rewrite),
which is why the marks are ``xfail(OLD_JAX, strict=False)``: on a fixed
jax they run as normal tests.

Run ``python tests/jax0437_repros.py`` to print each repro's outcome on
the current jax. Referenced by:

* ``tests/test_alltoall_ragged.py::test_ragged_gradient`` and
  ``tests/test_expert_parallel.py::TestSwitchMoERagged::
  test_ragged_gradients_match_dense_no_drop``  → :func:`repro_grad_of_psum`
* ``tests/test_flash_attention.py::TestFlashRingAttention::
  test_matches_dense[False]``                  → :func:`repro_partition_id`
* ``tests/test_optimizer.py::test_backward_passes_per_step_accumulates``
                                               → :func:`repro_cond_rep_mismatch`
"""

import numpy as np

OLD_JAX = None  # resolved lazily so importing this file never inits jax


def _old_jax() -> bool:
    import jax

    return tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 6)


def _mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]).reshape(1, 8), ("c", "l"))


def repro_grad_of_psum():
    """grad-of-psum ×N: differentiating a loss that closes with
    ``lax.psum`` under old shard_map multiplies the gradient by the axis
    size (the psum transpose inserts an extra sum instead of the
    identity). Expected ``dL/dx = x`` for ``L = psum(sum(x²)/2)``;
    jax 0.4.37 returns ``N·x``. This is what breaks every jax.grad-
    through-collective test (alltoall_ragged / SwitchMoE ragged grads:
    the values come back scaled)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    ax = ("c", "l")

    def loss(x):
        return jax.lax.psum(jnp.sum(x * x) / 2.0, ax)

    g = jax.jit(shard_map(jax.grad(loss), mesh=_mesh(),
                          in_specs=P(ax), out_specs=P(ax)))(jnp.arange(8.0))
    g = np.asarray(g)
    ok = np.allclose(g, np.arange(8.0))
    return ok, f"grad(psum(sum(x^2)/2)) = {g} (expected 0..7; x8 = the bug)"


def repro_partition_id():
    """PartitionId SPMD lowering: ``lax.axis_index`` over a mesh-axis
    TUPLE inside a ``lax.scan`` body lowers to ``stablehlo.partition_id``
    under old shard_map. When that instruction lands in a program region
    the SPMD partitioner must partition (the flash ring's non-causal
    kernel layout), compilation dies with ``UNIMPLEMENTED: PartitionId
    instruction is not supported for SPMD partitioning``. The repro
    counts the partition_id instructions in the lowered module — 0 on
    fixed jax (axis_index lowers to iota/replica arithmetic)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    ax = ("c", "l")

    def body(c, t):
        my = jax.lax.axis_index(ax)
        return c + jnp.where(t == my, 1.0, 0.0), None

    f = jax.jit(shard_map(lambda x: jax.lax.scan(body, x, jnp.arange(7))[0],
                          mesh=_mesh(), in_specs=P(ax), out_specs=P(ax)))
    txt = f.lower(jnp.zeros(8)).as_text()
    n = sum("partition_id" in line for line in txt.splitlines())
    return n == 0, f"{n} stablehlo.partition_id instructions in the module"


def repro_cond_rep_mismatch():
    """optax.MultiSteps cond rep mismatch: a ``lax.cond`` whose arms
    carry different replication types (replicated zeros vs a
    psum-derived update — exactly MultiSteps' accumulate-vs-apply
    selection) raises ``Exception: The branches of cond produced
    mismatched replication types`` under old shard_map's rep checker, so
    ``backward_passes_per_step > 1`` cannot trace. (The branchless
    ``where``-selected accumulators — ``_zero_multi_steps`` and the
    overlap-mode ``_overlap_multi_steps`` in parallel/optimizer.py — are
    the working spelling on 0.4.x.)"""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    ax = ("c", "l")

    def f(x, s):
        def acc(_):
            return jnp.zeros_like(s)

        def apply(_):
            return s + jax.lax.psum(x.sum(), ax)

        return jax.lax.cond(s[0] > 0, acc, apply, None)

    try:
        jax.jit(shard_map(f, mesh=_mesh(), in_specs=(P(ax), P()),
                          out_specs=P()))(jnp.arange(8.0), jnp.ones(3))
        return True, "cond with mixed-rep arms traced fine"
    except Exception as e:  # noqa: BLE001 - jax raises bare Exception here
        return False, f"{type(e).__name__}: {str(e)[:120]}"


if __name__ == "__main__":
    import os

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    print(f"jax {jax.__version__} (old shard_map: {_old_jax()})")
    for fn in (repro_grad_of_psum, repro_partition_id,
               repro_cond_rep_mismatch):
        ok, detail = fn()
        print(f"{'PASS' if ok else 'BUG '} {fn.__name__}: {detail}")
