"""Wire-plan IR tests (docs/wire-plan.md).

Four tiers:

* **validation** — illegal leg compositions fail loudly with actionable
  messages (ISSUE 9 satellite: plan validation units);
* **golden text** — ``hvd.describe_plan(...).table()`` is pinned as
  literal text, so any plan regression shows up as a readable diff;
* **equivalence matrix** — the plan compiler's output is bit-identical
  to the pre-refactor hand-composed paths for every knob combination in
  {quantized, zero_stage 0/2/3, overlap, hierarchical} on the 8-device
  2x4 mesh: the wire-level references below are literal copies of the
  deleted bespoke bodies (renamed), and the optimizer-level matrix
  re-asserts the cross-knob invariants (overlap-on ≡ overlap-off,
  plan= ≡ booleans) the old paths guaranteed;
* **3-level smoke** — a plan-compiled allreduce on an emulated 2x2x2
  ``(pod, cross, local)`` mesh, plus the ``--mesh-shape CxLxP`` parsing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.ops import collective_ops as C
from horovod_tpu.ops import compression as Z
from horovod_tpu.plan import (DCN, FLAT, ICI, INT8, POD, Leg, PlanError,
                              WirePlan, decode_tuned, describe_plan,
                              encode_tuned, planner)

N = 8


@pytest.fixture(scope="module", autouse=True)
def _mesh_2x4():
    """Emulated 2-host x 4-chip mesh (the DCN hop the quantized legs
    compress); restore the default mesh for later modules."""
    hvd.shutdown()
    hvd.init(mesh_shape=(2, 4))
    yield
    hvd.shutdown()
    hvd.init()


def mesh_2x4() -> Mesh:
    return hvd.mesh()


# ---------------------------------------------------------------------------
# Validation: illegal compositions fail loudly with actionable messages.
# ---------------------------------------------------------------------------


class TestValidation:
    def test_int8_on_ici_hop_rejected(self):
        p = WirePlan("allreduce", (Leg(ICI, "reduce_scatter", INT8),
                                   Leg(ICI, "all_gather")))
        with pytest.raises(PlanError, match="non-DCN hop"):
            p.validate()

    def test_reduce_leg_after_gather_rejected(self):
        p = WirePlan("allreduce", (
            Leg(ICI, "reduce_scatter"), Leg(ICI, "all_gather"),
            Leg(DCN, "psum")))
        with pytest.raises(PlanError, match="illegal leg order"):
            p.validate()

    def test_unbalanced_allreduce_rejected(self):
        p = WirePlan("allreduce", (Leg(ICI, "reduce_scatter"),
                                   Leg(DCN, "psum")))
        with pytest.raises(PlanError, match="re-gathered in mirror order"):
            p.validate()

    def test_bad_stream_count_rejected(self):
        p = WirePlan("allreduce", (Leg(FLAT, "psum"),), streams=3)
        with pytest.raises(PlanError, match="power of two in 1..4"):
            p.validate()

    def test_unknown_primitive_and_level_rejected(self):
        with pytest.raises(PlanError, match="unknown primitive"):
            WirePlan("allreduce", (Leg(ICI, "ring_exchange"),)).validate()
        with pytest.raises(PlanError, match="unknown level"):
            WirePlan("allreduce", (Leg("nvlink", "psum"),)).validate()
        with pytest.raises(PlanError, match="unknown collective"):
            WirePlan("gossip", (Leg(FLAT, "psum"),)).validate()

    def test_ef_on_exact_ici_leg_rejected(self):
        p = WirePlan("allreduce", (
            Leg(ICI, "reduce_scatter", error_feedback=True),
            Leg(ICI, "all_gather")))
        with pytest.raises(PlanError, match="error-feedback slot"):
            p.validate()

    def test_gather_leg_in_reduce_scatter_plan_rejected(self):
        p = WirePlan("reduce_scatter", (Leg(ICI, "reduce_scatter"),
                                        Leg(ICI, "all_gather")))
        with pytest.raises(PlanError, match="belongs to the all_gather"):
            p.validate()

    def test_flat_leg_cannot_compose(self):
        p = WirePlan("allreduce", (Leg(FLAT, "psum"),
                                   Leg(ICI, "all_gather")))
        with pytest.raises(PlanError, match="WHOLE plan"):
            p.validate()

    def test_valid_plans_validate(self):
        planner.flat_plan("allreduce")
        planner.tree_allreduce_plan()
        planner.tree_allreduce_plan(pod=True)
        planner.quantized_allreduce_plan(block=256, error_feedback=True)
        planner.zero_reduce_scatter_plan(quantized=True, block=128)
        planner.zero_all_gather_plan(quantized=True, block=128)


# ---------------------------------------------------------------------------
# Planner: knob combinations → plan structure; autotune plan encoding.
# ---------------------------------------------------------------------------


class TestPlanner:
    def test_knob_matrix_maps_to_expected_structures(self):
        levels = (ICI, DCN)
        flat = planner.derive_allreduce(levels=levels, quantized=False,
                                        hierarchical=False)
        assert flat.is_flat and not flat.is_quantized
        tree = planner.derive_allreduce(levels=levels, quantized=False,
                                        hierarchical=True)
        assert tree.levels == (ICI, DCN, ICI) and not tree.is_quantized
        quant = planner.derive_allreduce(levels=levels, quantized=True,
                                         hierarchical=False)
        assert quant.levels == (ICI, DCN, DCN, ICI)
        assert [l.wire_dtype for l in quant.legs] == [
            "payload", INT8, INT8, "payload"]
        # quantized wins over hierarchical (the pre-refactor precedence)
        both = planner.derive_allreduce(levels=levels, quantized=True,
                                        hierarchical=True)
        assert both == quant

    def test_custom_axes_always_flat(self):
        assert planner.derive_allreduce(
            levels=planner.levels_of(("tp",)), quantized=True,
            hierarchical=True).is_flat

    def test_zero_wire_is_the_split_allreduce(self):
        rs = planner.derive_reduce_scatter(levels=(ICI, DCN),
                                           quantized=True, block=256)
        ag = planner.derive_all_gather(levels=(ICI, DCN), quantized=True,
                                       block=256)
        q = planner.quantized_allreduce_plan(block=256)
        # rs legs == the reduce half, ag legs == the gather half.
        assert [(l.level, l.primitive) for l in rs.legs] == \
            [(l.level, l.primitive) for l in q.legs[:2]]
        assert [(l.level, l.primitive) for l in ag.legs] == \
            [(l.level, l.primitive) for l in q.legs[2:]]

    def test_describe_plan_three_level_tree(self):
        sp = describe_plan(hierarchical=True, mesh_shape=(2, 2, 2))
        assert sp.gradient.levels == (ICI, DCN, POD, ICI)
        sp0 = describe_plan(mesh_shape=(2, 2, 2))
        assert sp0.gradient.is_flat

    def test_encode_decode_round_trip(self):
        from horovod_tpu.autotune import TunedParams

        for p, quant in [
            (TunedParams(), False),
            (TunedParams(hierarchical_allreduce=True), False),
            (TunedParams(zero_stage=2, overlap=True,
                         num_comm_streams=4), True),
            (TunedParams(quant_block=128, overlap=True,
                         num_comm_streams=2), True),
        ]:
            enc = encode_tuned(p, quantized=quant)
            d = decode_tuned(enc)
            assert d["zero_stage"] == p.zero_stage
            assert d["overlap"] == p.overlap
            assert d["quantized"] == quant
            if quant:
                assert d["quant_block"] == p.quant_block
            if p.overlap:
                assert d["num_comm_streams"] == p.num_comm_streams

    def test_encoding_collapses_dead_knobs(self):
        from horovod_tpu.autotune import TunedParams

        # hierarchical is dead under the ZeRO rs+ag split; streams are
        # dead with overlap off — same wire, same encoding, ONE trial.
        a = encode_tuned(TunedParams(zero_stage=2,
                                     hierarchical_allreduce=True))
        b = encode_tuned(TunedParams(zero_stage=2))
        assert a == b
        c = encode_tuned(TunedParams(num_comm_streams=4))
        d = encode_tuned(TunedParams(num_comm_streams=1))
        assert c == d

    def test_decode_rejects_garbage(self):
        with pytest.raises(PlanError, match="unparseable plan encoding"):
            decode_tuned("ar.zigzag|fp|s1|sync")


# ---------------------------------------------------------------------------
# Golden text: the --dump-plan / describe_plan table, pinned literally.
# ---------------------------------------------------------------------------

GOLDEN_QUANTIZED_2x4 = """\
wire plan  mesh=2x4  payload=1048576B (itemsize 4)
knobs: quantized=on block=256 zero_stage=0 overlap=off hierarchical=off streams=1 fusion_threshold=67108864 fused=off quantized_pod=off
collective       leg level primitive      wire       ef  backend stream    bytes/dev  model ms  pred ms
allreduce          1 ici   reduce_scatter payload    -   xla          0       786432    0.0079   0.0109
allreduce          2 dcn   reduce_scatter int8/256   yes xla          0        33280    0.0013   0.0290
allreduce          3 dcn   all_gather     int8/256   yes xla          0        66560    0.0027   0.0329
allreduce          4 ici   all_gather     payload    -   xla          0      1572864    0.0157   0.0187
totals: ici=2359296 dcn=99840 pod=0 dcn_fp_equiv=393216 dcn_reduction=3.94x
predicted: 0.0915 ms step wire = bytes 0.0276 + latency 0.0560 + quant 0.0079 - hidden 0.0000 (modeled 0.0276 ms, 1 bucket) [cost model: static]
encoding: allreduce:ici.reduce_scatter[payload]>dcn.reduce_scatter[int8/256+ef]>dcn.all_gather[int8/256+ef]>ici.all_gather[payload]|s1|sync"""

GOLDEN_ZERO2_OVERLAP_2x4 = """\
wire plan  mesh=2x4  payload=1048576B (itemsize 4)
knobs: quantized=off block=256 zero_stage=2 overlap=on hierarchical=off streams=2 fusion_threshold=67108864 fused=off quantized_pod=off
collective       leg level primitive      wire       ef  backend stream    bytes/dev  model ms  pred ms
reduce_scatter     1 flat  reduce_scatter payload    -   xla          0       917504    0.0131   0.0411
all_gather         1 flat  all_gather     payload    -   xla          0      1835008    0.0262   0.0542
totals: ici=2359296 dcn=393216 pod=0 dcn_fp_equiv=393216 dcn_reduction=1.00x
predicted: 0.0953 ms step wire = bytes 0.0393 + latency 0.0560 + quant 0.0000 - hidden 0.0000 (modeled 0.0393 ms, 1 bucket) [cost model: static]
encoding: reduce_scatter:flat.reduce_scatter[payload]|s2|ovl + tail@all_gather:flat.all_gather[payload]|s2|ovl"""


class TestGoldenTables:
    def test_quantized_allreduce_table(self):
        sp = describe_plan(quantized=True, mesh_shape=(2, 4),
                           fusion_threshold_bytes=64 * 1024 * 1024,
                           quant_block=256)
        assert sp.table(payload_bytes=1 << 20) == GOLDEN_QUANTIZED_2x4

    def test_zero2_overlap_table(self):
        sp = describe_plan(zero_stage=2, overlap=True, num_comm_streams=2,
                           quantized=False, mesh_shape=(2, 4),
                           fusion_threshold_bytes=64 * 1024 * 1024,
                           quant_block=256)
        assert sp.table(payload_bytes=1 << 20) == GOLDEN_ZERO2_OVERLAP_2x4

    def test_quantized_reduction_matches_recorded_wire_ratio(self):
        # The 3.94x DCN reduction the PR-2 bench recorded is a cost-model
        # consequence, not a coincidence — the table must keep saying it.
        assert "dcn_reduction=3.94x" in GOLDEN_QUANTIZED_2x4


# ---------------------------------------------------------------------------
# Equivalence matrix, wire level: the compiler output is bit-identical to
# the pre-refactor bespoke bodies (copied here verbatim as references).
# ---------------------------------------------------------------------------


def _ref_tree_psum(x, local_axis=basics.LOCAL_AXIS,
                   cross_axis=basics.CROSS_AXIS):
    """Reference copy of the pre-plan hierarchical allreduce body."""
    shard = lax.psum_scatter(x, local_axis, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, cross_axis)
    li = lax.axis_index(local_axis)
    full = jnp.zeros(x.shape, x.dtype)
    full = lax.dynamic_update_slice_in_dim(
        full, shard, li * shard.shape[0], 0)
    return lax.psum(full, local_axis)


def _ref_quant_allreduce(x, residual, blk, nl, nc,
                         local_axis=basics.LOCAL_AXIS,
                         cross_axis=basics.CROSS_AXIS):
    """Reference copy of the pre-plan quantized hierarchical allreduce
    body (monolithic hops 1-4, padded-array error feedback)."""
    corrected = x if residual is None else x + residual.astype(x.dtype)
    n = int(np.prod(x.shape, dtype=np.int64))
    flat = jnp.ravel(corrected)
    sn = n // nl
    seg = sn // nc
    shard = lax.psum_scatter(flat, local_axis, scatter_dimension=0,
                             tiled=True)
    segs = shard.reshape(nc, seg).astype(jnp.float32)
    pad = (-seg) % blk
    if pad:
        segs = jnp.concatenate(
            [segs, jnp.zeros((nc, pad), jnp.float32)], axis=1)
    nb = segs.shape[1] // blk
    blocks = segs.reshape(nc, nb, blk)
    scales = Z._block_scales(blocks)
    q = jnp.clip(jnp.round(blocks / scales[..., None]),
                 -127, 127).astype(jnp.int8)
    err1 = blocks - q.astype(jnp.float32) * scales[..., None]
    qT = lax.all_to_all(q, cross_axis, split_axis=0, concat_axis=0,
                        tiled=True)
    sT = lax.all_to_all(scales, cross_axis, split_axis=0, concat_axis=0,
                        tiled=True)
    acc = jnp.sum(qT.astype(jnp.float32) * sT[..., None], axis=0)
    s2 = Z._block_scales(acc)
    q2 = jnp.clip(jnp.round(acc / s2[:, None]), -127, 127).astype(jnp.int8)
    err2 = acc - q2.astype(jnp.float32) * s2[:, None]
    ci = lax.axis_index(cross_axis)
    qfull = lax.dynamic_update_slice_in_dim(
        jnp.zeros((nc, nb, blk), jnp.int8), q2[None], ci, 0)
    sfull = lax.dynamic_update_slice_in_dim(
        jnp.zeros((nc, nb), jnp.float32), s2[None], ci, 0)
    qg = lax.psum(qfull, cross_axis)
    sg = lax.psum(sfull, cross_axis)
    shard_red = (qg.astype(jnp.float32) * sg[..., None]).reshape(
        nc, nb * blk)[:, :seg].reshape(sn).astype(x.dtype)
    li = lax.axis_index(local_axis)
    full = jnp.zeros((n,), x.dtype)
    full = lax.dynamic_update_slice_in_dim(full, shard_red, li * sn, 0)
    out = lax.psum(full, local_axis).reshape(x.shape)
    if residual is None:
        return out, None
    rows = jnp.arange(nc)[:, None, None]
    err_all = err1 + jnp.where(rows == ci, err2[None], 0.0)
    err_sh = err_all.reshape(nc, nb * blk)[:, :seg].reshape(sn)
    res_full = lax.dynamic_update_slice_in_dim(
        jnp.zeros((n,), jnp.float32), err_sh, li * sn, 0)
    return out, res_full.reshape(x.shape).astype(residual.dtype)


class TestWireEquivalence:
    """Compiler output vs the pre-refactor bodies, bitwise."""

    def _run(self, fn, in_specs, out_specs, *args):
        return hvd.shard_map(fn, mesh=mesh_2x4(), in_specs=in_specs,
                             out_specs=out_specs)(*args)

    def test_tree_psum_bit_identical(self):
        # Flat per-rank payloads with dim 0 divisible by local_size, so
        # the tree path engages (not its non-divisible flat fallback).
        x = np.random.RandomState(0).randn(8, 256).astype(np.float32)
        spec = P(hvd.HVD_AXES)
        got = self._run(
            lambda xs: hvd.allreduce(xs[0], op=hvd.Sum,
                                     hierarchical=True),
            (spec,), P(), x)
        ref = self._run(lambda xs: _ref_tree_psum(xs[0]), (spec,), P(), x)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        np.testing.assert_allclose(np.asarray(got), x.sum(axis=0),
                                   rtol=1e-4, atol=1e-5)

    def test_tree_psum_nondivisible_falls_back_flat(self):
        # dim 0 = 1 per rank (not divisible by local_size=4): the tree
        # plan's fallback leg must equal the flat psum bitwise — the
        # pre-refactor remainder contract.
        x = np.random.RandomState(5).randn(8, 7).astype(np.float32)
        spec = P(hvd.HVD_AXES)
        got = self._run(
            lambda xs: hvd.allreduce(xs, op=hvd.Sum, hierarchical=True),
            (spec,), P(), x)
        ref = self._run(
            lambda xs: lax.psum(xs, (basics.CROSS_AXIS,
                                     basics.LOCAL_AXIS)),
            (spec,), P(), x)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    @pytest.mark.parametrize("with_ef", [False, True])
    def test_quantized_allreduce_bit_identical(self, with_ef):
        rng = np.random.RandomState(1)
        x = rng.randn(8, 1024).astype(np.float32)
        res = (rng.randn(8, 1024).astype(np.float32) * 1e-3
               if with_ef else None)
        spec = P(hvd.HVD_AXES)

        def got_fn(xs, rs=None):
            if with_ef:
                out, nr = hvd.quantized_allreduce(xs, rs, op=hvd.Sum,
                                                  block=256)
                return out, nr
            return hvd.allreduce(xs, op=hvd.Sum, quantized=True,
                                 block=256)

        def ref_fn(xs, rs=None):
            out, nr = _ref_quant_allreduce(xs, rs, 256, nl=4, nc=2)
            return (out, nr) if with_ef else out

        if with_ef:
            got = self._run(got_fn, (spec, spec), (P(), spec), x, res)
            ref = self._run(ref_fn, (spec, spec), (P(), spec), x, res)
            np.testing.assert_array_equal(np.asarray(got[0]),
                                          np.asarray(ref[0]))
            np.testing.assert_array_equal(np.asarray(got[1]),
                                          np.asarray(ref[1]))
        else:
            got = self._run(got_fn, (spec,), P(), x)
            ref = self._run(ref_fn, (spec,), P(), x)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(ref))

    def test_quantized_rs_ag_split_telescopes_to_allreduce(self):
        # The ZeRO wire pair (rs plan + ag plan, no update in between)
        # must reproduce the quantized allreduce's stateless value
        # exactly for replicated-by-construction inputs: same legs, same
        # order, split in half.
        rng = np.random.RandomState(2)
        flat = rng.randn(N * 512).astype(np.float32)
        spec = P(hvd.HVD_AXES)
        xs = np.broadcast_to(flat, (N,) + flat.shape).copy()

        def split_fn(xrow):
            x = xrow[0]
            shard = hvd.reduce_scatter(x, op=hvd.Sum, quantized=True,
                                       block=256)
            return hvd.all_gather(shard, quantized=True, block=256)

        got = self._run(split_fn, (spec,), P(), xs)
        assert np.asarray(got).shape == flat.shape
        # Structure check: the wire actually moved int8 on DCN (the
        # accounting's fp-equivalent ratio is ~3.94x).
        with hvd.record_wire_stats() as ws:
            jax.jit(hvd.shard_map(split_fn, mesh=mesh_2x4(),
                                  in_specs=(spec,),
                                  out_specs=P())).lower(xs)
        assert ws.dcn_reduction == pytest.approx(3.94, abs=0.1)

    def test_flat_psum_unchanged_by_default(self):
        # Default knobs: the plan is the single flat psum — identical to
        # calling lax.psum directly.
        x = np.random.RandomState(3).randn(8, 64).astype(np.float32)
        spec = P(hvd.HVD_AXES)
        got = self._run(lambda xs: hvd.allreduce(xs, op=hvd.Sum),
                        (spec,), P(), x)
        ref = self._run(lambda xs: lax.psum(xs, hvd.HVD_AXES),
                        (spec,), P(), x)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_explicit_plan_equals_boolean_knobs(self):
        x = np.random.RandomState(4).randn(8, 512).astype(np.float32)
        spec = P(hvd.HVD_AXES)
        sp = describe_plan(quantized=True, mesh_shape=(2, 4))
        got = self._run(
            lambda xs: hvd.allreduce(xs, op=hvd.Sum, plan=sp.gradient),
            (spec,), P(), x)
        ref = self._run(
            lambda xs: hvd.allreduce(xs, op=hvd.Sum, quantized=True),
            (spec,), P(), x)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# Equivalence matrix, optimizer level: every knob combination still
# trains, and the plan-space invariants hold (overlap placement is
# bit-identical to sync; a threaded StepPlan is bit-identical to the
# boolean knobs it encodes).
# ---------------------------------------------------------------------------


def _loss_fn(params, batch):
    x, y = batch
    return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)


def _train(knobs, steps=3, seed=0):
    rng = np.random.RandomState(seed)
    d = 5
    x = rng.randn(96, d).astype(np.float32)
    y = (x @ rng.randn(d, 1).astype(np.float32)).astype(np.float32)
    params = {"w": jnp.zeros((d, 1)), "b": jnp.zeros((1,))}
    zero = knobs.get("zero_stage", 0) > 0
    via_plan = knobs.pop("via_plan", False)
    # Reduce-in-optimizer structure everywhere (the canonical bench/ZeRO
    # step shape): raw per-rank local gradients reach the optimizer, so
    # the gradient wire under test is ALWAYS the optimizer's plan.
    vg = hvd.value_and_grad(_loss_fn, reduce=False)
    if via_plan:
        sp = describe_plan(mesh_shape=(2, 4), **knobs)
        tx = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                      plan=sp)
    else:
        tx = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                      **knobs)
    state = tx.init(params)
    mesh = mesh_2x4()
    if zero:
        sspec = hvd.zero_state_pspecs(state)
        state = jax.device_put(
            state,
            jax.tree.map(lambda s: NamedSharding(mesh, s), sspec))
    elif knobs.get("quantized"):
        sspec = hvd.QuantizedEFState(
            inner=jax.tree.map(lambda _: P(), state.inner),
            residual=jax.tree.map(lambda _: P(hvd.HVD_AXES),
                                  state.residual))
        state = jax.device_put(
            state,
            jax.tree.map(lambda s: NamedSharding(mesh, s), sspec))
    else:
        sspec = jax.tree.map(lambda _: P(), state)

    @jax.jit
    def step(params, state, xb, yb):
        def spmd(params, state, xb, yb):
            loss, grads = vg(params, (xb, yb))
            updates, ns = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), ns, \
                hvd.allreduce(loss)

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), sspec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), sspec, P()))(params, state, xb, yb)

    losses = []
    bs = 16
    for i in range(steps):
        params, state, loss = step(params, state,
                                   jnp.asarray(x[i * bs:(i + 1) * bs]),
                                   jnp.asarray(y[i * bs:(i + 1) * bs]))
        losses.append(float(loss))
    return params, losses


_MATRIX = [
    dict(quantized=False, zero_stage=0, hierarchical=False),
    dict(quantized=False, zero_stage=0, hierarchical=True),
    dict(quantized=True, zero_stage=0),
    dict(quantized=False, zero_stage=2),
    dict(quantized=True, zero_stage=2),
    dict(quantized=False, zero_stage=3),
]


class TestOptimizerMatrix:
    @pytest.mark.parametrize("knobs", _MATRIX, ids=lambda k: (
        f"q{int(k.get('quantized', False))}"
        f"z{k.get('zero_stage', 0)}"
        f"h{int(k.get('hierarchical') or 0)}"))
    def test_overlap_placement_is_bit_identical(self, knobs):
        """Every knob point: overlap-on == overlap-off, bitwise (stream
        placement is a plan attribute, never math — the invariant the
        pre-refactor paths guaranteed and the compiler must keep)."""
        if knobs.get("zero_stage", 0) == 3:
            pytest.skip("stage 3 restructures the loop (params are "
                        "shards) — covered by test_zero's stage suite")
        p_sync, l_sync = _train({**knobs, "overlap": False})
        p_ovl, l_ovl = _train({**knobs, "overlap": True,
                               "num_comm_streams": 2})
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), p_sync, p_ovl)
        assert l_sync == l_ovl
        assert l_sync[-1] < l_sync[0]  # it actually trains

    @pytest.mark.parametrize("knobs", [
        dict(quantized=False, zero_stage=0, hierarchical=False),
        dict(quantized=True, zero_stage=0),
        dict(quantized=False, zero_stage=2),
    ], ids=("plain", "quant", "zero2"))
    def test_step_plan_thread_matches_booleans(self, knobs):
        """DistributedOptimizer(plan=describe_plan(**knobs)) trains
        bit-identically to the boolean spelling of the same knobs."""
        p_bool, l_bool = _train(dict(knobs))
        p_plan, l_plan = _train({**knobs, "via_plan": True})
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), p_bool, p_plan)
        assert l_bool == l_plan


# ---------------------------------------------------------------------------
# 3-level (pods) smoke: plan-compiled allreduce on an emulated 2x2x2
# (pod, cross, local) mesh + --mesh-shape parsing.
# ---------------------------------------------------------------------------


class TestThreeLevel:
    @pytest.fixture()
    def mesh_2x2x2(self):
        grid = np.array(jax.devices()[:N]).reshape(2, 2, 2)
        return Mesh(grid, basics.ALL_AXES)

    def test_flat_allreduce_smoke(self, mesh_2x2x2):
        x = np.arange(16, dtype=np.float32).reshape(8, 2)
        spec = P(basics.ALL_AXES)
        out = hvd.shard_map(
            lambda xs: hvd.allreduce(xs, op=hvd.Sum),
            mesh=mesh_2x2x2, in_specs=(spec,), out_specs=P())(x)
        np.testing.assert_allclose(np.asarray(out)[0], x.sum(axis=0))

    def test_tree_allreduce_smoke(self, mesh_2x2x2):
        # Per-rank payload dim 0 divisible by local_size=2 so the
        # 3-level [ici.rs > dcn.psum > pod.psum > ici.ag] ladder engages.
        x = np.random.RandomState(0).randn(8, 32).astype(np.float32)
        spec = P(basics.ALL_AXES)
        out = hvd.shard_map(
            lambda xs: hvd.allreduce(xs[0], op=hvd.Sum,
                                     hierarchical=True),
            mesh=mesh_2x2x2, in_specs=(spec,), out_specs=P())(x)
        np.testing.assert_allclose(np.asarray(out), x.sum(axis=0),
                                   rtol=1e-5)

    def test_rank_covers_pods(self, mesh_2x2x2):
        spec = P(basics.ALL_AXES)
        ranks = hvd.shard_map(
            lambda: hvd.rank()[None],
            mesh=mesh_2x2x2, in_specs=(), out_specs=spec)()
        assert sorted(np.asarray(ranks).ravel().tolist()) == list(range(8))

    def test_hvd_axes_in_trace_includes_pod(self, mesh_2x2x2):
        seen = {}

        def probe():
            seen["axes"] = C._hvd_axes_in_trace()
            return jnp.zeros(())

        hvd.shard_map(probe, mesh=mesh_2x2x2, in_specs=(),
                      out_specs=P())()
        assert seen["axes"] == basics.ALL_AXES

    def test_build_mesh_pods_one_collapses_to_2d(self):
        m = basics._build_mesh(jax.devices()[:N], (2, 4, 1))
        assert m.devices.shape == (2, 4)
        m3 = basics._build_mesh(jax.devices()[:N], (2, 2, 2))
        assert m3.devices.shape == (2, 2, 2)
        assert m3.axis_names == basics.ALL_AXES

    def test_bench_mesh_shape_parsing(self):
        import bench

        assert bench.parse_mesh_shape("2x4") == (2, 4)
        assert bench.parse_mesh_shape("2x2x2") == (2, 2, 2)
        assert bench.parse_mesh_shape("2,2,2") == (2, 2, 2)
        with pytest.raises(ValueError, match="CROSSxLOCAL"):
            bench.parse_mesh_shape("2x")
        with pytest.raises(ValueError, match="CROSSxLOCAL"):
            bench.parse_mesh_shape("2x2x2x2")
        with pytest.raises(ValueError, match=">= 1"):
            bench.parse_mesh_shape("0x8")
        assert bench.mesh_shape_str((2, 2, 2)) == "2x2x2"
