"""Quantized allreduce: blockwise int8 round-trip bounds, error-feedback
convergence vs full precision, compiled-mesh correctness over an emulated
2-host topology, and the default-off (bit-identical) contract.

The numerics tiers mirror how the feature is layered:

* pure quantization math (``ops/compression.py``) — no mesh needed;
* a 4-rank EF-SGD simulation built from the same primitives — the
  toy-model convergence criterion (quantized-with-EF loss within 1% of
  full precision);
* the real compiled collective (the quantized allreduce plan lowered by
  ``plan/compiler.py lower_quantized_allreduce``, docs/wire-plan.md)
  under ``jax.shard_map`` on a (2, 4) mesh, where the cross axis is the
  DCN-analogue hop that actually carries int8;
* the eager multi-process path in ``test_native_core``-style worker
  processes (``quantized_worker.py``).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops as C
from horovod_tpu.ops import compression as Z
from horovod_tpu.ops import fusion

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N = 8


def mesh_2x4() -> Mesh:
    """An emulated 2-host x 4-chip topology over the 8 CPU devices: the
    cross axis is the DCN hop the quantization compresses."""
    return Mesh(np.array(jax.devices()[:N]).reshape(2, 4), hvd.HVD_AXES)


class TestRoundTrip:
    def test_error_bounded_per_block(self):
        rs = np.random.RandomState(0)
        for n in (256, 1000, 64, 513):
            x = (rs.randn(n) * rs.uniform(0.1, 100)).astype(np.float32)
            q, s, meta = Z.quantize_int8(x)
            y = np.asarray(Z.dequantize_int8(q, s, meta))
            # Round-to-nearest: per-element error <= half an int8 step of
            # that element's block.
            bound = np.repeat(np.asarray(s) / 2, Z.QUANT_BLOCK)[:n]
            assert np.all(np.abs(x - y) <= bound + 1e-7)

    def test_zeros_exact_and_scale_guard(self):
        q, s, meta = Z.quantize_int8(jnp.zeros(512, jnp.float32))
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.asarray(s) == 1.0)  # 0/0 guard
        np.testing.assert_array_equal(
            np.asarray(Z.dequantize_int8(q, s, meta)), np.zeros(512))

    def test_absmax_is_exact(self):
        # The block's absmax maps to +-127 exactly and dequantizes back to
        # itself: the format never clips real data.
        x = np.linspace(-3.0, 3.0, 256).astype(np.float32)
        y = np.asarray(Z.fake_quantize_int8(x))
        assert y[0] == x[0] and y[-1] == x[-1]
        q, _, _ = Z.quantize_int8(x)
        assert np.asarray(q).min() == -127 and np.asarray(q).max() == 127

    def test_shape_dtype_preserved(self):
        rs = np.random.RandomState(1)
        for dtype in (jnp.float32, jnp.bfloat16):
            x = jnp.asarray(rs.randn(3, 5, 7), dtype)
            y = Z.fake_quantize_int8(x)
            assert y.shape == x.shape and y.dtype == x.dtype

    def test_fake_quant_idempotent(self):
        # Quantizing a quantized tensor is the identity: the absmax (hence
        # every scale) survives the first round trip exactly.
        x = jnp.asarray(np.random.RandomState(2).randn(777), jnp.float32)
        once = Z.fake_quantize_int8(x)
        twice = Z.fake_quantize_int8(once)
        np.testing.assert_array_equal(np.asarray(once), np.asarray(twice))

    def test_compressor_api(self):
        x = jnp.asarray(np.random.RandomState(3).randn(300), jnp.float32)
        wire, ctx = hvd.Compression.int8.compress(x)
        assert wire.dtype == x.dtype  # fake-quant, not a cast
        np.testing.assert_array_equal(
            np.asarray(wire), np.asarray(Z.fake_quantize_int8(x)))
        np.testing.assert_array_equal(
            np.asarray(hvd.Compression.int8.decompress(wire, ctx)),
            np.asarray(wire))
        i = jnp.arange(8, dtype=jnp.int32)
        wi, _ = hvd.Compression.int8.compress(i)
        np.testing.assert_array_equal(np.asarray(wi), np.asarray(i))


class TestErrorFeedbackConvergence:
    """The toy-model criterion: EF-quantized training matches full
    precision within 1% — built from the same quantize primitives the
    compiled wire uses, so it runs on any backend."""

    @staticmethod
    def _problem(seed=0, ranks=4, n=256, d=64):
        rs = np.random.RandomState(seed)
        X = rs.randn(ranks, n, d).astype(np.float32)
        w_true = rs.randn(d).astype(np.float32)
        y = np.einsum("knd,d->kn", X, w_true) + 0.01 * rs.randn(ranks, n)
        return X, y.astype(np.float32)

    @staticmethod
    def _grads(X, y, w):
        r = np.einsum("knd,d->kn", X, w) - y
        return 2.0 / X.shape[1] * np.einsum("knd,kn->kd", X, r)

    @staticmethod
    def _loss(X, y, w):
        r = np.einsum("knd,d->kn", X, w) - y
        return float(np.mean(r ** 2))

    def test_ef_training_matches_full_precision(self):
        X, y = self._problem()
        ranks, _, d = X.shape
        lr, steps = 0.05, 200

        w_fp = np.zeros(d, np.float32)
        for _ in range(steps):
            w_fp -= lr * self._grads(X, y, w_fp).mean(0)

        w_q = np.zeros(d, np.float32)
        res = np.zeros((ranks, d), np.float32)
        for _ in range(steps):
            g = self._grads(X, y, w_q)
            corrected = g + res
            sent = np.stack([np.asarray(Z.fake_quantize_int8(
                jnp.asarray(corrected[k]))) for k in range(ranks)])
            res = corrected - sent  # EF: carry the error to the next step
            w_q -= lr * sent.mean(0)

        lf, lq = self._loss(X, y, w_fp), self._loss(X, y, w_q)
        assert abs(lq - lf) / lf < 0.01, (lq, lf)

    def test_residual_stays_bounded(self):
        # EF residuals must not grow: each step's residual is one
        # quantization error, not an accumulating sum.
        X, y = self._problem(seed=1)
        ranks, _, d = X.shape
        w = np.zeros(d, np.float32)
        res = np.zeros((ranks, d), np.float32)
        norms = []
        for _ in range(60):
            g = self._grads(X, y, w)
            corrected = g + res
            sent = np.stack([np.asarray(Z.fake_quantize_int8(
                jnp.asarray(corrected[k]))) for k in range(ranks)])
            res = corrected - sent
            w -= 0.05 * sent.mean(0)
            norms.append(float(np.abs(res).max()))
        assert max(norms[30:]) <= 2 * max(norms[:10]) + 1e-6


class TestQuantizedAllreduceCompiled:
    """The real int8 collective over the (cross=2, local=4) mesh."""

    def _inputs(self, n=1024, seed=0, dtype=np.float32):
        return np.random.RandomState(seed).randn(N, n).astype(dtype)

    @staticmethod
    def _tolerance(x):
        """Analytic error bound: nc quantized contributions on the reduce
        hop plus one requantization on the gather hop, each off by at most
        half a step of its block's scale."""
        shard_sums = x.reshape(2, 4, -1).sum(1)  # ICI-reduced shards
        s1 = np.abs(shard_sums).max() / 127.0
        s2 = np.abs(x.sum(0)).max() / 127.0
        return 2 * (s1 / 2) + s2 / 2 + 1e-5

    def test_matches_exact_within_block_bound(self):
        x = self._inputs()

        def spmd(v):
            out, _ = hvd.quantized_allreduce(v[0], op=hvd.Sum)
            return out

        out = hvd.shard_map(spmd, mesh=mesh_2x4(),
                            in_specs=P(hvd.HVD_AXES),
                            out_specs=P())(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), x.sum(0),
                                   atol=self._tolerance(x))

    def test_replicated_output_and_all_ranks_agree(self):
        # out_specs=P() above already forces provable replication; here the
        # per-rank views are compared value-for-value too.
        x = self._inputs(seed=3)

        def spmd(v):
            out, _ = hvd.quantized_allreduce(v[0], op=hvd.Sum)
            return out[None]

        out = np.asarray(hvd.shard_map(
            spmd, mesh=mesh_2x4(), in_specs=P(hvd.HVD_AXES),
            out_specs=P(hvd.HVD_AXES))(jnp.asarray(x)))
        for r in range(1, N):
            np.testing.assert_array_equal(out[r], out[0])

    def test_average_op(self):
        x = self._inputs(seed=4)

        def spmd(v):
            out, _ = hvd.quantized_allreduce(v[0], op=hvd.Average)
            return out

        out = hvd.shard_map(spmd, mesh=mesh_2x4(),
                            in_specs=P(hvd.HVD_AXES),
                            out_specs=P())(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), x.mean(0),
                                   atol=self._tolerance(x) / N)

    def test_bf16_payload(self):
        # HiCCL placement: the ICI legs ride the payload dtype (bf16 when
        # combined with Compression.bf16); output returns as fp32.
        x = self._inputs(seed=5, dtype=np.float32)

        def spmd(v):
            return hvd.allreduce(v[0], op=hvd.Sum,
                                 compression=hvd.Compression.bf16,
                                 quantized=True)

        out = hvd.shard_map(spmd, mesh=mesh_2x4(),
                            in_specs=P(hvd.HVD_AXES),
                            out_specs=P())(jnp.asarray(x))
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=0.15,
                                   atol=0.5)

    def test_error_feedback_telescopes(self):
        # Sum of quantized outputs over many steps tracks the exact sum to
        # within one residual: errors are carried, never accumulated.
        rs = np.random.RandomState(6)
        n = 512

        def spmd(v, r):
            out, nr = hvd.quantized_allreduce(v[0], r[0], op=hvd.Sum)
            return out, nr[None]

        f = jax.jit(hvd.shard_map(
            spmd, mesh=mesh_2x4(),
            in_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), P(hvd.HVD_AXES))))
        res = jnp.zeros((N, n), jnp.float32)
        total_exact = np.zeros(n)
        total_quant = np.zeros(n)
        for _ in range(30):
            g = rs.randn(N, n).astype(np.float32)
            out, res = f(jnp.asarray(g), res)
            total_exact += g.sum(0)
            total_quant += np.asarray(out)
        drift = np.abs(total_quant - total_exact).max()
        # Residual-bounded (one step's error), NOT O(sqrt(steps)) growth.
        per_step = self._tolerance(g)
        assert drift <= 3 * per_step, (drift, per_step)

    def test_default_off_bit_identical(self):
        # HOROVOD_QUANTIZED_ALLREDUCE defaults to 0 and the default path is
        # byte-for-byte today's unquantized allreduce.
        from horovod_tpu.common import basics

        assert not basics.config().quantized_allreduce
        x = self._inputs(seed=7)

        def run(**kw):
            return np.asarray(hvd.shard_map(
                lambda v: hvd.allreduce(v[0], op=hvd.Sum, **kw),
                mesh=mesh_2x4(), in_specs=P(hvd.HVD_AXES),
                out_specs=P())(jnp.asarray(x)))

        np.testing.assert_array_equal(run(), run(quantized=False))

    def test_non_divisible_falls_back_exact(self):
        x = self._inputs(n=37, seed=8)  # 37 doesn't shard over 8

        def spmd(v):
            out, r = hvd.quantized_allreduce(v[0], v[0] * 0, op=hvd.Sum)
            return out, r[None]

        out, res = hvd.shard_map(
            spmd, mesh=mesh_2x4(), in_specs=P(hvd.HVD_AXES),
            out_specs=(P(), P(hvd.HVD_AXES)))(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)
        assert np.all(np.asarray(res) == 0)  # consumed, nothing lost


class TestQuantizedPytree:
    def test_fused_buckets_with_error_feedback(self):
        rs = np.random.RandomState(9)
        tree = {
            "w": jnp.asarray(rs.randn(N, 16, 8), jnp.float32),
            "b": jnp.asarray(rs.randn(N, 24), jnp.float32),
            "step": jnp.asarray(rs.randint(0, 5, (N,)), jnp.int32),
        }

        def spmd(t):
            local = jax.tree.map(lambda v: v[0], t)
            ef = jax.tree.map(jnp.zeros_like, local)
            out, new_ef = fusion.allreduce_pytree(
                local, op=hvd.Sum, quantized=True, error_feedback=ef)
            return out, jax.tree.map(lambda a: a[None], new_ef)

        out, ef = hvd.shard_map(
            spmd, mesh=mesh_2x4(), in_specs=P(hvd.HVD_AXES),
            out_specs=(P(), P(hvd.HVD_AXES)))(tree)
        x = np.asarray(tree["w"])
        np.testing.assert_allclose(
            np.asarray(out["w"]), x.sum(0),
            atol=np.abs(x).sum(0).max() / 40)
        # int leaves ride the exact wire and keep a zero residual
        np.testing.assert_array_equal(np.asarray(out["step"]),
                                      np.asarray(tree["step"]).sum(0))
        assert np.all(np.asarray(ef["step"]) == 0)
        # float residuals match the structure and dtypes of the gradients
        assert ef["w"].dtype == jnp.float32
        assert ef["w"].shape == tree["w"].shape

    def test_wire_stats_report_dcn_reduction(self):
        # The bench's acceptance instrumentation: the quantized bucket's
        # DCN bytes shrink >= 3.5x vs the same traffic at fp32.
        tree = [jnp.asarray(np.random.RandomState(10).randn(N, 4096),
                            jnp.float32)]

        def spmd(t):
            local = [v[0] for v in t]
            return fusion.allreduce_pytree(local, op=hvd.Sum,
                                           quantized=True)

        f = jax.jit(hvd.shard_map(spmd, mesh=mesh_2x4(),
                                  in_specs=P(hvd.HVD_AXES), out_specs=P()))
        with C.record_wire_stats() as ws:
            f.lower(tree)  # accounting happens at trace time
        assert ws.dcn_bytes > 0
        assert ws.dcn_reduction >= 3.5, ws.dcn_reduction
        assert ws.ici_bytes > 0


class TestMultiProcessQuantized:
    """Eager quantized semantics across real worker processes (the
    reference's `mpirun -np N` tier): HOROVOD_QUANTIZED_ALLREDUCE=1 fake-
    quantizes each rank's contribution before the native-core wire."""

    def test_world_2(self):
        import test_native_core as tnc

        tnc._run_world(
            2, {"HOROVOD_QUANTIZED_ALLREDUCE": "1"},
            worker=os.path.join(REPO, "tests", "quantized_worker.py"))

    def test_world_3(self):
        import test_native_core as tnc

        tnc._run_world(
            3, {"HOROVOD_QUANTIZED_ALLREDUCE": "1"},
            worker=os.path.join(REPO, "tests", "quantized_worker.py"))
