"""Stall-inspector integration test (reference:
test/integration/test_stall.py): a 2-process world where rank 1 lags
past the warning threshold — the coordinator must emit the stall
warning naming the stalled tensor and the ready/missing ranks, and the
job must still complete once the laggard arrives."""

import os

from test_native_core import REPO, _run_world

WORKER = os.path.join(REPO, "tests", "stall_worker.py")


def test_stall_warning_names_ready_and_missing_ranks():
    outs = _run_world(2, {
        "HOROVOD_STALL_CHECK_TIME_SECONDS": "1",
        "HOROVOD_LOG_LEVEL": "warning",
        "STALL_WORKER_LAG": "3",
    }, worker=WORKER)
    combined = "\n".join(outs)
    # The coordinator (rank 0) warned about the stalled tensor with the
    # rank bookkeeping, and both ranks finished the job afterwards.
    assert "waiting for remainder of ranks" in combined, combined
    assert "stalled.t" in combined
    assert "ready ranks: 0" in combined
    assert "missing ranks: 1" in combined
    for r in range(2):
        assert f"stall worker rank {r}: OK" in combined


def test_no_warning_under_threshold():
    outs = _run_world(2, {
        "HOROVOD_STALL_CHECK_TIME_SECONDS": "30",
        "HOROVOD_LOG_LEVEL": "warning",
        "STALL_WORKER_LAG": "1",
    }, worker=WORKER)
    combined = "\n".join(outs)
    assert "waiting for remainder of ranks" not in combined, combined
