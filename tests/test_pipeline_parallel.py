"""Pipeline parallelism: GPipe schedule on the virtual mesh.

The pipelined model must be EXACT against the dense model — the schedule
(microbatch relay over ppermute with masked output writes) is a
reorganization of the same layer-by-layer computation — including
gradients through the scan/ppermute/psum backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.parallel.pipeline import (
    gpipe,
    pipelined_gpt_apply,
    pp_split_blocks,
)


class TestGPipe:
    def test_scalar_stages(self):
        """Each stage multiplies by its own scalar: the pipeline output is
        x * prod(scalars), per microbatch."""
        mesh = hvd.mesh()
        n = hvd.size()
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(6, 4, 8), jnp.float32)   # [M, mb, d]
        scalars = jnp.asarray(rs.rand(n) + 0.5, jnp.float32)

        def spmd(x, s):
            return gpipe(lambda p, h: h * p[0], s[:, None], x,
                         axis=hvd.HVD_AXES)

        out = jax.jit(hvd.shard_map(
            spmd, mesh=mesh, in_specs=(P(), P(hvd.HVD_AXES)),
            out_specs=P()))(x, scalars)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(x * jnp.prod(scalars)),
                                   rtol=1e-5)

    def test_world_one_fallback(self):
        x = jnp.ones((3, 2, 4))
        out = gpipe(lambda p, h: h + p, 1.5, x, axis=())
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) + 1.5)


class TestPipelinedGPT:
    def _setup(self, L=8, B=4, T=16, seed=0):
        cfg = gpt_tiny(dtype=jnp.float32, num_layers=L)
        rs = np.random.RandomState(seed)
        tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
        variables = GPT(cfg).init(jax.random.PRNGKey(0), tokens)
        return cfg, variables["params"], tokens

    def test_pp8_matches_dense(self):
        """8 stages x 1 block over the full mesh == the dense model."""
        cfg, params, tokens = self._setup()
        expect = GPT(cfg).apply({"params": params}, tokens)
        stages, rest = pp_split_blocks(params, hvd.size())
        mesh = hvd.mesh()

        def spmd(stg, rst, tok):
            local = jax.tree.map(lambda a: a[0], stg)
            return pipelined_gpt_apply(cfg, local, rst, tok,
                                       axis=hvd.HVD_AXES,
                                       num_microbatches=2)

        out = jax.jit(hvd.shard_map(
            spmd, mesh=mesh, in_specs=(P(hvd.HVD_AXES), P(), P()),
            out_specs=P()))(stages, rest, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)

    def test_seq_parallel_attention_overlapping_pp_axis_rejected(self):
        """A ring/flash_ring/ulysses seq_axis that intersects the pipeline
        axis would rotate K/V between ranks holding DIFFERENT stages —
        must raise, mirroring the MoE guard (advisor r3)."""
        import dataclasses

        cfg, params, tokens = self._setup()
        stages, rest = pp_split_blocks(params, hvd.size())
        bad = dataclasses.replace(cfg, attention="ring",
                                  seq_axis=hvd.HVD_AXES)

        def spmd(stg, rst, tok):
            local = jax.tree.map(lambda a: a[0], stg)
            return pipelined_gpt_apply(bad, local, rst, tok,
                                       axis=hvd.HVD_AXES,
                                       num_microbatches=2)

        with pytest.raises(ValueError, match="overlaps the pipeline"):
            jax.jit(hvd.shard_map(
                spmd, mesh=hvd.mesh(),
                in_specs=(P(hvd.HVD_AXES), P(), P()),
                out_specs=P()))(stages, rest, tokens)

    def test_tp_axis_rejected(self):
        """tp_axis with un-tp-sliced stage params would psum complete
        outputs tp-fold — must raise, like the seq-axis/MoE guards."""
        import dataclasses

        cfg, params, tokens = self._setup()
        stages, rest = pp_split_blocks(params, hvd.size())
        bad = dataclasses.replace(cfg, tp_axis=hvd.LOCAL_AXIS)

        def spmd(stg, rst, tok):
            local = jax.tree.map(lambda a: a[0], stg)
            return pipelined_gpt_apply(bad, local, rst, tok,
                                       axis=hvd.HVD_AXES,
                                       num_microbatches=2)

        with pytest.raises(ValueError, match="tp_axis"):
            jax.jit(hvd.shard_map(
                spmd, mesh=hvd.mesh(),
                in_specs=(P(hvd.HVD_AXES), P(), P()),
                out_specs=P()))(stages, rest, tokens)

    def test_dp_pp_2d(self):
        """DP over hvd_cross x PP over hvd_local: batch-sharded pipelined
        forward equals the dense model."""
        mesh = hvd.mesh()
        n_pp = int(mesh.devices.shape[1])
        n_dp = int(mesh.devices.shape[0])
        # 2 microbatches x 2 sequences per DP shard, whatever the mesh.
        cfg, params, tokens = self._setup(L=2 * n_pp, B=4 * n_dp, seed=3)
        expect = GPT(cfg).apply({"params": params}, tokens)
        stages, rest = pp_split_blocks(params, n_pp)

        def spmd(stg, rst, tok):
            local = jax.tree.map(lambda a: a[0], stg)
            return pipelined_gpt_apply(cfg, local, rst, tok,
                                       axis=hvd.LOCAL_AXIS,
                                       num_microbatches=2)

        out = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.CROSS_AXIS)),
            out_specs=P(hvd.CROSS_AXIS)))(stages, rest, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-4)

    def test_pipelined_loss_matches_dense(self):
        """pipelined_gpt_loss (vocab-sharded head over the pipeline
        ranks) equals the dense model's mean cross-entropy, value AND
        gradients."""
        import optax

        from horovod_tpu.parallel.pipeline import pipelined_gpt_loss

        cfg, params, tokens = self._setup(seed=4)
        rs = np.random.RandomState(9)
        targets = jnp.asarray(
            rs.randint(0, cfg.vocab_size, tokens.shape))
        n = hvd.size()
        stages, rest = pp_split_blocks(params, n)
        mesh = hvd.mesh()

        def pp_loss(stages, rest):
            def spmd(stg, rst, tok, tgt):
                local = jax.tree.map(lambda a: a[0], stg)
                return pipelined_gpt_loss(cfg, local, rst, tok, tgt,
                                          axis=hvd.HVD_AXES,
                                          num_microbatches=2)

            return hvd.shard_map(
                spmd, mesh=mesh,
                in_specs=(P(hvd.HVD_AXES), P(), P(), P()),
                out_specs=P())(stages, rest, tokens, targets)

        def dense_loss(params):
            logits = GPT(cfg).apply({"params": params}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        (loss, (g_stages, g_rest)) = jax.jit(
            jax.value_and_grad(pp_loss, argnums=(0, 1)))(stages, rest)
        want_loss, g_dense = jax.value_and_grad(dense_loss)(params)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(g_rest["wte"]), np.asarray(g_dense["wte"]),
            rtol=1e-3, atol=1e-6)
        got = jax.tree.map(lambda a: np.asarray(a[2, 0]), g_stages)
        want = jax.tree.map(np.asarray, g_dense["h2"])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3,
                                                    atol=1e-6),
            got, want)

    def test_pipelined_loss_world1(self):
        import optax

        from horovod_tpu.parallel.pipeline import pipelined_gpt_loss

        cfg, params, tokens = self._setup(L=2, B=2, T=8, seed=5)
        rs = np.random.RandomState(10)
        targets = jnp.asarray(rs.randint(0, cfg.vocab_size, tokens.shape))
        stages, rest = pp_split_blocks(params, 1)
        local = jax.tree.map(lambda a: a[0], stages)
        loss = pipelined_gpt_loss(cfg, local, rest, tokens, targets,
                                  axis=hvd.LOCAL_AXIS, num_microbatches=2)
        logits = GPT(cfg).apply({"params": params}, tokens)
        want = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()
        np.testing.assert_allclose(float(loss), float(want), rtol=2e-5)

    def test_1f1b_matches_dense(self):
        """The 1F1B schedule's fused loss+grads equal the dense model's
        (loss, wte/wpe/ln_f grads, per-stage block grads) — the same
        contract as pipelined_gpt_loss + jax.grad, at O(n) activation
        memory."""
        import optax

        from horovod_tpu.parallel.pipeline import pipelined_gpt_train_1f1b

        cfg, params, tokens = self._setup(seed=6)
        rs = np.random.RandomState(11)
        targets = jnp.asarray(rs.randint(0, cfg.vocab_size, tokens.shape))
        n = hvd.size()
        stages, rest = pp_split_blocks(params, n)
        mesh = hvd.mesh()

        def spmd(stg, rst, tok, tgt):
            local = jax.tree.map(lambda a: a[0], stg)
            loss, g_st, g_rest = pipelined_gpt_train_1f1b(
                cfg, local, rst, tok, tgt, axis=hvd.HVD_AXES,
                num_microbatches=4)
            return loss, jax.tree.map(lambda a: a[None], g_st), g_rest

        loss, g_stages, g_rest = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.HVD_AXES), P(), P(), P()),
            out_specs=(P(), P(hvd.HVD_AXES), P())))(
            stages, rest, tokens, targets)

        def dense_loss(params):
            logits = GPT(cfg).apply({"params": params}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        want_loss, g_dense = jax.value_and_grad(dense_loss)(params)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(g_rest["wte"]), np.asarray(g_dense["wte"]),
            rtol=1e-3, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(g_rest["wpe"]), np.asarray(g_dense["wpe"]),
            rtol=1e-3, atol=1e-6)
        for s in (0, hvd.size() - 1):
            got = jax.tree.map(lambda a: np.asarray(a[s, 0]), g_stages)
            want = jax.tree.map(np.asarray, g_dense[f"h{s}"])
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=1e-3, atol=1e-6), got, want)

    def test_1f1b_stash_wraps_at_large_m(self):
        """M > 2n-1 makes the input-stash ring buffer actually wrap —
        the schedule's advertised large-M regime; slot reuse and the
        B-before-F collision ordering must stay exact."""
        import optax

        from horovod_tpu.parallel.pipeline import pipelined_gpt_train_1f1b

        n = hvd.size()
        M = 2 * (2 * n - 1)  # = 2S: every ring-buffer slot is reused
        cfg, params, tokens = self._setup(L=n, B=M, T=8, seed=8)
        rs = np.random.RandomState(13)
        targets = jnp.asarray(rs.randint(0, cfg.vocab_size, tokens.shape))
        stages, rest = pp_split_blocks(params, n)

        def spmd(stg, rst, tok, tgt):
            local = jax.tree.map(lambda a: a[0], stg)
            loss, g_st, g_rest = pipelined_gpt_train_1f1b(
                cfg, local, rst, tok, tgt, axis=hvd.HVD_AXES,
                num_microbatches=M)
            return loss, jax.tree.map(lambda a: a[None], g_st), g_rest

        loss, g_stages, g_rest = jax.jit(hvd.shard_map(
            spmd, mesh=hvd.mesh(),
            in_specs=(P(hvd.HVD_AXES), P(), P(), P()),
            out_specs=(P(), P(hvd.HVD_AXES), P())))(
            stages, rest, tokens, targets)

        def dense_loss(params):
            logits = GPT(cfg).apply({"params": params}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        want_loss, g_dense = jax.value_and_grad(dense_loss)(params)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(g_rest["wte"]), np.asarray(g_dense["wte"]),
            rtol=1e-3, atol=1e-6)
        got = jax.tree.map(lambda a: np.asarray(a[0, 0]), g_stages)
        want = jax.tree.map(np.asarray, g_dense["h0"])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3,
                                                    atol=1e-6),
            got, want)

    def test_dp_1f1b_2d(self):
        """DP over cross x 1F1B pipeline over local: per-shard fused
        grads averaged across the data axis equal the dense full-batch
        gradients (the 2-D composition users run at scale)."""
        # The conftest mesh is (1, 8) — re-form as (2, 4) so the data
        # axis is non-trivial (restored in the finally that wraps the
        # WHOLE body: a failure must not leak the mesh to later tests).
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 4))
            self._run_dp_1f1b()
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def test_dp_1f1b_single_stage(self):
        """Degenerate pipeline (n=1) under a real DP axis — the n==1
        fast path must keep the same per-shard gradient contract."""
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices()[:2], mesh_shape=(2, 1))
            self._run_dp_1f1b(expect_pp=1)
        finally:
            hvd.shutdown()
            hvd.init(devices=jax.devices())

    def _run_dp_1f1b(self, expect_pp=None):
        import optax

        from horovod_tpu.parallel.pipeline import pipelined_gpt_train_1f1b

        mesh = hvd.mesh()
        n_dp = int(mesh.devices.shape[0])
        n_pp = int(mesh.devices.shape[1])
        assert n_dp == 2
        if expect_pp is not None:
            assert n_pp == expect_pp
        B = 4 * n_dp
        cfg, params, tokens = self._setup(L=n_pp, B=B, T=8, seed=9)
        rs = np.random.RandomState(14)
        targets = jnp.asarray(rs.randint(0, cfg.vocab_size, tokens.shape))
        stages, rest = pp_split_blocks(params, n_pp)

        def spmd(stg, rst, tok, tgt):
            local = jax.tree.map(lambda a: a[0], stg)
            loss, g_st, g_rest = pipelined_gpt_train_1f1b(
                cfg, local, rst, tok, tgt, axis=hvd.LOCAL_AXIS,
                num_microbatches=2)
            # Data-parallel averaging of the per-shard fused grads.
            loss = hvd.allreduce(loss, op=hvd.Average,
                                 axes=hvd.CROSS_AXIS)
            g_st = hvd.allreduce_pytree(g_st, op=hvd.Average,
                                        axes=hvd.CROSS_AXIS)
            g_rest = hvd.allreduce_pytree(g_rest, op=hvd.Average,
                                          axes=hvd.CROSS_AXIS)
            return loss, jax.tree.map(lambda a: a[None], g_st), g_rest

        loss, g_stages, g_rest = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.CROSS_AXIS),
                      P(hvd.CROSS_AXIS)),
            out_specs=(P(), P(hvd.LOCAL_AXIS), P())))(
            stages, rest, tokens, targets)

        def dense_loss(params):
            logits = GPT(cfg).apply({"params": params}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        want_loss, g_dense = jax.value_and_grad(dense_loss)(params)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(g_rest["wte"]), np.asarray(g_dense["wte"]),
            rtol=1e-3, atol=1e-6)
        got = jax.tree.map(lambda a: np.asarray(a[0, 0]), g_stages)
        want = jax.tree.map(np.asarray, g_dense["h0"])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3,
                                                    atol=1e-6),
            got, want)

    def test_1f1b_world1(self):
        import optax

        from horovod_tpu.parallel.pipeline import pipelined_gpt_train_1f1b

        cfg, params, tokens = self._setup(L=2, B=4, T=8, seed=7)
        rs = np.random.RandomState(12)
        targets = jnp.asarray(rs.randint(0, cfg.vocab_size, tokens.shape))
        stages, rest = pp_split_blocks(params, 1)
        local = jax.tree.map(lambda a: a[0], stages)
        loss, g_st, g_rest = pipelined_gpt_train_1f1b(
            cfg, local, rest, tokens, targets, axis=hvd.LOCAL_AXIS,
            num_microbatches=2)

        def dense_loss(params):
            logits = GPT(cfg).apply({"params": params}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        want_loss, g_dense = jax.value_and_grad(dense_loss)(params)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(g_rest["wte"]), np.asarray(g_dense["wte"]),
            rtol=1e-3, atol=1e-6)

    def test_pp_grads_match_dense(self):
        """Gradients through the pipeline equal the dense gradients (for
        the replicated embedding AND a stage's block weights)."""
        cfg, params, tokens = self._setup(seed=1)
        n = hvd.size()
        stages, rest = pp_split_blocks(params, n)
        mesh = hvd.mesh()
        w = jax.random.normal(jax.random.PRNGKey(2), (cfg.vocab_size,))

        def pp_loss(stages, rest, tok):
            def spmd(stg, rst, tok):
                local = jax.tree.map(lambda a: a[0], stg)
                logits = pipelined_gpt_apply(cfg, local, rst, tok,
                                             axis=hvd.HVD_AXES,
                                             num_microbatches=2)
                return jnp.mean(logits * w)

            return hvd.shard_map(
                spmd, mesh=mesh, in_specs=(P(hvd.HVD_AXES), P(), P()),
                out_specs=P())(stages, rest, tok)

        def dense_loss(params, tok):
            return jnp.mean(GPT(cfg).apply({"params": params}, tok) * w)

        g_stages, g_rest = jax.jit(jax.grad(pp_loss, argnums=(0, 1)))(
            stages, rest, tokens)
        g_dense = jax.grad(dense_loss)(params, tokens)

        np.testing.assert_allclose(
            np.asarray(g_rest["wte"]), np.asarray(g_dense["wte"]),
            rtol=1e-3, atol=1e-6)
        # Stage 3's single block == dense block h3.
        got = jax.tree.map(lambda a: np.asarray(a[3, 0]), g_stages)
        want = jax.tree.map(np.asarray, g_dense["h3"])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3,
                                                    atol=1e-6),
            got, want)


class TestScheduleMemory:
    def test_1f1b_temp_memory_beats_gpipe(self):
        """The 1F1B schedule's claimed O(depth) activation stash vs
        GPipe's O(num_microbatches), verified by the COMPILER: XLA's
        memory analysis of the two compiled programs. At M=16
        microbatches over 8 stages the measured temp-buffer ratio is
        ~10x (254.8 vs 25.6 MiB on the CPU mesh); assert a conservative
        3x so layout/fusion changes don't flake the test while a stash
        regression (re-stashing all M activations) still fails it."""
        from horovod_tpu.parallel.pipeline import (pipelined_gpt_loss,
                                                   pipelined_gpt_train_1f1b)

        M = 16
        cfg = gpt_tiny(dtype=jnp.float32, num_layers=8, d_model=256,
                       d_ff=1024, max_seq_len=128)
        rs = np.random.RandomState(0)
        B, T = 32, 128
        tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
        targets = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
        params = GPT(cfg).init(jax.random.PRNGKey(0), tokens)["params"]
        stages, rest = pp_split_blocks(params, hvd.size())
        mesh = hvd.mesh()

        # tokens/targets are explicit arguments (not closure constants) so
        # both programs lower with the same parameter layout — a closed-over
        # batch would be baked into the GPipe executable as constants and
        # skew the temp-memory comparison.
        def gpipe_loss(stages, rest, tok, tgt):
            def spmd(stg, rst, tok, tgt):
                local = jax.tree.map(lambda a: a[0], stg)
                return pipelined_gpt_loss(cfg, local, rst, tok, tgt,
                                          axis=hvd.HVD_AXES,
                                          num_microbatches=M)

            return hvd.shard_map(
                spmd, mesh=mesh,
                in_specs=(P(hvd.HVD_AXES), P(), P(), P()),
                out_specs=P())(stages, rest, tok, tgt)

        def spmd_1f1b(stg, rst, tok, tgt):
            local = jax.tree.map(lambda a: a[0], stg)
            loss, g_st, g_rest = pipelined_gpt_train_1f1b(
                cfg, local, rst, tok, tgt, axis=hvd.HVD_AXES,
                num_microbatches=M)
            return loss, jax.tree.map(lambda a: a[None], g_st), g_rest

        gpipe_c = jax.jit(
            jax.value_and_grad(gpipe_loss, argnums=(0, 1))).lower(
            stages, rest, tokens, targets).compile()
        f1b1_c = jax.jit(hvd.shard_map(
            spmd_1f1b, mesh=mesh,
            in_specs=(P(hvd.HVD_AXES), P(), P(), P()),
            out_specs=(P(), P(hvd.HVD_AXES), P()))).lower(
            stages, rest, tokens, targets).compile()

        gpipe_tmp = gpipe_c.memory_analysis().temp_size_in_bytes
        f1b1_tmp = f1b1_c.memory_analysis().temp_size_in_bytes
        assert f1b1_tmp * 3 < gpipe_tmp, (
            f"1F1B temp {f1b1_tmp / 2**20:.1f} MiB not <3x GPipe's "
            f"{gpipe_tmp / 2**20:.1f} MiB")
