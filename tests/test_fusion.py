"""Tensor-fusion tests (reference: fusion buffer + FuseResponses logic,
controller.cc:686-809; fused/unfused matrix in test/parallel/test_tensorflow.py)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import fusion

N = 8


def test_plan_buckets_respects_threshold():
    leaves = [jnp.zeros(100, jnp.float32) for _ in range(10)]
    # 100 floats = 400 B; threshold 1000 B → 2 leaves (200 elems ≤ 250) per bucket.
    buckets = fusion.plan_buckets(leaves, threshold_bytes=1000)
    assert all(sum(b.sizes) * 4 <= 1008 for b in buckets)
    covered = sorted(i for b in buckets for i in b.leaf_indices)
    assert covered == list(range(10))


def test_plan_buckets_splits_dtypes():
    leaves = [jnp.zeros(4, jnp.float32), jnp.zeros(4, jnp.bfloat16),
              jnp.zeros(4, jnp.float32)]
    buckets = fusion.plan_buckets(leaves, threshold_bytes=1 << 20)
    dtypes = {b.dtype for b in buckets}
    assert len(buckets) == 2 and len(dtypes) == 2


def test_padding_to_atomic_unit():
    # Reference: FUSION_BUFFER_ATOMIC_UNIT = 64 (common.h:97).
    b = fusion.plan_buckets([jnp.zeros(65)], threshold_bytes=1 << 20)[0]
    assert b.padded_size == 128


def test_plan_buckets_oversized_leaf_own_bucket():
    # A single leaf bigger than the threshold must become its own bucket
    # — never an error, never shared with a following small leaf.
    leaves = [jnp.zeros(10, jnp.float32), jnp.zeros(5000, jnp.float32),
              jnp.zeros(10, jnp.float32)]
    buckets = fusion.plan_buckets(leaves, threshold_bytes=1000)
    by_leaf = {i: b for b in buckets for i in b.leaf_indices}
    assert by_leaf[1].leaf_indices == (1,)
    covered = sorted(i for b in buckets for i in b.leaf_indices)
    assert covered == [0, 1, 2]
    # Leading position too: still alone.
    buckets = fusion.plan_buckets(
        [jnp.zeros(5000, jnp.float32), jnp.zeros(10, jnp.float32)],
        threshold_bytes=1000)
    assert buckets[0].leaf_indices == (0,)
    assert buckets[1].leaf_indices == (1,)


def test_plan_buckets_zero_dim_and_empty_leaves():
    # 0-d and zero-size leaves occupy one slot (the `or 1` path): the
    # plan covers them and pack/unpack round-trips.
    leaves = [jnp.asarray(3.5, jnp.float32), jnp.zeros((0,), jnp.float32),
              jnp.asarray(np.arange(4), jnp.float32)]
    buckets = fusion.plan_buckets(leaves, threshold_bytes=1 << 20)
    assert len(buckets) == 1
    assert buckets[0].sizes == (1, 1, 4)
    covered = sorted(i for b in buckets for i in b.leaf_indices)
    assert covered == [0, 1, 2]
    out = fusion.unpack(buckets[0], fusion.pack(buckets[0], leaves))
    for a, b in zip(leaves, out):
        assert a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_plan_buckets_deterministic():
    # The autotune warm-start cache keys on (tree-hash, mesh, world): the
    # plan must be identical across identical pytrees and process runs.
    def make_leaves(seed):
        rs = np.random.RandomState(seed)
        return [jnp.asarray(rs.randn(n), jnp.float32)
                for n in (100, 7, 300, 1, 50)] + [
                jnp.zeros(9, jnp.bfloat16), jnp.zeros(2, jnp.float32)]

    p1 = fusion.plan_buckets(make_leaves(0), threshold_bytes=800)
    p2 = fusion.plan_buckets(make_leaves(1), threshold_bytes=800)
    assert p1 == p2  # values never enter the plan, only shapes/dtypes


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(0)
    leaves = [jnp.asarray(rng.randn(3, 4), jnp.float32),
              jnp.asarray(rng.randn(7), jnp.float32),
              jnp.asarray(rng.randn(2, 2, 2), jnp.float32)]
    bucket = fusion.plan_buckets(leaves, threshold_bytes=1 << 20)[0]
    buf = fusion.pack(bucket, leaves)
    assert buf.shape[0] == bucket.padded_size
    out = fusion.unpack(bucket, buf)
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_allreduce_pytree_matches_leafwise():
    rng = np.random.RandomState(1)
    tree = {
        "w": jnp.asarray(rng.randn(N, 5, 3), jnp.float32),
        "b": jnp.asarray(rng.randn(N, 7), jnp.float32),
        "scale": jnp.asarray(rng.randn(N), jnp.float32),
    }

    def f(t):
        local = jax.tree.map(lambda v: v[0], t)
        return fusion.allreduce_pytree(local, op=hvd.Sum)

    out = hvd.shard_map(
        f, mesh=hvd.mesh(),
        in_specs=P(hvd.HVD_AXES),
        out_specs=P())(tree)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.asarray(tree["w"]).sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["b"]),
                               np.asarray(tree["b"]).sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["scale"]),
                               np.asarray(tree["scale"]).sum(0), rtol=1e-5)


def test_allreduce_pytree_small_threshold_many_buckets():
    # Forcing a tiny threshold exercises the multi-bucket path; results
    # must not change (reference: fused vs unfused equivalence tests).
    rng = np.random.RandomState(2)
    tree = [jnp.asarray(rng.randn(N, 17), jnp.float32) for _ in range(5)]

    def f(t):
        local = [v[0] for v in t]
        return fusion.allreduce_pytree(local, op=hvd.Average,
                                       threshold_bytes=64)

    out = hvd.shard_map(
        f, mesh=hvd.mesh(),
        in_specs=P(hvd.HVD_AXES),
        out_specs=P())(tree)
    for o, t in zip(out, tree):
        np.testing.assert_allclose(np.asarray(o), np.asarray(t).mean(0),
                                   rtol=1e-5)


def test_allreduce_pytree_empty():
    assert fusion.allreduce_pytree({}) == {}


def test_allreduce_pytree_mixed_dtype_compression():
    rng = np.random.RandomState(3)
    tree = {"f32": jnp.asarray(rng.randn(N, 8), jnp.float32),
            "i32": jnp.asarray(rng.randint(0, 5, (N, 4)), jnp.int32)}

    def f(t):
        local = jax.tree.map(lambda v: v[0], t)
        return fusion.allreduce_pytree(local, op=hvd.Sum,
                                       compression=hvd.Compression.bf16)

    out = hvd.shard_map(
        f, mesh=hvd.mesh(),
        in_specs=P(hvd.HVD_AXES),
        out_specs=P())(tree)
    assert out["f32"].dtype == jnp.float32
    assert out["i32"].dtype == jnp.int32  # ints bypass float compression
    np.testing.assert_array_equal(np.asarray(out["i32"]),
                                  np.asarray(tree["i32"]).sum(0))
    np.testing.assert_allclose(np.asarray(out["f32"]),
                               np.asarray(tree["f32"]).sum(0),
                               rtol=5e-2, atol=0.3)
