"""Async rank-sharded checkpoint tests (docs/checkpoint.md).

Core invariants:
  * each rank's shards land as separate rank-major files; restore
    reassembles the exact global state (bit-identical round trip);
  * commits are atomic (manifest-last, tmp→rename) and retained last-K;
  * corruption fails LOUDLY on checksum mismatch — never loads garbage;
  * a restore at a different world size reshards exactly and training
    resumes bit-identically;
  * the writer is async (double-buffered, error-carrying) and the
    elastic bridge (CheckpointedJaxState) resumes a fresh process from
    the last committed step.
"""

import glob
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import checkpoint as ckpt
from horovod_tpu.checkpoint import layout
from horovod_tpu.checkpoint.writer import AsyncWriter
from horovod_tpu.ops import fusion

N = 8


@pytest.fixture(scope="module", autouse=True)
def _mesh_2x4():
    hvd.shutdown()
    hvd.init(mesh_shape=(2, 4))
    yield
    hvd.shutdown()
    hvd.init()


def loss_fn(params, batch):
    x, y = batch
    return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)


def init_params(d=5):
    return {"w": jnp.zeros((d, 1)), "b": jnp.zeros((1,))}


def _put(tree, spec):
    mesh = hvd.mesh()
    return jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), spec))


def _trained_state(steps=2, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(96, 5).astype(np.float32)
    y = (x @ rng.randn(5, 1).astype(np.float32)).astype(np.float32)
    params = init_params()
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero=True)
    state = tx.init(params)
    sspec = hvd.zero_state_pspecs(state)
    state = _put(state, sspec)
    mesh = hvd.mesh()

    @jax.jit
    def step(p, s, xb, yb):
        def spmd(p, s, xb, yb):
            loss, g = hvd.value_and_grad(loss_fn, zero=True)(p, (xb, yb))
            u, ns = tx.update(g, s, p)
            return optax.apply_updates(p, u), ns

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), sspec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), sspec))(p, s, xb, yb)

    for i in range(steps):
        params, state = step(params, state,
                             jnp.asarray(x[i * 16:(i + 1) * 16]),
                             jnp.asarray(y[i * 16:(i + 1) * 16]))
    nxt = (jnp.asarray(x[steps * 16:(steps + 1) * 16]),
           jnp.asarray(y[steps * 16:(steps + 1) * 16]))
    return tx, step, params, state, sspec, nxt


# --- layout ----------------------------------------------------------------


def test_layout_units(tmp_path):
    assert layout.step_dir_name(42) == "step_0000000042"
    assert layout.parse_step_dir("step_0000000042") == 42
    assert layout.parse_step_dir("step_x") is None
    assert layout.checksum(b"abc") == layout.checksum(b"abc")
    assert layout.checksum(b"abc") != layout.checksum(b"abd")
    # a step dir without a manifest is NOT a committed checkpoint
    os.makedirs(tmp_path / "step_0000000007")
    os.makedirs(tmp_path / "step_0000000009.tmp-123")
    assert layout.list_steps(str(tmp_path)) == []


# --- save / restore round trip ---------------------------------------------


def test_sharded_roundtrip_and_rank_files(tmp_path):
    """Every P(HVD_AXES) leaf lands as world rank-major files, each
    holding exactly 1/world of the leading axis; restore reassembles the
    bit-exact global state; replicated leaves get one file."""
    _, _, params, state, _, _ = _trained_state()
    d = str(tmp_path / "c")
    with ckpt.CheckpointManager(d, keep=3) as mgr:
        mgr.save(3, {"params": params, "opt_state": state,
                     "rng": jax.random.PRNGKey(7)})
        assert mgr.wait(60)
        # rank-sharded layout on disk
        step_dir = os.path.join(d, "step_0000000003")
        rank_files = glob.glob(os.path.join(step_dir,
                                            "opt_state.leaf*.rank*.npy"))
        assert rank_files
        ranks = {int(f.rsplit(".rank", 1)[1][:3]) for f in rank_files}
        assert ranks == set(range(N))
        moment = [l for l in jax.tree.leaves(jax.device_get(state.inner))
                  if getattr(l, "ndim", 0) >= 1][0]
        one = np.load(sorted(rank_files)[0])
        assert one.shape[0] == moment.shape[0] // N
        # params are replicated → single .rep file per leaf, written once
        assert glob.glob(os.path.join(step_dir, "params.leaf*.rep.npy"))
        assert not glob.glob(os.path.join(step_dir,
                                          "params.leaf*.rank*.npy"))
        meta, tree = mgr.restore()
        assert meta.step == 3 and meta.world == N
        for a, b in zip(jax.tree.leaves(jax.device_get(state)),
                        jax.tree.leaves(tree["opt_state"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for k in params:
            np.testing.assert_array_equal(np.asarray(params[k]),
                                          np.asarray(tree["params"][k]))
        np.testing.assert_array_equal(
            np.asarray(jax.random.PRNGKey(7)), np.asarray(tree["rng"]))


def test_retention_and_atomic_commit(tmp_path):
    d = str(tmp_path / "c")
    params = init_params()
    with ckpt.CheckpointManager(d, keep=2) as mgr:
        for s in (1, 4, 9, 16):
            mgr.save(s, {"params": params})
        assert mgr.wait(60)
        assert mgr.steps() == [9, 16]
        assert mgr.latest_step() == 16
        # no tmp orphans survive a drained writer
        assert not [n for n in os.listdir(d) if ".tmp-" in n]
        # a crashed writer's orphan is invisible to restore
        os.makedirs(os.path.join(d, "step_0000000099.tmp-777"))
        assert mgr.steps() == [9, 16]
        meta, _ = mgr.restore()
        assert meta.step == 16


def test_corrupt_shard_fails_loudly(tmp_path):
    _, _, params, state, _, _ = _trained_state()
    d = str(tmp_path / "c")
    with ckpt.CheckpointManager(d, keep=2) as mgr:
        mgr.save(1, {"opt_state": state})
        assert mgr.wait(60)
        f = sorted(glob.glob(os.path.join(
            d, "step_0000000001", "opt_state.leaf*.rank004.npy")))[0]
        raw = bytearray(open(f, "rb").read())
        raw[len(raw) // 2] ^= 0xFF  # one flipped bit mid-payload
        open(f, "wb").write(bytes(raw))
        with pytest.raises(ckpt.CheckpointCorruptError,
                           match="checksum mismatch"):
            mgr.restore(1)
        # a missing shard file fails loudly too
        os.remove(f)
        with pytest.raises(ckpt.CheckpointCorruptError):
            mgr.restore(1)


def test_restore_reshard_resumes_bit_identical(tmp_path):
    """The recovery contract: save async mid-training, restore the
    committed state, reshard it through a DIFFERENT world size (8→5→8,
    non-dividing paddings), and the next training step is bit-identical
    to the uninterrupted run."""
    tx, step, params, state, sspec, (xb, yb) = _trained_state()
    d = str(tmp_path / "c")
    with ckpt.CheckpointManager(d, keep=2) as mgr:
        mgr.save(2, {"params": params, "opt_state": state})
        assert mgr.wait(60)
        meta, tree = mgr.restore()
    params0 = init_params()
    r5 = hvd.zero_reshard_state(tree["opt_state"], params0,
                                from_world=meta.world, to_world=5,
                                to_local_size=5)
    back = hvd.zero_reshard_state(r5, params0, from_world=5,
                                  to_world=meta.world, to_local_size=4)
    restored = _put(back, sspec)
    p_resumed, _ = step(tree["params"], restored, xb, yb)
    p_straight, _ = step(params, state, xb, yb)
    for k in p_straight:
        np.testing.assert_array_equal(np.asarray(p_resumed[k]),
                                      np.asarray(p_straight[k]))


def test_zero3_param_shards_roundtrip(tmp_path):
    """Stage-3 parameter shard tuples checkpoint as sharded flat buckets
    and reshard exactly across worlds on restore."""
    params = {"w": jnp.arange(130.0).reshape(130, 1), "b": jnp.ones((7,))}
    psh = hvd.zero3_shard_params(params)
    psh_dev = _put(psh, hvd.zero3_param_pspecs(psh))
    d = str(tmp_path / "c")
    with ckpt.CheckpointManager(d, keep=1) as mgr:
        mgr.save(1, {"pshards": psh_dev})
        assert mgr.wait(60)
        meta, tree = mgr.restore()
    r5 = hvd.zero3_reshard_params(tree["pshards"], params,
                                  from_world=meta.world, to_world=5)
    back = hvd.zero3_reshard_params(r5, params, from_world=5,
                                    to_world=meta.world)
    for a, b in zip(psh, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the gathered model tree is the original
    got = hvd.zero3_gather_params(back, params)
    for k in params:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(params[k]))


# --- async writer ----------------------------------------------------------


def test_async_writer_double_buffer_and_errors():
    w = AsyncWriter()
    gate = threading.Event()
    started = []

    def slow():
        started.append(time.monotonic())
        gate.wait(10)

    t0 = time.monotonic()
    w.submit(slow)        # starts executing
    w.submit(slow)        # queued (second buffer)
    assert time.monotonic() - t0 < 1.0
    assert w.busy
    # a third submit must BLOCK until the writer frees a slot
    blocked = []

    def third():
        w.submit(lambda: None)
        blocked.append(time.monotonic())

    t = threading.Thread(target=third)
    t.start()
    time.sleep(0.2)
    assert not blocked  # still waiting on the double buffer
    gate.set()
    t.join(10)
    assert blocked
    assert w.drain(10)
    assert not w.busy
    # errors surface on the NEXT call, not silently
    w.submit(lambda: (_ for _ in ()).throw(RuntimeError("disk gone")))
    with pytest.raises(RuntimeError, match="disk gone"):
        w.drain(10)
    w.close()
    with pytest.raises(RuntimeError):
        w.submit(lambda: None)


def test_save_is_async_and_metrics_count(tmp_path):
    from horovod_tpu import monitor

    reg = monitor.metrics()
    commits0 = reg.counter("ckpt.commits").value
    restores0 = reg.counter("ckpt.restores").value
    _, _, params, state, _, _ = _trained_state()
    d = str(tmp_path / "c")
    with ckpt.CheckpointManager(d, keep=2) as mgr:
        t0 = time.perf_counter()
        mgr.save(1, {"params": params, "opt_state": state})
        stall = time.perf_counter() - t0
        assert mgr.wait(60)
        mgr.restore()
    assert reg.counter("ckpt.commits").value == commits0 + 1
    assert reg.counter("ckpt.restores").value == restores0 + 1
    assert reg.counter("ckpt.bytes").value > 0
    # the blocking part is the snapshot, not the write: generously under
    # a second for a toy state on tmpfs-or-disk either way
    assert stall < 5.0


# --- elastic bridge --------------------------------------------------------


def test_checkpointed_jax_state_resumes_fresh_process(tmp_path):
    """A fresh CheckpointedJaxState over a directory with committed
    steps overrides its initial values with the newest commit — the
    post-crash resume path — resharding the ZeroState to the current
    world (identity here) and restoring the step counter."""
    _, _, params, state, _, _ = _trained_state()
    d = str(tmp_path / "c")
    mgr = ckpt.CheckpointManager(d, keep=2)
    st = ckpt.CheckpointedJaxState(mgr, params_template=init_params(),
                                   params=params, opt_state=state, step=5)
    assert st.restored_from is None
    st.step = 7
    st.save()            # in-memory pin + async durable write
    assert st.wait(60)
    mgr.close()

    # "crash": a brand-new process would construct from scratch
    mgr2 = ckpt.CheckpointManager(d, keep=2)
    zeroed = jax.tree.map(jnp.zeros_like, jax.device_get(state))
    st2 = ckpt.CheckpointedJaxState(mgr2, params_template=init_params(),
                                    params=init_params(),
                                    opt_state=zeroed, step=0)
    assert st2.restored_from == 7
    assert st2.step == 7
    for a, b in zip(jax.tree.leaves(jax.device_get(state.inner)),
                    jax.tree.leaves(st2.opt_state.inner)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in params:
        np.testing.assert_array_equal(np.asarray(st2.params[k]),
                                      np.asarray(params[k]))
    mgr2.close()


def test_manifest_records_geometry(tmp_path):
    params = init_params()
    d = str(tmp_path / "c")
    with ckpt.CheckpointManager(d, keep=1) as mgr:
        mgr.save(2, {"params": params}, mesh_shape=(2, 4),
                 extra={"note": "hi"})
        assert mgr.wait(60)
        meta, _ = mgr.restore()
    assert meta.world == N and meta.mesh_shape == (2, 4)
    assert meta.extra["note"] == "hi"
    assert meta.plan_digest == layout.plan_digest_for({"params": params})
