"""Compiled uneven alltoall (static-capacity protocol) on the 8-CPU mesh.

Models the reference's uneven-split alltoall coverage
(test/parallel/test_tensorflow.py test_horovod_alltoall_uneven; runtime
recv-splits negotiation in operations.cc:1031-1092): compiled-ragged vs a
host-side numpy simulation vs the eager world-1 path, plus overflow
clamping and the gradient of the padded exchange.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from jax0437_repros import _old_jax

N = 8


def spmd(f, in_specs, out_specs):
    return hvd.shard_map(f, mesh=hvd.mesh(), in_specs=in_specs,
                         out_specs=out_specs)


def ragged_sim(x_all, splits_all, cap):
    """Numpy reference: returns (out [N, N*cap, ...], recv [N, N])."""
    n = x_all.shape[0]
    rest = x_all.shape[2:]
    out = np.zeros((n, n * cap) + rest, x_all.dtype)
    recv = np.zeros((n, n), np.int32)
    for d in range(n):  # destination rank
        rows = []
        for r in range(n):  # source rank
            offs = np.cumsum(splits_all[r]) - splits_all[r]
            k = min(int(splits_all[r, d]), cap)
            rows.append(x_all[r, offs[d]:offs[d] + k])
            recv[d, r] = k
        block = np.concatenate(rows, axis=0) if rows else \
            np.zeros((0,) + rest, x_all.dtype)
        out[d, :block.shape[0]] = block
    return out, recv


def run_compiled(x_all, splits_all, cap):
    def f(x, sp):
        out, rsp = hvd.alltoall_ragged(x[0], sp[0], capacity=cap)
        return out, rsp

    out, rsp = spmd(f, in_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
                    out_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)))(
        jnp.asarray(x_all), jnp.asarray(splits_all, jnp.int32))
    rest = x_all.shape[2:]
    return (np.asarray(out).reshape((N, N * cap) + rest),
            np.asarray(rsp).reshape(N, N))


@pytest.mark.parametrize("shape", [(), (5,)])
def test_ragged_matches_simulation(shape):
    rng = np.random.RandomState(0)
    # Random split matrix with rows summing to <= T.
    splits_all = rng.randint(0, 4, size=(N, N)).astype(np.int32)
    T = int(splits_all.sum(axis=1).max())
    x_all = rng.randn(N, T, *shape).astype(np.float32)
    cap = 4  # >= max split: lossless
    out, rsp = run_compiled(x_all, splits_all, cap)
    exp_out, exp_recv = ragged_sim(x_all, splits_all, cap)
    np.testing.assert_array_equal(rsp, exp_recv)
    np.testing.assert_array_equal(out, exp_out)


def test_ragged_overflow_clamped():
    rng = np.random.RandomState(1)
    splits_all = rng.randint(0, 6, size=(N, N)).astype(np.int32)
    T = int(splits_all.sum(axis=1).max())
    x_all = rng.randn(N, T).astype(np.float32)
    cap = 3  # below max split: rows beyond cap dropped, counts clamped
    out, rsp = run_compiled(x_all, splits_all, cap)
    exp_out, exp_recv = ragged_sim(x_all, splits_all, cap)
    assert rsp.max() == cap
    np.testing.assert_array_equal(rsp, exp_recv)
    np.testing.assert_array_equal(out, exp_out)


@pytest.mark.xfail(
    _old_jax(), strict=False,
    reason="upstream jax 0.4.37: grad-of-psum under old shard_map scales "
           "gradients by the axis size — pure-jax repro: "
           "tests/jax0437_repros.py::repro_grad_of_psum (fixed by the "
           "jax.shard_map graduation, jax >= 0.6)")
def test_ragged_gradient():
    # loss = psum over ranks of sum(out^2)/2  =>  dL/dx = x for delivered
    # rows, 0 for clamped-away rows (the exchange is a permutation+drop).
    rng = np.random.RandomState(2)
    splits_all = rng.randint(0, 5, size=(N, N)).astype(np.int32)
    T = int(splits_all.sum(axis=1).max())
    x_all = rng.randn(N, T).astype(np.float32)
    cap = 3

    def loss(x, sp):
        out, _ = hvd.alltoall_ragged(x[0], sp[0], capacity=cap)
        return jax.lax.psum(jnp.sum(out * out) / 2, hvd.HVD_AXES)

    def per_rank(x, sp):
        return jax.grad(lambda xx: loss(xx, sp))(x)

    g = spmd(per_rank, in_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
             out_specs=P(hvd.HVD_AXES))(
        jnp.asarray(x_all), jnp.asarray(splits_all, jnp.int32))
    g = np.asarray(g).reshape(N, T)
    exp = np.zeros_like(x_all)
    for r in range(N):
        offs = np.cumsum(splits_all[r]) - splits_all[r]
        for d in range(N):
            k = min(int(splits_all[r, d]), cap)
            exp[r, offs[d]:offs[d] + k] = x_all[r, offs[d]:offs[d] + k]
    np.testing.assert_allclose(g, exp, rtol=1e-6)


def test_ragged_world1_eager():
    # Outside shard_map the process world is 1: everything loops back,
    # padded to the capacity contract.
    x = jnp.arange(6.0).reshape(3, 2)
    out, rsp = hvd.alltoall_ragged(x, [2], capacity=4)
    assert out.shape == (4, 2)
    np.testing.assert_array_equal(np.asarray(rsp), [2])
    np.testing.assert_array_equal(np.asarray(out[:2]), np.asarray(x[:2]))
    np.testing.assert_array_equal(np.asarray(out[2:]), 0)


def test_ragged_validation():
    with pytest.raises(ValueError):
        hvd.alltoall_ragged(jnp.zeros(4), [1], capacity=0)
    with pytest.raises(ValueError):
        hvd.alltoall_ragged(jnp.asarray(1.0), [1], capacity=2)
    with pytest.raises(ValueError):
        hvd.alltoall_ragged(jnp.zeros(4), [1, 2], capacity=2)
