"""Serving subsystem tests (docs/serving.md): paged KV cache, scheduler
invariants, the continuous-batching engine, and elastic replica groups.

Core invariants (ISSUE 6):
  * admission never exceeds free pages; eviction frees exactly the
    finished sequence's pages; page reuse never aliases live sequences;
  * decode-with-cache logits match the full-context forward within
    tolerance — single device, 8-way TP over the full mesh, and with the
    page pool ring-striped across the mesh (contexts longer than one
    host's pages);
  * a replica resize mid-trace completes without dropping in-flight
    requests; the autoscaler follows the elastic discovery layer.

Compiled tests run on the 8-device CPU mesh via ``hvd.shard_map``.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.serve import kv_cache as kvlib
from horovod_tpu.serve import (
    GenerationEngine,
    PageAllocator,
    PageConfig,
    PoissonTrace,
    ReplicaAutoscaler,
    ReplicaSet,
    Request,
    Scheduler,
)
from horovod_tpu.serve.engine import VirtualClock

pytestmark = pytest.mark.serve

N = 8


def tiny_cfg(**over):
    return gpt_tiny(dtype=jnp.float32, num_heads=8, **over)


def tiny_page_cfg(cfg, **over):
    kw = dict(num_pages=64, page_size=4, max_slots=4, pages_per_slot=16,
              num_layers=cfg.num_layers, num_heads=cfg.num_heads,
              head_dim=cfg.d_model // cfg.num_heads)
    kw.update(over)
    return PageConfig(**kw)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    params = GPT(cfg).init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, params


# ---------------------------------------------------------------------------
# Page allocator / scheduler invariants


class TestPageAllocator:
    def test_atomic_alloc_and_free(self):
        a = PageAllocator(8)            # 7 allocatable (page 0 reserved)
        assert a.free_pages == 7
        p1 = a.alloc("a", 3)
        assert len(p1) == 3 and a.free_pages == 4
        assert a.alloc("b", 5) is None          # atomic: no partial grant
        assert a.free_pages == 4
        a.check_invariants()
        freed = a.free("a")
        assert sorted(freed) == sorted(p1)
        assert a.free_pages == 7
        a.check_invariants()

    def test_null_page_never_granted(self):
        a = PageAllocator(16)
        pages = a.alloc("s", 15)
        assert kvlib.NULL_PAGE not in pages
        assert a.free_pages == 0
        a.check_invariants()

    def test_extend_and_double_alloc_rejected(self):
        a = PageAllocator(8)
        a.alloc("s", 2)
        assert a.extend("s", 2) == a.pages_of("s")[2:]
        with pytest.raises(ValueError):
            a.alloc("s", 1)
        with pytest.raises(ValueError):
            a.extend("ghost", 1)

    def test_no_aliasing_across_reuse(self):
        """LIFO reuse hands freed pages straight to the next sequence —
        live grants must still never intersect."""
        a = PageAllocator(8)
        a.alloc("a", 3)
        a.alloc("b", 3)
        a.free("a")
        pages_c = a.alloc("c", 3)
        assert not set(pages_c) & set(a.pages_of("b"))
        a.check_invariants()


class TestScheduler:
    def cfg(self, **over):
        return tiny_page_cfg(tiny_cfg(), **over)

    def test_admission_never_exceeds_free_pages(self):
        # Pool of 6 allocatable pages; each request needs 3 (prompt 8 + 1
        # headroom at page_size 4) -> exactly 2 admissions.
        cfg = self.cfg(num_pages=7, max_slots=4, pages_per_slot=4)
        s = Scheduler(cfg)
        for _ in range(4):
            s.submit(Request(prompt=[2] * 8, max_new_tokens=4))
        admitted = s.admit(now=0.0)
        assert len(admitted) == 2
        assert s.allocator.free_pages == 0
        assert s.queue_depth() == 2
        s.check_invariants()

    def test_eviction_frees_exactly_the_finished_pages(self):
        cfg = self.cfg(num_pages=16)
        s = Scheduler(cfg)
        s.submit(Request(prompt=[2] * 6, max_new_tokens=4))
        s.submit(Request(prompt=[3] * 6, max_new_tokens=4))
        (s1, s2) = s.admit(0.0)
        free_before = s.allocator.free_pages
        held = len(s.allocator.pages_of(s.running[s1].req_id))
        req = s.evict(s1, 1.0, "length")
        assert req.finish_reason == "length"
        assert s.allocator.free_pages == free_before + held
        # the survivor's pages are untouched
        assert s.page_table[s2].any()
        s.check_invariants()

    def test_preemption_requeues_front_with_progress(self):
        cfg = self.cfg(num_pages=7, max_slots=4, pages_per_slot=4)
        s = Scheduler(cfg)
        s.submit(Request(prompt=[2] * 8, max_new_tokens=4))
        s.submit(Request(prompt=[3] * 8, max_new_tokens=4))
        s.admit(0.0)
        young = s._admit_order[-1]
        old = s._admit_order[0]
        s.running[young].generated = [9, 9]
        victim = s.preempt_for_page(needy_slot=old)
        assert victim == young
        assert s.queue[0].prompt[-2:] == [9, 9]     # progress folded
        assert s.queue[0].preemptions == 1
        s.check_invariants()

    def test_oversized_request_rejected(self):
        cfg = self.cfg(pages_per_slot=2, page_size=4)
        s = Scheduler(cfg)
        with pytest.raises(ValueError, match="exceeds"):
            s.submit(Request(prompt=[2] * 8, max_new_tokens=4))


# ---------------------------------------------------------------------------
# KV cache device ops


class TestKVCache:
    def test_append_gather_roundtrip_and_no_aliasing(self):
        cfg = PageConfig(num_pages=8, page_size=2, max_slots=2,
                         pages_per_slot=4, num_layers=1, num_heads=2,
                         head_dim=2)
        cache = kvlib.init_cache(cfg)
        alloc = PageAllocator(cfg.num_pages)
        table = np.array(cache.page_table)
        pa = alloc.alloc("a", 2)
        pb = alloc.alloc("b", 2)
        table[0, :2] = pa
        table[1, :2] = pb
        cache = cache._replace(page_table=jnp.asarray(table))
        active = jnp.ones((2,), bool)
        for t in range(4):
            meta = kvlib.step_meta(cache, active, cfg.page_size)
            k_new = jnp.full((2, 2, 2), 10.0 * t) + \
                jnp.arange(2, dtype=jnp.float32)[:, None, None]
            cache = kvlib.append_layer_kv(cache, 0, k_new, -k_new, meta)
            cache = kvlib.advance(cache, meta)
        for slot in range(2):
            k, v = kvlib.gather_slot_kv(cache, 0, slot, 4)
            expect = (10.0 * np.arange(4) + slot)[:, None, None]
            np.testing.assert_allclose(np.asarray(k),
                                       np.broadcast_to(expect, (4, 2, 2)))
            np.testing.assert_allclose(np.asarray(v), -np.broadcast_to(
                expect, (4, 2, 2)))
        # Evict "a", reuse its pages for "c": b's tokens must not change
        # (page reuse never aliases a live sequence).
        b_before = np.asarray(kvlib.gather_slot_kv(cache, 0, 1, 4)[0])
        alloc.free("a")
        pc_ = alloc.alloc("c", 2)
        assert not set(pc_) & set(alloc.pages_of("b"))
        table[0, :2] = pc_
        cache = cache._replace(page_table=jnp.asarray(table),
                               seq_lens=jnp.asarray([0, 4], jnp.int32))
        meta = kvlib.step_meta(cache, jnp.asarray([True, False]),
                               cfg.page_size)
        cache = kvlib.append_layer_kv(
            cache, 0, jnp.full((2, 2, 2), 99.0),
            jnp.full((2, 2, 2), -99.0), meta)
        np.testing.assert_array_equal(
            np.asarray(kvlib.gather_slot_kv(cache, 0, 1, 4)[0]), b_before)

    def test_inactive_slots_write_null_page_only(self):
        cfg = PageConfig(num_pages=4, page_size=2, max_slots=2,
                         pages_per_slot=2, num_layers=1, num_heads=1,
                         head_dim=2)
        cache = kvlib.init_cache(cfg)
        table = np.array(cache.page_table)
        table[0, 0] = 1
        cache = cache._replace(page_table=jnp.asarray(table))
        meta = kvlib.step_meta(cache, jnp.asarray([False, False]),
                               cfg.page_size)
        assert np.all(np.asarray(meta.write_page) == kvlib.NULL_PAGE)
        cache2 = kvlib.append_layer_kv(
            cache, 0, jnp.ones((2, 1, 2)), jnp.ones((2, 1, 2)), meta)
        # everything except the null page is untouched
        np.testing.assert_array_equal(np.asarray(cache2.k[0, 1:]),
                                      np.asarray(cache.k[0, 1:]))
        cache2 = kvlib.advance(cache2, meta)
        assert np.all(np.asarray(cache2.seq_lens) == 0)


# ---------------------------------------------------------------------------
# Decode-vs-full-context logits parity


def _full_logits(cfg, params, tokens):
    return np.asarray(GPT(cfg).apply({"params": params},
                                     jnp.asarray(tokens)[None])[0])


def _alloc_slot0(cache, pc, n_tokens):
    alloc = PageAllocator(pc.num_pages)
    pages = alloc.alloc("s0", pc.pages_for(n_tokens))
    table = np.array(cache.page_table)
    table[0, :len(pages)] = pages
    return cache._replace(page_table=jnp.asarray(table))


class TestDecodeParity:
    def test_single_device_parity(self, model):
        cfg, params = model
        pc = tiny_page_cfg(cfg, max_slots=2)
        rs = np.random.RandomState(0)
        T = 20
        toks = rs.randint(2, cfg.vocab_size, size=T)
        full = _full_logits(cfg, params, toks)
        cache = _alloc_slot0(kvlib.init_cache(pc), pc, T)
        step = jax.jit(lambda t, c: GPT(cfg).apply(
            {"params": params}, t, cache=c,
            active=jnp.asarray([True, False])))
        rows = []
        for t in toks:
            logits, cache = step(jnp.asarray([int(t), 0]), cache)
            rows.append(np.asarray(logits[0]))
        np.testing.assert_allclose(np.stack(rows), full,
                                   rtol=2e-4, atol=2e-4)
        assert int(cache.seq_lens[0]) == T and int(cache.seq_lens[1]) == 0

    def test_tp8_parity_over_full_mesh(self, model):
        """Decode with the page pools head-sharded P(HVD_AXES) over the
        8-device mesh == the dense full-context forward."""
        from horovod_tpu.parallel.tensor import (tp_merge_params,
                                                 tp_split_params)

        cfg, params = model
        tp_cfg = dataclasses.replace(cfg, tp_axis=hvd.HVD_AXES)
        pc = tiny_page_cfg(cfg, max_slots=2)
        rs = np.random.RandomState(1)
        T = 12
        toks = rs.randint(2, cfg.vocab_size, size=T)
        full = _full_logits(cfg, params, toks)

        mesh = hvd.mesh()
        stacked, repl = tp_split_params(params, N)
        cache = _alloc_slot0(kvlib.init_cache(pc), pc, T)
        pool = P(None, None, None, hvd.HVD_AXES, None)
        cache_specs = kvlib.KVCache(k=pool, v=pool, page_table=P(),
                                    seq_lens=P())

        def spmd(stk, rp, c, t):
            local = tp_merge_params(jax.tree.map(lambda a: a[0], stk), rp)
            return GPT(tp_cfg).apply(
                {"params": local}, t, cache=c,
                active=jnp.asarray([True, False]))

        step = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.HVD_AXES), P(), cache_specs, P()),
            out_specs=(P(), cache_specs)))
        rows = []
        for t in toks:
            logits, cache = step(stacked, repl, cache,
                                 jnp.asarray([int(t), 0]))
            rows.append(np.asarray(logits[0]))
        np.testing.assert_allclose(np.stack(rows), full,
                                   rtol=3e-4, atol=3e-4)

    def test_ring_striped_pages_parity(self, model):
        """Context longer than one host's page pool: pages stripe over
        the whole mesh (per-rank pool holds 8 of 24 tokens) and decode
        merges per-rank flash partials with the ring combine."""
        cfg, params = model
        ring_cfg = dataclasses.replace(cfg, kv_ring_axis=hvd.HVD_AXES)
        rs = np.random.RandomState(2)
        T = 24
        toks = rs.randint(2, cfg.vocab_size, size=T)
        full = _full_logits(cfg, params, toks)

        # 2 local pages x 4 tokens = 8 tokens/rank < T.
        pc = tiny_page_cfg(cfg, num_pages=2, max_slots=2, pages_per_slot=8)
        H, D = cfg.num_heads, cfg.d_model // cfg.num_heads
        alloc = PageAllocator(kvlib.ring_pool_ids(pc.num_pages, N))
        pages = alloc.alloc("s0", pc.pages_for(T))
        table = np.zeros((pc.max_slots, pc.pages_per_slot), np.int32)
        table[0, :len(pages)] = pages
        pool_shape = (N, cfg.num_layers, pc.num_pages, pc.page_size, H, D)
        cache = kvlib.KVCache(
            k=jnp.zeros(pool_shape, jnp.float32),
            v=jnp.zeros(pool_shape, jnp.float32),
            page_table=jnp.asarray(table),
            seq_lens=jnp.zeros((pc.max_slots,), jnp.int32))
        specs = kvlib.KVCache(k=P(hvd.HVD_AXES), v=P(hvd.HVD_AXES),
                              page_table=P(), seq_lens=P())
        mesh = hvd.mesh()

        def spmd(c, t):
            local = kvlib.KVCache(k=c.k[0], v=c.v[0],
                                  page_table=c.page_table,
                                  seq_lens=c.seq_lens)
            logits, c2 = GPT(ring_cfg).apply(
                {"params": params}, t, cache=local,
                active=jnp.asarray([True, False]))
            return logits, kvlib.KVCache(
                k=c2.k[None], v=c2.v[None], page_table=c2.page_table,
                seq_lens=c2.seq_lens)

        step = jax.jit(hvd.shard_map(
            spmd, mesh=mesh, in_specs=(specs, P()),
            out_specs=(P(), specs)))
        rows = []
        for t in toks:
            logits, cache = step(cache, jnp.asarray([int(t), 0]))
            rows.append(np.asarray(logits[0]))
        np.testing.assert_allclose(np.stack(rows), full,
                                   rtol=3e-4, atol=3e-4)

    def test_ring_overlapping_tp_axis_rejected(self, model):
        """kv_ring_axis inside tp_axis would stripe pages between ranks
        holding different heads — must fail loudly at trace time."""
        cfg, params = model
        bad = dataclasses.replace(cfg, tp_axis=hvd.HVD_AXES,
                                  kv_ring_axis=hvd.LOCAL_AXIS)
        pc = tiny_page_cfg(cfg, max_slots=1)
        from horovod_tpu.parallel.tensor import (tp_merge_params,
                                                 tp_split_params)

        stacked, repl = tp_split_params(params, N)
        cache = kvlib.init_cache(pc)
        mesh = hvd.mesh()

        def spmd(stk, rp, c, t):
            local = tp_merge_params(jax.tree.map(lambda a: a[0], stk), rp)
            return GPT(bad).apply({"params": local}, t, cache=c)

        with pytest.raises(ValueError, match="overlaps"):
            jax.jit(hvd.shard_map(
                spmd, mesh=mesh,
                in_specs=(P(hvd.HVD_AXES), P(),
                          jax.tree.map(lambda _: P(), cache), P()),
                out_specs=(P(), jax.tree.map(lambda _: P(), cache))))(
                stacked, repl, cache, jnp.zeros((1,), jnp.int32))


# ---------------------------------------------------------------------------
# Engine / continuous batching


class TestEngine:
    def test_trace_completes_and_greedy_matches_full_context(self, model):
        cfg, params = model
        pc = tiny_page_cfg(cfg)
        eng = GenerationEngine(cfg, params, pc, eos_id=1)
        prompt = [5, 9, 3, 7]
        req = Request(prompt=list(prompt), max_new_tokens=5,
                      arrival_time=0.0)
        stats = eng.run([req], clock=VirtualClock())
        assert len(stats.completed) == 1
        got = stats.completed[0].generated
        toks = list(prompt)
        for _ in range(5):
            nxt = int(np.argmax(_full_logits(cfg, params, toks)[-1]))
            toks.append(nxt)
            if nxt == 1:
                break
        assert got == toks[len(prompt):]

    def test_mixed_prefill_decode_and_continuous_admission(self, model):
        """Requests arriving mid-trace join while earlier ones decode —
        the same compiled step serves both phases (no static batch)."""
        cfg, params = model
        pc = tiny_page_cfg(cfg, max_slots=3)
        eng = GenerationEngine(cfg, params, pc, eos_id=1)
        trace = PoissonTrace(rate=2.0, num_requests=6, seed=0,
                             prompt_len=(3, 8), max_new_tokens=(2, 6),
                             vocab_size=cfg.vocab_size)
        stats = eng.run(list(trace), clock=VirtualClock(0.25))
        assert len(stats.completed) == 6
        assert stats.prefill_tokens > 0 and stats.decode_tokens > 0
        assert all(r.finish_reason in ("eos", "length")
                   for r in stats.completed)
        lat = stats.latency_percentiles()
        assert lat["p99"] >= lat["p50"] > 0

    def test_preemption_under_page_pressure_completes_all(self, model):
        """A pool too small for the full load forces preemptions; every
        request still completes (folded progress, front-of-queue)."""
        cfg, params = model
        pc = tiny_page_cfg(cfg, num_pages=13, max_slots=4,
                           pages_per_slot=12)
        eng = GenerationEngine(cfg, params, pc, eos_id=1)
        reqs = [Request(prompt=[3 + i] * 6, max_new_tokens=24,
                        arrival_time=0.0) for i in range(4)]
        stats = eng.run(reqs, clock=VirtualClock())
        assert len(stats.completed) == 4
        assert stats.preemptions > 0
        eng.sched.check_invariants()
        assert eng.sched.allocator.free_pages == pc.num_pages - 1

    def test_preempted_request_resumes_identically(self, model):
        """Preemption must not change WHAT a request generates — only
        when: compare against an uncontended run."""
        cfg, params = model
        prompt = [11, 4, 8, 2, 6, 13]
        solo = GenerationEngine(cfg, params, tiny_page_cfg(cfg), eos_id=1)
        want = solo.run(
            [Request(prompt=list(prompt), max_new_tokens=16)],
            clock=VirtualClock()).completed[0].generated
        pc = tiny_page_cfg(cfg, num_pages=13, max_slots=4,
                           pages_per_slot=12)
        eng = GenerationEngine(cfg, params, pc, eos_id=1)
        reqs = [Request(prompt=list(prompt), max_new_tokens=16)] + \
            [Request(prompt=[3 + i] * 6, max_new_tokens=16)
             for i in range(3)]
        stats = eng.run(reqs, clock=VirtualClock())
        assert stats.preemptions > 0
        got = next(r for r in stats.completed
                   if r.req_id == reqs[0].req_id).generated
        assert got == want

    def test_timeline_spans(self, model, tmp_path):
        cfg, params = model
        path = str(tmp_path / "serve_tl.json")
        tl = hvd.start_timeline(path)
        try:
            pc = tiny_page_cfg(cfg)
            eng = GenerationEngine(cfg, params, pc, eos_id=1)
            eng.run([Request(prompt=[5, 6, 7], max_new_tokens=3)],
                    clock=VirtualClock())
        finally:
            hvd.stop_timeline()
        events = json.load(open(path))
        names = [e["name"] for e in events]
        assert any(n.startswith("SERVE:ADMIT") for n in names)
        assert any(n.startswith("SERVE:EVICT") for n in names)
        assert "SERVE:PREFILL" in names and "SERVE:DECODE" in names
        # B/E balance per tid via the span-audit helper (raises on any
        # imbalance); both phases must have closed at least one span.
        from horovod_tpu.monitor.span_audit import audit_spans

        audit = audit_spans(events, prefix="SERVE:", require_spans=True)
        assert audit.count["SERVE:PREFILL"] > 0
        assert audit.count["SERVE:DECODE"] > 0


# ---------------------------------------------------------------------------
# Elastic replica groups


class TestReplicas:
    def test_resize_mid_trace_drops_nothing(self, model):
        cfg, params = model
        pc = tiny_page_cfg(cfg)
        rset = ReplicaSet(cfg, params, pc, n_replicas=2, eos_id=1)
        trace = PoissonTrace(rate=50.0, num_requests=10, seed=3,
                             prompt_len=(3, 8), max_new_tokens=(2, 6),
                             vocab_size=cfg.vocab_size)
        stats = rset.run(list(trace), clock=VirtualClock(0.05),
                         resize_plan={4: 1, 8: 2})
        assert len(stats.completed) == 10           # nothing dropped
        assert len(rset.resize_events) == 2
        assert rset.resize_events[0]["in_flight"] > 0   # drained, not idle
        assert {e["to"] for e in rset.resize_events} == {1, 2}

    def test_resize_preserves_generation(self, model):
        """A request migrated across a resize generates the same tokens
        as an undisturbed run (drain replays the folded prompt)."""
        cfg, params = model
        pc = tiny_page_cfg(cfg)
        prompt = [7, 3, 12, 5]
        solo = GenerationEngine(cfg, params, pc, eos_id=1)
        want = solo.run([Request(prompt=list(prompt), max_new_tokens=8)],
                        clock=VirtualClock()).completed[0].generated
        rset = ReplicaSet(cfg, params, pc, n_replicas=2, eos_id=1)
        req = Request(prompt=list(prompt), max_new_tokens=8,
                      arrival_time=0.0)
        stats = rset.run([req], clock=VirtualClock(),
                         resize_plan={3: 1})
        done = stats.completed[0]
        assert done.resizes >= 1
        assert done.generated == want

    def test_autoscaler_follows_discovery_and_queue(self, model):
        from horovod_tpu.elastic.discovery import HostDiscovery

        class MutableHosts(HostDiscovery):
            """What the elastic driver's discover loop would see from a
            discovery script as device groups come and go."""

            def __init__(self, hosts):
                self.hosts = dict(hosts)

            def find_available_hosts_and_slots(self):
                return dict(self.hosts)

        cfg, params = model
        pc = tiny_page_cfg(cfg)
        rset = ReplicaSet(cfg, params, pc, n_replicas=2, eos_id=1)
        hosts = MutableHosts({"group0": 1, "group1": 1})
        auto = ReplicaAutoscaler(rset, hosts, min_replicas=1,
                                 max_replicas=2, scale_up_depth=2,
                                 scale_down_depth=1)
        # discovery loses a group -> forced scale-down (drain, no drop)
        hosts.hosts = {"group0": 1}
        for req in PoissonTrace(rate=100.0, num_requests=6, seed=4,
                                prompt_len=(3, 6), max_new_tokens=(2, 4),
                                vocab_size=cfg.vocab_size):
            rset.submit(req)
        auto.poll(0.0)
        assert rset.n_replicas == 1
        # group comes back + queue pressure -> scale-up
        hosts.hosts = {"group0": 1, "group1": 1}
        auto.poll(1.0)
        assert rset.n_replicas == 2
        stats = rset.run(clock=VirtualClock(0.05))
        assert len(stats.completed) == 6


# ---------------------------------------------------------------------------
# PoissonTrace determinism


def test_poisson_trace_deterministic_and_sorted():
    a = PoissonTrace(rate=5.0, num_requests=20, seed=9)
    b = PoissonTrace(rate=5.0, num_requests=20, seed=9)
    ta = [(r.arrival_time, r.prompt, r.max_new_tokens) for r in a]
    tb = [(r.arrival_time, r.prompt, r.max_new_tokens) for r in b]
    assert ta == tb
    times = [r.arrival_time for r in a]
    assert times == sorted(times) and times[0] > 0
    assert all(1 not in r.prompt for r in a)    # never the eos id
