"""Expert-parallel MoE (docs/moe.md).

The a2a wire plan must validate/lower/account like every other leg, the
routing must be deterministic with documented overflow semantics, the
layer must be exact against dense references through gradients, the
``hvd_ep`` axis must isolate expert gradients while composing with
ZeRO, and the moe knobs must ride the autotune machinery (schema v9).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.moe import (
    EXPERT_LEAVES,
    default_a2a_plan,
    ep_mean_dense_grads,
    ep_param_pspecs,
    ep_stack_params,
    moe_capacity,
    moe_ef_residuals,
    moe_ffn,
    moe_positions,
    moe_router,
)
from horovod_tpu.ops.collective_ops import record_wire_stats
from horovod_tpu.plan import (
    ALL_TO_ALL,
    Leg,
    PlanError,
    WirePlan,
    a2a_plan,
    ep_a2a_level,
    predict_a2a_bytes,
)

E, C, F, K = 4, 8, 16, 2
EPALL = (hvd.EP_AXIS,) + hvd.HVD_AXES


def dense_params(seed=0):
    rs = np.random.RandomState(seed)
    return {
        "router": jnp.asarray(rs.randn(C, E) * 0.1, jnp.float32),
        "w1": jnp.asarray(rs.randn(E, C, F) * 0.1, jnp.float32),
        "b1": jnp.asarray(rs.randn(E, F) * 0.01, jnp.float32),
        "w2": jnp.asarray(rs.randn(E, F, C) * 0.1, jnp.float32),
        "b2": jnp.asarray(rs.randn(E, C) * 0.01, jnp.float32),
    }


def local_view(pt):
    return {k: (v[0] if k in EXPERT_LEAVES else v)
            for k, v in pt.items()}


def ep_mesh(ep=E, data=(2, 1)):
    hvd.shutdown()
    hvd.init(devices=jax.devices(), mesh_shape=data, ep_size=ep)
    return hvd.mesh()


def restore_mesh():
    hvd.shutdown()
    hvd.init(devices=jax.devices())


# ---------------------------------------------------------------------------
# IR: the a2a primitive.
# ---------------------------------------------------------------------------


class TestA2AIR:
    def test_a2a_plan_encodes(self):
        p = a2a_plan("dcn", quantized=True, block=256,
                     error_feedback=True)
        assert p.encode() == "a2a:dcn.all_to_all[int8/256+ef]|s1|sync"
        assert a2a_plan("ici").encode() == \
            "a2a:ici.all_to_all[payload]|s1|sync"

    def test_int8_on_ici_a2a_rejected(self):
        with pytest.raises(PlanError, match="non-DCN"):
            WirePlan("a2a", (Leg("ici", ALL_TO_ALL, "int8",
                                 block=256),)).validate()

    def test_a2a_leg_outside_a2a_plan_rejected(self):
        with pytest.raises(PlanError, match="only belongs to an 'a2a'"):
            WirePlan("allreduce", (Leg("dcn", ALL_TO_ALL),)).validate()

    def test_non_a2a_leg_inside_a2a_plan_rejected(self):
        with pytest.raises(PlanError, match="only all_to_all"):
            WirePlan("a2a", (Leg("dcn", "psum"),)).validate()

    def test_multi_leg_a2a_plan_rejected(self):
        with pytest.raises(PlanError, match="exactly ONE exchange"):
            WirePlan("a2a", (Leg("dcn", ALL_TO_ALL),
                             Leg("dcn", ALL_TO_ALL))).validate()

    def test_flat_a2a_rejected(self):
        with pytest.raises(PlanError, match="LINK CLASS"):
            WirePlan("a2a", (Leg("flat", ALL_TO_ALL),)).validate()

    def test_pallas_needs_int8(self):
        with pytest.raises(PlanError, match="payload-dtype a2a"):
            WirePlan("a2a", (Leg("dcn", ALL_TO_ALL,
                                 backend="pallas"),)).validate()
        # int8 + pallas is legal (the fused quantize pair backs it)
        WirePlan("a2a", (Leg("dcn", ALL_TO_ALL, "int8", block=256,
                             backend="pallas"),)).validate()

    def test_a2a_level_from_mesh(self):
        assert ep_a2a_level((2, 2)) == "dcn"
        assert ep_a2a_level((1, 4)) == "ici"
        assert ep_a2a_level((2, 2, 2)) == "pod"
        # quantization forced off on an ICI-class hop
        from horovod_tpu.plan import derive_a2a

        p = derive_a2a(mesh_shape=(1, 4), quantized=True)
        assert not p.is_quantized


# ---------------------------------------------------------------------------
# Routing: determinism + capacity overflow.
# ---------------------------------------------------------------------------


class TestRouting:
    def test_deterministic_routing_and_positions(self):
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(32, C), jnp.float32)
        p = dense_params(1)
        e1, g1, lb1, z1, _ = moe_router(x, p["router"], topk=K)
        e2, g2, lb2, z2, _ = moe_router(x, p["router"], topk=K)
        np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
        pos1, keep1 = moe_positions(e1, E, 8)
        pos2, keep2 = moe_positions(e2, E, 8)
        np.testing.assert_array_equal(np.asarray(pos1), np.asarray(pos2))
        np.testing.assert_array_equal(np.asarray(keep1),
                                      np.asarray(keep2))
        # renormalized top-k gates sum to one
        np.testing.assert_allclose(np.asarray(jnp.sum(g1, -1)), 1.0,
                                   rtol=1e-5)

    def test_positions_choice_major(self):
        # Every token's FIRST choice ranks before any second choice:
        # 3 tokens all first-choosing expert 0, second-choosing expert 0
        # again via a crafted [N, K] — first choices take slots 0..2.
        experts = jnp.asarray([[0, 1], [0, 1], [0, 1]], jnp.int32)
        pos, keep = moe_positions(experts, E, 8)
        np.testing.assert_array_equal(np.asarray(pos[:, 0]), [0, 1, 2])
        assert bool(jnp.all(keep))

    def test_capacity_overflow_drops_deterministically(self):
        # 5 tokens, all routed (top-1) to expert 0, capacity 2: the
        # FIRST two tokens in order keep, the rest drop.
        experts = jnp.zeros((5, 1), jnp.int32)
        pos, keep = moe_positions(experts, E, 2)
        np.testing.assert_array_equal(np.asarray(keep[:, 0]),
                                      [True, True, False, False, False])
        # and the dropped tokens pass through as ZERO layer output
        x = jnp.asarray(np.random.RandomState(0).randn(5, C),
                        jnp.float32)
        forced = jnp.concatenate(
            [jnp.full((5, 1), 1e3, jnp.float32),
             jnp.full((5, E - 1), -1e3, jnp.float32)], axis=1)
        # capacity_factor chosen so capacity == ceil(K*5*cf/E) == 2
        cf = 2 * E / (K * 5)
        y, aux, _ = moe_ffn(x, dense_params(0), topk=K,
                            capacity_factor=cf,
                            router_logits=forced)
        assert moe_capacity(5, E, cf, K) == 2
        got = np.asarray(y)
        assert np.abs(got[2:]).max() == 0.0        # dropped -> zeros
        assert np.abs(got[:2]).max() > 0.0
        assert float(aux.dropped_fraction) > 0.0

    def test_aux_losses_finite_and_balanced_case(self):
        x = jnp.asarray(np.random.RandomState(1).randn(64, C),
                        jnp.float32)
        p = dense_params(2)
        _, _, lb, z, probs = moe_router(x, p["router"], topk=K)
        assert np.isfinite(float(lb)) and np.isfinite(float(z))
        # perfectly uniform probs minimize the Switch loss at 1.0
        uni = jnp.zeros((64, E), jnp.float32)
        _, _, lb_u, _, _ = moe_router(x, p["router"], topk=K,
                                      router_logits=uni)
        assert float(lb_u) == pytest.approx(1.0, abs=1e-5)


# ---------------------------------------------------------------------------
# Exactness: forced-routing parity + top-2 gradient parity.
# ---------------------------------------------------------------------------


def _dense_reference(pt, x, experts, gates):
    """The same math as moe_ffn, spelled as dense einsums with no
    dispatch buffer: y_n = sum_k gate_nk * FFN_{e_nk}(x_n)."""
    import flax.linen as fnn

    h = fnn.gelu(jnp.einsum("nc,ecf->enf", x, pt["w1"])
                 + pt["b1"][:, None])
    y_all = jnp.einsum("enf,efc->enc", h, pt["w2"]) \
        + pt["b2"][:, None]                           # [E, N, C]
    oh = jax.nn.one_hot(experts, E, dtype=jnp.float32)  # [N, K, E]
    sel = jnp.einsum("nke,enc->nkc", oh, y_all)
    return jnp.sum(sel * gates[..., None], axis=1)


class TestExactness:
    def test_expert0_identity_gating_matches_dense(self):
        """Every token routed to expert 0 with gate 1 over the hvd_ep
        mesh == the dense expert-0 FFN (the a2a wire is exact)."""
        try:
            mesh = ep_mesh()
            pt = dense_params(5)
            stacked = ep_stack_params(pt, E)
            pspec = ep_param_pspecs(stacked)
            rs = np.random.RandomState(7)
            x = jnp.asarray(rs.randn(8 * 16, C), jnp.float32)

            def spmd(p, xb):
                n = xb.shape[0]
                forced = jnp.concatenate(
                    [jnp.full((n, 1), 1e3, jnp.float32),
                     jnp.zeros((n, E - 1), jnp.float32)], axis=1)
                y, _, _ = moe_ffn(xb, local_view(p), topk=K,
                                  capacity_factor=float(E),
                                  ep_axis=hvd.EP_AXIS,
                                  router_logits=forced)
                return y

            f = jax.jit(hvd.shard_map(
                spmd, mesh=mesh, in_specs=(pspec, P(EPALL)),
                out_specs=P(EPALL)))
            got = np.asarray(f(stacked, x))
            import flax.linen as fnn

            want = np.asarray(
                fnn.gelu(x @ pt["w1"][0] + pt["b1"][0]) @ pt["w2"][0]
                + pt["b2"][0])
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        finally:
            restore_mesh()

    def test_top2_gradient_parity_vs_dense_einsum(self):
        """Real top-2 routing (no drops): moe_ffn's value AND gradients
        match the dense einsum reference computing the identical math
        with no dispatch buffer."""
        pt = dense_params(9)
        rs = np.random.RandomState(11)
        x = jnp.asarray(rs.randn(32, C), jnp.float32)

        def moe_loss(p):
            y, _, _ = moe_ffn(x, p, topk=K, capacity_factor=float(E))
            return jnp.sum(y ** 2)

        def ref_loss(p):
            experts, gates, _, _, _ = moe_router(x, p["router"], topk=K)
            y = _dense_reference(p, x, experts, gates)
            return jnp.sum(y ** 2)

        v1, g1 = jax.value_and_grad(moe_loss)(pt)
        v2, g2 = jax.value_and_grad(ref_loss)(pt)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
            g1, g2)

    def test_moe_layer_module_sows_diagnostics(self):
        from horovod_tpu.moe import MoELayer

        x = jnp.asarray(np.random.RandomState(0).randn(2, 8, C),
                        jnp.float32)
        layer = MoELayer(num_experts=E, d_ff=F, topk=K,
                         capacity_factor=4.0)
        params = layer.init(jax.random.PRNGKey(0), x)
        y, state = layer.apply(params, x, mutable=["intermediates"])
        assert y.shape == x.shape
        inter = state["intermediates"]
        assert "moe_aux_loss" in inter and "moe_z_loss" in inter
        load = np.asarray(inter["moe_expert_load"][0])
        assert load.shape == (E,) and load.sum() > 0


# ---------------------------------------------------------------------------
# The int8+EF a2a wire.
# ---------------------------------------------------------------------------


class TestQuantizedA2A:
    def test_int8_exchange_error_bound_and_ef(self):
        """One int8 exchange's error is bounded by the per-block scale;
        with error feedback the bias telescopes instead of compounding
        (the running output sum tracks the exact sum)."""
        try:
            mesh = ep_mesh()
            from horovod_tpu.plan import compiler as _compiler

            blk = 64
            plan_q = a2a_plan("dcn", quantized=True, block=blk,
                              error_feedback=True)
            plan_x = a2a_plan("dcn")
            rs = np.random.RandomState(3)
            buf = jnp.asarray(rs.randn(8, E, 16, C), jnp.float32)

            def spmd(b):
                x = b[0]
                exact, _ = _compiler.lower_a2a(plan_x, x,
                                               axis=hvd.EP_AXIS)
                q1, _ = _compiler.lower_a2a(plan_q, x,
                                            axis=hvd.EP_AXIS)
                # EF: T exchanges of the SAME buffer, residual threaded
                res = jnp.zeros_like(x)
                acc = jnp.zeros_like(x)
                for _i in range(4):
                    out, res = _compiler.lower_a2a(
                        plan_q, x, axis=hvd.EP_AXIS, residual=res)
                    acc = acc + out
                return (exact[None], q1[None], acc[None])

            f = jax.jit(hvd.shard_map(
                spmd, mesh=mesh,
                in_specs=(P(EPALL),),
                out_specs=(P(EPALL), P(EPALL), P(EPALL))))
            exact, q1, acc = (np.asarray(v) for v in f(buf))
            scale_bound = np.abs(buf).max() / 127.0
            err1 = np.abs(q1 - exact).max()
            assert err1 <= scale_bound + 1e-6
            assert err1 > 0                       # int8 actually engaged
            # telescoping: |sum of 4 EF outputs - 4*exact| stays at the
            # single-exchange bound, not 4x it
            err_acc = np.abs(acc - 4 * exact).max()
            assert err_acc <= 2 * scale_bound + 1e-6
        finally:
            restore_mesh()

    def test_quantized_a2a_gradients_flow(self):
        """The int8 exchange's custom VJP keeps gradients alive (the
        backward rides the same int8 wire; a plain round would zero
        them)."""
        try:
            mesh = ep_mesh()
            from horovod_tpu.plan import compiler as _compiler

            plan_q = a2a_plan("dcn", quantized=True, block=64)
            rs = np.random.RandomState(5)
            buf = jnp.asarray(rs.randn(8, E, 4, C), jnp.float32)

            def spmd(b):
                def loss(x):
                    out, _ = _compiler.lower_a2a(plan_q, x,
                                                 axis=hvd.EP_AXIS)
                    return jnp.sum(out ** 2)

                g = jax.grad(loss)(b[0])
                return jnp.sum(jnp.abs(g))[None]

            f = jax.jit(hvd.shard_map(
                spmd, mesh=mesh, in_specs=(P(EPALL),),
                out_specs=P(EPALL)))
            gsum = np.asarray(f(buf))
            assert (gsum > 0).all()
        finally:
            restore_mesh()


# ---------------------------------------------------------------------------
# The hvd_ep mesh: geometry + expert-grad isolation (ZeRO-2 compose).
# ---------------------------------------------------------------------------


class TestEPMesh:
    def test_ep_mesh_geometry(self):
        try:
            mesh = ep_mesh(ep=2, data=(2, 2))
            assert hvd.ep_size() == 2
            assert hvd.pp_size() == 1
            assert hvd.data_mesh_shape() == (2, 2)
            assert mesh.axis_names == (hvd.EP_AXIS, hvd.CROSS_AXIS,
                                       hvd.LOCAL_AXIS)
            from horovod_tpu.common import basics

            assert basics.world_axes() == hvd.HVD_AXES
            assert "ep2" in basics.mesh_geometry()
        finally:
            restore_mesh()

    def test_ep_composes_with_pp_on_4d_mesh(self):
        hvd.shutdown()
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(1, 2),
                     ep_size=2, pp_stages=2)
            from horovod_tpu.common import basics

            assert hvd.pp_size() == 2
            assert hvd.ep_size() == 2
            assert hvd.data_mesh_shape() == (1, 2)
            assert hvd.mesh().axis_names == (
                hvd.PP_AXIS, hvd.EP_AXIS, hvd.CROSS_AXIS,
                hvd.LOCAL_AXIS)
            # pp/ep are NOT data axes: shards and gradient collectives
            # stay on (cross, local) per (stage, expert-group) cell.
            assert basics.world_axes() == hvd.HVD_AXES
            assert "pp2.ep2" in basics.mesh_geometry()
        finally:
            restore_mesh()

    def test_ep_does_not_compose_with_pods(self):
        hvd.shutdown()
        try:
            with pytest.raises(ValueError, match="3-level"):
                hvd.init(devices=jax.devices(), mesh_shape=(1, 2, 2),
                         ep_size=2)
        finally:
            restore_mesh()

    def test_moe_knob_validation(self):
        try:
            ep_mesh(ep=2, data=(2, 2))
            # experts must divide by the live ep axis
            with pytest.raises(ValueError, match="hvd_ep"):
                hvd.DistributedOptimizer(optax.sgd(0.1), moe_experts=3)
            with pytest.raises(ValueError, match="capacity"):
                hvd.DistributedOptimizer(optax.sgd(0.1), moe_experts=4,
                                         moe_capacity_factor=0.0)
            with pytest.raises(ValueError, match="moe_topk"):
                hvd.DistributedOptimizer(optax.sgd(0.1), moe_experts=4,
                                         moe_topk=9)
            hvd.DistributedOptimizer(optax.sgd(0.1), moe_experts=4,
                                     moe_capacity_factor=1.25,
                                     moe_topk=2)
            hvd.value_and_grad(lambda p: p, moe_experts=4,
                               moe_capacity_factor=1.25, moe_topk=2)
        finally:
            restore_mesh()

    def test_expert_grad_isolation_zero2_one_step_parity(self):
        """EP x ZeRO-2: one SGD-momentum step on the hvd_ep mesh — the
        batch sharded over (ep, cross, local), expert grads reducing
        ONLY within their expert's data group — equals the dense
        single-device step on the global-mean gradient."""
        try:
            mesh = ep_mesh(ep=2, data=(2, 2))
            ep = 2
            pt = dense_params(21)
            stacked = ep_stack_params(pt, ep)
            pspec = ep_param_pspecs(stacked)
            rs = np.random.RandomState(23)
            Ng = 8 * 16
            x = jnp.asarray(rs.randn(Ng, C), jnp.float32)
            y = jnp.asarray(rs.randn(Ng, C), jnp.float32)
            cf = float(E)  # no drops: distributed == global routing

            tx = hvd.DistributedOptimizer(
                optax.sgd(0.1, momentum=0.9), zero_stage=2,
                moe_experts=E, moe_capacity_factor=cf, moe_topk=K)
            sspec_of = lambda st: jax.tree.map(  # noqa: E731
                lambda l: P(EPALL) if getattr(l, "ndim", 0) >= 1
                else P(), st)
            state_tpl = tx.init(local_view(stacked))

            def init_spmd(p):
                return tx.init(local_view(p))

            state = jax.jit(hvd.shard_map(
                init_spmd, mesh=mesh, in_specs=(pspec,),
                out_specs=sspec_of(state_tpl)))(stacked)
            sspec = sspec_of(state)

            def step_spmd(p, st, xb, yb):
                lp = local_view(p)

                def loss_fn(q):
                    out, _, _ = moe_ffn(xb, q, topk=K,
                                        capacity_factor=cf,
                                        ep_axis=hvd.EP_AXIS)
                    return jnp.mean((out - yb) ** 2)

                loss, g = jax.value_and_grad(loss_fn)(lp)
                g = ep_mean_dense_grads(g)
                upd, st2 = tx.update(g, st, lp)
                new = optax.apply_updates(lp, upd)
                loss = lax.pmean(loss, EPALL)
                # Re-establish the router's ep replication by
                # construction (the ZeRO buckets mixed ep-varying
                # expert leaves into the gather).
                rep = lax.axis_index(hvd.EP_AXIS)
                new_router = lax.psum(
                    jnp.where(rep == 0, new["router"],
                              jnp.zeros_like(new["router"])),
                    hvd.EP_AXIS)
                new_p = {k: (v[None] if k in EXPERT_LEAVES else v)
                         for k, v in new.items()}
                new_p["router"] = new_router
                return loss, new_p, st2

            data = P(EPALL)
            step = jax.jit(hvd.shard_map(
                step_spmd, mesh=mesh,
                in_specs=(pspec, sspec, data, data),
                out_specs=(P(), pspec, sspec)))
            loss, new_stacked, state = step(stacked, state, x, y)

            # dense single-device reference on the global-mean gradient
            def ref_loss(q):
                out, _, _ = moe_ffn(x, q, topk=K, capacity_factor=cf)
                return jnp.mean((out - y) ** 2)

            want_loss, g_ref = jax.value_and_grad(ref_loss)(pt)
            np.testing.assert_allclose(float(loss), float(want_loss),
                                       rtol=1e-5)
            ref_tx = optax.sgd(0.1, momentum=0.9)
            upd, _ = ref_tx.update(g_ref, ref_tx.init(pt), pt)
            want_p = optax.apply_updates(pt, upd)
            got = jax.device_get(new_stacked)
            for k in ("w1", "b1", "w2", "b2"):
                got_full = np.concatenate(
                    [np.asarray(got[k][g]) for g in range(ep)], axis=0)
                np.testing.assert_allclose(
                    got_full, np.asarray(want_p[k]), rtol=2e-4,
                    atol=2e-6)
            np.testing.assert_allclose(
                np.asarray(got["router"]), np.asarray(want_p["router"]),
                rtol=2e-4, atol=2e-6)
            # isolation: the two ep groups hold DIFFERENT experts —
            # their updated expert weights must differ (nothing mixed
            # them across hvd_ep)
            assert not np.allclose(np.asarray(got["w1"][0]),
                                   np.asarray(got["w1"][1]))
        finally:
            restore_mesh()


# ---------------------------------------------------------------------------
# Accounting + spans.
# ---------------------------------------------------------------------------


class TestAccounting:
    def test_a2a_accounting_matches_prediction(self):
        """Trace-time a2a accounting == the router-predicted bytes of
        predict_a2a_bytes, per exchange, by construction."""
        try:
            mesh = ep_mesh()
            pt = dense_params(0)
            stacked = ep_stack_params(pt, E)
            pspec = ep_param_pspecs(stacked)
            x = jnp.asarray(np.random.RandomState(0).randn(8 * 16, C),
                            jnp.float32)
            cf = 2.0
            Nd = 16  # tokens per device
            cap = moe_capacity(Nd, E, cf, K)
            for quantized in (False, True):
                plan = a2a_plan("dcn", quantized=quantized, block=64)

                def spmd(p, xb):
                    y, _, _ = moe_ffn(xb, local_view(p), topk=K,
                                      capacity_factor=cf,
                                      ep_axis=hvd.EP_AXIS,
                                      a2a_plan=plan)
                    return y

                f = jax.jit(hvd.shard_map(
                    spmd, mesh=mesh, in_specs=(pspec, P(EPALL)),
                    out_specs=P(EPALL)))
                with record_wire_stats() as ws:
                    jax.block_until_ready(f(stacked, x))
                n = E * cap * C
                rows = predict_a2a_bytes(plan, n, 4, E)
                want = rows[0]["bytes"] * 2      # dispatch + combine
                assert ws.a2a_calls == 2
                assert ws.a2a_bytes == pytest.approx(want)
                assert ws.a2a_bytes_fp == pytest.approx(
                    rows[0]["fp_bytes"] * 2)
                if quantized:
                    assert ws.a2a_bytes < ws.a2a_bytes_fp
        finally:
            restore_mesh()

    def test_moe_spans_balanced_strict(self, tmp_path):
        from horovod_tpu.monitor import span_audit

        tl = str(tmp_path / "moe_tl.json")
        hvd.shutdown()
        import os

        os.environ["HOROVOD_TIMELINE"] = tl
        try:
            hvd.init(devices=jax.devices(), mesh_shape=(2, 1),
                     ep_size=4)
            mesh = hvd.mesh()
            pt = dense_params(0)
            stacked = ep_stack_params(pt, E)
            pspec = ep_param_pspecs(stacked)
            x = jnp.asarray(np.random.RandomState(0).randn(8 * 8, C),
                            jnp.float32)

            def spmd(p, xb):
                y, _, _ = moe_ffn(xb, local_view(p), topk=K,
                                  capacity_factor=2.0,
                                  ep_axis=hvd.EP_AXIS)
                return y

            f = jax.jit(hvd.shard_map(
                spmd, mesh=mesh, in_specs=(pspec, P(EPALL)),
                out_specs=P(EPALL)))
            jax.block_until_ready(f(stacked, x))
        finally:
            del os.environ["HOROVOD_TIMELINE"]
            hvd.shutdown()
            hvd.init(devices=jax.devices())
        audit = span_audit.audit_spans(tl, prefix="MOE:",
                                       require_balanced=True,
                                       require_spans=True, strict=True)
        assert audit.count.get("MOE:DISPATCH", 0) == 1
        assert audit.count.get("MOE:COMBINE", 0) == 1


# ---------------------------------------------------------------------------
# Golden --dump-plan table: the a2a rows are pinned text.
# ---------------------------------------------------------------------------


class TestGoldenPlan:
    def test_dump_plan_pins_a2a_leg(self):
        sp = hvd.describe_plan(mesh_shape=(2, 2), moe_experts=4,
                               moe_topk=2, moe_capacity=1.25,
                               moe_quantized=True, quantized=False,
                               zero_stage=0, overlap=False,
                               hierarchical=False, num_comm_streams=1,
                               quant_block=256,
                               fusion_threshold_bytes=64 * 1024 * 1024,
                               fused=False, quantized_pod=False,
                               pp_stages=0)
        table = sp.table(payload_bytes=4 * 1024 * 1024)
        assert ("a2a                1 dcn   all_to_all     int8/256   "
                "yes xla          0") in table
        assert ("moe: experts=4 topk=2 capacity_factor=1.25 "
                "quantized=on (a2a rows priced per issue — dispatch + "
                "combine = 2 per layer, docs/moe.md)") in table
        assert sp.encode() == (
            "allreduce:flat.psum[payload]|s1|sync + "
            "ep4.k2@a2a:dcn.all_to_all[int8/256+ef]|s1|sync")

    def test_ici_hop_never_quantizes(self):
        sp = hvd.describe_plan(mesh_shape=(1, 4), moe_experts=2,
                               moe_quantized=True, quantized=False,
                               zero_stage=0, overlap=False,
                               hierarchical=False, pp_stages=0)
        assert sp.moe.legs[0].level == "ici"
        assert not sp.moe.is_quantized
        assert not sp.moe_quantized


# ---------------------------------------------------------------------------
# Autotune schema v9.
# ---------------------------------------------------------------------------


class TestAutotuneV9:
    def test_encode_decode_moe_segment(self):
        from horovod_tpu.autotune.parameter_manager import TunedParams
        from horovod_tpu.plan.planner import decode_tuned, encode_tuned

        p = TunedParams(moe_capacity_factor=1.5, moe_quantized=True)
        enc = encode_tuned(p, moe=True)
        assert enc == "ar.flat|fp|s1|sync|moe1.5/q8"
        d = decode_tuned(enc)
        assert d["moe_capacity_factor"] == 1.5 and d["moe_quantized"]
        # moe off: the segment (and both knobs) drop out — dead knobs
        # never split trials
        assert encode_tuned(p) == "ar.flat|fp|s1|sync"
        d0 = decode_tuned(encode_tuned(p))
        assert d0["moe_capacity_factor"] == 0.0
        assert not d0["moe_quantized"]

    def test_manager_canonicalizes_dead_moe_knobs(self):
        from horovod_tpu.autotune.parameter_manager import (
            ParameterManager, TunedParams)

        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=3, tune_moe=False)
        c = pm._canonicalize(TunedParams(moe_capacity_factor=2.0,
                                        moe_quantized=True))
        assert c.moe_capacity_factor == 0.0 and not c.moe_quantized

    def test_manager_snaps_moe_proposals(self):
        from horovod_tpu.autotune.parameter_manager import (
            ParameterManager, TunedParams)

        pm = ParameterManager(TunedParams(moe_capacity_factor=1.25),
                              warmup_samples=0, max_samples=8,
                              tune_moe=True, moe_experts=4)
        for u9 in (0.0, 0.3, 0.7, 1.0):
            p = pm._from_unit((0.5, 0.5, 0.25, 0.25, 0.25, 0.0, 0.25,
                               0.0, 0.0, u9, 0.9))
            assert 1.0 <= p.moe_capacity_factor <= 2.0
            assert (p.moe_capacity_factor * 4) == int(
                p.moe_capacity_factor * 4)       # quarter-snapped
            assert p.moe_quantized
        # pre-v9 unit tuples (9 dims) still resolve
        p = pm._from_unit((0.5, 0.5, 0.25, 0.25, 0.25, 0.0, 0.25,
                           0.0, 0.0))
        assert p.moe_capacity_factor >= 1.0

    def test_csv_roundtrip_with_moe_columns(self, tmp_path):
        from horovod_tpu.autotune.parameter_manager import (
            CSV_FIELDS, ParameterManager, TunedParams, read_log)

        assert "moe_capacity_factor" in CSV_FIELDS
        assert "moe_quantized" in CSV_FIELDS
        path = str(tmp_path / "log.csv")
        pm = ParameterManager(TunedParams(moe_capacity_factor=1.25,
                                          moe_quantized=True),
                              warmup_samples=0, max_samples=3,
                              tune_moe=True, moe_experts=4,
                              log_path=path)
        while not pm.done:
            pm.record_sample(1.0)
        rows = read_log(path)
        assert rows and rows[0]["moe_capacity_factor"] == 1.25
        assert rows[0]["moe_quantized"] is True
        assert rows[0]["plan"].endswith("|moe1.25/q8")

    def test_read_log_tolerant_of_v8_csv(self, tmp_path):
        from horovod_tpu.autotune.parameter_manager import read_log

        path = tmp_path / "v8.csv"
        path.write_text(
            "sample,fusion_threshold_bytes,quant_block,"
            "hierarchical_allreduce,zero_sharding,zero_stage,overlap,"
            "num_comm_streams,fused,pp_microbatches,pp_interleave,"
            "score_steps_per_sec,plan\n"
            "1,4194304,256,0,0,0,0,1,0,0,1,12.5,ar.flat|fp|s1|sync\n")
        rows = read_log(str(path))
        assert rows[0]["moe_capacity_factor"] == 0.0
        assert rows[0]["moe_quantized"] is False

    def test_tuned_params_from_v8_dict(self):
        from horovod_tpu.autotune.parameter_manager import TunedParams

        p = TunedParams.from_dict({
            "fusion_threshold_bytes": 4 << 20, "quant_block": 256,
            "hierarchical_allreduce": False, "zero_stage": 2,
            "overlap": True, "num_comm_streams": 2, "fused": False,
            "pp_microbatches": 8, "pp_interleave": 2})
        assert p.moe_capacity_factor == 0.0
        assert p.moe_quantized is False

    def test_shortlist_prices_moe_candidates(self):
        from horovod_tpu.plan.planner import shortlist

        rows = shortlist(8 * 1024 * 1024, mesh_shape=(2, 2),
                         tune_moe=True, moe_experts=4,
                         tune_hierarchical=False, k=8)
        assert rows
        caps = {r.params.moe_capacity_factor for r in rows}
        assert len(caps) > 1       # distinct capacity candidates priced
        assert any(r.params.moe_quantized for r in rows)
        for r in rows:
            assert r.plan.moe is not None
            assert r.cost.moe_ms > 0


# ---------------------------------------------------------------------------
# Serving: per-expert load metrics + hot-expert replication.
# ---------------------------------------------------------------------------


@pytest.mark.serve
class TestServeMoE:
    @pytest.fixture(scope="class")
    def model(self):
        from horovod_tpu.models import GPT, gpt_tiny

        cfg = gpt_tiny(dtype=jnp.float32, num_heads=8)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (1, 8)))
        params = GPT(cfg).init(jax.random.PRNGKey(0), tokens)["params"]
        return cfg, params

    def _page_cfg(self, cfg):
        from horovod_tpu.serve.kv_cache import PageConfig

        return PageConfig(num_pages=64, page_size=4, max_slots=4,
                          pages_per_slot=16,
                          num_layers=cfg.num_layers,
                          num_heads=cfg.num_heads,
                          head_dim=cfg.d_model // cfg.num_heads)

    def test_engine_expert_load_metrics(self, model):
        from horovod_tpu.monitor import registry as _metrics
        from horovod_tpu.serve.engine import GenerationEngine
        from horovod_tpu.serve.engine import VirtualClock
        from horovod_tpu.serve.scheduler import Request

        cfg, params = model
        eng = GenerationEngine(cfg, params, self._page_cfg(cfg),
                               eos_id=1, moe_experts=4)
        reqs = [Request(prompt=[4 * i % 16, 3, 5], max_new_tokens=3,
                        arrival_time=0.0) for i in range(3)]
        eng.run(reqs, clock=VirtualClock())
        assert eng.expert_tokens.sum() > 0
        snap = _metrics.default_registry().snapshot()
        hists = {k: v for k, v in snap["histograms"].items()
                 if k.startswith("serve.expert_tokens")}
        assert hists and sum(h["count"] for h in hists.values()) > 0

    def test_hot_expert_replication_under_skew(self, model):
        from horovod_tpu.serve.replica import ReplicaSet
        from horovod_tpu.serve.engine import VirtualClock
        from horovod_tpu.serve.scheduler import Request

        cfg, params = model
        rset = ReplicaSet(cfg, params, self._page_cfg(cfg),
                          n_replicas=2, eos_id=1, moe_experts=4,
                          hot_expert_factor=1.5, rebalance_every=2)
        # Skewed traffic: EVERY consumed token routes to expert 0
        # (all prompt tokens are multiples of 4; max_new_tokens=1 means
        # no sampled token is ever fed back).
        reqs = [Request(prompt=[8, 4, 12], max_new_tokens=1,
                        arrival_time=0.0) for _ in range(8)]
        rset.run(reqs, clock=VirtualClock())
        assert int(rset.expert_replicas[0]) > 1      # expert 0 grew
        assert rset.hot_expert_events
        assert rset.hot_expert_events[0]["expert"] == 0
        # a cold expert did not replicate
        assert int(rset.expert_replicas[1]) == 1

    def test_expert_affinity_dispatch_spreads_hot_expert(self, model):
        from horovod_tpu.serve.replica import ReplicaSet

        cfg, params = model
        rset = ReplicaSet(cfg, params, self._page_cfg(cfg),
                          n_replicas=2, eos_id=1, moe_experts=4)
        assert rset._engine_set(0) == [0]
        rset.expert_replicas[0] = 2
        assert rset._engine_set(0) == [0, 1]

    def test_expert_load_rides_flight_dump(self, model, tmp_path):
        from horovod_tpu.monitor import flight as _flight
        from horovod_tpu.serve.engine import GenerationEngine
        from horovod_tpu.serve.engine import VirtualClock
        from horovod_tpu.serve.scheduler import Request

        cfg, params = model
        eng = GenerationEngine(cfg, params, self._page_cfg(cfg),
                               eos_id=1, moe_experts=4)
        eng.run([Request(prompt=[8, 3, 5], max_new_tokens=2,
                         arrival_time=0.0)], clock=VirtualClock())
        rec = _flight.recorder()
        dump = rec.build_dump("test")
        assert "expert_load" in dump
        assert sum(dump["expert_load"].values()) > 0
