"""Unified observability layer tests (horovod_tpu/monitor/): registry
semantics, sinks, cross-rank aggregation, StallInspector (including the
chaos-stall acceptance scenario), host/device profile correlation, span
audit, and the <1% registry-overhead budget on the 8-device CPU mesh."""

import importlib.util
import json
import os
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import chaos, monitor
from horovod_tpu.common import counters
from horovod_tpu.monitor import (
    JsonlSink,
    MetricsRegistry,
    PrometheusSink,
    StallInspector,
    audit_spans,
)
from horovod_tpu.monitor.registry import (
    LOG2_BUCKET_BOUNDS,
    NUM_BUCKETS,
    _bucket_index,
)
from horovod_tpu.monitor.span_audit import SpanImbalanceError


# ---------------------------------------------------------------------------
# Registry semantics


class TestRegistry:
    def test_counter_monotone(self):
        r = MetricsRegistry(enabled=True)
        c = r.counter("a.b")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        assert c.value == 3.5

    def test_gauge(self):
        r = MetricsRegistry(enabled=True)
        g = r.gauge("q", role="x")
        g.set(7)
        g.add(-2)
        assert g.value == 5.0

    def test_histogram_log2_buckets(self):
        r = MetricsRegistry(enabled=True)
        h = r.histogram("lat")
        assert _bucket_index(0.5) == 0       # <= 2^0
        assert _bucket_index(1.0) == 0
        assert _bucket_index(2.0) == 1
        assert _bucket_index(3.0) == 2       # 2 < 3 <= 4
        assert _bucket_index(1024.0) == 10
        assert _bucket_index(2.0 ** 40) == NUM_BUCKETS - 1  # +Inf bucket
        for v in (0.5, 3.0, 1024.0):
            h.observe(v)
        assert h.count == 3
        assert h.sum == pytest.approx(1027.5)
        assert h.counts[0] == 1 and h.counts[2] == 1 and h.counts[10] == 1
        assert LOG2_BUCKET_BOUNDS[-1] == float("inf")

    def test_labels_are_identity(self):
        r = MetricsRegistry(enabled=True)
        a = r.counter("c", hop="ici")
        b = r.counter("c", hop="dcn")
        assert a is not b
        assert a is r.counter("c", hop="ici")
        assert a.key == "c{hop=ici}"

    def test_kind_conflict_raises(self):
        r = MetricsRegistry(enabled=True)
        r.counter("x")
        with pytest.raises(ValueError):
            r.gauge("x")

    def test_disabled_registry_noops(self):
        r = MetricsRegistry(enabled=False)
        c = r.counter("n")
        c.inc(5)
        r.histogram("h").observe(1)
        assert c.value == 0.0
        assert r.histogram("h").count == 0

    def test_enabled_is_the_default(self):
        # The acceptance contract: the registry defaults ON.
        assert monitor.metrics_enabled()

    def test_snapshot_and_prefix_filter(self):
        r = MetricsRegistry(enabled=True)
        r.counter("serve.steps").inc(3)
        r.gauge("comm.depth").set(2)
        r.histogram("serve.lat").observe(4)
        snap = r.snapshot()
        assert snap["counters"]["serve.steps"] == 3.0
        assert snap["gauges"]["comm.depth"] == 2.0
        assert snap["histograms"]["serve.lat"]["count"] == 1
        only_serve = r.snapshot(prefix="serve.")
        assert "comm.depth" not in only_serve["gauges"]
        assert "serve.steps" in only_serve["counters"]


# ---------------------------------------------------------------------------
# Sinks


class TestSinks:
    def test_jsonl_sink_roundtrip(self, tmp_path):
        r = MetricsRegistry(enabled=True)
        r.counter("k").inc(2)
        path = str(tmp_path / "m.jsonl")
        sink = JsonlSink(path)
        sink.write(r.snapshot())
        r.counter("k").inc()
        sink.write(r.snapshot())
        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 2
        assert lines[0]["counters"]["k"] == 2.0
        assert lines[1]["counters"]["k"] == 3.0
        assert lines[1]["kind"] == "metrics"

    def test_prometheus_endpoint(self):
        r = MetricsRegistry(enabled=True)
        r.counter("comm.bytes", hop="ici").inc(128)
        r.gauge("serve.queue_depth").set(4)
        h = r.histogram("lat.ms")
        h.observe(3)
        h.observe(100)
        sink = PrometheusSink(r, port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{sink.port}/metrics",
                timeout=5).read().decode()
        finally:
            sink.close()
        assert 'horovod_comm_bytes{hop="ici"} 128' in body
        assert "# TYPE horovod_comm_bytes counter" in body
        assert "horovod_serve_queue_depth 4" in body
        # cumulative buckets: the le="4" bucket holds the 3-observation,
        # the +Inf bucket holds both
        assert 'horovod_lat_ms_bucket{le="4"} 1' in body
        assert 'horovod_lat_ms_bucket{le="+Inf"} 2' in body
        assert "horovod_lat_ms_count 2" in body

    def test_timeline_counter_mirror(self, tmp_path):
        path = str(tmp_path / "tl.json")
        hvd.start_timeline(path)
        try:
            monitor.metrics().counter("mirror.test").inc(5)
            monitor.flush()
        finally:
            hvd.stop_timeline()
        events = json.load(open(path))
        mirrors = [e for e in events if e["ph"] == "C"
                   and e["name"] == "METRIC:mirror.test"]
        assert mirrors and mirrors[-1]["args"]["value"] >= 5.0


# ---------------------------------------------------------------------------
# Wire-stats + collective instrumentation


def _traced_allreduce():
    mesh = hvd.mesh()
    f = jax.jit(hvd.shard_map(
        lambda x: hvd.allreduce(x, op=hvd.Sum),
        mesh=mesh, in_specs=P(hvd.HVD_AXES), out_specs=P()))
    with hvd.record_wire_stats() as ws:
        f.lower(jnp.ones((8, 4)))
    return ws


class TestWireInstrumentation:
    def test_traced_bytes_feed_registry(self):
        before = monitor.metrics().counter("comm.bytes", hop="ici").value
        traces_before = monitor.metrics().counter("comm.traces").value
        ws = _traced_allreduce()
        assert ws.ici_bytes > 0
        after = monitor.metrics().counter("comm.bytes", hop="ici").value
        assert after - before == pytest.approx(ws.ici_bytes)
        assert monitor.metrics().counter("comm.traces").value == \
            traces_before + 1
        # the published gauges describe the last traced program
        assert monitor.metrics().gauge("comm.wire.ici_bytes").value == \
            pytest.approx(ws.ici_bytes)

    def test_registry_counts_without_recorder(self):
        # _acct_enabled(): the registry accounts trace-time bytes even
        # with no record_wire_stats context installed.
        before = monitor.metrics().counter("comm.bytes", hop="ici").value
        mesh = hvd.mesh()
        jax.jit(hvd.shard_map(
            lambda x: hvd.allreduce(x, op=hvd.Sum),
            mesh=mesh, in_specs=P(hvd.HVD_AXES), out_specs=P()
        )).lower(jnp.ones((8, 2)))
        assert monitor.metrics().counter(
            "comm.bytes", hop="ici").value > before

    def test_eager_latency_histogram(self):
        h = monitor.metrics().histogram("comm.eager.latency_ms",
                                        kind="allreduce")
        before = h.count
        hvd.allreduce(jnp.ones(3), name="monitor.eager.probe")
        assert h.count == before + 1


# ---------------------------------------------------------------------------
# Cross-rank aggregation


class TestAggregation:
    def test_world_of_one_is_identity(self):
        monitor.metrics().counter("agg.probe").inc(4)
        agg = monitor.aggregate()
        assert agg["world"] == 1
        assert agg["counters"]["agg.probe"] == \
            monitor.metrics().counter("agg.probe").value

    def test_flat_layout_roundtrip_shapes(self):
        r = MetricsRegistry(enabled=True)
        r.counter("c1").inc(1)
        r.gauge("g1").set(2)
        r.histogram("h1").observe(3)
        snap = r.snapshot()
        keys, vals = r._flat_layout(snap)
        assert len(keys) == 3
        # histogram contributes counts + sum + count
        assert len(vals) == 2 + NUM_BUCKETS + 2

    def test_aggregation_survives_elastic_resize(self):
        """Counters persist across the shutdown→init cycle (an elastic
        world transition) and aggregation still works on the new world."""
        marker = monitor.metrics().counter("agg.resize_probe")
        marker.inc(11)
        inc_before = monitor.metrics().counter(
            "elastic.incarnations").value
        hvd.shutdown()
        try:
            hvd.init(mesh_shape=(2, 4))
            assert monitor.metrics().counter(
                "agg.resize_probe").value == 11.0
            agg1 = monitor.aggregate()
            assert agg1["counters"]["agg.resize_probe"] == 11.0
            hvd.shutdown()
            hvd.init(mesh_shape=(1, 8))  # resized world
            marker.inc()
            agg2 = monitor.aggregate()
            assert agg2["counters"]["agg.resize_probe"] == 12.0
            assert monitor.metrics().counter(
                "elastic.incarnations").value >= inc_before + 2
        finally:
            hvd.shutdown()
            hvd.init()


# ---------------------------------------------------------------------------
# StallInspector


class TestStallInspector:
    def test_warning_structure_and_api(self, tmp_path):
        path = str(tmp_path / "tl.json")
        hvd.start_timeline(path)
        insp = StallInspector(warning_secs=0.05)
        try:
            insp.record_start("stalled.tensor", kind="allreduce", rank=0)
            time.sleep(0.08)
            assert [s["name"] for s in insp.stalled()] == ["stalled.tensor"]
            fired = insp.check()
            assert len(fired) == 1
            w = fired[0]
            assert "waiting for remainder of ranks" in w["message"]
            assert "Stalled tensor: stalled.tensor" in w["message"]
            assert "ready ranks: 0" in w["message"]
            assert w["rank"] == 0
            # warned once, not per check
            assert insp.check() == []
            insp.record_done("stalled.tensor")
            assert insp.stalled() == []
        finally:
            hvd.stop_timeline()
        events = json.load(open(path))
        stall_evs = [e for e in events
                     if str(e["name"]).startswith("STALL:")]
        assert stall_evs and stall_evs[0]["ph"] == "i"
        assert stall_evs[0]["args"]["ready_ranks"] == [0]

    def test_watchdog_thread_fires(self):
        insp = StallInspector(warning_secs=0.05, check_interval=0.02)
        insp.start()
        try:
            insp.record_start("bg.tensor")
            time.sleep(0.25)
            assert insp.warnings()
        finally:
            insp.record_done("bg.tensor")
            insp.stop()

    def test_chaos_stall_produces_rank_attributed_warning(
            self, tmp_path, monkeypatch):
        """Acceptance: a deliberately stalled eager collective (chaos
        ``stall`` action) produces a rank-attributed StallInspector
        warning and a STALL:* timeline instant within stall_check_time."""
        from horovod_tpu.monitor import stall as stall_mod

        monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "0.2")
        hvd.shutdown()
        counters.reset_all()
        try:
            hvd.init()
            insp = stall_mod.stall_inspector()
            assert insp.warning_secs == 0.2  # config reached the watchdog
            n_before = len(insp.warnings())
            path = str(tmp_path / "tl.json")
            hvd.start_timeline(path)
            chaos.configure(chaos.FaultPlan().add(
                "collective.eager", action="stall", secs=1.0))
            warn_count = monitor.metrics().counter(
                "stall.warnings", kind="allreduce").value
            try:
                hvd.allreduce(jnp.ones(2), name="stalled.probe")
            finally:
                chaos.configure(None)
                hvd.stop_timeline()
            new = insp.warnings()[n_before:]
            assert new, "no stall warning fired during the injected stall"
            w = new[-1]
            assert w["name"] == "stalled.probe"
            assert w["rank"] == 0 and 0 in w["ready_ranks"]
            # fired while the op was still stalled — i.e. within
            # stall_check_time of crossing the threshold, not after the
            # 1 s injected stall completed
            assert w["elapsed_secs"] < 0.9
            assert monitor.metrics().counter(
                "stall.warnings", kind="allreduce").value > warn_count
            events = json.load(open(path))
            stall_evs = [e for e in events
                         if e["name"] == "STALL:stalled.probe"]
            assert stall_evs and stall_evs[0]["ph"] == "i"
            assert stall_evs[0]["args"]["rank"] == 0
            # after completion the op is no longer in flight
            assert not any(s["name"] == "stalled.probe"
                           for s in hvd.stalled_tensors())
        finally:
            chaos.reset()
            monkeypatch.delenv("HOROVOD_STALL_CHECK_TIME_SECONDS",
                               raising=False)
            hvd.shutdown()
            hvd.init()

    def test_serve_request_tracking_clears(self):
        from horovod_tpu.models import gpt_tiny
        from horovod_tpu.models.gpt import GPT
        from horovod_tpu.serve import PageConfig
        from horovod_tpu.serve.engine import GenerationEngine, VirtualClock
        from horovod_tpu.serve.scheduler import Request

        cfg = gpt_tiny(num_heads=2, num_layers=1, d_model=16,
                       vocab_size=32)
        params = GPT(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 4), jnp.int32))["params"]
        pc = PageConfig(num_pages=9, page_size=4, max_slots=2,
                        pages_per_slot=4, num_layers=cfg.num_layers,
                        num_heads=cfg.num_heads,
                        head_dim=cfg.d_model // cfg.num_heads)
        eng = GenerationEngine(cfg, params, pc, eos_id=1)
        steps_before = monitor.metrics().counter("serve.steps").value
        eng.run([Request(prompt=[5, 6, 7], max_new_tokens=3)],
                clock=VirtualClock())
        assert monitor.metrics().counter("serve.steps").value > steps_before
        # every tracked request was untracked on eviction
        from horovod_tpu.monitor.stall import stall_inspector

        assert not any(n.startswith("serve.req")
                       for n in stall_inspector().in_flight())


# ---------------------------------------------------------------------------
# Counters mirror + chaos monotonicity


class TestCounterMirror:
    def test_fault_counters_mirror_into_registry(self):
        before = monitor.metrics().counter("mirror.fault.probe").value
        counters.increment("mirror.fault.probe")
        assert monitor.metrics().counter(
            "mirror.fault.probe").value == before + 1

    def test_counters_stay_monotone_under_chaos(self):
        """With chaos faults active every registry counter must stay
        monotone — sampled across a run of dropping/succeeding eager
        collectives (the acceptance invariant for chaotic runs)."""
        chaos.configure(chaos.FaultPlan().add(
            "collective.eager", action="drop", every=2))
        try:
            reg = monitor.metrics()
            last = {}
            for i in range(8):
                try:
                    hvd.allreduce(jnp.ones(2), name=f"monotone.{i}")
                except Exception:
                    pass  # injected drop
                snap = reg.snapshot()
                for k, v in snap["counters"].items():
                    assert v >= last.get(k, 0.0), \
                        f"counter {k} decreased: {last.get(k)} -> {v}"
                last.update(snap["counters"])
            assert last.get("chaos.drop", 0) >= 1
        finally:
            chaos.reset()


# ---------------------------------------------------------------------------
# Overhead budget (acceptance: <1% of the 8-device CPU mesh step)


class TestOverhead:
    def test_registry_overhead_under_one_percent_of_step(self):
        """The per-step registry work the framework does (a bounded
        handful of counter/gauge/histogram updates — everything else is
        trace-time) must cost <1% of a real 8-device-mesh step."""
        mesh = hvd.mesh()
        tx = hvd.DistributedOptimizer(__import__("optax").sgd(0.01))
        # A bench-representative step (4-layer 512-wide MLP, batch 8/rank)
        # rather than a toy matmul: the budget is a FRACTION of step time,
        # so the denominator must look like a real training step.
        params = {f"w{i}": jnp.full((512, 512), 0.01) for i in range(4)}
        state = tx.init(params)

        def loss_fn(p, x):
            h = x
            for i in range(4):
                h = jnp.tanh(h @ p[f"w{i}"])
            return jnp.mean(h ** 2)

        def spmd(p, s, x):
            loss, grads = jax.value_and_grad(loss_fn)(p, x)
            updates, ns = tx.update(grads, s, p)
            import optax
            return optax.apply_updates(p, updates), ns, hvd.allreduce(loss)

        step = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(), P(hvd.HVD_AXES)),
            out_specs=(P(), P(), P())))
        x = jnp.ones((64, 512))
        params, state, loss = step(params, state, x)  # compile
        jax.block_until_ready(loss)
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            params, state, loss = step(params, state, x)
            jax.block_until_ready(loss)
            times.append(time.perf_counter() - t0)
        step_secs = float(np.median(times))

        reg = monitor.metrics()
        c = reg.counter("overhead.probe")
        g = reg.gauge("overhead.gauge")
        h = reg.histogram("overhead.hist")
        n = 3000
        t0 = time.perf_counter()
        for i in range(n):
            c.inc()
            g.set(i)
            h.observe(i)
        per_update_trio = (time.perf_counter() - t0) / n
        # generous per-step budget: 20 counter+gauge+histogram trios
        overhead = 20 * per_update_trio
        assert overhead < 0.01 * step_secs, (
            f"registry overhead {overhead * 1e6:.1f}us vs step "
            f"{step_secs * 1e6:.1f}us "
            f"({100 * overhead / step_secs:.2f}% >= 1%)")


# ---------------------------------------------------------------------------
# profile_window


class TestProfileWindow:
    def test_window_brackets_trace_and_timeline(self, tmp_path):
        path = str(tmp_path / "tl.json")
        logdir = str(tmp_path / "prof")
        hvd.start_timeline(path)
        f = jax.jit(lambda x: x * 2)
        try:
            with hvd.profile_window(3, logdir=logdir) as win:
                for _ in win.steps():
                    jax.block_until_ready(f(jnp.ones(4)))
        finally:
            hvd.stop_timeline()
        assert len(win.step_times_ms) == 3
        assert os.path.isdir(logdir)
        events = json.load(open(path))
        audit = audit_spans(events, prefix="PROFILE", require_spans=True)
        assert audit.count["PROFILE:STEP"] == 3
        assert audit.count["PROFILE:WINDOW"] == 1
        assert audit.instants.get("PROFILE:START") == 1
        assert audit.instants.get("PROFILE:STOP") == 1


# ---------------------------------------------------------------------------
# span_audit unit


class TestSpanAudit:
    def test_balanced_with_durations(self):
        events = [
            {"name": "A", "ph": "B", "tid": "t1", "ts": 0.0},
            {"name": "A", "ph": "E", "tid": "t1", "ts": 10.0},
            {"name": "B", "ph": "B", "tid": "t2", "ts": 5.0},
            {"name": "B", "ph": "E", "tid": "t2", "ts": 6.0},
            {"name": "N", "ph": "i", "tid": "t1", "ts": 7.0},
        ]
        audit = audit_spans(events)
        assert audit.balanced
        assert audit.total_spans == 2
        assert audit.duration_us == {"A": 10.0, "B": 1.0}
        assert audit.instants == {"N": 1}

    def test_unclosed_span_raises(self):
        events = [{"name": "A", "ph": "B", "tid": "t", "ts": 0.0}]
        with pytest.raises(SpanImbalanceError):
            audit_spans(events)
        audit = audit_spans(events, require_balanced=False)
        assert not audit.balanced and audit.open_depth == {"t": 1}

    def test_negative_depth_raises(self):
        events = [{"name": "A", "ph": "E", "tid": "t", "ts": 0.0}]
        with pytest.raises(SpanImbalanceError):
            audit_spans(events)

    def test_prefix_and_require_spans(self):
        events = [
            {"name": "X:1", "ph": "B", "tid": "t", "ts": 0.0},
            {"name": "X:1", "ph": "E", "tid": "t", "ts": 1.0},
        ]
        assert audit_spans(events, prefix="X").total_spans == 1
        with pytest.raises(SpanImbalanceError):
            audit_spans(events, prefix="Y", require_spans=True)

    def test_by_phase_grouping(self):
        events = [
            {"name": "X:a", "ph": "B", "tid": "t", "ts": 0.0},
            {"name": "X:a", "ph": "E", "tid": "t", "ts": 2.0},
            {"name": "X:b", "ph": "B", "tid": "t", "ts": 2.0},
            {"name": "X:b", "ph": "E", "tid": "t", "ts": 5.0},
        ]
        assert audit_spans(events).by_phase() == {"X": 5.0}


# ---------------------------------------------------------------------------
# perf-gate verdict snapshot (scripts/_perf_gate_check.py satellite)


def _load_gate_module():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "_perf_gate_check.py")
    spec = importlib.util.spec_from_file_location("_perf_gate_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestPerfGateSnapshot:
    def test_verdicts_written_as_metrics_jsonl(self, tmp_path,
                                               monkeypatch):
        mod = _load_gate_module()
        out = str(tmp_path / "gate.jsonl")
        monkeypatch.setenv("PERF_GATE_METRICS_JSONL", out)
        assert mod.gate(90.0, 100.0, 0.6, "serve goodput", leg="serve")
        assert not mod.gate(10.0, 100.0, 0.6, "serve throughput",
                            leg="serve")
        mod.write_verdict_snapshot()
        rec = json.loads(open(out).read().strip())
        assert rec["kind"] == "metrics"
        g = rec["gauges"]
        assert g["perf_gate.measured{leg=serve,what=serve_goodput}"] == 90.0
        assert g["perf_gate.pass{leg=serve,what=serve_goodput}"] == 1.0
        assert g["perf_gate.pass{leg=serve,what=serve_throughput}"] == 0.0
        assert rec["counters"][
            "perf_gate.regressions{leg=serve,what=serve_throughput}"] == 1.0
        assert rec["perf_gate"]["pass"] is False
