"""Unified observability layer tests (horovod_tpu/monitor/): registry
semantics, sinks, cross-rank aggregation, StallInspector (including the
chaos-stall acceptance scenario), host/device profile correlation, span
audit, the forensic layer (flight recorder ring/dumps/triggers,
straggler attribution with the chaos cross-wiring acceptance scenarios,
link health, postmortem join), and the <1% overhead budgets (registry,
and forensics armed) on the 8-device CPU mesh."""

import importlib.util
import json
import os
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import chaos, monitor
from horovod_tpu.common import counters
from horovod_tpu.monitor import (
    JsonlSink,
    MetricsRegistry,
    PrometheusSink,
    StallInspector,
    audit_spans,
)
from horovod_tpu.monitor.registry import (
    LOG2_BUCKET_BOUNDS,
    NUM_BUCKETS,
    _bucket_index,
)
from horovod_tpu.monitor.span_audit import SpanImbalanceError


# ---------------------------------------------------------------------------
# Registry semantics


class TestRegistry:
    def test_counter_monotone(self):
        r = MetricsRegistry(enabled=True)
        c = r.counter("a.b")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        assert c.value == 3.5

    def test_gauge(self):
        r = MetricsRegistry(enabled=True)
        g = r.gauge("q", role="x")
        g.set(7)
        g.add(-2)
        assert g.value == 5.0

    def test_histogram_log2_buckets(self):
        r = MetricsRegistry(enabled=True)
        h = r.histogram("lat")
        assert _bucket_index(0.5) == 0       # <= 2^0
        assert _bucket_index(1.0) == 0
        assert _bucket_index(2.0) == 1
        assert _bucket_index(3.0) == 2       # 2 < 3 <= 4
        assert _bucket_index(1024.0) == 10
        assert _bucket_index(2.0 ** 40) == NUM_BUCKETS - 1  # +Inf bucket
        for v in (0.5, 3.0, 1024.0):
            h.observe(v)
        assert h.count == 3
        assert h.sum == pytest.approx(1027.5)
        assert h.counts[0] == 1 and h.counts[2] == 1 and h.counts[10] == 1
        assert LOG2_BUCKET_BOUNDS[-1] == float("inf")

    def test_labels_are_identity(self):
        r = MetricsRegistry(enabled=True)
        a = r.counter("c", hop="ici")
        b = r.counter("c", hop="dcn")
        assert a is not b
        assert a is r.counter("c", hop="ici")
        assert a.key == "c{hop=ici}"

    def test_kind_conflict_raises(self):
        r = MetricsRegistry(enabled=True)
        r.counter("x")
        with pytest.raises(ValueError):
            r.gauge("x")

    def test_disabled_registry_noops(self):
        r = MetricsRegistry(enabled=False)
        c = r.counter("n")
        c.inc(5)
        r.histogram("h").observe(1)
        assert c.value == 0.0
        assert r.histogram("h").count == 0

    def test_enabled_is_the_default(self):
        # The acceptance contract: the registry defaults ON.
        assert monitor.metrics_enabled()

    def test_snapshot_and_prefix_filter(self):
        r = MetricsRegistry(enabled=True)
        r.counter("serve.steps").inc(3)
        r.gauge("comm.depth").set(2)
        r.histogram("serve.lat").observe(4)
        snap = r.snapshot()
        assert snap["counters"]["serve.steps"] == 3.0
        assert snap["gauges"]["comm.depth"] == 2.0
        assert snap["histograms"]["serve.lat"]["count"] == 1
        only_serve = r.snapshot(prefix="serve.")
        assert "comm.depth" not in only_serve["gauges"]
        assert "serve.steps" in only_serve["counters"]


# ---------------------------------------------------------------------------
# Sinks


class TestSinks:
    def test_jsonl_sink_roundtrip(self, tmp_path):
        r = MetricsRegistry(enabled=True)
        r.counter("k").inc(2)
        path = str(tmp_path / "m.jsonl")
        sink = JsonlSink(path)
        sink.write(r.snapshot())
        r.counter("k").inc()
        sink.write(r.snapshot())
        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 2
        assert lines[0]["counters"]["k"] == 2.0
        assert lines[1]["counters"]["k"] == 3.0
        assert lines[1]["kind"] == "metrics"

    def test_prometheus_endpoint(self):
        r = MetricsRegistry(enabled=True)
        r.counter("comm.bytes", hop="ici").inc(128)
        r.gauge("serve.queue_depth").set(4)
        h = r.histogram("lat.ms")
        h.observe(3)
        h.observe(100)
        sink = PrometheusSink(r, port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{sink.port}/metrics",
                timeout=5).read().decode()
        finally:
            sink.close()
        assert 'horovod_comm_bytes{hop="ici"} 128' in body
        assert "# TYPE horovod_comm_bytes counter" in body
        assert "horovod_serve_queue_depth 4" in body
        # cumulative buckets: the le="4" bucket holds the 3-observation,
        # the +Inf bucket holds both
        assert 'horovod_lat_ms_bucket{le="4"} 1' in body
        assert 'horovod_lat_ms_bucket{le="+Inf"} 2' in body
        assert "horovod_lat_ms_count 2" in body

    def test_timeline_counter_mirror(self, tmp_path):
        path = str(tmp_path / "tl.json")
        hvd.start_timeline(path)
        try:
            monitor.metrics().counter("mirror.test").inc(5)
            monitor.flush()
        finally:
            hvd.stop_timeline()
        events = json.load(open(path))
        mirrors = [e for e in events if e["ph"] == "C"
                   and e["name"] == "METRIC:mirror.test"]
        assert mirrors and mirrors[-1]["args"]["value"] >= 5.0


# ---------------------------------------------------------------------------
# Wire-stats + collective instrumentation


def _traced_allreduce():
    mesh = hvd.mesh()
    f = jax.jit(hvd.shard_map(
        lambda x: hvd.allreduce(x, op=hvd.Sum),
        mesh=mesh, in_specs=P(hvd.HVD_AXES), out_specs=P()))
    with hvd.record_wire_stats() as ws:
        f.lower(jnp.ones((8, 4)))
    return ws


class TestWireInstrumentation:
    def test_traced_bytes_feed_registry(self):
        before = monitor.metrics().counter("comm.bytes", hop="ici").value
        traces_before = monitor.metrics().counter("comm.traces").value
        ws = _traced_allreduce()
        assert ws.ici_bytes > 0
        after = monitor.metrics().counter("comm.bytes", hop="ici").value
        assert after - before == pytest.approx(ws.ici_bytes)
        assert monitor.metrics().counter("comm.traces").value == \
            traces_before + 1
        # the published gauges describe the last traced program
        assert monitor.metrics().gauge("comm.wire.ici_bytes").value == \
            pytest.approx(ws.ici_bytes)

    def test_registry_counts_without_recorder(self):
        # _acct_enabled(): the registry accounts trace-time bytes even
        # with no record_wire_stats context installed.
        before = monitor.metrics().counter("comm.bytes", hop="ici").value
        mesh = hvd.mesh()
        jax.jit(hvd.shard_map(
            lambda x: hvd.allreduce(x, op=hvd.Sum),
            mesh=mesh, in_specs=P(hvd.HVD_AXES), out_specs=P()
        )).lower(jnp.ones((8, 2)))
        assert monitor.metrics().counter(
            "comm.bytes", hop="ici").value > before

    def test_eager_latency_histogram(self):
        h = monitor.metrics().histogram("comm.eager.latency_ms",
                                        kind="allreduce")
        before = h.count
        hvd.allreduce(jnp.ones(3), name="monitor.eager.probe")
        assert h.count == before + 1


# ---------------------------------------------------------------------------
# Cross-rank aggregation


class TestAggregation:
    def test_world_of_one_is_identity(self):
        monitor.metrics().counter("agg.probe").inc(4)
        agg = monitor.aggregate()
        assert agg["world"] == 1
        assert agg["counters"]["agg.probe"] == \
            monitor.metrics().counter("agg.probe").value

    def test_flat_layout_roundtrip_shapes(self):
        r = MetricsRegistry(enabled=True)
        r.counter("c1").inc(1)
        r.gauge("g1").set(2)
        r.histogram("h1").observe(3)
        snap = r.snapshot()
        keys, vals = r._flat_layout(snap)
        assert len(keys) == 3
        # histogram contributes counts + sum + count
        assert len(vals) == 2 + NUM_BUCKETS + 2

    def test_aggregation_survives_elastic_resize(self):
        """Counters persist across the shutdown→init cycle (an elastic
        world transition) and aggregation still works on the new world."""
        marker = monitor.metrics().counter("agg.resize_probe")
        marker.inc(11)
        inc_before = monitor.metrics().counter(
            "elastic.incarnations").value
        hvd.shutdown()
        try:
            hvd.init(mesh_shape=(2, 4))
            assert monitor.metrics().counter(
                "agg.resize_probe").value == 11.0
            agg1 = monitor.aggregate()
            assert agg1["counters"]["agg.resize_probe"] == 11.0
            hvd.shutdown()
            hvd.init(mesh_shape=(1, 8))  # resized world
            marker.inc()
            agg2 = monitor.aggregate()
            assert agg2["counters"]["agg.resize_probe"] == 12.0
            assert monitor.metrics().counter(
                "elastic.incarnations").value >= inc_before + 2
        finally:
            hvd.shutdown()
            hvd.init()


# ---------------------------------------------------------------------------
# StallInspector


class TestStallInspector:
    def test_warning_structure_and_api(self, tmp_path):
        path = str(tmp_path / "tl.json")
        hvd.start_timeline(path)
        insp = StallInspector(warning_secs=0.05)
        try:
            insp.record_start("stalled.tensor", kind="allreduce", rank=0)
            time.sleep(0.08)
            assert [s["name"] for s in insp.stalled()] == ["stalled.tensor"]
            fired = insp.check()
            assert len(fired) == 1
            w = fired[0]
            assert "waiting for remainder of ranks" in w["message"]
            assert "Stalled tensor: stalled.tensor" in w["message"]
            assert "ready ranks: 0" in w["message"]
            assert w["rank"] == 0
            # warned once, not per check
            assert insp.check() == []
            insp.record_done("stalled.tensor")
            assert insp.stalled() == []
        finally:
            hvd.stop_timeline()
        events = json.load(open(path))
        stall_evs = [e for e in events
                     if str(e["name"]).startswith("STALL:")]
        assert stall_evs and stall_evs[0]["ph"] == "i"
        assert stall_evs[0]["args"]["ready_ranks"] == [0]

    def test_watchdog_thread_fires(self):
        insp = StallInspector(warning_secs=0.05, check_interval=0.02)
        insp.start()
        try:
            insp.record_start("bg.tensor")
            time.sleep(0.25)
            assert insp.warnings()
        finally:
            insp.record_done("bg.tensor")
            insp.stop()

    def test_chaos_stall_produces_rank_attributed_warning(
            self, tmp_path, monkeypatch):
        """Acceptance: a deliberately stalled eager collective (chaos
        ``stall`` action) produces a rank-attributed StallInspector
        warning and a STALL:* timeline instant within stall_check_time."""
        from horovod_tpu.monitor import stall as stall_mod

        monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "0.2")
        hvd.shutdown()
        counters.reset_all()
        try:
            hvd.init()
            insp = stall_mod.stall_inspector()
            assert insp.warning_secs == 0.2  # config reached the watchdog
            n_before = len(insp.warnings())
            path = str(tmp_path / "tl.json")
            hvd.start_timeline(path)
            chaos.configure(chaos.FaultPlan().add(
                "collective.eager", action="stall", secs=1.0))
            warn_count = monitor.metrics().counter(
                "stall.warnings", kind="allreduce").value
            try:
                hvd.allreduce(jnp.ones(2), name="stalled.probe")
            finally:
                chaos.configure(None)
                hvd.stop_timeline()
            new = insp.warnings()[n_before:]
            assert new, "no stall warning fired during the injected stall"
            w = new[-1]
            assert w["name"] == "stalled.probe"
            assert w["rank"] == 0 and 0 in w["ready_ranks"]
            # fired while the op was still stalled — i.e. within
            # stall_check_time of crossing the threshold, not after the
            # 1 s injected stall completed
            assert w["elapsed_secs"] < 0.9
            assert monitor.metrics().counter(
                "stall.warnings", kind="allreduce").value > warn_count
            events = json.load(open(path))
            stall_evs = [e for e in events
                         if e["name"] == "STALL:stalled.probe"]
            assert stall_evs and stall_evs[0]["ph"] == "i"
            assert stall_evs[0]["args"]["rank"] == 0
            # after completion the op is no longer in flight
            assert not any(s["name"] == "stalled.probe"
                           for s in hvd.stalled_tensors())
        finally:
            chaos.reset()
            monkeypatch.delenv("HOROVOD_STALL_CHECK_TIME_SECONDS",
                               raising=False)
            hvd.shutdown()
            hvd.init()

    def test_serve_request_tracking_clears(self):
        from horovod_tpu.models import gpt_tiny
        from horovod_tpu.models.gpt import GPT
        from horovod_tpu.serve import PageConfig
        from horovod_tpu.serve.engine import GenerationEngine, VirtualClock
        from horovod_tpu.serve.scheduler import Request

        cfg = gpt_tiny(num_heads=2, num_layers=1, d_model=16,
                       vocab_size=32)
        params = GPT(cfg).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 4), jnp.int32))["params"]
        pc = PageConfig(num_pages=9, page_size=4, max_slots=2,
                        pages_per_slot=4, num_layers=cfg.num_layers,
                        num_heads=cfg.num_heads,
                        head_dim=cfg.d_model // cfg.num_heads)
        eng = GenerationEngine(cfg, params, pc, eos_id=1)
        steps_before = monitor.metrics().counter("serve.steps").value
        eng.run([Request(prompt=[5, 6, 7], max_new_tokens=3)],
                clock=VirtualClock())
        assert monitor.metrics().counter("serve.steps").value > steps_before
        # every tracked request was untracked on eviction
        from horovod_tpu.monitor.stall import stall_inspector

        assert not any(n.startswith("serve.req")
                       for n in stall_inspector().in_flight())


# ---------------------------------------------------------------------------
# Counters mirror + chaos monotonicity


class TestCounterMirror:
    def test_fault_counters_mirror_into_registry(self):
        before = monitor.metrics().counter("mirror.fault.probe").value
        counters.increment("mirror.fault.probe")
        assert monitor.metrics().counter(
            "mirror.fault.probe").value == before + 1

    def test_counters_stay_monotone_under_chaos(self):
        """With chaos faults active every registry counter must stay
        monotone — sampled across a run of dropping/succeeding eager
        collectives (the acceptance invariant for chaotic runs)."""
        chaos.configure(chaos.FaultPlan().add(
            "collective.eager", action="drop", every=2))
        try:
            reg = monitor.metrics()
            last = {}
            for i in range(8):
                try:
                    hvd.allreduce(jnp.ones(2), name=f"monotone.{i}")
                except Exception:
                    pass  # injected drop
                snap = reg.snapshot()
                for k, v in snap["counters"].items():
                    assert v >= last.get(k, 0.0), \
                        f"counter {k} decreased: {last.get(k)} -> {v}"
                last.update(snap["counters"])
            assert last.get("chaos.drop", 0) >= 1
        finally:
            chaos.reset()


# ---------------------------------------------------------------------------
# Overhead budget (acceptance: <1% of the 8-device CPU mesh step)


class TestOverhead:
    def test_registry_overhead_under_one_percent_of_step(self):
        """The per-step registry work the framework does (a bounded
        handful of counter/gauge/histogram updates — everything else is
        trace-time) must cost <1% of a real 8-device-mesh step."""
        mesh = hvd.mesh()
        tx = hvd.DistributedOptimizer(__import__("optax").sgd(0.01))
        # A bench-representative step (4-layer 512-wide MLP, batch 8/rank)
        # rather than a toy matmul: the budget is a FRACTION of step time,
        # so the denominator must look like a real training step.
        params = {f"w{i}": jnp.full((512, 512), 0.01) for i in range(4)}
        state = tx.init(params)

        def loss_fn(p, x):
            h = x
            for i in range(4):
                h = jnp.tanh(h @ p[f"w{i}"])
            return jnp.mean(h ** 2)

        def spmd(p, s, x):
            loss, grads = jax.value_and_grad(loss_fn)(p, x)
            updates, ns = tx.update(grads, s, p)
            import optax
            return optax.apply_updates(p, updates), ns, hvd.allreduce(loss)

        step = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(), P(hvd.HVD_AXES)),
            out_specs=(P(), P(), P())))
        x = jnp.ones((64, 512))
        params, state, loss = step(params, state, x)  # compile
        jax.block_until_ready(loss)
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            params, state, loss = step(params, state, x)
            jax.block_until_ready(loss)
            times.append(time.perf_counter() - t0)
        step_secs = float(np.median(times))

        reg = monitor.metrics()
        c = reg.counter("overhead.probe")
        g = reg.gauge("overhead.gauge")
        h = reg.histogram("overhead.hist")
        n = 3000
        t0 = time.perf_counter()
        for i in range(n):
            c.inc()
            g.set(i)
            h.observe(i)
        per_update_trio = (time.perf_counter() - t0) / n
        # generous per-step budget: 20 counter+gauge+histogram trios
        overhead = 20 * per_update_trio
        assert overhead < 0.01 * step_secs, (
            f"registry overhead {overhead * 1e6:.1f}us vs step "
            f"{step_secs * 1e6:.1f}us "
            f"({100 * overhead / step_secs:.2f}% >= 1%)")


# ---------------------------------------------------------------------------
# profile_window


class TestProfileWindow:
    def test_window_brackets_trace_and_timeline(self, tmp_path):
        path = str(tmp_path / "tl.json")
        logdir = str(tmp_path / "prof")
        hvd.start_timeline(path)
        f = jax.jit(lambda x: x * 2)
        try:
            with hvd.profile_window(3, logdir=logdir) as win:
                for _ in win.steps():
                    jax.block_until_ready(f(jnp.ones(4)))
        finally:
            hvd.stop_timeline()
        assert len(win.step_times_ms) == 3
        assert os.path.isdir(logdir)
        events = json.load(open(path))
        audit = audit_spans(events, prefix="PROFILE", require_spans=True)
        assert audit.count["PROFILE:STEP"] == 3
        assert audit.count["PROFILE:WINDOW"] == 1
        assert audit.instants.get("PROFILE:START") == 1
        assert audit.instants.get("PROFILE:STOP") == 1


# ---------------------------------------------------------------------------
# span_audit unit


class TestSpanAudit:
    def test_balanced_with_durations(self):
        events = [
            {"name": "A", "ph": "B", "tid": "t1", "ts": 0.0},
            {"name": "A", "ph": "E", "tid": "t1", "ts": 10.0},
            {"name": "B", "ph": "B", "tid": "t2", "ts": 5.0},
            {"name": "B", "ph": "E", "tid": "t2", "ts": 6.0},
            {"name": "N", "ph": "i", "tid": "t1", "ts": 7.0},
        ]
        audit = audit_spans(events)
        assert audit.balanced
        assert audit.total_spans == 2
        assert audit.duration_us == {"A": 10.0, "B": 1.0}
        assert audit.instants == {"N": 1}

    def test_unclosed_span_raises(self):
        events = [{"name": "A", "ph": "B", "tid": "t", "ts": 0.0}]
        with pytest.raises(SpanImbalanceError):
            audit_spans(events)
        audit = audit_spans(events, require_balanced=False)
        assert not audit.balanced and audit.open_depth == {"t": 1}

    def test_negative_depth_raises(self):
        events = [{"name": "A", "ph": "E", "tid": "t", "ts": 0.0}]
        with pytest.raises(SpanImbalanceError):
            audit_spans(events)

    def test_prefix_and_require_spans(self):
        events = [
            {"name": "X:1", "ph": "B", "tid": "t", "ts": 0.0},
            {"name": "X:1", "ph": "E", "tid": "t", "ts": 1.0},
        ]
        assert audit_spans(events, prefix="X").total_spans == 1
        with pytest.raises(SpanImbalanceError):
            audit_spans(events, prefix="Y", require_spans=True)

    def test_by_phase_grouping(self):
        events = [
            {"name": "X:a", "ph": "B", "tid": "t", "ts": 0.0},
            {"name": "X:a", "ph": "E", "tid": "t", "ts": 2.0},
            {"name": "X:b", "ph": "B", "tid": "t", "ts": 2.0},
            {"name": "X:b", "ph": "E", "tid": "t", "ts": 5.0},
        ]
        assert audit_spans(events).by_phase() == {"X": 5.0}


# ---------------------------------------------------------------------------
# perf-gate verdict snapshot (scripts/_perf_gate_check.py satellite)


def _load_gate_module():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "_perf_gate_check.py")
    spec = importlib.util.spec_from_file_location("_perf_gate_check", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestPerfGateSnapshot:
    def test_verdicts_written_as_metrics_jsonl(self, tmp_path,
                                               monkeypatch):
        mod = _load_gate_module()
        out = str(tmp_path / "gate.jsonl")
        monkeypatch.setenv("PERF_GATE_METRICS_JSONL", out)
        assert mod.gate(90.0, 100.0, 0.6, "serve goodput", leg="serve")
        assert not mod.gate(10.0, 100.0, 0.6, "serve throughput",
                            leg="serve")
        mod.write_verdict_snapshot()
        rec = json.loads(open(out).read().strip())
        assert rec["kind"] == "metrics"
        g = rec["gauges"]
        assert g["perf_gate.measured{leg=serve,what=serve_goodput}"] == 90.0
        assert g["perf_gate.pass{leg=serve,what=serve_goodput}"] == 1.0
        assert g["perf_gate.pass{leg=serve,what=serve_throughput}"] == 0.0
        assert rec["counters"][
            "perf_gate.regressions{leg=serve,what=serve_throughput}"] == 1.0
        assert rec["perf_gate"]["pass"] is False


class TestPerfGateTrainBaselineFallback:
    """Empty-trajectory train legs gate against (or self-seed) the
    committed BENCH_train_baseline.json instead of silently passing."""

    def _mod(self, tmp_path):
        mod = _load_gate_module()
        mod.TRAIN_BASELINE = str(tmp_path / "BENCH_train_baseline.json")
        return mod

    def test_first_run_seeds_then_gates(self, tmp_path):
        mod = self._mod(tmp_path)
        rec = {"metric": "img_per_sec", "platform": "testplat",
               "value": 100.0}
        assert mod._train_baseline_gate(rec, "train", 0.6, False) == 0
        seeded = json.loads(open(mod.TRAIN_BASELINE).read())
        assert seeded["img_per_sec|testplat"]["value"] == 100.0
        # Within tolerance of the seeded baseline: pass.
        ok = dict(rec, value=70.0)
        assert mod._train_baseline_gate(ok, "train", 0.6, False) == 0
        # A regression below the floor: fail.
        bad = dict(rec, value=10.0)
        assert mod._train_baseline_gate(bad, "train", 0.6, False) == 1
        # PERF_GATE_UPDATE re-seeds instead of gating.
        assert mod._train_baseline_gate(bad, "train", 0.6, True) == 0
        seeded = json.loads(open(mod.TRAIN_BASELINE).read())
        assert seeded["img_per_sec|testplat"]["value"] == 10.0

    def test_keys_are_metric_and_platform_scoped(self, tmp_path):
        mod = self._mod(tmp_path)
        a = {"metric": "img_per_sec", "platform": "cpu", "value": 50.0}
        b = {"metric": "img_per_sec", "platform": "tpu", "value": 9.0}
        assert mod._train_baseline_gate(a, "train", 0.6, False) == 0
        # A different platform seeds its own key; no cross-gating.
        assert mod._train_baseline_gate(b, "train", 0.6, False) == 0
        seeded = json.loads(open(mod.TRAIN_BASELINE).read())
        assert set(seeded) == {"img_per_sec|cpu", "img_per_sec|tpu"}

    def test_non_numeric_value_is_a_usage_error(self, tmp_path):
        mod = self._mod(tmp_path)
        rec = {"metric": "img_per_sec", "platform": "cpu"}
        assert mod._train_baseline_gate(rec, "train", 0.6, False) == 2

    def test_corrupt_baseline_reseeds(self, tmp_path):
        mod = self._mod(tmp_path)
        with open(mod.TRAIN_BASELINE, "w") as f:
            f.write("{not json")
        rec = {"metric": "img_per_sec", "platform": "cpu", "value": 5.0}
        assert mod._train_baseline_gate(rec, "train", 0.6, False) == 0
        seeded = json.loads(open(mod.TRAIN_BASELINE).read())
        assert seeded["img_per_sec|cpu"]["value"] == 5.0


# ---------------------------------------------------------------------------
# Flight recorder (monitor/flight.py)


from horovod_tpu.monitor.flight import FlightRecorder  # noqa: E402
from horovod_tpu.monitor.span_audit import (  # noqa: E402
    KNOWN_PREFIXES,
    UnknownSpanPrefixError,
    event_prefix,
)
from horovod_tpu.monitor.straggler import StragglerDetector  # noqa: E402


def _load_postmortem():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "postmortem.py")
    spec = importlib.util.spec_from_file_location("_postmortem", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestFlightRecorder:
    def test_ring_is_bounded_and_ordered(self):
        fr = FlightRecorder(capacity=8, snapshot_every=0)
        for i in range(20):
            fr.record(f"FLIGHT:E{i}", tid="t")
        evs = fr.events()
        assert len(evs) == 8
        assert [e["name"] for e in evs] == \
            [f"FLIGHT:E{i}" for i in range(12, 20)]
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs) and seqs[-1] == 19
        assert all("wall" in e for e in evs)

    def test_capacity_zero_disables(self, tmp_path):
        fr = FlightRecorder(capacity=0)
        fr.record("FLIGHT:X")
        assert fr.events() == []
        assert fr.dump(directory=str(tmp_path)) is None

    def test_periodic_registry_snapshots(self):
        monitor.metrics().counter("flight.snap_probe").inc(3)
        fr = FlightRecorder(capacity=64, snapshot_every=4)
        for i in range(10):
            fr.record(f"FLIGHT:S{i}")
        snaps = [e for e in fr.events() if e["name"] == "FLIGHT:SNAPSHOT"]
        assert len(snaps) == 2  # after events 4 and 8
        assert snaps[0]["args"]["counters"]["flight.snap_probe"] >= 3.0

    def test_dump_atomic_crc_and_contents(self, tmp_path):
        import zlib

        fr = FlightRecorder(capacity=32, snapshot_every=0)
        fr.record("FLIGHT:A", args={"k": 1})
        fr.mark_step(7, {"compute": 12.5})
        path = fr.dump("unit", directory=str(tmp_path),
                       extra={"note": "x"})
        assert path and os.path.exists(path)
        assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]
        d = json.load(open(path))
        assert d["kind"] == "flight_record" and d["reason"] == "unit"
        assert d["extra"] == {"note": "x"}
        assert d["identity"]["pid"] == os.getpid()
        names = [e["name"] for e in d["events"]]
        assert names == ["FLIGHT:A", "FLIGHT:STEP"]
        assert d["events"][1]["args"]["step"] == 7
        payload = json.dumps(d["events"], sort_keys=True).encode()
        want = f"crc32:{zlib.crc32(payload) & 0xFFFFFFFF:08x}"
        assert d["events_crc32"] == want
        assert "registry" in d and "in_flight" in d

    def test_dump_without_destination_is_noop(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_FLIGHT_RECORDER_DIR", raising=False)
        fr = FlightRecorder(capacity=8)
        fr.record("FLIGHT:Y")
        assert fr.dump("nowhere") is None

    def test_timeline_events_are_tapped(self, tmp_path):
        from horovod_tpu.monitor import flight as flight_mod

        fr = monitor.flight_recorder()
        hvd.start_timeline(str(tmp_path / "tl.json"))
        try:
            hvd.mesh()  # ensure initialized
            from horovod_tpu.common import basics

            basics._state.timeline.instant("FAULT:tap.probe",
                                           tid="faults")
        finally:
            hvd.stop_timeline()
        assert any(e["name"] == "FAULT:tap.probe"
                   for e in fr.events())
        assert flight_mod.recorder() is fr

    def test_eager_collective_and_stall_reach_ring(self):
        fr = monitor.flight_recorder()
        hvd.allreduce(jnp.ones(2), name="flight.eager.probe")
        colls = [e for e in fr.events()
                 if e["name"] == "FLIGHT:COLLECTIVE"
                 and e["args"]["name"] == "flight.eager.probe"]
        assert colls and colls[-1]["args"]["kind"] == "allreduce"
        # a stall instant lands in the ring even with no timeline
        insp = StallInspector(warning_secs=0.01)
        insp.record_start("flight.stall.probe", rank=0)
        time.sleep(0.03)
        insp.check()
        assert any(e["name"] == "STALL:flight.stall.probe"
                   for e in fr.events())

    def test_excepthook_dump_in_subprocess(self, tmp_path):
        import subprocess
        import sys as _sys

        code = (
            "import os\n"
            "import horovod_tpu as hvd\n"
            "hvd.init()\n"
            "import jax.numpy as jnp\n"
            "hvd.allreduce(jnp.ones(2), name='pre.crash')\n"
            "raise RuntimeError('forensic boom')\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   HOROVOD_FLIGHT_RECORDER_DIR=str(tmp_path))
        env.pop("HOROVOD_TIMELINE", None)
        p = subprocess.run([_sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert p.returncode != 0
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_") and f.endswith(".json")]
        assert dumps, (p.stdout, p.stderr)
        d = json.load(open(os.path.join(tmp_path, dumps[0])))
        assert d["reason"] == "exception"
        assert d["extra"]["exc_type"] == "RuntimeError"
        assert "forensic boom" in d["extra"]["exc"]
        assert any(e["name"] == "FLIGHT:COLLECTIVE"
                   for e in d["events"])

    def test_sigterm_dump_in_subprocess(self, tmp_path):
        import signal
        import subprocess
        import sys as _sys

        code = (
            "import os, signal, time\n"
            "import horovod_tpu as hvd\n"
            "hvd.init()\n"
            "os.kill(os.getpid(), signal.SIGTERM)\n"
            "time.sleep(10)\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   HOROVOD_FLIGHT_RECORDER_DIR=str(tmp_path))
        p = subprocess.run([_sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        # delivery semantics preserved: the process still dies of SIGTERM
        assert p.returncode == -signal.SIGTERM or p.returncode == 143
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_") and f.endswith(".json")]
        assert dumps, (p.stdout, p.stderr)
        d = json.load(open(os.path.join(tmp_path, dumps[0])))
        assert d["reason"] == "sigterm"

    def test_explicit_dump_api(self, tmp_path):
        path = str(tmp_path / "explicit.json")
        got = hvd.dump_flight_record(path=path)
        assert got == path
        d = json.load(open(path))
        assert d["reason"] == "explicit"
        assert d["identity"]["world"] >= 1


# ---------------------------------------------------------------------------
# Straggler attribution (monitor/straggler.py)


def _rank_farm(world=4, registry=None, **kw):
    """One detector per emulated rank over ONE shared registry — each
    writes only its own rank's slots, exactly what the fused-allreduce
    SUM reconstructs in a real multi-process world."""
    reg = registry or MetricsRegistry(enabled=True)
    dets = [StragglerDetector(reg, world=world, rank=r, **kw)
            for r in range(world)]
    return reg, dets


class TestStragglerDetection:
    def test_clean_run_zero_false_positives(self):
        reg, dets = _rank_farm(world=4)
        for step in range(10):
            for r, det in enumerate(dets):
                det.record_phase("compute", 100.0 + 0.3 * r)
                det.record_phase("wire.dcn", 10.0 + 0.1 * step)
                det.end_step(step)
            assert dets[0].detect(snapshot=reg.snapshot()) == []
        assert not any(k.startswith("straggler.detected")
                       for k in reg.snapshot()["counters"])

    def test_pp_bubble_clean_run_zero_false_positives(self):
        """Rank-uniform zb idle ticks (the schedule table is geometry-
        determined) with uniform fill credit must never flag: the
        pp_bubble phase is identical across ranks."""
        from horovod_tpu.monitor import straggler as straggler_mod
        reg, dets = _rank_farm(world=4)
        # zb1 on (S=2, M=8): 2 idle ticks of 50 total, half of them
        # filled by ZeRO-3 flights on every rank.
        for step in range(10):
            for r, det in enumerate(dets):
                det.record_phase("compute", 100.0 + 0.3 * r)
                ms = straggler_mod.record_pp_bubble(
                    idle_ticks=2, ticks=50, step_ms=100.0,
                    filled_ticks=1, detector=det)
                assert ms == pytest.approx(100.0 * 1 / 50)
                det.end_step(step)
            assert dets[0].detect(snapshot=reg.snapshot()) == []
        assert not any(k.startswith("straggler.detected")
                       for k in reg.snapshot()["counters"])

    def test_pp_bubble_fill_credit_math(self):
        from horovod_tpu.monitor import straggler as straggler_mod
        reg, dets = _rank_farm(world=4)
        det = dets[0]
        # fully filled bubble charges nothing
        assert straggler_mod.record_pp_bubble(
            4, 40, 200.0, filled_ticks=4, detector=det) == 0.0
        # credit is capped at the measured idle ticks
        assert straggler_mod.record_pp_bubble(
            4, 40, 200.0, filled_ticks=99, detector=det) == 0.0
        # no credit charges the full idle fraction
        assert straggler_mod.record_pp_bubble(
            4, 40, 200.0, detector=det) == pytest.approx(20.0)
        # degenerate inputs clamp instead of raising
        assert straggler_mod.record_pp_bubble(
            -1, 0, 200.0, filled_ticks=-5, detector=det) == 0.0

    def test_pp_bubble_starved_rank_attributed(self):
        """One rank whose flights starve (no fill credit) surfaces as a
        pp_bubble outlier through the ordinary median/MAD gate."""
        from horovod_tpu.monitor import straggler as straggler_mod
        reg, dets = _rank_farm(world=4)
        for r, det in enumerate(dets):
            det.record_phase("compute", 100.0)
            straggler_mod.record_pp_bubble(
                idle_ticks=8, ticks=40, step_ms=100.0,
                filled_ticks=(0 if r == 2 else 8), detector=det)
            det.end_step(0)
        found = dets[0].detect(snapshot=reg.snapshot())
        assert [(d["rank"], d["phase"]) for d in found] == \
            [(2, "pp_bubble")]

    def test_delayed_rank_detected_and_attributed(self):
        reg, dets = _rank_farm(world=4)
        flagged_at = None
        for step in range(3):
            for r, det in enumerate(dets):
                det.record_phase("compute", 100.0)
                det.record_phase(
                    "wire.dcn", 10.0 + (80.0 if r == 2 else 0.0))
                det.end_step(step)
            found = dets[0].detect(snapshot=reg.snapshot())
            if found and flagged_at is None:
                flagged_at = step
                assert [(d["rank"], d["phase"]) for d in found] == \
                    [(2, "wire.dcn")]
        # bounded step count: attributed on the very first detect pass
        assert flagged_at == 0
        snap = reg.snapshot()
        assert snap["counters"][
            "straggler.detected{phase=wire.dcn,rank=2}"] >= 1
        assert snap["gauges"]["step.skew_ms{phase=wire.dcn}"] == \
            pytest.approx(80.0)
        # history rides the flight dump
        assert any(d["rank"] == 2 for d in dets[0].history())

    def test_fewer_than_three_ranks_never_flags(self):
        reg, dets = _rank_farm(world=2)
        for r, det in enumerate(dets):
            det.record_phase("compute", 100.0 + 500.0 * r)
            det.end_step(0)
        assert dets[0].detect(snapshot=reg.snapshot()) == []
        # the skew gauge still publishes for operators
        assert reg.snapshot()["gauges"][
            "step.skew_ms{phase=compute}"] > 0

    def test_detection_emits_straggler_instant(self, tmp_path):
        path = str(tmp_path / "tl.json")
        reg, dets = _rank_farm(world=3)
        for r, det in enumerate(dets):
            det.record_phase("ckpt", 5.0 + (200.0 if r == 1 else 0.0))
            det.end_step(0)
        hvd.start_timeline(path)
        try:
            found = dets[0].detect(snapshot=reg.snapshot())
        finally:
            hvd.stop_timeline()
        assert found and found[0]["rank"] == 1
        events = json.load(open(path))
        evs = [e for e in events if e["name"] == "STRAGGLER:CKPT"]
        assert evs and evs[0]["ph"] == "i"
        assert evs[0]["args"]["rank"] == 1
        assert event_prefix(evs[0]["name"]) in KNOWN_PREFIXES

    def test_phase_gauges_ride_registry_aggregation_schema(self):
        """Every rank pre-creates the full (phase, rank) matrix, so the
        flat aggregation layout is identical across ranks (the
        schema-digest contract of MetricsRegistry.aggregate)."""
        layouts = []
        for r in range(3):
            reg = MetricsRegistry(enabled=True)
            det = StragglerDetector(reg, world=3, rank=r)
            det.record_phase("compute", 10.0 * (r + 1))
            det.end_step(0)
            keys, _ = reg._flat_layout(reg.snapshot())
            layouts.append(keys)
        assert layouts[0] == layouts[1] == layouts[2]

    def test_chaos_delay_attributed_through_real_eager_path(
            self, monkeypatch):
        """Acceptance (chaos cross-wiring): a seeded ``delay`` fault on
        one rank's eager collectives is detected and attributed to that
        (rank, wire.dcn) within a bounded step count, with zero false
        positives on the clean control run."""
        from horovod_tpu.monitor import straggler as straggler_mod

        def drive(inject_rank, reg, dets, steps=2):
            found_all = []
            for step in range(steps):
                for r, det in enumerate(dets):
                    # route the global-path record_phase of
                    # _eager_instrumented to this emulated rank
                    monkeypatch.setattr(straggler_mod, "_global", det)
                    if r == inject_rank:
                        chaos.configure(chaos.FaultPlan(seed=9).add(
                            "collective.eager", "delay", secs=0.12))
                    try:
                        hvd.allreduce(jnp.ones(2),
                                      name=f"cw.{step}.{r}")
                    finally:
                        chaos.configure(None)
                    det.record_phase("compute", 50.0)
                    det.end_step(step)
                found_all += dets[0].detect(snapshot=reg.snapshot())
            return found_all

        try:
            reg, dets = _rank_farm(world=4)
            found = drive(2, reg, dets)
            assert found, "injected delay was never detected"
            assert {(d["rank"], d["phase"]) for d in found} == \
                {(2, "wire.dcn")}
            # clean control: no injection, nothing may fire
            reg2, dets2 = _rank_farm(world=4)
            assert drive(None, reg2, dets2) == []
        finally:
            chaos.reset()
            straggler_mod._reset_for_tests()


class TestLinkHealth:
    def test_degraded_link_flagged_and_recommends_recalibration(
            self, caplog):
        import logging as _logging

        reg = MetricsRegistry(enabled=True)
        det = StragglerDetector(reg, world=1, rank=0,
                                link_drift_gate=1.5, patience=2)
        from horovod_tpu.plan import cost

        predicted = cost.predict_hop_ms("dcn", 1e9)
        with caplog.at_level(_logging.WARNING,
                             logger="horovod_tpu.straggler"):
            # persistently 3x slower than the model predicts
            r1 = det.observe_wire("dcn", 1e9, predicted * 3.0)
            assert r1 == pytest.approx(3.0, rel=0.01)
            assert not reg.snapshot()["counters"].get(
                "straggler.link_degraded{hop=dcn}")  # patience not met
            det.observe_wire("dcn", 1e9, predicted * 3.0)
        snap = reg.snapshot()
        assert snap["counters"]["straggler.link_degraded{hop=dcn}"] == 1
        assert snap["gauges"]["link.health{hop=dcn}"] == \
            pytest.approx(3.0, rel=0.01)
        assert any(d["kind"] == "link" for d in det.history())
        assert any("calibrate_links" in r.message for r in caplog.records)

    def test_healthy_link_never_flags(self):
        reg = MetricsRegistry(enabled=True)
        det = StragglerDetector(reg, world=1, rank=0,
                                link_drift_gate=1.5, patience=2)
        from horovod_tpu.plan import cost

        for _ in range(6):
            det.observe_wire("ici", 1e8,
                             cost.predict_hop_ms("ici", 1e8) * 1.05)
        snap = reg.snapshot()
        assert "straggler.link_degraded{hop=ici}" not in snap["counters"]
        assert snap["gauges"]["link.health{hop=ici}"] == \
            pytest.approx(1.05, rel=0.01)

    def test_recovery_resets_patience(self):
        reg = MetricsRegistry(enabled=True)
        det = StragglerDetector(reg, world=1, rank=0,
                                link_drift_gate=1.5, patience=3)
        from horovod_tpu.plan import cost

        p = cost.predict_hop_ms("pod", 1e8)
        # transient blips that recover below the gate between drifts
        # never accumulate the 3 consecutive over-gate observations
        for _ in range(3):
            det.observe_wire("pod", 1e8, p * 2.0)   # EWMA over the gate
            det.observe_wire("pod", 1e8, p * 0.4)   # EWMA back under
        assert "straggler.link_degraded{hop=pod}" not in \
            reg.snapshot()["counters"]


# ---------------------------------------------------------------------------
# Span-audit vocabulary table (strict mode)


class TestSpanVocabulary:
    def test_known_prefixes_cover_the_documented_table(self):
        for p in ("FAULT", "AUTOTUNE", "OVERLAP", "SERVE", "STALL",
                  "METRIC", "PROFILE", "CYCLE_START", "CKPT", "FUSED",
                  "PP", "STRAGGLER", "FLIGHT"):
            assert p in KNOWN_PREFIXES

    def test_event_prefix(self):
        assert event_prefix("OVERLAP:ALLREDUCE") == "OVERLAP"
        assert event_prefix("CYCLE_START") == "CYCLE_START"

    def test_strict_rejects_unknown_prefix(self):
        events = [
            {"name": "PP:F", "ph": "B", "tid": "t", "ts": 0.0},
            {"name": "PP:F", "ph": "E", "tid": "t", "ts": 1.0},
            {"name": "TYPO:OOPS", "ph": "i", "tid": "t", "ts": 2.0},
        ]
        audit_spans(events, prefix="PP:")  # non-strict: fine
        with pytest.raises(UnknownSpanPrefixError, match="TYPO"):
            audit_spans(events, prefix="PP:", strict=True)

    def test_strict_accepts_full_vocabulary(self):
        events = [{"name": f"{p}:X", "ph": "i", "tid": "t", "ts": 0.0}
                  for p in sorted(KNOWN_PREFIXES - {"CYCLE_START"})]
        events.append({"name": "CYCLE_START", "ph": "i", "tid": "c",
                       "ts": 1.0})
        audit = audit_spans(events, strict=True)
        assert sum(audit.instants.values()) == len(events)


# ---------------------------------------------------------------------------
# Prometheus ephemeral-port discovery (lifecycle satellite)


class TestPrometheusDiscovery:
    def test_ephemeral_port_published_and_discoverable(
            self, tmp_path, monkeypatch):
        from horovod_tpu.monitor import lifecycle

        jsonl = str(tmp_path / "m.jsonl")
        monkeypatch.setenv("HOROVOD_METRICS_PORT", "0")
        monkeypatch.setenv("HOROVOD_METRICS_JSONL", jsonl)
        hvd.shutdown()
        try:
            hvd.init()
            port = lifecycle.prometheus_port()
            assert port and port > 0
            assert monitor.metrics().gauge("metrics.port").value == port
            disc = json.load(open(jsonl + ".port"))
            assert disc["port"] == port
            assert disc["pid"] == os.getpid()
            assert disc["endpoint"].endswith(f":{port}/metrics")
            body = urllib.request.urlopen(disc["endpoint"],
                                          timeout=5).read().decode()
            assert "horovod_" in body
        finally:
            hvd.shutdown()
            lifecycle._reset_for_tests()
            monkeypatch.delenv("HOROVOD_METRICS_PORT")
            monkeypatch.delenv("HOROVOD_METRICS_JSONL")
            hvd.init()


# ---------------------------------------------------------------------------
# Postmortem join (scripts/postmortem.py)


def _write_dump(directory, rank, reason, steps, *, world=3,
                extra_events=(), straggler=(), corrupt=False):
    import zlib

    events = [{"name": "FLIGHT:STEP", "ph": "i", "tid": "flight",
               "wall": 1000.0 + s, "seq": s, "args": {"step": s}}
              for s in range(steps + 1)]
    events += list(extra_events)
    payload = json.dumps(events, sort_keys=True).encode()
    dump = {
        "version": 1, "kind": "flight_record", "reason": reason,
        "ts": 2000.0 + rank,
        "identity": {"rank": rank, "world": world, "pid": 100 + rank,
                     "hostname": f"host{rank}", "local_rank": "0"},
        "events": events,
        "events_crc32":
            f"crc32:{zlib.crc32(payload) & 0xFFFFFFFF:08x}",
        "registry": None, "in_flight": [], "stalled": [],
        "straggler": list(straggler),
    }
    if corrupt:
        dump["events_crc32"] = "crc32:deadbeef"
    path = os.path.join(directory, f"flight_rank{rank}_pid{100+rank}_"
                                   f"000.json")
    with open(path, "w") as f:
        json.dump(dump, f)
    return path


class TestPostmortem:
    def test_join_names_crashing_rank_and_divergence(self, tmp_path):
        pm = _load_postmortem()
        d = str(tmp_path)
        _write_dump(d, 0, "elastic.reset", steps=7)
        _write_dump(d, 1, "elastic.reset", steps=7)
        _write_dump(d, 2, "chaos.crash", steps=4, extra_events=[
            {"name": "FAULT:chaos.crash", "ph": "i", "tid": "faults",
             "wall": 1100.0, "seq": 99}],
            straggler=[{"kind": "phase", "rank": 2, "phase": "wire.dcn",
                        "ms": 90.0, "median_ms": 10.0, "ts": 999.0}])
        report = pm.build_report(d)
        assert report["dumps"] == 3 and not report["corrupt"]
        assert report["crashed_ranks"] == ["rank2"]
        assert report["last_common_step"] == 4
        assert report["max_step"] == 7
        assert report["divergence_step"] == 5
        assert report["diverged_ranks"] == ["rank2"]
        assert report["ranks"]["rank2"]["faults"] == {"chaos.crash": 1}
        assert report["straggler_history"][0]["phase"] == "wire.dcn"
        # the human report renders without crashing
        pm.print_report(report)

    def test_corrupt_dump_rejected_not_trusted(self, tmp_path):
        pm = _load_postmortem()
        d = str(tmp_path)
        _write_dump(d, 0, "exception", steps=3)
        bad = _write_dump(d, 1, "exception", steps=9, corrupt=True)
        report = pm.build_report(d)
        assert report["dumps"] == 1
        assert [c["path"] for c in report["corrupt"]] == [bad]
        # the torn rank-1 file must not have moved last_common_step
        assert report["last_common_step"] == 3

    def test_empty_dir_exits_2(self, tmp_path, capsys):
        pm = _load_postmortem()
        import sys as _sys

        argv = _sys.argv
        _sys.argv = ["postmortem.py", "--dir", str(tmp_path)]
        try:
            assert pm.main() == 2
        finally:
            _sys.argv = argv


# ---------------------------------------------------------------------------
# Chaos cross-wiring: injected crash → parseable dumps on every rank →
# postmortem names the crashing rank (the elastic-driver harness of
# tests/test_elastic_integration.py, with forensics armed).


class TestCrashForensicsIntegration:
    @pytest.mark.chaos
    def test_chaos_crash_leaves_dumps_on_every_rank(self, tmp_path):
        import shlex
        import subprocess  # noqa: F401  (documents the child mechanism)
        import sys as _sys

        from horovod_tpu import chaos as chaos_mod
        from horovod_tpu.common import counters as counters_mod
        from horovod_tpu.elastic import constants
        from horovod_tpu.elastic.discovery import HostDiscoveryScript
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner import safe_shell_exec

        chaos_mod.reset()
        counters_mod.reset_all()
        constants.DISCOVER_HOSTS_FREQUENCY_SECS = 0.25
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(repo, "tests", "elastic_worker.py")
        flight_dir = str(tmp_path / "flight")
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho hostA:2\necho hostB:1\n")
        script.chmod(0o755)
        log_file = str(tmp_path / "log.jsonl")
        plan = chaos_mod.FaultPlan(seed=23).add(
            "collective.eager", "crash", where="hostB:0", after=3,
            max_count=1)

        driver = ElasticDriver(HostDiscoveryScript(str(script), 1),
                               min_np=2, max_np=3,
                               controller_addr_override="127.0.0.1")

        def _exec(slot, world_id):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "PYTHONPATH": repo,
                "HOROVOD_HOSTNAME": slot.hostname,
                "HOROVOD_LOCAL_RANK": str(slot.local_rank),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_ELASTIC_DRIVER_ADDR": "127.0.0.1",
                "HOROVOD_ELASTIC_DRIVER_PORT": str(driver.service_port),
                "HOROVOD_ELASTIC_DRIVER_KEY": driver.key.hex(),
                "HOROVOD_START_TIMEOUT": "30",
                "HOROVOD_FLIGHT_RECORDER_DIR": flight_dir,
            })
            env.update(plan.to_env())
            cmd = " ".join(shlex.quote(c) for c in [
                _sys.executable, worker, "--log-file", log_file,
                "--batches", "8", "--batch-sleep", "0.1"])
            return safe_shell_exec.execute(cmd, env=env)

        try:
            driver.start(_exec)
            ok = driver.join(timeout=240)
        finally:
            driver.stop()
            driver.shutdown_service()
            chaos_mod.reset()
        assert ok

        pm = _load_postmortem()
        report = pm.build_report(flight_dir)
        assert not report["corrupt"], report["corrupt"]
        # every rank of the crashed incarnation left a parseable dump:
        # the dead rank's chaos.crash black box + both survivors' reset
        # dumps
        assert len(report["ranks"]) == 3, report["ranks"]
        assert len(report["crashed_ranks"]) == 1, report["ranks"]
        dead = report["ranks"][report["crashed_ranks"][0]]
        assert dead["reason"] == "chaos.crash"
        assert dead["identity"]["hostname"] == "hostB"
        survivors = [r for k, r in report["ranks"].items()
                     if k not in report["crashed_ranks"]]
        assert len(survivors) == 2
        assert all(r["reason"] == "elastic.reset" for r in survivors)
        assert all(r["identity"]["hostname"] == "hostA"
                   for r in survivors)
        # the postmortem places the divergence: commits stop for the
        # dead rank at its crash batch while survivors got further
        assert report["last_common_step"] is not None
        assert dead["last_step"] <= 4
        assert report["divergence_step"] is not None
        assert report["crashed_ranks"][0] in report["diverged_ranks"]
        # the dead rank's trail ends in real events, not silence
        assert dead["events"] > 0


# ---------------------------------------------------------------------------
# Armed-forensics overhead budget (<1% of a representative step)


class TestForensicsOverhead:
    def test_armed_forensics_under_one_percent_of_step(self):
        """Flight recording + straggler phase accounting armed must cost
        <1% of the same representative 8-device-mesh step the registry
        budget is measured against (the acceptance gate; the heavier
        cross-rank detect() runs on the reporter interval, not per
        step)."""
        mesh = hvd.mesh()
        tx = hvd.DistributedOptimizer(__import__("optax").sgd(0.01))
        params = {f"w{i}": jnp.full((512, 512), 0.01) for i in range(4)}
        state = tx.init(params)

        def loss_fn(p, x):
            h = x
            for i in range(4):
                h = jnp.tanh(h @ p[f"w{i}"])
            return jnp.mean(h ** 2)

        def spmd(p, s, x):
            loss, grads = jax.value_and_grad(loss_fn)(p, x)
            updates, ns = tx.update(grads, s, p)
            import optax
            return optax.apply_updates(p, updates), ns, hvd.allreduce(loss)

        step = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(), P(hvd.HVD_AXES)),
            out_specs=(P(), P(), P())))
        x = jnp.ones((64, 512))
        params, state, loss = step(params, state, x)
        jax.block_until_ready(loss)
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            params, state, loss = step(params, state, x)
            jax.block_until_ready(loss)
            times.append(time.perf_counter() - t0)
        step_secs = float(np.median(times))

        fr = FlightRecorder(capacity=4096, snapshot_every=1024)
        det = StragglerDetector(MetricsRegistry(enabled=True),
                                world=8, rank=0)
        n = 300
        t0 = time.perf_counter()
        for i in range(n):
            # a generous per-step forensic load: 4 ring events, the
            # full phase vector, and the end-of-step publication
            for j in range(4):
                fr.record("FLIGHT:COLLECTIVE", tid="flight",
                          args={"name": f"op.{i}.{j}", "ms": 1.0})
            for ph in ("compute", "wire.ici", "wire.dcn", "wire.pod",
                       "pp_bubble", "ckpt"):
                det.record_phase(ph, 1.0)
            det.end_step(i)
        per_step = (time.perf_counter() - t0) / n
        assert per_step < 0.01 * step_secs, (
            f"armed forensics {per_step * 1e6:.1f}us vs step "
            f"{step_secs * 1e6:.1f}us "
            f"({100 * per_step / step_secs:.2f}% >= 1%)")
