"""Backend bring-up hygiene (common/backend.py): stale-lockfile clearing
and failure diagnostics — the round-4 postmortem machinery (a process
killed mid-run wedged every later PJRT creation with nothing logged)."""

import glob as glob_mod
import os

import pytest

from horovod_tpu.common import backend


@pytest.fixture()
def fake_locks(tmp_path, monkeypatch):
    """Redirect the module's lockfile glob to a temp directory."""
    real_glob = glob_mod.glob

    def fake(pattern, **kw):
        if pattern.startswith("/tmp/libtpu_lockfile"):
            return real_glob(
                str(tmp_path / pattern.rsplit("/", 1)[1]), **kw)
        return real_glob(pattern, **kw)

    monkeypatch.setattr(glob_mod, "glob", fake)
    return tmp_path


class TestClearStaleLocks:
    def test_dead_holder_removed(self, fake_locks):
        lock = fake_locks / "libtpu_lockfile"
        # A pid that cannot exist (pid_max is < 2**22 + 2 on Linux).
        lock.write_text("4194399")
        backend.clear_stale_tpu_locks()
        assert not lock.exists()

    def test_live_holder_kept(self, fake_locks):
        lock = fake_locks / "libtpu_lockfile"
        lock.write_text(str(os.getpid()))
        backend.clear_stale_tpu_locks()
        assert lock.exists()

    def test_unparseable_removed(self, fake_locks):
        # No holder recorded -> treated as stale (the common real-world
        # shape: libtpu writes an empty flock file).
        lock = fake_locks / "libtpu_lockfile"
        lock.write_text("")
        backend.clear_stale_tpu_locks()
        assert not lock.exists()

    def test_flock_held_kept(self, fake_locks):
        # The real libtpu shape: EMPTY file, liveness signalled purely
        # by a held flock. Must NOT be removed while the flock is held.
        import fcntl

        lock = fake_locks / "libtpu_lockfile"
        lock.write_text("")
        fd = os.open(str(lock), os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            backend.clear_stale_tpu_locks()
            assert lock.exists()
        finally:
            os.close(fd)
        # Once the holder releases (dies), it becomes clearable.
        backend.clear_stale_tpu_locks()
        assert not lock.exists()

    def test_no_locks_noop(self, fake_locks):
        backend.clear_stale_tpu_locks()  # nothing to do, no raise


class TestDiagnose:
    def test_diagnose_logs_relay_and_env(self, monkeypatch, capsys):
        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
        # An unroutable port: connection refused, logged as tunnel-down.
        monkeypatch.setenv("HOROVOD_AXON_RELAY_PORT", "1")
        backend.diagnose_backend()
        err = capsys.readouterr().err
        assert "NOT reachable" in err
        assert "backend env:" in err

    def test_pid_alive(self):
        assert backend._pid_alive(os.getpid())
        assert not backend._pid_alive(4194399)


class TestOverlapScheduling:
    """enable_overlap_scheduling (docs/overlap.md): TPU-only flag arming
    with a graceful no-op fallback everywhere else."""

    def test_cpu_platform_is_noop(self, monkeypatch, capsys):
        monkeypatch.setenv("XLA_FLAGS", "")
        assert backend.enable_overlap_scheduling("cpu") is False
        assert os.environ["XLA_FLAGS"] == ""  # untouched
        assert "latency hiding" in capsys.readouterr().err

    def test_tpu_platform_arms_flags_before_backend(self, monkeypatch):
        monkeypatch.setenv("XLA_FLAGS", "--existing=1")
        # Pretend no backend exists yet so the flags can apply.
        monkeypatch.setattr(backend, "_backend_already_created",
                            lambda: False)
        assert backend.enable_overlap_scheduling("tpu") is True
        flags = os.environ["XLA_FLAGS"]
        assert "--existing=1" in flags
        for f in backend._OVERLAP_XLA_FLAGS:
            assert f in flags
        # Idempotent: a second call adds nothing.
        before = os.environ["XLA_FLAGS"]
        assert backend.enable_overlap_scheduling("tpu") is True
        assert os.environ["XLA_FLAGS"] == before

    def test_tpu_after_backend_created_refuses(self, monkeypatch, capsys):
        monkeypatch.setenv("XLA_FLAGS", "")
        monkeypatch.setattr(backend, "_backend_already_created",
                            lambda: True)
        assert backend.enable_overlap_scheduling("tpu") is False
        assert "already initialized" in capsys.readouterr().err

    def test_auto_without_tpu_device_files_falls_back(self, monkeypatch,
                                                      capsys):
        monkeypatch.setenv("XLA_FLAGS", "")
        monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
        monkeypatch.setattr(glob_mod, "glob", lambda p: [])
        assert backend.enable_overlap_scheduling("auto") is False
        assert os.environ["XLA_FLAGS"] == ""
