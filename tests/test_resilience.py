"""Tests for horovod_tpu/resilience/: the failure-policy state machine,
health-gated readmission, the preemption priority-snapshot path (unit +
mid-save SIGTERM subprocess regression), and degraded-link replanning
end-to-end through chaos delay → latch → quantized swap → swap-back.
See docs/robustness.md."""

import glob
import json
import os
import shlex
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import chaos, resilience
from horovod_tpu.common import counters as counters_mod
from horovod_tpu.elastic.discovery import FixedHosts, HostManager
from horovod_tpu.monitor.registry import MetricsRegistry
from horovod_tpu.monitor.straggler import StragglerDetector
from horovod_tpu.resilience import policy as policy_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Policy state machine (resilience/policy.py)


class TestPolicyEngine:
    def _engine(self, **policies):
        return policy_mod.PolicyEngine(
            policies=policies, registry=MetricsRegistry(enabled=True))

    def test_budget_then_escalation_ladder(self):
        eng = self._engine()
        # worker_crash: budget 2 → retry, retry, then one ladder rung
        # per further failure, clamped at abort.
        actions = [eng.record_failure("worker_crash", key="hostX").action
                   for _ in range(6)]
        assert actions == ["retry", "retry", "blacklist", "shrink_world",
                           "abort", "abort"]

    def test_backoff_doubles_and_caps(self):
        eng = self._engine(worker_crash=policy_mod.Policy(
            retry_budget=6, backoff_base_secs=1.0, backoff_cap_secs=4.0))
        backs = [eng.record_failure("worker_crash").backoff_secs
                 for _ in range(5)]
        assert backs == [1.0, 2.0, 4.0, 4.0, 4.0]

    def test_success_resets_the_counter(self):
        eng = self._engine()
        eng.record_failure("worker_crash", key="hostX")
        eng.record_failure("worker_crash", key="hostX")
        eng.record_success("worker_crash", key="hostX")
        assert eng.failures("worker_crash", "hostX") == 0
        # ...and the ladder restarts from retry, not where it left off.
        assert eng.record_failure("worker_crash",
                                  key="hostX").action == "retry"

    def test_keys_are_independent(self):
        eng = self._engine()
        for _ in range(4):
            eng.record_failure("worker_crash", key="hostA")
        assert eng.record_failure("worker_crash",
                                  key="hostB").action == "retry"

    def test_ladder_start_skips_blacklist_for_flaps(self):
        # No specific host is at fault in a discovery flap: the ladder
        # enters at shrink_world.
        eng = self._engine()
        for _ in range(5):
            eng.record_failure("discovery_flap")
        assert eng.record_failure("discovery_flap").action == \
            "shrink_world"

    def test_class_specific_first_responses(self):
        eng = self._engine()
        assert eng.record_failure("preemption").action == "snapshot"
        assert eng.record_failure("degraded_link",
                                  key="dcn").action == "replan"
        assert eng.record_failure("stall").action == "blacklist"

    def test_unknown_class_rejected(self):
        with pytest.raises(ValueError):
            self._engine().record_failure("cosmic_rays")

    def test_counters_and_snapshot(self):
        reg = MetricsRegistry(enabled=True)
        eng = policy_mod.PolicyEngine(registry=reg)
        for _ in range(3):
            eng.record_failure("worker_crash", key="hostX")
        eng.record_success("worker_crash", key="hostX")
        snap = reg.snapshot()
        assert snap["counters"][
            "resilience.failures{cls=worker_crash}"] == 3
        assert snap["counters"][
            "resilience.escalations{action=blacklist,"
            "cls=worker_crash}"] == 1
        assert snap["counters"][
            "resilience.recoveries{cls=worker_crash}"] == 1
        state = eng.snapshot()
        assert state["failures"] == {}
        assert [d["action"] for d in state["decisions"]] == \
            ["retry", "retry", "blacklist"]


class TestReadmissionGate:
    def test_default_probe_passes(self):
        gate = policy_mod.ReadmissionGate(
            registry=MetricsRegistry(enabled=True))
        assert gate("hostA") is True

    def test_failing_and_raising_probes_block(self):
        reg = MetricsRegistry(enabled=True)

        def probe(host):
            if host == "bad":
                return False
            raise RuntimeError("probe transport down")

        gate = policy_mod.ReadmissionGate(probe=probe, registry=reg)
        assert gate("bad") is False
        assert gate("worse") is False
        snap = reg.snapshot()
        assert snap["counters"][
            "resilience.readmission{verdict=fail}"] == 2

    def test_host_manager_readmission_is_health_gated(self):
        # The wiring end-to-end: supervisor attach installs the gate on
        # the driver's HostManager; a failing probe re-arms the
        # cooldown, a passing one readmits.
        counters_mod.reset_all()
        verdicts = {"b": [False, True]}  # first probe fails, second passes

        class _Driver:
            host_manager = HostManager(FixedHosts({"a": 1, "b": 1}),
                                       cooldown_secs=0.15)

        sup = resilience.Supervisor(
            driver=_Driver(),
            readmission_probe=lambda h: verdicts[h].pop(0),
            registry=MetricsRegistry(enabled=True)).attach()
        try:
            hm = _Driver.host_manager
            hm.update_available_hosts()
            hm.blacklist("b")
            assert hm.is_blacklisted("b")
            time.sleep(0.2)
            assert hm.is_blacklisted("b")  # probe #1 fails → re-armed
            assert counters_mod.counters()[
                "elastic.blacklist.probe_fail"] == 1
            time.sleep(0.2)
            assert not hm.is_blacklisted("b")  # probe #2 passes
            assert counters_mod.counters()[
                "elastic.blacklist.readmit"] == 1
        finally:
            sup.detach()
            counters_mod.reset_all()


# ---------------------------------------------------------------------------
# Supervisor: preemption priority snapshot + restart budget


class _FakeCkptManager:
    def __init__(self, latest=None, wait_result=True):
        self.latest = latest
        self.wait_result = wait_result
        self.saves = []
        self.waits = []

    def latest_step(self):
        return self.latest

    def save(self, step, tree, extra=None, **kw):
        self.saves.append((step, tree, extra))
        self.latest = step

    def wait(self, timeout=None):
        self.waits.append(timeout)
        return self.wait_result


class TestSupervisorPreemption:
    def _sup(self, mgr, provider, **kw):
        kw.setdefault("registry", MetricsRegistry(enabled=True))
        return resilience.Supervisor(ckpt_manager=mgr,
                                     snapshot_provider=provider, **kw)

    def test_priority_snapshot_commits_under_deadline(self):
        mgr = _FakeCkptManager()
        sup = self._sup(
            mgr, lambda: (9, {"w": np.ones(2)}, {"src": "priority"}),
            snapshot_deadline_secs=5.0)
        event = sup.on_preemption_notice(source="test")
        assert event["saved_step"] == 9
        assert event["committed"] is True
        assert event["deadline_met"] is True
        assert event["policy_action"] == "snapshot"
        step, _tree, extra = mgr.saves[0]
        assert step == 9 and extra == {"src": "priority"}
        assert mgr.waits and mgr.waits[0] <= 5.0
        assert sup.report()["preemptions"][0]["saved_step"] == 9

    def test_nothing_newer_than_last_commit_skips_the_save(self):
        mgr = _FakeCkptManager(latest=12)
        sup = self._sup(mgr, lambda: (12, {"w": np.ones(2)}, None))
        event = sup.on_preemption_notice()
        assert mgr.saves == []          # no duplicate commit...
        assert mgr.waits                # ...but in-flight writes drain
        assert event["saved_step"] == 12
        assert event["deadline_met"] is True

    def test_missed_deadline_is_reported(self):
        mgr = _FakeCkptManager(wait_result=False)  # never quiesces
        sup = self._sup(mgr, lambda: (3, {}, None),
                        snapshot_deadline_secs=0.01)
        event = sup.on_preemption_notice()
        assert event["committed"] is False
        assert event["deadline_met"] is False

    def test_provider_failure_never_raises(self):
        def provider():
            raise RuntimeError("state is mid-update")

        sup = self._sup(_FakeCkptManager(), provider)
        event = sup.on_preemption_notice()
        assert event["saved_step"] is None

    def test_restart_budget(self):
        sup = resilience.Supervisor(
            restart_budget=2, registry=MetricsRegistry(enabled=True))
        assert sup.restart_allowed()
        assert sup.record_restart(restored_step=4) is True
        assert sup.record_restart(restored_step=7) is True
        assert not sup.restart_allowed()
        assert sup.record_restart(restored_step=7) is False
        rep = sup.report()
        assert rep["restarts"] == 3 and rep["restart_budget"] == 2


MIDSAVE_SCRIPT = textwrap.dedent("""\
    import os, signal, sys, time
    sys.path.insert(0, {repo!r})
    import numpy as np
    from horovod_tpu.monitor import flight
    from horovod_tpu import checkpoint as ck

    flight.arm()
    mgr = ck.CheckpointManager(sys.argv[1], keep=2)
    # Occupy the writer thread so the real save below is still in
    # flight (queued behind it) when the SIGTERM lands: the ordering
    # contract (hooks -> writer drain -> dump -> re-deliver) must hold
    # the signal until the commit completes.
    mgr._writer.submit(lambda: time.sleep(1.0))
    mgr.save(7, {{"train": {{"w": np.arange(8.0)}}}},
             extra={{"src": "midsave"}}, blocking=False)
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(30)  # never reached: the handler re-delivers SIGTERM
""")


class TestSigtermMidSaveOrdering:
    @pytest.mark.chaos
    def test_sigterm_drains_the_inflight_save_before_dump(self, tmp_path):
        """Regression for the SIGTERM ordering contract: a save whose
        commit is in flight when the signal lands must complete (writer
        drain) before the flight dump re-delivers SIGTERM."""
        script = tmp_path / "midsave.py"
        script.write_text(MIDSAVE_SCRIPT.format(repo=REPO))
        ckpt_dir = str(tmp_path / "ckpt")
        flight_dir = str(tmp_path / "flight")
        env = dict(os.environ, PYTHONPATH=REPO,
                   HOROVOD_FLIGHT_RECORDER_DIR=flight_dir,
                   HOROVOD_SIGTERM_DRAIN_SECS="10")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, str(script), ckpt_dir],
            env=env, capture_output=True, text=True, timeout=120)
        # Re-delivered SIGTERM, not a clean exit.
        assert proc.returncode in (-signal.SIGTERM, 143), \
            (proc.returncode, proc.stderr)
        # The in-flight commit landed whole: manifest-last protocol +
        # pre-dump drain ⇒ restorable, with the extra payload intact.
        from horovod_tpu import checkpoint as ck

        mgr = ck.CheckpointManager(ckpt_dir, async_save=False)
        manifest, tree = mgr.restore()
        assert manifest.step == 7
        assert manifest.extra.get("src") == "midsave"
        np.testing.assert_array_equal(
            np.asarray(tree["train"]["w"]), np.arange(8.0))
        # ...and the black box recorded the signal as the reason.
        dumps = glob.glob(os.path.join(flight_dir, "flight_*.json"))
        assert dumps, proc.stderr
        reasons = {json.load(open(p)).get("reason") for p in dumps}
        assert "sigterm" in reasons


# ---------------------------------------------------------------------------
# Degraded-link replanning


class _FakeDetector:
    def __init__(self):
        self.state = {}

    def degraded_hops(self):
        return dict(self.state)


class TestSupervisorReplan:
    def test_swap_holds_and_reverts(self):
        det = _FakeDetector()
        sup = resilience.Supervisor(
            straggler=det, registry=MetricsRegistry(enabled=True))
        det.state = {"dcn": 4.0}
        directive = sup.maybe_replan(1 << 20, mesh_shape=(2, 4), step=3)
        assert directive and "swap" in directive
        rec = directive["decision"]
        assert rec.hop == "dcn" and rec.step == 3
        assert rec.plan_after and "int8" in rec.plan_after
        assert rec.plan_before and "int8" not in rec.plan_before
        assert rec.predicted_ms > 0
        assert "dcn" in sup.active_swaps()
        # Still degraded: the swap holds, no re-decision every step.
        assert sup.maybe_replan(1 << 20, mesh_shape=(2, 4),
                                step=4) is None
        # Latch cleared: revert, recorded on the same decision.
        det.state = {}
        revert = sup.maybe_replan(1 << 20, mesh_shape=(2, 4), step=9)
        assert revert and revert.get("revert") and revert["hop"] == "dcn"
        assert sup.active_swaps() == {}
        report = sup.report()
        assert report["replans"][0]["reverted"] is True
        assert report["replans"][0]["step"] == 3

    def test_no_detector_and_no_degradation_are_quiet(self):
        det = _FakeDetector()
        sup = resilience.Supervisor(
            straggler=det, registry=MetricsRegistry(enabled=True))
        assert sup.maybe_replan(1 << 20, mesh_shape=(2, 4)) is None

    @pytest.mark.chaos
    def test_chaos_delay_to_quantized_swap_and_back(self):
        """End-to-end: chaos ``delay`` on the eager collective inflates
        the probe's wire time → the straggler latch flags the DCN hop →
        the supervisor re-prices under the EWMA override and swaps the
        step to the quantized wire → the delay expires, the latch
        clears, and the swap reverts."""
        from horovod_tpu.plan import cost as _cost

        chaos.reset()
        # Gate 4x with patience 2: the injected 60 ms delay scores
        # hundreds of x over the sub-ms healthy baseline, while CI
        # scheduling noise on the healthy probe stays within ~2x.
        det = StragglerDetector(registry=MetricsRegistry(enabled=True),
                                link_drift_gate=4.0, patience=2)
        sup = resilience.Supervisor(
            straggler=det, registry=MetricsRegistry(enabled=True))
        probe = np.zeros((64,), np.float32)
        nbytes = float(probe.nbytes)
        predicted = _cost.predict_hop_ms("dcn", nbytes)

        def probe_ms():
            t0 = time.perf_counter()
            hvd.allreduce(probe, name="test.replan.probe") \
                .block_until_ready()
            return (time.perf_counter() - t0) * 1e3

        for _ in range(3):
            probe_ms()  # warm the eager path before baselining
        baseline = float(np.median([probe_ms() for _ in range(3)]))
        # A 60 ms injected delay dwarfs any CI timing noise around the
        # sub-ms healthy baseline.
        chaos.configure(chaos.FaultPlan(seed=3).add(
            "collective.eager", "delay", secs=0.06, max_count=3))
        try:
            quantized = False
            swaps, reverts = [], []
            for step in range(16):
                hvd.allreduce(np.ones((8,), np.float32),
                              name=f"test.replan.step.{step}",
                              quantized=quantized).block_until_ready()
                measured = probe_ms()
                det.observe_wire("dcn", nbytes,
                                 predicted * measured
                                 / max(baseline, 1e-6))
                if measured < 1.5 * baseline:
                    # Track healthy drift so the ratio stays ~1 once
                    # the injected delay expires (the soak leg's rule).
                    baseline = 0.5 * baseline + 0.5 * measured
                d = sup.maybe_replan(nbytes, mesh_shape=(2, 4),
                                     step=step)
                if d and "swap" in d:
                    quantized = True
                    swaps.append(step)
                elif d and d.get("revert"):
                    quantized = False
                    reverts.append(step)
                if reverts:
                    break
            assert swaps, "degraded latch never produced a swap"
            assert reverts, "recovered link never reverted the swap"
            assert swaps[0] < reverts[0]
            report = sup.report()
            assert report["replans"][0]["reverted"] is True
            assert "int8" in report["replans"][0]["plan_after"]
        finally:
            chaos.reset()


# ---------------------------------------------------------------------------
# Chaos ``preempt`` action (chaos/plan.py + injector.py)


class TestPreemptAction:
    def test_in_grammar_and_round_trips(self):
        assert "preempt" in chaos.ACTIONS
        spec = chaos.FaultSpec.parse(
            "collective.eager:preempt,where=hostB:0,after=3,"
            "max=1,secs=0.5")
        assert spec.action == "preempt" and spec.secs == 0.5
        again = chaos.FaultSpec.parse(spec.serialize())
        assert again.serialize() == spec.serialize()
        plan = chaos.FaultPlan(seed=11, specs=[spec])
        restored = chaos.FaultPlan.from_env(plan.to_env())
        assert [s.serialize() for s in restored.specs] == \
            [spec.serialize()]

    def test_immediate_preempt_delivers_sigterm(self):
        counters_mod.reset_all()
        got = []
        prev = signal.signal(signal.SIGTERM,
                             lambda sig, frame: got.append(sig))
        try:
            chaos.configure(chaos.FaultPlan(seed=1).add(
                "test.preempt", "preempt", max_count=1))
            chaos.inject("test.preempt")
            deadline = time.monotonic() + 2.0
            while not got and time.monotonic() < deadline:
                time.sleep(0.01)
            assert got == [signal.SIGTERM]
            assert counters_mod.counters()["chaos.preempt"] == 1
        finally:
            signal.signal(signal.SIGTERM, prev)
            chaos.reset()
            counters_mod.reset_all()

    def test_grace_delay_defers_delivery(self):
        got = []
        prev = signal.signal(signal.SIGTERM,
                             lambda sig, frame: got.append(sig))
        try:
            chaos.configure(chaos.FaultPlan(seed=1).add(
                "test.preempt.grace", "preempt", secs=0.15,
                max_count=1))
            chaos.inject("test.preempt.grace")
            assert got == []  # the grace window
            deadline = time.monotonic() + 3.0
            while not got and time.monotonic() < deadline:
                time.sleep(0.02)
            assert got == [signal.SIGTERM]
        finally:
            signal.signal(signal.SIGTERM, prev)
            chaos.reset()
            counters_mod.reset_all()


# ---------------------------------------------------------------------------
# Preemption end-to-end through a real elastic worker (the gauntlet's
# smallest slice): chaos preempt → SIGTERM → priority snapshot →
# committed checkpoint + sigterm flight dump, survivors re-form.


WORKER = os.path.join(REPO, "tests", "soak_worker.py")


class TestPreemptionEndToEnd:
    @pytest.mark.chaos
    @pytest.mark.slow
    def test_preempted_worker_commits_a_priority_snapshot(self, tmp_path):
        from horovod_tpu.elastic import constants
        from horovod_tpu.elastic.discovery import HostDiscoveryScript
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner import safe_shell_exec

        chaos.reset()
        counters_mod.reset_all()
        constants.DISCOVER_HOSTS_FREQUENCY_SECS = 0.25
        flight_dir = str(tmp_path / "flight")
        ckpt_dir = str(tmp_path / "ckpt")
        log_file = str(tmp_path / "log.jsonl")
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho hostA:2\necho hostB:1\n")
        script.chmod(0o755)
        plan = chaos.FaultPlan(seed=5).add(
            "collective.eager", "preempt", where="hostB:0", after=3,
            max_count=1)
        driver = ElasticDriver(HostDiscoveryScript(str(script), 1),
                               min_np=2, max_np=3,
                               controller_addr_override="127.0.0.1")

        def _exec(slot, world_id):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.update({
                "PYTHONPATH": REPO,
                "HOROVOD_HOSTNAME": slot.hostname,
                "HOROVOD_LOCAL_RANK": str(slot.local_rank),
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_ELASTIC_DRIVER_ADDR": "127.0.0.1",
                "HOROVOD_ELASTIC_DRIVER_PORT": str(driver.service_port),
                "HOROVOD_ELASTIC_DRIVER_KEY": driver.key.hex(),
                "HOROVOD_START_TIMEOUT": "30",
                "HOROVOD_FLIGHT_RECORDER_DIR": flight_dir,
            })
            if world_id == 0:
                env.update(plan.to_env())
            cmd = " ".join(shlex.quote(c) for c in [
                sys.executable, WORKER, "--log-file", log_file,
                "--batches", "8", "--batch-sleep", "0.1",
                "--ckpt-dir", ckpt_dir])
            return safe_shell_exec.execute(cmd, env=env)

        try:
            driver.start(_exec)
            ok = driver.join(timeout=240)
        finally:
            driver.stop()
            driver.shutdown_service()
            chaos.reset()
        assert ok
        assert driver.world_id >= 1  # the preemption forced a re-form
        # The preempted rank's flight dump carries a deadline-met
        # RESILIENCE:PREEMPT event.
        events = []
        for path in glob.glob(os.path.join(flight_dir, "flight_*.json")):
            dump = json.load(open(path))
            events += [(dump.get("reason"), ev.get("args") or {})
                       for ev in dump.get("events", [])
                       if ev.get("name") == "RESILIENCE:PREEMPT"]
        assert events, "no RESILIENCE:PREEMPT in any flight dump"
        reason, args = events[0]
        assert reason == "sigterm"
        assert args.get("deadline_met") is True
        assert args.get("committed") is True
        # The run completed all batches on the re-formed world and the
        # final commit is restorable.
        from horovod_tpu import checkpoint as ck

        mgr = ck.CheckpointManager(ckpt_dir, async_save=False)
        manifest, _tree = mgr.restore()
        assert manifest.step == 8
