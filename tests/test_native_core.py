"""Native C++ core tests: single-process pipeline + multi-process localhost.

Mirrors the reference's test tiers (SURVEY §4): single-process logic tests
against the trivial world, and parallel tests running N real processes over
localhost TCP — the analogue of `mpirun -np 2 pytest test_tensorflow.py`.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from horovod_tpu import cc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "native_worker.py")
EAGER_WORKER = os.path.join(REPO, "tests", "eager_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def ctx():
    """Single-process core context (world of one, full pipeline)."""
    # Ensure a clean world regardless of inherited env.
    for k in ("HOROVOD_RANK", "HOROVOD_SIZE"):
        os.environ.pop(k, None)
    c = cc.CoreContext()
    yield c
    c.close()


class TestSingleProcess:
    def test_world(self, ctx):
        assert ctx.rank() == 0
        assert ctx.size() == 1
        assert ctx.fusion_threshold() == 64 * 1024 * 1024

    def test_allreduce_identity(self, ctx):
        a = np.arange(8, dtype=np.float32)
        out = ctx.allreduce_async(a.copy(), "sp_ar").wait()
        assert np.allclose(out, a)

    def test_allreduce_postscale(self, ctx):
        out = ctx.allreduce_async(np.ones(4, np.float32), "sp_ps",
                                  postscale=0.25).wait()
        assert np.allclose(out, 0.25)

    def test_allgather(self, ctx):
        out = ctx.allgather_async(np.ones((3, 2), np.float32),
                                  "sp_ag").wait()
        assert out.shape == (3, 2)

    def test_broadcast(self, ctx):
        out = ctx.broadcast_async(np.arange(4, dtype=np.int64), "sp_bc",
                                  root=0).wait()
        assert (out == np.arange(4)).all()

    def test_alltoall(self, ctx):
        h = ctx.alltoall_async(np.arange(6, dtype=np.float64).reshape(6, 1),
                               "sp_a2a")
        out = h.wait()
        assert np.allclose(out.ravel(), np.arange(6))
        assert h.recv_splits() == [6]

    def test_barrier(self, ctx):
        ctx.barrier()

    def test_duplicate_name_rejected(self, ctx):
        # Reference: DUPLICATE_NAME_ERROR (common.h:163) surfaces when a
        # name is re-submitted while still in flight.
        h1 = ctx.allreduce_async(np.ones(1024, np.float32), "sp_dup")
        try:
            h2 = ctx.allreduce_async(np.ones(1024, np.float32), "sp_dup")
        except cc.NativeError as e:
            assert "same name" in str(e)
        else:
            h2.wait()  # raced past the first completion — legal
        h1.wait()

    def test_int_dtypes(self, ctx):
        for dt in (np.uint8, np.int8, np.int32, np.int64):
            out = ctx.allreduce_async(np.ones(4, dt), f"sp_{dt.__name__}"
                                      ).wait()
            assert (out == 1).all()

    def test_cache_steady_state(self, ctx):
        for _ in range(20):
            out = ctx.allreduce_async(np.ones(4, np.float32),
                                      "sp_steady").wait()
            assert np.allclose(out, 1.0)

    def test_timeline(self, ctx, tmp_path):
        path = str(tmp_path / "tl.json")
        ctx.start_timeline(path)
        for i in range(5):
            ctx.allreduce_async(np.ones(4, np.float32), f"sp_tl{i}").wait()
        ctx.stop_timeline()
        import json
        text = open(path).read().rstrip().rstrip(",")
        events = json.loads(text + "]") if not text.endswith("]") else \
            json.loads(text)
        names = {e["name"] for e in events}
        assert any(n.startswith("NEGOTIATE_") for n in names)
        assert "ALLREDUCE" in names or "TCP_ALLREDUCE" in names


def _run_world(n, extra_env=None, timeout=120, worker=WORKER,
               local_size=None):
    port = _free_port()
    procs = []
    for r in range(n):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # workers don't need the 8-device mesh
        env.update({
            "PYTHONPATH": REPO,
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": str(n),
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
        })
        if local_size is not None:
            # Emulated multi-host topology: host-major rank packing
            # (reference hosts.py:100-150).
            env.update({
                "HOROVOD_LOCAL_RANK": str(r % local_size),
                "HOROVOD_LOCAL_SIZE": str(local_size),
                "HOROVOD_CROSS_RANK": str(r // local_size),
                "HOROVOD_CROSS_SIZE": str(n // local_size),
            })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            ok = False
        outs.append(out)
        ok = ok and p.returncode == 0
    assert ok, "worker failures:\n" + "\n----\n".join(outs)
    return outs


class TestMultiProcess:
    @pytest.mark.parametrize("n", [2, 4])
    def test_world(self, n):
        _run_world(n)

    def test_world_3_small_fusion(self):
        # Odd world + tiny fusion threshold forces multi-buffer fusion
        # rounds and non-divisible ring chunks.
        _run_world(3, {"HOROVOD_FUSION_THRESHOLD": str(256)})

    def test_hierarchical_2x2(self):
        # Full worker assertion suite with hierarchical allreduce+allgather
        # enabled on an emulated 2-host x 2-chip topology: numerics must be
        # identical to the flat ring paths (reference:
        # NCCLHierarchicalAllreduce nccl_operations.cc:190-380,
        # MPIHierarchicalAllgather mpi_operations.cc:180-280).
        _run_world(4, {
            "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
            "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
        }, local_size=2)

    def test_hierarchical_3x2_small_fusion(self):
        # Non-power-of-2 host count + tiny fusion buffers: uneven cross-ring
        # chunks through the hierarchical legs.
        _run_world(6, {
            "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
            "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
            "HOROVOD_FUSION_THRESHOLD": str(256),
        }, local_size=2)

    def test_autotune_smoke(self):
        # Small sample budget so the tuner converges inside the worker's
        # autotune traffic loop; the worker then asserts the tuned params
        # propagated identically to every rank.
        _run_world(2, {
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "2",
            "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "4",
        })

    def test_autotune_hierarchical_topology(self):
        # On a 2x2 topology the hierarchical flags join the search space;
        # the run must stay correct whichever way the tuner flips them
        # mid-stream (all the worker's numeric assertions still hold).
        _run_world(4, {
            "HOROVOD_AUTOTUNE": "1",
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "2",
            "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "6",
        }, local_size=2, timeout=180)


class TestEagerPythonAPI:
    """The full hvd.* Python surface across worker processes — the
    reference's `mpirun -np N pytest test_tensorflow.py` tier."""

    @pytest.mark.parametrize("n", [2, 4])
    def test_world(self, n):
        _run_world(n, timeout=240, worker=EAGER_WORKER)
