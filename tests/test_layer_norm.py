"""Fused residual+LayerNorm Pallas kernel (ops/layer_norm.py) vs the
plain-XLA formulation — forward, both outputs, full gradient set, odd
shapes, and the shard_map (DP) path. Interpreter mode on CPU; the same
code path compiles on TPU (bench --fused-ln A/B)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.layer_norm import ln_residual


def ref_ln_residual(x, res, gamma, beta, eps=1e-5):
    h = (x + res).astype(jnp.float32)
    mean = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(h - mean), axis=-1, keepdims=True)
    y = (h - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    return y.astype(x.dtype), h.astype(x.dtype)


def _data(shape=(4, 32, 128), dtype=jnp.float32, seed=0):
    rs = np.random.RandomState(seed)
    C = shape[-1]
    x = jnp.asarray(rs.randn(*shape), dtype)
    r = jnp.asarray(rs.randn(*shape), dtype) * 0.5
    g = jnp.asarray(1.0 + 0.1 * rs.randn(C), jnp.float32)
    b = jnp.asarray(0.1 * rs.randn(C), jnp.float32)
    return x, r, g, b


@pytest.mark.parametrize("shape", [(4, 32, 128), (8, 256), (2, 7, 384)])
def test_forward_matches_reference(shape):
    x, r, g, b = _data(shape)
    y, h = ln_residual(x, r, g, b)
    ye, he = ref_ln_residual(x, r, g, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ye),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), np.asarray(he),
                               rtol=1e-6, atol=1e-6)


def test_forward_bf16():
    x, r, g, b = _data((4, 64, 256), jnp.bfloat16)
    y, h = ln_residual(x, r, g, b)
    ye, he = ref_ln_residual(x, r, g, b)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(ye, np.float32),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(h, np.float32),
                               np.asarray(he, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_gradients_match_reference():
    x, r, g, b = _data((2, 16, 128))
    w = jnp.asarray(np.random.RandomState(5).randn(128), jnp.float32)

    def loss_fused(x, r, g, b):
        y, h = ln_residual(x, r, g, b)
        # use BOTH outputs so dh and dy cotangents are exercised
        return jnp.sum(y * w) + jnp.sum(jnp.square(h)) * 0.1

    def loss_ref(x, r, g, b):
        y, h = ref_ln_residual(x, r, g, b)
        return jnp.sum(y * w) + jnp.sum(jnp.square(h)) * 0.1

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, r, g, b)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, r, g, b)
    for a, e in zip(gf, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("shape", [(3, 5, 128), (1021, 128)])
def test_rows_pad_to_block_multiple(shape):
    # Non-multiple (and PRIME) row counts pad up to a block multiple —
    # never degrade to 1-row blocks — and grads see no padding rows.
    x, r, g, b = _data(shape, seed=2)
    y, _ = ln_residual(x, r, g, b, 1e-5, 256)
    ye, _ = ref_ln_residual(x, r, g, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ye),
                               rtol=1e-5, atol=1e-5)
    gf = jax.grad(lambda g: jnp.sum(ln_residual(x, r, g, b)[0]))(g)
    ge = jax.grad(lambda g: jnp.sum(ref_ln_residual(x, r, g, b)[0]))(g)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(ge),
                               rtol=1e-4, atol=1e-4)


def test_under_shard_map_dp():
    # DP over the batch: the kernel's vma harmonization must accept
    # varying streams with replicated gamma/beta.
    x, r, g, b = _data((8, 16, 128), seed=3)

    def f(xs, rs, g, b):
        y, h = ln_residual(xs, rs, g, b)
        return y, h

    y, h = jax.jit(hvd.shard_map(
        f, mesh=hvd.mesh(),
        in_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES), P(), P()),
        out_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES))))(x, r, g, b)
    ye, he = ref_ln_residual(x, r, g, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ye),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), np.asarray(he),
                               rtol=1e-6, atol=1e-6)


def test_gpt_fused_ln_matches_unfused():
    """GPTConfig.fused_ln swaps the add+ln2 pair for the kernel with an
    IDENTICAL param tree: same init loads into both, same outputs and
    gradients (the bench --fused-ln A/B is purely a perf lever)."""
    import dataclasses

    import optax

    from horovod_tpu.models import GPT, gpt_tiny

    cfg = gpt_tiny(dtype=jnp.float32)
    cfg_f = dataclasses.replace(cfg, fused_ln=True)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size, (2, 33))
    x, yt = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    v = GPT(cfg).init(jax.random.PRNGKey(0), x)
    # identical param trees
    assert jax.tree.structure(v) == jax.tree.structure(
        GPT(cfg_f).init(jax.random.PRNGKey(0), x))
    out_d = GPT(cfg).apply(v, x)
    out_f = GPT(cfg_f).apply(v, x)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)

    def loss(params, c):
        out = GPT(c).apply({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            out, yt).mean()

    gd = jax.grad(loss)(v["params"], cfg)
    gf = jax.grad(loss)(v["params"], cfg_f)
    flat_d = jax.tree.leaves(gd)
    flat_f = jax.tree.leaves(gf)
    for a, e in zip(flat_f, flat_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=5e-3, atol=1e-4)


def test_shape_validation():
    x, r, g, b = _data()
    with pytest.raises(ValueError, match="mismatch"):
        ln_residual(x, r[:2], g, b)
    with pytest.raises(ValueError, match="gamma"):
        ln_residual(x, r, g[:5], b)
