"""Collective-knob autotuner tests (reference: parameter_manager.cc +
gp.cc; the compiled-path Python port lives in horovod_tpu/autotune/).

Tiers mirror the subsystem layers: the NumPy GP against a known
quadratic, the warmup → sample → freeze state machine, the CSV log
schema round-trip, the warm-start cache (a rerun skips every trial), the
end-to-end toy tuning session on the CPU mesh (the ISSUE acceptance
criterion), the TunedParams override equivalence with hand-set env knobs,
and the three-layer CLI/YAML → env → Config contract."""

import argparse
import dataclasses
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.autotune import (
    AutotuneResult,
    GaussianProcess,
    ParameterManager,
    TunedParams,
    autotune_session,
    cache_key_for,
    load_cached_params,
    read_log,
)
from horovod_tpu.autotune import parameter_manager as pm_mod
from horovod_tpu.common import basics, config as config_mod
from horovod_tpu.ops import fusion
from horovod_tpu.runner import config_parser

MIB = 1024 * 1024


class TestGaussianProcess:
    def test_fit_predict_recovers_training_points(self):
        # Noise-free-ish GP interpolates a smooth function at its samples.
        xs = [[x] for x in np.linspace(0.0, 1.0, 9)]
        ys = [-((x[0] - 0.3) ** 2) * 4 for x in xs]
        gp = GaussianProcess(1, length_scale=0.3, noise=0.01)
        assert gp.fit(xs, ys)
        for x, y in zip(xs, ys):
            mu, sd = gp.predict(x)
            assert abs(mu - y) < 0.05
            assert sd < 0.05

    def test_predict_uncertainty_grows_off_data(self):
        gp = GaussianProcess(1, length_scale=0.1, noise=0.01)
        assert gp.fit([[0.1], [0.2]], [0.0, 0.1])
        _, sd_near = gp.predict([0.15])
        _, sd_far = gp.predict([0.9])
        assert sd_far > sd_near

    def test_ei_picks_the_basin(self):
        # Maximizing -(x-0.3)^2: EI over a candidate grid must peak near
        # x = 0.3 once the GP has seen points straddling it.
        xs = [[0.0], [0.15], [0.45], [0.6], [0.9]]
        ys = [-(x[0] - 0.3) ** 2 for x in xs]
        mean, sd = np.mean(ys), np.std(ys) or 1.0
        yn = [(y - mean) / sd for y in ys]
        gp = GaussianProcess(1, length_scale=0.3, noise=0.1)
        assert gp.fit(xs, yn)
        grid = np.linspace(0.0, 1.0, 101)
        eis = [gp.expected_improvement([x], max(yn)) for x in grid]
        assert abs(grid[int(np.argmax(eis))] - 0.3) < 0.1

    def test_fit_rejects_non_pd(self):
        # Duplicate rows with zero noise make K singular.
        gp = GaussianProcess(1, noise=0.0)
        assert not gp.fit([[0.5], [0.5]], [1.0, 1.0])
        assert not gp.fitted

    def test_predict_batch_matches_pointwise(self):
        # The batched path (one matrix solve for the whole EI candidate
        # pool) must agree with the per-point triangular solves.
        rng = np.random.RandomState(3)
        xs = rng.rand(8, 2).tolist()
        ys = [np.sin(4 * x[0]) + x[1] for x in xs]
        gp = GaussianProcess(2, length_scale=0.3, noise=0.1)
        assert gp.fit(xs, ys)
        cands = rng.rand(50, 2).tolist()
        mus, sds = gp.predict_batch(cands)
        eis = gp.expected_improvement_batch(cands, max(ys))
        for i, c in enumerate(cands):
            mu, sd = gp.predict(c)
            assert mus[i] == pytest.approx(mu, abs=1e-10)
            assert sds[i] == pytest.approx(sd, abs=1e-10)
            assert eis[i] == pytest.approx(
                gp.expected_improvement(c, max(ys)), abs=1e-10)

    def test_predict_batch_requires_fit(self):
        gp = GaussianProcess(2)
        with pytest.raises(RuntimeError):
            gp.predict_batch([[0.1, 0.2]])


def _run_manager(pm, score_fn):
    while not pm.done:
        pm.record_sample(score_fn(pm.current))
    return pm


class TestParameterManager:
    def test_warmup_then_sample_then_freeze(self):
        initial = TunedParams(fusion_threshold_bytes=64 * MIB)
        pm = ParameterManager(initial, warmup_samples=3, max_samples=8)
        # Warmup windows keep the initial setting and are discarded.
        for _ in range(3):
            assert pm.warming_up
            assert pm.current == initial
            pm.record_sample(123.0)
        assert pm.samples_done == 0 and not pm.done
        _run_manager(pm, lambda p: 1.0)
        assert pm.done
        assert pm.samples_done == 8
        with pytest.raises(RuntimeError):
            pm.record_sample(1.0)

    def test_explores_distinct_configs_and_freezes_on_best(self):
        # Score peaks at 8 MiB; the frozen winner must be the best-scored
        # trial, and the proposal dedup must yield >= 5 distinct configs.
        def score(p):
            return -abs(np.log2(p.fusion_threshold_bytes) - 23.0)

        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=10)
        _run_manager(pm, score)
        configs = {p for p, _ in pm.history}
        assert len(configs) >= 5
        best_seen = max(pm.history, key=lambda t: t[1])
        assert pm.best == best_seen[0]
        assert pm.current == pm.best  # frozen

    def test_bounds_respected(self):
        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=12, tune_quant_block=True)
        _run_manager(pm, lambda p: 0.0)
        for p, _ in pm.history:
            assert MIB <= p.fusion_threshold_bytes <= 256 * MIB
            assert 64 <= p.quant_block <= 1024
            assert p.quant_block & (p.quant_block - 1) == 0  # pow2 snap

    def test_untuned_dims_stay_fixed(self):
        init = TunedParams(quant_block=192, hierarchical_allreduce=True)
        pm = ParameterManager(init, warmup_samples=0, max_samples=6,
                              tune_quant_block=False,
                              tune_hierarchical=False)
        _run_manager(pm, lambda p: 0.0)
        for p, _ in pm.history:
            assert p.quant_block == 192
            assert p.hierarchical_allreduce is True

    def test_deterministic_replay(self):
        def score(p):
            return float(np.log2(p.fusion_threshold_bytes))

        runs = []
        for _ in range(2):
            pm = ParameterManager(TunedParams(), warmup_samples=1,
                                  max_samples=7, seed=42)
            pm.record_sample(0.0)  # warmup
            _run_manager(pm, score)
            runs.append([p for p, _ in pm.history])
        assert runs[0] == runs[1]

    def test_csv_log_round_trip(self, tmp_path):
        path = str(tmp_path / "autotune.csv")
        pm = ParameterManager(TunedParams(), warmup_samples=2,
                              max_samples=5, log_path=path,
                              tune_quant_block=True)
        _run_manager(pm, lambda p: float(p.quant_block))
        rows = read_log(path)
        assert len(rows) == 5
        with open(path) as f:
            assert f.readline().strip() == ",".join(pm_mod.CSV_FIELDS)
        for row, (p, s) in zip(rows, pm.history):
            assert row["fusion_threshold_bytes"] == p.fusion_threshold_bytes
            assert row["quant_block"] == p.quant_block
            assert row["hierarchical_allreduce"] == p.hierarchical_allreduce
            assert row["zero_sharding"] == p.zero_sharding
            assert row["score_steps_per_sec"] == pytest.approx(s, rel=1e-5)
        assert [r["sample"] for r in rows] == list(range(1, 6))

    def test_csv_round_trip_with_tune_zero(self, tmp_path):
        """zero_sharding rides the CSV schema: a tune_zero session
        explores both values and read_log round-trips them typed."""
        path = str(tmp_path / "autotune_zero.csv")
        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=8, log_path=path,
                              tune_zero=True, seed=7)
        _run_manager(pm, lambda p: 2.0 if p.zero_sharding else 1.0)
        with open(path) as f:
            header = f.readline().strip()
        assert header == ",".join(pm_mod.CSV_FIELDS)
        assert "zero_sharding" in pm_mod.CSV_FIELDS
        rows = read_log(path)
        assert {r["zero_sharding"] for r in rows} == {False, True}
        for row, (p, _) in zip(rows, pm.history):
            assert row["zero_sharding"] == p.zero_sharding
        # the winner is the zero=True arm (scored 2.0)
        assert pm.best.zero_sharding is True

    def test_tune_zero_off_never_proposes_zero(self):
        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=6, seed=3)
        _run_manager(pm, lambda p: 1.0)
        assert all(not p.zero_sharding for p, _ in pm.history)


class TestTunedParams:
    def test_dict_round_trip(self):
        p = TunedParams(fusion_threshold_bytes=8 * MIB, quant_block=128,
                        hierarchical_allreduce=True)
        assert TunedParams.from_dict(p.as_dict()) == p

    def test_from_config(self):
        cfg = config_mod.Config(fusion_threshold_bytes=2 * MIB,
                                quant_block=512,
                                hierarchical_allreduce=True)
        p = TunedParams.from_config(cfg)
        assert p.fusion_threshold_bytes == 2 * MIB
        assert p.quant_block == 512
        assert p.hierarchical_allreduce is True


class TestPlanSchemaV5:
    """The v5 plan-encoded schema (docs/wire-plan.md): the GP searches
    the compact plan encoding, the CSV/cache carry it, and readers stay
    tolerant of v3/v4 artifacts without it."""

    def test_csv_v5_plan_column_round_trips(self, tmp_path):
        from horovod_tpu.plan import decode_tuned, encode_tuned

        path = str(tmp_path / "v5.csv")
        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=6, log_path=path,
                              tune_overlap=True, tune_zero=True, seed=11)
        _run_manager(pm, lambda p: 1.0 + p.num_comm_streams)
        with open(path) as f:
            header = f.readline().strip().split(",")
        assert header == list(pm_mod.CSV_FIELDS)
        # v12 appends the compile pair after the v5 plan column
        # (docs/compile.md); plan stays the last knob-derived column.
        assert header[-3:] == ["plan", "compile_ms", "compile_cache_hit"]
        rows = read_log(path)
        for row, (p, _) in zip(rows, pm.history):
            assert row["plan"] == encode_tuned(p)
            # The encoding decodes back to the very knobs in the row.
            d = decode_tuned(row["plan"])
            assert d["zero_stage"] == row["zero_stage"]
            assert d["overlap"] == row["overlap"]
            assert d["num_comm_streams"] == row["num_comm_streams"]
            assert d["hierarchical_allreduce"] == \
                row["hierarchical_allreduce"]

    def test_read_log_tolerant_of_v4_log_without_plan_column(
            self, tmp_path):
        path = tmp_path / "v4.csv"
        path.write_text(
            "sample,fusion_threshold_bytes,quant_block,"
            "hierarchical_allreduce,zero_sharding,zero_stage,overlap,"
            "num_comm_streams,score_steps_per_sec\n"
            "1,67108864,256,0,0,0,1,2,10.5\n"
            "2,8388608,256,1,0,0,0,1,11.0\n")
        rows = read_log(str(path))
        # The canonical encoding is re-derived from the knob columns.
        assert rows[0]["plan"] == "ar.flat|fp|s2|ovl"
        assert rows[1]["plan"] == "ar.tree|fp|s1|sync"

    def test_read_log_tolerant_of_v3_log(self, tmp_path):
        # Pre-v4: no zero_stage/overlap/streams; boolean zero_sharding
        # named stage 2.
        path = tmp_path / "v3.csv"
        path.write_text(
            "sample,fusion_threshold_bytes,quant_block,"
            "hierarchical_allreduce,zero_sharding,score_steps_per_sec\n"
            "1,67108864,256,0,1,9.0\n")
        rows = read_log(str(path))
        assert rows[0]["zero_stage"] == 2
        assert rows[0]["plan"] == "rs+ag.z2|fp|s1|sync"

    def test_cache_entry_carries_plan_and_version_key(self, tmp_path,
                                                      monkeypatch):
        from horovod_tpu.autotune import driver as at_driver
        from horovod_tpu.ops import kernel_autotune

        monkeypatch.setenv("HOROVOD_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        TestSession._reset_kernel_cache()
        key = cache_key_for("v9-schema-probe")
        assert key.endswith(f"|v{at_driver._CACHE_VERSION}")
        # v12: the per-trial compile pair joins the CSV
        # (docs/compile.md); v11 added pp_schedule (docs/pipeline.md);
        # v10 the serve pair (docs/serving.md); v9 the MoE pair;
        # v8 the pipeline pair; v7 the geometry-fingerprinted key.
        assert key.endswith("|v12")
        winner = TunedParams(fusion_threshold_bytes=8 * MIB,
                             zero_stage=2, overlap=True,
                             num_comm_streams=2)
        at_driver._store_cached_params(key, winner, score=12.0,
                                       samples=6, quantized=True)
        entry = kernel_autotune.cache_lookup(key)
        assert entry["plan"] == "rs+ag.z2|int8/256|s2|ovl"
        assert load_cached_params(key) == winner

    def test_load_tolerant_of_v4_entry_without_plan(self, tmp_path,
                                                    monkeypatch):
        from horovod_tpu.ops import kernel_autotune

        monkeypatch.setenv("HOROVOD_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        TestSession._reset_kernel_cache()
        # A v4-era entry: params lack overlap/num_comm_streams, no
        # `plan` field — from_dict defaults apply, nothing crashes.
        kernel_autotune.cache_store("legacy|v4", {
            "params": {"fusion_threshold_bytes": 4 * MIB,
                       "quant_block": 128,
                       "hierarchical_allreduce": True,
                       "zero_sharding": True},
            "score_steps_per_sec": 3.0, "samples": 5})
        p = load_cached_params("legacy|v4")
        assert p == TunedParams(fusion_threshold_bytes=4 * MIB,
                                quant_block=128,
                                hierarchical_allreduce=True,
                                zero_stage=2)

    def test_proposals_canonicalized_onto_plan(self):
        """Dead knobs snap to the plan's canonical value: streams pin
        to 1 with overlap off, hierarchical drops out under ZeRO's
        rs+ag split — equal plans dedup as ONE trial."""
        from horovod_tpu.plan import encode_tuned

        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=10, tune_zero=True,
                              tune_overlap=True, seed=5)
        _run_manager(pm, lambda p: 1.0)
        seen = set()
        for p, _ in pm.history:
            # Dedup key = snapped fusion threshold + the plan encoding:
            # no two trials may share it (equal wire = one recompile).
            key = pm._unit_key(p)
            assert key not in seen, \
                f"duplicate plan trial {encode_tuned(p)}"
            seen.add(key)
            if not p.overlap:
                assert p.num_comm_streams == 1
            if p.zero_stage > 0:
                assert p.hierarchical_allreduce is False

    def test_canonicalize_collapses_dead_knob_pairs(self):
        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=1)
        a = pm._canonicalize(TunedParams(overlap=False,
                                         num_comm_streams=4))
        b = pm._canonicalize(TunedParams(overlap=False,
                                         num_comm_streams=1))
        assert a == b
        z = pm._canonicalize(TunedParams(zero_stage=2,
                                         hierarchical_allreduce=True))
        assert z.hierarchical_allreduce is False
        assert pm._unit_key(a) == pm._unit_key(b)


class TestWarmStart:
    """Cost-model warm start (docs/cost-model.md): the GP seeded with
    the planner's priced shortlist converges in ≤ half the trials of
    the cold search on the 2x4 CPU-mesh quadratic-basin fixture (the
    score surface IS the negated predicted-ms — the model-is-right
    world the warm start is built for)."""

    PAYLOAD = 32 * MIB
    MESH = (2, 4)

    def _score(self, p):
        from horovod_tpu.plan import describe_plan, price_step

        sp = describe_plan(tuned_params=p, quantized=True,
                           mesh_shape=self.MESH, quantized_pod=False)
        return -price_step(sp, self.PAYLOAD,
                           mesh_shape=self.MESH).predicted_ms

    def test_seeds_walk_in_order_before_gp(self):
        seeds = [TunedParams(fusion_threshold_bytes=2 * MIB),
                 TunedParams(fusion_threshold_bytes=16 * MIB,
                             overlap=True, num_comm_streams=2)]
        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=6, tune_overlap=True,
                              seeds=seeds)
        assert pm.seeded == 2
        trial_order = [pm.current]
        while not pm.done:
            pm.record_sample(1.0)
            if not pm.done:
                trial_order.append(pm.current)
        # Trial 0 is the initial setting; trials 1..2 are the seeds in
        # the given (predicted-ms) order; the GP takes over after.
        assert trial_order[0] == TunedParams()
        assert trial_order[1] == seeds[0]
        assert trial_order[2] == seeds[1]

    def test_seeds_equal_to_initial_or_duplicates_collapse(self):
        dup = TunedParams()
        pm = ParameterManager(TunedParams(), warmup_samples=0,
                              max_samples=3,
                              seeds=[dup, dup,
                                     TunedParams(
                                         fusion_threshold_bytes=MIB)])
        assert pm.seeded == 1  # initial + repeat collapse away

    def test_warm_start_converges_in_half_the_cold_trials(self):
        from horovod_tpu.plan import shortlist

        initial = TunedParams(fusion_threshold_bytes=1 * MIB)

        def run(pm):
            while not pm.done:
                pm.record_sample(self._score(pm.current))
            return pm

        cold = run(ParameterManager(
            initial, warmup_samples=0, max_samples=20,
            tune_quant_block=True, tune_overlap=True, seed=42))
        seeds = [pp.params for pp in shortlist(
            self.PAYLOAD, mesh_shape=self.MESH, quantized=True,
            tune_overlap=True, initial=initial, k=5)]
        warm = run(ParameterManager(
            initial, warmup_samples=0, max_samples=9,
            tune_quant_block=True, tune_overlap=True, seed=42,
            seeds=seeds))
        # ≤ half the cold trial budget, and at least as good a winner.
        assert len(warm.history) <= len(cold.history) // 2
        assert warm.best_score >= cold.best_score - 1e-9
        # The priced shortlist hits the basin immediately: within 2% of
        # the winner by trial 2 (trial 1 is the deliberately-bad
        # initial), where the cold search needs many times that.
        target = warm.best_score - abs(warm.best_score) * 0.02

        def first_hit(pm):
            for i, (_, s) in enumerate(pm.history):
                if s >= target:
                    return i + 1
            return len(pm.history) + 1

        assert first_hit(warm) <= 2
        assert first_hit(cold) > 2 * first_hit(warm)

    def test_session_warm_start_budget_and_fields(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("HOROVOD_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        TestSession._reset_kernel_cache()
        tree = {"w": jnp.ones((4096,), jnp.float32)}
        built = []

        def make_step(tuned):
            built.append(tuned)
            return _toy_make_step(tuned)

        res = autotune_session(
            make_step, cache_key=tree, enabled=True, warmup_samples=0,
            steps_per_sample=2, tune_hierarchical=False, warm_start=3)
        assert res.warm_start > 0
        assert res.shortlist  # the priced rows ride the result
        for row in res.shortlist:
            assert "predicted_ms" in row and "plan" in row
        # Budget shrinks to seeds + 4 refinement windows.
        assert res.samples <= res.warm_start + 4
        # The v7 cache entry records the winner's predicted_ms.
        from horovod_tpu.ops import kernel_autotune

        entry = kernel_autotune.cache_lookup(cache_key_for(tree))
        assert entry is not None
        assert "predicted_ms" in entry
        assert "geometry" in entry

    def test_string_cache_key_falls_back_cold(self, caplog):
        with caplog.at_level(logging.WARNING,
                             logger="horovod_tpu.autotune"):
            res = autotune_session(
                lambda t: _toy_make_step(t), cache_key=None,
                enabled=True, warmup_samples=0, steps_per_sample=1,
                max_samples=3, tune_hierarchical=False, warm_start=4)
        assert res.warm_start == 0 and res.shortlist == ()
        assert any("cold search" in r.message for r in caplog.records)

    def test_explicit_seed_list(self):
        seeds = [TunedParams(fusion_threshold_bytes=8 * MIB)]
        res = autotune_session(
            lambda t: _toy_make_step(t), enabled=True,
            warmup_samples=0, steps_per_sample=1, max_samples=3,
            tune_hierarchical=False, warm_start=seeds)
        assert res.warm_start == 1
        assert any(p.fusion_threshold_bytes == 8 * MIB
                   for p, _ in res.history)


class TestCacheSchemaV7:
    """v7 = geometry-fingerprinted keys + stored predicted_ms
    (docs/cost-model.md); v8 = the pipeline pair (docs/pipeline.md);
    v9 = the MoE pair (docs/moe.md); v11 = the pp_schedule knob
    (docs/pipeline.md); reads stay tolerant of older entries."""

    def test_key_carries_geometry_fingerprint(self):
        key = cache_key_for("geo-probe")
        geo = basics.mesh_geometry()
        assert f"|{geo}|" in key
        assert key.endswith("|v12")

    def test_load_tolerant_of_v6_entry(self, tmp_path, monkeypatch):
        from horovod_tpu.ops import kernel_autotune

        monkeypatch.setenv("HOROVOD_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        TestSession._reset_kernel_cache()
        # A v6-era entry: params carry fused, but no geometry /
        # predicted_ms fields — reads cleanly.
        kernel_autotune.cache_store("legacy|v6", {
            "params": {"fusion_threshold_bytes": 2 * MIB,
                       "quant_block": 256,
                       "hierarchical_allreduce": False,
                       "zero_stage": 2, "overlap": True,
                       "num_comm_streams": 2, "fused": True},
            "plan": "rs+ag.z2|int8/256|s2|ovl|pl",
            "score_steps_per_sec": 5.0, "samples": 9})
        p = load_cached_params("legacy|v6")
        assert p == TunedParams(fusion_threshold_bytes=2 * MIB,
                                zero_stage=2, overlap=True,
                                num_comm_streams=2, fused=True)

    def test_load_tolerant_of_v5_entry(self, tmp_path, monkeypatch):
        from horovod_tpu.ops import kernel_autotune

        monkeypatch.setenv("HOROVOD_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        TestSession._reset_kernel_cache()
        # v5: no fused knob at all — defaults to False (the exact
        # pre-v6 wire).
        kernel_autotune.cache_store("legacy|v5", {
            "params": {"fusion_threshold_bytes": 4 * MIB,
                       "quant_block": 128,
                       "hierarchical_allreduce": True,
                       "zero_stage": 0, "overlap": False,
                       "num_comm_streams": 1},
            "plan": "ar.tree|int8/128|s1|sync",
            "score_steps_per_sec": 3.0, "samples": 5})
        p = load_cached_params("legacy|v5")
        assert p == TunedParams(fusion_threshold_bytes=4 * MIB,
                                quant_block=128,
                                hierarchical_allreduce=True)
        assert p.fused is False


def _toy_make_step(tuned, sleep_by_threshold=None):
    """A compiled toy step honoring the TunedParams override: fused
    allreduce of a small gradient tree through the real bucket planner
    (eager data plane, world of one — tier-1, no TPU)."""
    tree = {"w": jnp.ones((256,), jnp.float32),
            "b": jnp.ones((8,), jnp.float32)}
    state = {"t": tree}

    def step():
        state["t"] = fusion.allreduce_pytree(
            state["t"], op=hvd.Sum, tuned_params=tuned)
        if sleep_by_threshold is not None:
            import time

            time.sleep(sleep_by_threshold(tuned))
        return state["t"]

    return step


class TestSession:
    def test_disabled_knob_is_noop(self, tmp_path, monkeypatch):
        calls = []

        def make_step(tuned):
            calls.append(tuned)
            return lambda: jnp.zeros(())

        res = autotune_session(make_step, enabled=False)
        assert isinstance(res, AutotuneResult)
        assert res.params == TunedParams.from_config(basics.config())
        assert res.history == () and not res.cache_hit
        assert calls == []  # no trial ever built

    def test_session_converges_writes_log_and_cache(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("HOROVOD_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        self._reset_kernel_cache()
        log_path = str(tmp_path / "tune.csv")

        # Favor small fusion thresholds: score = -log2(threshold) via a
        # deterministic sleep per step.
        def sleep_by_threshold(p):
            return np.log2(p.fusion_threshold_bytes) * 2e-4

        built = []

        def make_step(tuned):
            built.append(tuned)
            return _toy_make_step(tuned, sleep_by_threshold)

        res = autotune_session(
            make_step, cache_key="toy-e2e", enabled=True,
            warmup_samples=1, steps_per_sample=3, max_samples=6,
            tune_hierarchical=False,  # the toy step runs eagerly
            log_path=log_path)
        assert not res.cache_hit
        assert res.samples == 6
        # Explores >= 5 candidate configs (ISSUE acceptance criterion).
        assert len({p for p, _ in res.history}) >= 5
        # Converged to the best-scored trial (small thresholds win).
        best = max(res.history, key=lambda t: t[1])
        assert res.params == best[0]
        # CSV written with one row per scored sample.
        assert len(read_log(log_path)) == 6
        # Warm-start cache holds the winner...
        key = cache_key_for("toy-e2e")
        assert load_cached_params(key) == res.params
        # ...and a rerun skips every trial.
        built.clear()
        res2 = autotune_session(make_step, cache_key="toy-e2e",
                                enabled=True)
        assert res2.cache_hit
        assert res2.params == res.params
        assert built == []  # zero rebuilds, zero trials

    def test_failing_trial_scores_zero_not_abort(self):
        # A candidate that cannot compile/run (e.g. OOM at a huge
        # threshold) must not kill the session: it scores 0 and the
        # search continues elsewhere.
        def make_step(tuned):
            if tuned.fusion_threshold_bytes > 32 * MIB:
                raise MemoryError("synthetic compile OOM")
            return _toy_make_step(tuned)

        res = autotune_session(
            make_step, enabled=True, warmup_samples=0,
            steps_per_sample=2, max_samples=6, tune_hierarchical=False,
            initial=TunedParams(fusion_threshold_bytes=4 * MIB))
        assert res.samples == 6
        failed = [s for p, s in res.history
                  if p.fusion_threshold_bytes > 32 * MIB]
        ok = [s for p, s in res.history
              if p.fusion_threshold_bytes <= 32 * MIB]
        assert all(s == 0.0 for s in failed)
        assert ok and all(s > 0.0 for s in ok)
        assert res.params.fusion_threshold_bytes <= 32 * MIB

    def test_session_emits_timeline_events(self, monkeypatch):
        events = []

        class FakeTimeline:
            def instant(self, name, tid=None, args=None):
                events.append((name, args))

        monkeypatch.setattr(basics._state, "timeline", FakeTimeline())
        res = autotune_session(
            lambda tuned: _toy_make_step(tuned), enabled=True,
            warmup_samples=1, steps_per_sample=2, max_samples=3,
            tune_hierarchical=False)
        names = [n for n, _ in events]
        assert names[0] == "AUTOTUNE:SESSION_START"
        # One instant per window: 1 warmup + 3 scored.
        assert names.count("AUTOTUNE:SAMPLE") == 4
        samples = [a for n, a in events if n == "AUTOTUNE:SAMPLE"]
        assert samples[0]["warmup"] is True
        assert all("score_steps_per_sec" in a and
                   "fusion_threshold_bytes" in a for a in samples)
        assert names[-1] == "AUTOTUNE:CONVERGED"
        assert events[-1][1]["fusion_threshold_bytes"] == \
            res.params.fusion_threshold_bytes

    def test_cache_key_separates_mesh_and_model(self):
        k1 = cache_key_for({"w": jnp.zeros((4, 4))})
        k2 = cache_key_for({"w": jnp.zeros((4, 8))})
        k3 = cache_key_for({"v": jnp.zeros((4, 4))})
        assert len({k1, k2, k3}) == 3
        assert k1 == cache_key_for({"w": jnp.zeros((4, 4))})
        assert "mesh" in k1 and "world" in k1

    @staticmethod
    def _reset_kernel_cache():
        from horovod_tpu.ops import kernel_autotune

        with kernel_autotune._lock:
            kernel_autotune._mem.clear()
            kernel_autotune._loaded = False

    def test_sessions_counter_and_shutdown_warning(self, caplog):
        # HOROVOD_AUTOTUNE=1 with no session must warn once at shutdown
        # (the knob is otherwise a trace-time no-op); a session suppresses
        # the warning. Tested at the helper level so the live test world
        # stays up.
        from horovod_tpu.autotune import driver as at_driver

        cfg_on = config_mod.Config(autotune=True)
        monkey_sessions = at_driver._sessions_run[0]
        basics._autotune_unused_warned[0] = False
        try:
            at_driver._sessions_run[0] = 0
            with caplog.at_level(logging.WARNING,
                                 logger="horovod_tpu.autotune"):
                basics._warn_autotune_unused(cfg_on)
            assert any("no tuning session" in r.message
                       for r in caplog.records)
            # One warning per process.
            n = len(caplog.records)
            basics._warn_autotune_unused(cfg_on)
            assert len(caplog.records) == n
            # With a session run, no warning.
            caplog.clear()
            basics._autotune_unused_warned[0] = False
            at_driver._sessions_run[0] = 3
            basics._warn_autotune_unused(cfg_on)
            assert not caplog.records
            # Knob off: never warns.
            at_driver._sessions_run[0] = 0
            basics._warn_autotune_unused(config_mod.Config(autotune=False))
            assert not caplog.records
        finally:
            at_driver._sessions_run[0] = monkey_sessions
            basics._autotune_unused_warned[0] = True


class TestTunedParamsOverride:
    def test_override_matches_env_config_bucket_plan(self, monkeypatch):
        """The tuned override and the hand-set env knobs must steer the
        SAME trace-time decisions: identical bucket plans (the cache-key
        soundness contract) and identical reductions."""
        leaves = [jnp.ones((1000,), jnp.float32) for _ in range(6)]
        tuned = TunedParams(fusion_threshold_bytes=8192)
        plan_tuned = fusion.plan_buckets(
            leaves, threshold_bytes=tuned.fusion_threshold_bytes)
        cfg = dataclasses.replace(basics.config(),
                                  fusion_threshold_bytes=8192)
        monkeypatch.setattr(basics._state, "config", cfg)
        plan_env = fusion.plan_buckets(leaves, threshold_bytes=None)
        assert plan_tuned == plan_env
        assert len(plan_tuned) == 3  # 2048-elem cap -> 2 leaves/bucket

    def test_override_reduction_bit_identical_to_env(self, monkeypatch):
        rs = np.random.RandomState(7)
        tree = {"a": jnp.asarray(rs.randn(500), jnp.float32),
                "b": jnp.asarray(rs.randn(33), jnp.float32)}
        tuned = TunedParams(fusion_threshold_bytes=1024,
                            hierarchical_allreduce=False)
        out_tuned = fusion.allreduce_pytree(tree, op=hvd.Sum,
                                            tuned_params=tuned)
        cfg = dataclasses.replace(
            basics.config(), fusion_threshold_bytes=1024,
            hierarchical_allreduce=False)
        monkeypatch.setattr(basics._state, "config", cfg)
        out_env = fusion.allreduce_pytree(tree, op=hvd.Sum)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(out_tuned[k]),
                                          np.asarray(out_env[k]))

    def test_compiled_2x4_tuned_vs_env_bit_identical(self, monkeypatch):
        """Compiled smoke on the emulated 2-host x 4-chip mesh: a step
        built with tuned_params= must produce bit-identical reductions to
        one built under the equivalent hand-set env config."""
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    hvd.HVD_AXES)
        rs = np.random.RandomState(11)
        tree = {"w": jnp.asarray(rs.randn(8, 40, 3), jnp.float32),
                "b": jnp.asarray(rs.randn(8, 7), jnp.float32)}
        tuned = TunedParams(fusion_threshold_bytes=2 * MIB,
                            quant_block=128,
                            hierarchical_allreduce=True)

        def run(tp):
            def f(t):
                local = jax.tree.map(lambda v: v[0], t)
                return fusion.allreduce_pytree(local, op=hvd.Sum,
                                               tuned_params=tp)

            return hvd.shard_map(f, mesh=mesh, in_specs=P(hvd.HVD_AXES),
                                 out_specs=P())(tree)

        out_tuned = run(tuned)
        cfg = dataclasses.replace(
            basics.config(), fusion_threshold_bytes=2 * MIB,
            quant_block=128, hierarchical_allreduce=True)
        monkeypatch.setattr(basics._state, "config", cfg)
        out_env = run(None)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(out_tuned[k]),
                                          np.asarray(out_env[k]))


class TestConfigRoundTrip:
    """Three-layer contract: every --autotune-* CLI flag and YAML key
    must land in Config with the same value (the env plumbing the
    reference converges on; runner/config_parser.py)."""

    AUTOTUNE_ARGS = {
        "autotune": True,
        "autotune_log_file": "/tmp/at.csv",
        "autotune_warmup_samples": 5,
        "autotune_steps_per_sample": 7,
        "autotune_bayes_opt_max_samples": 11,
        "autotune_gaussian_process_noise": 0.25,
    }
    CONFIG_FIELDS = {
        "autotune": "autotune",
        "autotune_log_file": "autotune_log",
        "autotune_warmup_samples": "autotune_warmup_samples",
        "autotune_steps_per_sample": "autotune_steps_per_sample",
        "autotune_bayes_opt_max_samples": "autotune_bayes_opt_max_samples",
        "autotune_gaussian_process_noise":
            "autotune_gaussian_process_noise",
    }

    def _assert_lands_in_config(self, args, monkeypatch):
        env = {}
        config_parser.set_env_from_args(env, args)
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        cfg = config_mod.from_env()
        for attr, field in self.CONFIG_FIELDS.items():
            assert getattr(cfg, field) == self.AUTOTUNE_ARGS[attr], field

    def test_cli_flags_round_trip(self, monkeypatch):
        # Every --autotune-* flag the launcher defines maps onto an env
        # var (guards against adding a flag without wiring it).
        from horovod_tpu.runner import launch

        cli = ["--autotune", "--autotune-log-file", "/tmp/at.csv",
               "--autotune-warmup-samples", "5",
               "--autotune-steps-per-sample", "7",
               "--autotune-bayes-opt-max-samples", "11",
               "--autotune-gaussian-process-noise", "0.25"]
        args = launch.parse_args(cli + ["-np", "1", "true"])
        for attr, want in self.AUTOTUNE_ARGS.items():
            assert getattr(args, attr) == want, attr
            assert attr in config_parser._ARG_ENV or attr == "autotune", \
                f"{attr} missing from config_parser._ARG_ENV"
        self._assert_lands_in_config(args, monkeypatch)

    def test_yaml_keys_round_trip(self, tmp_path, monkeypatch):
        yaml_text = (
            "autotune:\n"
            "  enabled: true\n"
            "  log-file: /tmp/at.csv\n"
            "  warmup-samples: 5\n"
            "  steps-per-sample: 7\n"
            "  bayes-opt-max-samples: 11\n"
            "  gaussian-process-noise: 0.25\n")
        path = tmp_path / "hvd.yaml"
        path.write_text(yaml_text)
        args = argparse.Namespace(
            **{a: None for a in self.AUTOTUNE_ARGS})
        args.autotune = None
        config_parser.parse_config_file(str(path), args)
        for attr, want in self.AUTOTUNE_ARGS.items():
            assert getattr(args, attr) == want, attr
        self._assert_lands_in_config(args, monkeypatch)
