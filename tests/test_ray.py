"""Ray executor tests against the in-process fake ray (tests/fake_ray.py).

Reference analogue: test/single/test_ray.py + test_ray_elastic.py run a real
`ray.init()` local cluster; ray is not installable here, so the fake
reproduces the API surface (actors, subprocess-isolated tasks, placement
groups) and the assertions mirror the reference's: placement/colocation for
RayExecutor, and an elastic job surviving a killed worker for
ElasticRayExecutor.
"""

import json
import os
import sys
import time

import cloudpickle
import pytest

import fake_ray
from horovod_tpu.elastic import constants
from horovod_tpu.elastic.discovery import FixedHosts
from horovod_tpu.ray import ElasticRayExecutor, RayExecutor

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture
def ray_env(monkeypatch):
    fake_ray.install(monkeypatch)
    # Actors run as threads in this process; restore env they touch.
    saved = dict(os.environ)
    yield
    os.environ.clear()
    os.environ.update(saved)


class TestRayExecutorPlacement:
    def test_colocated_hosts_strict_spread(self, ray_env):
        ex = RayExecutor(num_hosts=2, num_workers_per_host=2,
                         cpus_per_worker=3)
        ex.start()
        try:
            assert len(fake_ray.CREATED_PLACEMENT_GROUPS) == 1
            pg = fake_ray.CREATED_PLACEMENT_GROUPS[0]
            # One bundle per host sized for that host's workers
            # (reference NodeColocator, ray/runner.py:48-110).
            assert pg.bundles == [{"CPU": 6}, {"CPU": 6}]
            assert pg.strategy == "STRICT_SPREAD"
            idx = [o["scheduling_strategy"].placement_group_bundle_index
                   for o in fake_ray.ACTOR_OPTIONS
                   if "scheduling_strategy" in o]
            assert idx == [0, 0, 1, 1]
            assert [o["num_cpus"] for o in fake_ray.ACTOR_OPTIONS] == [3] * 4
            # All workers execute
            assert ex.run(lambda: 7) == [7, 7, 7, 7]
        finally:
            ex.shutdown()
        assert pg.removed

    def test_flat_pack(self, ray_env):
        ex = RayExecutor(num_workers=3, cpus_per_worker=1)
        ex.start()
        try:
            pg = fake_ray.CREATED_PLACEMENT_GROUPS[0]
            assert pg.bundles == [{"CPU": 1}] * 3
            assert pg.strategy == "PACK"
            idx = [o["scheduling_strategy"].placement_group_bundle_index
                   for o in fake_ray.ACTOR_OPTIONS
                   if "scheduling_strategy" in o]
            assert idx == [0, 1, 2]
        finally:
            ex.shutdown()

    def test_env_contract_and_controller_port(self, ray_env):
        ex = RayExecutor(num_workers=2)
        ex.start()
        try:
            # Workers run in-process threads here, so the env contract
            # lands in os.environ: rank/size plus a controller address
            # chosen on the rank-0 worker's host.
            assert os.environ["HOROVOD_SIZE"] == "2"
            assert "HOROVOD_CONTROLLER_ADDR" in os.environ
            assert int(os.environ["HOROVOD_CONTROLLER_PORT"]) > 0
        finally:
            ex.shutdown()


def _read_log(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line.strip()))
    return out


def make_worker_fn(log_file, batches, exit_at=None):
    """Elastic worker body (subprocess): trains a toy loop under
    hvd.elastic.run with a real collective per step, logging JSON lines —
    the reference's integration worker pattern (elastic_common.py)."""

    def _worker():
        import json as _json
        import os as _os
        import time as _time

        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu import elastic

        identity = (f"{_os.environ['HOROVOD_HOSTNAME']}:"
                    f"{_os.environ['HOROVOD_LOCAL_RANK']}")
        crash_at = None
        if exit_at:
            h, lr, b = exit_at.rsplit(":", 2)
            if identity == f"{h}:{lr}":
                crash_at = int(b)

        def log(rec):
            rec["identity"] = identity
            with open(log_file, "a") as f:
                f.write(_json.dumps(rec) + "\n")

        @elastic.run
        def train(state):
            while state.batch < batches:
                total = hvd.allreduce(jnp.full((4,), 1.0), op=hvd.Sum,
                                      name=f"rayel.{state.batch}")
                assert np.allclose(total, hvd.size())
                state.batch += 1
                if crash_at is not None and state.batch == crash_at:
                    _os._exit(1)
                log({"rank": int(hvd.rank()), "size": int(hvd.size()),
                     "batch": int(state.batch)})
                state.commit()
                _time.sleep(0.15)

        state = elastic.ObjectState(batch=0)
        train(state)
        log({"rank": int(hvd.rank()), "size": int(hvd.size()), "done": True})
        return 0

    return _worker


class TestElasticRay:
    @pytest.fixture(autouse=True)
    def _fast_discovery(self, monkeypatch):
        monkeypatch.setattr(constants, "DISCOVER_HOSTS_FREQUENCY_SECS", 0.25)

    def test_survives_killed_worker(self, ray_env, tmp_path):
        """3 slots on 2 (fake) hosts; the hostB worker hard-crashes at
        batch 3. The driver must blacklist hostB and the survivors finish
        in a world of 2 (reference: test_ray_elastic.py fault cases)."""
        log_file = str(tmp_path / "log.jsonl")
        fake_ray.TASK_ENV.update({
            "HOROVOD_START_TIMEOUT": "30",
            "PYTHONPATH": os.pathsep.join(
                [fake_ray.REPO, os.path.join(fake_ray.REPO, "tests")]),
        })
        ex = ElasticRayExecutor(
            min_np=2, max_np=3,
            override_discovery=FixedHosts({"hostA": 2, "hostB": 1}),
            controller_addr_override="127.0.0.1")
        ok = ex.run(make_worker_fn(log_file, batches=6,
                                   exit_at="hostB:0:3"))
        records = _read_log(log_file)
        assert ok, records
        assert ex.driver.host_manager.is_blacklisted("hostB")
        done = [r for r in records if r.get("done")]
        assert len(done) == 2, records
        assert all(r["size"] == 2 for r in done), done
        b_records = [r for r in records
                     if r["identity"] == "hostB:0" and "batch" in r]
        assert all(r["batch"] < 3 for r in b_records), b_records
        # Every task was pinned to its slot's node and carried no static
        # rank env (rank/size must come from rendezvous).
        assert any("resources" in o and
                   any(k.startswith("node:") for k in o["resources"])
                   for o in fake_ray.TASK_OPTIONS)

    def test_elastic_env_wiring(self, ray_env, tmp_path):
        """The actor env must contain the driver-service coordinates and
        no pre-baked rank/size (round-1 verdict #3 / ADVICE medium)."""
        captured = {}

        class _StubDriver:
            service_port = 12345
            key = b"\x01\x02"

            def start(self, fn):
                captured["create_worker"] = fn

            def join(self):
                return True

            def stop(self):
                pass

            def shutdown_service(self):
                pass

        ex = ElasticRayExecutor(min_np=1)
        ex.driver = _StubDriver()

        # Intercept the task launch to capture the env instead of running.
        sent = {}

        def fake_worker():
            return 0

        ok = None

        import fake_ray as fr

        orig_remote = fr._RemoteFunction.remote

        def capture_remote(self_rf, *args, **kwargs):
            sent["env"] = args[0]
            fut = fr._Future()
            fut.set_result(0)
            return fr.ObjectRef(fut)

        fr._RemoteFunction.remote = capture_remote
        try:
            ok = ex.run(fake_worker)
            assert ok is True
            create_worker = captured["create_worker"]

            from horovod_tpu.runner.hosts import SlotInfo

            slot = SlotInfo(hostname="hostX", rank=0, local_rank=1,
                            cross_rank=0, size=2, local_size=2,
                            cross_size=1)
            assert create_worker(slot, 0) == 0
        finally:
            fr._RemoteFunction.remote = orig_remote
        env = sent["env"]
        assert env["HOROVOD_ELASTIC"] == "1"
        assert env["HOROVOD_ELASTIC_DRIVER_PORT"] == "12345"
        assert env["HOROVOD_ELASTIC_DRIVER_KEY"] == "0102"
        assert env["HOROVOD_HOSTNAME"] == "hostX"
        assert env["HOROVOD_LOCAL_RANK"] == "1"
        assert "HOROVOD_RANK" not in env
        assert "HOROVOD_SIZE" not in env
