"""Sequence/context parallelism tests on the 8-device virtual mesh.

Ring attention and Ulysses all-to-all attention must be numerically exact
against dense attention over the full sequence (they are exact algorithms,
not approximations), including causal masking across shard boundaries.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.parallel import sequence as seqpar


def _qkv(B=2, T=64, H=4, D=16, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(B, T, H, D), jnp.float32) * 0.3
    return mk(), mk(), mk()


def _shard_seq(fn, mesh, n_out=1):
    """Run fn inside shard_map with arrays sharded on seq dim over the full
    world (both mesh axes)."""
    spec = P(None, hvd.HVD_AXES)
    return jax.jit(hvd.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv()
        expect = seqpar.dense_attention(q, k, v, causal=causal)
        mesh = hvd.mesh()

        out = _shard_seq(
            lambda a, b, c: seqpar.ring_attention(
                a, b, c, axis=hvd.HVD_AXES, causal=causal),
            mesh)(q, k, v)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_local_axis_only(self):
        """Ring over just the intra-host (ICI) axis; batch stays whole."""
        q, k, v = _qkv(T=32)
        expect = seqpar.dense_attention(q, k, v, causal=True)
        mesh = hvd.mesh()
        spec = P(None, hvd.LOCAL_AXIS)
        out = jax.jit(hvd.shard_map(
            lambda a, b, c: seqpar.ring_attention(a, b, c,
                                                  axis=hvd.LOCAL_AXIS),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        ))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_world_of_one_fallback(self):
        q, k, v = _qkv(T=16)
        out = seqpar.ring_attention(q, k, v, axis=())
        expect = seqpar.dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=1e-5)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(H=8)  # heads divisible by world (8)
        expect = seqpar.dense_attention(q, k, v, causal=causal)
        mesh = hvd.mesh()
        out = _shard_seq(
            lambda a, b, c: seqpar.ulysses_attention(
                a, b, c, axis=hvd.HVD_AXES, causal=causal),
            mesh)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_indivisible_heads_rejected(self):
        q, k, v = _qkv(H=6)
        mesh = hvd.mesh()
        with pytest.raises(ValueError, match="divisible"):
            _shard_seq(
                lambda a, b, c: seqpar.ulysses_attention(
                    a, b, c, axis=hvd.HVD_AXES),
                mesh)(q, k, v)


class TestGPTSequenceParallel:
    def test_ring_gpt_matches_dense_gpt(self):
        """Full model forward: sequence-parallel GPT == single-device GPT."""
        cfg_d = gpt_tiny(dtype=jnp.float32)
        cfg_r = gpt_tiny(dtype=jnp.float32, attention="ring",
                         seq_axis=hvd.HVD_AXES)
        B, T = 2, 64
        rs = np.random.RandomState(0)
        tokens = jnp.asarray(rs.randint(0, cfg_d.vocab_size, (B, T)))

        model_d = GPT(cfg_d)
        variables = model_d.init(jax.random.PRNGKey(0), tokens)
        expect = model_d.apply(variables, tokens)

        model_r = GPT(cfg_r)
        mesh = hvd.mesh()
        out = jax.jit(hvd.shard_map(
            lambda v, t: model_r.apply(v, t),
            mesh=mesh, in_specs=(P(), P(None, hvd.HVD_AXES)),
            out_specs=P(None, hvd.HVD_AXES),
        ))(variables, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=5e-4, atol=5e-4)

    def test_ulysses_gpt_matches_dense_gpt(self):
        cfg_d = gpt_tiny(dtype=jnp.float32, num_heads=8, d_model=64)
        cfg_u = gpt_tiny(dtype=jnp.float32, num_heads=8, d_model=64,
                         attention="ulysses", seq_axis=hvd.HVD_AXES)
        B, T = 2, 64
        rs = np.random.RandomState(1)
        tokens = jnp.asarray(rs.randint(0, cfg_d.vocab_size, (B, T)))

        model_d = GPT(cfg_d)
        variables = model_d.init(jax.random.PRNGKey(0), tokens)
        expect = model_d.apply(variables, tokens)

        mesh = hvd.mesh()
        out = jax.jit(hvd.shard_map(
            lambda v, t: GPT(cfg_u).apply(v, t),
            mesh=mesh, in_specs=(P(), P(None, hvd.HVD_AXES)),
            out_specs=P(None, hvd.HVD_AXES),
        ))(variables, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                                   rtol=5e-4, atol=5e-4)

    def test_dp_sp_training_step(self):
        """2-D parallelism: data parallel over hvd_cross, sequence parallel
        over hvd_local — one full training step with the
        DistributedOptimizer (grads psum over the DP axis only)."""
        import optax

        cfg = gpt_tiny(dtype=jnp.float32, attention="ring",
                       seq_axis=hvd.LOCAL_AXIS, remat=True)
        mesh = hvd.mesh()
        n_dp = mesh.devices.shape[0]
        B, T = 2 * n_dp, 32
        rs = np.random.RandomState(2)
        tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
        targets = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))

        model = GPT(cfg)
        variables = model.init(jax.random.PRNGKey(0), tokens[:1])
        # Grads vary along BOTH axes (different batch shards over cross,
        # different token shards over local) → average over the full world.
        tx = hvd.DistributedOptimizer(optax.adam(1e-3))
        opt_state = tx.init(variables["params"])

        def loss_fn(params, tok, tgt):
            logits = model.apply({"params": params}, tok)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

        def spmd(params, opt_state, tok, tgt):
            loss, grads = hvd.value_and_grad(loss_fn)(params, tok, tgt)
            updates, new_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            loss = hvd.allreduce(loss)
            return params, new_state, loss

        step = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(), P(hvd.CROSS_AXIS, hvd.LOCAL_AXIS),
                      P(hvd.CROSS_AXIS, hvd.LOCAL_AXIS)),
            out_specs=(P(), P(), P())))
        params, opt_state, loss = step(variables["params"], opt_state,
                                       tokens, targets)
        assert np.isfinite(float(loss))
        # one more step to ensure state threading works
        params, opt_state, loss2 = step(params, opt_state, tokens, targets)
        assert np.isfinite(float(loss2))
        assert float(loss2) < float(loss)
