"""PyTorch binding tests.

Reference analogue: test/parallel/test_torch.py (op matrix, handle API,
optimizer wrapping, state broadcast) run as single-process semantics checks
plus real multi-process workers over localhost TCP (SURVEY §4).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest
import torch

import horovod_tpu.torch as hvd_torch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "torch_worker.py")


class TestOpsSingleProcess:
    """World-of-one semantics (every op must be exact identity modulo
    scaling, reference test_torch.py runs the same matrix at np=1)."""

    def test_allreduce_identity(self):
        t = torch.arange(6, dtype=torch.float32)
        assert torch.allclose(hvd_torch.allreduce(t), t)
        assert torch.allclose(hvd_torch.allreduce(t, op=hvd_torch.Sum), t)

    def test_allreduce_scaling(self):
        t = torch.ones(4)
        out = hvd_torch.allreduce(t, op=hvd_torch.Sum, prescale_factor=3.0)
        assert torch.allclose(out, torch.full((4,), 3.0))

    def test_allreduce_product_scaling(self):
        # Pre/postscale must apply for op=Product at np=1 too (the native
        # core applies them around the reduction for every op).
        t = torch.full((4,), 2.0)
        out = hvd_torch.allreduce(t, op=hvd_torch.Product,
                                  prescale_factor=2.0)
        assert torch.allclose(out, torch.full((4,), 4.0))

    def test_allreduce_inplace(self):
        t = torch.ones(4)
        out = hvd_torch.allreduce_(t, op=hvd_torch.Sum, postscale_factor=2.0)
        assert out is t
        assert torch.allclose(t, torch.full((4,), 2.0))

    def test_allreduce_grad(self):
        x = torch.ones(3, requires_grad=True)
        y = hvd_torch.allreduce(x).sum()
        y.backward()
        assert torch.allclose(x.grad, torch.ones(3))

    def test_allreduce_average_op_conflict(self):
        with pytest.raises(ValueError):
            hvd_torch.allreduce(torch.ones(2), average=True, op=hvd_torch.Sum)

    def test_allreduce_average_flag(self):
        out = hvd_torch.allreduce(torch.ones(2), average=False)
        assert torch.allclose(out, torch.ones(2))

    def test_allgather_identity(self):
        t = torch.randn(3, 2)
        assert torch.allclose(hvd_torch.allgather(t), t)

    def test_broadcast_identity(self):
        t = torch.randn(4)
        assert torch.allclose(hvd_torch.broadcast(t, root_rank=0), t)

    def test_broadcast_inplace(self):
        t = torch.randn(4)
        out = hvd_torch.broadcast_(t, root_rank=0)
        assert out is t

    def test_alltoall_identity(self):
        t = torch.arange(4, dtype=torch.float32)
        out, splits = hvd_torch.alltoall(t)
        assert torch.allclose(out, t)
        assert splits.tolist() == [4]

    def test_handle_api(self):
        h = hvd_torch.allreduce_async(torch.ones(5), name="sp.h1")
        assert hvd_torch.poll(h)
        out = hvd_torch.synchronize(h)
        assert torch.allclose(out, torch.ones(5))

    def test_duplicate_name_rejected(self):
        h = hvd_torch.allreduce_async(torch.ones(2), name="sp.dup")
        with pytest.raises(Exception, match="sp.dup"):
            hvd_torch.allreduce_async(torch.ones(2), name="sp.dup")
        hvd_torch.synchronize(h)

    def test_bf16_roundtrip(self):
        t = torch.ones(4, dtype=torch.bfloat16) * 1.5
        out = hvd_torch.allreduce(t, op=hvd_torch.Sum)
        assert out.dtype == torch.bfloat16
        assert torch.allclose(out.float(), torch.full((4,), 1.5))

    def test_join(self):
        assert hvd_torch.join() == 0

    def test_world_queries(self):
        assert hvd_torch.size() >= 1
        assert hvd_torch.rank() >= 0
        assert hvd_torch.local_size() >= 1
        assert hvd_torch.is_homogeneous()


class TestCompression:
    def test_fp16_roundtrip(self):
        t = torch.randn(8)
        c, ctx = hvd_torch.Compression.fp16.compress(t)
        assert c.dtype == torch.float16
        d = hvd_torch.Compression.fp16.decompress(c, ctx)
        assert d.dtype == torch.float32
        assert torch.allclose(d, t, atol=1e-2)

    def test_bf16(self):
        t = torch.randn(8)
        c, ctx = hvd_torch.Compression.bf16.compress(t)
        assert c.dtype == torch.bfloat16

    def test_none(self):
        t = torch.randn(8)
        c, ctx = hvd_torch.Compression.none.compress(t)
        assert c is t
        assert hvd_torch.Compression.none.decompress(c, ctx) is t

    def test_int_passthrough(self):
        t = torch.ones(4, dtype=torch.int64)
        c, ctx = hvd_torch.Compression.fp16.compress(t)
        assert c.dtype == torch.int64


class TestDistributedOptimizer:
    def test_elastic_construction_before_init(self, monkeypatch):
        """Elastic scripts build the optimizer BEFORE the first rendezvous
        initializes the world (examples/pytorch_elastic.py); the hook gate
        must tolerate that and register hooks anyway, since an elastic
        world of 1 can grow (reference optimizer.py:77: `size() > 1 or
        HOROVOD_ELASTIC == '1'`)."""
        from horovod_tpu.common.exceptions import NotInitializedError
        from horovod_tpu.torch import optimizer as opt_mod

        def _raise():
            raise NotInitializedError()

        monkeypatch.setattr(opt_mod.mpi_ops, "_world", _raise)

        def build():
            model = torch.nn.Linear(4, 2)
            return hvd_torch.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=model.named_parameters())

        # Static job, no init: constructing is a caller error, as before.
        monkeypatch.delenv("HOROVOD_ELASTIC", raising=False)
        with pytest.raises(NotInitializedError):
            build()
        # Elastic job: construction succeeds and hooks are registered.
        monkeypatch.setenv("HOROVOD_ELASTIC", "1")
        opt = build()
        assert len(opt._requires_update) == 2  # weight + bias hooked

    def test_wraps_class(self):
        model = torch.nn.Linear(4, 2)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.Adam(model.parameters(), lr=1e-3),
            named_parameters=model.named_parameters())
        assert isinstance(opt, torch.optim.Adam)

    def test_training_decreases_loss(self):
        torch.manual_seed(0)
        model = torch.nn.Sequential(torch.nn.Linear(8, 16), torch.nn.Tanh(),
                                    torch.nn.Linear(16, 1))
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        x = torch.randn(32, 8)
        y = x.sum(dim=1, keepdim=True)
        first = None
        for _ in range(20):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            first = first if first is not None else loss.item()
        assert loss.item() < first * 0.5

    def test_duplicate_named_parameters_rejected(self):
        model = torch.nn.Linear(2, 2)
        p = list(model.named_parameters())
        with pytest.raises(ValueError):
            hvd_torch.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=p + p)

    def test_missing_named_parameters_rejected(self):
        model = torch.nn.Linear(2, 2)
        partial = list(model.named_parameters())[:1]
        with pytest.raises(ValueError, match="missing"):
            hvd_torch.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=partial)

    def test_predivide_requires_average(self):
        model = torch.nn.Linear(2, 2)
        with pytest.raises(ValueError):
            hvd_torch.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                op=hvd_torch.Sum, gradient_predivide_factor=2.0)

    def test_adasum_optimizer_single(self):
        torch.manual_seed(0)
        model = torch.nn.Linear(3, 1)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(), op=hvd_torch.Adasum)
        x = torch.randn(4, 3)
        opt.zero_grad()
        model(x).sum().backward()
        opt.step()  # world of one: plain step


class TestFunctions:
    def test_broadcast_parameters_world1(self):
        model = torch.nn.Linear(3, 3)
        before = {k: v.clone() for k, v in model.state_dict().items()}
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
        for k, v in model.state_dict().items():
            assert torch.allclose(v, before[k])

    def test_broadcast_object_world1(self):
        obj = {"a": 1}
        assert hvd_torch.broadcast_object(obj) == obj

    def test_allgather_object_world1(self):
        assert hvd_torch.allgather_object(42) == [42]

    def test_broadcast_optimizer_state_world1(self):
        model = torch.nn.Linear(3, 3)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
        # dummy-step trick must have populated state
        assert len(opt.state_dict()["state"]) > 0


class TestSyncBatchNorm:
    def test_matches_batchnorm_world1(self):
        torch.manual_seed(0)
        sbn = hvd_torch.SyncBatchNorm(4)
        bn = torch.nn.BatchNorm2d(4)
        x = torch.randn(8, 4, 3, 3)
        # world of one falls back to plain batch_norm
        assert torch.allclose(sbn(x), bn(x), atol=1e-5)
        assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-5)

    def test_eval_mode(self):
        sbn = hvd_torch.SyncBatchNorm(4)
        sbn.eval()
        x = torch.randn(2, 4)
        out = sbn(x)
        assert out.shape == x.shape

    def test_momentum_none_cumulative(self):
        # momentum=None = cumulative moving average; must not crash and
        # must track num_batches
        sbn = hvd_torch.SyncBatchNorm(4, momentum=None)
        bn = torch.nn.BatchNorm2d(4, momentum=None)
        x = torch.randn(8, 4, 3, 3)
        assert torch.allclose(sbn(x), bn(x), atol=1e-5)
        assert sbn.num_batches_tracked.item() == 1
        assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-5)
        sbn.eval()
        sbn(x)  # eval with momentum=None must not crash either

    def test_rejects_1d(self):
        sbn = hvd_torch.SyncBatchNorm(4)
        with pytest.raises(ValueError):
            sbn(torch.randn(4))


class TestTorchElastic:
    def test_state_save_restore(self):
        model = torch.nn.Linear(2, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        state = hvd_torch.elastic.TorchState(model=model, optimizer=opt,
                                             epoch=5)
        state.save()
        with torch.no_grad():
            for p in model.parameters():
                p.fill_(77.0)
        state.epoch = 9
        state.restore()
        for p in model.parameters():
            assert not torch.allclose(p, torch.full_like(p, 77.0))
        assert state.epoch == 5

    def test_sampler_shards_and_records(self):
        sampler = hvd_torch.elastic.ElasticSampler(list(range(10)),
                                                   shuffle=False)
        idx = list(iter(sampler))
        assert idx == list(range(10))
        sampler.record_batch(0, 4)
        sampler.reset()
        assert len(set(iter(sampler)) & set(range(4))) == 0
        assert len(sampler) == 6

    def test_sampler_state_dict(self):
        sampler = hvd_torch.elastic.ElasticSampler(list(range(8)),
                                                   shuffle=False)
        sampler.record_batch(0, 2)
        sd = sampler.state_dict()
        sampler.reset()
        s2 = hvd_torch.elastic.ElasticSampler(list(range(8)), shuffle=False)
        s2.load_state_dict(sd)
        assert set(iter(s2)) == set(iter(sampler))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_world(n, timeout=180):
    port = _free_port()
    procs = []
    for r in range(n):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "PYTHONPATH": REPO,
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": str(n),
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
        })
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs, ok = [], True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            ok = False
        outs.append(out)
        ok = ok and p.returncode == 0
    assert ok, "torch worker failures:\n" + "\n----\n".join(outs)


class TestMultiProcess:
    @pytest.mark.parametrize("n", [2, 4])
    def test_world(self, n):
        _run_world(n)
