"""Collective-op semantics on the 8-device mesh.

Models the reference's op matrix tests (test/parallel/test_tensorflow.py:
every dtype x op x fused/unfused over a real 2-process world) — here the
world is 8 XLA devices and the collectives are the compiled shard_map path.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import collective_ops as C

N = 8


def spmd(f, in_specs=P(hvd.HVD_AXES), out_specs=P()):
    return hvd.shard_map(f, mesh=hvd.mesh(), in_specs=in_specs,
                         out_specs=out_specs)


def per_rank_inputs(shape, dtype):
    """world-stacked input: rank i sees slice i."""
    rng = np.random.RandomState(42)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return rng.randint(-10, 10, size=(N,) + shape).astype(dtype)
    return rng.randn(N, *shape).astype(dtype)


@pytest.mark.parametrize("dtype", [np.float32, np.float16, jnp.bfloat16,
                                   np.int32])
@pytest.mark.parametrize("shape", [(4,), (3, 5), (2, 3, 4)])
def test_allreduce_sum(dtype, shape):
    x = per_rank_inputs(shape, dtype)
    out = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Sum),
               in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    expect = np.asarray(x, dtype=np.float64).sum(axis=0)
    rtol = 5e-2 if jnp.dtype(dtype).itemsize == 2 else 1e-5
    np.testing.assert_allclose(np.asarray(out, np.float64), expect, rtol=rtol,
                               atol=1e-1 if jnp.dtype(dtype).itemsize == 2 else 1e-5)


@pytest.mark.parametrize("dtype", [np.float32, np.int32])
def test_allreduce_average(dtype):
    x = per_rank_inputs((6,), dtype)
    out = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Average),
               in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    if np.issubdtype(np.dtype(dtype), np.integer):
        expect = x.sum(axis=0) // N  # integer average truncates
        np.testing.assert_array_equal(np.asarray(out), expect)
    else:
        np.testing.assert_allclose(np.asarray(out), x.mean(axis=0), rtol=1e-5)


def test_allreduce_min_max():
    x = per_rank_inputs((5,), np.float32)
    out_min = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Min),
                   in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    out_max = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Max),
                   in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out_min), x.min(axis=0))
    np.testing.assert_allclose(np.asarray(out_max), x.max(axis=0))


def test_allreduce_product():
    x = np.full((N, 3), 2.0, np.float32)
    out = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Product),
               in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.full(3, 2.0 ** N))


def test_allreduce_prescale_postscale():
    # Reference: prescale/postscale factors in the request
    # (message.h:48-113; test_tensorflow.py prescale tests).
    x = per_rank_inputs((4,), np.float32)
    out = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Sum, prescale_factor=0.5,
                                       postscale_factor=3.0),
               in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), x.sum(0) * 0.5 * 3.0,
                               rtol=1e-5)


def test_allreduce_compression_roundtrip():
    x = per_rank_inputs((16,), np.float32)
    out = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Sum,
                                       compression=hvd.Compression.bf16),
               in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    assert out.dtype == jnp.float32  # decompressed back
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=5e-2, atol=0.2)


def test_allreduce_hierarchical_matches_flat():
    # Reference: NCCLHierarchicalAllreduce must agree with flat ring
    # (nccl_operations.cc:190-380).
    x = per_rank_inputs((8, 3), np.float32)  # dim0 divisible by local_size
    flat = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Sum, hierarchical=False),
                in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    hier = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Sum, hierarchical=True),
                in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(hier), np.asarray(flat), rtol=1e-5)


def test_allreduce_hierarchical_remainder_shape():
    # Non-divisible leading dim falls back to flat psum (the reference
    # handles the remainder via a separate root-reduce leg,
    # nccl_operations.cc:244-307).
    x = per_rank_inputs((5, 3), np.float32)
    hier = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Sum, hierarchical=True),
                in_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(hier), x.sum(0), rtol=1e-5)


@pytest.mark.parametrize("dtype", [np.float32, np.int32])
def test_allgather(dtype):
    # all_gather output carries a per-device varying mark (each rank holds
    # its own—identical—copy), so collect every rank's copy and compare.
    x = per_rank_inputs((2, 3), dtype)
    out = spmd(lambda v: hvd.allgather(v[0])[None],
               in_specs=P(hvd.HVD_AXES),
               out_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    out = np.asarray(out)
    assert out.shape == (N, N * 2, 3)
    for r in range(N):
        np.testing.assert_array_equal(out[r], x.reshape(N * 2, 3))


def test_allgather_hierarchical_matches_flat():
    # Reference: MPIHierarchicalAllgather must agree with the flat gather
    # (mpi_operations.cc:180-280); host-major packing makes the local→cross
    # two-stage gather order identical to rank order.
    x = per_rank_inputs((2, 3), np.float32)
    flat = spmd(lambda v: hvd.allgather(v[0], hierarchical=False)[None],
                in_specs=P(hvd.HVD_AXES),
                out_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    hier = spmd(lambda v: hvd.allgather(v[0], hierarchical=True)[None],
                in_specs=P(hvd.HVD_AXES),
                out_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(hier), np.asarray(flat))


def test_allgather_hierarchical_flag_from_config(monkeypatch):
    # The HOROVOD_HIERARCHICAL_ALLGATHER knob must actually change the path
    # (round-1 verdict: dead flag). Equality of results is asserted above;
    # here just prove the flagged path executes end-to-end.
    import dataclasses

    from horovod_tpu.common import basics as B

    monkeypatch.setattr(
        B._state, "config",
        dataclasses.replace(B.config(), hierarchical_allgather=True))
    x = per_rank_inputs((2, 3), np.float32)
    out = spmd(lambda v: hvd.allgather(v[0])[None],
               in_specs=P(hvd.HVD_AXES),
               out_specs=P(hvd.HVD_AXES))(jnp.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(out)[0], x.reshape(N * 2, 3))


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(root):
    # Each rank holds rank-dependent values; all must end with root's.
    def f(_):
        mine = jnp.full((4,), hvd.rank(), jnp.float32)
        return hvd.broadcast(mine, root_rank=root)

    out = spmd(f, in_specs=P(hvd.HVD_AXES))(jnp.zeros(N))
    np.testing.assert_array_equal(np.asarray(out), np.full(4, root))


def test_broadcast_bool():
    def f(_):
        mine = jnp.asarray([hvd.rank() % 2 == 1])
        return hvd.broadcast(mine, root_rank=3)

    out = spmd(f, in_specs=P(hvd.HVD_AXES))(jnp.zeros(N))
    assert bool(np.asarray(out)[0]) is True


def test_broadcast_int():
    def f(_):
        mine = jnp.asarray([hvd.rank()], jnp.int32)
        return hvd.broadcast(mine, root_rank=5)

    out = spmd(f, in_specs=P(hvd.HVD_AXES))(jnp.zeros(N))
    assert int(np.asarray(out)[0]) == 5


def test_alltoall_even():
    # rank r sends row block [r*N+k] to rank k; rank r receives [k*N+r].
    def f(_):
        mine = (jnp.arange(N, dtype=jnp.float32) + N * hvd.rank())
        out, splits = hvd.alltoall(mine)
        return out, splits

    out, splits = spmd(f, in_specs=P(hvd.HVD_AXES),
                       out_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)))(
        jnp.zeros(N))
    out = np.asarray(out).reshape(N, N)
    for r in range(N):
        np.testing.assert_array_equal(out[r], np.arange(N) * N + r)
    assert np.all(np.asarray(splits) == 1)


def test_alltoall_uneven_rejected_in_jit():
    with pytest.raises(NotImplementedError):
        spmd(lambda v: hvd.alltoall(v[0], splits=[2, 1, 1, 1, 1, 1, 0, 1])[0],
             in_specs=P(hvd.HVD_AXES))(jnp.zeros((N, N)))


def test_grouped_allreduce():
    x = per_rank_inputs((3,), np.float32)
    y = per_rank_inputs((2,), np.float32)

    def f(a, b):
        return tuple(hvd.grouped_allreduce([a[0], b[0]], op=hvd.Sum))

    outs = spmd(f, in_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
                out_specs=(P(), P()))(jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_allclose(np.asarray(outs[0]), x.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]), y.sum(0), rtol=1e-5)


def test_eager_singleprocess_semantics():
    # Eager ops run over the process world (=1 here): identity results.
    x = jnp.arange(4.0)
    np.testing.assert_array_equal(np.asarray(hvd.allreduce(x, op=hvd.Sum)), x)
    np.testing.assert_array_equal(np.asarray(hvd.allgather(x)), x)
    np.testing.assert_array_equal(np.asarray(hvd.broadcast(x, 0)), x)
    out, splits = hvd.alltoall(x)
    np.testing.assert_array_equal(np.asarray(out), x)
    hvd.barrier()


def test_async_handles():
    # Reference: handle-based async API (torch/mpi_ops.py:66-161).
    x = jnp.arange(4.0)
    h = hvd.allreduce_async(x, name="t1", op=hvd.Sum)
    assert isinstance(h, int)
    out = hvd.synchronize(h)
    np.testing.assert_array_equal(np.asarray(out), x)


def test_async_duplicate_name_rejected():
    from horovod_tpu.common.exceptions import DuplicateTensorNameError

    x = jnp.zeros(2)
    h = hvd.allreduce_async(x, name="dup")
    with pytest.raises(DuplicateTensorNameError):
        hvd.allreduce_async(x, name="dup")
    hvd.synchronize(h)
    h2 = hvd.allreduce_async(x, name="dup")  # name freed after synchronize
    hvd.synchronize(h2)


def test_join_single_process():
    assert hvd.join() == hvd.rank()


def test_uninitialized_collectives_say_call_init_first():
    """Every compiled-path entry point (allreduce, reduce_scatter,
    all_gather, and the stream variants) must answer an uninitialized
    backend with the reference-style "call init() first" error — not the
    raw KeyError the axis-env lookup used to leak (ISSUE 6 satellite).
    Subprocess: the session fixture keeps THIS process initialized."""
    import subprocess
    import sys

    code = """
import jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu.common.basics import HVD_AXES
from horovod_tpu.common.exceptions import NotInitializedError

calls = [
    lambda: hvd.allreduce(jnp.ones(4)),
    lambda: hvd.allreduce(jnp.ones(4), axes=HVD_AXES),
    lambda: hvd.reduce_scatter(jnp.ones(8), axes=HVD_AXES),
    lambda: hvd.all_gather(jnp.ones(4), axes=HVD_AXES),
    lambda: hvd.allreduce_stream(jnp.ones(4), axes=HVD_AXES),
    lambda: hvd.reduce_scatter_stream(jnp.ones(8), axes=HVD_AXES),
    lambda: hvd.all_gather_stream(jnp.ones(4), axes=HVD_AXES),
]
for fn in calls:
    try:
        fn()
    except NotInitializedError as e:
        assert "init() first" in str(e), str(e)
    else:
        raise SystemExit("no error raised before hvd.init()")
# initialized but axes unbound outside shard_map: actionable ValueError
hvd.init()
try:
    hvd.allreduce(jnp.ones(4), axes=HVD_AXES)
except ValueError as e:
    assert "hvd.shard_map" in str(e), str(e)
else:
    raise SystemExit("unbound axes outside shard_map not rejected")
print("INIT-GUARDS-OK")
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=180,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "INIT-GUARDS-OK" in r.stdout
