"""Model zoo + SyncBatchNorm tests (reference: sync-batch-norm tests in
test/parallel/test_torch.py; benchmark models in examples/)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import MnistNet, ResNet18, ResNet50
from horovod_tpu.parallel.sync_batch_norm import SyncBatchNorm

N = 8


def test_mnist_forward():
    model = MnistNet()
    x = jnp.zeros((2, 28, 28, 1))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)


def test_resnet18_forward_small():
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out, _ = model.apply(variables, x, train=False,
                         mutable=["batch_stats"])
    assert out.shape == (2, 10)


def test_resnet_space_to_depth_stem():
    """Folded stem: same output shape, 4x4x12 stem kernel, odd spatial
    dims rejected."""
    import pytest

    from horovod_tpu.models.resnet import ResNet18

    model = ResNet18(num_classes=10, dtype=jnp.float32,
                     space_to_depth=True)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert variables["params"]["conv_init"]["kernel"].shape == (4, 4, 12, 64)
    out, _ = model.apply(variables, x, train=False,
                         mutable=["batch_stats"])
    ref = ResNet18(num_classes=10, dtype=jnp.float32)
    rv = ref.init(jax.random.PRNGKey(0), x, train=False)
    ref_out, _ = ref.apply(rv, x, train=False, mutable=["batch_stats"])
    assert out.shape == ref_out.shape

    with pytest.raises(ValueError, match="even spatial"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 33, 33, 3)),
                   train=False)


def test_resnet50_param_count():
    # ~25.6M params is the well-known ResNet-50 size; catches structural bugs.
    model = ResNet50(num_classes=1000, dtype=jnp.float32)
    x = jnp.zeros((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(variables["params"]))
    assert 25.4e6 < n_params < 25.8e6, n_params


def test_sync_batch_norm_global_moments():
    """SyncBatchNorm must normalize with global-batch statistics: feeding
    rank-dependent data, the normalized global batch has mean≈0, var≈1
    (reference: test_horovod_sync_batch_norm in test/parallel/test_torch.py).
    """
    model = SyncBatchNorm(use_running_average=False, momentum=0.9)
    rng = np.random.RandomState(0)
    data = (rng.randn(N * 4, 3) * 5 + 7).astype(np.float32)

    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((4, 3)))

    def f(xb):
        out, _ = model.apply(variables, xb, mutable=["batch_stats"])
        return out

    out = hvd.shard_map(f, mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                        out_specs=P(hvd.HVD_AXES))(jnp.asarray(data))
    out = np.asarray(out)
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-3)
    np.testing.assert_allclose(out.std(axis=0), 1.0, atol=1e-2)


def test_sync_batch_norm_matches_big_batch():
    """Per-rank SyncBatchNorm output must equal single-device BatchNorm on
    the concatenated batch."""
    import flax.linen as nn

    rng = np.random.RandomState(1)
    data = (rng.randn(N * 2, 5) * 3 + 1).astype(np.float32)

    sync = SyncBatchNorm(use_running_average=False)
    plain = nn.BatchNorm(use_running_average=False)
    v_sync = sync.init(jax.random.PRNGKey(0), jnp.zeros((2, 5)))
    v_plain = plain.init(jax.random.PRNGKey(0), jnp.zeros((2, 5)))

    def f(xb):
        out, _ = sync.apply(v_sync, xb, mutable=["batch_stats"])
        return out

    out_sync = np.asarray(
        hvd.shard_map(f, mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                      out_specs=P(hvd.HVD_AXES))(jnp.asarray(data)))
    out_plain, _ = plain.apply(v_plain, jnp.asarray(data),
                               mutable=["batch_stats"])
    np.testing.assert_allclose(out_sync, np.asarray(out_plain), atol=1e-4)


def test_mnist_dp_training_step_decreases_loss():
    """End-to-end: one DP training epoch on synthetic data lowers loss —
    the reference's MNIST example smoke test (examples/tensorflow2_mnist.py)."""
    model = MnistNet()
    rng = np.random.RandomState(0)
    x = rng.randn(N * 8, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, N * 8)

    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    params = variables["params"]
    tx = hvd.DistributedOptimizer(optax.sgd(0.05))
    opt_state = tx.init(params)

    def loss_fn(p, xb, yb):
        logits = model.apply({"params": p}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()

    @jax.jit
    def step(params, opt_state, xb, yb):
        def spmd(params, opt_state, xb, yb):
            loss, grads = hvd.value_and_grad(loss_fn)(params, xb, yb)
            updates, new_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), new_state,
                    hvd.allreduce(loss))

        return hvd.shard_map(
            spmd, mesh=hvd.mesh(),
            in_specs=(P(), P(), P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), P(), P()))(params, opt_state, xb, yb)

    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(x), jnp.asarray(y))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
