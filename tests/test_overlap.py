"""Overlapped gradient reduction tests (docs/overlap.md).

Core invariants:
  * ``HOROVOD_OVERLAP=1`` / ``DistributedOptimizer(overlap=True)`` is
    BIT-identical to default-off — the stream schedule reorders
    collective issue only, never bucket contents or per-bucket math
    (SGD-momentum + Adam, 3 steps, 2x4 mesh — the ISSUE acceptance
    criterion);
  * compose matrix: overlap × {quantized+EF, zero, zero+quantized,
    backward_passes_per_step > 1, zero × bpps > 1};
  * the reverse-layer bucket schedule orders buckets by descending max
    leaf index, leaf→bucket assignment untouched;
  * streamed collectives emit ``OVERLAP:*`` timeline spans and account
    ``WireStats.overlap_bytes`` (the bench's ``comm_hidden_fraction``);
  * eager world-of-1 fallback matches the plain optimizer.

All compiled tests run on the 8-device CPU mesh shaped 2x4 so the
hierarchical/DCN decompositions are exercised under the stream schedule.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import fusion

N = 8


@pytest.fixture(scope="module", autouse=True)
def _mesh_2x4():
    hvd.shutdown()
    hvd.init(mesh_shape=(2, 4))
    yield
    hvd.shutdown()
    hvd.init()


def loss_fn(params, batch):
    x, y = batch
    return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)


def make_data(rng, n=96, d=5):
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ rng.randn(d, 1).astype(np.float32)
         + 0.1 * rng.randn(n, 1).astype(np.float32))
    return x, y


def init_params(d=5):
    return {"w": jnp.zeros((d, 1)), "b": jnp.zeros((1,))}


def train(tx, x, y, steps, bs=16, sspec=None):
    """shard_map DP training with reduce-in-optimizer local gradients
    (the canonical overlap step structure). ``sspec`` is the optimizer
    state's spec tree (device_put with it too); defaults to replicated."""
    params = init_params(x.shape[1])
    state = tx.init(params)
    mesh = hvd.mesh()
    if sspec is None:
        sspec = jax.tree.map(lambda _: P(), state)
    state = jax.device_put(
        state, jax.tree.map(lambda s: NamedSharding(mesh, s), sspec))

    @jax.jit
    def step(params, state, xb, yb):
        def spmd(params, state, xb, yb):
            loss, grads = hvd.value_and_grad(
                loss_fn, reduce=False)(params, (xb, yb))
            updates, ns = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), ns, \
                hvd.allreduce(loss)

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), sspec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), sspec, P()))(params, state, xb, yb)

    losses = []
    for i in range(steps):
        params, state, loss = step(params, state,
                                   jnp.asarray(x[i * bs:(i + 1) * bs]),
                                   jnp.asarray(y[i * bs:(i + 1) * bs]))
        losses.append(float(loss))
    return params, state, losses


# --- bit-identical parity (the acceptance criterion) -----------------------


@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_overlap_bit_identical_to_default(opt):
    """overlap=True vs default-off over 3 training steps: identical
    bucket contents + identical per-bucket collectives in a different
    issue order must produce bit-identical parameters. The tiny fusion
    threshold forces a multi-bucket plan so the stream schedule actually
    reorders something."""
    rng = np.random.RandomState(0)
    x, y = make_data(rng)
    mk = (lambda: optax.sgd(0.1, momentum=0.9)) if opt == "sgd" \
        else (lambda: optax.adam(1e-2))
    p_off, _, _ = train(
        hvd.DistributedOptimizer(mk(), fusion_threshold_bytes=16),
        x, y, steps=3)
    p_on, _, _ = train(
        hvd.DistributedOptimizer(mk(), fusion_threshold_bytes=16,
                                 overlap=True, num_comm_streams=2),
        x, y, steps=3)
    for k in p_off:
        np.testing.assert_array_equal(np.asarray(p_on[k]),
                                      np.asarray(p_off[k]))


def test_overlap_env_knob(monkeypatch):
    import dataclasses

    from horovod_tpu.common import basics as B

    cfg = dataclasses.replace(B.config(), overlap=True, num_comm_streams=2)
    monkeypatch.setattr(B._state, "config", cfg)
    rng = np.random.RandomState(1)
    x, y = make_data(rng, n=48)
    p_env, _, _ = train(hvd.DistributedOptimizer(optax.sgd(0.1)),
                        x, y, steps=2)
    monkeypatch.undo()
    p_off, _, _ = train(hvd.DistributedOptimizer(optax.sgd(0.1)),
                        x, y, steps=2)
    for k in p_off:
        np.testing.assert_array_equal(np.asarray(p_env[k]),
                                      np.asarray(p_off[k]))


# --- reverse-layer bucket schedule -----------------------------------------


def test_stream_order_reverse_layer():
    """Buckets issue in descending max-leaf-index order (deepest layers'
    gradients are ready first in backprop) without changing the plan."""
    leaves = [jnp.zeros(100, jnp.float32) for _ in range(10)]
    plan = fusion.plan_buckets(leaves, threshold_bytes=1000)
    assert len(plan) > 2
    order = fusion.stream_order(plan)
    assert sorted(order) == list(range(len(plan)))  # a permutation
    maxes = [max(plan[j].leaf_indices) for j in order]
    assert maxes == sorted(maxes, reverse=True)
    # tree-order plan => stream order is exactly reversed
    assert list(order) == list(range(len(plan)))[::-1]


def test_stream_order_mixed_dtypes_interleaves_globally():
    # Two dtype groups: the schedule orders ACROSS groups by leaf
    # readiness, not group-by-group.
    leaves = [jnp.zeros(4, jnp.float32), jnp.zeros(4, jnp.bfloat16),
              jnp.zeros(4, jnp.float32), jnp.zeros(4, jnp.bfloat16)]
    plan = fusion.plan_buckets(leaves, threshold_bytes=8)
    order = fusion.stream_order(plan)
    maxes = [max(plan[j].leaf_indices) for j in order]
    assert maxes == sorted(maxes, reverse=True)


# --- compose matrix --------------------------------------------------------


def test_overlap_quantized_ef_bit_identical():
    """overlap × quantized+EF: same bucket plan → same scale-block
    boundaries → bit-identical to quantized without overlap."""
    rng = np.random.RandomState(2)
    x, y = make_data(rng)

    def run(overlap):
        tx = hvd.DistributedOptimizer(optax.sgd(0.1), quantized=True,
                                      overlap=overlap)
        st = tx.init(init_params())
        spec = hvd.QuantizedEFState(
            jax.tree.map(lambda _: P(), st.inner),
            jax.tree.map(lambda _: hvd.data_pspec(), st.residual))
        return train(tx, x, y, steps=4, sspec=spec)

    p_on, s_on, _ = run(True)
    p_off, _, _ = run(False)
    for k in p_off:
        np.testing.assert_array_equal(np.asarray(p_on[k]),
                                      np.asarray(p_off[k]))
    # EF residuals became active through the streamed wire too
    assert any(float(jnp.abs(l).max()) > 0
               for l in jax.tree.leaves(s_on.residual))


def test_overlap_zero_bit_identical():
    rng = np.random.RandomState(3)
    x, y = make_data(rng)

    def run(overlap, quantized=False):
        tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero=True,
                                      quantized=quantized, overlap=overlap,
                                      num_comm_streams=2)
        st = tx.init(init_params())
        return train(tx, x, y, steps=3, sspec=hvd.zero_state_pspecs(st))

    for quantized in (False, True):
        p_on, _, _ = run(True, quantized)
        p_off, _, _ = run(False, quantized)
        for k in p_off:
            np.testing.assert_array_equal(np.asarray(p_on[k]),
                                          np.asarray(p_off[k]))


def test_overlap_backward_passes_double_buffer():
    """overlap × backward_passes_per_step=2 (replicated path): the
    double-buffered accumulator — k microbatches then one apply — matches
    one step on the concatenated batch. This composition has no
    MultiSteps equivalent on jax 0.4.x (cond rep mismatch, see
    tests/jax0437_repros.py::repro_cond_rep_mismatch): the branchless
    overlap accumulator is what makes it trace at all."""
    rng = np.random.RandomState(4)
    x, y = make_data(rng)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), overlap=True,
                                  backward_passes_per_step=2)
    st = tx.init(init_params())
    assert isinstance(st, hvd.OverlapMultiStepsState)
    spec = hvd.overlap_state_pspecs(st)
    pk, sk, _ = train(tx, x, y, steps=2, bs=16, sspec=spec)
    # one big-batch step with the plain optimizer
    p1, _, _ = train(hvd.DistributedOptimizer(optax.sgd(0.1)),
                     x, y, steps=1, bs=32)
    for k in p1:
        np.testing.assert_allclose(np.asarray(pk[k]), np.asarray(p1[k]),
                                   rtol=2e-5, atol=1e-7)
    # mid-cycle state: pending holds the last microbatch's raw grads?
    # after 2 full cycles (2 steps of k=2... each train step is ONE
    # microbatch call), mini_step wrapped correctly
    assert int(jax.device_get(sk.mini_step)) == 2 % 2


def test_overlap_zero_backward_passes_double_buffer():
    """overlap × zero × backward_passes_per_step=2: the shard-level
    double buffer (packed-bucket pending) matches one ZeRO step on the
    concatenated batch, and the accumulator stays 1/world per rank."""
    rng = np.random.RandomState(5)
    x, y = make_data(rng)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), zero=True, overlap=True,
                                  backward_passes_per_step=2)
    st = tx.init(init_params())
    assert isinstance(st.inner, hvd.ZeroOverlapMultiStepsState)
    pk, sk, _ = train(tx, x, y, steps=2, bs=16,
                      sspec=hvd.zero_state_pspecs(st))
    t1 = hvd.DistributedOptimizer(optax.sgd(0.1), zero=True)
    s1 = t1.init(init_params())
    p1, _, _ = train(t1, x, y, steps=1, bs=32,
                     sspec=hvd.zero_state_pspecs(s1))
    for k in p1:
        np.testing.assert_allclose(np.asarray(pk[k]), np.asarray(p1[k]),
                                   rtol=2e-5, atol=1e-7)
    # acc shards are flat buckets sharded 1/world on device
    plan = fusion.plan_buckets(jax.tree.leaves(init_params()),
                               shard_multiple=N)
    acc = jax.tree.leaves(sk.inner.acc_shards)
    assert {l.shape for l in acc} == {(b.padded_size,) for b in plan}
    for l in acc:
        assert {s.data.shape for s in l.addressable_shards} == \
            {(l.shape[0] // N,)}


def test_overlap_presummed_fallback_matches_default():
    """Auto-psummed (jax.value_and_grad) gradients + overlap + bpps>1:
    statically detected, falls back to accumulate-locally semantics —
    same result, no wire blow-up."""
    rng = np.random.RandomState(6)
    x, y = make_data(rng)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), overlap=True,
                                  backward_passes_per_step=2)
    st = tx.init(init_params())
    spec = hvd.overlap_state_pspecs(st)
    mesh = hvd.mesh()
    params = init_params()
    state = jax.device_put(
        st, jax.tree.map(lambda s: NamedSharding(mesh, s), spec))

    @jax.jit
    def step(params, state, xb, yb):
        def spmd(params, state, xb, yb):
            loss, grads = jax.value_and_grad(loss_fn)(params, (xb, yb))
            updates, ns = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), ns

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), spec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), spec))(params, state, xb, yb)

    for i in range(2):
        params, state = step(params, state,
                             jnp.asarray(x[i * 16:(i + 1) * 16]),
                             jnp.asarray(y[i * 16:(i + 1) * 16]))
    p1, _, _ = train(hvd.DistributedOptimizer(optax.sgd(0.1)),
                     x, y, steps=1, bs=32)
    for k in p1:
        np.testing.assert_allclose(np.asarray(params[k]),
                                   np.asarray(p1[k]),
                                   rtol=2e-5, atol=1e-7)


# --- timeline + wire accounting --------------------------------------------


def _trace_overlap_step(**opt_kwargs):
    tx = hvd.DistributedOptimizer(optax.sgd(0.1),
                                  fusion_threshold_bytes=16, **opt_kwargs)
    params = init_params()
    state = tx.init(params)
    mesh = hvd.mesh()
    rng = np.random.RandomState(7)
    x, y = make_data(rng, n=16)

    def spmd(params, state, xb, yb):
        loss, grads = hvd.value_and_grad(
            loss_fn, reduce=False)(params, (xb, yb))
        updates, ns = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), ns, hvd.allreduce(loss)

    f = jax.jit(hvd.shard_map(
        spmd, mesh=mesh,
        in_specs=(P(), P(), P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
        out_specs=(P(), P(), P())))
    with hvd.record_wire_stats() as ws:
        f.lower(params, state, jnp.asarray(x), jnp.asarray(y))
    return ws


def test_timeline_overlap_spans(tmp_path):
    path = str(tmp_path / "tl.json")
    hvd.start_timeline(path)
    try:
        _trace_overlap_step(overlap=True)
    finally:
        hvd.stop_timeline()
    events = json.load(open(path))
    names = {e["name"] for e in events}
    assert any(n.startswith("OVERLAP:ALLREDUCE") for n in names), names
    # spans, not instants: B/E pairs balance per tid (monitor/span_audit
    # raises SpanImbalanceError on any unbalanced or negative depth)
    from horovod_tpu.monitor.span_audit import audit_spans

    audit = audit_spans(events, prefix="OVERLAP", require_spans=True)
    assert audit.balanced


def test_wire_stats_overlap_accounting():
    ws_on = _trace_overlap_step(overlap=True)
    ws_off = _trace_overlap_step(overlap=False)
    # same wire bytes either way (schedule, not traffic, changes)...
    assert ws_on.ici_bytes + ws_on.dcn_bytes == \
        ws_off.ici_bytes + ws_off.dcn_bytes
    # ...but only overlap mode marks them stream-issued
    assert ws_off.overlap_bytes == 0 and ws_off.hidden_fraction == 0.0
    assert ws_on.overlap_bytes > 0
    assert ws_on.streamed_buckets >= 1
    # below 1.0: the loss allreduce is not part of the gradient stream
    assert 0.0 < ws_on.hidden_fraction < 1.0


def test_zero_overlap_streams_rs_and_ag():
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), zero=True, overlap=True)
    params = init_params()
    state = tx.init(params)
    mesh = hvd.mesh()
    sspec = hvd.zero_state_pspecs(state)
    rng = np.random.RandomState(8)
    x, y = make_data(rng, n=16)

    def spmd(params, state, xb, yb):
        loss, grads = hvd.value_and_grad(
            loss_fn, reduce=False)(params, (xb, yb))
        updates, ns = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), ns

    f = jax.jit(hvd.shard_map(
        spmd, mesh=mesh,
        in_specs=(P(), sspec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
        out_specs=(P(), sspec)))
    with hvd.record_wire_stats() as ws:
        f.lower(params, state, jnp.asarray(x), jnp.asarray(y))
    # both halves of the ZeRO wire (reduce-scatter AND all-gather) ride
    # the stream schedule: everything the step moves is stream-issued
    assert ws.overlap_bytes == pytest.approx(ws.ici_bytes + ws.dcn_bytes)
    assert ws.streamed_buckets >= 2  # >= one RS + one AG


# --- eager world-of-1 fallback ---------------------------------------------


def test_eager_world_of_one_matches_plain_optimizer():
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), overlap=True)
    ref = optax.adam(1e-2)
    params = init_params()
    rng = np.random.RandomState(9)
    x, y = make_data(rng, n=16)
    g = jax.grad(loss_fn)(params, (jnp.asarray(x), jnp.asarray(y)))
    u1, _ = tx.update(g, tx.init(params), params)
    u2, _ = ref.update(g, ref.init(params), params)
    for k in u2:
        np.testing.assert_allclose(np.asarray(u1[k]), np.asarray(u2[k]),
                                   rtol=1e-6, atol=1e-8)


def test_eager_world_of_one_double_buffer_applies_every_k():
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), overlap=True,
                                  backward_passes_per_step=2)
    params = init_params()
    rng = np.random.RandomState(10)
    x, y = make_data(rng, n=16)
    g = jax.grad(loss_fn)(params, (jnp.asarray(x), jnp.asarray(y)))
    state = tx.init(params)
    u, state = tx.update(g, state, params)
    assert all(float(jnp.abs(l).max()) == 0 for l in jax.tree.leaves(u))
    u, state = tx.update(g, state, params)
    assert any(float(jnp.abs(l).max()) > 0 for l in jax.tree.leaves(u))
    # k identical microbatches => the apply uses their mean == g
    ref = optax.sgd(0.1)
    ur, _ = ref.update(g, ref.init(params), params)
    for a, b in zip(jax.tree.leaves(u), jax.tree.leaves(ur)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-8)


# --- autotune integration --------------------------------------------------


def test_tuned_params_override_threads_overlap():
    from horovod_tpu.autotune import TunedParams

    tuned = TunedParams(overlap=True, num_comm_streams=2)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), tuned_params=tuned,
                                  backward_passes_per_step=2)
    # overlap=True + k>1 via the override selects the double-buffered
    # accumulator state
    assert isinstance(tx.init(init_params()), hvd.OverlapMultiStepsState)


def test_autotune_overlap_csv_round_trip(tmp_path):
    from horovod_tpu.autotune import ParameterManager, TunedParams, read_log
    from horovod_tpu.autotune import parameter_manager as pm_mod

    path = str(tmp_path / "at.csv")
    pm = ParameterManager(TunedParams(), warmup_samples=0, max_samples=10,
                          log_path=path, tune_overlap=True, seed=11)
    while not pm.done:
        pm.record_sample(2.0 if pm.current.overlap else 1.0)
    assert "overlap" in pm_mod.CSV_FIELDS
    assert "num_comm_streams" in pm_mod.CSV_FIELDS
    rows = read_log(path)
    assert {r["overlap"] for r in rows} == {False, True}
    for row, (p, _) in zip(rows, pm.history):
        assert row["overlap"] == p.overlap
        assert row["num_comm_streams"] == p.num_comm_streams
        assert p.num_comm_streams in (1, 2, 4)
        if not p.overlap:
            assert p.num_comm_streams == 1  # dead knob pinned
    assert pm.best.overlap is True


def test_autotune_overlap_gate_off_never_proposes():
    from horovod_tpu.autotune import ParameterManager, TunedParams

    pm = ParameterManager(TunedParams(), warmup_samples=0, max_samples=6,
                          seed=12)
    while not pm.done:
        pm.record_sample(1.0)
    assert all(not p.overlap and p.num_comm_streams == 1
               for p, _ in pm.history)


def test_cache_schema_v4_tolerant_from_dict():
    from horovod_tpu.autotune import TunedParams
    from horovod_tpu.autotune import driver as at_driver

    # v12 = the compile-ahead autotune schema (docs/compile.md); the
    # tolerant-read contract below is version-independent.
    assert at_driver._CACHE_VERSION == 12
    assert "v12" in at_driver.cache_key_for("x")
    # v1/v2-era dicts (no overlap keys) stay readable with defaults
    old = {"fusion_threshold_bytes": 1 << 22, "quant_block": 128,
           "hierarchical_allreduce": True}
    p = TunedParams.from_dict(old)
    assert p.overlap is False and p.num_comm_streams == 1
    assert p.zero_stage == 0
    assert TunedParams.from_dict(p.as_dict()) == p
    # v2/v3-era boolean zero_sharding names stage 2 (the PR-4 behavior)
    p = TunedParams.from_dict({**old, "zero_sharding": True})
    assert p.zero_stage == 2 and p.zero_sharding is True
