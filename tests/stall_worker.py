"""Worker for the stall-inspector integration test (reference:
test/integration/test_stall.py — run a job where one rank lags past the
warning threshold and assert the coordinator's stall warning names the
ready and missing ranks)."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from horovod_tpu import cc  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    ctx = cc.CoreContext()
    if rank != 0:
        # Lag past HOROVOD_STALL_CHECK_TIME_SECONDS before submitting:
        # the coordinator's inspector must warn about the stalled tensor.
        time.sleep(float(os.environ.get("STALL_WORKER_LAG", "3")))
    out = ctx.allreduce_async(np.ones(4, np.float32), "stalled.t").wait()
    assert np.allclose(out, ctx.size())
    # A second, prompt collective proves the world recovered.
    out = ctx.allreduce_async(np.ones(2, np.float32), "after.t").wait()
    assert np.allclose(out, ctx.size())
    ctx.barrier()
    ctx.close()
    print(f"stall worker rank {rank}: OK")


if __name__ == "__main__":
    main()
