"""In-process fake of the ray API surface horovod_tpu.ray uses.

ray is not installable in this image, so the Ray executors are tested
against this stand-in (the reference tests run a real `ray.init()` local
cluster; same idea, one dependency lighter). Semantics:

- ``@ray.remote`` **classes** become in-process actors: each actor owns a
  worker thread; method ``.remote()`` calls enqueue onto it and return
  ``ObjectRef`` futures. ``ray.kill(actor)`` makes subsequent calls raise.
- ``@ray.remote`` **functions** run in a fresh *subprocess* (cloudpickled
  over stdin), because real ray tasks are process-isolated — which is what
  lets N elastic workers each own HOROVOD_* env and a native controller
  rank without clobbering each other.
- ``ray.util.placement_group`` records bundles/strategy for assertions and
  returns an object whose ``.ready()`` resolves immediately.

Install with ``fake_ray.install(monkeypatch)``.
"""

from __future__ import annotations

import os
import pickle
import queue
import subprocess
import sys
import tempfile
import threading
import types
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import cloudpickle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Extra env applied to every task subprocess (tests point JAX at CPU so a
# wedged TPU tunnel can't hang workers — verify-skill gotcha).
TASK_ENV: Dict[str, str] = {}

# What ray.nodes() reports; tests overwrite.
NODES: List[dict] = []

# Records for assertions.
CREATED_PLACEMENT_GROUPS: List["FakePlacementGroup"] = []
TASK_OPTIONS: List[dict] = []
ACTOR_OPTIONS: List[dict] = []


class RayError(Exception):
    pass


class ObjectRef:
    def __init__(self, fut):
        self._fut = fut

    def result(self, timeout=None):
        return self._fut.result(timeout)


class _Future:
    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None

    def set_result(self, v):
        self._value = v
        self._event.set()

    def set_exception(self, e):
        self._exc = e
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("fake-ray get timed out")
        if self._exc is not None:
            raise self._exc
        return self._value


class _ActorHandle:
    def __init__(self, cls, args, kwargs, options=None):
        self._obj = cls(*args, **kwargs)
        self._q: "queue.Queue" = queue.Queue()
        self._killed = False
        self._options = options or {}
        ACTOR_OPTIONS.append(self._options)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, method, args, kwargs = item
            try:
                fut.set_result(getattr(self._obj, method)(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

    def __getattr__(self, name):
        handle = self

        class _Method:
            def remote(self, *args, **kwargs):
                if handle._killed:
                    raise RayError("actor is dead")
                fut = _Future()
                handle._q.put((fut, name, args, kwargs))
                return ObjectRef(fut)

        return _Method()

    def _kill(self):
        self._killed = True
        self._q.put(None)


@dataclass
class _RemoteFunction:
    fn: Any
    options_dict: dict = field(default_factory=dict)

    def options(self, **opts):
        merged = dict(self.options_dict)
        merged.update(opts)
        return _RemoteFunction(self.fn, merged)

    def remote(self, *args, **kwargs):
        TASK_OPTIONS.append(dict(self.options_dict))
        fut = _Future()
        payload = cloudpickle.dumps((self.fn, args, kwargs))
        out_path = tempfile.mktemp(prefix="fake_ray_out_")

        def _run():
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            env.update(TASK_ENV)
            child = (
                "import sys, pickle, cloudpickle\n"
                "fn, args, kwargs = cloudpickle.load(sys.stdin.buffer)\n"
                "res = fn(*args, **kwargs)\n"
                f"pickle.dump(res, open({out_path!r}, 'wb'))\n")
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", child], input=payload, env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    timeout=self.options_dict.get("_timeout", 300))
                if proc.returncode != 0:
                    fut.set_exception(RayError(
                        f"task subprocess rc={proc.returncode}: "
                        f"{proc.stdout.decode(errors='replace')[-2000:]}"))
                    return
                with open(out_path, "rb") as f:
                    fut.set_result(pickle.load(f))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
            finally:
                if os.path.exists(out_path):
                    os.unlink(out_path)

        threading.Thread(target=_run, daemon=True).start()
        return ObjectRef(fut)


@dataclass
class _RemoteClass:
    cls: Any
    options_dict: dict = field(default_factory=dict)

    def options(self, **opts):
        merged = dict(self.options_dict)
        merged.update(opts)
        return _RemoteClass(self.cls, merged)

    def remote(self, *args, **kwargs):
        return _ActorHandle(self.cls, args, kwargs, self.options_dict)


def remote(*args, **kwargs):
    def _wrap(target):
        if isinstance(target, type):
            return _RemoteClass(target, dict(kwargs))
        return _RemoteFunction(target, dict(kwargs))

    if len(args) == 1 and not kwargs and (
            callable(args[0]) or isinstance(args[0], type)):
        return _wrap(args[0])
    return _wrap


def get(refs, timeout=None):
    if isinstance(refs, list):
        return [r.result(timeout) for r in refs]
    return refs.result(timeout)


def kill(actor, no_restart=True):  # noqa: ARG001 - parity signature
    actor._kill()


def nodes():
    return list(NODES)


def is_initialized():
    return True


@dataclass
class FakePlacementGroup:
    bundles: List[dict]
    strategy: str
    removed: bool = False

    def ready(self):
        fut = _Future()
        fut.set_result(True)
        return ObjectRef(fut)


def _placement_group(bundles, strategy="PACK", **kwargs):  # noqa: ARG001
    pg = FakePlacementGroup([dict(b) for b in bundles], strategy)
    CREATED_PLACEMENT_GROUPS.append(pg)
    return pg


def _remove_placement_group(pg):
    pg.removed = True


def _get_current_placement_group():
    return None


def _get_node_ip_address():
    return "127.0.0.1"


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: Optional[bool] = None


def reset():
    TASK_ENV.clear()
    NODES.clear()
    CREATED_PLACEMENT_GROUPS.clear()
    TASK_OPTIONS.clear()
    ACTOR_OPTIONS.clear()


def install(monkeypatch):
    """Register this fake as the importable `ray` package."""
    reset()
    ray_mod = types.ModuleType("ray")
    ray_mod.remote = remote
    ray_mod.get = get
    ray_mod.kill = kill
    ray_mod.nodes = nodes
    ray_mod.is_initialized = is_initialized
    ray_mod.__version__ = "0.0-fake"

    util_mod = types.ModuleType("ray.util")
    util_mod.placement_group = _placement_group
    util_mod.remove_placement_group = _remove_placement_group
    util_mod.get_current_placement_group = _get_current_placement_group
    util_mod.get_node_ip_address = _get_node_ip_address

    sched_mod = types.ModuleType("ray.util.scheduling_strategies")
    sched_mod.PlacementGroupSchedulingStrategy = \
        PlacementGroupSchedulingStrategy

    ray_mod.util = util_mod
    util_mod.scheduling_strategies = sched_mod

    monkeypatch.setitem(sys.modules, "ray", ray_mod)
    monkeypatch.setitem(sys.modules, "ray.util", util_mod)
    monkeypatch.setitem(sys.modules, "ray.util.scheduling_strategies",
                        sched_mod)
    return ray_mod
