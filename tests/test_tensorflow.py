"""TensorFlow/Keras binding tests (reference analogue:
test/parallel/test_tensorflow.py + test_keras.py, SURVEY §4): single-process
semantics plus real multi-process workers over localhost TCP."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = pytest.importorskip("keras")

import horovod_tpu.tensorflow as hvd_tf  # noqa: E402
import horovod_tpu.keras as hvd_keras  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "tf_worker.py")


@pytest.fixture(autouse=True)
def _tf_state_isolation():
    """Order-independence guard for the tf.function tests.

    ``tf.function`` tracing depends on process-global state that earlier
    tier-1 tests can leak: ``tf.config.run_functions_eagerly`` toggles
    (keras fits flip it), a dangling default FuncGraph from a test that
    died inside a ``graph.as_default()`` context, and the per-function
    autograph conversion allowlist — the source of the pre-PR-5
    order-dependent ``test_allreduce_in_tf_function`` flake, which never
    reproduced in isolation. Pin the state before every test in this
    module and restore the caller's afterwards.
    """
    was_eager_fns = tf.config.functions_run_eagerly()
    tf.config.run_functions_eagerly(False)
    # A leaked graph-mode default context would silently reroute every
    # hvd_tf op through the graph path — fail loudly instead, naming the
    # leak, rather than flaking on whatever that path returns.
    assert tf.executing_eagerly(), (
        "a previous test left a graph context as default; tf.function "
        "tests cannot run order-independently")
    yield
    tf.config.run_functions_eagerly(was_eager_fns)


class TestOpsSingleProcess:
    def test_allreduce_identity(self):
        t = tf.range(6, dtype=tf.float32)
        assert np.allclose(hvd_tf.allreduce(t).numpy(), t.numpy())

    def test_allreduce_scaling(self):
        out = hvd_tf.allreduce(tf.ones([4]), op=hvd_tf.Sum,
                               prescale_factor=3.0)
        assert np.allclose(out.numpy(), 3.0)

    def test_allreduce_grad(self):
        x = tf.Variable(tf.ones([3]))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd_tf.allreduce(x))
        g = tape.gradient(y, x)
        assert np.allclose(g.numpy(), 1.0)

    def test_allreduce_in_tf_function(self):
        # autograph=False: the body is pure TF ops (no python control
        # flow), so the autograph source-conversion machinery — whose
        # per-process caches made this test order-dependent — has nothing
        # to contribute and is excluded outright; _tf_state_isolation
        # guards the rest of the global tracing state.
        @tf.function(autograph=False)
        def f(t):
            return hvd_tf.allreduce(t, op=hvd_tf.Sum)

        assert np.allclose(f(tf.ones([4])).numpy(), 1.0)

    def test_average_op_conflict(self):
        with pytest.raises(ValueError):
            hvd_tf.allreduce(tf.ones([2]), average=True, op=hvd_tf.Sum)

    def test_allgather_identity(self):
        t = tf.random.normal([3, 2])
        assert np.allclose(hvd_tf.allgather(t).numpy(), t.numpy())

    def test_broadcast_identity(self):
        t = tf.random.normal([4])
        assert np.allclose(hvd_tf.broadcast(t, 0).numpy(), t.numpy())

    def test_alltoall_identity(self):
        t = tf.range(4, dtype=tf.float32)
        out, splits = hvd_tf.alltoall(t)
        assert np.allclose(out.numpy(), t.numpy())
        assert list(splits.numpy()) == [4]

    def test_broadcast_variables(self):
        v = tf.Variable(tf.ones([3]))
        hvd_tf.broadcast_variables([v], root_rank=0)
        assert np.allclose(v.numpy(), 1.0)

    def test_broadcast_object(self):
        assert hvd_tf.broadcast_object({"a": 1}) == {"a": 1}

    def test_allgather_object(self):
        assert hvd_tf.allgather_object(7) == [7]

    def test_join(self):
        assert hvd_tf.join() == 0

    def test_compression_fp16(self):
        from horovod_tpu.tensorflow.compression import Compression

        t = tf.random.normal([8])
        c, ctx = Compression.fp16.compress(t)
        assert c.dtype == tf.float16
        d = Compression.fp16.decompress(c, ctx)
        assert d.dtype == tf.float32


class TestDistributedGradientTape:
    def test_wraps_and_computes(self):
        w = tf.Variable(tf.ones([3, 1]))
        x = tf.ones([2, 3])
        with hvd_tf.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.reduce_sum(tf.matmul(x, w))
        (g,) = tape.gradient(loss, [w])
        assert np.allclose(g.numpy(), 2.0)

    def test_sparse_indexedslices(self):
        emb = tf.Variable(tf.random.normal([10, 4]))
        with hvd_tf.DistributedGradientTape(tf.GradientTape()) as tape:
            rows = tf.gather(emb, [1, 3])
            loss = tf.reduce_sum(rows)
        (g,) = tape.gradient(loss, [emb])
        assert isinstance(g, tf.IndexedSlices)
        assert g.values.shape[0] == 2

    def test_sparse_average_scales_by_size(self, monkeypatch):
        """Average must divide gathered sparse values by the world the
        allgather spanned — the PROCESS world, not size()'s device world
        (reference tensorflow/__init__.py:107; ADVICE r1 + the r5
        sparse_as_dense agreement test exposing the divisor mismatch)."""
        from horovod_tpu.ops import collective_ops as C

        monkeypatch.setattr(C, "_eager_world", lambda: 4)
        emb = tf.Variable(tf.ones([10, 4]))
        with hvd_tf.DistributedGradientTape(tf.GradientTape()) as tape:
            rows = tf.gather(emb, [1, 3])
            loss = tf.reduce_sum(rows)
        (g,) = tape.gradient(loss, [emb])
        # world-1 allgather is identity, so values = raw/4.
        assert np.allclose(g.values.numpy(), 0.25)

    def test_sparse_as_dense_densifies(self):
        """sparse_as_dense=True turns the IndexedSlices gradient into a
        dense tensor before reduction, numerically equal to the
        densified gather-path result (reference
        tensorflow/__init__.py:260,299,437; the 2-process agreement leg
        lives in tests/tf_worker.py)."""
        emb = tf.Variable(tf.ones([6, 3]))

        def grad(sparse_as_dense):
            with hvd_tf.DistributedGradientTape(
                    tf.GradientTape(),
                    sparse_as_dense=sparse_as_dense) as tape:
                rows = tf.gather(emb, [1, 3, 1])  # duplicate index
                loss = tf.reduce_sum(rows * rows)
            (g,) = tape.gradient(loss, [emb])
            return g


        g_dense = grad(True)
        assert not isinstance(g_dense, tf.IndexedSlices)
        g_gather = grad(False)
        assert isinstance(g_gather, tf.IndexedSlices)
        np.testing.assert_allclose(
            g_dense.numpy(), tf.convert_to_tensor(g_gather).numpy(),
            rtol=1e-6)
        # row 1 hit twice -> 2*2*1, row 3 once -> 2*1.
        assert np.allclose(g_dense.numpy()[1], 4.0)
        assert np.allclose(g_dense.numpy()[3], 2.0)

    def test_sparse_adasum_rejected(self):
        emb = tf.Variable(tf.ones([10, 4]))
        with pytest.raises(NotImplementedError):
            with hvd_tf.DistributedGradientTape(
                    tf.GradientTape(), op=hvd_tf.Adasum) as tape:
                rows = tf.gather(emb, [1, 3])
                loss = tf.reduce_sum(rows)
            tape.gradient(loss, [emb])


class TestSyncBatchNorm:
    def test_matches_stock_bn_world1(self, monkeypatch):
        """World-1 allreduce is identity, so the synchronized path must
        reproduce the stock layer's training output exactly (forced onto
        the sync path by faking size=2)."""
        from horovod_tpu.tensorflow import sync_batch_norm as sbn_mod

        monkeypatch.setattr(sbn_mod, "size", lambda: 2)
        rs = np.random.RandomState(0)
        x = tf.constant(rs.randn(8, 5).astype(np.float32))
        sbn = hvd_tf.SyncBatchNormalization(momentum=0.9, epsilon=1e-3)
        ref = keras.layers.BatchNormalization(momentum=0.9, epsilon=1e-3)
        sbn.build(x.shape)
        ref.build(x.shape)
        out = sbn(x, training=True)
        expect = ref(x, training=True)
        assert np.allclose(out.numpy(), expect.numpy(), atol=1e-5)
        assert np.allclose(np.asarray(sbn.moving_mean),
                           np.asarray(ref.moving_mean), atol=1e-5)
        assert np.allclose(np.asarray(sbn.moving_variance),
                           np.asarray(ref.moving_variance), atol=1e-5)

    def test_inference_uses_moving_stats(self):
        x = tf.constant(np.random.RandomState(1).randn(4, 3)
                        .astype(np.float32))
        sbn = hvd_tf.SyncBatchNormalization()
        out = sbn(x, training=False)
        # moving stats are identity at init: output ~= x (eps shift only)
        assert np.allclose(out.numpy(), x.numpy(), atol=1e-2)


class TestTensorFlowState:
    def test_save_restore_sync_world1(self):
        v = tf.Variable([1.0, 2.0])
        st = hvd_tf.elastic.TensorFlowState(variables=[v], epoch=3)
        v.assign([9.0, 9.0])
        st.epoch = 7
        st.restore()
        assert np.allclose(v.numpy(), [1.0, 2.0])
        assert st.epoch == 3
        v.assign([5.0, 5.0])
        st.epoch = 4
        st.save()
        st.sync()  # world 1: broadcast is identity
        assert np.allclose(v.numpy(), [5.0, 5.0])
        assert st.epoch == 4

    def test_keras_state_wraps_model(self):
        model = keras.Sequential([keras.layers.Input(shape=(2,)),
                                  keras.layers.Dense(1)])
        st = hvd_tf.elastic.TensorFlowKerasState(model, epoch=0)
        w0 = [w.copy() for w in model.get_weights()]
        model.set_weights([w + 1.0 for w in w0])
        st.restore()
        for a, b in zip(model.get_weights(), w0):
            assert np.allclose(a, b)


class TestKerasOptimizer:
    def test_wraps_class_and_trains(self):
        keras.utils.set_random_seed(0)
        model = keras.Sequential([
            keras.layers.Input(shape=(4,)),
            keras.layers.Dense(8, activation="tanh"),
            keras.layers.Dense(1),
        ])
        opt = hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.1))
        assert isinstance(opt, keras.optimizers.SGD)
        model.compile(optimizer=opt, loss="mse")
        xs = np.random.RandomState(0).randn(64, 4).astype(np.float32)
        ys = xs.sum(axis=1, keepdims=True).astype(np.float32)
        hist = model.fit(xs, ys, batch_size=16, epochs=3, verbose=0)
        assert hist.history["loss"][-1] < hist.history["loss"][0]

    def test_serialization_roundtrip(self):
        opt = hvd_keras.DistributedOptimizer(
            keras.optimizers.Adam(learning_rate=3e-4))
        cfg = opt.get_config()
        assert abs(cfg["learning_rate"] - 3e-4) < 1e-9


class TestKerasCallbacks:
    def _model(self):
        keras.utils.set_random_seed(0)
        model = keras.Sequential([
            keras.layers.Input(shape=(2,)),
            keras.layers.Dense(1),
        ])
        model.compile(optimizer=keras.optimizers.SGD(learning_rate=0.01),
                      loss="mse")
        return model

    def test_broadcast_callback_world1(self):
        model = self._model()
        xs = np.random.randn(8, 2).astype(np.float32)
        ys = np.zeros((8, 1), np.float32)
        model.fit(xs, ys, epochs=1, verbose=0, callbacks=[
            hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0)])

    def test_metric_average_world1(self):
        model = self._model()
        xs = np.random.randn(8, 2).astype(np.float32)
        ys = np.zeros((8, 1), np.float32)
        model.fit(xs, ys, epochs=1, verbose=0, callbacks=[
            hvd_keras.callbacks.MetricAverageCallback()])

    def test_warmup_semantics_size4(self, monkeypatch):
        """Reference semantics (_keras/callbacks.py:139-143): warm from
        initial_lr/size up to initial_lr (the size-scaled LR the user set)."""
        model = self._model()
        cb = hvd_keras.callbacks.LearningRateWarmupCallback(
            initial_lr=0.4, warmup_epochs=2, steps_per_epoch=4)
        monkeypatch.setattr(cb, "_size", lambda: 4)
        cb.set_model(model)
        cb.on_epoch_begin(0)
        cb.on_train_batch_begin(0)
        lr0 = float(np.asarray(model.optimizer.learning_rate))
        cb.on_epoch_begin(1)
        cb.on_train_batch_begin(4)  # progress = (1 + 4/4)/2 = 1.0
        lr1 = float(np.asarray(model.optimizer.learning_rate))
        assert lr0 == pytest.approx(0.4 / 4)
        assert lr1 == pytest.approx(0.4)

    def test_warmup_reaches_target(self):
        model = self._model()
        cb = hvd_keras.callbacks.LearningRateWarmupCallback(
            initial_lr=0.01, warmup_epochs=2, steps_per_epoch=4)
        cb.set_model(model)
        cb.on_epoch_begin(0)
        cb.on_train_batch_begin(0)
        lr0 = float(np.asarray(model.optimizer.learning_rate))
        cb.on_epoch_begin(1)
        cb.on_train_batch_begin(3)
        lr1 = float(np.asarray(model.optimizer.learning_rate))
        # world of one: multiplier stays 1.0 throughout
        assert lr0 == pytest.approx(0.01)
        assert lr1 == pytest.approx(0.01)

    def test_schedule_staircase(self):
        model = self._model()
        cb = hvd_keras.callbacks.LearningRateScheduleCallback(
            initial_lr=0.1, multiplier=lambda e: 0.1 ** e, start_epoch=0)
        cb.set_model(model)
        cb.on_epoch_begin(0)
        assert float(np.asarray(
            model.optimizer.learning_rate)) == pytest.approx(0.1)
        cb.on_epoch_begin(2)
        assert float(np.asarray(
            model.optimizer.learning_rate)) == pytest.approx(0.001)


class TestKerasElastic:
    def test_state_save_restore(self):
        model = self._make()
        state = hvd_keras.elastic.KerasState(model, epoch=3)
        w0 = [np.copy(w) for w in model.get_weights()]
        model.set_weights([w * 0 + 99.0 for w in model.get_weights()])
        state.epoch = 7
        state.restore()
        for a, b in zip(model.get_weights(), w0):
            assert np.allclose(a, b)
        assert state.epoch == 3

    @staticmethod
    def _make():
        keras.utils.set_random_seed(0)
        model = keras.Sequential([
            keras.layers.Input(shape=(2,)),
            keras.layers.Dense(1),
        ])
        model.compile(optimizer="sgd", loss="mse")
        return model


class TestMXNetGate:
    def test_informative_import_error(self):
        with pytest.raises(ImportError, match="mxnet"):
            import horovod_tpu.mxnet  # noqa: F401


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_world(n, timeout=420):
    port = _free_port()
    procs = []
    for r in range(n):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "PYTHONPATH": REPO,
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": str(n),
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
        })
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs, ok = [], True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            ok = False
        outs.append(out)
        ok = ok and p.returncode == 0
    assert ok, "tf worker failures:\n" + "\n----\n".join(outs)


class TestMultiProcess:
    def test_world_2(self):
        _run_world(2)
