"""World/topology API tests (reference: test/parallel/test_tensorflow.py
rank/size assertions + basics.py surface)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd


def test_initialized():
    assert hvd.is_initialized()


def test_world_shape():
    assert hvd.size() == 8
    assert hvd.local_size() * hvd.cross_size() == hvd.size()
    assert hvd.size() == hvd.mesh().devices.size


def test_eager_ranks():
    # Single process: leader rank 0, cross rank 0.
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.cross_rank() == 0
    assert hvd.is_homogeneous()
    assert hvd.mpi_threads_supported()


def test_traced_ranks_are_per_chip():
    mesh = hvd.mesh()

    def f(x):
        return x + hvd.rank()

    out = hvd.shard_map(f, mesh=mesh, in_specs=P(hvd.HVD_AXES),
                        out_specs=P(hvd.HVD_AXES))(jnp.zeros(8))
    np.testing.assert_array_equal(np.asarray(out), np.arange(8))


def test_traced_local_cross_ranks():
    mesh = hvd.mesh()
    n_local = hvd.local_size()

    def f(x):
        return x + hvd.local_rank() + 100 * hvd.cross_rank()

    out = hvd.shard_map(f, mesh=mesh, in_specs=P(hvd.HVD_AXES),
                        out_specs=P(hvd.HVD_AXES))(jnp.zeros(8))
    expect = [100 * (i // n_local) + (i % n_local) for i in range(8)]
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_local_batch_size():
    assert hvd.local_batch_size(64) == 8
    with pytest.raises(ValueError):
        hvd.local_batch_size(7)


def test_reinit_after_shutdown():
    # Reference: elastic reset re-runs hvd.shutdown + hvd.init
    # (common/elastic.py:147-168).
    hvd.shutdown()
    assert not hvd.is_initialized()
    hvd.init()
    assert hvd.is_initialized()
    assert hvd.size() == 8


def test_double_init_is_noop():
    hvd.init()
    hvd.init()
    assert hvd.size() == 8
