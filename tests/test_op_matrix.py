"""Systematic collective-op matrix: op x dtype x path.

Models the reference's exhaustive parallel tier
(test/parallel/test_tensorflow.py — every dtype x dim x error case over a
real multi-process world) across this framework's three data planes:

* compiled — shard_map over the 8-device CPU mesh (the XLA/ICI plane);
* eager    — host-path ops in a single process (identity semantics);
* native   — a real 2-process world through the C++ controller + TCP
  data plane (tests/matrix_worker.py), including the cross-rank
  mismatch ERROR cases (shape/dtype/op/reduce-op/root), asserting the
  controller's error text reaches every rank.

64-bit dtypes run under ``jax.experimental.enable_x64`` (JAX truncates
them to 32-bit otherwise).
"""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from test_native_core import _run_world, REPO  # noqa: F401

import os

MATRIX_WORKER = os.path.join(REPO, "tests", "matrix_worker.py")

N = 8

DTYPES = [np.uint8, np.int8, np.int32, np.int64, np.float16,
          jnp.bfloat16, np.float32, np.float64]


def _is64(dtype):
    return np.dtype(dtype).itemsize == 8


def _ctx(dtype):
    if not _is64(dtype):
        return contextlib.nullcontext()
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(True)
    from jax.experimental import enable_x64  # jax < 0.6 spelling

    return enable_x64()


def spmd(f, in_specs, out_specs):
    return hvd.shard_map(f, mesh=hvd.mesh(), in_specs=in_specs,
                         out_specs=out_specs)


def as_f64(a):
    return np.asarray(a, dtype=np.float64)


class TestCompiledMatrix:
    """Every op in every wire dtype on the compiled plane. Values stay
    tiny so the sums are exact in every dtype (incl. uint8/fp16/bf16)."""

    @pytest.mark.parametrize("dtype", DTYPES)
    def test_allreduce(self, dtype):
        with _ctx(dtype):
            base = np.arange(8) % 3
            x = np.stack([base + r for r in range(N)]).astype(
                np.dtype(dtype) if not _is64(dtype) else dtype)
            out = spmd(lambda v: hvd.allreduce(v[0], op=hvd.Sum),
                       in_specs=P(hvd.HVD_AXES), out_specs=P())(
                jnp.asarray(x, dtype=dtype))
            exp = base * N + sum(range(N))
            assert np.array_equal(as_f64(out), as_f64(exp))

    @pytest.mark.parametrize("dtype", DTYPES)
    def test_grouped_allreduce(self, dtype):
        with _ctx(dtype):
            a = np.ones((N, 3)); b = np.full((N, 2), 2)

            def f(x, y):
                return tuple(hvd.grouped_allreduce([x[0], y[0]],
                                                   op=hvd.Sum))

            outs = spmd(f, in_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
                        out_specs=(P(), P()))(
                jnp.asarray(a, dtype=dtype), jnp.asarray(b, dtype=dtype))
            assert np.array_equal(as_f64(outs[0]), np.full(3, N))
            assert np.array_equal(as_f64(outs[1]), np.full(2, 2 * N))

    @pytest.mark.parametrize("dtype", DTYPES)
    def test_allgather(self, dtype):
        with _ctx(dtype):
            x = np.stack([np.full((2, 2), r) for r in range(N)])
            # all_gather output carries a varying mark (each rank holds
            # its own identical copy): stack per-rank copies.
            out = spmd(lambda v: hvd.allgather(v[0])[None],
                       in_specs=P(hvd.HVD_AXES),
                       out_specs=P(hvd.HVD_AXES))(
                jnp.asarray(x, dtype=dtype))
            assert out.shape == (N, 2 * N, 2)
            for r in range(N):
                for s in range(N):
                    assert (as_f64(out[r, 2 * s:2 * s + 2]) == s).all()

    @pytest.mark.parametrize("dtype", DTYPES)
    def test_broadcast(self, dtype):
        with _ctx(dtype):
            x = np.stack([np.full(4, r) for r in range(N)])
            out = spmd(lambda v: hvd.broadcast(v[0], root_rank=3),
                       in_specs=P(hvd.HVD_AXES), out_specs=P())(
                jnp.asarray(x, dtype=dtype))
            assert (as_f64(out) == 3).all()

    @pytest.mark.parametrize("dtype", DTYPES)
    def test_alltoall(self, dtype):
        with _ctx(dtype):
            # rank r sends value r in row-block k to rank k.
            x = np.stack([np.arange(N).repeat(1)[:, None] * 0 + r
                          for r in range(N)])  # [N, N, 1] value r

            def f(v):
                out, sp = hvd.alltoall(v[0])
                return out, sp

            out, sp = spmd(f, in_specs=P(hvd.HVD_AXES),
                           out_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)))(
                jnp.asarray(x, dtype=dtype))
            out = as_f64(out).reshape(N, N)
            for r in range(N):
                assert (out[r] == np.arange(N)).all()
            assert (np.asarray(sp) == 1).all()


class TestEagerMatrix:
    """Host-path ops, process world of 1: identity semantics in every
    dtype (reference: single-process eager behavior of each binding)."""

    @pytest.mark.parametrize("dtype", DTYPES)
    def test_all_ops(self, dtype):
        with _ctx(dtype):
            x = jnp.asarray(np.arange(6).reshape(3, 2), dtype=dtype)
            assert np.array_equal(
                as_f64(hvd.allreduce(x, op=hvd.Sum)), as_f64(x))
            assert np.array_equal(as_f64(hvd.allgather(x)), as_f64(x))
            assert np.array_equal(as_f64(hvd.broadcast(x, 0)), as_f64(x))
            out, sp = hvd.alltoall(x)
            assert np.array_equal(as_f64(out), as_f64(x))
            assert np.asarray(sp).tolist() == [3]
            outs = hvd.grouped_allreduce([x, x + x], op=hvd.Sum)
            assert np.array_equal(as_f64(outs[1]), 2 * as_f64(x))


class TestNativeMatrix:
    """Real 2- and 3-process worlds through the C++ controller + TCP
    plane: the full dtype matrix per op plus the cross-rank mismatch
    ERROR cases (shape, dtype, collective-op, reduce-op, root) —
    asserting the controller's ERROR text reaches every rank."""

    @pytest.mark.parametrize("n", [2, 3])
    def test_world(self, n):
        _run_world(n, timeout=180, worker=MATRIX_WORKER)

    def test_world_2_hierarchical(self):
        _run_world(2, {
            "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
            "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
        }, timeout=180, worker=MATRIX_WORKER, local_size=1)
