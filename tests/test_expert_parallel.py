"""Expert parallelism: Switch-MoE with all-to-all dispatch on the mesh.

With a generous capacity (no overflow drops) the EP-sharded layer is
EXACT against the world-1 all-experts-local computation: buffering and
the two all-to-alls are a reorganization of the same per-token FFN.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.parallel.expert import (
    SwitchMoE,
    ep_split_params,
    switch_moe,
    switch_moe_ragged,
)
from horovod_tpu.parallel.tensor import tp_merge_params
from jax0437_repros import _old_jax


def _layer_data(N=64, C=16, F=32, E=8, seed=0):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(N, C), jnp.float32) * 0.5
    router = jnp.asarray(rs.randn(C, E), jnp.float32) * 0.3
    w1 = jnp.asarray(rs.randn(E, C, F), jnp.float32) * 0.1
    b1 = jnp.asarray(rs.randn(E, F), jnp.float32) * 0.01
    w2 = jnp.asarray(rs.randn(E, F, C), jnp.float32) * 0.1
    b2 = jnp.asarray(rs.randn(E, C), jnp.float32) * 0.01
    return x, router, w1, b1, w2, b2


class TestSwitchMoE:
    def test_matches_per_token_ffn(self):
        """No-drop regime: y_i == gate_i * FFN_{e_i}(x_i) exactly."""
        x, router, w1, b1, w2, b2 = _layer_data()
        y, aux = switch_moe(x, router, w1, b1, w2, b2,
                            capacity_factor=8.0)
        probs = jax.nn.softmax(x @ router)
        e = jnp.argmax(probs, axis=-1)
        gate = jnp.take_along_axis(probs, e[:, None], axis=-1)[:, 0]
        import flax.linen as nn

        h = nn.gelu(jnp.einsum("nc,ncf->nf", x, w1[e]) + b1[e])
        expect = (jnp.einsum("nf,nfc->nc", h, w2[e]) + b2[e]) * gate[:, None]
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=1e-5, atol=1e-5)
        assert float(aux) > 0

    def test_capacity_drops_overflow(self):
        """capacity_factor ~0 forces drops: dropped tokens emit zeros."""
        x, router, w1, b1, w2, b2 = _layer_data(N=32, E=4)
        y, _ = switch_moe(x, router, w1, b1, w2, b2,
                          capacity_factor=0.125)  # capacity 1/expert
        # At most E tokens (one per expert) can be non-zero.
        nonzero = np.count_nonzero(
            np.abs(np.asarray(y)).sum(axis=-1) > 1e-9)
        assert nonzero <= 4

    def test_ep_sharded_matches_local(self):
        """8-way EP over the mesh == all-experts-local (no drops)."""
        x, router, w1, b1, w2, b2 = _layer_data()
        expect, aux_e = switch_moe(x, router, w1, b1, w2, b2,
                                   capacity_factor=8.0)
        mesh = hvd.mesh()
        n = hvd.size()

        def spmd(x, router, w1s, b1s, w2s, b2s):
            y, aux = switch_moe(
                x, router, w1s[0], b1s[0], w2s[0], b2s[0],
                axis=hvd.HVD_AXES, capacity_factor=8.0)
            # y is identical on every rank (same tokens everywhere) but
            # vma cannot prove it — emit stacked per-rank copies.
            return y[None], hvd.allreduce(aux, op=hvd.Average)

        stack = lambda a: jnp.stack(jnp.split(a, n, axis=0))
        y, aux = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(), P(hvd.HVD_AXES), P(hvd.HVD_AXES),
                      P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(hvd.HVD_AXES), P())))(
            x, router, stack(w1), stack(b1), stack(w2), stack(b2))
        for r in range(n):   # every rank's copy equals the local reference
            np.testing.assert_allclose(np.asarray(y[r]), np.asarray(expect),
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(aux), float(aux_e), rtol=1e-5)

    def test_expert_count_must_divide(self):
        x, router, w1, b1, w2, b2 = _layer_data(E=8)
        with pytest.raises(ValueError, match="experts"):
            # Router says 8 experts but locals x axis = 8 * 8 = 64.
            jax.jit(hvd.shard_map(
                lambda x, r, a, b, c, d: switch_moe(
                    x, r, a, b, c, d, axis=hvd.HVD_AXES)[0],
                mesh=hvd.mesh(),
                in_specs=(P(), P(), P(), P(), P(), P()),
                out_specs=P()))(x, router, w1, b1, w2, b2)


def _per_token_expect(x, router, w1, b1, w2, b2):
    import flax.linen as nn

    probs = jax.nn.softmax(x @ router)
    e = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, e[:, None], axis=-1)[:, 0]
    h = nn.gelu(jnp.einsum("nc,ncf->nf", x, w1[e]) + b1[e])
    return (jnp.einsum("nf,nfc->nc", h, w2[e]) + b2[e]) * gate[:, None]


class TestSwitchMoERagged:
    def test_matches_per_token_ffn_world1(self):
        x, router, w1, b1, w2, b2 = _layer_data()
        y, aux = switch_moe_ragged(x, router, w1, b1, w2, b2,
                                   capacity_factor=8.0)
        expect = _per_token_expect(x, router, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=1e-5, atol=1e-5)
        assert float(aux) > 0

    def test_ep_sharded_per_rank_tokens(self):
        """8-way EP, DIFFERENT tokens per rank, no drops: exact against
        the per-token FFN on every rank's own tokens."""
        n = hvd.size()
        Np, C, F, E = 8, 16, 32, 8
        rs = np.random.RandomState(3)
        x_all = jnp.asarray(rs.randn(n * Np, C), jnp.float32) * 0.5
        _, router, w1, b1, w2, b2 = _layer_data(C=C, F=F, E=E, seed=3)

        def spmd(x, router, w1s, b1s, w2s, b2s):
            y, aux = switch_moe_ragged(
                x, router, w1s[0], b1s[0], w2s[0], b2s[0],
                axis=hvd.HVD_AXES, capacity_factor=8.0,
                pair_capacity_factor=8.0)
            return y, hvd.allreduce(aux, op=hvd.Average)

        stack = lambda a: jnp.stack(jnp.split(a, n, axis=0))
        y, _ = jax.jit(hvd.shard_map(
            spmd, mesh=hvd.mesh(),
            in_specs=(P(hvd.HVD_AXES), P(), P(hvd.HVD_AXES),
                      P(hvd.HVD_AXES), P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(hvd.HVD_AXES), P())))(
            x_all, router, stack(w1), stack(b1), stack(w2), stack(b2))
        expect = _per_token_expect(x_all, router, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=1e-4, atol=1e-5)

    def test_ragged_pools_capacity_fixed_drops(self):
        """Sender-skewed routing: the fixed path's per-(sender, expert)
        quota drops tokens the ragged pooled capacity keeps."""
        n = hvd.size()
        Np, C = 8, 8
        E, F = 8, 16
        # Router ~ 10*I with C == E: token one_hot(e) routes to expert e.
        router = jnp.eye(C, E) * 10.0
        rs = np.random.RandomState(4)
        w1 = jnp.asarray(rs.randn(E, C, F), jnp.float32) * 0.1
        b1 = jnp.asarray(rs.randn(E, F), jnp.float32) * 0.01
        w2 = jnp.asarray(rs.randn(E, F, C), jnp.float32) * 0.1
        b2 = jnp.asarray(rs.randn(E, C), jnp.float32) * 0.01
        # Rank 0's tokens ALL route to expert 0; rank r>0's tokens to
        # expert r. Global expert-0 load (8) == pooled cap at cf=1.0
        # (N*n/E = 8), but blows the per-sender quota (N*cf/E = 1).
        dest_e = np.zeros((n, Np), np.int64)
        for r in range(1, n):
            dest_e[r, :] = r
        x_all = jnp.asarray(np.eye(C)[dest_e.reshape(-1)], jnp.float32)

        def run(moe_fn, **kw):
            def spmd(x, router, w1s, b1s, w2s, b2s):
                y, _ = moe_fn(x, router, w1s[0], b1s[0], w2s[0], b2s[0],
                              axis=hvd.HVD_AXES, capacity_factor=1.0, **kw)
                return y

            stack = lambda a: jnp.stack(jnp.split(a, n, axis=0))
            return np.asarray(jax.jit(hvd.shard_map(
                spmd, mesh=hvd.mesh(),
                in_specs=(P(hvd.HVD_AXES), P(), P(hvd.HVD_AXES),
                          P(hvd.HVD_AXES), P(hvd.HVD_AXES),
                          P(hvd.HVD_AXES)),
                out_specs=P(hvd.HVD_AXES)))(
                x_all, router, stack(w1), stack(b1), stack(w2), stack(b2)))

        y_fixed = run(switch_moe)
        y_ragged = run(switch_moe_ragged, pair_capacity_factor=8.0)
        # Fixed: rank 0 keeps only 1 of its 8 expert-0 tokens.
        rank0_fixed = np.abs(y_fixed[:Np]).sum(-1)
        assert np.count_nonzero(rank0_fixed > 1e-9) == 1
        # Ragged: pooled capacity keeps all of them — exact everywhere.
        expect = np.asarray(_per_token_expect(x_all, router, w1, b1, w2, b2))
        np.testing.assert_allclose(y_ragged, expect, rtol=1e-4, atol=1e-5)

    @pytest.mark.xfail(
        _old_jax(), strict=False,
        reason="upstream jax 0.4.37: grad-of-psum under old shard_map "
               "scales gradients by the axis size — pure-jax repro: "
               "tests/jax0437_repros.py::repro_grad_of_psum (fixed by "
               "the jax.shard_map graduation, jax >= 0.6)")
    def test_ragged_gradients_match_dense_no_drop(self):
        """d(loss)/d(params) through the ragged dispatch == world-1."""
        n = hvd.size()
        Np, C, F, E = 4, 8, 16, 8
        rs = np.random.RandomState(5)
        x_all = jnp.asarray(rs.randn(n * Np, C), jnp.float32) * 0.5
        _, router, w1, b1, w2, b2 = _layer_data(C=C, F=F, E=E, seed=5)

        def loss_world1(w1, w2):
            y, _ = switch_moe_ragged(x_all, router, w1, b1, w2, b2,
                                     capacity_factor=8.0)
            return jnp.sum(y * y)

        g1 = jax.grad(loss_world1, argnums=(0, 1))(w1, w2)

        def loss_spmd(x, w1s, b1s, w2s, b2s):
            def inner(w1r, w2r):
                y, _ = switch_moe_ragged(
                    x, router, w1r, b1s[0], w2r, b2s[0],
                    axis=hvd.HVD_AXES, capacity_factor=8.0,
                    pair_capacity_factor=8.0)
                return jax.lax.psum(jnp.sum(y * y), hvd.HVD_AXES)

            return jax.grad(inner, argnums=(0, 1))(w1s[0], w2s[0])

        stack = lambda a: jnp.stack(jnp.split(a, n, axis=0))
        g8 = jax.jit(hvd.shard_map(
            loss_spmd, mesh=hvd.mesh(),
            in_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES), P(hvd.HVD_AXES),
                      P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES))))(
            x_all, stack(w1), stack(b1), stack(w2), stack(b2))
        np.testing.assert_allclose(np.asarray(g8[0]).reshape(w1.shape),
                                   np.asarray(g1[0]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(g8[1]).reshape(w2.shape),
                                   np.asarray(g1[1]), rtol=1e-4, atol=1e-5)


class TestMoEGPT:
    def test_moe_gpt_trains(self):
        """World-1 MoE GPT: loss decreases with router aux loss mixed in."""
        cfg = gpt_tiny(dtype=jnp.float32, moe_experts=4)
        B, T = 4, 32
        rs = np.random.RandomState(0)
        toks = rs.randint(0, cfg.vocab_size, (B, T + 1))
        x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
        model = GPT(cfg)
        variables = model.init(jax.random.PRNGKey(0), x)
        tx = optax.adam(1e-2)
        opt = tx.init(variables["params"])

        @jax.jit
        def step(p, s):
            def loss_fn(p):
                logits, inter = model.apply(
                    {"params": p}, x, mutable=["intermediates"])
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()
                aux = sum(jax.tree.leaves(inter["intermediates"]))
                return loss + 0.01 * aux
            loss, g = jax.value_and_grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            return optax.apply_updates(p, u), s, loss

        params = variables["params"]
        losses = []
        for _ in range(8):
            params, opt, loss = step(params, opt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_dp_ep_moe_trains_with_aux_balancing(self):
        """End-to-end DP x EP TRAINING step: the router's sown aux losses
        are collected (``mutable=['intermediates']``) and mixed into the
        objective, so load balancing has gradient effect in the
        distributed wiring too — the pattern users should copy (advisor
        r3: no training path retrieved the sown aux)."""
        cfg = gpt_tiny(dtype=jnp.float32, moe_experts=8,
                       moe_capacity_factor=8.0)
        B, T = 4, 16
        rs = np.random.RandomState(2)
        toks = rs.randint(0, cfg.vocab_size, (B, T + 1))
        x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
        variables = GPT(cfg).init(jax.random.PRNGKey(0), x)
        mesh = hvd.mesh()
        n_ep = mesh.devices.shape[1]
        ep_cfg = dataclasses.replace(cfg, ep_axis=hvd.LOCAL_AXIS)
        sharded, repl = ep_split_params(variables["params"], n_ep)

        def spmd(stk, rp, tok, tgt):
            def loss_fn(stk, rp):
                local = tp_merge_params(
                    jax.tree.map(lambda a: a[0], stk), rp)
                logits, inter = GPT(ep_cfg).apply(
                    {"params": local}, tok, mutable=["intermediates"])
                task = optax.softmax_cross_entropy_with_integer_labels(
                    logits, tgt).mean()
                aux = sum(jax.tree.leaves(inter["intermediates"]))
                return task + 0.01 * aux, aux

            (loss, aux), (g_stk, g_rp) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(stk, rp)
            # Replicated params (router included): grads averaged over the
            # whole mesh. Expert shards live on one ep rank each: average
            # over the data axis only.
            g_rp = hvd.allreduce_pytree(g_rp, op=hvd.Average)
            g_stk = hvd.allreduce_pytree(g_stk, op=hvd.Average,
                                         axes=hvd.CROSS_AXIS)
            stk = jax.tree.map(lambda p, g: p - 0.05 * g, stk, g_stk)
            rp = jax.tree.map(lambda p, g: p - 0.05 * g, rp, g_rp)
            return (stk, rp, hvd.allreduce(loss, op=hvd.Average),
                    hvd.allreduce(aux, op=hvd.Average))

        step = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.CROSS_AXIS),
                      P(hvd.CROSS_AXIS)),
            out_specs=(P(hvd.LOCAL_AXIS), P(), P(), P())))
        losses, auxes = [], []
        for _ in range(6):
            sharded, repl, loss, aux = step(sharded, repl, x, y)
            losses.append(float(loss))
            auxes.append(float(aux))
        assert losses[-1] < losses[0], losses
        assert all(np.isfinite(a) and a > 0 for a in auxes), auxes

    def test_dp_ep_gpt_matches_dense_params(self):
        """DP over cross x EP over local: forward equals the world-1 MoE
        model on the same (sliced) parameters."""
        cfg = gpt_tiny(dtype=jnp.float32, moe_experts=8,
                       moe_capacity_factor=8.0)
        B, T = 4, 16
        rs = np.random.RandomState(1)
        tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
        variables = GPT(cfg).init(jax.random.PRNGKey(0), tokens)
        expect = GPT(cfg).apply(variables, tokens)

        mesh = hvd.mesh()
        n_ep = mesh.devices.shape[1]
        ep_cfg = dataclasses.replace(cfg, ep_axis=hvd.LOCAL_AXIS)
        sharded, repl = ep_split_params(variables["params"], n_ep)

        def spmd(stk, rp, tok):
            local = tp_merge_params(
                jax.tree.map(lambda a: a[0], stk), rp)
            logits = GPT(ep_cfg).apply({"params": local}, tok)
            # Identical across the ep axis in value (every rank holds the
            # full combined output) but not provably so — stack copies.
            return logits[None]

        out = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.CROSS_AXIS)),
            out_specs=P(hvd.LOCAL_AXIS, hvd.CROSS_AXIS)))(
            sharded, repl, tokens)
        for r in range(n_ep):
            np.testing.assert_allclose(np.asarray(out[r]),
                                       np.asarray(expect),
                                       rtol=2e-4, atol=2e-4)
