"""Spark/Ray platform integration tests (reference analogue:
test/integration/test_spark.py + test/single/test_ray.py — run without a
real cluster by exercising the pure coordination logic and gating)."""

import pytest

from horovod_tpu.ray import Coordinator, RayExecutor, RayHostDiscovery
from horovod_tpu.spark import build_task_env
from horovod_tpu.spark.store import LocalStore, Store
from horovod_tpu.spark.estimator import (
    KerasEstimator,
    TorchEstimator,
    _EstimatorParams,
)


class TestSparkTaskEnv:
    def test_single_host(self):
        env = build_task_env(1, ["h1", "h1", "h1"], 9000)
        assert env["HOROVOD_RANK"] == "1"
        assert env["HOROVOD_SIZE"] == "3"
        assert env["HOROVOD_LOCAL_RANK"] == "1"
        assert env["HOROVOD_LOCAL_SIZE"] == "3"
        assert env["HOROVOD_CROSS_RANK"] == "0"
        assert env["HOROVOD_CROSS_SIZE"] == "1"
        assert env["HOROVOD_CONTROLLER_ADDR"] == "h1"
        assert env["HOROVOD_CONTROLLER_PORT"] == "9000"

    def test_multi_host_grouping(self):
        addrs = ["a", "a", "b", "b"]
        env2 = build_task_env(2, addrs, 9000)
        assert env2["HOROVOD_LOCAL_RANK"] == "0"
        assert env2["HOROVOD_CROSS_RANK"] == "1"
        assert env2["HOROVOD_CROSS_SIZE"] == "2"
        env3 = build_task_env(3, addrs, 9000)
        assert env3["HOROVOD_LOCAL_RANK"] == "1"
        # controller always lives with rank 0's host
        assert env3["HOROVOD_CONTROLLER_ADDR"] == "a"

    def test_base_env_preserved(self):
        env = build_task_env(0, ["h"], 1, base_env={"FOO": "bar"})
        assert env["FOO"] == "bar"


class TestSparkGating:
    def test_run_requires_pyspark(self):
        import horovod_tpu.spark as sp

        with pytest.raises(ImportError, match="pyspark"):
            sp.run(lambda: None, num_proc=2)

    def test_estimator_param_validation(self):
        with pytest.raises(ValueError, match="model"):
            _EstimatorParams(model=None, feature_cols=["x"],
                             label_cols=["y"])
        with pytest.raises(ValueError, match="feature_cols"):
            _EstimatorParams(model=object(), feature_cols=None,
                             label_cols=["y"])


class TestLocalStore:
    def test_paths_and_io(self, tmp_path):
        store = LocalStore(str(tmp_path / "artifacts"))
        ckpt = store.get_checkpoint_path("run_7")
        assert "run_7" in ckpt
        store.write(ckpt + "/weights.bin", b"abc123")
        assert store.exists(ckpt + "/weights.bin")
        assert store.read(ckpt + "/weights.bin") == b"abc123"
        assert store.get_train_data_path(0).endswith(
            "intermediate_train_data.0")

    def test_create_picks_local(self, tmp_path):
        s = Store.create(str(tmp_path / "x"))
        assert isinstance(s, LocalStore)


class TestRayCoordinator:
    def test_single_node(self):
        c = Coordinator()
        for r in range(4):
            c.register("n1", r)
        envs = c.finalize_registration()
        assert c.world_size == 4
        assert envs[2]["HOROVOD_LOCAL_RANK"] == "2"
        assert envs[2]["HOROVOD_CROSS_SIZE"] == "1"

    def test_multi_node_host_grouping(self):
        c = Coordinator()
        c.register("n1", 0)
        c.register("n1", 1)
        c.register("n2", 2)
        c.register("n2", 3)
        envs = c.finalize_registration()
        assert envs[3]["HOROVOD_LOCAL_RANK"] == "1"
        assert envs[3]["HOROVOD_CROSS_RANK"] == "1"
        assert envs[3]["HOROVOD_LOCAL_SIZE"] == "2"
        assert envs[0]["HOROVOD_SIZE"] == "4"

    def test_interleaved_registration_renumbered_host_major(self):
        """PACK scheduling can interleave hosts in registration order; the
        coordinator must renumber world ranks host-major so
        rank == cross_rank*local_size + local_rank holds (the invariant
        hierarchical collectives and the native fail-fast check rely on)."""
        c = Coordinator()
        c.register("n1", 0)
        c.register("n2", 1)
        c.register("n1", 2)
        c.register("n2", 3)
        envs = c.finalize_registration()
        for reg_id, env in envs.items():
            rank = int(env["HOROVOD_RANK"])
            assert rank == (int(env["HOROVOD_CROSS_RANK"])
                            * int(env["HOROVOD_LOCAL_SIZE"])
                            + int(env["HOROVOD_LOCAL_RANK"])), env
        # n1 got ranks 0,1 (reg ids 0,2); n2 got 2,3 (reg ids 1,3)
        assert envs[0]["HOROVOD_RANK"] == "0"
        assert envs[2]["HOROVOD_RANK"] == "1"
        assert envs[1]["HOROVOD_RANK"] == "2"
        assert envs[3]["HOROVOD_RANK"] == "3"

    def test_rendezvous_env(self):
        c = Coordinator()
        env = c.establish_rendezvous("10.0.0.1", 12345)
        assert env == {"HOROVOD_CONTROLLER_ADDR": "10.0.0.1",
                       "HOROVOD_CONTROLLER_PORT": "12345"}


class TestRayGating:
    def test_executor_requires_ray(self):
        ex = RayExecutor(num_workers=2)
        with pytest.raises(ImportError, match="ray"):
            ex.start()

    def test_discovery_requires_ray(self):
        d = RayHostDiscovery()
        with pytest.raises(ImportError, match="ray"):
            d.find_available_hosts_and_slots()
