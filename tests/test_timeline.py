"""Timeline tests (reference: test/parallel/test_timeline.py — run a job
with HOROVOD_TIMELINE set and validate the JSON trace)."""

import json

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.utils.timeline import Timeline


def test_timeline_writes_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "timeline.json")
    tl = Timeline(path, mark_cycles=True)
    tl.begin("grads", "NEGOTIATE_ALLREDUCE")
    tl.end("grads", "NEGOTIATE_ALLREDUCE")
    with tl.trace("grads", "XLA_ALLREDUCE"):
        pass
    tl.mark_cycle_start()
    tl.instant("STEP", args={"step": 1})
    tl.close()

    events = json.load(open(path))
    names = [e["name"] for e in events]
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "XLA_ALLREDUCE" in names
    assert "CYCLE_START" in names
    assert "STEP" in names
    phases = {e["ph"] for e in events}
    assert {"B", "E", "i"} <= phases
    # Begin/End pairing per tid
    for tid in {e["tid"] for e in events}:
        stack = 0
        for e in events:
            if e["tid"] != tid:
                continue
            if e["ph"] == "B":
                stack += 1
            elif e["ph"] == "E":
                stack -= 1
                assert stack >= 0
        assert stack == 0


def test_start_stop_timeline_runtime(tmp_path):
    path = str(tmp_path / "tl.json")
    tl = hvd.start_timeline(path)
    tl.instant("MARK")
    hvd.stop_timeline()
    events = json.load(open(path))
    assert any(e["name"] == "MARK" for e in events)


def test_poll_after_synchronize_reports_done():
    # Regression: poll on a cleared handle must return True, not raise
    # (reference HandleManager contract).
    h = hvd.allreduce_async(jnp.zeros(2), name="pollsync")
    hvd.synchronize(h)
    assert hvd.poll(h) is True


# ---------------------------------------------------------------------------
# Shutdown-ordering regressions (PR 7 satellite): close() must flush the
# writer queue and join the writer thread; start/stop must be idempotent.


def test_close_flushes_all_queued_events(tmp_path):
    # Regression: a burst emitted right before close() used to race the
    # daemon writer thread — close() now drains the queue and joins the
    # writer, so EVERY event emitted before close lands in the file.
    path = str(tmp_path / "flush.json")
    tl = Timeline(path)
    n = 5000
    for i in range(n):
        tl.instant(f"EV{i}", tid="burst")
    tl.close()
    events = json.load(open(path))
    burst = [e for e in events if e["tid"] == "burst"]
    assert len(burst) == n
    assert burst[0]["name"] == "EV0" and burst[-1]["name"] == f"EV{n - 1}"


def test_close_is_idempotent(tmp_path):
    path = str(tmp_path / "twice.json")
    tl = Timeline(path)
    tl.instant("ONE")
    tl.close()
    tl.close()  # second close: no-op, no double-bracket corruption
    events = json.load(open(path))
    assert [e["name"] for e in events] == ["ONE"]


def test_concurrent_emit_during_close_keeps_file_valid(tmp_path):
    # Events racing close() may or may not land (closed flag), but the
    # file must stay a parseable Chrome trace either way.
    import threading

    path = str(tmp_path / "race.json")
    tl = Timeline(path)
    stop = threading.Event()

    def emitter():
        i = 0
        while not stop.is_set():
            tl.instant(f"R{i}")
            i += 1

    t = threading.Thread(target=emitter)
    t.start()
    try:
        tl.close()
    finally:
        stop.set()
        t.join()
    json.load(open(path))  # parseable = balanced brackets, no torn line


def test_stop_timeline_idempotent_and_restart(tmp_path):
    p1 = str(tmp_path / "a.json")
    p2 = str(tmp_path / "b.json")
    tl1 = hvd.start_timeline(p1)
    tl1.instant("A")
    # restart without an explicit stop: the old timeline must be closed
    # into a valid trace before the new one attaches
    tl2 = hvd.start_timeline(p2)
    tl2.instant("B")
    hvd.stop_timeline()
    hvd.stop_timeline()  # second stop: no-op
    assert any(e["name"] == "A" for e in json.load(open(p1)))
    assert any(e["name"] == "B" for e in json.load(open(p2)))


def test_counter_events(tmp_path):
    path = str(tmp_path / "c.json")
    tl = Timeline(path)
    tl.counter("METRIC:depth", {"value": 3})
    tl.close()
    events = json.load(open(path))
    cs = [e for e in events if e["ph"] == "C"]
    assert cs and cs[0]["name"] == "METRIC:depth"
    assert cs[0]["args"]["value"] == 3
