"""Timeline tests (reference: test/parallel/test_timeline.py — run a job
with HOROVOD_TIMELINE set and validate the JSON trace)."""

import json

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.utils.timeline import Timeline


def test_timeline_writes_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "timeline.json")
    tl = Timeline(path, mark_cycles=True)
    tl.begin("grads", "NEGOTIATE_ALLREDUCE")
    tl.end("grads", "NEGOTIATE_ALLREDUCE")
    with tl.trace("grads", "XLA_ALLREDUCE"):
        pass
    tl.mark_cycle_start()
    tl.instant("STEP", args={"step": 1})
    tl.close()

    events = json.load(open(path))
    names = [e["name"] for e in events]
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "XLA_ALLREDUCE" in names
    assert "CYCLE_START" in names
    assert "STEP" in names
    phases = {e["ph"] for e in events}
    assert {"B", "E", "i"} <= phases
    # Begin/End pairing per tid
    for tid in {e["tid"] for e in events}:
        stack = 0
        for e in events:
            if e["tid"] != tid:
                continue
            if e["ph"] == "B":
                stack += 1
            elif e["ph"] == "E":
                stack -= 1
                assert stack >= 0
        assert stack == 0


def test_start_stop_timeline_runtime(tmp_path):
    path = str(tmp_path / "tl.json")
    tl = hvd.start_timeline(path)
    tl.instant("MARK")
    hvd.stop_timeline()
    events = json.load(open(path))
    assert any(e["name"] == "MARK" for e in events)


def test_poll_after_synchronize_reports_done():
    # Regression: poll on a cleared handle must return True, not raise
    # (reference HandleManager contract).
    h = hvd.allreduce_async(jnp.zeros(2), name="pollsync")
    hvd.synchronize(h)
    assert hvd.poll(h) is True
