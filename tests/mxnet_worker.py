"""Worker for multi-process MXNet binding tests (reference analogue:
``mpirun -np 2 pytest test_mxnet.py``, SURVEY §4). Runs against the
fake-mxnet shim (tests/fake_mxnet.py) over the real native TCP data plane."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import fake_mxnet  # noqa: E402

mx = fake_mxnet.install()

import horovod_tpu.mxnet as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size

    # -- allreduce: average (default), sum, in-place, prescale --
    t = mx.nd.array(np.full(4, float(rank), np.float32))
    out = hvd.allreduce(t)
    assert np.allclose(out.asnumpy(), sum(range(size)) / size), out
    assert np.allclose(t.asnumpy(), rank), "input mutated"

    hvd.allreduce_(t, average=False, prescale_factor=2.0,
                   postscale_factor=0.5)
    assert np.allclose(t.asnumpy(), float(sum(range(size)))), t

    # -- allgather with per-rank dim0 --
    g = hvd.allgather(mx.nd.array(np.full((rank + 1, 2), rank, np.float32)))
    expect = np.concatenate([np.full((r + 1, 2), r) for r in range(size)])
    assert np.allclose(g.asnumpy(), expect), g

    # -- broadcast in/out of place --
    b = hvd.broadcast(mx.nd.array(np.full(3, rank, np.float32)), root_rank=1)
    assert np.allclose(b.asnumpy(), 1.0), b
    b2 = mx.nd.array(np.full(3, rank, np.float32))
    hvd.broadcast_(b2, root_rank=0)
    assert np.allclose(b2.asnumpy(), 0.0), b2

    # -- alltoall, even splits --
    a = hvd.alltoall(mx.nd.array(np.arange(size * 2, dtype=np.float32)
                                 + 100 * rank))
    expect = np.concatenate([np.arange(2) + 2 * rank + 100 * r
                             for r in range(size)])
    assert np.allclose(a.asnumpy(), expect), a

    # -- broadcast_object / allgather_object --
    obj = hvd.broadcast_object({"epoch": rank, "tag": f"r{rank}"},
                               root_rank=0)
    assert obj == {"epoch": 0, "tag": "r0"}, obj
    objs = hvd.allgather_object(("rank", rank))
    assert objs == [("rank", r) for r in range(size)], objs

    # -- DistributedOptimizer: grads summed, average folded in rescale --
    w = mx.nd.array(np.ones(3, np.float32))
    grad = mx.nd.array(np.full(3, float(rank + 1), np.float32))
    opt = hvd.DistributedOptimizer(mx.optimizer.SGD(learning_rate=1.0))
    opt.update(0, w, grad, None)
    # rescale_grad = 1/size, grads summed -> effective grad = mean(rank+1)
    mean_grad = sum(r + 1 for r in range(size)) / size
    assert np.allclose(w.asnumpy(), 1.0 - mean_grad), w
    # every rank's weight identical after the update
    gathered = hvd.allgather(mx.nd.array(w.asnumpy()[None, :]))
    gn = gathered.asnumpy()
    assert np.allclose(gn[0], gn[-1]), gn

    # -- DistributedTrainer over gluon parameters --
    p = mx.gluon.parameter.Parameter("dense0_weight")
    p.initialize(np.ones(4, np.float32) * (rank + 5))
    trainer = hvd.DistributedTrainer([p], "sgd",
                                     {"learning_rate": 0.5})
    hvd.broadcast_parameters({"dense0_weight": p}, root_rank=0)
    assert np.allclose(p.data().asnumpy(), 5.0), p.data()
    p.list_grad()[0][:] = np.full(4, float(rank), np.float32)
    trainer.step(batch_size=1)
    # scale=1/size, grads summed -> w = 5 - 0.5 * mean(rank) everywhere
    expect_w = 5.0 - 0.5 * (sum(range(size)) / size)
    assert np.allclose(p.data().asnumpy(), expect_w), p.data()

    # -- deferred-init parameter: broadcast injected after materialize --
    d = mx.gluon.parameter.Parameter("late_weight")
    hvd.broadcast_parameters({"late_weight": d}, root_rank=0)
    d.initialize(np.full(2, float(rank + 7), np.float32))
    assert np.allclose(d.data().asnumpy(), 7.0), d.data()

    hvd.shutdown()
    print(f"rank {rank}: mxnet worker OK")


if __name__ == "__main__":
    main()
