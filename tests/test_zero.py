"""ZeRO-1 sharded optimizer tests (docs/zero.md).

Core invariants:
  * the sharded update is numerically the replicated update — bit-identical
    for SGD given the same gradients, allclose for Adam across a training
    trajectory;
  * every optimizer-moment leaf is exactly ``1/world`` of its bucket's
    padded size (the memory claim);
  * composition with the quantized int8 wire + error feedback, local
    gradient accumulation, and gradient predivide;
  * host-side state reshard round-trips through ``hvd.elastic`` at a
    different world size.

All compiled tests run on the 8-device CPU mesh shaped 2x4 so the
reduce-scatter/all-gather decomposition has a real cross (DCN) hop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import fusion

N = 8


@pytest.fixture(scope="module", autouse=True)
def _mesh_2x4():
    """Re-init the world as an emulated 2-host x 4-chip mesh so the
    reduce-scatter/all-gather decomposition (and the quantized DCN leg)
    has a real cross hop; restore the default mesh for later modules."""
    hvd.shutdown()
    hvd.init(mesh_shape=(2, 4))
    yield
    hvd.shutdown()
    hvd.init()


def loss_fn(params, batch):
    x, y = batch
    return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)


def make_data(rng, n=96, d=5):
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ rng.randn(d, 1).astype(np.float32)
         + 0.1 * rng.randn(n, 1).astype(np.float32))
    return x, y


def init_params(d=5):
    return {"w": jnp.zeros((d, 1)), "b": jnp.zeros((1,))}


def _put_zero_state(state, mesh):
    spec = hvd.zero_state_pspecs(state)
    return jax.device_put(
        state, jax.tree.map(lambda s: NamedSharding(mesh, s), spec)), spec


def train(tx, zero, x, y, steps, bs=16, reduce_in_optimizer=True):
    """shard_map DP training; under ``reduce_in_optimizer`` the raw
    per-rank local gradients are handed to the optimizer (the canonical
    ZeRO step structure)."""
    params = init_params(x.shape[1])
    state = tx.init(params)
    mesh = hvd.mesh()
    if zero:
        state, sspec = _put_zero_state(state, mesh)
    else:
        sspec = jax.tree.map(lambda _: P(), state)

    @jax.jit
    def step(params, state, xb, yb):
        def spmd(params, state, xb, yb):
            loss, grads = hvd.value_and_grad(
                loss_fn, reduce=not reduce_in_optimizer)(params, (xb, yb))
            updates, ns = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), ns, \
                hvd.allreduce(loss)

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), sspec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), sspec, P()))(params, state, xb, yb)

    losses = []
    for i in range(steps):
        params, state, loss = step(params, state,
                                   jnp.asarray(x[i * bs:(i + 1) * bs]),
                                   jnp.asarray(y[i * bs:(i + 1) * bs]))
        losses.append(float(loss))
    return params, state, losses


# --- parity ----------------------------------------------------------------


def test_sgd_update_bit_identical_to_replicated():
    """Same gradients in, bit-identical updates out: both the sharded and
    the replicated SGD-momentum update run in ONE compiled step on the
    identical auto-psummed gradient, over 3 steps of evolving moments."""
    rng = np.random.RandomState(0)
    x, y = make_data(rng, n=48)
    tx_z = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9), zero=True)
    tx_r = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    params = init_params()
    sz = tx_z.init(params)
    sr = tx_r.init(params)
    mesh = hvd.mesh()
    sz, zspec = _put_zero_state(sz, mesh)
    rspec = jax.tree.map(lambda _: P(), sr)

    @jax.jit
    def step(params, sz, sr, xb, yb):
        def spmd(params, sz, sr, xb, yb):
            loss, grads = jax.value_and_grad(loss_fn)(params, (xb, yb))
            uz, nsz = tx_z.update(grads, sz, params)
            ur, nsr = tx_r.update(grads, sr, params)
            return uz, ur, nsz, nsr

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), zspec, rspec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), P(), zspec, rspec))(params, sz, sr, xb, yb)

    for i in range(3):
        uz, ur, sz, sr = step(params, sz, sr,
                              jnp.asarray(x[i * 16:(i + 1) * 16]),
                              jnp.asarray(y[i * 16:(i + 1) * 16]))
        for k in ur:
            np.testing.assert_array_equal(np.asarray(uz[k]),
                                          np.asarray(ur[k]))
        params = optax.apply_updates(params, ur)


def test_sgd_training_parity_local_grads():
    """Full training trajectory with the canonical ZeRO structure (local
    grads → optimizer-owned reduce-scatter) matches replicated training
    (auto-psummed grads) to fp tolerance."""
    rng = np.random.RandomState(1)
    x, y = make_data(rng)
    pz, _, _ = train(hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                              zero=True),
                     True, x, y, steps=4)
    pr, _, _ = train(hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9)),
                     False, x, y, steps=4)
    for k in pr:
        np.testing.assert_allclose(np.asarray(pz[k]), np.asarray(pr[k]),
                                   rtol=2e-5, atol=1e-7)


def test_adam_training_parity():
    rng = np.random.RandomState(2)
    x, y = make_data(rng)
    pz, _, _ = train(hvd.DistributedOptimizer(optax.adam(1e-2), zero=True),
                     True, x, y, steps=4)
    pr, _, _ = train(hvd.DistributedOptimizer(optax.adam(1e-2)),
                     False, x, y, steps=4)
    for k in pr:
        np.testing.assert_allclose(np.asarray(pz[k]), np.asarray(pr[k]),
                                   rtol=1e-5, atol=1e-6)


def test_zero_matches_single_device_global_batch():
    """The reference's core correctness property, ZeRO edition: sharded DP
    training == single-device training on the concatenated batch."""
    rng = np.random.RandomState(3)
    x, y = make_data(rng, n=64)
    pz, _, _ = train(hvd.DistributedOptimizer(optax.sgd(0.1), zero=True),
                     True, x, y, steps=4)
    params = init_params()
    opt = optax.sgd(0.1)
    state = opt.init(params)

    @jax.jit
    def step(params, state, xb, yb):
        grads = jax.grad(loss_fn)(params, (xb, yb))
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    for i in range(4):
        params, state = step(params, state,
                             jnp.asarray(x[i * 16:(i + 1) * 16]),
                             jnp.asarray(y[i * 16:(i + 1) * 16]))
    for k in params:
        np.testing.assert_allclose(np.asarray(pz[k]), np.asarray(params[k]),
                                   rtol=1e-4, atol=1e-6)


# --- state layout ----------------------------------------------------------


def test_moment_leaves_are_one_world_th():
    """Every non-scalar inner-state leaf is a flat bucket array whose
    per-rank shard is exactly padded_size // world — the ZeRO memory
    claim, asserted on the device shards themselves."""
    rng = np.random.RandomState(4)
    x, y = make_data(rng)
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero=True)
    _, state, _ = train(tx, True, x, y, steps=1)
    plan = fusion.plan_buckets(jax.tree.leaves(init_params()),
                               shard_multiple=N)
    padded = {b.padded_size for b in plan}
    moment_leaves = [l for l in jax.tree.leaves(state.inner)
                     if getattr(l, "ndim", 0) >= 1]
    assert moment_leaves, "no moment leaves found"
    for leaf in moment_leaves:
        assert leaf.shape[0] in padded  # global view: the full flat bucket
        # the actual per-device shard is 1/world of it
        shards = {s.data.shape for s in leaf.addressable_shards}
        assert shards == {(leaf.shape[0] // N,)}, shards


def test_zero_state_pspecs_shape():
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero=True)
    state = tx.init(init_params())
    spec = hvd.zero_state_pspecs(state)
    flat_state = jax.tree.leaves(state)
    flat_spec = jax.tree.leaves(spec, is_leaf=lambda s: isinstance(s, P))
    assert len(flat_state) == len(flat_spec)
    for l, s in zip(flat_state, flat_spec):
        if getattr(l, "ndim", 0) >= 1:
            assert s == P(hvd.HVD_AXES)
        else:
            assert s == P()


def test_plan_buckets_shard_multiple():
    leaves = [jnp.zeros((130,)), jnp.zeros((7,)), jnp.zeros((3, 3))]
    for world in (1, 3, 8):
        plan = fusion.plan_buckets(leaves, shard_multiple=world)
        for b in plan:
            assert b.padded_size % np.lcm(fusion.ATOMIC_UNIT, world) == 0
        # leaf->bucket assignment is world-independent
        base = fusion.plan_buckets(leaves)
        assert [b.leaf_indices for b in plan] == \
            [b.leaf_indices for b in base]
    # shard slicing round-trips
    buf = jnp.arange(192.0)
    shards = [fusion.shard_slice(buf, 8, r) for r in range(8)]
    assert all(s.shape == (24,) for s in shards)
    np.testing.assert_array_equal(np.asarray(fusion.shard_unslice(shards)),
                                  np.asarray(buf))


# --- primitives ------------------------------------------------------------


def test_reduce_scatter_all_gather_roundtrip():
    rng = np.random.RandomState(5)
    x = rng.randn(N, 256).astype(np.float32)

    def f(v):
        sh = hvd.reduce_scatter(v[0], op=hvd.Sum)
        return sh, hvd.all_gather(sh)

    sh, full = hvd.shard_map(
        f, mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
        out_specs=(P(hvd.HVD_AXES), P()))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(full), x.sum(0), rtol=1e-5)
    # the scatter shards concatenate (rank-major) to the reduction
    np.testing.assert_allclose(np.asarray(sh).ravel(), x.sum(0), rtol=1e-5)


def test_reduce_scatter_average_divides():
    rng = np.random.RandomState(6)
    x = rng.randn(N, 64).astype(np.float32)

    def f(v):
        return hvd.all_gather(hvd.reduce_scatter(v[0], op=hvd.Average))

    out = hvd.shard_map(f, mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                        out_specs=P())(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), x.mean(0), rtol=1e-5)


def test_reduce_scatter_rejects_indivisible():
    with pytest.raises(ValueError, match="does not divide"):
        hvd.shard_map(lambda v: hvd.reduce_scatter(v[0]),
                      mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                      out_specs=P(hvd.HVD_AXES))(
            jnp.zeros((N, 12), jnp.float32))


def test_quantized_reduce_scatter_error_bounded():
    """int8 DCN leg: the per-element error of the quantized reduce-scatter
    is bounded by the sum of per-sender block scales / 254."""
    rng = np.random.RandomState(7)
    x = rng.randn(N, 512).astype(np.float32)

    def f(v):
        sh = hvd.reduce_scatter(v[0], op=hvd.Sum, quantized=True, block=64)
        return hvd.all_gather(sh)

    out = hvd.shard_map(f, mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                        out_specs=P())(jnp.asarray(x))
    exact = x.sum(0)
    # Only the DCN (cross=2) hop quantizes; the 4-rank ICI leg is exact.
    # Each of the 2 cross senders quantizes its ICI-summed shard (absmax
    # up to 4x the input absmax), error <= scale/2 per element.
    scale_bound = 2 * (4 * np.abs(x).max() / 127.0)
    assert float(np.abs(np.asarray(out) - exact).max()) <= scale_bound


# --- composition -----------------------------------------------------------


def test_zero_quantized_error_feedback_compose():
    """zero + quantized: training tracks the fp ZeRO run and the EF
    residuals become (and stay) active."""
    rng = np.random.RandomState(8)
    x, y = make_data(rng)
    tq = hvd.DistributedOptimizer(optax.sgd(0.1), zero=True, quantized=True)
    tf_ = hvd.DistributedOptimizer(optax.sgd(0.1), zero=True,
                                   quantized=False)
    pq, sq, lq = train(tq, True, x, y, steps=6)
    pf, _, lf = train(tf_, True, x, y, steps=6)
    assert lq[-1] < lq[0]  # trains
    for k in pf:
        np.testing.assert_allclose(np.asarray(pq[k]), np.asarray(pf[k]),
                                   rtol=0.05, atol=5e-3)
    assert isinstance(sq, hvd.ZeroState)
    rs = [l for l in jax.tree.leaves(sq.residual) if l is not None]
    ag = [l for l in jax.tree.leaves(sq.gather_residual) if l is not None]
    assert rs and ag
    assert any(float(jnp.abs(l).max()) > 0 for l in rs)
    assert any(float(jnp.abs(l).max()) > 0 for l in ag)
    # residuals are shard-local: [world, padded/local] and [world, padded/world]
    plan = fusion.plan_buckets(jax.tree.leaves(init_params()),
                               shard_multiple=N)
    local = hvd.local_size()
    assert {tuple(l.shape) for l in rs} == \
        {(N, b.padded_size // local) for b in plan}
    assert {tuple(l.shape) for l in ag} == \
        {(N, b.padded_size // N) for b in plan}


def test_zero_backward_passes_accumulates_shard():
    """k accumulation microbatches then one apply == one step on the
    concatenated batch; the accumulator leaf is bucket-flat (1/world per
    rank), not a full gradient replica."""
    rng = np.random.RandomState(9)
    x, y = make_data(rng)
    tk = hvd.DistributedOptimizer(optax.sgd(0.1), zero=True,
                                  backward_passes_per_step=2)
    pk, sk, _ = train(tk, True, x, y, steps=2)
    t1 = hvd.DistributedOptimizer(optax.sgd(0.1), zero=True)
    p1, _, _ = train(t1, True, x, y, steps=1, bs=32)
    for k in p1:
        np.testing.assert_allclose(np.asarray(pk[k]), np.asarray(p1[k]),
                                   rtol=2e-5, atol=1e-7)
    plan = fusion.plan_buckets(jax.tree.leaves(init_params()),
                               shard_multiple=N)
    acc = jax.tree.leaves(sk.inner.acc_grads)
    assert {l.shape for l in acc} == {(b.padded_size,) for b in plan}
    for l in acc:  # sharded 1/world on device
        assert {s.data.shape for s in l.addressable_shards} == \
            {(l.shape[0] // N,)}


def test_zero_gradient_predivide():
    rng = np.random.RandomState(10)
    x, y = make_data(rng)
    pp, _, _ = train(hvd.DistributedOptimizer(
        optax.sgd(0.1), zero=True, gradient_predivide_factor=4.0),
        True, x, y, steps=2)
    pa, _, _ = train(hvd.DistributedOptimizer(optax.sgd(0.1), zero=True),
                     True, x, y, steps=2)
    for k in pa:
        np.testing.assert_allclose(np.asarray(pp[k]), np.asarray(pa[k]),
                                   rtol=1e-5, atol=1e-7)


def test_zero_env_knob(monkeypatch):
    from horovod_tpu.common import basics as B
    import dataclasses

    cfg = dataclasses.replace(B.config(), zero_sharding=True)
    monkeypatch.setattr(B._state, "config", cfg)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    state = tx.init(init_params())
    assert isinstance(state, hvd.ZeroState)


def test_eager_world_of_one_matches_plain_optimizer():
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero=True)
    ref = optax.adam(1e-2)
    params = init_params()
    rng = np.random.RandomState(11)
    x, y = make_data(rng, n=16)
    g = jax.grad(loss_fn)(params, (jnp.asarray(x), jnp.asarray(y)))
    uz, _ = tx.update(g, tx.init(params), params)
    ur, _ = ref.update(g, ref.init(params), params)
    for k in ur:
        np.testing.assert_allclose(np.asarray(uz[k]), np.asarray(ur[k]),
                                   rtol=1e-6, atol=1e-8)


# --- elastic reshard -------------------------------------------------------


def test_elastic_reshard_roundtrip():
    """ZeRO state round-trips through hvd.elastic save/restore at a
    different world size: 8 → 3 (different lcm padding: 64 vs 192) → 8 is
    the identity on every moment leaf, and training continues
    bit-identically afterwards."""
    rng = np.random.RandomState(12)
    x, y = make_data(rng)
    params0 = init_params()
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero=True)
    p1, s1, _ = train(tx, True, x, y, steps=2)
    host_state = jax.device_get(s1)

    # world 3 uses a different padding unit (lcm(64,3)=192)
    r3 = hvd.zero_reshard_state(host_state, params0, from_world=8,
                                to_world=3, to_local_size=3)
    plan3 = fusion.plan_buckets(jax.tree.leaves(params0), shard_multiple=3)
    for l in jax.tree.leaves(r3.inner):
        if getattr(l, "ndim", 0) >= 1:
            assert l.shape[0] in {b.padded_size for b in plan3}
            assert l.shape[0] % 3 == 0

    back = hvd.zero_reshard_state(r3, params0, from_world=3, to_world=8,
                                  to_local_size=4)
    for a, b in zip(jax.tree.leaves(host_state.inner),
                    jax.tree.leaves(back.inner)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # ride the hvd.elastic state container through save/restore/sync
    state_obj = hvd.elastic.JaxState(params=p1, opt_state=back)
    state_obj.save()
    state_obj.opt_state = jax.tree.map(jnp.zeros_like, back)  # "crash"
    state_obj.restore()
    restored = state_obj.opt_state
    for a, b in zip(jax.tree.leaves(host_state.inner),
                    jax.tree.leaves(restored.inner)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # continuing from the restored state == continuing uninterrupted
    mesh = hvd.mesh()
    sspec = hvd.zero_state_pspecs(restored)

    @jax.jit
    def step(params, state, xb, yb):
        def spmd(params, state, xb, yb):
            loss, grads = hvd.value_and_grad(
                loss_fn, reduce=False)(params, (xb, yb))
            updates, ns = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), ns

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), sspec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(P(), sspec))(params, state, xb, yb)

    xb, yb = jnp.asarray(x[32:48]), jnp.asarray(y[32:48])
    restored_dev = jax.device_put(
        restored, jax.tree.map(lambda s: NamedSharding(mesh, s), sspec))
    p_resumed, _ = step(state_obj.params, restored_dev, xb, yb)
    p_straight, _ = step(p1, s1, xb, yb)
    for k in p_straight:
        np.testing.assert_array_equal(np.asarray(p_resumed[k]),
                                      np.asarray(p_straight[k]))


# --- ZeRO stages 1/2/3 (docs/zero.md) --------------------------------------


def _put(tree, spec, mesh=None):
    mesh = mesh or hvd.mesh()
    return jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), spec))


def test_stage123_parity_one_program():
    """The stage-parity contract: all three stage updates run
    side-by-side in ONE compiled step sharing a single gradient
    computation (the bitwise methodology of
    test_sgd_update_bit_identical_to_replicated). Stage 1 vs 2 is
    bit-identical over the whole 3-step trajectory; stage 3 tracks at
    ≤1e-5 rel (XLA fuses the structurally different shard-apply path
    with different FMA formation — ulp-level compiler noise; gradients,
    moments, and shard updates are bit-identical, verified where the
    expressions coincide)."""
    rng = np.random.RandomState(20)
    x, y = make_data(rng)
    params0 = init_params()
    tpl = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                      params0)
    mesh = hvd.mesh()
    txs = [hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                    zero_stage=s) for s in (1, 2, 3)]
    states = [tx.init(params0) for tx in txs]
    sspecs = [hvd.zero_state_pspecs(s) for s in states]
    states = [_put(s, sp, mesh) for s, sp in zip(states, sspecs)]
    psh = hvd.zero3_shard_params(params0)
    pspec = hvd.zero3_param_pspecs(psh)
    psh = _put(psh, pspec, mesh)

    @jax.jit
    def step(p, psh, s1, s2, s3, xb, yb):
        def spmd(p, psh, s1, s2, s3, xb, yb):
            pg = hvd.zero3_gather_params(psh, tpl)
            _, g = hvd.value_and_grad(loss_fn, zero=True)(pg, (xb, yb))
            u1, ns1 = txs[0].update(g, s1, p)
            u2, ns2 = txs[1].update(g, s2, p)
            u3, ns3 = txs[2].update(g, s3, psh)
            return (optax.apply_updates(p, u1),
                    optax.apply_updates(p, u2),
                    optax.apply_updates(psh, u3), ns1, ns2, ns3)

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), pspec, *sspecs, P(hvd.HVD_AXES),
                      P(hvd.HVD_AXES)),
            out_specs=(P(), P(), pspec, *sspecs))(
            p, psh, s1, s2, s3, xb, yb)

    p = params0
    for i in range(3):
        xb = jnp.asarray(x[i * 16:(i + 1) * 16])
        yb = jnp.asarray(y[i * 16:(i + 1) * 16])
        p1, p2, psh, *states = step(p, psh, *states, xb, yb)
        p3 = hvd.zero3_gather_params(jax.device_get(psh), params0)
        for k in p1:
            np.testing.assert_array_equal(np.asarray(p1[k]),
                                          np.asarray(p2[k]))
            np.testing.assert_allclose(np.asarray(p1[k]),
                                       np.asarray(p3[k]),
                                       rtol=1e-5, atol=1e-7)
        p = p1
    # the stage-3 state kept no gather residual and its inner moments
    # match stage 2's bit-for-bit (same reduce-scattered shards in)
    s2f, s3f = jax.device_get(states[1]), jax.device_get(states[2])
    assert s3f.gather_residual is None
    for a, b in zip(jax.tree.leaves(s2f.inner), jax.tree.leaves(s3f.inner)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_true_is_stage2_alias():
    """``zero=True`` (the PR-4 spelling) and ``zero_stage=2`` build the
    identical transformation: same state classes, bit-identical 3-step
    trajectory."""
    rng = np.random.RandomState(21)
    x, y = make_data(rng)
    pa, sa, _ = train(hvd.DistributedOptimizer(
        optax.sgd(0.1, momentum=0.9), zero=True,
        backward_passes_per_step=2), True, x, y, steps=4, bs=8)
    pb, sb, _ = train(hvd.DistributedOptimizer(
        optax.sgd(0.1, momentum=0.9), zero_stage=2,
        backward_passes_per_step=2), True, x, y, steps=4, bs=8)
    assert type(sa.inner) is type(sb.inner)
    for k in pa:
        np.testing.assert_array_equal(np.asarray(pa[k]), np.asarray(pb[k]))
    for a, b in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_stage_env_knob(monkeypatch):
    import dataclasses

    from horovod_tpu.common import basics as B

    cfg = dataclasses.replace(B.config(), zero_stage=1)
    monkeypatch.setattr(B._state, "config", cfg)
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    state = tx.init(init_params())
    assert isinstance(state, hvd.ZeroState)
    # the boolean knob still maps to stage 2
    cfg = dataclasses.replace(B.config(), zero_stage=0, zero_sharding=True)
    monkeypatch.setattr(B._state, "config", cfg)
    state = hvd.DistributedOptimizer(optax.sgd(0.1)).init(init_params())
    assert isinstance(state, hvd.ZeroState)


def test_stage1_full_accumulator_layout():
    """Stage 1 + backward_passes_per_step: the gradient accumulator is
    the classic FULL per-rank local-gradient state ([world, *shape]
    leading-axis leaves — what stage 2 shrinks world×), k microbatches
    then one apply matches one big-batch step, and the stage-2
    trajectory agrees to fp tolerance."""
    rng = np.random.RandomState(22)
    x, y = make_data(rng)
    t1 = hvd.DistributedOptimizer(optax.sgd(0.1), zero_stage=1,
                                  backward_passes_per_step=2)
    p1, s1, _ = train(t1, True, x, y, steps=2)
    assert isinstance(s1.inner, hvd.ZeroFullMultiStepsState)
    # full model-layout accumulator, per-rank leading axis
    for acc, leaf in zip(s1.inner.acc, jax.tree.leaves(init_params())):
        assert tuple(acc.shape) == (N,) + tuple(leaf.shape)
        # sharded over the leading axis: each device holds [1, *shape]
        assert {s.data.shape[0] for s in acc.addressable_shards} == {1}
        # cycle boundary after 2 steps of k=2: accumulator drained
        assert float(jnp.abs(acc).max()) == 0.0
    tb = hvd.DistributedOptimizer(optax.sgd(0.1), zero_stage=1)
    pb, _, _ = train(tb, True, x, y, steps=1, bs=32)
    for k in pb:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(pb[k]),
                                   rtol=2e-5, atol=1e-7)
    t2 = hvd.DistributedOptimizer(optax.sgd(0.1), zero_stage=2,
                                  backward_passes_per_step=2)
    p2, s2, _ = train(t2, True, x, y, steps=2)
    assert hasattr(s2.inner, "acc_grads")  # the 1/world shard accumulator
    for k in p2:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=2e-5, atol=1e-7)


def train3(tx, x, y, steps, bs=16, **gather_kw):
    """Stage-3 training loop: the loop owns flat bucket shards."""
    params0 = init_params(x.shape[1])
    tpl = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                      params0)
    mesh = hvd.mesh()
    psh = hvd.zero3_shard_params(params0)
    pspec = hvd.zero3_param_pspecs(psh)
    psh = _put(psh, pspec, mesh)
    state = tx.init(params0)
    sspec = hvd.zero_state_pspecs(state)
    state = _put(state, sspec, mesh)

    @jax.jit
    def step(psh, s, xb, yb):
        def spmd(psh, s, xb, yb):
            p = hvd.zero3_gather_params(psh, tpl, **gather_kw)
            loss, grads = hvd.value_and_grad(
                loss_fn, zero_stage=3)(p, (xb, yb))
            u, ns = tx.update(grads, s, psh)
            return optax.apply_updates(psh, u), ns, hvd.allreduce(loss)

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(pspec, sspec, P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
            out_specs=(pspec, sspec, P()))(psh, s, xb, yb)

    losses = []
    for i in range(steps):
        psh, state, loss = step(psh, state,
                                jnp.asarray(x[i * bs:(i + 1) * bs]),
                                jnp.asarray(y[i * bs:(i + 1) * bs]))
        losses.append(float(loss))
    params = hvd.zero3_gather_params(jax.device_get(psh), params0)
    return params, jax.device_get(psh), state, losses


def test_stage3_param_shard_shapes_and_training():
    """Stage 3: every persistent parameter buffer on device is exactly
    padded//world (the memory claim), the loop trains, and the result
    tracks the stage-2 run at fp tolerance."""
    rng = np.random.RandomState(23)
    x, y = make_data(rng)
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero_stage=3)
    p3, psh, state, losses = train3(tx, x, y, steps=6)
    assert losses[-1] < losses[0]
    plan = fusion.plan_buckets(jax.tree.leaves(init_params()),
                               shard_multiple=N)
    assert len(psh) == len(plan)
    # device shards: 1/world of the padded bucket — nothing bigger
    # persists (host view is the global [padded] bucket)
    dev = jax.device_put(psh, jax.tree.map(
        lambda _: NamedSharding(hvd.mesh(), P(hvd.HVD_AXES)), tuple(psh)))
    for buf, b in zip(dev, plan):
        assert buf.shape == (b.padded_size,)
        assert {s.data.shape for s in buf.addressable_shards} == \
            {(b.padded_size // N,)}
    p2, _, _ = train(hvd.DistributedOptimizer(optax.adam(1e-2),
                                              zero_stage=2),
                     True, x, y, steps=6)
    for k in p2:
        np.testing.assert_allclose(np.asarray(p3[k]), np.asarray(p2[k]),
                                   rtol=1e-4, atol=1e-6)


def test_stage3_overlap_quantized_compose():
    """stage 3 × overlap × quantized: the gradient reduce-scatter rides
    the int8 DCN wire with shard-local EF (residual active), the param
    gather issues through the stream entry points, and training tracks
    the exact-wire stage-3 run."""
    rng = np.random.RandomState(24)
    x, y = make_data(rng)
    tq = hvd.DistributedOptimizer(optax.sgd(0.1), zero_stage=3,
                                  quantized=True, overlap=True,
                                  num_comm_streams=2)
    pq, _, sq, lq = train3(tq, x, y, steps=6, overlap=True,
                           num_comm_streams=2)
    assert lq[-1] < lq[0]
    assert isinstance(sq, hvd.ZeroState)
    assert sq.gather_residual is None  # no trailing all-gather leg
    rs = [l for l in jax.tree.leaves(sq.residual) if l is not None]
    assert rs and any(float(jnp.abs(l).max()) > 0 for l in rs)
    tf_ = hvd.DistributedOptimizer(optax.sgd(0.1), zero_stage=3)
    pf, _, _, _ = train3(tf_, x, y, steps=6)
    for k in pf:
        np.testing.assert_allclose(np.asarray(pq[k]), np.asarray(pf[k]),
                                   rtol=0.05, atol=5e-3)


def test_zero3_shard_gather_roundtrip_host():
    """Host-side: shard → gather is the exact identity, plans agree with
    gradient-side plan_buckets, and reshard 8→5→8 / 1→8 / 8→1 round-trip
    the parameters bit-exactly (the world sizes that do NOT divide the
    padded buckets)."""
    params = {"w": jnp.arange(130.0).reshape(130, 1),
              "b": jnp.arange(7.0) * 0.5}
    psh = hvd.zero3_shard_params(params)
    plan = hvd.zero3_plan(params)
    assert [tuple(p.shape) for p in psh] == \
        [(b.padded_size,) for b in plan]
    back = hvd.zero3_gather_params(psh, params)
    for k in params:
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(params[k]))
    for w_from, w_to in ((8, 5), (1, 8), (8, 1), (5, 3)):
        a = hvd.zero3_reshard_params(
            hvd.zero3_reshard_params(psh, params, from_world=8,
                                     to_world=w_from),
            params, from_world=w_from, to_world=w_to)
        b = hvd.zero3_reshard_params(a, params, from_world=w_to,
                                     to_world=8)
        for s0, s1 in zip(psh, b):
            np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))


# --- reshard edge cases (ISSUE 8 satellite) --------------------------------


def test_reshard_worlds_that_do_not_divide():
    """8→5→8, 1→8→1, 8→1→8: world sizes whose lcm padding does not
    divide each other still round-trip every moment leaf bit-exactly
    (the 8→3→8 case lives in test_elastic_reshard_roundtrip)."""
    rng = np.random.RandomState(30)
    x, y = make_data(rng)
    tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero=True)
    _, s1, _ = train(tx, True, x, y, steps=2)
    host = jax.device_get(s1)
    params0 = init_params()
    for w_mid in (5, 1):
        mid = hvd.zero_reshard_state(host, params0, from_world=8,
                                     to_world=w_mid, to_local_size=w_mid)
        plan_m = fusion.plan_buckets(jax.tree.leaves(params0),
                                     shard_multiple=w_mid)
        for l in jax.tree.leaves(mid.inner):
            if getattr(l, "ndim", 0) >= 1:
                assert l.shape[0] in {b.padded_size for b in plan_m}
        back = hvd.zero_reshard_state(mid, params0, from_world=w_mid,
                                      to_world=8, to_local_size=4)
        for a, b in zip(jax.tree.leaves(host.inner),
                        jax.tree.leaves(back.inner)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the w_mid=1 loop above IS the N→1 and 1→N pair: 8→1 collapses to
    # the single-worker padding (lcm(64,1)=64) and 1→8 fans back out


def test_reshard_microbatch_state_rebuilds_at_boundary():
    """Stage-1/stage-2 accumulation state reshards at cycle boundaries:
    bucket-flat shard accumulators (stage 2) remap exactly; leading-axis
    microbatch state (stage-1 full accumulator) rebuilds as zeros at the
    new world with the right shapes."""
    rng = np.random.RandomState(31)
    x, y = make_data(rng)
    params0 = init_params()
    # stage 2: acc_grads is bucket-flat and remaps like a moment
    t2 = hvd.DistributedOptimizer(optax.sgd(0.1), zero_stage=2,
                                  backward_passes_per_step=2)
    _, s2, _ = train(t2, True, x, y, steps=2)
    host2 = jax.device_get(s2)
    back2 = hvd.zero_reshard_state(
        hvd.zero_reshard_state(host2, params0, from_world=8, to_world=5,
                               to_local_size=5),
        params0, from_world=5, to_world=8, to_local_size=4)
    for a, b in zip(jax.tree.leaves(host2.inner),
                    jax.tree.leaves(back2.inner)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # stage 1: acc is [world, *shape]; at a cycle boundary it is zeros
    # and rebuilds as zeros shaped for the new world
    t1 = hvd.DistributedOptimizer(optax.sgd(0.1), zero_stage=1,
                                  backward_passes_per_step=2)
    _, s1, _ = train(t1, True, x, y, steps=2)
    host1 = jax.device_get(s1)
    r5 = hvd.zero_reshard_state(host1, params0, from_world=8, to_world=5,
                                to_local_size=5)
    assert isinstance(r5.inner, hvd.ZeroFullMultiStepsState)
    for acc, leaf in zip(r5.inner.acc, jax.tree.leaves(params0)):
        assert tuple(acc.shape) == (5,) + tuple(jnp.shape(leaf))
        assert float(jnp.abs(acc).max()) == 0.0


# --- tape threading --------------------------------------------------------


def test_value_and_grad_zero_returns_locals():
    rng = np.random.RandomState(13)
    xs = rng.randn(N, 3).astype(np.float32)

    def f(p, x):
        return jnp.sum(p * x)

    def spmd(p, x):
        _, g_zero = hvd.value_and_grad(f, zero=True)(p, x[0])
        _, g_red = hvd.value_and_grad(f)(p, x[0])
        # zero=True grads are per-rank locals; reduced grads are the mean
        return g_zero, g_red

    gz, gr = hvd.shard_map(spmd, mesh=hvd.mesh(),
                           in_specs=(P(), P(hvd.HVD_AXES)),
                           out_specs=(P(hvd.HVD_AXES), P()))(
        jnp.ones(3), jnp.asarray(xs))
    np.testing.assert_allclose(np.asarray(gz).reshape(N, 3), xs, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gr), xs.mean(0), rtol=1e-5)
