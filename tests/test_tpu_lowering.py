"""Cross-platform TPU lowering checks for every Pallas kernel.

Interpreter-mode tests (the rest of the suite) verify kernel *numerics*
but never run Mosaic's lowering-time legality checks — block shapes whose
last two dims are neither (8, 128)-divisible nor equal to the array dims
lower fine in interpreter mode and then fail on real hardware at compile
time. That is exactly how the fused-LN backward's per-block ``(1, C)``
dgamma/dbeta outputs survived a full CPU suite and died in the round-5
hardware session (BENCH_r05_sweep/gpt350m_fusedln.log).

These tests force the non-interpreter kernels and AOT-lower for the
``tpu`` platform on the CPU host (no device needed): the Mosaic lowering
rule — including ``_check_block_mappings`` — runs during StableHLO
lowering, so an illegal BlockSpec fails HERE, one round before hardware.
Execution is NOT attempted (that needs a chip); legality is the contract.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax
import jax.numpy as jnp
import pytest

jax.config.update("jax_platforms", "cpu")


def _lower_tpu(fn, *args):
    return jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))


@pytest.fixture()
def real_kernels(monkeypatch):
    """Force interpret=False so Mosaic lowering (and its block-mapping
    legality checks) actually runs."""
    import horovod_tpu.ops.flash_attention as F
    import horovod_tpu.ops.layer_norm as L
    import horovod_tpu.ops.softmax_xent as X

    monkeypatch.setattr(F, "_interpret", lambda: False)
    monkeypatch.setattr(L, "_interpret", lambda: False)
    monkeypatch.setattr(X, "_interpret", lambda: False)
    yield


@pytest.mark.parametrize("B,T,C", [
    (8, 1024, 1024),   # the round-5 hardware failure shape (350M blocks)
    (16, 1024, 768),   # 124M bench shape
    (1, 7, 256),       # N < 8 rows: single whole-array block
    (2, 300, 512),     # N not a block multiple: padded rows
])
def test_ln_residual_lowers_for_tpu(real_kernels, B, T, C):
    from horovod_tpu.ops.layer_norm import ln_residual

    x = jnp.zeros((B, T, C), jnp.bfloat16)
    g = jnp.ones((C,), jnp.float32)
    b = jnp.zeros((C,), jnp.float32)

    def f(x, r, g, b):
        def loss(x):
            y, h = ln_residual(x, r, g, b, 1e-6)
            return y.astype(jnp.float32).sum() + h.astype(jnp.float32).sum()

        return jax.grad(loss)(x)

    _lower_tpu(f, x, x, g, b)


@pytest.mark.parametrize("B,T,H,D,blocks", [
    (16, 1024, 12, 64, None),      # 124M bench shape, default blocks
    (8, 1024, 16, 64, None),       # 350M bench shape
    (2, 1024, 4, 64, (512, 512)),  # explicit non-default blocking
    (1, 384, 4, 128, None),        # whole-sequence single block
])
def test_flash_attention_lowers_for_tpu(real_kernels, B, T, H, D, blocks):
    from horovod_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros((B, T, H, D), jnp.bfloat16)
    kw = {}
    if blocks is not None:
        kw = {"block_q": blocks[0], "block_k": blocks[1]}

    def f(q, k, v):
        def loss(q):
            return flash_attention(q, k, v, causal=True,
                                   **kw).astype(jnp.float32).sum()

        return jax.grad(loss)(q)

    _lower_tpu(f, q, q, q)


@pytest.mark.parametrize("N,V,C", [
    (1024, 32000, 768),    # bench LM head
    (512, 1000, 256),      # small head
])
def test_linear_cross_entropy_lowers_for_tpu(real_kernels, N, V, C):
    from horovod_tpu.ops.softmax_xent import linear_cross_entropy

    x = jnp.zeros((N, C), jnp.bfloat16)
    w = jnp.zeros((V, C), jnp.bfloat16)
    y = jnp.zeros((N,), jnp.int32)

    def f(x, w, y):
        def loss(x):
            return linear_cross_entropy(x, w, y).mean()

        return jax.grad(loss)(x)

    _lower_tpu(f, x, w, y)


def test_quantized_allreduce_lowers_for_tpu():
    """The quantized collective path AOT-lowers for the tpu platform: the
    int8 all_to_all (hop 2), the masked int8 psum (hop 3), and the
    round/clip/convert quantize math must all have TPU lowerings — checked
    here, one round before hardware (the round-5 fused-LN lesson)."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import horovod_tpu as hvd

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), hvd.HVD_AXES)

    def f(x, r):
        def spmd(v, res):
            out, nr = hvd.quantized_allreduce(v[0], res[0], op=hvd.Sum)
            return out, nr[None]

        return hvd.shard_map(spmd, mesh=mesh,
                             in_specs=(P(hvd.HVD_AXES), P(hvd.HVD_AXES)),
                             out_specs=(P(), P(hvd.HVD_AXES)))(x, r)

    x = jnp.zeros((8, 1024), jnp.float32)
    _lower_tpu(f, x, x)


def test_fused_ln_gpt_block_lowers_for_tpu(real_kernels):
    """The composition that actually failed on hardware: a fused-LN GPT
    block's full fwd+bwd (flash attention + ln_residual together)."""
    from horovod_tpu.models import GPT, gpt_tiny

    cfg = gpt_tiny(attention="flash", fused_ln=True, max_seq_len=512)
    model = GPT(cfg)
    tokens = jnp.zeros((2, 512), jnp.int32)
    # Abstract init: eager execution would run the forced non-interpret
    # kernels on the CPU backend; shapes are all lowering needs.
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tokens))["params"]

    def f(p, tokens):
        def loss(p):
            return model.apply({"params": p},
                               tokens).astype(jnp.float32).mean()

        return jax.grad(loss)(p)

    _lower_tpu(f, params, tokens)
