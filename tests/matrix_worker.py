"""Worker for the systematic op x dtype matrix over the native 2-process
plane (tests/test_op_matrix.py), plus the cross-rank mismatch ERROR
cases.

Models the reference's exhaustive parallel tier
(test/parallel/test_tensorflow.py: every dtype x dim x error case over a
real multi-process world): every collective runs in every wire dtype the
native core supports, with exact numeric assertions, then deliberately
inconsistent submissions assert that the controller's consistency
checker (cc/src/controller.cc ConstructResponse) delivers the Mismatched
error text to EVERY rank — not just rank 0."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from horovod_tpu import cc  # noqa: E402

try:
    import ml_dtypes

    BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - baked image has ml_dtypes
    BF16 = None

DTYPES = [np.dtype(d) for d in (np.uint8, np.int8, np.int32, np.int64,
                                np.float16, np.float32, np.float64)]
if BF16 is not None:
    DTYPES.append(BF16)


def as_f64(a):
    return np.asarray(a, dtype=np.float64)


def check_dtype(ctx, dt, rank, size):
    name = dt.name
    # Values stay tiny so every dtype (incl. uint8/int8/fp16/bf16) holds
    # the exact sum: max element = 2 + (size-1), summed over <= 6 ranks.
    base = (np.arange(8) % 3).astype(np.int64)

    # --- allreduce (SUM) ---
    x = (base + rank).astype(dt)
    out = ctx.allreduce_async(x.copy(), f"ar.{name}").wait()
    exp = base * size + sum(range(size))
    assert np.array_equal(as_f64(out), as_f64(exp)), (name, "allreduce")

    # --- grouped allreduce: concurrent handles ride the fusion buffer,
    # the eager analogue of hvd.grouped_allreduce ---
    hs = [ctx.allreduce_async((base[:4] + rank + i).astype(dt),
                              f"grp{i}.{name}") for i in range(3)]
    for i, h in enumerate(hs):
        exp = base[:4] * size + sum(range(size)) + i * size
        assert np.array_equal(as_f64(h.wait()), as_f64(exp)), (
            name, "grouped", i)

    # --- allgather (ragged: rank r contributes r+1 rows) ---
    g = ctx.allgather_async(np.full((rank + 1, 2), rank, dt),
                            f"ag.{name}").wait()
    assert g.dtype == dt, (name, g.dtype)
    row = 0
    for r in range(size):
        assert (as_f64(g[row:row + r + 1]) == r).all(), (name, "allgather")
        row += r + 1

    # --- broadcast (non-zero root) ---
    root = 1 % size
    out = ctx.broadcast_async(np.full(4, rank, dt), f"bc.{name}",
                              root=root).wait()
    assert (as_f64(out) == root).all(), (name, "broadcast")

    # --- alltoall (uneven splits: d+1 rows to dest d) ---
    splits = [d + 1 for d in range(size)]
    h = ctx.alltoall_async(np.full((sum(splits), 3), rank, dt),
                           f"a2a.{name}", splits=splits)
    out = h.wait()
    assert h.recv_splits() == [rank + 1] * size, (name, "recv_splits")
    assert out.dtype == dt and (as_f64(out) >= 0).all()
    row = 0
    for r in range(size):
        assert (as_f64(out[row:row + rank + 1]) == r).all(), (
            name, "alltoall")
        row += rank + 1


def expect_error(fn, substr, what):
    try:
        fn().wait()
    except cc.NativeError as e:
        msg = str(e)
        assert substr.lower() in msg.lower(), (what, substr, msg)
        return
    raise AssertionError(f"{what}: rank did not receive the controller "
                         f"ERROR response (expected '{substr}')")


def check_mismatches(ctx, rank, size):
    """Deliberately inconsistent submissions: the controller's cross-rank
    validation must deliver the ERROR text to every rank (reference:
    ConstructResponse error paths, horovod/common/controller.cc)."""
    # Shape mismatch (allreduce): rank 0 sends 4 elements, others 5.
    expect_error(
        lambda: ctx.allreduce_async(
            np.ones(4 + (rank != 0), np.float32), "err.shape"),
        "Mismatched allreduce tensor shapes", "shape mismatch")
    # Dtype mismatch: rank 0 fp32, others int32.
    expect_error(
        lambda: ctx.allreduce_async(
            np.ones(4, np.float32 if rank == 0 else np.int32), "err.dtype"),
        "Mismatched data types", "dtype mismatch")
    # Collective-op mismatch: rank 0 allreduce, others allgather.
    expect_error(
        lambda: (ctx.allreduce_async(np.ones(4, np.float32), "err.op")
                 if rank == 0 else
                 ctx.allgather_async(np.ones(4, np.float32), "err.op")),
        "Mismatched collective operations", "op mismatch")
    # Reduce-op mismatch: SUM vs MIN under one name.
    expect_error(
        lambda: ctx.allreduce_async(
            np.ones(4, np.float32), "err.rop",
            op=ctx.SUM if rank == 0 else ctx.MIN),
        "Mismatched reduce ops", "reduce-op mismatch")
    # Broadcast root mismatch.
    expect_error(
        lambda: ctx.broadcast_async(np.ones(4, np.float32), "err.root",
                                    root=rank % size),
        "Mismatched broadcast root ranks", "root mismatch")
    # The world must still be healthy after every ERROR response.
    out = ctx.allreduce_async(np.ones(4, np.float32), "post.err").wait()
    assert np.allclose(out, size)


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    ctx = cc.CoreContext()
    assert ctx.rank() == rank and ctx.size() == size
    for dt in DTYPES:
        check_dtype(ctx, dt, rank, size)
    if size > 1:
        check_mismatches(ctx, rank, size)
    ctx.barrier()
    ctx.close()
    print(f"matrix worker rank {rank}/{size}: OK "
          f"({len(DTYPES)} dtypes x 5 ops + error matrix)")


if __name__ == "__main__":
    main()
