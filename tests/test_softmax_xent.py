"""Fused linear cross-entropy vs the reference einsum+optax formulation.

The fused kernel is exact — per-token losses and dx/dw gradients must
match the dense head to float tolerance (interpreter mode on CPU; the
same code compiles through Mosaic on TPU, measured in bench.py --model
gpt --lm-loss fused).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.ops.softmax_xent import linear_cross_entropy


def _data(N=256, C=64, V=1024, seed=0, dtype=jnp.float32):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(N, C), dtype) * 0.5
    w = jnp.asarray(rs.randn(V, C), dtype) * 0.1
    lab = jnp.asarray(rs.randint(0, V, N))
    return x, w, lab


def _ref(x, w, lab):
    logits = jnp.einsum("nc,vc->nv", x.astype(jnp.float32),
                        w.astype(jnp.float32))
    return optax.softmax_cross_entropy_with_integer_labels(logits, lab)


class TestLinearCrossEntropy:
    def test_matches_dense(self):
        x, w, lab = _data()
        out = linear_cross_entropy(x, w, lab, block_n=128, block_v=512)
        np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, w, lab)),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_dense(self):
        x, w, lab = _data(seed=1)

        gf = jax.grad(lambda x, w: linear_cross_entropy(
            x, w, lab, block_n=128, block_v=512).mean(),
            argnums=(0, 1))(x, w)
        gd = jax.grad(lambda x, w: _ref(x, w, lab).mean(),
                      argnums=(0, 1))(x, w)
        for a, b, name in zip(gf, gd, ("dx", "dw")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7,
                err_msg=f"{name} mismatch")

    def test_leading_shape_and_single_block(self):
        x, w, lab = _data(N=64, V=256, seed=2)
        x3 = x.reshape(2, 32, -1)
        lab3 = lab.reshape(2, 32)
        out = linear_cross_entropy(x3, w, lab3)
        assert out.shape == (2, 32)
        np.testing.assert_allclose(
            np.asarray(out).reshape(-1), np.asarray(_ref(x, w, lab)),
            rtol=1e-5, atol=1e-5)

    def test_bf16(self):
        x, w, lab = _data(seed=3, dtype=jnp.bfloat16)
        out = linear_cross_entropy(x, w, lab, block_n=128, block_v=512)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(_ref(x, w, lab)),
            rtol=5e-2, atol=5e-2)

    def test_no_aligned_blocking_falls_back(self):
        # V = 520 > default block has no 128-multiple divisor → XLA path.
        x, w, lab = _data(N=33, V=520, seed=4)
        out = linear_cross_entropy(x, w, lab, block_v=512)
        np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, w, lab)),
                                   rtol=1e-5, atol=1e-5)

    def test_dp_shard_map(self):
        """Per-shard fused loss under data parallelism: allreduced mean
        equals the global dense mean."""
        x, w, lab = _data(N=256, seed=5)
        expect = float(_ref(x, w, lab).mean())
        mesh = hvd.mesh()

        def spmd(x, w, lab):
            local = linear_cross_entropy(x, w, lab, block_n=32,
                                         block_v=512).mean()
            return hvd.allreduce(local, op=hvd.Average)

        out = jax.jit(hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(hvd.HVD_AXES), P(), P(hvd.HVD_AXES)),
            out_specs=P()))(x, w, lab)
        np.testing.assert_allclose(float(out), expect, rtol=1e-5)

    def test_lm_head_loss_dispatch(self, monkeypatch):
        """auto = dense under the logits budget, fused above; both match
        the reference formulation numerically."""
        from horovod_tpu.ops.softmax_xent import lm_head_loss

        x, w, lab = _data()
        want = np.asarray(_ref(x, w, lab))
        for mode in ("dense", "fused", "auto"):
            got = lm_head_loss(x, w, lab, mode=mode)
            np.testing.assert_allclose(np.asarray(got), want,
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=mode)
        # Force the budget below this shape's logits: auto must take the
        # fused path (and still match).
        monkeypatch.setenv("HOROVOD_XENT_AUTO_LOGITS_GB", "0")
        got = lm_head_loss(x, w, lab, mode="auto")
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)
        with pytest.raises(ValueError, match="auto|dense|fused"):
            lm_head_loss(x, w, lab, mode="bogus")

    def test_gpt_fused_loss_matches_logits_loss(self):
        cfg = gpt_tiny(dtype=jnp.float32)
        B, T = 2, 64
        rs = np.random.RandomState(6)
        tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))
        targets = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)))

        variables = GPT(cfg).init(jax.random.PRNGKey(0), tokens)
        logits = GPT(cfg).apply(variables, tokens)
        expect = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

        hidden = GPT(dataclasses.replace(cfg, return_hidden=True)).apply(
            variables, tokens)
        fused = linear_cross_entropy(
            hidden, variables["params"]["wte"].astype(cfg.dtype),
            targets).mean()
        np.testing.assert_allclose(float(fused), float(expect), rtol=1e-5)
