"""Minimal in-process stand-in for ``mxnet``, pinning the exact API surface
``horovod_tpu.mxnet`` touches (the same test strategy as ``fake_ray.py``:
MXNet is EOL and not installable in this image, so the binding is exercised
against a faithful shim of the real mxnet 1.9 interfaces).

Pinned surfaces (each attribute below exists with the same name/shape in
real mxnet):

- ``mx.nd.array(arr, dtype=None)`` -> NDArray with ``asnumpy()``,
  ``__setitem__`` (slice assignment), ``shape``, ``dtype``
- ``mx.optimizer.Optimizer`` base with ``rescale_grad``; ``mx.optimizer.SGD``
  with ``update(index, weight, grad, state)`` applying
  ``weight -= lr * rescale_grad * grad``
- ``mx.gluon.Trainer(params, optimizer, optimizer_params, kvstore)`` with
  ``_params``, ``_scale``, ``_allreduce_grads()``, ``step(batch_size)``
- ``mx.gluon.parameter.Parameter`` with ``data()``, ``list_grad()``,
  ``grad_req``, ``_init_impl``; ``DeferredInitializationError``;
  ``ParameterDict`` (a plain dict subclass, as in mxnet 1.x)

``install()`` registers the shim as ``sys.modules['mxnet']`` (plus the
``mxnet.gluon.parameter`` submodule path) so ``import mxnet`` inside the
binding resolves here.
"""

import sys
import types

import numpy as np


class NDArray:
    def __init__(self, data, dtype=None):
        self._data = np.array(data, dtype=dtype)

    def asnumpy(self):
        return self._data.copy()

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        self._data[key] = value

    def __getitem__(self, key):
        return NDArray(self._data[key])

    @property
    def shape(self):
        return self._data.shape

    @property
    def dtype(self):
        return self._data.dtype

    def __repr__(self):
        return f"NDArray({self._data!r})"


def _nd_array(arr, dtype=None):
    return NDArray(arr, dtype=dtype)


class Optimizer:
    def __init__(self, learning_rate=0.01, rescale_grad=1.0):
        self.lr = learning_rate
        self.rescale_grad = rescale_grad

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self._lr_mult = args_lr_mult

    def set_wd_mult(self, args_wd_mult):
        self._wd_mult = args_wd_mult


class SGD(Optimizer):
    def update(self, index, weight, grad, state):
        # real mxnet optimizers accept parallel lists (multi-tensor update)
        if isinstance(index, (tuple, list)):
            for i, w, g in zip(index, weight, grad):
                self.update(i, w, g, None)
            return
        weight[:] = weight.asnumpy() - self.lr * (self.rescale_grad *
                                                  grad.asnumpy())


class DeferredInitializationError(Exception):
    pass


class Parameter:
    def __init__(self, name, shape=None, grad_req="write"):
        self.name = name
        self.shape = shape
        self.grad_req = grad_req
        self._data = None
        self._grad = None

    def initialize(self, value):
        """Materialize the parameter (real mxnet routes this through
        ``_init_impl``, which horovod wraps for deferred-init broadcast)."""
        self._init_impl(value)

    def _init_impl(self, value):
        self._data = NDArray(value)
        self._grad = NDArray(np.zeros_like(self._data._data))

    def data(self):
        if self._data is None:
            raise DeferredInitializationError(
                f"Parameter {self.name} has not been initialized")
        return self._data

    def list_grad(self):
        return [self._grad]


class ParameterDict(dict):
    pass


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device"):
        if isinstance(params, dict):
            params = [params[k] for k in sorted(params)]
        self._params = list(params)
        if isinstance(optimizer, str):
            opts = dict(optimizer_params or {})
            assert optimizer == "sgd", optimizer
            optimizer = SGD(**opts)
        self._optimizer = optimizer
        self._scale = 1.0

    def _allreduce_grads(self):
        pass  # kvstore push/pull in real gluon; horovod overrides

    def step(self, batch_size):
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._optimizer.update(i, param.data(), param.list_grad()[0],
                                       None)


def install():
    """Register the shim as ``mxnet`` in sys.modules."""
    mx = types.ModuleType("mxnet")
    nd = types.ModuleType("mxnet.nd")
    nd.array = _nd_array
    nd.NDArray = NDArray
    optimizer = types.ModuleType("mxnet.optimizer")
    optimizer.Optimizer = Optimizer
    optimizer.SGD = SGD
    gluon = types.ModuleType("mxnet.gluon")
    parameter = types.ModuleType("mxnet.gluon.parameter")
    parameter.Parameter = Parameter
    parameter.ParameterDict = ParameterDict
    parameter.DeferredInitializationError = DeferredInitializationError
    gluon.Trainer = Trainer
    gluon.parameter = parameter
    mx.nd = nd
    mx.optimizer = optimizer
    mx.gluon = gluon
    sys.modules["mxnet"] = mx
    sys.modules["mxnet.nd"] = nd
    sys.modules["mxnet.optimizer"] = optimizer
    sys.modules["mxnet.gluon"] = gluon
    sys.modules["mxnet.gluon.parameter"] = parameter
    return mx
