"""Elastic integration worker (reference analogue: the training scripts in
test/integration/data/ driven by elastic_common.py).

Trains a toy objective under ``hvd.elastic.run``, logging one JSON line per
batch to --log-file: {identity, rank, size, batch, value}. Fault injection
via --exit-at "<hostname>:<local_rank>:<batch>" (os._exit(1), simulating a
hard crash mid-epoch, reference elastic_common.py --exit-schedule).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import elastic  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--log-file", required=True)
    p.add_argument("--batches", type=int, default=10)
    p.add_argument("--batch-sleep", type=float, default=0.1)
    p.add_argument("--exit-at", default=None,
                   help="hostname:local_rank:batch hard-crash injection")
    args = p.parse_args()

    identity = (f"{os.environ['HOROVOD_HOSTNAME']}:"
                f"{os.environ['HOROVOD_LOCAL_RANK']}")
    exit_at = None
    if args.exit_at:
        h, lr, b = args.exit_at.rsplit(":", 2)
        if identity == f"{h}:{lr}":
            exit_at = int(b)

    def log(record):
        record["identity"] = identity
        with open(args.log_file, "a") as f:
            f.write(json.dumps(record) + "\n")

    @elastic.run
    def train(state):
        while state.batch < args.batches:
            # A real collective every step so peer failure surfaces as
            # HorovodInternalError and state stays world-consistent.
            contrib = jnp.full((4,), 1.0)
            total = hvd.allreduce(contrib, op=hvd.Sum,
                                  name=f"train.step.{state.batch}")
            assert np.allclose(total, hvd.size()), (total, hvd.size())
            state.weights = state.weights + float(total[0])
            state.batch += 1
            if exit_at is not None and state.batch == exit_at:
                os._exit(1)
            log({"rank": hvd.rank(), "size": hvd.size(),
                 "batch": state.batch, "weights": state.weights})
            state.commit()
            time.sleep(args.batch_sleep)

    state = elastic.ObjectState(batch=0, weights=0.0)
    train(state)
    log({"rank": hvd.rank(), "size": hvd.size(), "done": True,
         "weights": state.weights})


if __name__ == "__main__":
    main()
