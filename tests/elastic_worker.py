"""Elastic integration worker (reference analogue: the training scripts in
test/integration/data/ driven by elastic_common.py).

Trains a toy objective under ``hvd.elastic.run``, logging one JSON line per
batch to --log-file: {identity, rank, size, batch, value}. Fault injection
via --exit-at "<hostname>:<local_rank>:<batch>" (os._exit(1), simulating a
hard crash mid-epoch, reference elastic_common.py --exit-schedule).
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import elastic  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--log-file", required=True)
    p.add_argument("--batches", type=int, default=10)
    p.add_argument("--batch-sleep", type=float, default=0.1)
    p.add_argument("--exit-at", default=None,
                   help="hostname:local_rank:batch hard-crash injection")
    p.add_argument("--ckpt-dir", default=None,
                   help="durable mode: restore from the latest committed "
                        "checkpoint here and save (rank 0) every batch")
    p.add_argument("--exit-at-batch", type=int, default=None,
                   help="EVERY rank hard-crashes after committing this "
                        "batch (whole-job loss; only disk survives)")
    args = p.parse_args()

    identity = (f"{os.environ['HOROVOD_HOSTNAME']}:"
                f"{os.environ['HOROVOD_LOCAL_RANK']}")
    exit_at = None
    if args.exit_at:
        h, lr, b = args.exit_at.rsplit(":", 2)
        if identity == f"{h}:{lr}":
            exit_at = int(b)

    def log(record):
        record["identity"] = identity
        with open(args.log_file, "a") as f:
            f.write(json.dumps(record) + "\n")

    # Durable mode (scripts/chaos_soak.py --fault ckpt): restore from the
    # last COMMITTED checkpoint before entering the elastic loop. Every
    # rank reads the same manifest (read-only), so the restored state is
    # world-consistent without a broadcast; only rank 0 writes (the state
    # is replicated — one complete copy per commit is the contract).
    mgr = None
    start_batch, start_weights = 0, 0.0
    if args.ckpt_dir:
        from horovod_tpu import checkpoint as hvd_ckpt

        mgr = hvd_ckpt.CheckpointManager(args.ckpt_dir, keep=3)
        latest = mgr.latest_step()
        if latest is not None:
            manifest, tree = mgr.restore()
            start_batch = manifest.step
            start_weights = float(np.asarray(tree["train"]["weights"])[0])
        log({"resumed_from": latest or 0, "start_weights": start_weights})

    @elastic.run
    def train(state):
        while state.batch < args.batches:
            # A real collective every step so peer failure surfaces as
            # HorovodInternalError and state stays world-consistent.
            if mgr is None:
                contrib = jnp.full((4,), 1.0)
                total = hvd.allreduce(contrib, op=hvd.Sum,
                                      name=f"train.step.{state.batch}")
                assert np.allclose(total, hvd.size()), (total, hvd.size())
                state.weights = state.weights + float(total[0])
            else:
                # Deterministic batch-dependent "loss" contribution,
                # normalized by world size: with a FIXED world the whole
                # trajectory depends only on the batch number, so an
                # interrupted-and-resumed run must match an uninterrupted
                # one bit-for-bit.
                contrib = jnp.full((4,), math.cos(0.3 * state.batch),
                                   dtype=jnp.float32)
                total = hvd.allreduce(contrib, op=hvd.Sum,
                                      name=f"train.step.{state.batch}")
                state.weights = (state.weights
                                 + float(total[0]) / hvd.size())
            state.batch += 1
            if exit_at is not None and state.batch == exit_at:
                os._exit(1)
            log({"rank": hvd.rank(), "size": hvd.size(),
                 "batch": state.batch, "weights": state.weights})
            state.commit()
            if mgr is not None and hvd.rank() == 0:
                # Async: blocks ~only for the host snapshot; the commit
                # lands on the writer thread (double-buffered).
                mgr.save(state.batch, {"train": {
                    "weights": np.full((4,), state.weights,
                                       dtype=np.float64)}})
            if (args.exit_at_batch is not None
                    and state.batch >= args.exit_at_batch):
                os._exit(1)  # post-commit whole-job crash (ckpt soak)
            time.sleep(args.batch_sleep)

    state = elastic.ObjectState(batch=start_batch, weights=start_weights)
    train(state)
    if mgr is not None:
        mgr.wait(30)
        mgr.close()
    log({"rank": hvd.rank(), "size": hvd.size(), "done": True,
         "weights": state.weights})


if __name__ == "__main__":
    main()
