"""Cost-model-driven planner tests (docs/cost-model.md).

Tiers mirror the subsystem: the per-link CostModel and its env
resolution, analytic plan/step pricing (alpha-beta + quantize + overlap
terms over the exact trace-time byte formulas), the enumerate → price →
shortlist pipeline, the calibration sweep's alpha-beta fit and its
persistence contract (geometry-keyed store beside the autotune cache;
corrupted/missing/mismatched entries fall back to the static defaults
with a warning, never an abort), and the predicted-vs-measured drift
contract against the live trace-time accounting."""

import dataclasses
import json
import logging
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.common import basics
from horovod_tpu.ops import fusion
from horovod_tpu.plan import (
    CostModel,
    LinkClass,
    StepPlan,
    calibrate as hvd_calibrate,
    cost as hvd_cost,
    describe_plan,
    enumerate_tuned,
    modeled_wire_ms,
    price_plan,
    price_step,
    quantized_allreduce_plan,
    record_wire_stats,
    shortlist,
    tree_allreduce_plan,
    flat_plan,
)

MIB = 1024 * 1024


def mesh_2x4():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), hvd.HVD_AXES)


class TestCostModel:
    def test_static_defaults_match_bench_gbps(self):
        m = CostModel.from_env()
        assert m.source == "static"
        assert m.ici.bandwidth_gbps == 100.0
        assert m.dcn.bandwidth_gbps == 25.0
        assert m.pod.bandwidth_gbps == 25.0  # pod defaults to DCN
        assert m.ici.latency_us == 1.0
        assert m.dcn.latency_us == 25.0
        assert m.pod.latency_us == 25.0
        assert m.dcn.quant_rate_gbps == 50.0

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BENCH_DCN_GBPS", "10")
        monkeypatch.setenv("HOROVOD_BENCH_DCN_LAT_US", "100")
        monkeypatch.setenv("HOROVOD_BENCH_QUANT_GBPS", "5")
        m = CostModel.from_env()
        assert m.dcn.bandwidth_gbps == 10.0
        assert m.dcn.latency_us == 100.0
        assert m.ici.quant_rate_gbps == 5.0
        # pod inherits the overridden DCN values when unset
        assert m.pod.bandwidth_gbps == 10.0
        assert m.pod.latency_us == 100.0

    def test_link_lookup_rejects_unknown_hop(self):
        m = CostModel.from_env()
        assert m.link("dcn") is m.dcn
        with pytest.raises(ValueError, match="unknown link class"):
            m.link("nvlink")


class TestPricePlan:
    N = (1 << 20) // 4  # 1 MiB fp32

    def test_modeled_is_bytes_at_bench_bandwidth(self):
        pc = price_plan(flat_plan("allreduce"), self.N, 4, (2, 4))
        # flat psum over 2x4: ici 2n(3/4), dcn 2(n/4)(1/2) — at
        # 100/25 GB/s.
        n_bytes = self.N * 4
        want = (2 * n_bytes * 3 / 4 / 100e9
                + 2 * (n_bytes / 4) * 1 / 2 / 25e9) * 1e3
        assert pc.modeled_ms == pytest.approx(want, rel=1e-9)
        # Static model: predicted wire == modeled wire (drift-free by
        # construction; only latency/quant terms are added on top).
        assert pc.wire_ms == pytest.approx(pc.modeled_ms, rel=1e-9)

    def test_alpha_counts_ring_hops(self):
        pc = price_plan(tree_allreduce_plan(), self.N, 4, (2, 4))
        # ici legs: (4-1) hops at 1 us each; dcn psum: (2-1) at 25 us.
        ici_alpha = sum(l.alpha_ms for l in pc.legs if l.hop == "ici")
        dcn_alpha = sum(l.alpha_ms for l in pc.legs if l.hop == "dcn")
        assert ici_alpha == pytest.approx(2 * 3 * 1.0 / 1e3)
        assert dcn_alpha == pytest.approx(1 * 25.0 / 1e3)

    def test_quant_term_prices_fp_equivalent_payload(self):
        q = price_plan(quantized_allreduce_plan(block=256), self.N, 4,
                       (2, 4))
        assert q.quant_ms > 0
        # fp-equivalent payload of the two int8 legs at the 50 GB/s
        # quant rate: rs fp = sn(nc-1)/nc, ag fp = 2 sn(nc-1)/nc.
        sn = self.N // 4
        fp = (sn * 0.5 + 2 * sn * 0.5) * 4
        assert q.quant_ms == pytest.approx(fp / 50e9 * 1e3, rel=1e-6)

    def test_pallas_backend_halves_quant_cost(self):
        xla = price_plan(quantized_allreduce_plan(block=256), self.N, 4,
                         (2, 4))
        pl = price_plan(quantized_allreduce_plan(block=256, fused=True),
                        self.N, 4, (2, 4))
        assert pl.quant_ms == pytest.approx(xla.quant_ms / 2, rel=1e-9)
        assert pl.wire_ms == pytest.approx(xla.wire_ms, rel=1e-9)

    def test_quantized_wire_cheaper_on_slow_dcn(self):
        # The int8 wire must price below the exact wire once the DCN
        # link is slow enough — EQuARX's premise as a model consequence.
        slow_dcn = CostModel(
            ici=LinkClass(100.0, 1.0, 50.0),
            dcn=LinkClass(2.0, 25.0, 50.0),
            pod=LinkClass(2.0, 25.0, 50.0))
        exact = price_plan(tree_allreduce_plan(), self.N, 4, (2, 4),
                           slow_dcn)
        quant = price_plan(quantized_allreduce_plan(block=256), self.N,
                           4, (2, 4), slow_dcn)
        assert quant.total_ms < exact.total_ms

    def test_calibrated_bandwidth_changes_wire_not_modeled(self):
        fast = CostModel(
            ici=LinkClass(200.0, 1.0, 50.0),
            dcn=LinkClass(50.0, 25.0, 50.0),
            pod=LinkClass(50.0, 25.0, 50.0), source="calibrated")
        pc = price_plan(flat_plan("allreduce"), self.N, 4, (2, 4), fast)
        # Calibrated wire halves; the modeled (static-bandwidth) column
        # stays the WireStats-comparable figure.
        assert pc.wire_ms == pytest.approx(pc.modeled_ms / 2, rel=1e-9)


class TestPriceStep:
    def _sp(self, **kw):
        kw.setdefault("quantized", False)
        kw.setdefault("mesh_shape", (2, 4))
        kw.setdefault("fusion_threshold_bytes", 4 * MIB)
        kw.setdefault("quant_block", 256)
        return describe_plan(**kw)

    def test_buckets_multiply_alpha_not_bytes(self):
        one = price_step(self._sp(fusion_threshold_bytes=64 * MIB),
                         32 * MIB)
        many = price_step(self._sp(fusion_threshold_bytes=4 * MIB),
                          32 * MIB)
        assert one.buckets == 1 and many.buckets == 8
        assert many.wire_ms == pytest.approx(one.wire_ms, rel=1e-9)
        assert many.alpha_ms == pytest.approx(one.alpha_ms * 8, rel=1e-9)

    def test_overlap_hides_all_but_the_tail_bucket(self):
        sync = price_step(self._sp(fusion_threshold_bytes=4 * MIB),
                          32 * MIB)
        ovl = price_step(self._sp(fusion_threshold_bytes=4 * MIB,
                                  overlap=True), 32 * MIB)
        assert sync.hidden_ms == 0.0
        assert ovl.hidden_ms == pytest.approx(
            ovl.wire_ms * (1 - 1 / 8), rel=1e-9)
        assert ovl.predicted_ms < sync.predicted_ms

    def test_compute_budget_caps_the_overlap_credit(self):
        ovl = price_step(self._sp(fusion_threshold_bytes=4 * MIB,
                                  overlap=True), 32 * MIB,
                         compute_ms=0.01)
        assert ovl.hidden_ms == pytest.approx(0.01)

    def test_streams_amortize_flight_alphas(self):
        s1 = price_step(self._sp(fusion_threshold_bytes=4 * MIB,
                                 overlap=True, num_comm_streams=1),
                        32 * MIB)
        s4 = price_step(self._sp(fusion_threshold_bytes=4 * MIB,
                                 overlap=True, num_comm_streams=4),
                        32 * MIB)
        assert s1.flights == 8 and s4.flights == 2
        assert s4.alpha_ms == pytest.approx(s1.alpha_ms / 4, rel=1e-9)

    def test_zero_step_prices_both_halves(self):
        sp = self._sp(zero_stage=2)
        sc = price_step(sp, 8 * MIB)
        assert len(sc.plan_costs) == 2  # rs + ag
        assert sc.predicted_ms > 0


class TestShortlist:
    def test_every_candidate_validates_and_is_ranked(self):
        rows = shortlist(16 * MIB, mesh_shape=(2, 4), quantized=True,
                         tune_overlap=True, tune_fused=True,
                         tune_zero=True)
        assert rows
        preds = [r.predicted_ms for r in rows]
        assert preds == sorted(preds)
        for r in rows:
            assert isinstance(r.plan, StepPlan)
            for plan in r.plan.plans:
                plan.validate()  # must already be legal

    def test_derived_wire_dedup(self):
        rows = shortlist(16 * MIB, mesh_shape=(2, 4), quantized=True,
                         tune_overlap=True)
        keys = [(r.plan.encode(), r.params.fusion_threshold_bytes)
                for r in rows]
        assert len(keys) == len(set(keys))

    def test_gates_pin_dimensions(self):
        rows = shortlist(16 * MIB, mesh_shape=(2, 4), quantized=False)
        assert all(r.params.zero_stage == 0 for r in rows)
        assert all(not r.params.overlap for r in rows)
        assert all(not r.params.fused for r in rows)
        zrows = shortlist(16 * MIB, mesh_shape=(2, 4), quantized=False,
                          tune_zero=True)
        assert {r.params.zero_stage for r in zrows} == {0, 1, 2}

    def test_k_truncates_the_head(self):
        full = shortlist(16 * MIB, mesh_shape=(2, 4), quantized=True)
        top = shortlist(16 * MIB, mesh_shape=(2, 4), quantized=True, k=3)
        assert len(top) == 3
        assert [r.plan.encode() for r in top] == \
            [r.plan.encode() for r in full[:3]]

    def test_as_dict_round_trips_to_json(self):
        rows = shortlist(8 * MIB, mesh_shape=(2, 4), quantized=True, k=2)
        blob = json.dumps([r.as_dict() for r in rows])
        back = json.loads(blob)
        assert back[0]["plan"] == rows[0].plan.encode()
        assert back[0]["predicted_ms"] == pytest.approx(
            rows[0].predicted_ms, abs=1e-6)

    def test_enumerate_respects_initial_for_pinned_dims(self):
        from horovod_tpu.autotune import TunedParams

        init = TunedParams(fusion_threshold_bytes=2 * MIB,
                           quant_block=192)
        cands = enumerate_tuned(quantized=True, initial=init)
        assert any(p.fusion_threshold_bytes == 2 * MIB for p in cands)
        assert any(p.quant_block == 192 for p in cands)


class TestAlphaBetaFit:
    def test_recovers_synthetic_link(self):
        # t = 50us + bytes / 40 GB/s
        pts = [(b, 50e-6 + b / 40e9)
               for b in (16e3, 128e3, 1e6, 4e6)]
        bw, lat = hvd_calibrate.alpha_beta_fit(
            pts, fallback_gbps=1.0, fallback_lat_us=0.0)
        assert bw == pytest.approx(40.0, rel=1e-6)
        assert lat == pytest.approx(50.0, rel=1e-6)

    def test_degenerate_slope_falls_back_to_static(self):
        pts = [(16e3, 1e-3), (1e6, 1e-3)]  # flat: timer noise
        bw, lat = hvd_calibrate.alpha_beta_fit(
            pts, fallback_gbps=25.0, fallback_lat_us=7.0)
        assert (bw, lat) == (25.0, 7.0)
        assert hvd_calibrate.alpha_beta_fit(
            [(1e6, 1e-3)], fallback_gbps=3.0,
            fallback_lat_us=2.0) == (3.0, 2.0)

    def test_negative_intercept_clamps_to_zero(self):
        pts = [(b, b / 40e9 - 1e-6) for b in (1e6, 2e6, 4e6)]
        _, lat = hvd_calibrate.alpha_beta_fit(
            pts, fallback_gbps=1.0, fallback_lat_us=9.0)
        assert lat == 0.0


class TestCalibrationPersistence:
    def _calib(self, geometry=None):
        return hvd_calibrate.Calibration(
            geometry=geometry or basics.mesh_geometry(),
            links={"ici": LinkClass(123.0, 2.5, 44.0),
                   "dcn": LinkClass(20.0, 30.0, 44.0)},
            points={"ici": [(16e3, 1e-4), (1e6, 2e-4)]},
            created_unix=1.0)

    def test_json_round_trip(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_CALIBRATION_CACHE",
                           str(tmp_path / "cal.json"))
        calib = self._calib()
        hvd_calibrate.store_calibration(calib)
        loaded = hvd_calibrate.load_calibration()
        assert loaded is not None
        assert loaded.geometry == calib.geometry
        assert loaded.links == calib.links
        assert loaded.points["ici"] == calib.points["ici"]
        model = hvd_calibrate.get_cost_model()
        assert model.source == "calibrated"
        assert model.ici.bandwidth_gbps == 123.0
        # Levels the sweep did not fit keep the static defaults.
        assert model.pod.bandwidth_gbps == \
            CostModel.from_env().pod.bandwidth_gbps

    def test_geometry_mismatch_forces_resweep(self, tmp_path,
                                              monkeypatch):
        monkeypatch.setenv("HOROVOD_CALIBRATION_CACHE",
                           str(tmp_path / "cal.json"))
        # A sweep from a DIFFERENT geometry is stored, but never
        # trusted for this one: load misses, the model stays static.
        hvd_calibrate.store_calibration(
            self._calib(geometry="mesh64x4|world256|tpu-v5e"))
        assert hvd_calibrate.load_calibration() is None
        assert hvd_calibrate.get_cost_model().source == "static"
        # The mismatched-geometry entry itself is still on disk intact.
        disk = json.load(open(str(tmp_path / "cal.json")))
        assert any("mesh64x4" in k for k in disk)

    def test_corrupted_file_warns_and_falls_back(self, tmp_path,
                                                 monkeypatch, caplog):
        path = tmp_path / "cal.json"
        path.write_text("{ not json !!!")
        monkeypatch.setenv("HOROVOD_CALIBRATION_CACHE", str(path))
        with caplog.at_level(logging.WARNING,
                             logger="horovod_tpu.plan"):
            assert hvd_calibrate.load_calibration() is None
            model = hvd_calibrate.get_cost_model()
        assert model.source == "static"
        assert model.dcn.bandwidth_gbps == 25.0  # HOROVOD_BENCH default
        assert any("unreadable" in r.message for r in caplog.records)

    def test_malformed_entry_warns_and_falls_back(self, tmp_path,
                                                  monkeypatch, caplog):
        path = tmp_path / "cal.json"
        key = hvd_calibrate.geometry_key()
        path.write_text(json.dumps({key: {"geometry": "x"}}))  # no links
        monkeypatch.setenv("HOROVOD_CALIBRATION_CACHE", str(path))
        with caplog.at_level(logging.WARNING,
                             logger="horovod_tpu.plan"):
            assert hvd_calibrate.load_calibration() is None
        assert hvd_calibrate.get_cost_model().source == "static"
        assert any("malformed" in r.message for r in caplog.records)

    def test_missing_file_is_silent_static(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_CALIBRATION_CACHE",
                           str(tmp_path / "nope" / "cal.json"))
        assert hvd_calibrate.load_calibration() is None
        assert hvd_calibrate.get_cost_model().source == "static"

    def test_default_path_sits_beside_the_autotune_cache(
            self, tmp_path, monkeypatch):
        monkeypatch.delenv("HOROVOD_CALIBRATION_CACHE", raising=False)
        monkeypatch.setenv("HOROVOD_AUTOTUNE_CACHE",
                           str(tmp_path / "sub" / "kernel.json"))
        assert hvd_calibrate.calibration_path() == \
            str(tmp_path / "sub" / "link_calibration.json")


class TestCalibrationSweep:
    def test_live_sweep_fits_and_persists(self, tmp_path, monkeypatch):
        """Real microbenchmark on the live test mesh: fits positive
        finite triples for every level the mesh has, persists, and
        resolves as the calibrated model."""
        monkeypatch.setenv("HOROVOD_CALIBRATION_CACHE",
                           str(tmp_path / "cal.json"))
        calib = hvd_calibrate.calibrate_links(sizes=(4096, 65536),
                                              reps=1)
        assert calib.geometry == basics.mesh_geometry()
        assert "ici" in calib.links  # the local axis always exists
        for lk in calib.links.values():
            assert lk.bandwidth_gbps > 0
            assert math.isfinite(lk.bandwidth_gbps)
            assert lk.latency_us >= 0
            assert lk.quant_rate_gbps > 0
        assert "quant" in calib.points
        assert hvd_calibrate.get_cost_model().source == "calibrated"

    def test_sweep_requires_init(self, monkeypatch):
        monkeypatch.setattr(basics._state, "initialized", False)
        with pytest.raises(RuntimeError, match="init"):
            hvd_calibrate.calibrate_links()


class TestMeshGeometry:
    def test_explicit_shape(self):
        geo = basics.mesh_geometry(mesh_shape=(2, 4))
        assert geo.startswith("mesh2x4|world8|")

    def test_three_level_shape(self):
        geo = basics.mesh_geometry(mesh_shape=(2, 2, 2))
        assert geo.startswith("mesh2x2x2|world8|")

    def test_live_mesh_matches_devices_shape(self):
        geo = basics.mesh_geometry()
        shp = hvd.mesh().devices.shape
        assert geo.startswith(
            "mesh" + "x".join(str(v) for v in shp) + "|world8|")


class TestDriftContract:
    def test_predicted_matches_traced_accounting(self):
        """The drift gate's core promise: the planner's byte model and
        the compiler's trace-time accounting are the same formulas. A
        real quantized allreduce traced on the 2x4 mesh must account
        wire bytes whose modeled-ms matches the prediction within a few
        percent (bucket padding is the only slack)."""
        n = 256 * 1024  # elements, divisible by world and block
        tree = {"w": jnp.zeros((8, n), jnp.float32)}
        payload_bytes = n * 4

        with record_wire_stats() as ws:
            jax.jit(hvd.shard_map(
                lambda t: fusion.allreduce_pytree(
                    jax.tree.map(lambda v: v[0], t), op=hvd.Sum,
                    quantized=True),
                mesh=mesh_2x4(), in_specs=(P(hvd.HVD_AXES),),
                out_specs=P())).lower(tree)
        measured = modeled_wire_ms(ws.ici_bytes, ws.dcn_bytes,
                                   ws.pod_bytes)
        sp = describe_plan(quantized=True, mesh_shape=(2, 4),
                           quant_block=256,
                           fusion_threshold_bytes=64 * MIB)
        predicted = price_step(sp, payload_bytes).wire_ms
        assert measured > 0
        assert predicted == pytest.approx(measured, rel=0.03)

    def test_static_model_is_drift_free_by_construction(self):
        sp = describe_plan(quantized=True, mesh_shape=(2, 4),
                           quant_block=256,
                           fusion_threshold_bytes=64 * MIB)
        sc = price_step(sp, 4 * MIB)
        assert sc.wire_ms == pytest.approx(sc.modeled_ms, rel=1e-9)
        assert sc.as_dict()["model"] == "static"


class TestTablePricing:
    def test_table_carries_model_and_pred_columns(self):
        sp = describe_plan(quantized=True, mesh_shape=(2, 4),
                           fusion_threshold_bytes=64 * MIB,
                           quant_block=256)
        text = sp.table(payload_bytes=1 << 20)
        assert "model ms" in text and "pred ms" in text
        assert "predicted:" in text
        assert "[cost model: static]" in text

    def test_table_prices_with_a_calibrated_model(self):
        sp = describe_plan(quantized=False, mesh_shape=(2, 4),
                           fusion_threshold_bytes=64 * MIB,
                           quant_block=256)
        fast = CostModel(
            ici=LinkClass(200.0, 0.0, 50.0),
            dcn=LinkClass(50.0, 0.0, 50.0),
            pod=LinkClass(50.0, 0.0, 50.0),
            source="calibrated", geometry="mesh2x4|world8|test")
        text = sp.table(payload_bytes=1 << 20, model=fast)
        assert "[cost model: calibrated]" in text
