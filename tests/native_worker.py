"""Worker script for multi-process native-core tests.

Launched by tests/test_native_core.py as N subprocesses on localhost with
the launcher env contract (HOROVOD_RANK/SIZE + controller address) — the
same pattern the reference uses for its parallel test tier
(`mpirun -np 2 pytest`, SURVEY §4). Exercises every collective, fusion,
the response-cache steady state, the cross-rank consistency checker, and
Adasum, asserting numerics at each step.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from horovod_tpu import cc  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    ctx = cc.CoreContext()
    assert ctx.rank() == rank and ctx.size() == size

    # allreduce sum / average / min / max / product
    out = ctx.allreduce_async(np.arange(10, dtype=np.float32) + rank,
                              "ar").wait()
    assert np.allclose(out, np.arange(10) * size + sum(range(size)))
    out = ctx.allreduce_async(np.full(5, float(rank), np.float32), "avg",
                              postscale=1.0 / size).wait()
    assert np.allclose(out, sum(range(size)) / size)
    out = ctx.allreduce_async(np.array([float(rank)], np.float32), "mn",
                              op=ctx.MIN).wait()
    assert out[0] == 0.0
    out = ctx.allreduce_async(np.array([float(rank)], np.float32), "mx",
                              op=ctx.MAX).wait()
    assert out[0] == size - 1
    out = ctx.allreduce_async(np.array([2.0], np.float32), "pr",
                              op=ctx.PRODUCT).wait()
    assert out[0] == 2.0 ** size

    # 16-bit dtypes through the software-converted reduction paths
    out = ctx.allreduce_async(np.ones(8, np.float16), "f16").wait()
    assert np.allclose(out.astype(np.float32), size)
    try:
        import ml_dtypes
        out = ctx.allreduce_async(np.ones(8, ml_dtypes.bfloat16),
                                  "bf16").wait()
        assert np.allclose(np.asarray(out, np.float32), size)
    except ImportError:
        pass

    # prescale/postscale
    out = ctx.allreduce_async(np.ones(4, np.float32), "sc", prescale=2.0,
                              postscale=0.5).wait()
    assert np.allclose(out, size)

    # ragged allgather: rank r contributes r+1 rows
    g = ctx.allgather_async(np.full((rank + 1, 2), rank, np.int32),
                            "ag").wait()
    assert g.shape == (sum(r + 1 for r in range(size)), 2)
    row = 0
    for r in range(size):
        assert (g[row:row + r + 1] == r).all()
        row += r + 1

    # broadcast from a non-zero root
    root = 1 % size
    out = ctx.broadcast_async(np.full(4, rank, np.float64), "bc",
                              root=root).wait()
    assert (out == root).all()

    # alltoall with uneven splits: rank r sends d+1 rows to dest d
    splits = [d + 1 for d in range(size)]
    h = ctx.alltoall_async(np.full((sum(splits), 3), rank, np.float32),
                           "a2a", splits=splits)
    out = h.wait()
    assert h.recv_splits() == [rank + 1] * size
    row = 0
    for src in range(size):
        assert (out[row:row + rank + 1] == src).all()
        row += rank + 1

    # tensor fusion: a burst of small same-dtype tensors
    hs = [ctx.allreduce_async(np.full(3, float(i), np.float32), f"f{i}")
          for i in range(20)]
    for i, h in enumerate(hs):
        assert np.allclose(h.wait(), i * size)

    # response-cache steady state: same name over many cycles
    for _ in range(30):
        out = ctx.allreduce_async(np.ones(4, np.float32), "steady").wait()
        assert np.allclose(out, size)
    # shape change on a cached tensor: must invalidate + renegotiate cleanly
    for _ in range(3):
        out = ctx.allreduce_async(np.ones(9, np.float32), "steady").wait()
        assert np.allclose(out, size)

    # cross-rank consistency checker (reference: ConstructResponse errors)
    if size > 1:
        try:
            ctx.allreduce_async(np.ones(4 + rank, np.float32), "bad").wait()
            raise AssertionError("expected shape-mismatch error")
        except cc.NativeError as e:
            assert "Mismatched" in str(e)
        try:
            arr = (np.ones(4, np.float32) if rank == 0
                   else np.ones(4, np.float64))
            ctx.allreduce_async(arr, "badtype").wait()
            raise AssertionError("expected dtype-mismatch error")
        except cc.NativeError as e:
            assert "Mismatched" in str(e)

    # adasum (power-of-2 worlds): identical vectors average to themselves
    if size & (size - 1) == 0:
        out = ctx.allreduce_async(np.ones(4, np.float32), "ads",
                                  op=ctx.ADASUM).wait()
        assert np.allclose(out, 1.0, atol=1e-5)

        # numerics vs the pairwise-tree reference formula (adasum.h:73-141)
        # on rank-distinct vectors with an odd length, so the VHDD halving
        # hits uneven splits (reference: test_adasum_* numerics checks).
        def adasum_ref(vecs):
            vecs = [v.astype(np.float64) for v in vecs]
            while len(vecs) > 1:
                nxt = []
                for i in range(0, len(vecs), 2):
                    a, b = vecs[i], vecs[i + 1]
                    dot, na, nb = float(a @ b), float(a @ a), float(b @ b)
                    ac = 1.0 if na <= 0 else 1.0 - dot / (2 * na)
                    bc = 1.0 if nb <= 0 else 1.0 - dot / (2 * nb)
                    nxt.append(ac * a + bc * b)
                vecs = nxt
            return vecs[0]

        def contrib(r):
            return (np.sin(np.arange(13) + r) + r).astype(np.float32)

        out = ctx.allreduce_async(contrib(rank).copy(), "ads_num",
                                  op=ctx.ADASUM).wait()
        expected = adasum_ref([contrib(r) for r in range(size)])
        assert np.allclose(out, expected, rtol=1e-4, atol=1e-5), \
            (out, expected)

        # bf16/fp16: TPU-native gradient dtypes ride the widen-to-fp32
        # software path (adasum.cc Vhdd16); coefficients stay fp32-accurate
        # so only the final rounding differs from the fp32 result.
        import ml_dtypes

        out16 = ctx.allreduce_async(
            contrib(rank).astype(ml_dtypes.bfloat16), "ads_bf16",
            op=ctx.ADASUM).wait()
        assert out16.dtype == ml_dtypes.bfloat16, out16.dtype
        assert np.allclose(out16.astype(np.float32), expected,
                           rtol=2e-2, atol=2e-2), (out16, expected)
        out16 = ctx.allreduce_async(
            contrib(rank).astype(np.float16), "ads_fp16",
            op=ctx.ADASUM).wait()
        assert out16.dtype == np.float16, out16.dtype
        assert np.allclose(out16.astype(np.float32), expected,
                           rtol=5e-3, atol=5e-3), (out16, expected)

    # large buffer: ring chunks far beyond kernel socket buffers must not
    # deadlock (regression: blocking send() in the bidirectional exchange)
    big = np.ones(8 << 20, np.float32)  # 32 MB
    out = ctx.allreduce_async(big, "big").wait()
    assert out[0] == size and out[-1] == size

    # join: ranks exit the data loop at different times; late collectives
    # proceed with identity contributions from joined ranks
    if size > 1:
        if rank == 0:
            # Regression: a tensor enqueued *before* join must still
            # contribute real data, not identity.
            pre = ctx.allreduce_async(np.full(4, 2.0, np.float32),
                                      "post_join")
            jh = ctx.join_async()
            assert np.allclose(pre.wait(), 2.0 * size)
        else:
            out = ctx.allreduce_async(
                np.full(4, 2.0, np.float32), "post_join").wait()
            assert np.allclose(out, 2.0 * size), out
            # A second collective after rank 0 joined for real: identity
            # contribution from the joined rank.
            out = ctx.allreduce_async(
                np.full(4, 3.0, np.float32), "post_join2").wait()
            assert np.allclose(out, 3.0 * (size - 1)), out
            jh = ctx.join_async()
        jh.wait()
        assert jh.join_result() >= 0

    if os.environ.get("HOROVOD_AUTOTUNE") == "1":
        # Drive enough traffic for the tuner to sample, propose, and (with
        # the test's small max-samples) converge; then verify the tuned
        # values propagated identically to every rank (reference:
        # SynchronizeParameters broadcasts the Params struct to workers,
        # controller.cc:34-48).
        import time as _time

        for i in range(150):
            ctx.allreduce_async(np.ones(2048, np.float32), f"at{i}").wait()
        ctx.barrier()
        _time.sleep(0.3)  # let the final broadcast's application land
        ft = np.array([[float(ctx.fusion_threshold())]], np.float64)
        g = ctx.allgather_async(ft, "at_sync").wait()
        assert g.shape == (size, 1)
        assert np.all(g == g[0]), f"tuned fusion thresholds diverge: {g}"
        assert 1024 <= g[0, 0] <= 256 * 1024 * 1024, g

    ctx.barrier()
    ctx.close()
    print(f"rank {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
