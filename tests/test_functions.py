"""broadcast_variables / broadcast_object / optimizer-state tests
(reference: test/parallel/test_torch.py broadcast_parameters and
broadcast_optimizer_state cases; tensorflow/functions.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd

N = 8


def test_broadcast_variables_in_mesh():
    # Each rank starts with rank-dependent params; after broadcast all must
    # equal root's (rank 3).
    def f(_):
        me = hvd.rank().astype(jnp.float32)
        params = {"w": jnp.full((4, 3), me), "b": jnp.full((2,), me * 10)}
        out = hvd.broadcast_variables(params, root_rank=3)
        return out

    out = hvd.shard_map(f, mesh=hvd.mesh(), in_specs=P(hvd.HVD_AXES),
                        out_specs=P())(jnp.zeros(N))
    np.testing.assert_array_equal(np.asarray(out["w"]), np.full((4, 3), 3.0))
    np.testing.assert_array_equal(np.asarray(out["b"]), np.full((2,), 30.0))


def test_broadcast_variables_eager_identity():
    params = {"w": jnp.arange(6.0).reshape(2, 3)}
    out = hvd.broadcast_variables(params, root_rank=0)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(params["w"]))


def test_broadcast_optimizer_state():
    params = {"w": jnp.ones((3,))}
    tx = optax.adam(1e-3)
    state = tx.init(params)
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    # Structure preserved, arrays intact (eager single-process: identity).
    la, ta = jax.tree.flatten(state)
    lb, tb = jax.tree.flatten(out)
    assert ta == tb
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_broadcast_object_roundtrip():
    obj = {"epoch": 3, "lr": 0.1, "name": "resnet"}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_allgather_object_single_process():
    assert hvd.allgather_object({"x": 1}) == [{"x": 1}]
