"""Chaos (fault-injection) subsystem tests: plan wire format, schedule
determinism, action semantics, and the hardened RPC retry path reacting
to injected faults over real localhost sockets."""

import os
import time

import pytest

from horovod_tpu import chaos
from horovod_tpu.common import counters
from horovod_tpu.runner import network, secret

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_chaos(monkeypatch):
    """Each test starts with no plan active and fresh counters, and never
    leaks its plan into the next test (the injector is process-global)."""
    monkeypatch.delenv(chaos.PLAN_ENV, raising=False)
    monkeypatch.delenv(chaos.SEED_ENV, raising=False)
    chaos.reset()
    counters.reset_all()
    yield
    chaos.reset()
    counters.reset_all()


class TestFaultPlanWireFormat:
    def test_round_trip_through_env(self):
        plan = chaos.FaultPlan(seed=42)
        plan.add("network.client.send", "drop", prob=0.5, max_count=3)
        plan.add("collective.eager", "crash", where="hostB:0", after=3,
                 max_count=1)
        plan.add("driver.slot_grant", "delay", secs=0.25, every=2)
        env = plan.to_env()
        parsed = chaos.FaultPlan.from_env(env)
        assert parsed.seed == 42
        assert [s.serialize() for s in parsed.specs] == \
            [s.serialize() for s in plan.specs]

    def test_where_may_contain_colon(self):
        # Worker identities are host:local_rank — the rule separator must
        # not eat them.
        spec = chaos.FaultSpec.parse("collective.eager:stall,where=h1:3,secs=2")
        assert spec.where == "h1:3"
        assert spec.secs == 2.0
        assert chaos.FaultSpec.parse(spec.serialize()).where == "h1:3"

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError, match="unknown chaos action"):
            chaos.FaultSpec.parse("p:explode")

    def test_unknown_option_rejected(self):
        with pytest.raises(ValueError, match="unknown chaos rule option"):
            chaos.FaultSpec.parse("p:drop,frequency=2")

    def test_no_plan_in_env_means_none(self):
        assert chaos.FaultPlan.from_env({}) is None


def _schedule(seed, calls):
    """Run a scripted (point, where) call sequence against a fresh
    injector; return the fired-fault schedule."""
    plan = chaos.FaultPlan(seed=seed)
    plan.add("a.*", "delay", prob=0.5, secs=0.0)
    plan.add("b.*", "delay", prob=0.5, secs=0.0)
    inj = chaos.ChaosInjector(plan)
    for point, where in calls:
        inj.inject(point, where=where)
    return tuple(inj.schedule)


class TestDeterminism:
    CALLS = [("a.x", "w1"), ("b.y", "w2"), ("a.x", "w1")] * 20

    def test_same_seed_same_schedule(self):
        assert _schedule(7, self.CALLS) == _schedule(7, self.CALLS)

    def test_different_seed_different_schedule(self):
        # 60 p=0.5 decisions: collision probability ~2^-60.
        assert _schedule(7, self.CALLS) != _schedule(8, self.CALLS)

    def test_rule_streams_independent_of_interleaving(self):
        """Rule decisions depend only on that rule's own invocation
        count, not on how other rules' calls interleave."""
        a_only = [c for c in self.CALLS if c[0] == "a.x"]
        mixed = _schedule(7, self.CALLS)
        alone = _schedule(7, a_only)
        assert [e for e in mixed if e[0] == "a.x"] == list(alone)


class TestActionSemantics:
    def test_after_every_max(self):
        plan = chaos.FaultPlan().add("p", "delay", secs=0.0, after=2,
                                     every=3, max_count=2)
        inj = chaos.ChaosInjector(plan)
        fired = [bool(inj.decide("p", "w")) for _ in range(12)]
        # skip 2, then every 3rd considered, capped at 2 hits
        assert fired == [False, False, True, False, False, True,
                         False, False, False, False, False, False]

    def test_where_glob_gates_firing(self):
        plan = chaos.FaultPlan().add("p", "delay", secs=0.0,
                                     where="hostB:*")
        inj = chaos.ChaosInjector(plan)
        assert inj.inject("p", where="hostA:0") is None
        assert not inj.schedule
        inj.inject("p", where="hostB:1")
        assert len(inj.schedule) == 1

    def test_drop_is_a_connection_error(self):
        inj = chaos.ChaosInjector(chaos.FaultPlan().add("p", "drop"))
        with pytest.raises(ConnectionError):
            inj.inject("p", where="w")
        assert counters.get("chaos.drop") == 1

    def test_delay_sleeps(self):
        inj = chaos.ChaosInjector(
            chaos.FaultPlan().add("p", "delay", secs=0.15))
        t0 = time.monotonic()
        assert inj.inject("p", where="w") is None
        assert time.monotonic() - t0 >= 0.14

    def test_dup_and_flap_are_returned_to_caller(self):
        inj = chaos.ChaosInjector(chaos.FaultPlan()
                                  .add("p.dup", "dup")
                                  .add("p.flap", "flap"))
        assert inj.inject("p.dup", where="w") == "dup"
        assert inj.inject("p.flap", where="w") == "flap"

    def test_env_activation(self, monkeypatch):
        plan = chaos.FaultPlan(seed=5).add("p", "delay", secs=0.0)
        for k, v in plan.to_env().items():
            monkeypatch.setenv(k, v)
        chaos.reset()  # re-arm env discovery
        assert chaos.enabled()
        chaos.inject("p")
        assert counters.get("chaos.delay") == 1


class _CountingService(network.BasicService):
    def __init__(self, key):
        super().__init__("counting service", key)
        self.handled = 0

    def _handle(self, req, client_address):
        self.handled += 1
        return super()._handle(req, client_address)


@pytest.fixture()
def rpc_pair():
    key = secret.make_secret_key()
    service = _CountingService(key)
    try:
        yield service, key
    finally:
        service.shutdown()


class TestRpcUnderChaos:
    """The hardened BasicClient retry path driven by injected faults —
    the RPC-drop leg of the recovery demonstration."""

    def _client(self, service, key, **kw):
        kw.setdefault("attempts", 4)
        kw.setdefault("timeout", 5.0)
        return network.BasicClient("counting service", "127.0.0.1",
                                   service.port, key, **kw)

    def test_client_send_drops_are_retried(self, rpc_pair):
        service, key = rpc_pair
        chaos.configure(chaos.FaultPlan().add(
            "network.client.send", "drop", max_count=2))
        resp = self._client(service, key).ping()
        assert isinstance(resp, network.PingResponse)
        assert counters.get("chaos.drop") == 2
        assert counters.get("rpc.client.retry") == 2
        assert counters.get("rpc.client.failure") == 0

    def test_server_side_drop_is_survived(self, rpc_pair):
        service, key = rpc_pair
        chaos.configure(chaos.FaultPlan().add(
            "network.server.handle", "drop", max_count=1))
        resp = self._client(service, key).ping()
        assert isinstance(resp, network.PingResponse)
        # the dropped request never reached _handle; the retry did
        assert service.handled == 1
        assert counters.get("rpc.client.retry") >= 1

    def test_duplicate_delivery(self, rpc_pair):
        service, key = rpc_pair
        chaos.configure(chaos.FaultPlan().add(
            "network.client.send", "dup", max_count=1))
        resp = self._client(service, key).ping()
        assert isinstance(resp, network.PingResponse)
        assert service.handled == 2  # idempotent service: both answered

    def test_exhausted_retries_name_service_and_attempts(self):
        port = network.find_free_port()  # nothing listening
        client = network.BasicClient("doomed service", "127.0.0.1", port,
                                     b"k" * 32, attempts=2, timeout=0.5)
        with pytest.raises(ConnectionError) as err:
            client.ping()
        msg = str(err.value)
        assert "doomed service" in msg
        assert f"127.0.0.1:{port}" in msg
        assert "2 attempt(s)" in msg
        assert counters.get("rpc.client.failure") == 1

    def test_deadline_budget_caps_attempts(self):
        port = network.find_free_port()
        client = network.BasicClient("budgeted service", "127.0.0.1", port,
                                     b"k" * 32, attempts=1000, timeout=0.5,
                                     total_deadline=0.5)
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            client.ping()
        # Bounded by the budget, not by 1000 connection attempts.
        assert time.monotonic() - t0 < 5.0

    def test_backoff_spaces_out_retries(self, rpc_pair, monkeypatch):
        service, key = rpc_pair
        monkeypatch.setenv("HOROVOD_RPC_RETRY_BASE_SECS", "0.1")
        chaos.configure(chaos.FaultPlan().add(
            "network.client.send", "drop", max_count=2))
        t0 = time.monotonic()
        self._client(service, key).ping()
        # two backoff sleeps, each >= 0.5 * base * 2^i: >= 0.05 + 0.1
        assert time.monotonic() - t0 >= 0.1


class TestDiscoveryFlap:
    def test_flap_empties_then_recovers(self):
        from horovod_tpu.elastic.discovery import (FixedHosts, HostManager,
                                                   HostUpdateResult)

        chaos.configure(chaos.FaultPlan().add(
            "discovery.update", "flap", after=1, max_count=1))
        mgr = HostManager(FixedHosts({"a": 2}))
        assert mgr.update_available_hosts() == HostUpdateResult.added
        # injected flap: world transiently empty
        assert mgr.update_available_hosts() == HostUpdateResult.removed
        assert mgr.current_hosts == {}
        # next poll sees the real host set again
        assert mgr.update_available_hosts() == HostUpdateResult.added
        assert mgr.current_hosts == {"a": 2}
        assert counters.get("chaos.flap") == 1


class TestCrashSubprocess:
    def test_crash_kills_process_with_exit_code(self, tmp_path):
        """crash must be a hard os._exit — no unwind, no atexit."""
        import subprocess
        import sys

        plan = chaos.FaultPlan().add("p", "crash", exit_code=3)
        env = dict(os.environ)
        env.update(plan.to_env())
        env["PYTHONPATH"] = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        code = (
            "import atexit, sys\n"
            "atexit.register(lambda: print('ATEXIT RAN'))\n"
            "from horovod_tpu import chaos\n"
            "chaos.inject('p')\n"
            "print('SURVIVED')\n"
        )
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 3
        assert "SURVIVED" not in proc.stdout
        assert "ATEXIT RAN" not in proc.stdout
