"""Worker for multi-process TensorFlow/Keras binding tests (reference
analogue: `mpirun -np 2 pytest test_tensorflow.py`, SURVEY §4)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import tensorflow as tf  # noqa: E402

import horovod_tpu.tensorflow as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size

    # -- allreduce (average default / sum / scaling) --
    out = hvd.allreduce(tf.fill([4], float(rank)))
    assert np.allclose(out.numpy(), sum(range(size)) / size)
    out = hvd.allreduce(tf.ones([4]), op=hvd.Sum, prescale_factor=2.0,
                        postscale_factor=0.5)
    assert np.allclose(out.numpy(), size)

    # gradient through allreduce
    x = tf.Variable(tf.fill([3], float(rank)))
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.allreduce(x, op=hvd.Sum))
    g = tape.gradient(y, x)
    assert np.allclose(g.numpy(), size), g.numpy()

    # -- allreduce inside tf.function (graph mode via py_function) --
    @tf.function
    def graph_reduce(t):
        return hvd.allreduce(t, op=hvd.Sum)

    out = graph_reduce(tf.ones([5]))
    assert np.allclose(out.numpy(), size)

    # -- allgather (ragged) / broadcast / alltoall --
    g = hvd.allgather(tf.fill([rank + 1, 2], float(rank)))
    assert g.shape[0] == sum(r + 1 for r in range(size))
    out = hvd.broadcast(tf.fill([4], float(rank)), root_rank=0)
    assert np.allclose(out.numpy(), 0.0)
    out, splits = hvd.alltoall(tf.range(size * 2, dtype=tf.float32))
    assert out.shape[0] == size * 2 and list(splits.numpy()) == [2] * size

    # -- broadcast_variables / broadcast_object / allgather_object --
    v = tf.Variable(tf.fill([3], float(rank + 1)))
    hvd.broadcast_variables([v], root_rank=0)
    assert np.allclose(v.numpy(), 1.0)
    obj = hvd.broadcast_object({"r": rank}, root_rank=0)
    assert obj["r"] == 0
    objs = hvd.allgather_object(rank)
    assert objs == list(range(size))

    # -- DistributedGradientTape: ranks converge identically --
    tf.random.set_seed(0)
    w = tf.Variable(tf.ones([3, 1]))
    xb = tf.fill([1, 3], float(rank + 1))
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(tf.matmul(xb, w))
    (gw,) = tape.gradient(loss, [w])
    mean_x = np.mean([r + 1 for r in range(size)])
    assert np.allclose(gw.numpy(), mean_x), gw.numpy()

    # -- sparse_as_dense: embedding (IndexedSlices) gradients — the
    # allgather path and the densify path must agree numerically
    # (reference: tensorflow/__init__.py:260,299,437) --
    emb = tf.Variable(np.full((size + 1, 4), 0.5, np.float32))
    idx = tf.constant([rank, rank + 1, rank])  # rank-dependent + dup

    def emb_grad(sparse_as_dense, tag):
        with hvd.DistributedGradientTape(
                tf.GradientTape(),
                sparse_as_dense=sparse_as_dense) as tape:
            vals = tf.nn.embedding_lookup(emb, idx)
            loss = tf.reduce_sum(vals * vals)
        (g,) = tape.gradient(loss, [emb])
        if sparse_as_dense:
            assert not isinstance(g, tf.IndexedSlices), tag
        else:
            assert isinstance(g, tf.IndexedSlices), tag
            g = tf.convert_to_tensor(g)  # duplicate indices sum
        return g.numpy()

    g_gather = emb_grad(False, "gather")
    g_dense = emb_grad(True, "dense")
    # Expected: average over ranks of each rank's dense grad
    # (row r: 2 hits -> 2.0; row r+1: 1 hit -> 1.0; grad d/dv v^2 = 2v).
    exp = np.zeros((size + 1, 4), np.float64)
    for r in range(size):
        exp[r] += 2 * 2 * 0.5
        exp[r + 1] += 2 * 0.5
    exp /= size
    assert np.allclose(g_gather, exp), (g_gather, exp)
    assert np.allclose(g_dense, exp), (g_dense, exp)

    # -- Keras: DistributedOptimizer + callbacks through model.fit --
    import keras

    import horovod_tpu.keras as hvdk

    keras.utils.set_random_seed(1234 + rank)  # intentionally different init
    model = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(8, activation="tanh"),
        keras.layers.Dense(1),
    ])
    opt = hvdk.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.05))
    model.compile(optimizer=opt, loss="mse")

    rs = np.random.RandomState(100 + rank)  # different data per rank
    xs = rs.randn(64, 4).astype(np.float32)
    ys = xs.sum(axis=1, keepdims=True).astype(np.float32)
    hist = model.fit(
        xs, ys, batch_size=16, epochs=3, verbose=0,
        callbacks=[
            hvdk.callbacks.BroadcastGlobalVariablesCallback(0),
            hvdk.callbacks.MetricAverageCallback(),
        ])
    losses = hist.history["loss"]
    assert losses[-1] < losses[0], losses

    # Weights must be identical across ranks after synchronized training
    flat = np.concatenate([w.flatten() for w in model.get_weights()])
    gathered = hvd.allgather(tf.constant(flat[None, :]))
    assert np.allclose(gathered.numpy()[0], gathered.numpy()[-1],
                       atol=1e-5), "keras ranks diverged"

    # MetricAverageCallback averaged the logged loss across ranks: all
    # ranks log the same value
    lv = hvd.allgather(tf.constant([[losses[-1]]]))
    assert np.allclose(lv.numpy()[0], lv.numpy()[-1]), lv.numpy()

    # -- Keras + embedding (IndexedSlices) gradients: the optimizer's
    # sparse grads ride the shared allgather path by default and the
    # densify path with sparse_as_dense=True; both must train and end
    # with identical weights across ranks --
    for sad in (False, True):
        keras.utils.set_random_seed(99 + rank)
        emodel = keras.Sequential([
            keras.layers.Input(shape=(3,), dtype="int32"),
            keras.layers.Embedding(16, 4),
            keras.layers.Flatten(),
            keras.layers.Dense(1),
        ])
        eopt = hvdk.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.05),
            sparse_as_dense=sad)
        emodel.compile(optimizer=eopt, loss="mse")
        ers = np.random.RandomState(200 + rank)
        exs = ers.randint(0, 16, (32, 3)).astype(np.int32)
        eys = exs.sum(axis=1, keepdims=True).astype(np.float32) * 0.1
        ehist = emodel.fit(
            exs, eys, batch_size=8, epochs=2, verbose=0,
            callbacks=[hvdk.callbacks.BroadcastGlobalVariablesCallback(0)])
        assert ehist.history["loss"][-1] < ehist.history["loss"][0], (
            "embedding keras", sad, ehist.history["loss"])
        eflat = np.concatenate([w.flatten() for w in emodel.get_weights()])
        eg = hvd.allgather(tf.constant(eflat[None, :]))
        assert np.allclose(eg.numpy()[0], eg.numpy()[-1], atol=1e-5), (
            "embedding keras ranks diverged", sad)

    # -- KerasState sync --
    state = hvdk.elastic.KerasState(model, epoch=rank)
    state.sync()
    assert state.epoch == 0

    # -- SyncBatchNormalization: per-rank shard stats must equal the
    # big-batch moments (reference: test_horovod_sync_batch_norm) --
    rs = np.random.RandomState(7)
    full = rs.randn(size * 4, 6).astype(np.float32) * 2.0 + 1.0
    shard = tf.constant(full[rank * 4:(rank + 1) * 4])
    sbn = hvd.SyncBatchNormalization(momentum=0.5, epsilon=1e-5)
    sbn.build(shard.shape)
    out = sbn(shard, training=True)
    gmean = full.mean(axis=0)
    gvar = full.var(axis=0)
    expect = (full[rank * 4:(rank + 1) * 4] - gmean) / \
        np.sqrt(gvar + 1e-5)
    assert np.allclose(out.numpy(), expect, atol=1e-4), "sync BN moments"
    # Moving variance uses the *biased* global variance — the stock Keras
    # layer's convention, and what test_tensorflow.py's world-1 parity
    # test asserts.
    assert np.allclose(np.asarray(sbn.moving_mean), 0.5 * gmean,
                       atol=1e-4)
    assert np.allclose(np.asarray(sbn.moving_variance),
                       0.5 + 0.5 * gvar, atol=1e-4)

    # -- native graph-mode collectives: REAL graph nodes (custom op,
    # reference mpi_ops.cc analogue), not tf.py_function --
    from horovod_tpu.tensorflow import _native_ops

    @tf.function
    def graph_coll(t):
        s = hvd.allreduce(t, op=hvd.Sum, name="g.ar")
        b = hvd.broadcast(t, root_rank=0, name="g.bc")
        g = hvd.allgather(tf.reshape(t, [1, 3]), name="g.ag")
        return s, b, g

    cf = graph_coll.get_concrete_function(
        tf.TensorSpec([3], tf.float32))
    op_types = {op.type for op in cf.graph.get_operations()}
    if _native_ops() is not None:
        assert {"HvdtpuAllreduce", "HvdtpuBroadcast",
                "HvdtpuAllgather"} <= op_types, op_types
        assert not any("PyFunc" in t for t in op_types), op_types
    for _ in range(2):  # stable per-node names across repeated executions
        s, b, g = graph_coll(tf.fill([3], float(rank)))
        assert np.allclose(s.numpy(), sum(range(size))), s
        assert np.allclose(b.numpy(), 0.0), b
        assert g.shape == (size, 3) and np.allclose(
            g.numpy()[:, 0], np.arange(size)), g

    # many concurrent collective nodes in one graph: must not deadlock the
    # inter-op pool (async kernels + waiter thread; a sync kernel design
    # pins a pool thread per node and hangs when nodes outnumber threads)
    @tf.function
    def graph_flood(t):
        outs = [hvd.allreduce(t + float(i), op=hvd.Sum,
                              name=f"g.flood.{i}") for i in range(64)]
        return tf.add_n(outs)

    f = graph_flood(tf.fill([16], float(rank)))
    expect = sum(sum(r + i for r in range(size)) for i in range(64))
    assert np.allclose(f.numpy(), expect), (f, expect)

    # -- native graph-mode alltoall + join (reference: HorovodAlltoallOp
    # mpi_ops.cc:754-792, HorovodJoinOp :604-634) --
    @tf.function
    def graph_a2a(t, sp):
        return hvd.alltoall(t, splits=sp, name="g.a2a")

    cf = graph_a2a.get_concrete_function(
        tf.TensorSpec([None], tf.float32), tf.TensorSpec([None], tf.int64))
    a2a_types = {op.type for op in cf.graph.get_operations()}
    if _native_ops() is not None:
        assert "HvdtpuAlltoall" in a2a_types, a2a_types
        assert not any("PyFunc" in t for t in a2a_types), a2a_types
    # even splits
    out, rsp = graph_a2a(tf.range(size * 2, dtype=tf.float32),
                         tf.zeros([0], tf.int64))
    expect = np.concatenate([np.arange(2) + 2 * rank for _ in range(size)])
    assert np.allclose(out.numpy(), expect), out.numpy()
    assert list(rsp.numpy()) == [2] * size, rsp.numpy()
    # uneven splits: rank r sends r+1 rows to every peer
    rows = size * (rank + 1)
    out, rsp = graph_a2a(
        tf.fill([rows], float(rank)),
        tf.constant([rank + 1] * size, dtype=tf.int64))
    assert list(rsp.numpy()) == [r + 1 for r in range(size)], rsp.numpy()
    expect = np.concatenate([np.full(r + 1, float(r)) for r in range(size)])
    assert np.allclose(out.numpy(), expect), out.numpy()

    @tf.function
    def graph_join():
        return hvd.join()

    cfj = graph_join.get_concrete_function()
    join_types = {op.type for op in cfj.graph.get_operations()}
    if _native_ops() is not None:
        assert "HvdtpuJoin" in join_types, join_types
    last = graph_join()
    assert 0 <= int(last.numpy()) < size, last

    # gradient THROUGH the native graph op (custom_gradient wraps it)
    @tf.function
    def graph_grad(t):
        with tf.GradientTape() as tape:
            tape.watch(t)
            y = tf.reduce_sum(hvd.allreduce(t, op=hvd.Sum, name="g.gr"))
        return tape.gradient(y, t)

    gr = graph_grad(tf.fill([3], float(rank)))
    assert np.allclose(gr.numpy(), size), gr  # d(sum)/dt allreduced again

    # -- TensorFlowState: sync pulls rank-0 values everywhere --
    v = tf.Variable(tf.fill([3], float(rank)))
    tstate = hvd.elastic.TensorFlowState(variables=[v], batch=rank)
    tstate.sync()
    assert np.allclose(v.numpy(), 0.0), v.numpy()
    assert tstate.batch == 0
    v.assign(tf.fill([3], 99.0))
    tstate.restore()
    assert np.allclose(v.numpy(), 0.0), v.numpy()

    hvd.shutdown()
    print(f"rank {rank}: tf worker OK")


if __name__ == "__main__":
    main()
